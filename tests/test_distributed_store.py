"""Distributed shard store: owner bounds, assembly plan, store slice
semantics, sharded-checkpoint manifest discipline (ISSUE 15).

Everything except the last test is jax-free index math pinned without a
backend (the data/residency.py discipline); the final test pins the
``draw_pos`` permutation contract on the REAL streamed round program —
an owner-permuted cohort with permuted per-position draws trains every
client identically to the draw-order program.
"""

import numpy as np
import pytest

from distributed_learning_simulator_tpu.data.residency import (
    DistributedShardStore,
    host_axis_bounds,
    owner_of,
    plan_owner_assembly,
)
from distributed_learning_simulator_tpu.utils.checkpoint import (
    load_latest_valid_sharded_checkpoint,
    manifest_rounds,
    save_shard_checkpoint,
    validate_manifest,
    write_manifest,
)


def _bounds(n, hosts):
    return host_axis_bounds(n, [1] * hosts)


def test_host_axis_bounds_even_and_proportional():
    assert _bounds(8, 2).tolist() == [0, 4, 8]
    assert _bounds(9, 2).tolist() == [0, 4, 9]
    # Device-proportional: a host with 3 of 4 devices owns 3/4 of rows.
    assert host_axis_bounds(8, [3, 1]).tolist() == [0, 6, 8]
    assert owner_of([0, 3, 4, 7], _bounds(8, 2)).tolist() == [0, 0, 1, 1]


def test_plan_single_host_is_identity():
    """num_hosts == 1: the assignment is the identity and nothing
    spills — the zero-cost contract the single-process stream leg's
    bench floor rests on."""
    idx = np.array([6, 1, 3, 2])
    p = plan_owner_assembly(idx, _bounds(8, 1), _bounds(4, 1))
    assert p.draw_pos.tolist() == [0, 1, 2, 3]
    assert p.spill_q.size == 0
    assert p.idx_perm.tolist() == idx.tolist()


def test_plan_owner_contiguous_blocks_and_spill():
    """Own members fill the owner's block in draw order; the ownership
    imbalance (and only it) spills to the other host's free rows."""
    idx = np.array([6, 1, 3, 2])  # owners: 1, 0, 0, 0 under [0,4,8)
    p = plan_owner_assembly(idx, _bounds(8, 2), _bounds(4, 2))
    # Host 0's block (rows 0-1): its first two members in draw order.
    assert p.idx_perm[:2].tolist() == [1, 3]
    # Host 1's block: its one member, then host 0's overflow member.
    assert sorted(p.idx_perm[2:].tolist()) == [2, 6]
    # Exactly one spill entry: client 2 (owner 0) placed in block 1.
    assert p.spill_q.size == 1
    assert p.spill_owner.tolist() == [0]
    assert p.spill_block.tolist() == [1]
    assert idx[p.spill_q[0]] == 2
    # draw_pos inverts row_of.
    assert p.draw_pos[p.row_of].tolist() == list(range(4))


def test_plan_is_permutation_and_deterministic():
    rng = np.random.default_rng(0)
    owner_bounds = _bounds(1000, 4)
    block_bounds = _bounds(64, 4)
    for _ in range(10):
        idx = rng.choice(1000, size=64, replace=False)
        p1 = plan_owner_assembly(idx, owner_bounds, block_bounds)
        p2 = plan_owner_assembly(idx, owner_bounds, block_bounds)
        assert np.array_equal(p1.draw_pos, p2.draw_pos)
        assert sorted(p1.draw_pos.tolist()) == list(range(64))
        # Every non-spill row is served by its block's owner.
        for h in range(4):
            lo, hi = block_bounds[h], block_bounds[h + 1]
            owners = owner_of(p1.idx_perm[lo:hi], owner_bounds)
            n_own = int((owners == h).sum())
            # Own members come first, contiguously.
            assert (owners[:n_own] == h).all()
        # Spill accounting balances.
        assert p1.send_counts().sum() == p1.recv_counts().sum()
        assert p1.send_counts().sum() == p1.spill_q.size


def test_plan_spill_is_imbalance_only():
    """Spill is exactly sum over hosts of max(0, members - capacity) —
    the per-round ownership imbalance, not the cohort."""
    rng = np.random.default_rng(3)
    owner_bounds = _bounds(100, 2)
    block_bounds = _bounds(16, 2)
    for _ in range(20):
        idx = rng.choice(100, size=16, replace=False)
        p = plan_owner_assembly(idx, owner_bounds, block_bounds)
        owners = owner_of(idx, owner_bounds)
        expect = sum(
            max(0, int((owners == h).sum()) - 8) for h in range(2)
        )
        assert p.spill_q.size == expect


def test_distributed_store_owns_slice_and_maps_global_ids():
    x = np.arange(8 * 3, dtype=np.float32).reshape(8, 3)
    y = np.arange(8, dtype=np.int32)[:, None]
    m = np.ones((8, 1), np.float32)
    sz = np.arange(8, dtype=np.float32)
    s = DistributedShardStore(x, y, m, sz, host_id=1,
                             owner_bounds=_bounds(8, 2))
    assert (s.lo, s.hi, s.n_owned, s.n_hosts) == (4, 8, 4, 2)
    gx, _, _, gsz = s.gather_data(np.array([5, 7]))
    assert np.array_equal(gx, x[[5, 7]])
    assert np.array_equal(gsz, sz[[5, 7]])
    # Whole-slice gather (the full-population upload path).
    fx, _, _, _ = s.gather_data(None)
    assert np.array_equal(fx, x[4:8])
    with pytest.raises(IndexError, match="owns clients"):
        s.gather_data(np.array([3]))


def test_distributed_store_state_scatter_by_global_id():
    x = np.zeros((6, 2), np.float32)
    state = {"mom": np.zeros((3, 2), np.float32)}  # host 1 owns [3, 6)
    s = DistributedShardStore(
        x, np.zeros((6, 1), np.int32), np.ones((6, 1), np.float32),
        np.ones(6, np.float32), state=state, host_id=1,
        owner_bounds=_bounds(6, 2),
    )
    s.scatter_state(np.array([4]), {"mom": np.full((1, 2), 7.0,
                                                   np.float32)})
    assert s.state["mom"][1, 0] == 7.0
    got = s.gather_state(np.array([4]))
    assert got["mom"][0, 1] == 7.0
    with pytest.raises(NotImplementedError, match="dynamic"):
        s.grow(x, x, x, x)
    with pytest.raises(NotImplementedError, match="valuation"):
        s.attach_valuation(np.zeros(6))


def test_sharded_checkpoint_roundtrip_and_fallback(tmp_path):
    d = str(tmp_path)
    for r in (0, 1):
        for h in (0, 1):
            save_shard_checkpoint(d, r, h, 2, {
                "global_params": {"w": np.full(3, float(r))},
                "client_state": None,
                "algo_state": {"prev_metrics": {"loss": float(r)}},
                "rng_key": None,
            })
        write_manifest(d, r, {"n_hosts": 2, "n_clients": 8,
                              "owner_bounds": [0, 4, 8]})
    assert [r for r, _ in manifest_rounds(d)] == [0, 1]
    manifest, payload = load_latest_valid_sharded_checkpoint(d, 0, 2)
    assert manifest["round"] == 1
    assert payload["round_idx"] == 1 and payload["host_id"] == 0
    assert payload["global_params"]["w"][0] == 1.0
    # A round whose manifest never landed is invisible: discovery falls
    # back to the newest COMMITTED round (a host died pre-barrier).
    save_shard_checkpoint(d, 2, 0, 2, {"global_params": None,
                                       "client_state": None,
                                       "algo_state": {}, "rng_key": None})
    manifest, _ = load_latest_valid_sharded_checkpoint(d, 0, 2)
    assert manifest["round"] == 1
    # A manifest whose shard file is missing is skipped with a warning.
    write_manifest(d, 2, {"n_hosts": 2, "n_clients": 8,
                          "owner_bounds": [0, 4, 8]})
    manifest, _ = load_latest_valid_sharded_checkpoint(d, 0, 2)
    assert manifest["round"] == 1  # host 1's round-2 shard never landed


def test_resume_under_changed_host_count_refuses_at_discovery(tmp_path):
    """A REAL topology change (resume with a different host count, no
    manifest tampering) must refuse at discovery, not silently restart:
    this host's shard path derives from the CURRENT topology, so
    without the loader-level check the of-2 shards would read as
    'missing' and every round would be skipped."""
    d = str(tmp_path)
    for h in (0, 1):
        save_shard_checkpoint(d, 0, h, 2, {
            "global_params": None, "client_state": None,
            "algo_state": {}, "rng_key": None,
        })
    write_manifest(d, 0, {"n_hosts": 2, "n_clients": 8,
                          "owner_bounds": [0, 4, 8]})
    with pytest.raises(RuntimeError, match="topology mismatch"):
        load_latest_valid_sharded_checkpoint(d, 0, 3)
    # The matching topology still loads.
    manifest, payload = load_latest_valid_sharded_checkpoint(d, 0, 2)
    assert manifest["round"] == 0 and payload["host_id"] == 0


def test_validate_manifest_refusals_name_the_cause():
    base = {"n_hosts": 2, "n_clients": 8, "owner_bounds": [0, 4, 8]}
    validate_manifest(dict(base), n_hosts=2, n_clients=8,
                      owner_bounds=[0, 4, 8])
    with pytest.raises(RuntimeError, match="topology mismatch"):
        validate_manifest(dict(base), n_hosts=3, n_clients=8)
    with pytest.raises(RuntimeError, match="population mismatch"):
        validate_manifest(dict(base), n_hosts=2, n_clients=16)
    with pytest.raises(RuntimeError, match="ownership mismatch"):
        validate_manifest(dict(base), n_hosts=2, n_clients=8,
                          owner_bounds=[0, 6, 8])


def test_config_refusals_name_causes():
    """Streamed x multihost composes; every remaining refusal names its
    blocking cause (the PR 2/6/7 discipline)."""
    from distributed_learning_simulator_tpu.config import ExperimentConfig

    def cfg(**kw):
        base = dict(
            dataset_name="synthetic", model_name="mlp", worker_number=8,
            multihost=True, client_residency="streamed", mesh_devices=2,
            participation_fraction=0.5, participation_sampler="hashed",
        )
        base.update(kw)
        return ExperimentConfig(**base).validate()

    cfg()  # the lifted composition validates
    with pytest.raises(ValueError, match="GLOBAL device count"):
        cfg(mesh_devices=None)
    with pytest.raises(ValueError, match="hashed"):
        cfg(participation_sampler="exact")
    with pytest.raises(ValueError, match="rounds_per_dispatch=1"):
        cfg(rounds_per_dispatch=2)
    with pytest.raises(ValueError, match="async"):
        cfg(async_mode="on", arrival_model="bimodal")
    with pytest.raises(ValueError, match="client_stats"):
        cfg(client_stats="on")
    with pytest.raises(ValueError, match="valuation vector"):
        cfg(client_stats="off", client_valuation="on")
    with pytest.raises(ValueError, match="persistent per-client state"):
        cfg(participation_fraction=1.0, reset_client_optimizer=False)
    with pytest.raises(ValueError, match="re-partition the distributed"):
        cfg(population="dynamic", join_rate=1.0)
    with pytest.raises(ValueError, match="stochastic-quantization"):
        cfg(distributed_algorithm="fed_quant", client_eval=False)


def test_draw_pos_permutes_back_to_draw_order(tiny_dataset):
    """The round-program half of the owner-permutation contract: calling
    the streamed round fn with owner-permuted operands + ``draw_pos``
    yields BIT-identical per-client outputs to the draw-order call
    (training keys and fault draws follow the client), with the
    aggregate equal up to summation order — pinned here on one device
    so the 2-process harness only has to cover placement."""
    import jax
    import numpy as np

    from distributed_learning_simulator_tpu.config import ExperimentConfig
    from distributed_learning_simulator_tpu.data.partition import (
        iid_partition,
        pack_client_shards,
    )
    from distributed_learning_simulator_tpu.factory import get_algorithm
    from distributed_learning_simulator_tpu.models.registry import (
        get_model,
        init_params,
    )
    from distributed_learning_simulator_tpu.parallel.engine import (
        make_decoder,
        make_optimizer,
    )

    config = ExperimentConfig(
        dataset_name="synthetic", model_name="mlp",
        distributed_algorithm="fed", worker_number=8, round=1, epoch=1,
        learning_rate=0.1, batch_size=16, n_train=256, n_test=128,
        log_level="ERROR", client_residency="streamed",
        participation_fraction=0.5, participation_sampler="hashed",
        failure_mode="dropout", failure_prob=0.3,  # positional draws
    ).validate()
    ds = tiny_dataset
    data = pack_client_shards(
        ds.x_train, ds.y_train,
        iid_partition(len(ds.x_train), 8, seed=0), batch_size=16,
    )
    model = get_model("mlp", num_classes=ds.num_classes)
    params = init_params(model, ds.x_train[:1], seed=0)
    opt = make_optimizer("SGD", 0.1)
    algo = get_algorithm("fed", config)
    round_fn = algo.make_round_fn(
        model.apply, opt, 8,
        preprocess=make_decoder(data.sample_shape) if data.compact
        else None,
    )
    key = jax.random.key(7)
    idx = np.asarray(algo.cohort_indices(key, 8))
    perm = np.array([2, 0, 3, 1])[: idx.size]
    idx_perm = idx[perm]

    def call(order, draw_pos):
        import jax.numpy as jnp

        take = lambda a: jnp.asarray(np.take(a, order, axis=0))  # noqa
        kw = {} if draw_pos is None else {
            "draw_pos": jnp.asarray(draw_pos, jnp.int32)
        }
        return round_fn(
            params, None, take(data.x), take(data.y), take(data.mask),
            take(data.sizes), jnp.asarray(order, jnp.int32), key, **kw
        )

    g_ref, _, aux_ref = call(idx, None)
    g_perm, _, aux_perm = call(idx_perm, perm)
    # Per-client outputs are bit-identical per CLIENT.
    ref_loss = np.asarray(aux_ref["client_loss"])
    perm_loss = np.asarray(aux_perm["client_loss"])
    assert np.array_equal(perm_loss, ref_loss[perm])
    # Fault draws followed the client too (survivor counts agree).
    assert int(aux_ref["survivor_count"]) == int(
        aux_perm["survivor_count"]
    )
    # The aggregate differs only by summation order.
    for a, b in zip(jax.tree_util.tree_leaves(g_ref),
                    jax.tree_util.tree_leaves(g_perm)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-6, atol=2e-7)
