"""Multi-chip sharding on the fake 8-device CPU mesh.

The sharded run must produce the SAME results as the single-device vmap run
— sharding the client axis is an execution detail, not a semantics change.
This is the test story the reference's dormant multi-process path never had
(reference servers/server.py:10-13, simulator.py:56).
"""

import dataclasses

import jax
import numpy as np

from distributed_learning_simulator_tpu.parallel.mesh import (
    make_mesh,
    shard_client_data,
)
from distributed_learning_simulator_tpu.simulator import run_simulation


def test_mesh_construction():
    mesh = make_mesh(8)
    assert mesh.devices.shape == (8,)
    assert mesh.axis_names == ("clients",)


def test_mesh_shortfall_fails_fast_without_optin(monkeypatch):
    """Requesting more devices than visible must raise unless the CPU
    fallback is explicitly opted into (production misconfig guard)."""
    import pytest

    monkeypatch.delenv("DLS_ALLOW_CPU_MESH_FALLBACK", raising=False)
    with pytest.raises(ValueError, match="DLS_ALLOW_CPU_MESH_FALLBACK"):
        make_mesh(len(jax.devices()) + 1)


def test_shard_client_data_placement():
    mesh = make_mesh(8)
    x = np.zeros((16, 4), np.float32)
    (sharded,) = shard_client_data((x,), mesh)
    assert len(sharded.sharding.device_set) == 8


def _accs(cfg, **overrides):
    cfg = dataclasses.replace(cfg, **overrides)
    res = run_simulation(cfg, setup_logging=False)
    return [h["test_accuracy"] for h in res["history"]]


def test_sharded_matches_unsharded_fedavg(tiny_config):
    base = _accs(tiny_config, worker_number=8, round=3)
    sharded = _accs(tiny_config, worker_number=8, round=3, mesh_devices=8)
    np.testing.assert_allclose(sharded, base, atol=1e-4)


def test_sharded_matches_unsharded_sign_sgd(tiny_config):
    base = _accs(tiny_config, worker_number=8, round=2,
                 distributed_algorithm="sign_SGD", learning_rate=0.01)
    sharded = _accs(tiny_config, worker_number=8, round=2,
                    distributed_algorithm="sign_SGD", learning_rate=0.01,
                    mesh_devices=8)
    np.testing.assert_allclose(sharded, base, atol=1e-4)


def test_chunked_sharded_composition_matches_baseline(tiny_config):
    """client_chunk_size < cohort composed WITH mesh sharding — the flagship
    large-model configuration (ResNet-18 at scale needs both at once) —
    must equal the unchunked, unsharded run."""
    base = _accs(tiny_config, worker_number=16, round=3)
    both = _accs(tiny_config, worker_number=16, round=3, mesh_devices=8,
                 client_chunk_size=4)
    np.testing.assert_allclose(both, base, atol=1e-4)


def test_chunked_sharded_remainder_matches_baseline(tiny_config):
    """Chunk size that does not divide the cohort (remainder path) composed
    with mesh sharding."""
    base = _accs(tiny_config, worker_number=16, round=2)
    both = _accs(tiny_config, worker_number=16, round=2, mesh_devices=8,
                 client_chunk_size=5)
    np.testing.assert_allclose(both, base, atol=1e-4)


def test_chunked_sharded_materializing_path(tiny_config):
    """The materializing path (robust aggregation keeps the full client
    stack) under chunking + sharding together."""
    base = _accs(tiny_config, worker_number=16, round=2,
                 aggregation="median")
    both = _accs(tiny_config, worker_number=16, round=2,
                 aggregation="median", mesh_devices=8, client_chunk_size=4)
    np.testing.assert_allclose(both, base, atol=1e-4)


def test_sharded_matches_unsharded_fed_quant(tiny_config):
    """fed_quant's per-client payload RNG (stochastic quantize keys split
    inside the round program) under sharding: jax.random values are
    placement-independent, so the sharded run must match the single-device
    run to reduction-order tolerance. client_eval off keeps the fused
    path — the composition the flagship uses at scale."""
    kw = dict(worker_number=8, round=3, distributed_algorithm="fed_quant",
              client_eval=False)
    base = _accs(tiny_config, **kw)
    sharded = _accs(tiny_config, mesh_devices=8, **kw)
    np.testing.assert_allclose(sharded, base, atol=1e-4)


def test_sharded_client_stack_multiround_shapley(tiny_config):
    """Exact-Shapley post_round consuming a SHARDED aux['client_params']
    stack through _SubsetEvaluator (subset weighted means = einsums over
    the sharded client axis): per-round SVs must match the unsharded run
    to fp-reduction tolerance."""
    kw = dict(worker_number=8, round=2,
              distributed_algorithm="multiround_shapley_value")
    base = run_simulation(
        dataclasses.replace(tiny_config, **kw), setup_logging=False
    )
    sharded = run_simulation(
        dataclasses.replace(tiny_config, mesh_devices=8, **kw),
        setup_logging=False,
    )
    for hb, hs in zip(base["history"], sharded["history"]):
        np.testing.assert_allclose(hs["test_accuracy"], hb["test_accuracy"],
                                   atol=1e-4)
        sv_b, sv_s = hb["shapley_values"], hs["shapley_values"]
        np.testing.assert_allclose(
            [sv_s[i] for i in sorted(sv_s)], [sv_b[i] for i in sorted(sv_b)],
            atol=1e-4,
        )


def test_sharded_client_stack_gtg(tiny_config):
    """GTG's data-dependent permutation walk driven by a sharded client
    stack (with shapley_eval_samples subsampling the utility evals): SVs
    finite, accuracy matches the unsharded run."""
    kw = dict(worker_number=8, round=2,
              distributed_algorithm="GTG_shapley_value",
              shapley_eval_samples=64)
    base = run_simulation(
        dataclasses.replace(tiny_config, **kw), setup_logging=False
    )
    sharded = run_simulation(
        dataclasses.replace(tiny_config, mesh_devices=8, **kw),
        setup_logging=False,
    )
    np.testing.assert_allclose(
        sharded["history"][-1]["test_accuracy"],
        base["history"][-1]["test_accuracy"], atol=1e-4,
    )
    sv = sharded["history"][0]["shapley_values"]
    assert all(np.isfinite(v) for v in sv.values())


def test_chunked_sharded_participation_sampling(tiny_config):
    """Client sampling (cohort < population) + chunking + sharding: the
    three execution knobs compose."""
    cfg = dataclasses.replace(
        tiny_config, worker_number=16, round=2, participation_fraction=0.5,
        client_chunk_size=4, mesh_devices=8,
    )
    res = run_simulation(cfg, setup_logging=False)
    assert len(res["history"]) == 2
    assert all(np.isfinite(h["test_accuracy"]) for h in res["history"])


def test_uneven_clients_rejected(tiny_config):
    import pytest

    cfg = dataclasses.replace(tiny_config, worker_number=6, mesh_devices=8)
    with pytest.raises(ValueError, match="multiple of"):
        run_simulation(cfg, setup_logging=False)


def _driver_subprocess(code):
    """Run `code` exactly as the driver invokes the graft entry: fresh
    interpreter, ONLY XLA_FLAGS set (no JAX_PLATFORMS, no conftest)."""
    import os
    import subprocess
    import sys

    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "DLS_ALLOW_CPU_MESH_FALLBACK")}
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    repo = os.path.join(os.path.dirname(__file__), "..")
    return subprocess.run([sys.executable, "-c", code], cwd=repo, env=env,
                          capture_output=True, text=True, timeout=600)


def test_graft_entry_dryrun_driver_identical():
    """dryrun_multichip must pin the platform itself so it never dispatches
    to an accelerator plugin, even one that sitecustomize force-registers
    ahead of JAX_PLATFORMS (the round-1 MULTICHIP failure mode)."""
    proc = _driver_subprocess(
        "import __graft_entry__; __graft_entry__.dryrun_multichip(8); "
        "print('DRYRUN_OK')"
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "DRYRUN_OK" in proc.stdout


def test_graft_entry_dryrun_rejects_initialized_accelerator():
    """If JAX already initialized on a non-CPU backend in this interpreter,
    dryrun_multichip must fail fast with a clear message — config.update
    is a silent no-op post-init, so silent accelerator dispatch is the
    alternative (the round-1 failure mode)."""
    code = (
        "import jax, jax.numpy as jnp\n"
        "try:\n"
        "    jnp.zeros(1).block_until_ready()\n"  # initialize default backend
        "except Exception:\n"
        "    print('BROKEN_ACCEL_INIT')\n"  # accel plugin broken: N/A here
        "    raise SystemExit(0)\n"
        "import __graft_entry__\n"
        "if jax.default_backend() == 'cpu':\n"
        "    print('CPU_ONLY_BOX')\n"  # no accelerator here: vacuous pass
        "else:\n"
        "    try:\n"
        "        __graft_entry__.dryrun_multichip(8)\n"
        "    except RuntimeError as e:\n"
        "        assert 'fresh process' in str(e), e\n"
        "        print('GUARD_RAISED')\n"
        "    else:\n"
        "        raise SystemExit('dryrun ran on initialized accelerator')\n"
    )
    proc = _driver_subprocess(code)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert any(s in proc.stdout for s in
               ("GUARD_RAISED", "CPU_ONLY_BOX", "BROKEN_ACCEL_INIT"))


def test_graft_entry_dryrun():
    """The driver's multi-chip compile check must pass on 8 virtual devices."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "__graft_entry__",
        os.path.join(os.path.dirname(__file__), "..", "__graft_entry__.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fn, args = mod.entry()
    out = jax.jit(fn)(*args)
    assert np.isfinite(np.asarray(out)).all()
    mod.dryrun_multichip(8)
