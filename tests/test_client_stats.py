"""telemetry/client_stats.py: in-program per-client statistics, the
median/MAD anomaly detector, and their wiring through every execution
path (docs/OBSERVABILITY.md § Client statistics).

Acceptance pins (ISSUE 4): client_stats='off' (the default) compiles an
identical program — bit-identical accuracy history to 'on', zero
post-warmup compiles, and byte-identical v2 metrics records; with
corrupt_nan/corrupt_scale injection active the detector flags exactly
the injected clients and stays silent on clean seeded runs (differential
test reusing the PR 2 fault harness); the fused and materializing
aggregation paths produce agreeing stats without the fused path ever
holding the client stack.
"""

import dataclasses
import glob
import json
import os

import jax
import numpy as np
import pytest

from distributed_learning_simulator_tpu.config import ExperimentConfig
from distributed_learning_simulator_tpu.robustness.faults import FailureModel
from distributed_learning_simulator_tpu.simulator import run_simulation
from distributed_learning_simulator_tpu.telemetry.client_stats import (
    STAT_FIELDS,
    ClientStats,
    attribution_crosscheck,
    client_stats_record,
    detect_anomalies,
)
from distributed_learning_simulator_tpu.utils.reporting import config_hash

_IDX = {name: i for i, name in enumerate(STAT_FIELDS)}


def _stats(n, update_norm=1.0, loss_after=2.0, nonfinite=0.0):
    s = np.zeros((n, len(STAT_FIELDS)))
    s[:, _IDX["loss_before"]] = 2.5
    s[:, _IDX["loss_after"]] = loss_after
    s[:, _IDX["update_norm"]] = update_norm
    s[:, _IDX["grad_norm"]] = 1.0
    s[:, _IDX["agg_cosine"]] = 0.9
    s[:, _IDX["nonfinite_count"]] = nonfinite
    return s


# --------------------------------------------------------------- detector


def test_detector_single_client_never_z_flags():
    """N=1: no population to compare against — only the non-finite rule
    can fire."""
    flagged, reasons = detect_anomalies(_stats(1, update_norm=1e9))
    assert flagged == []
    flagged, reasons = detect_anomalies(_stats(1, nonfinite=3.0))
    assert flagged == [0] and reasons[0] == "non_finite"


def test_detector_all_identical_updates_silent():
    """MAD 0 from identical rows must not flag float-jitter: the z
    denominator floors at a relative epsilon of the median."""
    s = _stats(8)
    s[3, _IDX["update_norm"]] += 1e-7  # float noise, not an anomaly
    assert detect_anomalies(s) == ([], {})


def test_detector_single_nan_client():
    """One all-NaN upload among healthy peers: exactly that client,
    reason non_finite — even though its norm/loss columns are NaN."""
    s = _stats(8)
    s[5, _IDX["nonfinite_count"]] = 1234.0
    s[5, _IDX["update_norm"]] = np.nan
    s[5, _IDX["loss_after"]] = np.nan
    flagged, reasons = detect_anomalies(s)
    assert flagged == [5] and reasons[5] == "non_finite"


def test_detector_scaled_outlier_high_side_only():
    """A 100x-norm upload is flagged via the robust z-score; a tiny-norm
    client (an empty shard) is NOT an anomaly (high side only)."""
    s = _stats(8)
    s[2, _IDX["update_norm"]] *= 100.0
    s[6, _IDX["update_norm"]] = 0.0
    flagged, reasons = detect_anomalies(s)
    assert flagged == [2] and reasons[2] == "update_norm"
    diverged = _stats(8)
    diverged[1, _IDX["loss_after"]] = 400.0
    flagged, reasons = detect_anomalies(diverged)
    assert flagged == [1] and reasons[1] == "loss_diverged"


def test_detector_majority_empty_shards_silent():
    """Empty-shard clients keep all-zero stats rows (the bucketed path's
    design); a mostly-empty cohort must not collapse the median to 0 and
    flag every honest client — zero-norm rows are excluded from the z
    population AND the flaggable set."""
    s = _stats(8)
    for i in range(5):  # 5 empty shards, 3 honest clients
        s[i] = 0.0
    assert detect_anomalies(s) == ([], {})
    # A genuine outlier among the active minority is still caught once
    # enough active clients exist.
    s = _stats(8)
    for i in range(4):
        s[i] = 0.0
    s[7, _IDX["update_norm"]] *= 100.0
    flagged, reasons = detect_anomalies(s)
    assert flagged == [7] and reasons[7] == "update_norm"


def test_detector_reasons_join():
    s = _stats(8)
    s[4, _IDX["nonfinite_count"]] = 1.0
    s[4, _IDX["update_norm"]] *= 100.0
    flagged, reasons = detect_anomalies(s)
    assert flagged == [4]
    assert set(reasons[4].split("+")) == {"non_finite", "update_norm"}


def test_record_builder_quantiles_cap_and_sanitization():
    s = _stats(4)
    s[1, _IDX["loss_after"]] = np.nan
    rec = client_stats_record(s, [1], {1: "non_finite"},
                              participants=np.asarray([7, 5, 3, 1]),
                              extras={"quant_mse": np.nan})
    assert rec["n_clients"] == 4
    assert rec["flagged_clients"] == [5]  # mapped through participants
    assert rec["flag_reason"] == {"5": "non_finite"}
    assert rec["per_client"]["client_ids"] == [7, 5, 3, 1]
    assert rec["per_client"]["loss_after"][1] is None  # NaN -> null
    assert rec["quant_mse"] is None  # extras sanitized too
    assert rec["quantiles"]["update_norm"]["p50"] == 1.0
    # Large cohorts: quantiles only, no per-client arrays.
    big = client_stats_record(_stats(33), [], {})
    assert "per_client" not in big and big["quantiles"]


def test_attribution_crosscheck():
    sv = np.asarray([0.1, 0.2, 0.3, 0.4])
    s = _stats(4)
    s[:, _IDX["loss_before"]] = 2.0 + sv  # improvement == sv
    s[:, _IDX["loss_after"]] = 2.0
    assert attribution_crosscheck(sv, s) == pytest.approx(1.0)
    assert attribution_crosscheck(np.zeros(4), s) is None  # degenerate
    assert attribution_crosscheck(sv[:1], s[:1]) is None  # too few


# ------------------------------------------------------------ config knobs


def test_from_config_and_validation(tiny_config):
    assert ClientStats.from_config(tiny_config) is None
    on = ClientStats.from_config(
        dataclasses.replace(tiny_config, client_stats="on",
                            client_stats_every=3)
    )
    assert on is not None and on.every == 3
    assert on.fetch_round(0) and not on.fetch_round(2) and on.fetch_round(3)
    with pytest.raises(ValueError, match="client_stats"):
        dataclasses.replace(tiny_config, client_stats="loud").validate()
    with pytest.raises(ValueError, match="client_stats_every"):
        dataclasses.replace(
            tiny_config, client_stats_every=0
        ).validate()
    # The knobs are program-defining: they land in the bench provenance
    # hash (compare_bench's comparability refusal covers them).
    h = config_hash(tiny_config)
    assert config_hash(
        dataclasses.replace(tiny_config, client_stats="on")
    ) != h
    assert config_hash(
        dataclasses.replace(tiny_config, client_stats_probe=128)
    ) != h
    # The detector threshold is host-side only — tuning it must keep
    # bench runs comparable.
    assert config_hash(
        dataclasses.replace(tiny_config, client_stats_mad_threshold=4.0)
    ) == h


# ------------------------------------------------------------- integration


def _records(log_root):
    metrics = glob.glob(
        os.path.join(log_root, "**", "metrics.jsonl"), recursive=True
    )
    assert len(metrics) == 1
    with open(metrics[0]) as f:
        return [json.loads(line) for line in f]


def _validate_schema(records):
    import jsonschema

    with open(os.path.join(os.path.dirname(__file__), "data",
                           "metrics_record.schema.json")) as f:
        schema = json.load(f)
    for r in records:
        jsonschema.validate(r, schema)


def test_off_is_identical_program_and_v2_records(tiny_config, tmp_path):
    """The acceptance pin: client_stats='off' + telemetry keeps the
    byte-identical v2 record layout and zero post-warmup compiles, and
    'on' trains BIT-identically (no RNG consumed, no math changed) while
    upgrading records to v3 — with zero false positives on this clean
    seeded run."""
    cfg_off = dataclasses.replace(
        tiny_config, round=3, telemetry_level="basic",
        compilation_cache_dir=None, log_root=str(tmp_path / "off"),
    )
    assert cfg_off.client_stats == "off"
    r_off = run_simulation(cfg_off)
    off_records = _records(cfg_off.log_root)
    assert r_off["post_warmup_compiles"] == 0
    assert r_off["clients_flagged"] is None
    for r in off_records:
        assert r["schema_version"] == 2
        assert "client_stats" not in r
        assert set(r) == {
            "round", "test_accuracy", "test_loss", "mean_client_loss",
            "round_seconds", "schema_version", "telemetry",
        }
    _validate_schema(off_records)

    cfg_on = dataclasses.replace(
        cfg_off, client_stats="on", log_root=str(tmp_path / "on"),
    )
    r_on = run_simulation(cfg_on)
    on_records = _records(cfg_on.log_root)
    assert r_on["post_warmup_compiles"] == 0
    assert r_on["clients_flagged"] == 0  # no false positives, clean run
    # Identical program: the stats ride along without touching training.
    assert [h["test_accuracy"] for h in r_on["history"]] == [
        h["test_accuracy"] for h in r_off["history"]
    ]
    for r in on_records:
        assert r["schema_version"] == 3
        cs = r["client_stats"]
        assert cs["flagged_clients"] == []
        assert cs["n_clients"] == tiny_config.worker_number
        assert set(cs["quantiles"]) == set(STAT_FIELDS)
        assert cs["quantiles"]["nonfinite_count"]["p100"] == 0.0
        assert cs["quantiles"]["update_norm"]["p0"] > 0.0
        assert len(cs["per_client"]["loss_after"]) == cfg_on.worker_number
    _validate_schema(on_records)


def _injected_per_round(cfg, n, rounds):
    """Replay the simulator's round-key chain (the same splits
    fedavg.round_fn makes) to recover which clients the failure model
    corrupted each round — the PR 2 fault harness as detection oracle."""
    fm = FailureModel.from_config(cfg)
    key = jax.random.key(cfg.seed + 1)
    out = []
    for _ in range(rounds):
        key, round_key = jax.random.split(key)
        fault_key = jax.random.split(round_key, 5)[4]
        failed = np.asarray(fm.draw_failed(fault_key, n))
        out.append(sorted(np.flatnonzero(failed).tolist()))
    return out


def test_detector_flags_exactly_injected_corrupt_nan(tiny_config):
    cfg = dataclasses.replace(
        tiny_config, worker_number=8, round=3, client_stats="on",
        failure_mode="corrupt_nan", failure_prob=0.4, min_survivors=1,
    )
    r = run_simulation(cfg, setup_logging=False)
    injected = _injected_per_round(cfg, 8, 3)
    assert any(injected), "seeded run must inject at least once"
    for h, inj in zip(r["history"], injected):
        cs = h["client_stats"]
        assert cs["flagged_clients"] == inj
        assert all(
            "non_finite" in cs["flag_reason"][str(i)] for i in inj
        )
    assert r["clients_flagged"] == sum(len(i) for i in injected)


def test_detector_flags_exactly_injected_corrupt_scale(tiny_config):
    """Finite Byzantine garbage (x100 uploads): caught by the update-norm
    z-score on every round with an honest majority (the detector's
    documented assumption — shared with the robust aggregation rules)."""
    cfg = dataclasses.replace(
        tiny_config, worker_number=8, round=3, client_stats="on",
        failure_mode="corrupt_scale", failure_prob=0.3,
    )
    r = run_simulation(cfg, setup_logging=False)
    injected = _injected_per_round(cfg, 8, 3)
    checked = 0
    for h, inj in zip(r["history"], injected):
        if len(inj) > 4:  # poisoned median: out of the detector's contract
            continue
        checked += 1
        assert h["client_stats"]["flagged_clients"] == inj
        for i in inj:
            assert h["client_stats"]["flag_reason"][str(i)] == "update_norm"
    assert checked and any(injected)


def test_fused_and_materializing_stats_agree(tiny_config):
    """The fused path's streaming per-chunk stats must agree with the
    materializing path's whole-stack stats (client_eval=True forces the
    stack): same fault points, same stat definitions."""
    base = dataclasses.replace(
        tiny_config, round=2, client_stats="on", client_chunk_size=2,
    )
    fused = run_simulation(
        dataclasses.replace(base, client_eval=False), setup_logging=False
    )
    mat = run_simulation(
        dataclasses.replace(base, client_eval=True), setup_logging=False
    )
    for hf, hm in zip(fused["history"], mat["history"]):
        pf, pm = (h["client_stats"]["per_client"] for h in (hf, hm))
        assert pf["client_ids"] == pm["client_ids"]
        for field in STAT_FIELDS:
            np.testing.assert_allclose(
                np.asarray(pf[field], dtype=np.float64),
                np.asarray(pm[field], dtype=np.float64),
                rtol=2e-4, atol=1e-6, err_msg=field,
            )


def test_bucketed_path_reports_stats(tiny_config):
    """Size-aware scheduling (Dirichlet skew + chunking) scatters the
    per-client stats back to original positions; empty clients keep
    zero rows and are never flagged (high-side rules)."""
    cfg = dataclasses.replace(
        tiny_config, round=2, client_stats="on", client_chunk_size=2,
        partition="dirichlet",
    )
    r = run_simulation(cfg, setup_logging=False)
    for h in r["history"]:
        cs = h["client_stats"]
        assert cs["flagged_clients"] == []
        assert cs["n_clients"] == cfg.worker_number
        assert cs["quantiles"]["nonfinite_count"]["p100"] == 0.0


def test_cadence_and_participant_mapping(tiny_config):
    """client_stats_every=2 fetches rounds 0 and 2 only (round 1 keeps
    its un-upgraded record), and sampled cohorts report TRUE client ids
    through aux['participants']."""
    cfg = dataclasses.replace(
        tiny_config, worker_number=8, round=3, client_stats="on",
        client_stats_every=2, participation_fraction=0.5,
    )
    r = run_simulation(cfg, setup_logging=False)
    h0, h1, h2 = r["history"]
    assert "client_stats" in h0 and "client_stats" in h2
    assert "client_stats" not in h1 and "schema_version" not in h1
    for h in (h0, h2):
        ids = h["client_stats"]["per_client"]["client_ids"]
        assert h["client_stats"]["n_clients"] == 4
        assert len(set(ids)) == 4 and set(ids) <= set(range(8))


def test_sign_sgd_vote_agreement(tiny_config):
    """sign_SGD exposes the per-step majority-vote agreement fraction as
    a round statistic (0.5 = coin-flip directions, 1.0 = unanimous)."""
    cfg = dataclasses.replace(
        tiny_config, distributed_algorithm="sign_SGD", learning_rate=0.01,
        client_stats="on",
    )
    off = run_simulation(
        dataclasses.replace(cfg, client_stats="off"), setup_logging=False
    )
    r = run_simulation(cfg, setup_logging=False)
    for h in r["history"]:
        cs = h["client_stats"]
        assert 0.5 <= cs["vote_agreement"] <= 1.0
        assert "flagged_clients" not in cs  # no per-client deltas to score
    # The agreement reduction is a pure read of the vote sum: identical
    # training either way.
    assert [h["test_accuracy"] for h in r["history"]] == [
        h["test_accuracy"] for h in off["history"]
    ]


def test_fed_quant_quantization_mse(tiny_config):
    """fed_quant reports the downlink quantization MSE — nonzero, small,
    and consistent with 8-bit stochastic rounding."""
    cfg = dataclasses.replace(
        tiny_config, distributed_algorithm="fed_quant", client_stats="on",
    )
    r = run_simulation(cfg, setup_logging=False)
    for h in r["history"]:
        mse = h["client_stats"]["quant_mse"]
        assert mse is not None and 0.0 < mse < 1e-3


def test_shapley_attribution_crosscheck(tiny_config):
    """The Shapley servers cross-check their utility attribution against
    the in-round stats (SV vs local loss improvement correlation)."""
    cfg = dataclasses.replace(
        tiny_config, distributed_algorithm="multiround_shapley_value",
        client_stats="on",
    )
    r = run_simulation(cfg, setup_logging=False)
    corrs = [h.get("sv_stats_corr") for h in r["history"]]
    assert any(c is not None for c in corrs)
    assert all(c is None or -1.0 <= c <= 1.0 for c in corrs)


def test_threaded_client_stats(tmp_path):
    """The threaded oracle reports stats off its rendezvous stack through
    the same shared record builder: update-norm/cosine columns live,
    loss columns null (its workers report no losses)."""
    cfg = ExperimentConfig(
        dataset_name="synthetic", model_name="mlp",
        distributed_algorithm="fed", worker_number=2, round=2, epoch=1,
        learning_rate=0.1, batch_size=32, n_train=128, n_test=64,
        log_level="WARNING", dataset_args={"difficulty": 0.5},
        execution_mode="threaded", client_stats="on",
        compilation_cache_dir=None, log_root=str(tmp_path / "log"),
    )
    result = run_simulation(cfg)
    assert result["clients_flagged"] == 0  # same contract as vmap
    records = _records(cfg.log_root)
    assert len(records) == 2
    for r in records:
        assert r["schema_version"] == 3
        cs = r["client_stats"]
        assert cs["quantiles"]["update_norm"]["p50"] > 0.0
        assert cs["per_client"]["loss_after"] == [None, None]
        assert cs["flagged_clients"] == []
    _validate_schema(records)
