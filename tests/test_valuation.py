"""Always-on client valuation (telemetry/valuation.py, ISSUE 9).

Pins the streaming estimator's exact arithmetic (hand-computed 3-client
decay trace), the correlation helpers, the off-gate bit-identity
contract (client_valuation='off' = the exact pre-feature program and
records; config_hash unchanged for pre-feature configs), streamed-
residency scatter parity, checkpoint/resume of the valuation vector,
the truncated-GTG audit on the graded-quality differential config
(fidelity >= the compare_bench gate's default floor), and the GTG
cross-round memo (ROADMAP item 4b).
"""

import dataclasses
import json
import os

import jsonschema
import numpy as np
import pytest

from distributed_learning_simulator_tpu.config import ExperimentConfig
from distributed_learning_simulator_tpu.data.registry import get_dataset
from distributed_learning_simulator_tpu.telemetry.valuation import (
    ClientValuation,
    ValuationState,
    grade_client_labels,
    pearson_corr,
    spearman_corr,
    valuation_record,
)
from distributed_learning_simulator_tpu.utils.reporting import config_hash

_SCHEMA_PATH = os.path.join(
    os.path.dirname(__file__), "data", "metrics_record.schema.json"
)


def _validate_record(record: dict) -> None:
    with open(_SCHEMA_PATH) as f:
        jsonschema.validate(record, json.load(f))


def _tiny(**kw) -> ExperimentConfig:
    base = dict(
        dataset_name="synthetic", model_name="mlp",
        distributed_algorithm="fed", worker_number=6, round=4, epoch=1,
        learning_rate=0.1, batch_size=32, n_train=512, n_test=256,
        log_level="WARNING", dataset_args={"difficulty": 0.5},
        compilation_cache_dir=None,
    )
    base.update(kw)
    return ExperimentConfig(**base)


def _run(config, **kw):
    from distributed_learning_simulator_tpu.simulator import run_simulation

    return run_simulation(config, setup_logging=False, **kw)


# ---- pure host-side arithmetic ---------------------------------------------


def test_scores_hand_computed():
    """cos * norm, non-finite zeroed, unit-L1 normalized — against the
    stats-matrix column layout (STAT_FIELDS order)."""
    import jax.numpy as jnp

    from distributed_learning_simulator_tpu.telemetry.client_stats import (
        STAT_FIELDS,
    )

    cv = ClientValuation()
    n = 3
    stats = np.zeros((n, len(STAT_FIELDS)))
    cols = {name: i for i, name in enumerate(STAT_FIELDS)}
    stats[:, cols["agg_cosine"]] = [0.8, -0.5, np.nan]
    stats[:, cols["update_norm"]] = [2.0, 1.0, 3.0]
    out = np.asarray(cv.scores(jnp.asarray(stats, jnp.float32)))
    raw = np.array([1.6, -0.5, 0.0])  # NaN row zeroed
    expect = raw / np.abs(raw).sum()
    np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_fold_hand_computed_3_client_trace():
    """The exponential-decay fold, scatter semantics included, against a
    hand trace: v <- d*v + (1-d)*loss_delta*score for participants,
    untouched for everyone else."""
    st = ValuationState(3)
    d = 0.5
    # Round 1: all participate, delta 0.1, scores (0.5, 0.3, 0.2).
    st.fold(None, np.array([0.5, 0.3, 0.2]), 0.1, d)
    np.testing.assert_allclose(st.values, [0.025, 0.015, 0.010])
    # Round 2: cohort {0, 2}, delta -0.2 (the round HURT), scores
    # (0.6, 0.4) -> those entries move toward negative credit; client 1
    # keeps its value exactly.
    st.fold(np.array([0, 2]), np.array([0.6, 0.4]), -0.2, d)
    np.testing.assert_allclose(
        st.values,
        [0.5 * 0.025 + 0.5 * (-0.2 * 0.6),
         0.015,
         0.5 * 0.010 + 0.5 * (-0.2 * 0.4)],
    )
    # Round 3: non-finite scores contribute 0, not NaN poison.
    st.fold(np.array([1]), np.array([np.nan]), 0.3, d)
    assert st.values[1] == pytest.approx(0.5 * 0.015)
    assert np.isfinite(st.values).all()


def test_correlations_hand_computed():
    # Perfectly monotonic but non-linear: spearman 1, pearson < 1.
    a = np.array([1.0, 2.0, 3.0, 4.0])
    b = np.array([1.0, 10.0, 100.0, 1000.0])
    assert spearman_corr(a, b) == pytest.approx(1.0)
    assert 0 < pearson_corr(a, b) < 1.0
    # Reversed ranking.
    assert spearman_corr(a, -b) == pytest.approx(-1.0)
    # Ties take average ranks: hand value via the classic formula on
    # ranks [0, 1.5, 1.5, 3] vs [0, 1, 2, 3].
    t = np.array([1.0, 2.0, 2.0, 3.0])
    ra = np.array([0.0, 1.5, 1.5, 3.0])
    rb = np.array([0.0, 1.0, 2.0, 3.0])
    expect = float(np.corrcoef(ra, rb)[0, 1])
    assert spearman_corr(t, a) == pytest.approx(expect)
    # Degenerate inputs -> None, never a crash.
    assert spearman_corr(np.zeros(4), a) is None
    assert pearson_corr(np.array([1.0]), np.array([2.0])) is None
    assert spearman_corr(
        np.array([np.nan, np.nan, 1.0]), np.array([1.0, 2.0, 3.0])
    ) is None


def test_valuation_record_shape_and_cap():
    st = ValuationState(4)
    st.fold(None, np.array([0.4, 0.3, 0.2, 0.1]), 0.5, 0.0)
    rec = valuation_record(st, np.array([0, 1, 2, 3]), 0.5)
    assert rec["n_clients"] == 4 and rec["updated"] == 4
    assert rec["top_clients"][0]["id"] == 0
    assert rec["bottom_clients"][0]["id"] == 3
    assert rec["per_client"]["value"] == [
        pytest.approx(v) for v in (0.2, 0.15, 0.1, 0.05)
    ]
    # Above the cap: no raw per-client dump (metrics.jsonl bloat rule).
    big = ValuationState(64)
    rec = valuation_record(big, None, 0.0)
    assert "per_client" not in rec and rec["updated"] == 64


# ---- off-gate + config-hash invariance -------------------------------------


def test_off_gate_bit_identity_and_records(tiny_dataset):
    """client_valuation='off' with client_stats='on' is the exact PR 4
    program (v3 records, no valuation key); turning valuation ON changes
    records to v7 but must NOT change the training trajectory (the
    scores are a pure extra output of existing intermediates)."""
    import jax

    base = _tiny(client_stats="on")
    off = _run(base, dataset=tiny_dataset)
    on = _run(
        dataclasses.replace(base, client_valuation="on"),
        dataset=tiny_dataset,
    )
    for rec in off["history"]:
        assert rec["schema_version"] == 3
        assert "valuation" not in rec
    for rec in on["history"]:
        assert rec["schema_version"] == 7
        assert rec["valuation"]["n_clients"] == 6
        _validate_record(rec)
    # Bit-identical training history.
    for leaf_off, leaf_on in zip(
        jax.tree_util.tree_leaves(off["global_params"]),
        jax.tree_util.tree_leaves(on["global_params"]),
    ):
        np.testing.assert_array_equal(
            np.asarray(leaf_off), np.asarray(leaf_on)
        )
    accs_off = [r["test_accuracy"] for r in off["history"]]
    accs_on = [r["test_accuracy"] for r in on["history"]]
    assert accs_off == accs_on
    assert off["valuation"] is None and off["valuation_state"] is None
    assert on["valuation_state"] is not None
    assert on["client_valuation"] == "on"
    # Batched dispatch (rounds_per_dispatch=2): stacked [K, N] score rows
    # fold per round through the shared emit_record tail — same vector,
    # same v7 records, as the K=1 loop.
    batched = _run(
        dataclasses.replace(base, client_valuation="on",
                            rounds_per_dispatch=2),
        dataset=tiny_dataset,
    )
    np.testing.assert_array_equal(
        on["valuation_state"].values, batched["valuation_state"].values
    )
    assert all(
        r["schema_version"] == 7 and "valuation" in r
        for r in batched["history"]
    )


def test_config_hash_off_gate_invariance():
    """Pre-feature configs keep their pre-feature hash: at 'off' every
    valuation knob (and gtg_cross_round_memo=False) drops out of the
    hash, so longitudinal bench comparability survives the feature
    landing; any active setting lands all its knobs."""
    cfg = _tiny()
    h_default = config_hash(cfg)
    # Simulate the pre-feature hash: asdict without the new fields.
    import hashlib

    d = dataclasses.asdict(cfg)
    from distributed_learning_simulator_tpu.utils.reporting import (
        _NON_PROGRAM_FIELDS,
    )

    for k in _NON_PROGRAM_FIELDS + (
        "client_valuation", "valuation_decay", "valuation_audit_every",
        "valuation_audit_permutations", "gtg_cross_round_memo",
        # Off-gated at its 'exact' default like the valuation knobs
        # (ISSUE 10, ops/sampling.py).
        "participation_sampler",
        # Off-gated at their inactive defaults (ISSUE 11, sweep/):
        # persistence knobs sit in _NON_PROGRAM_FIELDS already.
        "sweep_seeds", "sweep_points", "sweep_strategy",
        # Off-gated at 'static' (ISSUE 13, robustness/population.py).
        "population", "population_seed", "join_rate", "depart_rate",
        "drift_fraction", "drift_factor",
    ):
        d.pop(k, None)
    pre_feature = hashlib.sha256(
        json.dumps(d, sort_keys=True, default=repr).encode()
    ).hexdigest()[:12]
    assert h_default == pre_feature
    # Off-mode knob tweaks don't move the hash (the program is
    # untouched); activation does, and then every knob lands.
    assert config_hash(
        dataclasses.replace(cfg, valuation_decay=0.5)
    ) == h_default
    on = dataclasses.replace(
        cfg, client_stats="on", client_valuation="on"
    )
    h_on = config_hash(on)
    assert h_on != config_hash(dataclasses.replace(cfg, client_stats="on"))
    assert config_hash(
        dataclasses.replace(on, valuation_decay=0.5)
    ) != h_on
    assert config_hash(
        dataclasses.replace(cfg, gtg_cross_round_memo=True)
    ) != h_default


def test_validate_refusals():
    with pytest.raises(ValueError, match="client_stats='on'"):
        _tiny(client_valuation="on").validate()
    with pytest.raises(ValueError, match="sign_SGD"):
        _tiny(distributed_algorithm="sign_SGD", client_stats="on",
              client_valuation="on").validate()
    with pytest.raises(ValueError, match="vmap"):
        _tiny(execution_mode="threaded", client_stats="on",
              client_valuation="on").validate()
    with pytest.raises(ValueError, match="streaming vector to audit"):
        _tiny(valuation_audit_every=2).validate()
    ok = dict(client_stats="on", client_valuation="on",
              valuation_audit_every=2)
    _tiny(**ok).validate()
    with pytest.raises(ValueError, match="failure injection"):
        _tiny(failure_mode="dropout", failure_prob=0.5, **ok).validate()
    with pytest.raises(ValueError, match="'fed' only"):
        # fed_quant's per-chunk upload-quantization keys cannot be
        # replayed exactly on a whole-stack audit.
        _tiny(distributed_algorithm="fed_quant", **ok).validate()
    with pytest.raises(ValueError, match="rounds_per_dispatch"):
        _tiny(rounds_per_dispatch=2, **ok).validate()
    with pytest.raises(ValueError, match="reset_client_optimizer"):
        _tiny(reset_client_optimizer=False, **ok).validate()
    with pytest.raises(ValueError, match="weighted-mean"):
        _tiny(aggregation="median", **ok).validate()
    with pytest.raises(ValueError, match="valuation_decay"):
        _tiny(valuation_decay=1.0).validate()


# ---- residency / resume ----------------------------------------------------


def test_streamed_residency_scatter_parity(tiny_dataset):
    """Streamed residency is bit-identical to resident (the PR 7
    contract), so the valuation vector — folded from the same fetched
    scores under participation sampling — must match exactly, and under
    'streamed' it must live IN the host shard store."""
    base = _tiny(
        worker_number=8, participation_fraction=0.5, round=4,
        client_stats="on", client_valuation="on",
    )
    resident = _run(base, dataset=tiny_dataset)
    streamed = _run(
        dataclasses.replace(base, client_residency="streamed"),
        dataset=tiny_dataset,
    )
    v_res = resident["valuation_state"].values
    v_str = streamed["valuation_state"].values
    np.testing.assert_array_equal(v_res, v_str)
    # Sampling at 0.5: some clients were never drawn and sit at exactly
    # 0 — the scatter leaves non-participants untouched.
    assert (v_res != 0).any()
    for rec in streamed["history"]:
        assert rec["schema_version"] == 7
        assert rec["valuation"]["updated"] == 4
    # The store owns the vector under streamed residency.
    assert streamed["valuation_state"]._store is not None
    assert (
        streamed["valuation_state"]._store.valuation
        is streamed["valuation_state"].values
    )


def test_checkpoint_resume_restores_vector(tiny_dataset, tmp_path):
    """A resumed run's valuation vector continues bit-exactly from the
    checkpoint — same contract as every other piece of carried state."""
    ckpt = str(tmp_path / "ckpt")
    base = _tiny(
        round=4, client_stats="on", client_valuation="on",
        checkpoint_dir=ckpt, checkpoint_every=2,
    )
    full = _run(base, dataset=tiny_dataset)
    # Simulate a crash after round 1's checkpoint: wipe the completed
    # run's later checkpoint so resume restarts mid-run from round 1.
    late = os.path.join(ckpt, "round_3.ckpt")
    assert os.path.exists(late)
    os.remove(late)
    resumed = _run(
        dataclasses.replace(base, resume=True), dataset=tiny_dataset,
    )
    np.testing.assert_array_equal(
        full["valuation_state"].values, resumed["valuation_state"].values
    )
    accs_full = [r["test_accuracy"] for r in full["history"]]
    accs_res = [r["test_accuracy"] for r in resumed["history"]]
    assert accs_full[2:] == accs_res


# ---- audit + cross-round memo ----------------------------------------------


def test_audit_fidelity_on_graded_differential():
    """The acceptance differential: a monotonic data-quality gradient
    (grade_client_labels), streaming vector vs cumulative truncated-GTG
    audit SVs — Spearman must clear compare_bench's default
    --valuation-corr-threshold floor (0.8). Also pins the audit's
    schema, its purity (training history identical with audits off),
    and that the valuation ranking itself recovers the gradient."""
    n, rounds = 8, 9
    config = _tiny(
        worker_number=n, round=rounds, n_train=1024, n_test=2048,
        client_stats="on", client_valuation="on",
        valuation_audit_every=2, valuation_audit_permutations=500,
        gtg_eps=1e-4,
    )
    ds = get_dataset(
        "synthetic", n_train=1024, n_test=2048, seed=0, difficulty=0.5
    )
    from distributed_learning_simulator_tpu.simulator import (
        build_client_data,
    )

    cd = build_client_data(config, ds)
    cd.y[:] = grade_client_labels(cd.y, ds.num_classes, seed=1)
    result = _run(config, dataset=ds, client_data=cd)
    audits = [
        r["valuation"]["audit"] for r in result["history"]
        if "audit" in r.get("valuation", {})
    ]
    assert len(audits) == 4  # rounds 2, 4, 6, 8
    assert audits[-1]["audits"] == 4
    last = result["valuation"]["last_audit"]
    assert last["spearman"] >= 0.8
    # Fresh memos by default: no cross-round reuse is reported.
    assert all(a["memo_hit_rate"] is None for a in audits)
    for r in result["history"]:
        _validate_record(r)
    # The streaming ranking itself recovers the quality gradient:
    # cleaner clients (lower index) valued higher.
    v = result["valuation_state"].values
    assert spearman_corr(v, -np.arange(n, dtype=float)) >= 0.9
    # Audit purity: the same run with audits off trains identically.
    no_audit = _run(
        dataclasses.replace(config, valuation_audit_every=0),
        dataset=ds, client_data=cd,
    )
    assert (
        [r["test_accuracy"] for r in no_audit["history"]]
        == [r["test_accuracy"] for r in result["history"]]
    )
    np.testing.assert_array_equal(v, no_audit["valuation_state"].values)


def test_report_run_flagged_overlay():
    """scripts/report_run.py's valuation section: the flagged-client
    overlay pairs each detector-flagged id with its valuation value and
    descending-value rank (jax-free, synthetic records)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "report_run",
        os.path.join(
            os.path.dirname(__file__), "..", "scripts", "report_run.py"
        ),
    )
    rr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rr)
    records = [{
        "round": 1, "test_accuracy": 0.5, "test_loss": 1.0,
        "round_seconds": 0.1, "schema_version": 7,
        "client_stats": {
            "n_clients": 4, "flagged_clients": [2],
            "flag_reason": {"2": "update_norm"}, "quantiles": {},
        },
        "valuation": {
            "n_clients": 4, "updated": 4, "loss_delta": 0.05,
            "top_clients": [{"id": 0, "value": 0.4}],
            "bottom_clients": [{"id": 2, "value": -0.1}],
            "per_client": {
                "client_ids": [0, 1, 2, 3],
                "value": [0.4, 0.2, -0.1, 0.3],
            },
            "audit": {
                "spearman": 0.9, "pearson": 0.8, "spearman_round": 0.9,
                "audits": 1, "permutations": 10, "subset_evals": 20,
                "converged": True, "memo_hit_rate": None, "seconds": 0.2,
            },
        },
    }]
    summary = rr.summarize_run(records)
    overlay = summary["valuation"]["flagged_overlay"]
    assert overlay == [{"id": 2, "value": -0.1, "rank": 3}]
    assert summary["valuation"]["last_audit"]["spearman"] == 0.9
    lines = "\n".join(rr.render_summary(summary))
    assert "flagged client 2" in lines and "GTG audit" in lines


def test_gtg_cross_round_memo(tiny_dataset):
    """ROADMAP item 4b: with gtg_cross_round_memo=True the GTG server
    reuses interior subset utilities across rounds of the same cohort —
    hit rate recorded in the round record and the result dict; the
    default (off) keeps pre-feature records exactly."""
    base = _tiny(
        worker_number=4, round=3,
        distributed_algorithm="GTG_shapley_value",
        round_trunc_threshold=0.0,
    )
    off = _run(base, dataset=tiny_dataset)
    assert off["gtg_memo_hit_rate"] is None
    assert all(
        "gtg_memo_hit_rate" not in r for r in off["history"]
    )
    on = _run(
        dataclasses.replace(base, gtg_cross_round_memo=True),
        dataset=tiny_dataset,
    )
    rates = [
        r["gtg_memo_hit_rate"] for r in on["history"]
        if "gtg_memo_hit_rate" in r
    ]
    # Round 0 has nothing to reuse (rate 0); later rounds walk the same
    # cohort and MUST find seeded interior subsets.
    assert rates and rates[0] == 0.0
    assert max(rates[1:]) > 0.0
    assert on["gtg_memo_hit_rate"] == rates[-1]
    # Same permutation stream either way (the memo changes utilities
    # reused, never the RNG): permutation counts match round 0, where
    # no seeding existed yet.
    assert (
        on["history"][0]["gtg_permutations"]
        == off["history"][0]["gtg_permutations"]
    )
    assert (
        on["history"][0]["gtg_subset_evals"]
        == off["history"][0]["gtg_subset_evals"]
    )
