"""utils/tracing.py: device-trace parsing against synthetic fixtures.

iter_device_ops / parse_device_trace define the event-selection rule the
bench regression proxy depends on (complete 'X' events with XLA op
annotations, wrapper ``while``/``jit(`` frames excluded). These tests pin
that rule with hand-built gzipped ``*.trace.json.gz`` fixtures, so a
selection-rule regression shows up here instead of as a silently shifted
proxy baseline.
"""

import gzip
import json
import os

import pytest

from distributed_learning_simulator_tpu.utils.tracing import (
    device_op_report,
    iter_device_ops,
    parse_device_trace,
    top_device_ops,
)

GIB = 2**30


def _write_trace(root, events, run="run1", fname="host.trace.json.gz"):
    """Lay out the jax.profiler directory shape the parser globs:
    ``<root>/plugins/profile/<run>/<fname>``."""
    d = os.path.join(root, "plugins", "profile", run)
    os.makedirs(d, exist_ok=True)
    with gzip.open(os.path.join(d, fname), "wt") as f:
        json.dump({"traceEvents": events}, f)


def _op(name, dur_us, nbytes=None, long_name=None):
    args = {}
    if nbytes is not None:
        args["raw_bytes_accessed"] = nbytes
    if long_name is not None:
        args["long_name"] = long_name
    return {"ph": "X", "name": name, "dur": dur_us, "args": args}


def test_selection_rule_and_aggregation(tmp_path):
    """Annotated X events are summed; wrapper frames, non-X phases, and
    unannotated host events are excluded even when they carry bytes."""
    events = [
        _op("fusion.1", 100.0, nbytes=GIB, long_name="fusion kernel"),
        _op("copy.2", 50.0, nbytes=GIB // 2),
        # Wrapper frames: would double count their children's bytes/time.
        _op("while", 1000.0, nbytes=100 * GIB),
        _op("jit(round_fn)", 800.0, nbytes=100 * GIB, long_name="jit frame"),
        # Non-X phase events are skipped outright.
        {"ph": "M", "name": "process_name", "args": {"name": "meta"}},
        # X event with no op annotation (host lane) is skipped.
        {"ph": "X", "name": "host_callback", "dur": 5.0},
        # long_name alone qualifies (CPU traces carry no byte counts).
        _op("dot.3", 25.0, long_name="dot_general"),
    ]
    _write_trace(str(tmp_path), events)
    ops = list(iter_device_ops(str(tmp_path)))
    assert sorted(ev["name"] for ev in ops) == [
        "copy.2", "dot.3", "fusion.1",
    ]
    stats = parse_device_trace(str(tmp_path))
    assert stats["op_count"] == 3
    assert stats["device_ms"] == (100.0 + 50.0 + 25.0) / 1e3
    assert stats["bytes_gb"] == (GIB + GIB // 2) / GIB


def test_wrapper_exclusion_is_prefix_based(tmp_path):
    """The exclusion rule is the documented name-PREFIX match: any
    ``while*``/``jit(*`` name is a wrapper, whatever its suffix."""
    events = [
        _op("while.body.fusion", 10.0, nbytes=GIB),  # prefix 'while' -> out
        _op("jit(train_step)/mul", 10.0, nbytes=GIB),  # prefix 'jit(' -> out
        _op("jitted_mul", 10.0, nbytes=GIB),  # 'jit' but not 'jit(' -> in
    ]
    _write_trace(str(tmp_path), events)
    names = [ev["name"] for ev in iter_device_ops(str(tmp_path))]
    assert names == ["jitted_mul"]


def test_missing_and_empty_dirs_yield_nothing(tmp_path):
    """Missing/empty trace dirs parse to zeros, never raise (bench's
    proxy leg must degrade, not crash, when a trace comes back empty)."""
    missing = str(tmp_path / "nope")
    assert list(iter_device_ops(missing)) == []
    assert parse_device_trace(missing) == {
        "device_ms": 0.0, "bytes_gb": 0.0, "op_count": 0,
    }
    empty = tmp_path / "empty"
    empty.mkdir()
    assert parse_device_trace(str(empty)) == {
        "device_ms": 0.0, "bytes_gb": 0.0, "op_count": 0,
    }
    # A session dir whose trace holds no events at all.
    _write_trace(str(tmp_path / "blank"), [])
    assert parse_device_trace(str(tmp_path / "blank"))["op_count"] == 0


def test_multiple_trace_files_are_summed(tmp_path):
    """Every *.trace.json.gz under the dir contributes (the documented
    one-session-per-dir contract: a reused dir accumulates)."""
    _write_trace(str(tmp_path), [_op("a", 10.0, nbytes=GIB)],
                 fname="one.trace.json.gz")
    _write_trace(str(tmp_path), [_op("b", 20.0, nbytes=GIB)],
                 fname="two.trace.json.gz")
    stats = parse_device_trace(str(tmp_path))
    assert stats["op_count"] == 2
    assert stats["bytes_gb"] == 2.0


def test_top_device_ops_ranks_by_bytes(tmp_path):
    """top_device_ops aggregates per op name and ranks by bytes with time
    as tiebreaker — the report_run 'where did the bytes go' table."""
    events = [
        _op("fusion.1", 10.0, nbytes=GIB),
        _op("fusion.1", 10.0, nbytes=GIB),      # same name: aggregated
        _op("copy.2", 500.0, nbytes=GIB // 4),  # slow but few bytes
        _op("zerobytes.a", 90.0, long_name="x"),   # 0 B, more time
        _op("zerobytes.b", 10.0, long_name="y"),   # 0 B, less time
    ]
    _write_trace(str(tmp_path), events)
    top = top_device_ops(str(tmp_path), k=10)
    assert [t["name"] for t in top] == [
        "fusion.1", "copy.2", "zerobytes.a", "zerobytes.b",
    ]
    assert top[0]["count"] == 2 and top[0]["bytes_gb"] == 2.0
    assert top_device_ops(str(tmp_path), k=1)[0]["name"] == "fusion.1"
    assert top_device_ops(str(tmp_path / "missing")) == []

    # by="time": same aggregation, ranked on device time with bytes as
    # tiebreaker — report_run's "where did the time go" table.
    by_time = top_device_ops(str(tmp_path), k=10, by="time")
    assert [t["name"] for t in by_time] == [
        "copy.2", "zerobytes.a", "fusion.1", "zerobytes.b",
    ]
    with pytest.raises(ValueError, match="by"):
        top_device_ops(str(tmp_path), by="flops")

    # device_op_report: totals + both rankings from ONE gzip pass must
    # match the single-purpose helpers (report_run consumes this).
    report = device_op_report(str(tmp_path), k=10)
    assert report["by_bytes"] == top
    assert report["by_time"] == by_time
    single = parse_device_trace(str(tmp_path))
    assert report["totals"]["op_count"] == single["op_count"]
    assert report["totals"]["bytes_gb"] == pytest.approx(single["bytes_gb"])
    assert report["totals"]["device_ms"] == pytest.approx(
        single["device_ms"]
    )
