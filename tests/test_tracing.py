"""utils/tracing.py: device-trace parsing against synthetic fixtures.

iter_device_ops / parse_device_trace define the event-selection rule the
bench regression proxy depends on (complete 'X' events with XLA op
annotations, wrapper ``while``/``jit(`` frames excluded). These tests pin
that rule with hand-built gzipped ``*.trace.json.gz`` fixtures, so a
selection-rule regression shows up here instead of as a silently shifted
proxy baseline.
"""

import gzip
import json
import os

import pytest

from distributed_learning_simulator_tpu.utils.tracing import (
    OP_CLASSES,
    STAGE_RULES,
    categorize_long_name,
    categorize_ops,
    classify_op,
    device_op_report,
    iter_device_ops,
    parse_device_trace,
    top_device_ops,
)

GIB = 2**30


def _write_trace(root, events, run="run1", fname="host.trace.json.gz"):
    """Lay out the jax.profiler directory shape the parser globs:
    ``<root>/plugins/profile/<run>/<fname>``."""
    d = os.path.join(root, "plugins", "profile", run)
    os.makedirs(d, exist_ok=True)
    with gzip.open(os.path.join(d, fname), "wt") as f:
        json.dump({"traceEvents": events}, f)


def _op(name, dur_us, nbytes=None, long_name=None):
    args = {}
    if nbytes is not None:
        args["raw_bytes_accessed"] = nbytes
    if long_name is not None:
        args["long_name"] = long_name
    return {"ph": "X", "name": name, "dur": dur_us, "args": args}


def test_selection_rule_and_aggregation(tmp_path):
    """Annotated X events are summed; wrapper frames, non-X phases, and
    unannotated host events are excluded even when they carry bytes."""
    events = [
        _op("fusion.1", 100.0, nbytes=GIB, long_name="fusion kernel"),
        _op("copy.2", 50.0, nbytes=GIB // 2),
        # Wrapper frames: would double count their children's bytes/time.
        _op("while", 1000.0, nbytes=100 * GIB),
        _op("jit(round_fn)", 800.0, nbytes=100 * GIB, long_name="jit frame"),
        # Non-X phase events are skipped outright.
        {"ph": "M", "name": "process_name", "args": {"name": "meta"}},
        # X event with no op annotation (host lane) is skipped.
        {"ph": "X", "name": "host_callback", "dur": 5.0},
        # long_name alone qualifies (CPU traces carry no byte counts).
        _op("dot.3", 25.0, long_name="dot_general"),
    ]
    _write_trace(str(tmp_path), events)
    ops = list(iter_device_ops(str(tmp_path)))
    assert sorted(ev["name"] for ev in ops) == [
        "copy.2", "dot.3", "fusion.1",
    ]
    stats = parse_device_trace(str(tmp_path))
    assert stats["op_count"] == 3
    assert stats["device_ms"] == (100.0 + 50.0 + 25.0) / 1e3
    assert stats["bytes_gb"] == (GIB + GIB // 2) / GIB


def test_wrapper_exclusion_is_prefix_based(tmp_path):
    """The exclusion rule is the documented name-PREFIX match: any
    ``while*``/``jit(*`` name is a wrapper, whatever its suffix."""
    events = [
        _op("while.body.fusion", 10.0, nbytes=GIB),  # prefix 'while' -> out
        _op("jit(train_step)/mul", 10.0, nbytes=GIB),  # prefix 'jit(' -> out
        _op("jitted_mul", 10.0, nbytes=GIB),  # 'jit' but not 'jit(' -> in
    ]
    _write_trace(str(tmp_path), events)
    names = [ev["name"] for ev in iter_device_ops(str(tmp_path))]
    assert names == ["jitted_mul"]


def test_missing_and_empty_dirs_yield_nothing(tmp_path):
    """Missing/empty trace dirs parse to zeros, never raise (bench's
    proxy leg must degrade, not crash, when a trace comes back empty)."""
    missing = str(tmp_path / "nope")
    assert list(iter_device_ops(missing)) == []
    assert parse_device_trace(missing) == {
        "device_ms": 0.0, "bytes_gb": 0.0, "op_count": 0,
    }
    empty = tmp_path / "empty"
    empty.mkdir()
    assert parse_device_trace(str(empty)) == {
        "device_ms": 0.0, "bytes_gb": 0.0, "op_count": 0,
    }
    # A session dir whose trace holds no events at all.
    _write_trace(str(tmp_path / "blank"), [])
    assert parse_device_trace(str(tmp_path / "blank"))["op_count"] == 0


def test_multiple_trace_files_are_summed(tmp_path):
    """Every *.trace.json.gz under the dir contributes (the documented
    one-session-per-dir contract: a reused dir accumulates)."""
    _write_trace(str(tmp_path), [_op("a", 10.0, nbytes=GIB)],
                 fname="one.trace.json.gz")
    _write_trace(str(tmp_path), [_op("b", 20.0, nbytes=GIB)],
                 fname="two.trace.json.gz")
    stats = parse_device_trace(str(tmp_path))
    assert stats["op_count"] == 2
    assert stats["bytes_gb"] == 2.0


def test_classify_op_classes():
    """The op-class rules the cost model prices by: collectives before
    matmul (an all-reduce OF conv grads is ICI volume), copies by name
    PREFIX only, the u8 shard decode as its own byte budget."""
    assert classify_op("all-reduce.1") == "collective"
    assert classify_op("reduce-scatter.2") == "collective"
    assert classify_op("convolution.5", "convolution") == "matmul_conv"
    assert classify_op("convolution_convert_fusion.3") == "matmul_conv"
    assert classify_op("dot.3", "dot_general") == "matmul_conv"
    assert classify_op("fusion.8", "... dot_general ...") == "matmul_conv"
    assert classify_op("copy.2") == "copy_layout"
    assert classify_op("transpose.1") == "copy_layout"
    assert classify_op("bitcast.9") == "copy_layout"
    # A fusion whose long_name merely mentions copy is NOT a copy.
    assert classify_op("fusion.4", "copies nothing") == "elementwise"
    assert classify_op("fusion.9", "u8[1000,50,3072]") == "decode"
    # s32 alone is NOT decode: eval argmax / cohort-index fusions keep
    # their own class (only the stage map treats s32 as decode).
    assert classify_op("fusion.10", "s32[1000] argmax") == "elementwise"
    assert classify_op("dot.4", "dot_general s32[40] indices") == \
        "matmul_conv"
    assert classify_op("loop_reduce_fusion.2") == "elementwise"
    assert classify_op("convert.1") == "elementwise"
    assert classify_op("dynamic-update-slice.1") == "other"
    for name in ("all-reduce.1", "fusion.1", "copy.1", "custom-call.2"):
        assert classify_op(name) in OP_CLASSES


def test_categorize_long_name_stage_rules():
    """The promoted scripts/trace_categories.py rule table: first match
    wins, unmatched long_names land in 'other'."""
    assert categorize_long_name("= f32[3,3,256,256]") == "s3_wgrad"
    assert categorize_long_name("fusion over 8,8,256 tensors") == "stage3"
    assert categorize_long_name("u8[1000,50,3072] decode") == "decode"
    assert categorize_long_name("nothing recognizable") == "other"
    assert [c for c, _ in STAGE_RULES][:4] == [
        "s4_wgrad", "s3_wgrad", "s2_wgrad", "s1_wgrad",
    ]


def test_categorize_ops_ledger(tmp_path):
    """categorize_ops shares iter_device_ops' selection rule (wrapper
    frames excluded) and aggregates bytes/time/flops/count per class;
    ledger totals reconcile with parse_device_trace."""
    events = [
        _op("convolution.1", 100.0, nbytes=GIB, long_name="convolution"),
        _op("fusion.2", 50.0, nbytes=GIB // 2, long_name="loop fusion"),
        _op("fusion.2", 25.0, nbytes=GIB // 2, long_name="loop fusion"),
        _op("copy.3", 10.0, nbytes=GIB // 4),
        _op("all-reduce.4", 5.0, nbytes=GIB // 4),
        # Wrapper frames and unannotated host events stay excluded.
        _op("while", 1000.0, nbytes=100 * GIB),
        {"ph": "X", "name": "host_callback", "dur": 5.0},
    ]
    # One event carrying an XLA flops annotation.
    events[0]["args"]["flops"] = 4e9
    _write_trace(str(tmp_path), events)
    ledger = categorize_ops(str(tmp_path))
    assert set(ledger) == {"matmul_conv", "elementwise", "copy_layout",
                           "collective"}
    assert ledger["matmul_conv"] == {
        "device_ms": pytest.approx(0.1), "bytes_gb": 1.0,
        "flops_g": pytest.approx(4.0), "op_count": 1,
    }
    assert ledger["elementwise"]["op_count"] == 2
    assert ledger["elementwise"]["bytes_gb"] == 1.0
    totals = parse_device_trace(str(tmp_path))
    assert sum(e["bytes_gb"] for e in ledger.values()) == pytest.approx(
        totals["bytes_gb"]
    )
    assert sum(e["op_count"] for e in ledger.values()) == (
        totals["op_count"]
    )
    # Stage-rule mode: the same pass keyed by long_name rules.
    staged = categorize_ops(str(tmp_path), rules=STAGE_RULES)
    assert set(staged) == {"other"}  # no flagship shapes in this fixture
    assert staged["other"]["op_count"] == 5
    # Missing dirs yield an empty ledger, never raise.
    assert categorize_ops(str(tmp_path / "missing")) == {}


def test_top_device_ops_ranks_by_bytes(tmp_path):
    """top_device_ops aggregates per op name and ranks by bytes with time
    as tiebreaker — the report_run 'where did the bytes go' table."""
    events = [
        _op("fusion.1", 10.0, nbytes=GIB),
        _op("fusion.1", 10.0, nbytes=GIB),      # same name: aggregated
        _op("copy.2", 500.0, nbytes=GIB // 4),  # slow but few bytes
        _op("zerobytes.a", 90.0, long_name="x"),   # 0 B, more time
        _op("zerobytes.b", 10.0, long_name="y"),   # 0 B, less time
    ]
    _write_trace(str(tmp_path), events)
    top = top_device_ops(str(tmp_path), k=10)
    assert [t["name"] for t in top] == [
        "fusion.1", "copy.2", "zerobytes.a", "zerobytes.b",
    ]
    assert top[0]["count"] == 2 and top[0]["bytes_gb"] == 2.0
    assert top_device_ops(str(tmp_path), k=1)[0]["name"] == "fusion.1"
    assert top_device_ops(str(tmp_path / "missing")) == []

    # by="time": same aggregation, ranked on device time with bytes as
    # tiebreaker — report_run's "where did the time go" table.
    by_time = top_device_ops(str(tmp_path), k=10, by="time")
    assert [t["name"] for t in by_time] == [
        "copy.2", "zerobytes.a", "fusion.1", "zerobytes.b",
    ]
    with pytest.raises(ValueError, match="by"):
        top_device_ops(str(tmp_path), by="flops")

    # device_op_report: totals + both rankings from ONE gzip pass must
    # match the single-purpose helpers (report_run consumes this).
    report = device_op_report(str(tmp_path), k=10)
    assert report["by_bytes"] == top
    assert report["by_time"] == by_time
    single = parse_device_trace(str(tmp_path))
    assert report["totals"]["op_count"] == single["op_count"]
    assert report["totals"]["bytes_gb"] == pytest.approx(single["bytes_gb"])
    assert report["totals"]["device_ms"] == pytest.approx(
        single["device_ms"]
    )
