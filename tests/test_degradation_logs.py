"""Silent mode degradations must be announced (VERDICT r2 weak #6).

When a requested performance/telemetry feature self-disables (pipelining
under checkpoint+state, client_eval at large cohorts), the run log must say
so — the perf contract stays honest without the user diffing round timings.
"""

import dataclasses
import logging

from distributed_learning_simulator_tpu.utils.logging import get_logger


class _Capture(logging.Handler):
    def __init__(self):
        super().__init__(level=logging.INFO)
        self.lines: list[str] = []

    def emit(self, record):
        self.lines.append(record.getMessage())


def _capture_logs():
    logger = get_logger()
    handler = _Capture()
    logger.addHandler(handler)
    prev = logger.level
    logger.setLevel(logging.INFO)
    return logger, handler, prev


def test_pipeline_disable_announced(tiny_config, tmp_path):
    """pipeline_rounds=True + checkpointing + persistent client state:
    pipelining self-disables (donation hazard) and must log why."""
    from distributed_learning_simulator_tpu.simulator import run_simulation

    cfg = dataclasses.replace(
        tiny_config,
        pipeline_rounds=True,
        reset_client_optimizer=False,  # -> client_state is not None
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_every=1,
        momentum=0.9,
        log_level="INFO",  # run_simulation applies config.log_level
    )
    logger, handler, prev = _capture_logs()
    try:
        run_simulation(cfg, setup_logging=False)
    finally:
        logger.removeHandler(handler)
        logger.setLevel(prev)
    assert any("pipeline_rounds disabled" in ln for ln in handler.lines), (
        handler.lines
    )


def test_pipeline_disable_announced_for_algorithm(tiny_config):
    """Shapley's post_round consumes round metrics; asking for pipelining
    logs the algorithm reason."""
    from distributed_learning_simulator_tpu.simulator import run_simulation

    cfg = dataclasses.replace(
        tiny_config,
        distributed_algorithm="multiround_shapley_value",
        pipeline_rounds=True,
        round=1,
        log_level="INFO",
    )
    logger, handler, prev = _capture_logs()
    try:
        run_simulation(cfg, setup_logging=False)
    finally:
        logger.removeHandler(handler)
        logger.setLevel(prev)
    assert any(
        "pipeline_rounds disabled" in ln and "post_round" in ln
        for ln in handler.lines
    ), handler.lines


def test_client_eval_auto_disable_announced(tiny_config):
    """fed_quant auto-enables client_eval only at cohorts <= 32; above that
    the auto-off must be logged (config docstring alone is not a run log)."""
    from distributed_learning_simulator_tpu.factory import get_algorithm

    cfg = dataclasses.replace(
        tiny_config,
        distributed_algorithm="fed_quant",
        worker_number=64,
    )
    logger, handler, prev = _capture_logs()
    try:
        get_algorithm(cfg.distributed_algorithm, cfg)
    finally:
        logger.removeHandler(handler)
        logger.setLevel(prev)
    assert any("client_eval auto-disabled" in ln for ln in handler.lines), (
        handler.lines
    )
