"""Sweep engine (sweep/): fleets, scheduling, refusals, resume.

The load-bearing contracts (ISSUE 11):

* a vmapped fleet point's metric history is BIT-identical to a solo
  ``run_simulation`` with that seed on the shared data (including the
  in-program cohort draw — cohort_hash matches);
* points are RNG-independent: a point's history does not depend on who
  else is in the fleet;
* the scheduler groups by config_hash but caches programs under the
  seed-normalized program key, so seed-varied groups share ONE compiled
  program — and its lean warm-program loop reproduces run_simulation
  bit-for-bit;
* non-sweepable features refuse with causes;
* an interrupted sweep resumes from sweep_dir and stitches
  bit-identically.
"""

import dataclasses
import json
import os

import jsonschema
import pytest

from distributed_learning_simulator_tpu.config import ExperimentConfig
from distributed_learning_simulator_tpu.data.registry import get_dataset
from distributed_learning_simulator_tpu.simulator import (
    build_client_data,
    run_simulation,
)
from distributed_learning_simulator_tpu.sweep import (
    SweepScheduler,
    SweepSpec,
    run_sweep,
)
from distributed_learning_simulator_tpu.utils.reporting import config_hash

#: The metric fields the bit-identity contract covers (round_seconds is
#: wall-clock and legitimately differs; cohort_hash pins the sampled
#: cohort stream).
_KEYS = ("test_accuracy", "test_loss", "mean_client_loss", "cohort_hash")


def _base(**overrides) -> ExperimentConfig:
    kw = dict(
        dataset_name="synthetic",
        model_name="mlp",
        distributed_algorithm="fed",
        worker_number=8,
        round=3,
        epoch=1,
        learning_rate=0.1,
        batch_size=16,
        n_train=256,
        n_test=128,
        log_level="WARNING",
        dataset_args={"difficulty": 0.5},
        participation_fraction=0.5,
        compilation_cache_dir=None,
    )
    kw.update(overrides)
    return ExperimentConfig(**kw)


@pytest.fixture(scope="module")
def shared():
    base = _base()
    ds = get_dataset("synthetic", n_train=256, n_test=128, seed=base.seed,
                     difficulty=0.5)
    cd = build_client_data(base, ds)
    return base, ds, cd


def _solo(base, ds, cd, **overrides):
    cfg = dataclasses.replace(base, **overrides)
    return run_simulation(cfg, dataset=ds, client_data=cd,
                          setup_logging=False)["history"]


def _assert_history_equal(a, b, context=""):
    assert len(a) == len(b), context
    for ra, rb in zip(a, b):
        for k in _KEYS:
            assert ra.get(k) == rb.get(k), (context, k, ra, rb)


def test_fleet_bit_identical_to_solo_and_v8_records(shared, tmp_path):
    """The acceptance pin: a vmapped seed fleet reproduces each seed's
    solo history bit-for-bit (incl. the sampled-cohort stream), pays
    ONE compile for the whole fleet, and writes valid schema-v8
    records."""
    base, ds, cd = shared
    seeds = [0, 1, 2]
    spec = SweepSpec(base, [{"seed": s} for s in seeds],
                     strategy="vmapped", sweep_dir=str(tmp_path))
    out = run_sweep(spec, dataset=ds, client_data=cd)
    assert out["strategy"] == "vmapped"
    assert out["programs_compiled"] == 1
    assert out["compile_reuse_fraction"] == pytest.approx(2 / 3)
    for p in out["points"]:
        solo = _solo(base, ds, cd, seed=p["seed"])
        _assert_history_equal(solo, p["history"], f"seed {p['seed']}")
    # The winner is the argmax final accuracy over the points.
    finals = [p["final_accuracy"] for p in out["points"]]
    assert out["winner"]["final_accuracy"] == max(finals)
    # Persisted records validate against the checked-in v8 schema.
    schema_path = os.path.join(
        os.path.dirname(__file__), "data", "metrics_record.schema.json"
    )
    with open(schema_path) as f:
        schema = json.load(f)
    with open(os.path.join(str(tmp_path), "metrics.jsonl")) as f:
        records = [json.loads(line) for line in f if line.strip()]
    assert len(records) == len(seeds) * base.round
    for rec in records:
        assert rec["schema_version"] == 8
        assert rec["sweep"]["strategy"] == "vmapped"
        assert rec["sweep"]["experiments"] == len(seeds)
        jsonschema.validate(rec, schema)
    # compile_reused accounting: point 0 carries the fleet's compile.
    assert [p["compile_reused"] for p in out["points"]] == [
        False, True, True,
    ]


def test_fleet_point_independence(shared):
    """A point's history must not depend on who else is in the fleet —
    the property sweep-level resume (re-running only missing points)
    rests on."""
    base, ds, cd = shared
    small = dataclasses.replace(base, round=2)
    out_a = run_sweep(
        SweepSpec(small, [{"seed": 0}, {"seed": 1}], strategy="vmapped"),
        dataset=ds, client_data=cd,
    )
    out_b = run_sweep(
        SweepSpec(small, [{"seed": 0}, {"seed": 5}], strategy="vmapped"),
        dataset=ds, client_data=cd,
    )
    _assert_history_equal(
        out_a["points"][0]["history"], out_b["points"][0]["history"],
        "fleet composition changed point 0",
    )


def test_fleet_lr_axis(shared):
    """learning_rate is a fleet axis: lr-varied points run in one
    program as a length-E factor vector. The base-lr point (factor
    exactly 1.0) stays bit-identical to its solo run; the varied point
    genuinely trains at a different rate."""
    base, ds, cd = shared
    small = dataclasses.replace(base, round=2)
    out = run_sweep(
        SweepSpec(
            small,
            [{"learning_rate": 0.1}, {"learning_rate": 0.05}],
            strategy="vmapped",
        ),
        dataset=ds, client_data=cd,
    )
    solo = _solo(small, ds, cd, learning_rate=0.1)
    _assert_history_equal(solo, out["points"][0]["history"], "base-lr")
    assert (
        out["points"][0]["history"][-1]["test_loss"]
        != out["points"][1]["history"][-1]["test_loss"]
    )


def test_fleet_mesh_packing(shared):
    """Experiment-axis mesh packing: E experiments sharded over the mesh
    (each device owns whole experiments) keep every RNG stream exact —
    cohort hashes bit-match the solo runs — while metric VALUES agree to
    reduction-order tolerance: the SPMD partitioner may re-associate
    intra-experiment reductions, the same documented contract as
    resident-vs-mesh fed runs (PR 10, docs/ROBUSTNESS.md)."""
    base, ds, cd = shared
    meshed = dataclasses.replace(base, round=2, mesh_devices=2)
    out = run_sweep(
        SweepSpec(meshed, [{"seed": 0}, {"seed": 1}], strategy="vmapped"),
        dataset=ds, client_data=cd,
    )
    for p in out["points"]:
        solo = _solo(base, ds, cd, seed=p["seed"], round=2)
        assert len(solo) == len(p["history"])
        for rs, rf in zip(solo, p["history"]):
            assert rs["cohort_hash"] == rf["cohort_hash"]
            for k in ("test_accuracy", "test_loss", "mean_client_loss"):
                assert rs[k] == pytest.approx(rf[k], rel=1e-5), (
                    p["seed"], k,
                )


def test_scheduled_grouping_reuse_and_bit_identity(shared):
    """The 2-hash sweep: seeds x horizons give two distinct config
    hashes but ONE seed-normalized program — the scheduler compiles
    once, every later point rides it warm, and the lean loop's
    histories equal run_simulation's bit-for-bit."""
    base, ds, cd = shared
    points = [
        {"seed": s, "round": r} for s in (0, 1) for r in (2, 3)
    ]
    out = run_sweep(
        SweepSpec(base, points, strategy="scheduled"),
        dataset=ds, client_data=cd,
    )
    assert out["strategy"] == "scheduled"
    hashes = {p["config_hash"] for p in out["points"]}
    assert len(hashes) == 2  # seed in the hash, round not
    assert out["programs_compiled"] == 1
    assert out["compile_reuse_fraction"] == 0.75
    assert [p["compile_reused"] for p in out["points"]] == [
        False, True, True, True,
    ]
    for p in out["points"]:
        solo = _solo(base, ds, cd, seed=p["seed"], round=p["rounds"])
        _assert_history_equal(solo, p["history"], f"point {p['index']}")


def test_auto_strategy_resolution(shared):
    """'auto' picks the fleet when every point is fleet-compatible and
    falls back to the scheduler (with the blocking feature nameable)
    when not."""
    base, _, _ = shared
    fleet = SweepSpec(base, [{"seed": 0}, {"seed": 1}]).validate()
    assert fleet.resolve_strategy() == "vmapped"
    mixed = SweepSpec(
        base, [{"seed": 0}, {"batch_size": 32}]
    ).validate()
    ok, reason = mixed.fleet_compatible()
    assert not ok and "batch_size" in reason
    assert mixed.resolve_strategy() == "scheduled"


def test_refusal_causes(shared):
    base, _, _ = shared
    # Threaded oracle: no shared program to warm.
    with pytest.raises(ValueError, match="threaded"):
        dataclasses.replace(
            base, execution_mode="threaded", sweep_seeds="0,1"
        ).validate()
    # Shapley: post_round must observe every round synchronously.
    with pytest.raises(ValueError, match="post_round"):
        dataclasses.replace(
            base, distributed_algorithm="GTG_shapley_value",
            sweep_seeds="0,1",
        ).validate()
    # Streamed residency + K>1: no host-replayable plan across points.
    with pytest.raises(ValueError, match="rounds_per_dispatch"):
        dataclasses.replace(
            base, client_residency="streamed", rounds_per_dispatch=2,
            participation_fraction=0.5, sweep_seeds="0,1",
        ).validate()
    # Forcing 'vmapped' on a non-fleet feature names the blocker.
    with pytest.raises(ValueError, match="client_stats"):
        SweepSpec(
            dataclasses.replace(base, client_stats="on"),
            [{"seed": 0}, {"seed": 1}], strategy="vmapped",
        ).validate()
    # Duplicate points are refused, not silently recomputed.
    with pytest.raises(ValueError, match="identical"):
        SweepSpec(base, [{"seed": 3}, {"seed": 3}]).validate()
    # sweep_resume without a sweep_dir to resume from.
    with pytest.raises(ValueError, match="sweep_dir"):
        dataclasses.replace(
            base, sweep_seeds="0,1", sweep_resume=True
        ).validate()


def test_sweep_resume_bit_identical(shared, tmp_path):
    """Chaos-crash after 2 points, then resume: the persisted points
    load (not re-executed), the remainder runs, and the stitched sweep
    equals the uninterrupted one bit-for-bit."""
    base, ds, cd = shared
    small = dataclasses.replace(base, round=2)
    points = [{"seed": s} for s in range(4)]
    sweep_dir = str(tmp_path / "sweep")
    os.environ["DLS_SWEEP_CRASH_AFTER"] = "2"
    try:
        with pytest.raises(RuntimeError, match="chaos"):
            run_sweep(
                SweepSpec(small, points, strategy="scheduled",
                          sweep_dir=sweep_dir),
                dataset=ds, client_data=cd,
            )
    finally:
        del os.environ["DLS_SWEEP_CRASH_AFTER"]
    resumed = run_sweep(
        SweepSpec(small, points, strategy="scheduled",
                  sweep_dir=sweep_dir, resume=True),
        dataset=ds, client_data=cd,
    )
    assert resumed["resumed_points"] == 2
    assert resumed["executed_points"] == 2
    assert [p["resumed"] for p in resumed["points"]] == [
        True, True, False, False,
    ]
    reference = run_sweep(
        SweepSpec(small, points, strategy="scheduled"),
        dataset=ds, client_data=cd,
    )
    for pr, pf in zip(reference["points"], resumed["points"]):
        _assert_history_equal(
            pr["history"], pf["history"], f"resumed point {pr['index']}"
        )


def test_scheduler_reusable_outside_sweeps(shared):
    """The warm-program cache is a standalone tool (bench.py routes its
    same-program legs through one): two configs differing only in seed
    and horizon share a program, and the second run reports the
    reuse."""
    base, ds, cd = shared
    sched = SweepScheduler()
    r1 = sched.run(dataclasses.replace(base, round=2),
                   dataset=ds, client_data=cd)
    r2 = sched.run(dataclasses.replace(base, seed=9, round=3),
                   dataset=ds, client_data=cd)
    assert r1["compile_reused"] is False
    assert r2["compile_reused"] is True
    assert sched.programs_compiled == 1
    _assert_history_equal(
        _solo(base, ds, cd, seed=9, round=3), r2["history"],
        "scheduler lean loop",
    )


def test_sweep_knobs_offgate_config_hash(shared):
    """Sweep knobs drop out of config_hash at their off values (the
    PR 9/10 off-gate discipline): persistence knobs never hash, an
    ACTIVE sweep does."""
    base, _, _ = shared
    assert config_hash(base) == config_hash(
        dataclasses.replace(base, sweep_dir="/tmp/x", sweep_resume=True)
    )
    assert config_hash(base) != config_hash(
        dataclasses.replace(base, sweep_seeds="0,1")
    )
    # Point configs strip the sweep knobs: a point's hash equals the
    # standalone config's hash (the scheduler-grouping comparability).
    spec = SweepSpec.from_config(
        dataclasses.replace(base, sweep_seeds="0,5")
    )
    assert config_hash(spec.points[1].config) == config_hash(
        dataclasses.replace(base, seed=5)
    )


def test_from_config_grid(shared):
    """sweep_seeds x sweep_points build the grid; JSON parsing covers
    the CLI path."""
    base, _, _ = shared
    spec = SweepSpec.from_config(dataclasses.replace(
        base, sweep_seeds="0,1",
        sweep_points='[{"learning_rate": 0.1}, {"learning_rate": 0.05}]',
    ))
    assert len(spec.points) == 4
    assert {(p.config.seed, p.config.learning_rate)
            for p in spec.points} == {
        (0, 0.1), (1, 0.1), (0, 0.05), (1, 0.05),
    }
    with pytest.raises(ValueError, match="override"):
        SweepSpec.from_config(
            dataclasses.replace(base, sweep_points='{"not": "a list"}')
        )
