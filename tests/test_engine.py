"""Client-axis training engine: loss decreases, masking works, eval is exact."""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_learning_simulator_tpu.models.registry import get_model, init_params
from distributed_learning_simulator_tpu.parallel.engine import (
    make_eval_fn,
    make_local_train_fn,
    make_loss_fn,
    make_optimizer,
    make_reshaper,
    pad_eval_set,
)


def _setup(tiny_dataset):
    model = get_model("mlp", num_classes=tiny_dataset.num_classes)
    params = init_params(model, tiny_dataset.x_train[:1])
    return model, params


def test_local_train_reduces_loss(tiny_dataset):
    model, params = _setup(tiny_dataset)
    opt = make_optimizer("SGD", 0.1)
    local_train = make_local_train_fn(model.apply, opt, local_epochs=3,
                                      batch_size=32)
    xs = jnp.asarray(tiny_dataset.x_train[:256])
    ys = jnp.asarray(tiny_dataset.y_train[:256])
    mask = jnp.ones(256)
    loss_fn = make_loss_fn(model.apply)
    loss_before, _ = loss_fn(params, xs, ys, mask)
    opt_state = opt.init(params)
    new_params, _, metrics = jax.jit(local_train)(
        params, opt_state, xs, ys, mask, jax.random.key(0)
    )
    loss_after, _ = loss_fn(new_params, xs, ys, mask)
    assert float(loss_after) < float(loss_before)
    assert np.isfinite(float(metrics["loss"]))


def test_masked_samples_do_not_contribute(tiny_dataset):
    """Training with garbage in masked-out rows == training without them."""
    model, params = _setup(tiny_dataset)
    opt = make_optimizer("SGD", 0.1)
    local_train = jax.jit(
        make_local_train_fn(model.apply, opt, local_epochs=1, batch_size=32)
    )
    xs = np.array(tiny_dataset.x_train[:64])
    ys = np.array(tiny_dataset.y_train[:64])
    mask = np.ones(64, np.float32)
    mask[32:] = 0.0
    xs_garbage = xs.copy()
    xs_garbage[32:] = 999.0
    ys_garbage = ys.copy()
    ys_garbage[32:] = 0

    opt_state = opt.init(params)
    p1, _, _ = local_train(params, opt_state, jnp.asarray(xs),
                           jnp.asarray(ys), jnp.asarray(mask), jax.random.key(1))
    p2, _, _ = local_train(params, opt_state, jnp.asarray(xs_garbage),
                           jnp.asarray(ys_garbage), jnp.asarray(mask),
                           jax.random.key(1))
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_eval_fn_matches_numpy(tiny_dataset):
    model, params = _setup(tiny_dataset)
    xb, yb, mb = pad_eval_set(tiny_dataset.x_test, tiny_dataset.y_test, 100)
    out = jax.jit(make_eval_fn(model.apply))(
        params, jnp.asarray(xb), jnp.asarray(yb), jnp.asarray(mb)
    )
    logits = model.apply({"params": params},
                         jnp.asarray(tiny_dataset.x_test))
    acc = float((np.argmax(np.asarray(logits), 1) ==
                 tiny_dataset.y_test).mean())
    np.testing.assert_allclose(float(out["accuracy"]), acc, atol=1e-6)


def test_pad_eval_set_shapes():
    x = np.zeros((10, 3, 3, 1), np.float32)
    y = np.zeros((10,), np.int32)
    xb, yb, mb = pad_eval_set(x, y, 4)
    assert xb.shape == (3, 4, 3, 3, 1)
    assert mb.sum() == 10


def test_flattened_eval_matches_unflattened(tiny_dataset):
    """Flat eval storage + in-program reshape (the TPU layout path) must give
    identical metrics to direct NHWC batches."""
    model, params = _setup(tiny_dataset)
    direct = pad_eval_set(tiny_dataset.x_test, tiny_dataset.y_test, 100)
    out1 = jax.jit(make_eval_fn(model.apply))(
        params, *(jnp.asarray(a) for a in direct)
    )
    flat = pad_eval_set(tiny_dataset.x_test, tiny_dataset.y_test, 100,
                        flatten=True)
    assert flat[0].ndim == 3  # [n_batches, batch, prod(sample_shape)]
    reshaper = make_reshaper(tiny_dataset.x_test.shape[1:])
    out2 = jax.jit(make_eval_fn(model.apply, preprocess=reshaper))(
        params, *(jnp.asarray(a) for a in flat)
    )
    np.testing.assert_allclose(
        float(out1["accuracy"]), float(out2["accuracy"]), atol=1e-6
    )
    np.testing.assert_allclose(
        float(out1["loss"]), float(out2["loss"]), atol=1e-5
    )
