"""Test configuration: fake 8-device CPU mesh.

The reference has no tests at all (SURVEY §4). This suite uses JAX's virtual
CPU devices as the "fake backend" the reference lacks: 8 host devices let the
multi-chip sharding path run in CI without TPU hardware. Must run before any
JAX backend initialization — hence env + config here.

Note: this environment's sitecustomize force-registers the 'axon' TPU
platform ahead of JAX_PLATFORMS, so we pin the platform via jax.config.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from distributed_learning_simulator_tpu.config import ExperimentConfig  # noqa: E402
from distributed_learning_simulator_tpu.data.registry import get_dataset  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _assert_cpu_mesh():
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual CPU devices"


@pytest.fixture()
def tiny_config():
    """Small, fast config on the explicit synthetic dataset."""
    return ExperimentConfig(
        dataset_name="synthetic",
        model_name="mlp",
        distributed_algorithm="fed",
        worker_number=4,
        round=2,
        epoch=1,
        learning_rate=0.1,
        batch_size=32,
        n_train=512,
        n_test=256,
        log_level="WARNING",
        dataset_args={"difficulty": 0.5},
    )


@pytest.fixture(scope="session")
def tiny_dataset():
    return get_dataset("synthetic", n_train=512, n_test=256, seed=0,
                       difficulty=0.5)


@pytest.fixture()
def rng():
    return np.random.default_rng(0)
