"""metrics.jsonl record layouts vs the checked-in JSON schema.

Jax-free (imports only utils.reporting + jsonschema): the schema at
tests/data/metrics_record.schema.json is the reviewable contract every
emitter (vmap simulator, threaded oracle, sweep engine) writes through
``build_round_record``. v1 (legacy), v2 (+telemetry), v3
(+client_stats), v4 (+async), v5 (+stream), v6 (+costmodel), v7
(+valuation), v8 (+sweep), v9 (+population), v10 (+gtg), v11
(+multihost) and v12 (+spans) records must validate;
records that mix versions and sub-objects inconsistently must not. The
integration tests in test_client_stats.py (test_costmodel.py for v6,
test_valuation.py for v7, test_sweep.py for v8, test_population.py for
v9, test_gtg_mesh.py for v10, test_multihost.py's 2-process harness
for v11 and v12 with span_trace='on') validate REAL produced records
against the same file.
"""

import json
import os

import jsonschema
import pytest

from distributed_learning_simulator_tpu.utils.reporting import (
    METRICS_SCHEMA_VERSION,
    _GTG_SCHEMA_VERSION,
    _MULTIHOST_SCHEMA_VERSION,
    build_round_record,
)

_SCHEMA_PATH = os.path.join(
    os.path.dirname(__file__), "data", "metrics_record.schema.json"
)


def load_schema() -> dict:
    with open(_SCHEMA_PATH) as f:
        return json.load(f)


def validate(record: dict) -> None:
    jsonschema.validate(record, load_schema())


def _base() -> dict:
    return {
        "round": 3,
        "test_accuracy": 0.61,
        "test_loss": 1.1,
        "mean_client_loss": 1.2,
        "round_seconds": 0.41,
    }


def _telemetry() -> dict:
    return {
        "phase_seconds": {"client_step": 0.31, "eval": 0.04,
                          "host_sync": 0.05, "post_round": 0.0},
        "compiles": 1,
        "compiled": ["round_fn"],
        "peak_hbm_bytes": 9126805504,
    }


def _client_stats() -> dict:
    return {
        "n_clients": 4,
        "flagged_clients": [2],
        "flag_reason": {"2": "non_finite+update_norm"},
        "quantiles": {
            "loss_before": {"p0": 2.1, "p25": 2.2, "p50": 2.3, "p75": 2.4,
                            "p100": 2.5},
            "update_norm": {"p0": 0.1, "p25": 0.2, "p50": 0.2, "p75": 0.3,
                            "p100": None},
        },
        "per_client": {
            "client_ids": [0, 1, 2, 3],
            "loss_after": [2.0, 2.1, None, 2.2],
            "update_norm": [0.1, 0.2, None, 0.3],
        },
        "quant_mse": 1e-06,
    }


def test_schema_file_is_valid_draft7():
    jsonschema.Draft7Validator.check_schema(load_schema())


def test_v1_record_validates():
    record = build_round_record(_base(), None, None)
    assert record is not None and "schema_version" not in record
    validate(record)
    # Algorithm extras (compression ratios, shapley dicts rendered as
    # numbers by the host loop's filter) are allowed in every version.
    validate({**_base(), "uplink_compression_ratio": 4.0,
              "survivor_count": 7, "round_rejected": False})


def test_v2_record_validates():
    record = build_round_record(_base(), _telemetry())
    assert record["schema_version"] == 2
    validate(record)


def test_v2_batched_dispatch_record_validates():
    """A batched dispatch's telemetry (rounds_per_dispatch > 1) adds
    dispatch_rounds + the warmup marker; still plain v2."""
    tel = {**_telemetry(), "dispatch_rounds": 8, "warmup": True}
    record = build_round_record(_base(), tel)
    assert record["schema_version"] == 2
    validate(record)


def _async() -> dict:
    return {
        "on_time": 6, "late": 2, "buffer": 5, "applied": False,
        "mean_staleness": 1.5,
        "sim_round_s": 1.5, "sim_round_sync_s": 11.2, "sim_clock_s": 19.5,
    }


def test_v3_record_validates():
    record = build_round_record(_base(), _telemetry(), _client_stats())
    assert record["schema_version"] == 3
    validate(record)
    # client_stats without telemetry (telemetry_level='off') is still v3.
    validate(build_round_record(_base(), None, _client_stats()))
    # Round-scalar-only sub-object (sign_SGD's vote agreement).
    validate(build_round_record(
        _base(), None, {"n_clients": 4, "vote_agreement": 0.93}
    ))


def test_v4_record_validates():
    record = build_round_record(
        _base(), _telemetry(), _client_stats(), _async()
    )
    assert record["schema_version"] == 4
    validate(record)
    # async alone (telemetry_level='off', client_stats='off') is still v4.
    validate(build_round_record(_base(), None, None, _async()))
    # A quiet round: nothing late -> null mean staleness.
    validate(build_round_record(_base(), None, None, {
        **_async(), "late": 0, "mean_staleness": None,
    }))


def _stream() -> dict:
    return {
        "h2d_bytes": 655360, "h2d_seconds": 0.0123,
        "hidden_seconds": 0.0119, "overlap_ratio": 0.9675,
        "d2h_bytes": 1024, "d2h_seconds": 0.0004,
    }


def test_v5_record_validates():
    record = build_round_record(
        _base(), _telemetry(), _client_stats(), _async(), _stream()
    )
    assert record["schema_version"] == 5
    validate(record)
    # stream alone (every other feature off) is still v5.
    validate(build_round_record(_base(), None, None, None, _stream()))
    # Stateless runs carry no d2h fields; batched dispatches stamp the
    # rounds their transfer covers.
    validate(build_round_record(_base(), None, None, None, {
        "h2d_bytes": 655360, "h2d_seconds": 0.0123,
        "hidden_seconds": 0.0, "overlap_ratio": 0.0,
        "dispatch_rounds": 4,
    }))
    # Sampled-cohort uploads name the sampler + the cohort-draw replay
    # cost (participation_sampler, ops/sampling.py) — still v5.
    for sampler in ("exact", "hashed"):
        validate(build_round_record(_base(), None, None, None, {
            **_stream(), "sampler": sampler, "sample_ms": 1203.4,
        }))
    # An unknown sampler name is a schema break, not a silent extension.
    with pytest.raises(jsonschema.ValidationError):
        validate(build_round_record(_base(), None, None, None, {
            **_stream(), "sampler": "quantum", "sample_ms": 0.1,
        }))


def _costmodel() -> dict:
    return {
        "anchor_topology": "v5e-1",
        "predicted_ms": 2274.2,
        "measured_ms": 2275.4,
        "model_error_ratio": 0.9995,
        "bottleneck": "memory",
        "trace_rounds": 1,
        "run_rounds": 150,
        "categories": {
            "matmul_conv": {
                "bytes_gb": 348.967, "device_ms": 675.3, "flops_g": 0.0,
                "predicted_ms": 635.5, "bottleneck": "memory",
            },
            "elementwise": {
                "bytes_gb": 900.0, "device_ms": 1600.0, "flops_g": 0.0,
                "predicted_ms": 1638.7, "bottleneck": "memory",
            },
        },
        "per_topology": {
            "v5e-1": {"chips": 1, "predicted_ms": 2274.2,
                      "bottleneck": "memory", "usd_per_round": 0.000758,
                      "usd_per_run": 0.1137},
            "v4-32": {"chips": 32, "predicted_ms": 47.4,
                      "bottleneck": "memory", "usd_per_round": 0.001357,
                      "usd_per_run": 0.2035},
        },
    }


def test_v6_record_validates():
    record = build_round_record(
        _base(), _telemetry(), _client_stats(), _async(), _stream(),
        _costmodel(),
    )
    assert record["schema_version"] == 6
    validate(record)
    # costmodel alone (every other feature off) is still v6 — the
    # simulator's last-round record under cost_model_trace with
    # telemetry_level='off'.
    validate(build_round_record(
        _base(), None, None, None, None, _costmodel()
    ))
    # Prediction without a measured anchor (offline pricing of a trace).
    validate(build_round_record(_base(), None, None, None, None, {
        **_costmodel(), "measured_ms": None, "model_error_ratio": None,
    }))


def _valuation() -> dict:
    return {
        "n_clients": 4,
        "updated": 3,
        "loss_delta": 0.0412,
        "top_clients": [{"id": 0, "value": 0.0051}, {"id": 3, "value": 0.0047}],
        "bottom_clients": [{"id": 2, "value": 0.0012}, {"id": 1, "value": 0.003}],
        "per_client": {
            "client_ids": [0, 1, 2, 3],
            "value": [0.0051, 0.003, 0.0012, 0.0047],
        },
        "audit": {
            "spearman": 0.881, "pearson": 0.506, "spearman_round": 0.881,
            "audits": 2, "permutations": 225, "subset_evals": 466,
            "converged": True, "memo_hit_rate": None, "seconds": 2.48,
        },
    }


def test_v7_record_validates():
    record = build_round_record(
        _base(), _telemetry(), _client_stats(), _async(), _stream(),
        _costmodel(), _valuation(),
    )
    assert record["schema_version"] == 7
    validate(record)
    # valuation alone (every other feature off) is still v7 — a
    # client_valuation='on' run with telemetry_level='off' ... except
    # valuation requires client_stats='on', so the realistic minimum
    # carries both; the schema allows either.
    validate(build_round_record(
        _base(), None, None, None, None, None, _valuation()
    ))
    validate(build_round_record(
        _base(), None, _client_stats(), None, None, None, _valuation()
    ))
    # Non-audit rounds carry no audit sub-object; degenerate
    # correlations (all-zero vector on round 1) are null.
    no_audit = {k: v for k, v in _valuation().items() if k != "audit"}
    validate(build_round_record(
        _base(), None, None, None, None, None, no_audit
    ))
    validate(build_round_record(
        _base(), None, None, None, None, None,
        {**_valuation(), "audit": {
            "spearman": None, "pearson": None, "spearman_round": None,
            "audits": 1, "permutations": 8, "subset_evals": 12,
            "converged": False, "memo_hit_rate": 0.5, "seconds": 0.1,
        }},
    ))


def _sweep() -> dict:
    return {
        "point": 3,
        "seed": 7,
        "lr": 0.1,
        "strategy": "vmapped",
        "group": "9c2f3e1a4b5d",
        "compile_reused": True,
        "experiments": 8,
    }


def test_v8_record_validates():
    record = build_round_record(
        _base(), _telemetry(), _client_stats(), _async(), _stream(),
        _costmodel(), _valuation(), _sweep(),
    )
    assert record["schema_version"] == 8
    validate(record)
    # sweep alone (every other feature off) is still v8 — the sweep
    # engine's per-point records at defaults.
    validate(build_round_record(_base(), sweep=_sweep()))
    # Scheduled points carry no fleet width (experiments is vmapped-only)
    # and may carry the usual round extras (cohort_hash, lr_factor).
    sched = {k: v for k, v in _sweep().items() if k != "experiments"}
    sched["strategy"] = "scheduled"
    sched["compile_reused"] = False
    validate(build_round_record(
        {**_base(), "cohort_hash": 12345, "lr_factor": 0.5,
         "mean_client_loss": 1.2},
        sweep=sched,
    ))


def _population() -> dict:
    return {
        "n_initial": 8,
        "n_registered": 16,
        "n_alive": 14,
        "joins": 2,
        "departs": 1,
        "cohort_departs": 1,
        "drift_cohort_size": 3,
        "rejected_by_churn": False,
        "drift_clients": [1, 4, 6],
    }


def test_v9_record_validates():
    record = build_round_record(
        _base(), _telemetry(), _client_stats(), _async(), _stream(),
        _costmodel(), _valuation(), _sweep(), _population(),
    )
    assert record["schema_version"] == 9
    validate(record)
    # population alone (every other feature off) is still v9 — a
    # dynamic-population run at default telemetry.
    validate(build_round_record(_base(), population=_population()))
    # A churn-rejected round carries the quorum fields too; large drift
    # cohorts report the size only (no id list).
    big = {k: v for k, v in _population().items()
           if k != "drift_clients"}
    big["drift_cohort_size"] = 500
    big["rejected_by_churn"] = True
    validate(build_round_record(
        {**_base(), "cohort_hash": 99, "survivor_count": 2,
         "round_rejected": True, "mean_client_loss": 1.2},
        population=big,
    ))


def _gtg() -> dict:
    return {
        "devices": 2,
        "evals_per_s": 1412.5,
        "wave_width": 32,
        "walk_seconds": 4.731,
    }


def test_v10_record_validates():
    record = build_round_record(
        _base(), _telemetry(), _client_stats(), _async(), _stream(),
        _costmodel(), _valuation(), _sweep(), _population(), _gtg(),
    )
    assert record["schema_version"] == _GTG_SCHEMA_VERSION == 10
    validate(record)
    # gtg alone (every other feature off) is still v10 — a mesh-sharded
    # GTG run at default telemetry. (keep_client_params always leaves
    # the shapley extras as base-record scalars, allowed in every
    # version like the other algorithm extras.)
    validate(build_round_record(
        {**_base(), "gtg_permutations": 40, "gtg_subset_evals": 715,
         "mean_client_loss": 1.2},
        gtg=_gtg(),
    ))
    # A tiny walk can have no throughput sample (0 evals -> null rate).
    validate(build_round_record(
        _base(), gtg={**_gtg(), "evals_per_s": None}
    ))
    # The audit-side face: a v7 valuation audit carrying the walk's
    # device count stays v7 (the gtg sub-object is the GTG server's
    # per-round record, not the auditor's).
    record = build_round_record(
        _base(), None, None, None, None, None,
        {**_valuation(), "audit": {
            **_valuation()["audit"], "devices": 2,
        }},
    )
    assert record["schema_version"] == 7
    validate(record)


def _multihost() -> dict:
    return {
        "hosts": 2,
        "host_id": 0,
        "owned_clients": 500000,
        "shard_bytes": 551182336,
        "spill_rows": 9,
        "dcn_bytes": 41544,
        "h2d_seconds": 0.0041,
        "overlap_ratio": 0.83,
    }


def test_v11_record_validates():
    record = build_round_record(
        _base(), _telemetry(), None, None, _stream(),
        multihost=_multihost(),
    )
    assert record["schema_version"] == _MULTIHOST_SCHEMA_VERSION == 11
    validate(record)
    # multihost alone (default telemetry) is still v11 — a distributed
    # streamed run with everything else off.
    validate(build_round_record(
        {**_base(), "cohort_hash": 7, "mean_client_loss": 1.2},
        multihost=_multihost(),
    ))
    # The full-cohort regime reports structurally-zero spill.
    validate(build_round_record(
        _base(),
        multihost={**_multihost(), "spill_rows": 0, "dcn_bytes": 0},
    ))


def _spans() -> dict:
    return {
        "host_id": 0,
        "hosts": 2,
        "count": 23,
        "dropped": 0,
        "seconds_by_cat": {"phase": 0.412, "dcn_wait": 0.031,
                           "dcn": 0.004, "io": 0.009, "round": 0.46},
        "dcn_wait_s": 0.031,
        "dcn_transfer_s": 0.004,
        "spill_skew_ms": 28.4,
        "ckpt_skew_ms": None,
    }


def test_v12_record_validates():
    record = build_round_record(
        _base(), _telemetry(), None, None, _stream(),
        multihost=_multihost(), spans=_spans(),
    )
    assert record["schema_version"] == METRICS_SCHEMA_VERSION == 12
    validate(record)
    # spans alone (every other feature off) is still v12 — a
    # single-process span_trace='on' run; skews are null on rounds that
    # crossed no barrier, and single-host runs report hosts=1.
    validate(build_round_record(_base(), spans={
        "host_id": 0, "hosts": 1, "count": 5,
        "seconds_by_cat": {"phase": 0.01},
        "dcn_wait_s": 0.0, "dcn_transfer_s": 0.0,
        "spill_skew_ms": None, "ckpt_skew_ms": None,
    }))
    # A buffer-overrun round reports what it dropped.
    validate(build_round_record(
        _base(), spans={**_spans(), "dropped": 12},
    ))


def test_lowest_version_stamping_preserved():
    """Adding v10 must not disturb the lower stamps: the version is the
    LOWEST that describes the record (longitudinal byte-identity)."""
    assert "schema_version" not in build_round_record(_base())
    assert build_round_record(_base(), _telemetry())[
        "schema_version"] == 2
    assert build_round_record(_base(), None, _client_stats())[
        "schema_version"] == 3
    assert build_round_record(_base(), None, None, _async())[
        "schema_version"] == 4
    assert build_round_record(_base(), None, None, None, _stream())[
        "schema_version"] == 5
    assert build_round_record(_base(), None, None, None, None,
                              _costmodel())["schema_version"] == 6
    assert build_round_record(_base(), None, None, None, None, None,
                              _valuation())["schema_version"] == 7
    assert build_round_record(_base(), sweep=_sweep())[
        "schema_version"] == 8
    assert build_round_record(_base(), population=_population())[
        "schema_version"] == 9
    assert build_round_record(_base(), gtg=_gtg())[
        "schema_version"] == 10
    assert build_round_record(_base(), multihost=_multihost())[
        "schema_version"] == 11
    assert build_round_record(_base(), spans=_spans())[
        "schema_version"] == 12


def test_version_content_mismatches_rejected():
    # v2 stamp carrying a client_stats sub-object: the builder never
    # emits it, and the schema must refuse it too.
    bad = build_round_record(_base(), _telemetry())
    bad["client_stats"] = _client_stats()
    with pytest.raises(jsonschema.ValidationError):
        validate(bad)
    # v3 stamp without the client_stats sub-object.
    bad = build_round_record(_base(), _telemetry())
    bad["schema_version"] = 3
    with pytest.raises(jsonschema.ValidationError):
        validate(bad)
    # Unversioned record smuggling a telemetry sub-object.
    bad = dict(_base())
    bad["telemetry"] = _telemetry()
    with pytest.raises(jsonschema.ValidationError):
        validate(bad)
    # Unknown keys inside the versioned sub-objects are schema breaks,
    # not silent extensions.
    bad = build_round_record(_base(), {**_telemetry(), "mystery": 1})
    with pytest.raises(jsonschema.ValidationError):
        validate(bad)
    bad = build_round_record(
        _base(), None, {**_client_stats(), "mystery": 1}
    )
    with pytest.raises(jsonschema.ValidationError):
        validate(bad)
    # v3 stamp smuggling an async sub-object (the builder always stamps
    # async records v4).
    bad = build_round_record(_base(), None, _client_stats())
    bad["async"] = _async()
    with pytest.raises(jsonschema.ValidationError):
        validate(bad)
    bad = build_round_record(
        _base(), None, None, {**_async(), "mystery": 1}
    )
    with pytest.raises(jsonschema.ValidationError):
        validate(bad)
    # v4 stamp smuggling a stream sub-object (the builder always stamps
    # stream records v5).
    bad = build_round_record(_base(), None, None, _async())
    bad["stream"] = _stream()
    with pytest.raises(jsonschema.ValidationError):
        validate(bad)
    bad = build_round_record(
        _base(), None, None, None, {**_stream(), "mystery": 1}
    )
    with pytest.raises(jsonschema.ValidationError):
        validate(bad)
    # v5 stamp smuggling a costmodel sub-object (the builder always
    # stamps costmodel records v6).
    bad = build_round_record(_base(), None, None, None, _stream())
    bad["costmodel"] = _costmodel()
    with pytest.raises(jsonschema.ValidationError):
        validate(bad)
    # v6 stamp without the costmodel sub-object.
    bad = build_round_record(_base(), _telemetry())
    bad["schema_version"] = 6
    with pytest.raises(jsonschema.ValidationError):
        validate(bad)
    # Unknown keys inside costmodel (top level, a category, a topology
    # row) are schema breaks, not silent extensions.
    for poison in (
        {"mystery": 1},
        {"categories": {"matmul_conv": {
            "bytes_gb": 1.0, "predicted_ms": 1.0, "bottleneck": "memory",
            "mystery": 1,
        }}},
        {"per_topology": {"v4-32": {"chips": 32, "predicted_ms": 1.0,
                                    "mystery": 1}}},
    ):
        bad = build_round_record(
            _base(), None, None, None, None, {**_costmodel(), **poison}
        )
        with pytest.raises(jsonschema.ValidationError):
            validate(bad)
    # A bottleneck outside the compute/memory/collective enum.
    bad = build_round_record(
        _base(), None, None, None, None,
        {**_costmodel(), "bottleneck": "vibes"},
    )
    with pytest.raises(jsonschema.ValidationError):
        validate(bad)
    # v6 stamp smuggling a valuation sub-object (the builder always
    # stamps valuation records v7).
    bad = build_round_record(_base(), None, None, None, None, _costmodel())
    bad["valuation"] = _valuation()
    with pytest.raises(jsonschema.ValidationError):
        validate(bad)
    # v7 stamp without the valuation sub-object.
    bad = build_round_record(_base(), _telemetry())
    bad["schema_version"] = 7
    with pytest.raises(jsonschema.ValidationError):
        validate(bad)
    # Unknown keys inside valuation (top level, the audit, a ranked
    # entry) are schema breaks, not silent extensions.
    for poison in (
        {"mystery": 1},
        {"audit": {**_valuation()["audit"], "mystery": 1}},
        {"top_clients": [{"id": 0, "value": 1.0, "mystery": 1}]},
        {"per_client": {"client_ids": [0], "value": [1.0], "mystery": 1}},
    ):
        bad = build_round_record(
            _base(), None, None, None, None, None,
            {**_valuation(), **poison},
        )
        with pytest.raises(jsonschema.ValidationError):
            validate(bad)
    # v7 stamp smuggling a sweep sub-object (the builder always stamps
    # sweep records v8).
    bad = build_round_record(_base(), None, None, None, None, None,
                             _valuation())
    bad["sweep"] = _sweep()
    with pytest.raises(jsonschema.ValidationError):
        validate(bad)
    # v8 stamp without the sweep sub-object.
    bad = build_round_record(_base(), _telemetry())
    bad["schema_version"] = 8
    with pytest.raises(jsonschema.ValidationError):
        validate(bad)
    # Unknown keys / a strategy outside the vmapped/scheduled enum are
    # schema breaks, not silent extensions.
    for poison in (
        {"mystery": 1},
        {"strategy": "psychic"},
    ):
        bad = build_round_record(_base(), sweep={**_sweep(), **poison})
        with pytest.raises(jsonschema.ValidationError):
            validate(bad)
    # v8 stamp smuggling a population sub-object (the builder always
    # stamps population records v9).
    bad = build_round_record(_base(), sweep=_sweep())
    bad["population"] = _population()
    with pytest.raises(jsonschema.ValidationError):
        validate(bad)
    # v9 stamp without the population sub-object.
    bad = build_round_record(_base(), _telemetry())
    bad["schema_version"] = 9
    with pytest.raises(jsonschema.ValidationError):
        validate(bad)
    # Unknown population keys are schema breaks, not silent extensions.
    bad = build_round_record(
        _base(), population={**_population(), "mystery": 1}
    )
    with pytest.raises(jsonschema.ValidationError):
        validate(bad)
    # v9 stamp smuggling a gtg sub-object (the builder always stamps
    # gtg records v10).
    bad = build_round_record(_base(), population=_population())
    bad["gtg"] = _gtg()
    with pytest.raises(jsonschema.ValidationError):
        validate(bad)
    # v10 stamp without the gtg sub-object.
    bad = build_round_record(_base(), _telemetry())
    bad["schema_version"] = 10
    with pytest.raises(jsonschema.ValidationError):
        validate(bad)
    # Unknown gtg keys — and a serial walk claiming the sub-object
    # (devices < 2: serial rounds must keep pre-v10 records) — are
    # schema breaks, not silent extensions.
    for poison in ({"mystery": 1}, {"devices": 1}):
        bad = build_round_record(_base(), gtg={**_gtg(), **poison})
        with pytest.raises(jsonschema.ValidationError):
            validate(bad)
    # v10 stamp smuggling a multihost sub-object (the builder always
    # stamps multihost records v11).
    bad = build_round_record(_base(), gtg=_gtg())
    bad["multihost"] = _multihost()
    with pytest.raises(jsonschema.ValidationError):
        validate(bad)
    # v11 stamp without the multihost sub-object.
    bad = build_round_record(_base(), _telemetry())
    bad["schema_version"] = 11
    with pytest.raises(jsonschema.ValidationError):
        validate(bad)
    # Unknown multihost keys — and a single-process run claiming the
    # sub-object (hosts < 2: 1-process streamed runs must keep pre-v11
    # records) — are schema breaks, not silent extensions.
    for poison in ({"mystery": 1}, {"hosts": 1}):
        bad = build_round_record(
            _base(), multihost={**_multihost(), **poison}
        )
        with pytest.raises(jsonschema.ValidationError):
            validate(bad)
    # v11 stamp smuggling a spans sub-object (the builder always stamps
    # span-trace records v12).
    bad = build_round_record(_base(), multihost=_multihost())
    bad["spans"] = _spans()
    with pytest.raises(jsonschema.ValidationError):
        validate(bad)
    # v12 stamp without the spans sub-object.
    bad = build_round_record(_base(), _telemetry())
    bad["schema_version"] = 12
    with pytest.raises(jsonschema.ValidationError):
        validate(bad)
    # Unknown spans keys are schema breaks, not silent extensions.
    bad = build_round_record(_base(), spans={**_spans(), "mystery": 1})
    with pytest.raises(jsonschema.ValidationError):
        validate(bad)


def test_missing_required_base_fields_rejected():
    record = build_round_record(_base(), _telemetry())
    del record["test_accuracy"]
    with pytest.raises(jsonschema.ValidationError):
        validate(record)
