"""Mesh-sharded GTG: permutation-parallel Shapley evaluation (ISSUE 14).

The multi-walk determinism contract, generalized from the PR 1 prefix-mode
differential: the mesh-sharded walk (the subset evaluator's vmapped
model-batch axis partitioned over D devices, client stack replicated —
algorithms/shapley.py) must be BIT-identical to the serial walk on a fixed
seed — SVs, permutation counts, eval counts, convergence flags, and the
cross-permutation ``SubsetMemo``'s exact contents (keys AND values),
including eps-truncated walks. The mechanism: each device's local call
shapes are exactly the serial evaluator's (the call width scales by D),
so XLA compiles the identical per-element program — nothing reduces
across devices. Plus: the schema-v10 ``gtg`` record sub-object on sharded
end-to-end runs (serial runs keep pre-feature records), the
audit-under-mesh fidelity pin, and the lifted-vs-kept refusal causes.
"""

import dataclasses
import json
import os

import jax.numpy as jnp
import jsonschema
import numpy as np
import pytest

from distributed_learning_simulator_tpu.algorithms.shapley import (
    GTGShapley,
    SubsetMemo,
    _SubsetEvaluator,
    eval_mesh_devices,
    eval_subsets,
    gtg_walk,
)
from distributed_learning_simulator_tpu.config import ExperimentConfig
from distributed_learning_simulator_tpu.simulator import run_simulation

_SCHEMA_PATH = os.path.join(
    os.path.dirname(__file__), "data", "metrics_record.schema.json"
)


def _validate_record(record: dict) -> None:
    with open(_SCHEMA_PATH) as f:
        jsonschema.validate(record, json.load(f))


# ---- walk-level contract: sharded == serial, bit for bit -------------------
#
# Driven through gtg_walk/_SubsetEvaluator directly on a FIXED synthetic
# stack: end-to-end runs shard the TRAINING client axis too, whose
# per-device tiling legitimately moves the trained stack by ulps (the
# documented resident-vs-mesh reduction-order tolerance), so the walk
# contract is pinned where it is exact — same inputs, D in {1, 2}.


def _toy_workload(n=20, p=400, seed=3):
    rng = np.random.default_rng(seed)
    stack = {"w": jnp.asarray(rng.standard_normal((n, p)), jnp.float32)}
    sizes = jnp.asarray(rng.integers(1, 9, n), jnp.float32)
    prev = {"w": jnp.asarray(rng.standard_normal(p), jnp.float32)}
    xb = jnp.asarray(rng.standard_normal((2, 32, p)), jnp.float32)
    yb = jnp.asarray(rng.integers(0, 4, (2, 32)), jnp.int32)
    mb = jnp.ones((2, 32), jnp.float32)
    return stack, sizes, prev, (xb, yb, mb)


def _toy_eval(params, xb, yb, mb):
    h = jnp.tanh(xb @ params["w"])
    return {"accuracy": jnp.sum(h * mb) / jnp.sum(mb), "loss": 0.0}


def _walk(devices, prefix_mode, eps, chunk=16, n=20, cap=12):
    stack, sizes, prev, batches = _toy_workload(n=n)
    ev = _SubsetEvaluator(
        _toy_eval, chunk=chunk,
        mesh_devices=devices if devices and devices > 1 else None,
    )
    memo = SubsetMemo()
    eval_subsets(ev, stack, sizes, prev, batches, n, memo,
                 [frozenset(), frozenset(range(n))])
    rng = np.random.default_rng(7)
    sv, n_perms, converged = gtg_walk(
        ev, stack, sizes, prev, batches, n, rng,
        eps=eps, cap=cap, last_k=10, converge_criteria=0.05,
        trunc_ref=memo[frozenset(range(n))], prefix_mode=prefix_mode,
        memo=memo,
    )
    return sv, n_perms, converged, dict(memo), memo.evaluated


@pytest.mark.parametrize("prefix_mode", ["cumsum", "masked"])
@pytest.mark.parametrize("eps", [1e-9, 0.02])
def test_sharded_walk_bit_identical(prefix_mode, eps):
    """D=2 == D=1 bit for bit: SVs, permutation counts, convergence,
    eval counts, and the memo's exact keys AND values — full walks
    (eps=1e-9: truncation never fires) and eps-truncated walks (0.02:
    walks stop mid-permutation; the sharded wave must drop exactly the
    same carries). n=20 forces multi-block walks (block 16 + short
    final block 4), so wave padding, the short-block guard, and the
    group compaction are all on the sharded path."""
    serial = _walk(1, prefix_mode, eps)
    sharded = _walk(2, prefix_mode, eps)
    np.testing.assert_array_equal(serial[0], sharded[0])
    assert serial[1] == sharded[1]  # permutation counts
    assert serial[2] == sharded[2]  # convergence flag
    assert serial[3] == sharded[3]  # memo partition/merge: exact contents
    assert serial[4] == sharded[4]  # evaluated counts
    if eps == 0.02:
        # The truncated case must actually truncate, or it pins nothing.
        full = _walk(1, prefix_mode, 1e-9)
        assert serial[4] < full[4]


def test_sharded_walk_chunk_not_dividing_block():
    """A chunk below the prefix block (call width 2x5 sharded vs 5
    serial; masked path padding + the cumsum group floor) keeps the
    bit-identity contract — the width always scales by exactly D, so
    per-device shapes stay the serial call's."""
    for mode in ("cumsum", "masked"):
        serial = _walk(1, mode, 1e-9, chunk=5)
        sharded = _walk(2, mode, 1e-9, chunk=5)
        np.testing.assert_array_equal(serial[0], sharded[0])
        assert serial[3] == sharded[3]


def test_eval_mesh_devices_capability():
    """The capability resolution: single-host mesh shards, multihost and
    single-device stay serial — and the evaluators the servers build
    honor it (GTGShapley/MultiRoundShapley/the auditor all route
    through eval_mesh_devices)."""
    cfg = ExperimentConfig(worker_number=8, mesh_devices=2)
    assert eval_mesh_devices(cfg) == 2
    assert eval_mesh_devices(ExperimentConfig(worker_number=8)) is None
    assert eval_mesh_devices(
        ExperimentConfig(worker_number=8, mesh_devices=1)
    ) is None
    assert eval_mesh_devices(
        ExperimentConfig(worker_number=8, mesh_devices=2, multihost=True)
    ) is None
    gtg = GTGShapley(
        dataclasses.replace(cfg, distributed_algorithm="GTG_shapley_value")
    )
    gtg.prepare(None, _toy_eval)
    assert gtg._evaluator.devices == 2
    assert gtg._evaluator.call_width == 32  # 16 x D, 16 per device
    assert gtg.shards_subset_eval


# ---- end-to-end: the v10 record + the serial off-gate ----------------------


def _gtg_run(tiny_config, **kw):
    cfg = dataclasses.replace(
        tiny_config, distributed_algorithm="GTG_shapley_value",
        worker_number=8, round=1, round_trunc_threshold=0.0,
        shapley_eval_samples=64, **kw,
    )
    return run_simulation(cfg, setup_logging=False)


def test_end_to_end_mesh_records_v10(tiny_config):
    """A mesh_devices=2 GTG run: SVs match the serial run to the
    documented resident-vs-mesh tolerance (the TRAINED stack moves by
    reduction order; the walk itself is bit-exact —
    test_sharded_walk_bit_identical), the round record carries the
    schema-v10 ``gtg`` sub-object (devices/evals_per_s/wave_width/
    walk_seconds, validated against the checked-in schema), and the
    SERIAL run's records stay pre-feature — no gtg key, no version
    stamp (the off-gate discipline)."""
    serial = _gtg_run(tiny_config)
    sharded = _gtg_run(tiny_config, mesh_devices=2)
    sv_s = serial["history"][0]["shapley_values"]
    sv_m = sharded["history"][0]["shapley_values"]
    np.testing.assert_allclose(
        [sv_m[i] for i in sorted(sv_m)], [sv_s[i] for i in sorted(sv_s)],
        atol=1e-4,
    )
    rec = sharded["history"][0]
    assert rec["schema_version"] == 10
    gtg = rec["gtg"]
    assert gtg["devices"] == 2
    assert gtg["wave_width"] == 32
    assert gtg["walk_seconds"] > 0
    _validate_record(rec)
    # Serial off-gate: pre-feature record layout, byte-discipline kept.
    assert "gtg" not in serial["history"][0]
    assert "schema_version" not in serial["history"][0]
    _validate_record(serial["history"][0])


# ---- audits at production cadence on the mesh ------------------------------


def test_audit_under_mesh_fidelity():
    """The PR 9 graded-quality differential, now with the run — and the
    audit walk — sharded over 2 devices (the previously-refused
    combination): the streaming-vs-audit Spearman must still clear the
    compare_bench floor (0.8), the audit record carries the walk's
    device count, and the records validate."""
    from distributed_learning_simulator_tpu.data.registry import get_dataset
    from distributed_learning_simulator_tpu.simulator import (
        build_client_data,
    )
    from distributed_learning_simulator_tpu.telemetry.valuation import (
        grade_client_labels,
    )

    n, rounds = 8, 9
    config = ExperimentConfig(
        dataset_name="synthetic", model_name="mlp",
        distributed_algorithm="fed", worker_number=n, round=rounds,
        epoch=1, learning_rate=0.1, batch_size=32,
        n_train=1024, n_test=2048, log_level="WARNING",
        dataset_args={"difficulty": 0.5}, compilation_cache_dir=None,
        client_stats="on", client_valuation="on",
        valuation_audit_every=2, valuation_audit_permutations=500,
        gtg_eps=1e-4, mesh_devices=2,
    )
    ds = get_dataset(
        "synthetic", n_train=1024, n_test=2048, seed=0, difficulty=0.5
    )
    cd = build_client_data(config, ds)
    cd.y[:] = grade_client_labels(cd.y, ds.num_classes, seed=1)
    result = run_simulation(config, dataset=ds, client_data=cd,
                            setup_logging=False)
    audits = [
        r["valuation"]["audit"] for r in result["history"]
        if "audit" in r.get("valuation", {})
    ]
    assert len(audits) == 4  # rounds 2, 4, 6, 8
    assert all(a["devices"] == 2 for a in audits)
    assert result["valuation"]["last_audit"]["spearman"] >= 0.8
    for r in result["history"]:
        _validate_record(r)


# ---- refusal causes: lifted vs kept ----------------------------------------


def test_refusal_causes_lifted_and_kept():
    """Single-host mesh + audits now validates (the lifted refusal);
    multihost + audits keeps a cause-named refusal; and a SCHEDULED
    sweep point carrying mesh + audits validates too — a sweep's audit
    load spreads across the same mesh via the full-run fallback."""
    audit_kw = dict(
        worker_number=8, client_stats="on", client_valuation="on",
        valuation_audit_every=2,
    )
    # Lifted: audits compose with single-host mesh sharding.
    ExperimentConfig(mesh_devices=2, **audit_kw).validate()
    # Kept, cause named: the audit walk is single-process host control
    # flow.
    with pytest.raises(ValueError, match="multihost"):
        ExperimentConfig(multihost=True, **audit_kw).validate()
    # Sweep composition: the scheduled strategy accepts the point (the
    # Shapley SERVERS stay refused in sweeps — unchanged).
    from distributed_learning_simulator_tpu.sweep.spec import SweepSpec

    base = ExperimentConfig(
        dataset_name="synthetic", model_name="mlp", round=2,
        n_train=256, n_test=128, mesh_devices=2, **audit_kw,
    )
    SweepSpec(base, [{"seed": 0}, {"seed": 1}],
              strategy="scheduled").validate()
    with pytest.raises(ValueError, match="Shapley"):
        SweepSpec(
            dataclasses.replace(
                ExperimentConfig(worker_number=8),
                distributed_algorithm="GTG_shapley_value",
            ),
            [{"seed": 0}, {"seed": 1}],
        ).validate()
