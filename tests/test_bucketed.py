"""Size-aware work scheduling (config.bucket_client_work).

The fused FedAvg path groups clients into chunks whose scan length matches
the chunk's largest member instead of the padded global maximum — the fix
for the Dirichlet-skew flagship config (BASELINE configs[4]), where the
reference's thread-per-worker loop naturally runs each worker only as long
as its own dataset (reference workers/fed_worker.py:25-27) while a naive
packed vmap pays the maximum everywhere.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from distributed_learning_simulator_tpu.data.partition import (
    pack_client_shards,
)
from distributed_learning_simulator_tpu.data.registry import get_dataset
from distributed_learning_simulator_tpu.factory import get_algorithm
from distributed_learning_simulator_tpu.models.registry import (
    get_model,
    init_params,
)
from distributed_learning_simulator_tpu.parallel.engine import (
    make_eval_fn,
    make_optimizer,
)
from distributed_learning_simulator_tpu.simulator import run_simulation


def _run(cfg, **overrides):
    cfg = dataclasses.replace(cfg, **overrides)
    return run_simulation(cfg, setup_logging=False)


def _history(res):
    return [h["test_accuracy"] for h in res["history"]]


def test_uniform_shards_bitwise_unchanged(tiny_config):
    """IID (uniform) shards: the scheduler is a no-op and the run must be
    bit-identical to bucket_client_work=False (guards the fallback gate)."""
    base = dict(round=3, client_chunk_size=2)
    r_on = _run(tiny_config, bucket_client_work=True, **base)
    r_off = _run(tiny_config, bucket_client_work=False, **base)
    assert _history(r_on) == _history(r_off)
    for a, b in zip(
        jax.tree_util.tree_leaves(r_on["global_params"]),
        jax.tree_util.tree_leaves(r_off["global_params"]),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_dirichlet_bucketed_learns_and_is_deterministic(tiny_config):
    """Heterogeneous shards engage the scheduler: the run must still learn
    (same per-epoch sample coverage; only batch composition differs, like
    any reshuffle) and be bit-deterministic under a fixed seed."""
    base = dict(
        round=4, worker_number=8, client_chunk_size=2,
        partition="dirichlet", dirichlet_alpha=0.5, n_train=1024,
    )
    r1 = _run(tiny_config, bucket_client_work=True, **base)
    r2 = _run(tiny_config, bucket_client_work=True, **base)
    assert _history(r1) == _history(r2)
    r_off = _run(tiny_config, bucket_client_work=False, **base)
    # Not bitwise comparable (batch composition differs); both must learn
    # to a similar level on the easy synthetic task.
    assert _history(r1)[-1] > 0.3
    assert _history(r_off)[-1] > 0.3
    assert abs(_history(r1)[-1] - _history(r_off)[-1]) < 0.2


def _hetero_round(cfg, sizes, *, lr, bucket=True, algo_name="fed"):
    """One hand-driven round over clients with the given real shard sizes
    (client i gets sizes[i] samples; one may be 0). Returns (round out,
    initial params, per-client norm weights)."""
    ds = get_dataset("synthetic", n_train=512, n_test=64, seed=0,
                     difficulty=0.5)
    rng = np.random.default_rng(0)
    indices = []
    cursor = 0
    for n in sizes:
        indices.append(np.arange(cursor, cursor + n, dtype=np.int64))
        cursor += n
    cd = pack_client_shards(ds.x_train, ds.y_train, indices,
                            batch_size=cfg.batch_size)
    model = get_model("mlp", num_classes=ds.num_classes)
    gp = init_params(model, ds.x_train[:1], seed=0)
    opt = make_optimizer("sgd", lr)
    cfg = dataclasses.replace(
        cfg, learning_rate=lr, worker_number=len(sizes),
        bucket_client_work=bucket,
    )
    algo = get_algorithm(algo_name, cfg)
    algo.prepare(model.apply, make_eval_fn(model.apply))
    round_fn = algo.make_round_fn(
        model.apply, opt, cd.n_clients, client_sizes=cd.sizes,
    )
    out = jax.jit(round_fn)(
        gp, None, jnp.asarray(cd.x), jnp.asarray(cd.y),
        jnp.asarray(cd.mask), jnp.asarray(cd.sizes), jax.random.key(3),
    )
    del rng
    return out, gp, cd.sizes / cd.sizes.sum()


def test_bucketed_metrics_scatter_to_original_positions(tiny_config):
    """Clients are REGROUPED for execution; per-client metrics must come
    back in original client order: the empty client reports exactly 0
    (matching the padded path's all-masked behavior), trained ones > 0."""
    cfg = dataclasses.replace(tiny_config, batch_size=8, client_chunk_size=2)
    (_, _, aux), _, _ = _hetero_round(cfg, [40, 8, 0, 16, 8, 24], lr=0.1)
    loss = np.asarray(aux["client_loss"])
    assert loss.shape == (6,)
    assert loss[2] == 0.0
    assert all(loss[i] > 0 for i in (0, 1, 3, 4, 5))


def test_bucketed_zero_lr_preserves_global(tiny_config):
    """lr=0: every client returns the broadcast params, so the weighted
    aggregate must reproduce the global model (catches slot-slicing or
    weight-indexing corruption in the scheduler)."""
    cfg = dataclasses.replace(tiny_config, batch_size=8, client_chunk_size=2)
    (new_global, _, _), gp, _ = _hetero_round(
        cfg, [40, 8, 0, 16, 8, 24], lr=0.0
    )
    for got, prev in zip(jax.tree_util.tree_leaves(new_global),
                         jax.tree_util.tree_leaves(gp)):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(prev), rtol=1e-6, atol=1e-7
        )


def test_bucketed_fed_quant_composes(tiny_config):
    """fed_quant (client_eval off -> fused path) composes with the
    scheduler: compression telemetry present, learning happens."""
    res = _run(
        tiny_config, distributed_algorithm="fed_quant", client_eval=False,
        round=3, worker_number=8, client_chunk_size=2,
        partition="dirichlet", dirichlet_alpha=0.5, n_train=1024,
    )
    assert res["history"][-1]["uplink_compression_ratio"] > 3.5
    assert np.isfinite(res["history"][-1]["test_loss"])


def test_dirichlet_with_sampling_skips_scheduler(tiny_config):
    """Client sampling gates the scheduler off (per-round cohorts change);
    a Dirichlet + participation_fraction < 1 run must still work."""
    res = _run(
        tiny_config, round=3, worker_number=8, client_chunk_size=2,
        partition="dirichlet", dirichlet_alpha=0.5, n_train=1024,
        participation_fraction=0.5,
    )
    assert len(res["history"]) == 3
    assert all(np.isfinite(h["test_accuracy"]) for h in res["history"])


def test_bucketed_respects_weighting(tiny_config):
    """Aggregation weights ride the original sizes: a giant client must
    dominate the aggregate regardless of execution grouping. Train client 0
    on lots of data and the rest on almost none; the aggregate must sit
    much closer to client 0's solo update than to the tiny clients'."""
    cfg = dataclasses.replace(tiny_config, batch_size=8, client_chunk_size=2)
    (new_global, _, aux), gp, w = _hetero_round(
        cfg, [256, 8, 8, 8], lr=0.05
    )
    # weight sanity: w0 dominates
    assert w[0] > 0.9
    # the aggregate must have moved (client 0 trained 32 steps)
    moved = sum(
        float(np.abs(np.asarray(a) - np.asarray(b)).sum())
        for a, b in zip(jax.tree_util.tree_leaves(new_global),
                        jax.tree_util.tree_leaves(gp))
    )
    assert moved > 0.0
