"""Open-world dynamic populations (robustness/population.py, ISSUE 13).

Pins the masked hashed-sampler contract (jit == numpy mirror, departed
never resampled, all-alive == unmasked), the registration stream's
determinism and departure cap, drift's absolute/idempotent schedule,
HostShardStore append-growth, the static off-gate (config_hash + history
invariance), the bit-identical-until-first-join acceptance differential,
quorum-rejection under churn (rejected_by_churn), the 10x-growth run
with schema-v9 records, the streaming-valuation drift-tracking floor
(Spearman >= 0.8 against the planted grades), refusal causes, the
vmapped-sweep blocker, and report_run's population section.
"""

import dataclasses
import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import jsonschema
import numpy as np
import pytest

from distributed_learning_simulator_tpu.config import ExperimentConfig
from distributed_learning_simulator_tpu.data.residency import HostShardStore
from distributed_learning_simulator_tpu.ops.sampling import (
    hashed_cohort,
    hashed_cohort_np,
)
from distributed_learning_simulator_tpu.robustness.population import (
    PopulationModel,
    pop_key_words,
)
from distributed_learning_simulator_tpu.telemetry.valuation import (
    spearman_corr,
)
from distributed_learning_simulator_tpu.utils.reporting import config_hash

_SCHEMA_PATH = os.path.join(
    os.path.dirname(__file__), "data", "metrics_record.schema.json"
)


def _validate_record(record: dict) -> None:
    with open(_SCHEMA_PATH) as f:
        jsonschema.validate(record, json.load(f))


def _dyn(**kw) -> ExperimentConfig:
    base = dict(
        dataset_name="synthetic", model_name="mlp",
        distributed_algorithm="fed", worker_number=8, round=5, epoch=1,
        learning_rate=0.1, batch_size=32, n_train=512, n_test=256,
        log_level="WARNING", dataset_args={"difficulty": 0.5},
        participation_fraction=0.5, participation_sampler="hashed",
        client_residency="streamed", compilation_cache_dir=None,
        population="dynamic",
    )
    base.update(kw)
    return ExperimentConfig(**base)


def _run(config, **kw):
    from distributed_learning_simulator_tpu.simulator import run_simulation

    return run_simulation(config, setup_logging=False, **kw)


# ---- masked hashed sampler (ops/sampling.py) -------------------------------


def test_masked_hashed_draw_jit_equals_numpy():
    words = np.asarray(
        jax.random.key_data(jax.random.key(7))
    ).ravel()
    key = jax.random.wrap_key_data(jnp.asarray(words))
    rng = np.random.default_rng(3)
    for n, k in ((37, 9), (100, 25), (64, 16)):
        alive = np.ones(n, dtype=bool)
        alive[rng.choice(n, size=n // 3, replace=False)] = False
        got_np = hashed_cohort_np(words, n, k, alive=alive)
        got_jit = np.asarray(
            jax.jit(
                lambda kk, a, _n=n, _k=k: hashed_cohort(kk, _n, _k, alive=a)
            )(key, jnp.asarray(alive))
        )
        np.testing.assert_array_equal(got_np, got_jit)
        # Departed indices are never sampled; the cohort is duplicate-free.
        assert alive[got_np].all()
        assert len(set(got_np.tolist())) == k


def test_all_alive_mask_equals_unmasked_draw():
    """The static-until-first-event bit-identity contract: an all-True
    mask only adds rejections that never fire, so the selection is the
    unmasked draw element-for-element."""
    words = np.asarray(
        jax.random.key_data(jax.random.key(11))
    ).ravel()
    for n, k in ((50, 10), (128, 32)):
        np.testing.assert_array_equal(
            hashed_cohort_np(words, n, k),
            hashed_cohort_np(words, n, k, alive=np.ones(n, dtype=bool)),
        )


def test_masked_draw_errors():
    words = np.asarray(
        jax.random.key_data(jax.random.key(0))
    ).ravel()
    with pytest.raises(ValueError, match="alive"):
        hashed_cohort_np(
            words, 10, 5, alive=np.zeros(10, dtype=bool)
        )
    # The jitted path refuses a concrete infeasible mask too — the
    # fixed-shape while_loop would otherwise spin forever on device.
    with pytest.raises(ValueError, match="alive"):
        hashed_cohort(
            jax.random.key(0), 10, 5, alive=np.zeros(10, dtype=bool)
        )
    from distributed_learning_simulator_tpu.ops.sampling import (
        draw_cohort_host,
    )

    with pytest.raises(ValueError, match="exact"):
        draw_cohort_host(
            jax.random.key(0), 10, 5, "exact",
            alive=np.ones(10, dtype=bool),
        )


# ---- registration stream (PopulationModel) ---------------------------------


def _model(n=10, cohort=4, **kw):
    cfg = _dyn(worker_number=n, **kw)
    return PopulationModel.from_config(cfg, n, cohort)


def test_event_stream_deterministic_and_decoupled():
    pm = _model(join_rate=1.5, depart_rate=0.3)
    key = jax.random.key(42)
    words = pop_key_words(key, pm.seed)
    e1 = pm.draw_events(words, 3)
    e2 = pm.draw_events(words, 3)
    assert e1.joins == e2.joins
    np.testing.assert_array_equal(e1.departs, e2.departs)
    assert e1.joins in (1, 2)  # floor(1.5) + bernoulli(0.5)
    # A different population_seed re-rolls the events (the fold_in
    # stream), without touching any other round-key consumer.
    pm2 = _model(join_rate=1.5, depart_rate=0.3, population_seed=9)
    words2 = pop_key_words(key, pm2.seed)
    assert not np.array_equal(words, words2)


def test_departure_cap_keeps_cohort_fillable():
    """Departures never push the alive population below the pinned
    cohort size (the sampler must fill k slots); excess draws drop in
    index order — deterministic."""
    pm = _model(n=6, cohort=4, depart_rate=0.999)
    words = pop_key_words(jax.random.key(1), pm.seed)
    ev = pm.draw_events(words, 0)
    assert ev.departs.size <= 6 - 4
    store = _store(6)
    pm.apply(ev, store)
    assert int(pm.alive.sum()) >= 4
    # Never resampled: a second round's draw can only depart ALIVE ids.
    ev2 = pm.draw_events(pop_key_words(jax.random.key(2), pm.seed), 1)
    assert not np.isin(ev2.departs, ev.departs).any()


def _store(n, slots=4, dim=3, state=None):
    return HostShardStore(
        np.arange(n * slots * dim, dtype=np.float32).reshape(
            n, slots, dim
        ),
        np.zeros((n, slots), dtype=np.int32),
        np.ones((n, slots), dtype=np.float32),
        np.full(n, float(slots), dtype=np.float32),
        state=state,
    )


def test_store_grow_appends_without_touching_resident_rows():
    store = _store(4)
    before = np.array(store.x, copy=True)
    first = store.grow(
        np.ones((2, 4, 3), np.float32), np.ones((2, 4), np.int32),
        np.ones((2, 4), np.float32), np.full(2, 4.0, np.float32),
    )
    assert first == 4 and store.n_clients == 6
    np.testing.assert_array_equal(store.x[:4], before)
    np.testing.assert_array_equal(store.x[4:], np.ones((2, 4, 3)))
    # Gather/scatter index math covers the grown rows.
    x, y, m, s = store.gather_data(np.array([0, 5]))
    assert x.shape[0] == 2 and s[1] == 4.0
    # Repeated growth amortizes through the capacity-doubling backing.
    for _ in range(5):
        store.grow(
            np.zeros((3, 4, 3), np.float32), np.zeros((3, 4), np.int32),
            np.ones((3, 4), np.float32), np.full(3, 4.0, np.float32),
        )
    assert store.n_clients == 21
    np.testing.assert_array_equal(store.x[:4], before)
    # The attached valuation vector grows with zeros.
    store2 = _store(3)
    store2.attach_valuation(np.array([1.0, 2.0, 3.0]))
    store2.grow(
        np.zeros((2, 4, 3), np.float32), np.zeros((2, 4), np.int32),
        np.ones((2, 4), np.float32), np.full(2, 4.0, np.float32),
    )
    np.testing.assert_array_equal(
        store2.valuation, [1.0, 2.0, 3.0, 0.0, 0.0]
    )
    # A leaf REPLACED between grows (attach_valuation on resume) must
    # not resurrect stale backing rows on the next grow.
    store2.attach_valuation(np.array([9.0, 8.0, 7.0, 6.0, 5.0]))
    store2.grow(
        np.zeros((1, 4, 3), np.float32), np.zeros((1, 4), np.int32),
        np.ones((1, 4), np.float32), np.full(1, 4.0, np.float32),
    )
    np.testing.assert_array_equal(
        store2.valuation, [9.0, 8.0, 7.0, 6.0, 5.0, 0.0]
    )
    # Stateful stores require state rows for the joiners.
    store3 = _store(2, state={"m": np.zeros((2, 5), np.float32)})
    with pytest.raises(ValueError, match="state_rows"):
        store3.grow(
            np.zeros((1, 4, 3), np.float32), np.zeros((1, 4), np.int32),
            np.ones((1, 4), np.float32), np.full(1, 4.0, np.float32),
        )
    store3.grow(
        np.zeros((1, 4, 3), np.float32), np.zeros((1, 4), np.int32),
        np.ones((1, 4), np.float32), np.full(1, 4.0, np.float32),
        state_rows={"m": np.ones((1, 5), np.float32)},
    )
    assert store3.state["m"].shape == (3, 5)


def test_drift_schedule_absolute_and_idempotent():
    """Drift corruption is an absolute per-round level (fixed slot order
    + fixed noise labels): re-applying any level is idempotent, levels
    are monotone in the round, and the final level matches the planted
    grade — the property resume-exactness rests on."""
    pm = _model(n=6, cohort=3, drift_fraction=1.0, drift_factor=0.9,
                round=8)
    store = _store(6, slots=8)
    store.y[:] = 7  # uniform original labels; noise shows as != 7
    pm._num_classes = 5
    pm.apply_drift(store, 7)  # final round -> peak level
    final = np.array(store.y, copy=True)
    corrupted = (final != 7).sum(axis=1)
    # Peak corruption ~ grade * slots, monotone across the graded ranks.
    grades_by_client = np.zeros(6)
    grades_by_client[pm.drift_ids] = pm.drift_grades
    assert spearman_corr(corrupted, grades_by_client) > 0.99
    # Earlier rounds corrupt a NESTED PREFIX of the same slots.
    pm2 = _model(n=6, cohort=3, drift_fraction=1.0, drift_factor=0.9,
                 round=8)
    pm2._num_classes = 5
    store2 = _store(6, slots=8)
    store2.y[:] = 7
    pm2.apply_drift(store2, 3)
    mid = np.array(store2.y, copy=True)
    assert ((mid != 7) <= (final != 7)).all()
    # Idempotent: applying the same level twice changes nothing.
    pm2.apply_drift(store2, 3)
    np.testing.assert_array_equal(store2.y, mid)
    # And applying the final level on top reaches the same state as the
    # fresh model did (absolute, not incremental).
    pm2.apply_drift(store2, 7)
    np.testing.assert_array_equal(store2.y, final)


# ---- config refusals / off-gate --------------------------------------------


def test_validate_refusal_causes():
    cases = [
        (dict(client_residency="resident"), "streamed"),
        (dict(participation_sampler="exact"), "hashed"),
        (dict(participation_fraction=1.0), "participation_fraction"),
        (dict(rounds_per_dispatch=2), "rounds_per_dispatch"),
        (dict(async_mode="on", arrival_model="bimodal"), "speed"),
        (dict(distributed_algorithm="sign_SGD"), "FedAvg"),
        (dict(distributed_algorithm="GTG_shapley_value"), "cohort"),
        (dict(execution_mode="threaded"), "thread"),
        (dict(client_stats="on", client_valuation="on",
              valuation_audit_every=2), "audit"),
    ]
    for overrides, needle in cases:
        with pytest.raises(ValueError, match=needle):
            _dyn(**overrides).validate()
    _dyn().validate()  # the composed base is legal


def test_static_offgate_hash_and_history(tiny_dataset):
    """population='static' is the exact pre-feature path: the hash drops
    every population knob at the static default, and off-mode knob
    tweaks change nothing about the run."""
    base = _dyn(population="static")
    assert config_hash(base) == config_hash(
        dataclasses.replace(
            base, population_seed=5, join_rate=3.0, depart_rate=0.2,
            drift_fraction=0.4, drift_factor=0.9,
        )
    )
    assert config_hash(base) != config_hash(
        dataclasses.replace(base, population="dynamic")
    )
    r1 = _run(base, dataset=tiny_dataset)
    r2 = _run(
        dataclasses.replace(base, population_seed=5, join_rate=3.0),
        dataset=tiny_dataset,
    )
    assert [h["test_accuracy"] for h in r1["history"]] == [
        h["test_accuracy"] for h in r2["history"]
    ]
    assert [h["cohort_hash"] for h in r1["history"]] == [
        h["cohort_hash"] for h in r2["history"]
    ]
    assert r1["population_summary"] is None


def test_sweep_vmapped_refuses_dynamic_and_auto_schedules():
    from distributed_learning_simulator_tpu.sweep.spec import SweepSpec

    cfg = _dyn(sweep_seeds="0,1", sweep_strategy="vmapped")
    spec = SweepSpec.from_config(cfg)
    with pytest.raises(ValueError, match="fixed N"):
        spec.validate()
    auto = SweepSpec.from_config(
        dataclasses.replace(cfg, sweep_strategy="auto")
    )
    assert auto.resolve_strategy() == "scheduled"
    ok, reason = auto.fleet_compatible()
    assert not ok and "population='dynamic'" in reason


# ---- integration -----------------------------------------------------------


def test_dynamic_bit_identical_to_static_until_first_join(tiny_dataset):
    """The acceptance differential's first half: with join-only churn
    (one join per round, applied at the round boundary), the dynamic
    run's round 0 — metrics AND cohort hash — is bit-identical to the
    static run; later rounds diverge because the hashed draw's index
    space grew."""
    static = _run(
        _dyn(population="static"), dataset=tiny_dataset
    )
    dyn = _run(_dyn(join_rate=1.0), dataset=tiny_dataset)
    s0, d0 = static["history"][0], dyn["history"][0]
    for key in ("test_accuracy", "test_loss", "mean_client_loss",
                "cohort_hash"):
        assert s0[key] == d0[key], key
    # Divergence after the first join is REAL (the draw covers a grown
    # index space) — identical tails would mean the mask/space is dead.
    assert [h["cohort_hash"] for h in static["history"][1:]] != [
        h["cohort_hash"] for h in dyn["history"][1:]
    ]
    assert dyn["population_summary"]["joins_total"] == len(
        dyn["history"]
    )


def test_tenx_growth_run_records_and_summary(tiny_dataset):
    """A 10x population-growth run: every record validates against the
    checked-in v9 schema, joined clients enter cohorts, and the summary
    books the growth."""
    n0, rounds = 8, 6
    cfg = _dyn(
        round=rounds, join_rate=float(round(9 * n0 / rounds)),
        depart_rate=0.05, drift_fraction=0.25, drift_factor=0.8,
    )
    result = _run(cfg, dataset=tiny_dataset)
    summary = result["population_summary"]
    assert summary["n_registered"] == n0 + summary["joins_total"]
    assert summary["growth_ratio"] >= 9.0
    participants = set()
    for r in result["history"]:
        assert r["schema_version"] == 9
        _validate_record(r)
        p = r["population"]
        assert p["n_alive"] <= p["n_registered"]
        participants.add(r["cohort_hash"])
    # The grown index space is actually sampled: cohort hashes differ
    # every round (a frozen index space would repeat only by chance,
    # but never under growth — n changes the whole stream).
    assert len(participants) == rounds
    # Mid-growth state survives the result surface for library callers.
    assert result["client_state"] is None  # stateless default


def test_churn_quorum_rejection_flagged(tiny_dataset):
    """Departures colliding with the quorum floor: a round whose
    survivors fall below min_survivors after mid-round departures is
    rejected in-program (previous global retained — the PR 2 contract)
    and its record carries rejected_by_churn."""
    cfg = _dyn(depart_rate=0.6, min_survivors=4)
    result = _run(cfg, dataset=tiny_dataset)
    assert result["rounds_rejected"] >= 1
    flagged = [
        r for r in result["history"]
        if r["population"]["rejected_by_churn"]
    ]
    assert flagged
    for r in flagged:
        assert r["round_rejected"] is True
        assert r["population"]["cohort_departs"] > 0
        _validate_record(r)
    assert (
        result["population_summary"]["rounds_rejected_by_churn"]
        == len(flagged)
    )


def test_valuation_tracks_drifting_cohort_through_churn():
    """The acceptance differential's second half: the PR 9 streaming
    valuation tracks the planted drifting-quality cohort THROUGH churn
    (joins + departures active) — Spearman >= 0.8 between the final
    valuation of the startup population and the negated planted grades
    (the compare_bench fidelity floor)."""
    n, rounds = 12, 20
    cfg = _dyn(
        worker_number=n, round=rounds, n_train=1024, n_test=512,
        participation_fraction=0.75,
        client_stats="on", client_valuation="on",
        join_rate=0.5, depart_rate=0.03,
        drift_fraction=1.0, drift_factor=0.9,
    )
    result = _run(cfg)
    v = result["valuation_state"].values
    pm = PopulationModel.from_config(cfg, n, cfg.cohort_size(n))
    grades = np.zeros(n)
    grades[pm.drift_ids] = pm.drift_grades
    sp = spearman_corr(v[:n], -grades)
    assert sp is not None and sp >= 0.8, sp
    # Valued ids stay TRUE indices across growth: the vector covers the
    # grown population and joiners accumulated their own evidence.
    assert v.shape[0] == result["population_summary"]["n_registered"]
    assert v.shape[0] > n


def test_dynamic_run_does_not_mutate_caller_client_data(tiny_dataset):
    """Drift mutates label rows in place, and the store normally aliases
    the caller's packed arrays — a dynamic run must take ownership of
    the labels so a shared client_data (bench legs run several legs on
    one packed set) is never corrupted as a side effect."""
    from distributed_learning_simulator_tpu.simulator import (
        build_client_data,
    )

    cfg = _dyn(join_rate=1.0, drift_fraction=0.5, drift_factor=0.9)
    cd = build_client_data(cfg, tiny_dataset)
    y_before = np.array(cd.y, copy=True)
    x_before = np.array(cd.x, copy=True)
    _run(cfg, dataset=tiny_dataset, client_data=cd)
    np.testing.assert_array_equal(cd.y, y_before)
    np.testing.assert_array_equal(cd.x, x_before)
    assert cd.n_clients == 8  # growth never leaks into the caller


def test_report_run_population_section(tmp_path):
    spec = importlib.util.spec_from_file_location(
        "report_run", os.path.join(
            os.path.dirname(__file__), "..", "scripts", "report_run.py"
        )
    )
    rr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rr)
    records = []
    for i in range(4):
        records.append({
            "round": i, "test_accuracy": 0.5 + 0.1 * i, "test_loss": 1.0,
            "mean_client_loss": 1.1, "round_seconds": 0.2,
            "schema_version": 9,
            "round_rejected": i == 2,
            "population": {
                "n_initial": 8,
                "n_registered": 8 + 2 * (i + 1), "n_alive": 7 + 2 * i,
                "joins": 2, "departs": 1 if i else 0,
                "cohort_departs": 1 if i == 2 else 0,
                "drift_cohort_size": 2, "drift_clients": [1, 5],
                "rejected_by_churn": i == 2,
            },
            "valuation": {
                "n_clients": 8, "updated": 4, "loss_delta": 0.01,
                "top_clients": [{"id": 0, "value": 0.5}],
                "bottom_clients": [{"id": 5, "value": -0.4},
                                   {"id": 1, "value": -0.2}],
            },
        })
    summary = rr.summarize_run(records)
    p = summary["population"]
    assert p["n_initial"] == 8
    assert p["n_registered_final"] == 16
    assert p["joins_total"] == 8 and p["departs_total"] == 3
    assert p["churn_rejected_rounds"] == [2]
    assert p["drift_clients"] == [1, 5]
    ov = summary["valuation"]["drift_overlay"]
    assert ov["drift_in_bottom"] == [5, 1]
    assert ov["drift_in_top"] == []
    text = "\n".join(rr.render_summary(summary))
    assert "dynamic population: 8 -> 16" in text
    assert "rejected by churn" in text
    assert "drift overlay: 2/2" in text
