"""scripts/compare_bench.py: the bench-JSON regression gate's self-test.

Pure-Python (the script deliberately imports no jax), so this is the
fast tier-1 wiring the satellite task asks for: the gate's direction
semantics, the provenance refusal, and the CLI exit codes.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

_SCRIPT = os.path.join(
    os.path.dirname(__file__), "..", "scripts", "compare_bench.py"
)


@pytest.fixture(scope="module")
def cb():
    spec = importlib.util.spec_from_file_location("compare_bench", _SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _record(value=9000.0, gtg=50.0, bytes_gb=100.0, **extra):
    return {
        "schema_version": 2,
        "config_hash": "abcdef123456",
        "metric": "simulated_clients_x_rounds_per_sec",
        "value": value,
        "mean_rate": value * 0.98,
        "flagship.unused": 1,
        "gtg": {"value": gtg},
        "proxy": {"traced_bytes_gb": bytes_gb, "traced_op_count": 500},
        "robustness": {"rounds_rejected": 0, "mean_survivor_count": 9.0},
        **extra,
    }


def test_no_regression_within_threshold(cb):
    old, new = _record(), _record(value=9100.0, gtg=49.0)
    assert cb.check_comparable(old, new) is None
    result = cb.compare_records(old, new, threshold=0.05)
    assert result["regressions"] == []
    assert any(e["metric"] == "value" for e in result["unchanged"])


def test_detects_regressions_in_both_directions(cb):
    """higher-is-better dropping and lower-is-better growing both gate."""
    old = _record(value=9000.0, gtg=50.0, bytes_gb=100.0)
    new = _record(value=8000.0, gtg=60.0, bytes_gb=120.0)  # all worse >5%
    result = cb.compare_records(old, new, threshold=0.05)
    flagged = {e["metric"] for e in result["regressions"]}
    assert {"value", "gtg.value", "proxy.traced_bytes_gb"} <= flagged
    # The same moves in the GOOD direction are improvements, not flags.
    result_rev = cb.compare_records(new, old, threshold=0.05)
    assert result_rev["regressions"] == []
    assert {e["metric"] for e in result_rev["improvements"]} >= {
        "value", "gtg.value", "proxy.traced_bytes_gb",
    }


def test_zero_baseline_counter_gates_on_any_increase(cb):
    """rounds_rejected 0 -> 2 must gate even though relative change is
    undefined at a zero baseline."""
    old, new = _record(), _record()
    new["robustness"]["rounds_rejected"] = 2
    result = cb.compare_records(old, new, threshold=0.05)
    assert any(
        e["metric"] == "robustness.rounds_rejected"
        for e in result["regressions"]
    )


def test_missing_metrics_are_skipped_not_flagged(cb):
    old, new = _record(), _record()
    del new["gtg"]
    result = cb.compare_records(old, new, threshold=0.05)
    assert any(e["metric"] == "gtg.value" for e in result["skipped"])
    assert not any(
        e["metric"] == "gtg.value" for e in result["regressions"]
    )


def test_client_stats_overhead_not_relatively_tracked(cb):
    """The overhead ratio is a near-zero noisy quantity: it must NOT be
    in the relative-change TRACKED list (0.01 -> 0.02 would read as
    +100%); only the absolute self-gate below judges it."""
    old, new = _record(), _record()
    old["client_stats"] = {"overhead_ratio": 0.01}
    new["client_stats"] = {"overhead_ratio": 0.04}  # within the gate
    result = cb.compare_records(old, new, threshold=0.05)
    assert not any(
        "client_stats" in e["metric"]
        for e in result["regressions"] + result["improvements"]
    )


def test_client_stats_overhead_self_gate(cb, tmp_path):
    """The in-record gate fires on the NEW record alone: its own bench
    run already measured the on-vs-off round-time ratio."""
    assert cb.overhead_gate(_record(), 0.10) is None  # leg absent: skip
    ok = _record(client_stats={"overhead_ratio": 0.04})
    assert cb.overhead_gate(ok, 0.10) is None
    bad = _record(client_stats={"overhead_ratio": 0.37})
    entry = cb.overhead_gate(bad, 0.10)
    assert entry and entry["new"] == 0.37

    # CLI: the self-gate alone must exit 1 even when every cross-record
    # metric is unchanged, and the threshold flag overrides.
    old_p = tmp_path / "old.json"
    bad_p = tmp_path / "bad.json"
    old_p.write_text(json.dumps(_record()))
    bad_p.write_text(json.dumps(bad))
    import subprocess

    proc = subprocess.run(
        [sys.executable, _SCRIPT, str(old_p), str(bad_p)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    assert "client_stats.overhead_ratio" in proc.stdout
    proc = subprocess.run(
        [sys.executable, _SCRIPT, str(old_p), str(bad_p),
         "--stats-overhead-threshold", "0.5"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0


def test_round_batch_amortization_not_relatively_tracked(cb):
    """The K-vs-1 amortization ratio hovers near 1.0 — like the
    client-stats overhead it must never be a relative TRACKED metric;
    only the absolute in-record floor judges it."""
    old = _record(round_batch={"amortization_ratio": 1.08})
    new = _record(round_batch={"amortization_ratio": 1.01})
    result = cb.compare_records(old, new, threshold=0.05)
    assert not any(
        "round_batch" in e["metric"]
        for e in result["regressions"] + result["improvements"]
    )


def test_round_batch_amortization_self_gate(cb, tmp_path):
    """In-record absolute floor: batching that stops paying for itself
    (ratio < threshold) gates on the NEW record alone."""
    assert cb.batch_amortization_gate(_record(), 0.95) is None  # leg absent
    ok = _record(round_batch={"amortization_ratio": 1.12})
    assert cb.batch_amortization_gate(ok, 0.95) is None
    bad = _record(round_batch={"amortization_ratio": 0.71})
    entry = cb.batch_amortization_gate(bad, 0.95)
    assert entry and entry["new"] == 0.71 and entry["direction"] == "higher"

    old_p = tmp_path / "old.json"
    bad_p = tmp_path / "bad.json"
    old_p.write_text(json.dumps(_record()))
    bad_p.write_text(json.dumps(bad))
    proc = subprocess.run(
        [sys.executable, _SCRIPT, str(old_p), str(bad_p)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    assert "round_batch.amortization_ratio" in proc.stdout
    proc = subprocess.run(
        [sys.executable, _SCRIPT, str(old_p), str(bad_p),
         "--batch-amortization-threshold", "0.5"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0


def test_async_speedup_not_relatively_tracked(cb):
    """The async speedup sits at a fixed operating point per config —
    like the other in-record ratios it must never be a relative TRACKED
    metric; only the absolute in-record floor judges it."""
    old = _record(**{"async": {"async_speedup_ratio": 7.4}})
    new = _record(**{"async": {"async_speedup_ratio": 6.9}})
    result = cb.compare_records(old, new, threshold=0.05)
    assert not any(
        "async" in e["metric"]
        for e in result["regressions"] + result["improvements"]
    )


def test_async_speedup_self_gate(cb, tmp_path):
    """In-record absolute floor: deadline rounds that stop beating the
    sync wait-for-everyone counterfactual gate on the NEW record alone."""
    assert cb.async_speedup_gate(_record(), 1.0) is None  # leg absent
    ok = _record(**{"async": {"async_speedup_ratio": 4.2}})
    assert cb.async_speedup_gate(ok, 1.0) is None
    bad = _record(**{"async": {"async_speedup_ratio": 0.84}})
    entry = cb.async_speedup_gate(bad, 1.0)
    assert entry and entry["new"] == 0.84 and entry["direction"] == "higher"

    old_p = tmp_path / "old.json"
    bad_p = tmp_path / "bad.json"
    old_p.write_text(json.dumps(_record()))
    bad_p.write_text(json.dumps(bad))
    proc = subprocess.run(
        [sys.executable, _SCRIPT, str(old_p), str(bad_p)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    assert "async.async_speedup_ratio" in proc.stdout
    proc = subprocess.run(
        [sys.executable, _SCRIPT, str(old_p), str(bad_p),
         "--async-speedup-threshold", "0.5"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0


def test_stream_overlap_not_relatively_tracked(cb):
    """The prefetch overlap ratio sits near a fixed operating point —
    like the other in-record ratios it must never be a relative TRACKED
    metric; only the absolute in-record floor judges it."""
    old = _record(stream={"overlap_ratio": 0.97})
    new = _record(stream={"overlap_ratio": 0.90})
    result = cb.compare_records(old, new, threshold=0.05)
    assert not any(
        "stream" in e["metric"]
        for e in result["regressions"] + result["improvements"]
    )


def test_stream_overlap_self_gate(cb, tmp_path):
    """In-record absolute floor: a streamed-residency prefetch that
    stops hiding the host->HBM upload behind compute gates on the NEW
    record alone."""
    assert cb.stream_overlap_gate(_record(), 0.5) is None  # leg absent
    ok = _record(stream={"overlap_ratio": 0.93})
    assert cb.stream_overlap_gate(ok, 0.5) is None
    bad = _record(stream={"overlap_ratio": 0.12})
    entry = cb.stream_overlap_gate(bad, 0.5)
    assert entry and entry["new"] == 0.12 and entry["direction"] == "higher"

    old_p = tmp_path / "old.json"
    bad_p = tmp_path / "bad.json"
    old_p.write_text(json.dumps(_record()))
    bad_p.write_text(json.dumps(bad))
    proc = subprocess.run(
        [sys.executable, _SCRIPT, str(old_p), str(bad_p)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    assert "stream.overlap_ratio" in proc.stdout
    proc = subprocess.run(
        [sys.executable, _SCRIPT, str(old_p), str(bad_p),
         "--stream-overlap-threshold", "0.05"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0


def test_stream_cohort_rate_not_relatively_tracked(cb):
    """The streamed cohort rate is gated by its own absolute in-record
    floor, never as a relative TRACKED metric (the PR 4/5/7 precedent
    for in-record gates)."""
    old = _record(stream={"cohort_rate": 19000.0})
    new = _record(stream={"cohort_rate": 15000.0})
    result = cb.compare_records(old, new, threshold=0.05)
    assert not any(
        "stream" in e["metric"]
        for e in result["regressions"] + result["improvements"]
    )


def test_stream_cohort_rate_self_gate(cb, tmp_path):
    """In-record absolute floor: the largest-population streamed leg
    going host-bound again (cohort rate under the floor) gates on the
    NEW record alone — the O(cohort) sampler's regression signal."""
    assert cb.stream_cohort_rate_gate(_record(), 900.0) is None  # absent
    ok = _record(stream={"cohort_rate": 18000.0, "overlap_ratio": 0.9})
    assert cb.stream_cohort_rate_gate(ok, 900.0) is None
    bad = _record(stream={"cohort_rate": 330.0, "overlap_ratio": 0.9})
    entry = cb.stream_cohort_rate_gate(bad, 900.0)
    assert entry and entry["new"] == 330.0 and entry["direction"] == "higher"

    old_p = tmp_path / "old.json"
    bad_p = tmp_path / "bad.json"
    old_p.write_text(json.dumps(_record()))
    bad_p.write_text(json.dumps(bad))
    proc = subprocess.run(
        [sys.executable, _SCRIPT, str(old_p), str(bad_p)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    assert "stream.cohort_rate" in proc.stdout
    proc = subprocess.run(
        [sys.executable, _SCRIPT, str(old_p), str(bad_p),
         "--stream-cohort-rate-threshold", "100"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0


def test_valuation_corr_not_relatively_tracked(cb):
    """The estimator-fidelity correlation sits near a fixed operating
    point (~0.85-0.9) — like every other in-record ratio it must never
    be a relative TRACKED metric; only the absolute floor judges it."""
    old = _record(valuation={"audit_spearman": 0.95})
    new = _record(valuation={"audit_spearman": 0.85})
    result = cb.compare_records(old, new, threshold=0.05)
    assert not any(
        "valuation" in e["metric"]
        for e in result["regressions"] + result["improvements"]
    )


def test_valuation_corr_self_gate(cb, tmp_path):
    """In-record absolute floor: a streaming valuation vector that stops
    tracking the exact GTG audit SVs gates on the NEW record alone."""
    assert cb.valuation_corr_gate(_record(), 0.8) is None  # leg absent
    ok = _record(valuation={"audit_spearman": 0.881,
                            "overhead_ratio": 0.01})
    assert cb.valuation_corr_gate(ok, 0.8) is None
    # A null correlation (degenerate audit) is absent data, not a
    # regression — the leg reports it, the gate skips it.
    assert cb.valuation_corr_gate(
        _record(valuation={"audit_spearman": None}), 0.8
    ) is None
    bad = _record(valuation={"audit_spearman": 0.41})
    entry = cb.valuation_corr_gate(bad, 0.8)
    assert entry and entry["new"] == 0.41 and entry["direction"] == "higher"

    old_p = tmp_path / "old.json"
    bad_p = tmp_path / "bad.json"
    old_p.write_text(json.dumps(_record()))
    bad_p.write_text(json.dumps(bad))
    proc = subprocess.run(
        [sys.executable, _SCRIPT, str(old_p), str(bad_p)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    assert "valuation.audit_spearman" in proc.stdout
    proc = subprocess.run(
        [sys.executable, _SCRIPT, str(old_p), str(bad_p),
         "--valuation-corr-threshold", "0.3"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0


def test_sweep_amortization_not_relatively_tracked(cb):
    """The serial-vs-fleet wall ratio sits at the operating point the
    compile/run balance sets — like every other in-record ratio it must
    never be a relative TRACKED metric; only the absolute floor judges
    it."""
    old = _record(sweep={"sweep_amortization_ratio": 5.0})
    new = _record(sweep={"sweep_amortization_ratio": 2.6})
    result = cb.compare_records(old, new, threshold=0.05)
    assert not any(
        "sweep" in e["metric"]
        for e in result["regressions"] + result["improvements"]
    )


def test_sweep_amortization_self_gate(cb, tmp_path):
    """In-record absolute floor: a vmapped fleet that stops amortizing
    its compile/dispatch (ratio under the floor) gates on the NEW
    record alone."""
    assert cb.sweep_amortization_gate(_record(), 2.0) is None  # absent
    ok = _record(sweep={"sweep_amortization_ratio": 3.4,
                        "compile_reuse_fraction": 0.875})
    assert cb.sweep_amortization_gate(ok, 2.0) is None
    bad = _record(sweep={"sweep_amortization_ratio": 1.3})
    entry = cb.sweep_amortization_gate(bad, 2.0)
    assert entry and entry["new"] == 1.3 and entry["direction"] == "higher"

    old_p = tmp_path / "old.json"
    bad_p = tmp_path / "bad.json"
    old_p.write_text(json.dumps(_record()))
    bad_p.write_text(json.dumps(bad))
    proc = subprocess.run(
        [sys.executable, _SCRIPT, str(old_p), str(bad_p)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    assert "sweep.sweep_amortization_ratio" in proc.stdout
    proc = subprocess.run(
        [sys.executable, _SCRIPT, str(old_p), str(bad_p),
         "--sweep-amortization-threshold", "1.0"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0


def test_churn_overhead_not_relatively_tracked(cb):
    """The dynamic-vs-static round-time overhead sits near a fixed small
    operating point — like every other in-record ratio it must never be
    a relative TRACKED metric; only the absolute ceiling judges it."""
    old = _record(churn={"churn_overhead_ratio": 0.01})
    new = _record(churn={"churn_overhead_ratio": 0.06})
    result = cb.compare_records(old, new, threshold=0.05)
    assert not any(
        "churn" in e["metric"]
        for e in result["regressions"] + result["improvements"]
    )


def test_churn_overhead_self_gate(cb, tmp_path):
    """In-record absolute ceiling: a registration stream that stops
    riding the round at marginal cost (10x-growth overhead above the
    ceiling) gates on the NEW record alone."""
    assert cb.churn_overhead_gate(_record(), 0.10) is None  # leg absent
    ok = _record(churn={"churn_overhead_ratio": 0.04,
                        "population": {"growth_ratio": 10.0}})
    assert cb.churn_overhead_gate(ok, 0.10) is None
    # A NEGATIVE ratio (dynamic measured faster — run noise) holds too.
    assert cb.churn_overhead_gate(
        _record(churn={"churn_overhead_ratio": -0.02}), 0.10
    ) is None
    bad = _record(churn={"churn_overhead_ratio": 0.31})
    entry = cb.churn_overhead_gate(bad, 0.10)
    assert entry and entry["new"] == 0.31 and entry["direction"] == "lower"

    old_p = tmp_path / "old.json"
    bad_p = tmp_path / "bad.json"
    old_p.write_text(json.dumps(_record()))
    bad_p.write_text(json.dumps(bad))
    proc = subprocess.run(
        [sys.executable, _SCRIPT, str(old_p), str(bad_p)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    assert "churn.churn_overhead_ratio" in proc.stdout
    proc = subprocess.run(
        [sys.executable, _SCRIPT, str(old_p), str(bad_p),
         "--churn-overhead-threshold", "0.5"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0


def test_gtg_scaling_not_relatively_tracked(cb):
    """The D=2/D=1 subset-eval throughput ratio sits near a fixed
    operating point (~2.0 on a real mesh) — like every other in-record
    ratio it must never be a relative TRACKED metric; only the absolute
    floor judges it."""
    old, new = _record(), _record()
    old["gtg"]["gtg_scaling_ratio"] = 1.9
    new["gtg"]["gtg_scaling_ratio"] = 1.6
    result = cb.compare_records(old, new, threshold=0.05)
    assert not any(
        "gtg_scaling" in e["metric"]
        for e in result["regressions"] + result["improvements"]
    )


def test_gtg_scaling_self_gate(cb, tmp_path):
    """In-record absolute floor: a mesh-sharded walk that stops buying
    throughput (D=2/D=1 below the floor) gates on the NEW record
    alone; an unarmed record (1-core host — bench keeps the measured
    ratio under gtg.scaling but never sets the gated key) skips."""
    assert cb.gtg_scaling_gate(_record(), 1.5) is None  # key absent
    # Unarmed 1-core measurement: ratio recorded, gate key absent.
    unarmed = _record()
    unarmed["gtg"]["scaling"] = {"d2_over_d1": 1.05, "host_cores": 1}
    assert cb.gtg_scaling_gate(unarmed, 1.5) is None
    ok = _record()
    ok["gtg"]["gtg_scaling_ratio"] = 1.82
    assert cb.gtg_scaling_gate(ok, 1.5) is None
    bad = _record()
    bad["gtg"]["gtg_scaling_ratio"] = 1.12
    entry = cb.gtg_scaling_gate(bad, 1.5)
    assert entry and entry["new"] == 1.12 and entry["direction"] == "higher"

    old_p = tmp_path / "old.json"
    bad_p = tmp_path / "bad.json"
    old_p.write_text(json.dumps(_record()))
    bad_p.write_text(json.dumps(bad))
    proc = subprocess.run(
        [sys.executable, _SCRIPT, str(old_p), str(bad_p)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    assert "gtg.gtg_scaling_ratio" in proc.stdout
    proc = subprocess.run(
        [sys.executable, _SCRIPT, str(old_p), str(bad_p),
         "--gtg-scaling-threshold", "1.0"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0


def test_model_drift_not_relatively_tracked(cb):
    """model_error_ratio sits near 1.0 — like the other in-record
    ratios it must never be a relative TRACKED metric (PR 4/5
    precedent); only the absolute band gate judges it."""
    old = _record(costmodel={"cnn": {"model_error_ratio": 1.02}})
    new = _record(costmodel={"cnn": {"model_error_ratio": 0.93}})
    result = cb.compare_records(old, new, threshold=0.05)
    assert not any(
        "costmodel" in e["metric"]
        for e in result["regressions"] + result["improvements"]
    )


def test_model_drift_gate_is_a_band(cb):
    """The in-record gate fires when predicted-vs-measured leaves the
    absolute band around 1.0 — in EITHER direction, per program."""
    assert cb.model_drift_gate(_record(), 0.35) == []  # leg absent
    ok = _record(costmodel={
        "cnn": {"model_error_ratio": 0.75},
        "flagship": {"model_error_ratio": 1.0},
        "pod_projection": {"topology": "v4-32"},
    })
    assert cb.model_drift_gate(ok, 0.35) == []
    # Under-prediction out of band (cnn) and over-prediction out of
    # band (flagship) both gate, each with its own entry.
    bad = _record(costmodel={
        "cnn": {"model_error_ratio": 0.5},
        "flagship": {"model_error_ratio": 1.6},
    })
    entries = cb.model_drift_gate(bad, 0.35)
    assert {e["metric"] for e in entries} == {
        "costmodel.cnn.model_error_ratio",
        "costmodel.flagship.model_error_ratio",
    }
    # A leg that degraded to an error sub-object is skipped, not gated.
    degraded = _record(costmodel={"cnn": {"error": "no byte annotations"}})
    assert cb.model_drift_gate(degraded, 0.35) == []


def test_model_drift_gate_cli(cb, tmp_path):
    """The drift gate alone must exit 1, and the threshold flag widens
    the band back to passing."""
    old_p, bad_p = tmp_path / "old.json", tmp_path / "bad.json"
    old_p.write_text(json.dumps(_record()))
    bad_p.write_text(json.dumps(
        _record(costmodel={"flagship": {"model_error_ratio": 1.55}})
    ))
    proc = subprocess.run(
        [sys.executable, _SCRIPT, str(old_p), str(bad_p)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    assert "costmodel.flagship.model_error_ratio" in proc.stdout
    proc = subprocess.run(
        [sys.executable, _SCRIPT, str(old_p), str(bad_p),
         "--model-drift-threshold", "0.6"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0


def test_provenance_refusal(cb):
    old, new = _record(), _record()
    new["config_hash"] = "fedcba654321"
    assert "config_hash" in cb.check_comparable(old, new)
    new["config_hash"] = old["config_hash"]
    new["schema_version"] = 3
    assert "schema_version" in cb.check_comparable(old, new)
    # Records predating the stamp can't prove incomparability -> allowed.
    legacy = {"metric": "simulated_clients_x_rounds_per_sec", "value": 9000}
    assert cb.check_comparable(legacy, _record()) is None


def test_cli_exit_codes(cb, tmp_path):
    """0 = clean, 1 = regression, 2 = provenance refusal (--force
    overrides)."""
    old, good, bad = _record(), _record(value=9050.0), _record(value=5000.0)
    foreign = _record(value=9050.0)
    foreign["config_hash"] = "fedcba654321"
    paths = {}
    for name, rec in [("old", old), ("good", good), ("bad", bad),
                      ("foreign", foreign)]:
        p = tmp_path / f"{name}.json"
        p.write_text(json.dumps(rec))
        paths[name] = str(p)

    def run(*args):
        return subprocess.run(
            [sys.executable, _SCRIPT, *args],
            capture_output=True, text=True, timeout=120,
        )

    assert run(paths["old"], paths["good"]).returncode == 0
    proc = run(paths["old"], paths["bad"])
    assert proc.returncode == 1
    assert "REGRESSIONS" in proc.stdout and "value" in proc.stdout
    proc = run(paths["old"], paths["foreign"])
    assert proc.returncode == 2
    assert "config_hash" in proc.stderr
    # --force compares anyway; identical-enough values -> clean exit.
    assert run(paths["old"], paths["foreign"], "--force").returncode == 0
    # --json emits the machine-readable comparison.
    proc = run(paths["old"], paths["bad"], "--json")
    assert proc.returncode == 1
    assert json.loads(proc.stdout)["regressions"]


def test_mhost_cohort_rate_not_relatively_tracked(cb):
    """The 2-process distributed-store cohort rate is machine-bound —
    like every other in-record gated value it must never be a relative
    TRACKED metric; only the absolute floor judges it."""
    old = _record(mhost={"mhost_cohort_rate": 9000.0})
    new = _record(mhost={"mhost_cohort_rate": 5000.0})
    result = cb.compare_records(old, new, threshold=0.05)
    assert not any(
        "mhost" in e["metric"]
        for e in result["regressions"] + result["improvements"]
    )


def test_mhost_cohort_rate_self_gate(cb, tmp_path):
    """In-record absolute floor on the 2-process streamed sweep's
    steady cohort rate; an unarmed record (1-core host — bench keeps
    the honest number under mhost.cohort_rate but never sets the gated
    key, the PR 14 arming precedent) skips."""
    assert cb.mhost_cohort_rate_gate(_record(), 200.0) is None  # absent
    unarmed = _record(mhost={"cohort_rate": 38.2, "host_cores": 1})
    assert cb.mhost_cohort_rate_gate(unarmed, 200.0) is None
    ok = _record(mhost={"mhost_cohort_rate": 512.0, "cohort_rate": 512.0})
    assert cb.mhost_cohort_rate_gate(ok, 200.0) is None
    bad = _record(mhost={"mhost_cohort_rate": 61.0, "cohort_rate": 61.0})
    entry = cb.mhost_cohort_rate_gate(bad, 200.0)
    assert entry and entry["new"] == 61.0 and entry["direction"] == "higher"

    old_p = tmp_path / "old.json"
    bad_p = tmp_path / "bad.json"
    old_p.write_text(json.dumps(_record()))
    bad_p.write_text(json.dumps(bad))
    proc = subprocess.run(
        [sys.executable, _SCRIPT, str(old_p), str(bad_p)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    assert "mhost.mhost_cohort_rate" in proc.stdout
    proc = subprocess.run(
        [sys.executable, _SCRIPT, str(old_p), str(bad_p),
         "--mhost-cohort-rate-threshold", "50"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0


def test_span_overhead_not_relatively_tracked(cb):
    """The span-trace overhead ratio hovers near zero like the
    client-stats one: it must NOT be in the relative-change TRACKED
    list; only the absolute ceiling below judges it."""
    old = _record(spans={"overhead_ratio": 0.005})
    new = _record(spans={"overhead_ratio": 0.03})  # within the gate
    result = cb.compare_records(old, new, threshold=0.05)
    assert not any(
        "spans" in e["metric"]
        for e in result["regressions"] + result["improvements"]
    )


def test_span_overhead_self_gate(cb, tmp_path):
    """In-record absolute ceiling on the spans leg's on-vs-off round
    time ratio (span_trace='on', telemetry/spans.py): the distributed
    tracer must stay cheap enough to leave on."""
    assert cb.span_overhead_gate(_record(), 0.05) is None  # leg absent
    ok = _record(spans={"overhead_ratio": 0.018})
    assert cb.span_overhead_gate(ok, 0.05) is None
    bad = _record(spans={"overhead_ratio": 0.22})
    entry = cb.span_overhead_gate(bad, 0.05)
    assert entry and entry["new"] == 0.22 and entry["direction"] == "lower"

    old_p = tmp_path / "old.json"
    bad_p = tmp_path / "bad.json"
    old_p.write_text(json.dumps(_record()))
    bad_p.write_text(json.dumps(bad))
    proc = subprocess.run(
        [sys.executable, _SCRIPT, str(old_p), str(bad_p)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    assert "spans.overhead_ratio" in proc.stdout
    proc = subprocess.run(
        [sys.executable, _SCRIPT, str(old_p), str(bad_p),
         "--span-overhead-threshold", "0.5"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0
