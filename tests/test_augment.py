"""In-step augmentation ops (ops/augment.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_learning_simulator_tpu.ops.augment import (
    cifar_augment,
    get_augment,
)


def test_registry():
    assert get_augment("none") is None
    assert get_augment(None) is None
    assert get_augment("cifar") is cifar_augment
    with pytest.raises(ValueError, match="augmentation"):
        get_augment("bogus")


def test_cifar_augment_shapes_and_determinism():
    x = jnp.asarray(np.random.default_rng(0).uniform(size=(8, 32, 32, 3)),
                    jnp.float32)
    key = jax.random.key(0)
    a1 = cifar_augment(x, key)
    a2 = cifar_augment(x, key)
    assert a1.shape == x.shape and a1.dtype == x.dtype
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))
    # a different key gives a different augmentation
    a3 = cifar_augment(x, jax.random.key(1))
    assert not np.array_equal(np.asarray(a1), np.asarray(a3))


def test_cifar_augment_content_preserved_up_to_shift_flip():
    """Values in the output are a subset of {0 (padding)} ∪ input values."""
    x = jnp.asarray(np.random.default_rng(1).uniform(0.5, 1.0,
                                                     size=(4, 32, 32, 3)),
                    jnp.float32)
    out = np.asarray(cifar_augment(x, jax.random.key(2)))
    in_vals = set(np.asarray(x).ravel().tolist())
    for v in out.ravel().tolist():
        assert v == 0.0 or v in in_vals


def test_end_to_end_with_augment(tiny_config):
    import dataclasses

    from distributed_learning_simulator_tpu.simulator import run_simulation

    cfg = dataclasses.replace(
        tiny_config, round=2, augment="cifar",
        dataset_args={"difficulty": 0.5, "shape": (32, 32, 3)},
    )
    res = run_simulation(cfg, setup_logging=False)
    losses = [h["test_loss"] for h in res["history"]]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0] * 1.5  # training is not diverging


def test_resnet34_registry():
    from distributed_learning_simulator_tpu.models.registry import (
        get_model,
        init_params,
    )

    model = get_model("resnet34", num_classes=10)
    x = jnp.zeros((2, 32, 32, 3), jnp.float32)
    params = init_params(model, x, seed=0)
    out = model.apply({"params": params}, x)
    assert out.shape == (2, 10)
    n18 = sum(
        a.size for a in jax.tree_util.tree_leaves(
            init_params(get_model("resnet18"), x, seed=0)
        )
    )
    n34 = sum(a.size for a in jax.tree_util.tree_leaves(params))
    assert n34 > n18  # deeper stages
