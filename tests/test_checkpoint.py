"""Checkpoint/resume: interrupted run == uninterrupted run (exceeds reference,
which has no persistence at all — SURVEY §5)."""

import dataclasses
import os
import pickle

import pytest

from distributed_learning_simulator_tpu.simulator import run_simulation
from distributed_learning_simulator_tpu.utils.checkpoint import (
    CheckpointCorruptError,
    gc_checkpoints,
    latest_checkpoint,
    load_checkpoint,
    load_latest_valid_checkpoint,
    save_checkpoint,
)


def test_save_load_roundtrip(tmp_path):
    import jax.numpy as jnp

    params = {"w": jnp.arange(4.0)}
    state = {"m": jnp.zeros(4)}
    path = save_checkpoint(str(tmp_path / "round_3.ckpt"), 3, params, state,
                           {"shapley_values": {0: {0: 1.0}}})
    ckpt = load_checkpoint(path)
    assert ckpt["round_idx"] == 3
    assert list(ckpt["global_params"]["w"]) == [0.0, 1.0, 2.0, 3.0]
    assert ckpt["algo_state"]["shapley_values"] == {0: {0: 1.0}}


def test_latest_checkpoint_ordering(tmp_path):
    import jax.numpy as jnp

    for r in (0, 2, 10):
        save_checkpoint(str(tmp_path / f"round_{r}.ckpt"), r,
                        {"w": jnp.zeros(1)}, {})
    assert latest_checkpoint(str(tmp_path)).endswith("round_10.ckpt")
    assert latest_checkpoint(str(tmp_path / "missing")) is None


def test_latest_checkpoint_skips_stray_files_and_resume_sweeps_tmps(tmp_path):
    """A stray `foo.ckpt` (no _N suffix) must be ignored, not crash the
    sort; stale `*.ckpt.tmp` left by a crashed writer are swept by the
    RESUME entry point only (read-only discovery must not race a live
    writer's tmp file)."""
    import jax.numpy as jnp

    save_checkpoint(str(tmp_path / "round_3.ckpt"), 3, {"w": jnp.zeros(1)}, {})
    (tmp_path / "foo.ckpt").write_bytes(b"not a checkpoint")
    (tmp_path / "round_9.ckpt.tmp").write_bytes(b"torn write")
    assert latest_checkpoint(str(tmp_path)).endswith("round_3.ckpt")
    assert (tmp_path / "round_9.ckpt.tmp").exists()  # discovery: no sweep
    found, _ = load_latest_valid_checkpoint(str(tmp_path))
    assert found.endswith("round_3.ckpt")
    assert not (tmp_path / "round_9.ckpt.tmp").exists()  # resume: swept
    assert (tmp_path / "foo.ckpt").exists()  # ignored, never deleted


def test_truncated_checkpoint_detected_and_fallback(tmp_path):
    """Acceptance: a checkpoint truncated to half its bytes fails the CRC
    at load, and discovery falls back to the previous valid one."""
    import jax.numpy as jnp

    for r in (0, 1):
        save_checkpoint(str(tmp_path / f"round_{r}.ckpt"), r,
                        {"w": jnp.full((8,), float(r))}, {})
    path1 = tmp_path / "round_1.ckpt"
    blob = path1.read_bytes()
    path1.write_bytes(blob[: len(blob) // 2])
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(str(path1))
    found, payload = load_latest_valid_checkpoint(str(tmp_path))
    assert found.endswith("round_0.ckpt")
    assert payload["round_idx"] == 0
    assert load_latest_valid_checkpoint(str(tmp_path / "none")) == (None, None)


def test_legacy_headerless_checkpoint_loads(tmp_path):
    """Pre-CRC checkpoints (raw pickle, no magic) still load."""
    legacy = {"round_idx": 7, "global_params": {"w": [1.0]},
              "client_state": None, "algo_state": {}, "rng_key": None}
    path = tmp_path / "round_7.ckpt"
    with open(path, "wb") as f:
        pickle.dump(legacy, f)
    assert load_checkpoint(str(path))["round_idx"] == 7
    # ...and a truncated legacy file surfaces as corrupt, not a raw
    # pickle exception, so the fallback scan keeps walking.
    path.write_bytes(path.read_bytes()[:10])
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(str(path))


def test_gc_checkpoints_keep_last(tmp_path):
    import jax.numpy as jnp

    for r in range(5):
        save_checkpoint(str(tmp_path / f"round_{r}.ckpt"), r,
                        {"w": jnp.zeros(1)}, {})
    removed = gc_checkpoints(str(tmp_path), keep_last=2)
    assert len(removed) == 3
    left = sorted(f for f in os.listdir(tmp_path) if f.endswith(".ckpt"))
    assert left == ["round_3.ckpt", "round_4.ckpt"]
    assert gc_checkpoints(str(tmp_path), keep_last=None) == []


def test_resume_falls_back_past_truncated_checkpoint(tiny_config, tmp_path):
    """Resume-level acceptance: truncating the latest checkpoint degrades
    resume by one interval (with a warning) instead of crashing, and the
    resumed history still matches the straight run bit-for-bit."""
    straight = run_simulation(
        dataclasses.replace(tiny_config, round=4), setup_logging=False
    )
    ckdir = tmp_path / "ck"
    run_simulation(
        dataclasses.replace(tiny_config, round=2, checkpoint_dir=str(ckdir),
                            checkpoint_every=1),
        setup_logging=False,
    )
    blob = (ckdir / "round_1.ckpt").read_bytes()
    (ckdir / "round_1.ckpt").write_bytes(blob[: len(blob) // 2])
    resumed = run_simulation(
        dataclasses.replace(tiny_config, round=4, checkpoint_dir=str(ckdir),
                            resume=True),
        setup_logging=False,
    )
    # fell back to round_0.ckpt -> resumed history covers rounds 1..3
    assert [h["round"] for h in resumed["history"]] == [1, 2, 3]
    straight_accs = [h["test_accuracy"] for h in straight["history"]]
    resumed_accs = [h["test_accuracy"] for h in resumed["history"]]
    assert resumed_accs == straight_accs[1:]


def test_checkpoint_keep_last_retention_end_to_end(tiny_config, tmp_path):
    ckdir = tmp_path / "ck"
    run_simulation(
        dataclasses.replace(tiny_config, round=4, checkpoint_dir=str(ckdir),
                            checkpoint_every=1, checkpoint_keep_last=2),
        setup_logging=False,
    )
    left = sorted(f for f in os.listdir(ckdir) if f.endswith(".ckpt"))
    assert left == ["round_2.ckpt", "round_3.ckpt"]
    resumed = run_simulation(
        dataclasses.replace(tiny_config, round=6, checkpoint_dir=str(ckdir),
                            checkpoint_every=1, checkpoint_keep_last=2,
                            resume=True),
        setup_logging=False,
    )
    assert [h["round"] for h in resumed["history"]] == [4, 5]


def test_server_opt_resume_matches_straight_run(tiny_config, tmp_path):
    """FedAvgM momentum state survives checkpoint/resume bit-exactly."""
    fedavgm = dict(server_optimizer_name="sgd", server_learning_rate=1.0,
                   server_momentum=0.9)
    straight = run_simulation(
        dataclasses.replace(tiny_config, round=4, **fedavgm),
        setup_logging=False,
    )
    ckdir = str(tmp_path / "ck")
    run_simulation(
        dataclasses.replace(tiny_config, round=2, checkpoint_dir=ckdir,
                            checkpoint_every=1, **fedavgm),
        setup_logging=False,
    )
    resumed = run_simulation(
        dataclasses.replace(tiny_config, round=4, checkpoint_dir=ckdir,
                            resume=True, **fedavgm),
        setup_logging=False,
    )
    straight_accs = [h["test_accuracy"] for h in straight["history"]]
    resumed_accs = [h["test_accuracy"] for h in resumed["history"]]
    assert resumed_accs == straight_accs[2:]


def test_server_opt_resume_config_mismatch_raises(tiny_config, tmp_path):
    """Resuming an sgd-momentum checkpoint under adam must fail clearly, not
    crash inside the jitted update with a tree-structure error."""
    import pytest

    ckdir = str(tmp_path / "ck")
    run_simulation(
        dataclasses.replace(tiny_config, round=1, checkpoint_dir=ckdir,
                            checkpoint_every=1, server_optimizer_name="sgd",
                            server_momentum=0.9),
        setup_logging=False,
    )
    with pytest.raises(ValueError, match="server optimizer state"):
        run_simulation(
            dataclasses.replace(tiny_config, round=2, checkpoint_dir=ckdir,
                                resume=True, server_optimizer_name="adam"),
            setup_logging=False,
        )


def test_resume_matches_straight_run(tiny_config, tmp_path):
    """Run 4 rounds straight vs 2 + checkpoint + resume 2."""
    straight = run_simulation(
        dataclasses.replace(tiny_config, round=4), setup_logging=False
    )
    ckdir = str(tmp_path / "ck")
    run_simulation(
        dataclasses.replace(tiny_config, round=2, checkpoint_dir=ckdir,
                            checkpoint_every=1),
        setup_logging=False,
    )
    resumed = run_simulation(
        dataclasses.replace(tiny_config, round=4, checkpoint_dir=ckdir,
                            resume=True),
        setup_logging=False,
    )
    # resumed history covers rounds 2..3; accuracies must match the straight
    # run's same rounds exactly (same rng key chain).
    straight_accs = [h["test_accuracy"] for h in straight["history"]]
    resumed_accs = [h["test_accuracy"] for h in resumed["history"]]
    assert resumed_accs == straight_accs[2:]


def test_resume_unfolded_checkpoint_via_model_args(tiny_config, tmp_path):
    """The ADVICE-r3 escape hatch end-to-end: a checkpoint written with
    fold_stage1=False (pre-fold parameter structure) resumes ONLY with the
    matching model_args; the default (folded) config rejects it with the
    structure-mismatch error instead of failing inside jit."""
    base = dataclasses.replace(
        tiny_config, model_name="resnet18", worker_number=2, batch_size=8,
        n_train=64, n_test=32,
        dataset_args={"difficulty": 0.5, "shape": (32, 32, 3)},
        model_args={"fold_stage1": False},
    )
    ckdir = str(tmp_path / "ck")
    run_simulation(
        dataclasses.replace(base, round=1, checkpoint_dir=ckdir,
                            checkpoint_every=1),
        setup_logging=False,
    )
    # default (folded) structure must refuse the unfolded checkpoint
    with pytest.raises(ValueError, match="parameter structure"):
        run_simulation(
            dataclasses.replace(base, round=2, checkpoint_dir=ckdir,
                                resume=True, model_args={}),
            setup_logging=False,
        )
    # the matching model_args resume works
    resumed = run_simulation(
        dataclasses.replace(base, round=2, checkpoint_dir=ckdir,
                            resume=True),
        setup_logging=False,
    )
    assert len(resumed["history"]) == 1


def test_resume_client_state_mismatch_raises(tiny_config, tmp_path):
    """A checkpoint whose per-client state shape disagrees with the current
    config (e.g. sign_SGD momentum=0 -> no buffers, momentum>0 -> buffers)
    must fail loudly instead of crashing inside jit or silently dropping
    the saved buffers."""
    ckdir = str(tmp_path / "ck")
    run_simulation(
        dataclasses.replace(
            tiny_config, distributed_algorithm="sign_SGD",
            learning_rate=0.01, momentum=0.0, round=2,
            checkpoint_dir=ckdir, checkpoint_every=1,
        ),
        setup_logging=False,
    )
    with pytest.raises(ValueError, match="client_state"):
        run_simulation(
            dataclasses.replace(
                tiny_config, distributed_algorithm="sign_SGD",
                learning_rate=0.01, momentum=0.9, round=3,
                checkpoint_dir=ckdir, resume=True,
            ),
            setup_logging=False,
        )
