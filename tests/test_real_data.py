"""End-to-end training on REAL pixels (no network).

Round-1 verdict gap: every prior end-to-end run used the synthetic
surrogate. scikit-learn bundles the UCI/NIST handwritten-digits images
(1797 real 8x8 grayscale scans) inside the package itself, so this
environment can exercise the full pipeline — registry -> partition ->
packed client axis -> jitted round -> eval — on genuine data, including
the ``_load_npz`` file path used for downloaded MNIST/CIFAR archives.
The accuracy-parity protocol for the full datasets is docs/ACCURACY.md.
"""

import dataclasses

import numpy as np
import pytest

from distributed_learning_simulator_tpu.config import ExperimentConfig
from distributed_learning_simulator_tpu.data.registry import get_dataset
from distributed_learning_simulator_tpu.simulator import run_simulation


@pytest.fixture(scope="module")
def digits():
    return get_dataset("digits", seed=0)


def test_digits_is_real_data(digits):
    """Shape/range sanity + a pixel-content check no synthetic surrogate
    would pass: class-mean images of real digits are strongly structured
    (many near-zero border pixels, bright strokes)."""
    assert digits.x_train.shape == (1500, 8, 8, 1)
    assert digits.x_test.shape == (297, 8, 8, 1)
    assert digits.num_classes == 10
    assert 0.0 <= digits.x_train.min() and digits.x_train.max() <= 1.0
    # Real scans: corner pixels are almost always blank, center almost never.
    corners = digits.x_train[:, 0, 0, 0]
    center = digits.x_train[:, 3:5, 3:5, 0].mean(axis=(1, 2))
    assert corners.mean() < 0.05
    assert center.mean() > 0.3


def _digits_config(**overrides):
    base = dict(
        dataset_name="digits",
        model_name="mlp",
        distributed_algorithm="fed",
        worker_number=4,
        round=8,
        epoch=2,
        learning_rate=0.1,
        batch_size=25,
        log_level="WARNING",
        eval_batch_size=512,
    )
    base.update(overrides)
    return ExperimentConfig(**base)


def test_fedavg_learns_real_digits():
    """FedAvg on real pixels: 4 IID clients must reach >=85% test accuracy
    (centralized MLP reference on this split is ~95%+)."""
    res = run_simulation(_digits_config(), setup_logging=False)
    accs = [h["test_accuracy"] for h in res["history"]]
    assert accs[-1] > 0.85, accs
    assert accs[-1] > accs[0]


def test_dirichlet_noniid_real_digits():
    """Non-IID Dirichlet partitioning on real data still learns."""
    res = run_simulation(
        _digits_config(partition="dirichlet", dirichlet_alpha=0.5,
                       max_shard_size=500),
        setup_logging=False,
    )
    assert res["history"][-1]["test_accuracy"] > 0.7


def test_npz_path_end_to_end_real_pixels(tmp_path):
    """The downloaded-archive code path (_load_npz: uint8 -> /255, HW ->
    NHWC) exercised with real pixels written as a raw uint8 .npz, exactly
    the layout scripts/fetch_datasets.py produces."""
    from sklearn.datasets import load_digits

    d = load_digits()
    x = np.round(d.images / 16.0 * 255.0).astype(np.uint8)  # [N, 8, 8] raw
    y = d.target.astype(np.int64)
    np.savez(
        tmp_path / "mnist.npz",
        x_train=x[:1500], y_train=y[:1500],
        x_test=x[1500:], y_test=y[1500:],
    )
    ds = get_dataset("mnist", data_dir=str(tmp_path))
    assert ds.x_train.shape == (1500, 8, 8, 1)  # HW -> NHWC applied
    assert ds.x_train.max() <= 1.0  # /255 applied
    cfg = _digits_config(dataset_name="mnist", data_dir=str(tmp_path),
                         round=6)
    res = run_simulation(cfg, dataset=ds, setup_logging=False)
    assert res["history"][-1]["test_accuracy"] > 0.8


def test_bf16_sr_matches_f32_on_real_pixels():
    """bf16 local state with hash-dither SR must track the f32 trajectory
    on REAL data over a moderate horizon (the synthetic tiny-config test
    can't catch slow stochastic-rounding bias; 50-round bench comparisons
    in docs/PERFORMANCE.md are the long-horizon evidence)."""
    f32 = run_simulation(_digits_config(round=10), setup_logging=False)
    bf16 = run_simulation(
        _digits_config(round=10, local_compute_dtype="bfloat16"),
        setup_logging=False,
    )
    a32 = f32["history"][-1]["test_accuracy"]
    a16 = bf16["history"][-1]["test_accuracy"]
    assert a16 > 0.85
    assert abs(a16 - a32) < 0.05, (a16, a32)


def test_many_dirichlet_clients_with_sampling_real_digits():
    """Population-scale axis on REAL pixels (VERDICT r2 item 8: earlier
    real-data coverage stopped at N=8 full participation): 100 Dirichlet
    clients — 15 real scans each — with 30% client sampling per round, plus
    a cosine lr schedule. Exercises partition skew, the fixed-size sampled
    cohort path, and state scatter-back at population scale."""
    res = run_simulation(
        _digits_config(
            worker_number=100,
            partition="dirichlet",
            dirichlet_alpha=0.3,
            participation_fraction=0.3,
            round=20,
            batch_size=5,
            max_shard_size=60,
            lr_schedule="cosine",
            lr_min_factor=0.1,
        ),
        setup_logging=False,
    )
    accs = [h["test_accuracy"] for h in res["history"]]
    assert accs[-1] > 0.8, accs
    assert accs[-1] > accs[0]


def test_fed_quant_real_digits_telemetry():
    """Quantized exchange + per-client eval telemetry on real pixels."""
    res = run_simulation(
        _digits_config(distributed_algorithm="fed_quant", round=5),
        setup_logging=False,
    )
    last = res["history"][-1]
    assert last["test_accuracy"] > 0.75
    assert last["client_eval"]["pre_agg_accuracy_mean"] > 0.5
