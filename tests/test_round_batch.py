"""Round batching (config.rounds_per_dispatch; parallel/engine.py
make_batched_round_fn): K>1 fuses K federated rounds + server eval into
one dispatched scan whose history must be BIT-identical to the K=1
per-round loop — including participation sampling, failure draws, quorum
verdicts, lr-schedule factors, and server-optimizer state — while K=1
(the default) keeps the exact pre-feature per-round program. Checkpoint
cadence clips dispatch sizes, so resume composes at batch granularity.
"""

import dataclasses
import glob
import json
import os

import numpy as np
import pytest

from distributed_learning_simulator_tpu.config import ExperimentConfig
from distributed_learning_simulator_tpu.simulator import (
    lr_factors,
    run_simulation,
)


def _run(cfg, **overrides):
    cfg = dataclasses.replace(cfg, **overrides)
    return run_simulation(cfg, setup_logging=False)


def _series(result, *keys):
    return {k: [h.get(k) for h in result["history"]] for k in keys}


# ------------------------------------------------------------- validation


def test_config_validation():
    with pytest.raises(ValueError, match="rounds_per_dispatch"):
        ExperimentConfig(rounds_per_dispatch=0).validate()
    with pytest.raises(ValueError, match="vmap execution mode"):
        ExperimentConfig(
            rounds_per_dispatch=2, execution_mode="threaded"
        ).validate()
    ExperimentConfig(rounds_per_dispatch=8).validate()


def test_default_is_one():
    assert ExperimentConfig().rounds_per_dispatch == 1


def test_shapley_refuses_round_batching(tiny_config):
    """Shapley's post_round must see every round's stack + metrics
    synchronously; the simulator refuses with the cause, before any
    training dispatch."""
    with pytest.raises(ValueError, match="rounds_per_dispatch"):
        _run(tiny_config, distributed_algorithm="GTG_shapley_value",
             rounds_per_dispatch=2)


def test_fed_quant_client_eval_gates_batching(tiny_config):
    """fed_quant auto-enables client_eval at reference-like cohorts, whose
    post_round needs each round's raw stack — batching is refused unless
    client_eval is explicitly off (then the capability comes back)."""
    from distributed_learning_simulator_tpu.factory import get_algorithm

    cfg = dataclasses.replace(
        tiny_config, distributed_algorithm="fed_quant", rounds_per_dispatch=2
    )
    assert not get_algorithm("fed_quant", cfg).supports_round_batching
    with pytest.raises(ValueError, match="rounds_per_dispatch"):
        run_simulation(cfg, setup_logging=False)
    opted_out = dataclasses.replace(cfg, client_eval=False)
    assert get_algorithm("fed_quant", opted_out).supports_round_batching


def test_lr_factors_vector_matches_scalar(tiny_config):
    cfg = dataclasses.replace(
        tiny_config, round=6, lr_schedule="cosine", lr_min_factor=0.1
    )
    from distributed_learning_simulator_tpu.simulator import _lr_factor

    vec = lr_factors(cfg, 2, 3)
    assert vec.dtype == np.float32 and vec.shape == (3,)
    for i in range(3):
        assert vec[i] == np.float32(_lr_factor(cfg, 2 + i))


# ------------------------------------------------------- K=1 default pin


def test_k1_default_keeps_per_round_program(tiny_config, tmp_path):
    """The default dispatches the per-round program exactly as before:
    warmup compiles name round_fn/server_eval (never the batched scan),
    0 post-warmup compiles, records carry no dispatch_rounds marker, and
    an explicit rounds_per_dispatch=1 writes byte-identical metrics
    lines to the default."""
    cfg = dataclasses.replace(
        tiny_config, round=3, telemetry_level="basic",
        compilation_cache_dir=None, log_root=str(tmp_path / "log_a"),
    )
    result = run_simulation(cfg)
    assert result["post_warmup_compiles"] == 0

    def read_records(root):
        paths = glob.glob(os.path.join(root, "**", "metrics.jsonl"),
                          recursive=True)
        assert len(paths) == 1
        with open(paths[0]) as f:
            return f.read()

    lines_a = read_records(str(tmp_path / "log_a"))
    records = [json.loads(line) for line in lines_a.splitlines()]
    warmup_names = records[0]["telemetry"]["compiled"]
    assert any("round_fn" in n for n in warmup_names)
    assert not any("batched" in n for n in warmup_names)
    for r in records:
        assert "dispatch_rounds" not in r["telemetry"]

    explicit = dataclasses.replace(
        cfg, rounds_per_dispatch=1, log_root=str(tmp_path / "log_b"),
    )
    run_simulation(explicit)
    lines_b = read_records(str(tmp_path / "log_b"))
    strip = lambda text: [  # noqa: E731 — timing fields differ run-to-run
        {k: v for k, v in json.loads(line).items()
         if k not in ("round_seconds",) and k != "telemetry"}
        for line in text.splitlines()
    ]
    assert strip(lines_a) == strip(lines_b)


# --------------------------------------------------- K>1 differential


def test_k3_matches_k1_fedavg_full_feature(tiny_config):
    """FedAvg with participation sampling, dropout faults, quorum, a
    cosine lr schedule, and a momentum server optimizer: K=3 (dispatch
    sizes 3 then 1 — the remainder dispatch included) must reproduce the
    K=1 history bit-for-bit, cohort hashes and failure draws included."""
    cfg = dataclasses.replace(
        tiny_config, worker_number=8, round=4,
        participation_fraction=0.5, failure_mode="dropout",
        failure_prob=0.3, min_survivors=2, lr_schedule="cosine",
        server_optimizer_name="sgd", server_learning_rate=1.0,
        server_momentum=0.9,
    )
    keys = ("test_accuracy", "test_loss", "mean_client_loss", "lr_factor",
            "survivor_count", "round_rejected", "cohort_hash")
    base = _series(_run(cfg), *keys)
    batched = _series(_run(cfg, rounds_per_dispatch=3), *keys)
    assert base == batched
    assert None not in base["cohort_hash"]  # sampling actually exercised


def test_k2_matches_k1_sign_sgd(tiny_config):
    """sign_SGD (momentum, straggler faults, quorum): the per-step vote
    loop scans identically inside the batched dispatch."""
    cfg = dataclasses.replace(
        tiny_config, distributed_algorithm="sign_SGD", learning_rate=0.01,
        momentum=0.9, round=3, failure_mode="straggler", failure_prob=0.3,
        min_survivors=1,
    )
    keys = ("test_accuracy", "test_loss", "mean_client_loss",
            "survivor_count", "round_rejected", "uplink_compression_ratio")
    assert _series(_run(cfg), *keys) == _series(
        _run(cfg, rounds_per_dispatch=2), *keys
    )


# ----------------------------------------------- checkpoint/resume + tel


def test_checkpoint_resume_non_aligned_boundary(tiny_config, tmp_path):
    """checkpoint_every=3 with K=4: dispatch sizes clip to the boundary
    (3, then 1 at the round=4 horizon), the checkpoint lands mid-run on
    a non-K-aligned round, and the resumed batched run stitches a
    history bit-identical to an uninterrupted K=1 run."""
    cfg = dataclasses.replace(
        tiny_config, round=6, momentum=0.9,
        server_optimizer_name="sgd", server_momentum=0.9,
    )
    golden = [h["test_accuracy"] for h in _run(cfg)["history"]]

    ckpt = str(tmp_path / "ckpt")
    first = _run(cfg, round=4, rounds_per_dispatch=4,
                 checkpoint_dir=ckpt, checkpoint_every=3)
    assert sorted(os.listdir(ckpt)) == ["round_2.ckpt"]
    resumed = _run(cfg, rounds_per_dispatch=4, checkpoint_dir=ckpt,
                   checkpoint_every=3, resume=True)
    assert [h["round"] for h in resumed["history"]] == [3, 4, 5]
    stitched = [h["test_accuracy"] for h in first["history"][:3]] + [
        h["test_accuracy"] for h in resumed["history"]
    ]
    assert stitched == golden


def test_batched_telemetry_per_dispatch(tiny_config, tmp_path):
    """K=2 with telemetry + client_stats: one telemetry sub-object per
    dispatch (on its LAST record, stamped dispatch_rounds), client-stats
    rows on their cadence, 0 post-warmup compiles (each dispatch length
    is warmup once), schema-valid records, and report_run renders
    per-dispatch without double-counting."""
    import importlib.util

    import jsonschema

    cfg = dataclasses.replace(
        tiny_config, round=4, rounds_per_dispatch=2,
        telemetry_level="basic", client_stats="on", client_stats_every=2,
        compilation_cache_dir=None, log_root=str(tmp_path / "log"),
    )
    result = run_simulation(cfg)
    assert result["post_warmup_compiles"] == 0
    paths = glob.glob(os.path.join(cfg.log_root, "**", "metrics.jsonl"),
                      recursive=True)
    with open(paths[0]) as f:
        records = [json.loads(line) for line in f]
    assert [r["round"] for r in records] == [0, 1, 2, 3]
    with open(os.path.join(os.path.dirname(__file__), "data",
                           "metrics_record.schema.json")) as f:
        schema = json.load(f)
    for r in records:
        jsonschema.validate(r, schema)
    # Telemetry on dispatch-last records only; stats rows on the cadence.
    assert [("telemetry" in r) for r in records] == [
        False, True, False, True,
    ]
    assert [("client_stats" in r) for r in records] == [
        True, False, True, False,
    ]
    for r in (records[1], records[3]):
        assert r["telemetry"]["dispatch_rounds"] == 2
        assert {"client_step", "host_sync", "post_round"} <= set(
            r["telemetry"]["phase_seconds"]
        )
    assert records[1]["telemetry"]["compiles"] > 0  # warmup dispatch
    assert records[1]["telemetry"]["warmup"] is True
    assert records[3]["telemetry"]["compiles"] == 0

    spec = importlib.util.spec_from_file_location(
        "report_run",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "report_run.py"),
    )
    report_run = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(report_run)
    summary = report_run.summarize_run(report_run.load_metrics(
        os.path.dirname(paths[0])
    ))
    assert summary["phase_unit"] == "dispatch"
    assert summary["compiles"]["post_warmup"] == 0
    assert summary["compiles"]["warmup"] > 0
    # No double-counting: the summary totals equal the record sums.
    rec_total = sum(
        sum(r["telemetry"]["phase_seconds"].values())
        for r in records if "telemetry" in r
    )
    sum_total = sum(st["total_s"] for st in summary["phases"].values())
    assert abs(rec_total - sum_total) < 1e-3
    rendered = "\n".join(report_run.render_summary(summary))
    assert "per-dispatch mean" in rendered
    assert "post-warmup recompiles: none" in rendered
