"""Deterministic end-to-end round tests on tiny models/synthetic data.

Covers the reference's only executable validation — the smoke run of
simulator.sh:1 — but as real assertions: learning happens, every algorithm
completes, Shapley outputs satisfy game-theoretic sanity checks, and the
whole simulation is bit-deterministic under a fixed seed.
"""

import dataclasses

import numpy as np
import pytest

from distributed_learning_simulator_tpu.simulator import run_simulation


def _run(cfg, **overrides):
    cfg = dataclasses.replace(cfg, **overrides)
    return run_simulation(cfg, setup_logging=False)


def test_fedavg_learns(tiny_config):
    res = _run(tiny_config, round=5)
    accs = [h["test_accuracy"] for h in res["history"]]
    assert accs[-1] > 0.3  # well above 10-class chance
    assert accs[-1] > accs[0]


def test_pipelined_rounds_match_sync(tiny_config):
    """Round pipelining only moves device->host fetch timing; metric history
    must be bit-identical to the synchronous loop."""
    r1 = _run(tiny_config, round=4, pipeline_rounds=True)
    r2 = _run(tiny_config, round=4, pipeline_rounds=False)
    assert [h["test_accuracy"] for h in r1["history"]] == [
        h["test_accuracy"] for h in r2["history"]
    ]
    assert [h["test_loss"] for h in r1["history"]] == [
        h["test_loss"] for h in r2["history"]
    ]


def test_cnn_tpu_learns(tiny_config):
    """The MXU-aligned CIFAR CNN trains end-to-end on 32x32x3 inputs.

    At test scale (512 samples, 3 rounds) the 450k-param model moves loss,
    not yet accuracy — assert on monotone test-loss descent.
    """
    res = _run(
        tiny_config, model_name="cnn_tpu", round=3, learning_rate=0.05,
        dataset_args={"difficulty": 0.5, "shape": (32, 32, 3)},
    )
    losses = [h["test_loss"] for h in res["history"]]
    assert losses[-1] < losses[0]
    assert all(np.isfinite(losses))


def test_server_sgd_lr1_equals_plain_fedavg(tiny_config):
    """FedOpt sanity: server sgd(lr=1, momentum=0) applies
    prev - 1.0*(prev - aggregate) = aggregate, i.e. exactly plain FedAvg."""
    r1 = _run(tiny_config, round=3)
    r2 = _run(tiny_config, round=3, server_optimizer_name="sgd",
              server_learning_rate=1.0, server_momentum=0.0)
    a1 = [h["test_accuracy"] for h in r1["history"]]
    a2 = [h["test_accuracy"] for h in r2["history"]]
    np.testing.assert_allclose(a1, a2, atol=1e-6)


def test_server_momentum_learns_and_differs(tiny_config):
    """FedAvgM (server momentum) trains and actually changes the trajectory."""
    plain = _run(tiny_config, round=4)
    fedavgm = _run(tiny_config, round=4, server_optimizer_name="sgd",
                   server_learning_rate=1.0, server_momentum=0.9)
    accs = [h["test_accuracy"] for h in fedavgm["history"]]
    assert accs[-1] > 0.2  # learns
    assert accs != [h["test_accuracy"] for h in plain["history"]]


def test_unknown_server_optimizer_raises(tiny_config):
    with pytest.raises(ValueError, match="server optimizer"):
        _run(tiny_config, round=1, server_optimizer_name="bogus")


def test_fedavg_deterministic(tiny_config):
    r1 = _run(tiny_config)
    r2 = _run(tiny_config)
    assert [h["test_accuracy"] for h in r1["history"]] == [
        h["test_accuracy"] for h in r2["history"]
    ]


def test_sign_sgd_learns(tiny_config):
    res = _run(tiny_config, distributed_algorithm="sign_SGD",
               learning_rate=0.01, round=3)
    accs = [h["test_accuracy"] for h in res["history"]]
    assert accs[-1] > 0.25
    assert res["history"][-1]["uplink_compression_ratio"] > 30  # ~32x for fp32->1bit


def test_sign_sgd_chunked_matches_unchunked(tiny_config):
    """Chunked per-step vote accumulation (partial sign-sums) must equal
    the all-clients vmap vote bitwise (same math, different scheduling)."""
    base = _run(tiny_config, distributed_algorithm="sign_SGD",
                learning_rate=0.01, round=3)
    chunked = _run(tiny_config, distributed_algorithm="sign_SGD",
                   learning_rate=0.01, round=3, client_chunk_size=2)
    assert [h["test_accuracy"] for h in base["history"]] == [
        h["test_accuracy"] for h in chunked["history"]
    ]


def test_sign_sgd_momentum_free_no_buffers(tiny_config):
    """momentum=0 allocates NO per-client buffers (torch semantics; the
    memory fix that lets large-model sign_SGD run) and still learns."""
    res = _run(tiny_config, distributed_algorithm="sign_SGD",
               learning_rate=0.01, momentum=0.0, round=3)
    assert res["client_state"] is None
    assert res["history"][-1]["test_accuracy"] > 0.25


def test_sign_sgd_momentum_chunked_matches(tiny_config):
    """Chunking with momentum: per-client buffers round-trip through the
    chunk scan (reshape/stack) without reordering clients."""
    base = _run(tiny_config, distributed_algorithm="sign_SGD",
                learning_rate=0.01, momentum=0.9, round=2)
    chunked = _run(tiny_config, distributed_algorithm="sign_SGD",
                   learning_rate=0.01, momentum=0.9, round=2,
                   client_chunk_size=2)
    assert [h["test_accuracy"] for h in base["history"]] == [
        h["test_accuracy"] for h in chunked["history"]
    ]


def test_sign_sgd_nondivisor_chunk_matches(tiny_config):
    """A chunk size that does not divide the client count takes the
    remainder path and still equals the unchunked vote bitwise (the OOM
    advisor may suggest any chunk size)."""
    base = _run(tiny_config, distributed_algorithm="sign_SGD",
                learning_rate=0.01, round=2)
    chunked = _run(tiny_config, distributed_algorithm="sign_SGD",
                   learning_rate=0.01, round=2, client_chunk_size=3)
    assert [h["test_accuracy"] for h in base["history"]] == [
        h["test_accuracy"] for h in chunked["history"]
    ]


def test_sign_sgd_rejects_participation_sampling(tiny_config):
    with pytest.raises(ValueError, match="participation"):
        _run(tiny_config, distributed_algorithm="sign_SGD",
             participation_fraction=0.5)


def test_sign_sgd_requires_sgd(tiny_config):
    with pytest.raises(ValueError, match="SGD"):
        _run(tiny_config, distributed_algorithm="sign_SGD",
             optimizer_name="adam")


def test_fed_quant_learns_and_reports_compression(tiny_config):
    res = _run(tiny_config, distributed_algorithm="fed_quant", round=3)
    accs = [h["test_accuracy"] for h in res["history"]]
    assert accs[-1] > 0.2
    last = res["history"][-1]
    assert 3.5 < last["uplink_compression_ratio"] < 4.1  # fp32 -> 8-bit


def test_fed_quant_client_eval_telemetry(tiny_config):
    """Per-round pre/post-aggregation accuracy telemetry (parity with
    reference fed_quant_worker.py:55-69, batched under vmap here)."""
    res = _run(tiny_config, distributed_algorithm="fed_quant", round=3)
    for h in res["history"]:
        ce = h["client_eval"]
        assert 0.0 <= ce["pre_agg_accuracy_min"] <= ce["pre_agg_accuracy_mean"]
        assert ce["pre_agg_accuracy_mean"] <= ce["pre_agg_accuracy_max"] <= 1.0
        assert ce["post_agg_accuracy"] == h["test_accuracy"]
    # clients train on disjoint shards with per-client RNG, so their local
    # models must not collapse to one evaluator (catches a vmap in_axes bug
    # broadcasting a single params tree); deterministic under the fixed seed
    last = res["history"][-1]["client_eval"]
    assert last["pre_agg_accuracy_max"] > last["pre_agg_accuracy_min"]


def test_fed_quant_client_eval_uses_raw_local_model(tiny_config):
    """The telemetry evaluates the RAW local QAT model (reference
    fed_quant_worker.py:55-58), not the quantized upload: at 2-level
    (1-bit) quantization the dequantized uploads — and the global model
    aggregated from them — are near-chance, while the raw local models
    genuinely learn. Under the old dequantized-upload evaluation this
    gap cannot appear."""
    res = _run(tiny_config, distributed_algorithm="fed_quant", round=3,
               quant_levels=2, qat=False)
    ce = res["history"][-1]["client_eval"]
    assert ce["pre_agg_accuracy_mean"] > ce["post_agg_accuracy"] + 0.05, ce


def test_fed_quant_client_eval_vmap_matches_individual(tiny_config):
    """The vmapped per-client evaluation must equal evaluating each
    client's params individually (guards the in_axes wiring)."""
    import jax

    from distributed_learning_simulator_tpu.algorithms.fed_quant import FedQuant

    res = _run(tiny_config, distributed_algorithm="fed_quant", round=1,
               pipeline_rounds=False)
    algo: FedQuant = res["algorithm"]
    # Re-run one round worth of eval by hand via the algorithm's jit
    assert algo._client_eval_jit is not None
    # build a tiny fake stacked params: use the final global replicated 3x
    stacked = jax.tree_util.tree_map(
        lambda p: np.stack([np.asarray(p)] * 3), res["global_params"]
    )
    # identical params must produce identical per-client accuracies equal
    # to the single-model eval
    import jax.numpy as jnp

    from distributed_learning_simulator_tpu.data.registry import get_dataset
    from distributed_learning_simulator_tpu.parallel.engine import pad_eval_set

    ds = get_dataset("synthetic", n_train=512, n_test=256, seed=0,
                     difficulty=0.5)
    eval_batches = tuple(
        jnp.asarray(a)
        for a in pad_eval_set(ds.x_test, ds.y_test, 512, flatten=True)
    )
    m = algo._client_eval_jit(
        jax.tree_util.tree_map(jnp.asarray, stacked), *eval_batches
    )
    accs = np.asarray(m["accuracy"])
    assert accs.shape == (3,)
    assert accs[0] == accs[1] == accs[2]
    # The client-eval program applies the QAT fake-quant transform at
    # inference (the reference's QAT-instrumented eval forward), so the
    # single-model comparison must too.
    transform = algo.client_param_transform()
    single_params = (
        transform(res["global_params"]) if transform is not None
        else res["global_params"]
    )
    single = algo._eval_fn(single_params, *eval_batches)
    np.testing.assert_allclose(accs[0], float(single["accuracy"]), atol=1e-6)


def test_fed_client_eval_opt_in(tiny_config):
    """client_eval=True works for plain FedAvg too (the telemetry is
    FedAvg-family machinery, not fed_quant-specific)."""
    res = _run(tiny_config, round=2, client_eval=True)
    for h in res["history"]:
        ce = h["client_eval"]
        assert 0.0 <= ce["pre_agg_accuracy_mean"] <= 1.0
        assert ce["post_agg_accuracy"] == h["test_accuracy"]
    # auto (None) keeps plain fed on the fused path: no telemetry
    res2 = _run(tiny_config, round=1)
    assert "client_eval" not in res2["history"][0]


def test_client_eval_rejected_outside_fedavg_family(tiny_config):
    with pytest.raises(ValueError, match="client_eval"):
        _run(tiny_config, distributed_algorithm="sign_SGD",
             client_eval=True)
    with pytest.raises(ValueError, match="client_eval"):
        _run(tiny_config, distributed_algorithm="multiround_shapley_value",
             client_eval=True)


def test_fed_quant_client_eval_auto_disables_large_cohort(tiny_config):
    """client_eval=None (auto) must keep the fused memory-bounded path for
    large cohorts: no telemetry above the auto threshold."""
    from distributed_learning_simulator_tpu.algorithms.fed_quant import FedQuant

    big = dataclasses.replace(tiny_config, worker_number=64, client_eval=None)
    assert FedQuant(big).materializes_client_stack is False
    small = dataclasses.replace(tiny_config, worker_number=8, client_eval=None)
    assert FedQuant(small).materializes_client_stack is True
    forced = dataclasses.replace(tiny_config, worker_number=64,
                                 client_eval=True)
    assert FedQuant(forced).materializes_client_stack is True
    # client_eval rides a private channel, NOT the keep_client_params
    # subclass contract (aux['client_params'] stays absent).
    assert FedQuant(forced).keep_client_params is False


def test_fed_quant_client_eval_disabled(tiny_config):
    """client_eval=False keeps the memory-safe fused path: no telemetry,
    same compression reporting."""
    res = _run(tiny_config, distributed_algorithm="fed_quant", round=2,
               client_eval=False)
    for h in res["history"]:
        assert "client_eval" not in h
        assert h["uplink_compression_ratio"] > 3.5


def test_bf16_local_compute_learns_close_to_f32(tiny_config):
    """local_compute_dtype='bfloat16' (per-client diverged state in bf16,
    f32 aggregation) must track the f32 trajectory closely on a short run."""
    f32 = _run(tiny_config, round=4)
    bf16 = _run(tiny_config, round=4, local_compute_dtype="bfloat16")
    a32 = [h["test_accuracy"] for h in f32["history"]]
    a16 = [h["test_accuracy"] for h in bf16["history"]]
    assert a16[-1] > 0.3  # learns
    assert abs(a16[-1] - a32[-1]) < 0.1, (a16, a32)
    # global params stay f32 (aggregation accumulates in f32)
    import jax

    leaves = jax.tree_util.tree_leaves(bf16["global_params"])
    assert all(leaf.dtype == np.float32 for leaf in leaves)


def test_bf16_local_compute_shapley_materialize_path(tiny_config):
    """The materializing path (Shapley keeps the client stack) restores f32
    before subset statistics."""
    res = _run(tiny_config, distributed_algorithm="multiround_shapley_value",
               round=2, local_compute_dtype="bfloat16")
    assert set(res["algorithm"].shapley_values) == {0, 1}


def test_bf16_composes_with_fed_quant(tiny_config):
    """bf16 local state + 8-bit quantized exchange (the two compression
    layers compose: quantize computes in f32 internally, aggregation
    accumulates f32)."""
    res = _run(tiny_config, distributed_algorithm="fed_quant", round=3,
               local_compute_dtype="bfloat16")
    last = res["history"][-1]
    assert last["test_accuracy"] > 0.2
    assert 3.5 < last["uplink_compression_ratio"] < 4.1
    assert last["client_eval"]["pre_agg_accuracy_mean"] > 0.1


def test_bf16_requires_reset_optimizer(tiny_config):
    with pytest.raises(ValueError, match="reset_client_optimizer"):
        _run(tiny_config, local_compute_dtype="bfloat16",
             reset_client_optimizer=False)


def test_bf16_rejected_for_sign_sgd(tiny_config):
    # A bf16 shared-tree mode was built and measured in round 5: device
    # time was IDENTICAL to f32 (2740 vs 2678 ms at flagship scale — the
    # model's activations/convs are bf16 either way and the f32 tensors in
    # the trace are XLA materialization choices, not the params tree), so
    # the mode was removed rather than shipped as a dead knob.
    with pytest.raises(ValueError, match="local_compute_dtype"):
        _run(tiny_config, distributed_algorithm="sign_SGD",
             local_compute_dtype="bfloat16")


def test_multiround_shapley(tiny_config):
    res = _run(tiny_config, distributed_algorithm="multiround_shapley_value",
               round=2)
    algo = res["algorithm"]
    assert set(algo.shapley_values) == {0, 1}
    for r, sv in algo.shapley_values.items():
        assert set(sv) == {0, 1, 2, 3}
        # efficiency: sum of SVs == acc(all) - acc(empty) for that round
        accs = [h["test_accuracy"] for h in res["history"]]
        assert np.isfinite(sum(sv.values()))


def test_gtg_matches_exact_shapley(tiny_config):
    """GTG Monte-Carlo estimates should land near the exact powerset values
    on the same run (same seed -> identical training trajectories)."""
    exact = _run(tiny_config, distributed_algorithm="multiround_shapley_value",
                 round=2)["algorithm"].shapley_values
    gtg = _run(tiny_config, distributed_algorithm="GTG_shapley_value",
               round=2, round_trunc_threshold=-1.0)["algorithm"].shapley_values
    # round_trunc_threshold=-1 disables round truncation so both score
    # every round.
    for r in exact:
        ev = np.array([exact[r][i] for i in range(4)])
        gv = np.array([gtg[r][i] for i in range(4)])
        assert np.abs(ev - gv).max() < 0.05


def test_cifar100_hundred_class_path(tiny_config):
    """The 100-class registry entry plumbs num_classes through model
    construction, eval, and the loss (loss under 100 classes starts near
    ln(100) and must descend)."""
    res = _run(
        tiny_config, dataset_name="cifar100", model_name="cnn", round=2,
        n_train=512, n_test=256, learning_rate=0.05,
        dataset_args={"difficulty": 0.5},
    )
    losses = [h["test_loss"] for h in res["history"]]
    assert losses[0] < 5.0  # near ln(100) ~ 4.6, not diverged
    assert losses[-1] < losses[0]


def test_dirichlet_partition_end_to_end(tiny_config):
    res = _run(tiny_config, partition="dirichlet", dirichlet_alpha=0.5,
               round=3)
    assert res["final_accuracy"] > 0.15


def test_unknown_algorithm_raises(tiny_config):
    with pytest.raises(RuntimeError, match="unknown distributed algorithm"):
        _run(tiny_config, distributed_algorithm="nope")


def test_heterogeneous_client_override(tiny_config, tiny_dataset):
    """Per-client dataset override (reference simulator_backup.py:71-77)."""
    from distributed_learning_simulator_tpu.simulator import build_client_data

    cd = build_client_data(tiny_config, tiny_dataset)
    bad_x = np.zeros((50,) + tiny_dataset.input_shape, np.float32)
    bad_y = np.zeros((50,), np.int32)
    cd.override_client(0, bad_x, bad_y)
    assert cd.sizes[0] == 50.0
    res = run_simulation(tiny_config, dataset=tiny_dataset, client_data=cd,
                         setup_logging=False)
    assert res["final_accuracy"] is not None


def test_client_chunking_matches_unchunked(tiny_config):
    """lax.map chunking is an execution detail: results must match pure vmap."""
    base = _run(tiny_config, worker_number=8, round=2)
    chunked = _run(tiny_config, worker_number=8, round=2, client_chunk_size=2)
    a = [h["test_accuracy"] for h in base["history"]]
    b = [h["test_accuracy"] for h in chunked["history"]]
    np.testing.assert_allclose(b, a, atol=1e-5)


def test_client_chunking_remainder_matches(tiny_config):
    """Chunk size that does not divide the cohort must still use the fused
    memory-safe path and match the unchunked result (8 % 3 == 2)."""
    base = _run(tiny_config, worker_number=8, round=2)
    chunked = _run(tiny_config, worker_number=8, round=2, client_chunk_size=3)
    a = [h["test_accuracy"] for h in base["history"]]
    b = [h["test_accuracy"] for h in chunked["history"]]
    np.testing.assert_allclose(b, a, atol=1e-5)


def test_auto_chunk_size(tiny_config):
    """client_chunk_size=0 resolves to a positive footprint-model estimate
    (clamped to the cohort) and the run completes."""
    cfg = dataclasses.replace(tiny_config, client_chunk_size=0, round=2)
    res = run_simulation(cfg, setup_logging=False)
    assert len(res["history"]) == 2
    # resolved into the result, NOT written back to the caller's config
    # (a reused config with a different model must re-resolve auto)
    assert cfg.client_chunk_size == 0
    assert 1 <= res["client_chunk_size"] <= cfg.worker_number


def test_negative_chunk_rejected(tiny_config):
    with pytest.raises(ValueError, match="client_chunk_size"):
        _run(tiny_config, client_chunk_size=-5)


def test_all_empty_cohort_keeps_model(tiny_config, tiny_dataset):
    """A round whose every participant has zero samples (possible under
    extreme Dirichlet skew + sampling) must keep the previous global model,
    not NaN it (parity with reference fed_server.py:45-47 empty-subset)."""
    import jax

    from distributed_learning_simulator_tpu.simulator import build_client_data

    cd = build_client_data(tiny_config, tiny_dataset)
    cd.mask[:] = 0.0
    cd.sizes[:] = 0.0
    res = run_simulation(tiny_config, dataset=tiny_dataset, client_data=cd,
                         setup_logging=False)
    for leaf in jax.tree_util.tree_leaves(res["global_params"]):
        assert np.isfinite(np.asarray(leaf)).all()
    accs = [h["test_accuracy"] for h in res["history"]]
    assert accs[0] == accs[-1]  # model never moved


def test_participation_sampling(tiny_config):
    """Client sampling: cohort of half the clients per round still learns,
    and Shapley refuses partial participation."""
    res = _run(tiny_config, worker_number=8, round=3,
               participation_fraction=0.5)
    assert res["final_accuracy"] > 0.15
    with pytest.raises(ValueError, match="participation"):
        _run(tiny_config, distributed_algorithm="multiround_shapley_value",
             participation_fraction=0.5)


def test_metrics_jsonl_written(tiny_config, tmp_path):
    import dataclasses, json, glob, os
    cfg = dataclasses.replace(tiny_config, log_root=str(tmp_path))
    run_simulation(cfg)  # setup_logging defaults True -> writes artifacts
    files = glob.glob(str(tmp_path / "**" / "metrics.jsonl"), recursive=True)
    assert len(files) == 1
    lines = [json.loads(l) for l in open(files[0])]
    assert len(lines) == cfg.round
    assert {"round", "test_accuracy", "round_seconds"} <= set(lines[0])


def test_metrics_jsonl_written_threaded_sign(tiny_config, tmp_path):
    """The per-run artifact contract (log file + metrics.jsonl) holds in
    threaded sign_SGD mode too — same layout as the vmap path."""
    import glob
    import json

    cfg = dataclasses.replace(
        tiny_config, log_root=str(tmp_path), distributed_algorithm="sign_SGD",
        learning_rate=0.01, round=2, execution_mode="threaded",
    )
    run_simulation(cfg)
    files = glob.glob(str(tmp_path / "**" / "metrics.jsonl"), recursive=True)
    assert len(files) == 1
    lines = [json.loads(line) for line in open(files[0])]
    assert len(lines) == cfg.round
    assert {"round", "test_accuracy", "uplink_compression_ratio",
            "sync_steps"} <= set(lines[0])


def test_heterogeneous_entry_point(tiny_config, tmp_path):
    import dataclasses
    from distributed_learning_simulator_tpu.simulator_heterogeneous import (
        run_heterogeneous,
    )
    cfg = dataclasses.replace(tiny_config, log_root=str(tmp_path), round=2)
    res = run_heterogeneous(cfg, bad_dataset_name="synthetic")
    assert res["final_accuracy"] is not None


def test_compact_storage_matches_float(tiny_config):
    """uint8-flat client storage is an execution detail; with 8-bit-exact
    inputs the trajectories should be near-identical to float32 storage."""
    base = _run(tiny_config, compact_client_data=False, round=2)
    compact = _run(tiny_config, compact_client_data=True, round=2)
    a = [h["test_accuracy"] for h in base["history"]]
    b = [h["test_accuracy"] for h in compact["history"]]
    np.testing.assert_allclose(b, a, atol=0.02)


def test_max_shard_size_caps(tiny_config, tiny_dataset):
    from distributed_learning_simulator_tpu.simulator import build_client_data
    import dataclasses

    cfg = dataclasses.replace(tiny_config, max_shard_size=64)
    cd = build_client_data(cfg, tiny_dataset)
    assert cd.shard_size == 64
    res = _run(tiny_config, max_shard_size=64, round=2)
    assert res["final_accuracy"] is not None
