"""telemetry/costmodel.py + topologies.py: the predictive cost model.

Hand-computed roofline numbers on a tiny synthetic ledger (efficiency
factors pinned to 1.0 so the arithmetic is exact), topology-table
validation, the schema-v6 ``costmodel`` record shape against the
checked-in JSON schema, a gzipped-trace-fixture end-to-end pass (same
fixture pattern as tests/test_tracing.py — the wrapper-frame exclusion
rule is shared with the bench proxy), and the simulator integration:
``cost_model_trace`` attaches the sub-object to the run's LAST record
only, and the default keeps records at schema v5 or below.
"""

import dataclasses
import gzip
import json
import os

import jsonschema
import pytest

from distributed_learning_simulator_tpu.config import ExperimentConfig
from distributed_learning_simulator_tpu.telemetry.costmodel import (
    DEFAULT_ANCHOR,
    DEFAULT_EFFICIENCY,
    GIB,
    costmodel_record,
    ledger_totals,
    predict_round,
)
from distributed_learning_simulator_tpu.telemetry.topologies import (
    TOPOLOGIES,
    Topology,
    get_topology,
)
from distributed_learning_simulator_tpu.utils.reporting import (
    build_round_record,
    config_hash,
)

_EXACT = {"mxu": 1.0, "hbm": 1.0, "ici": 1.0}

# One GiB/s of SI bandwidth: makes bytes/seconds arithmetic exact below.
_GIBPS = GIB / 1e9


def _toy(chips=1, peak_tflops=1e-3, hbm_gbps=_GIBPS, ici_gbps=_GIBPS,
         usd=3.6):
    return Topology("toy", chips, peak_tflops, hbm_gbps, ici_gbps, usd)


# ------------------------------------------------------------- topologies


def test_topology_table_contents():
    """The checked-in table must keep the entries the docs and the bench
    anchor name — including a >= 32-chip pod (the acceptance
    projection) — with physically sensible positive specs."""
    for required in ("cpu-host", "v5e-1", "v5e-8", "v4-8", "v4-32"):
        assert required in TOPOLOGIES, required
    assert DEFAULT_ANCHOR in TOPOLOGIES
    assert any(t.chips >= 32 for t in TOPOLOGIES.values())
    for t in TOPOLOGIES.values():
        assert t.chips >= 1
        assert t.peak_tflops > 0 and t.hbm_gbps > 0
        assert t.ici_gbps >= 0 and t.usd_per_chip_hour >= 0
        assert TOPOLOGIES[t.name] is t  # keys match names


def test_get_topology_error_names_known_entries():
    assert get_topology("v4-32").chips == 32
    with pytest.raises(ValueError, match="v5e-1"):
        get_topology("v9-9000")


def test_efficiency_factors_are_fractions_of_peak():
    for name, value in DEFAULT_EFFICIENCY.items():
        assert 0.0 < value <= 1.0, name


# ------------------------------------------------------- roofline by hand


def test_memory_bound_category_hand_computed():
    """1 GiB over a 1 GiB/s topology at efficiency 1.0 = exactly 1 s."""
    ledger = {"elementwise": {"bytes_gb": 1.0, "flops_g": 0.0,
                              "device_ms": 5.0, "op_count": 3}}
    pred = predict_round(ledger, _toy(), efficiency=_EXACT)
    assert pred["predicted_ms"] == pytest.approx(1000.0)
    assert pred["bottleneck"] == "memory"
    assert pred["categories"]["elementwise"]["bottleneck"] == "memory"


def test_compute_bound_category_hand_computed():
    """2 GFLOP against a 1 GFLOP/s peak takes 2 s and beats its own
    byte term — the category flips compute-bound."""
    ledger = {"matmul_conv": {"bytes_gb": 1.0, "flops_g": 2.0,
                              "device_ms": 5.0, "op_count": 1}}
    pred = predict_round(ledger, _toy(), efficiency=_EXACT)
    assert pred["predicted_ms"] == pytest.approx(2000.0)
    assert pred["bottleneck"] == "compute"


def test_chips_divide_bytes_and_trace_rounds_normalize():
    """Data-parallel scaling: n chips divide the byte volume; a trace
    covering 2 rounds halves the per-round basis."""
    ledger = {"elementwise": {"bytes_gb": 1.0, "flops_g": 0.0,
                              "device_ms": 5.0, "op_count": 3}}
    two_chip = predict_round(ledger, _toy(chips=2), efficiency=_EXACT)
    assert two_chip["predicted_ms"] == pytest.approx(500.0)
    per_round = predict_round(ledger, _toy(), trace_rounds=2,
                              efficiency=_EXACT)
    assert per_round["predicted_ms"] == pytest.approx(500.0)
    with pytest.raises(ValueError, match="trace_rounds"):
        predict_round(ledger, _toy(), trace_rounds=0)


def test_collective_category_rides_ici():
    """Traced collective volume: each of 4 chips moves its 1/4 share
    over 1 GiB/s of ICI = 0.25 s; on a single chip (no ICI) the same
    bytes are charged to HBM instead."""
    ledger = {"collective": {"bytes_gb": 1.0, "flops_g": 0.0,
                             "device_ms": 5.0, "op_count": 2}}
    pred = predict_round(ledger, _toy(chips=4), efficiency=_EXACT)
    assert pred["predicted_ms"] == pytest.approx(250.0)
    assert pred["bottleneck"] == "collective"
    single = predict_round(ledger, _toy(chips=1), efficiency=_EXACT)
    assert single["predicted_ms"] == pytest.approx(1000.0)
    assert single["bottleneck"] == "memory"


def test_allreduce_estimate_needs_params_and_chips():
    """The FedAvg global-model all-reduce (absent from single-chip
    traces) is estimated from param_bytes: 2 * P * (n-1)/n over ICI."""
    ledger = {"elementwise": {"bytes_gb": 1.0, "flops_g": 0.0,
                              "device_ms": 5.0, "op_count": 3}}
    base = predict_round(ledger, _toy(chips=2), efficiency=_EXACT)
    with_ar = predict_round(ledger, _toy(chips=2), efficiency=_EXACT,
                            param_bytes=GIB)
    # 2 * 1 GiB * 1/2 / 1 GiB/s = 1 s on top of the 0.5 s memory term.
    assert with_ar["predicted_ms"] - base["predicted_ms"] == (
        pytest.approx(1000.0)
    )
    assert with_ar["bottleneck"] == "collective"
    # Single chip: no interconnect, no all-reduce charge.
    alone = predict_round(ledger, _toy(chips=1), efficiency=_EXACT,
                          param_bytes=GIB)
    assert alone["predicted_ms"] == pytest.approx(1000.0)


def test_ledger_totals():
    ledger = {
        "a": {"bytes_gb": 1.0, "flops_g": 2.0, "device_ms": 3.0,
              "op_count": 4},
        "b": {"bytes_gb": 0.5, "flops_g": 0.0, "device_ms": 1.0,
              "op_count": 1},
    }
    t = ledger_totals(ledger)
    assert t == {"bytes_gb": 1.5, "flops_g": 2.0, "device_ms": 4.0,
                 "op_count": 5}
    assert ledger_totals({})["bytes_gb"] == 0.0


# ------------------------------------------------------- costmodel_record


def _ledger():
    return {"elementwise": {"bytes_gb": 1.0, "flops_g": 0.0,
                            "device_ms": 5.0, "op_count": 3}}


def test_costmodel_record_anchor_and_error_ratio():
    topos = {"toy": _toy(), "toy-4": _toy(chips=4)}
    rec = costmodel_record(
        _ledger(), anchor="toy", measured_ms=500.0, topologies=topos,
        efficiency=_EXACT, run_rounds=100,
    )
    assert rec["anchor_topology"] == "toy"
    assert rec["predicted_ms"] == pytest.approx(1000.0)
    assert rec["measured_ms"] == 500.0
    # predicted / measured: the drift-gate number.
    assert rec["model_error_ratio"] == pytest.approx(2.0)
    assert rec["run_rounds"] == 100
    assert set(rec["per_topology"]) == {"toy", "toy-4"}
    assert rec["per_topology"]["toy-4"]["predicted_ms"] == (
        pytest.approx(250.0)
    )
    # $/round at 3.6 USD/chip-hour: 1 s * 1 chip = 0.001 USD; $/run
    # multiplies by the horizon.
    assert rec["per_topology"]["toy"]["usd_per_round"] == (
        pytest.approx(0.001)
    )
    assert rec["per_topology"]["toy"]["usd_per_run"] == pytest.approx(0.1)
    # Per-category breakdown normalized to the per-round basis.
    assert rec["categories"]["elementwise"]["bytes_gb"] == 1.0
    assert rec["categories"]["elementwise"]["predicted_ms"] == (
        pytest.approx(1000.0)
    )


def test_costmodel_record_without_measurement():
    rec = costmodel_record(_ledger(), anchor="toy",
                           topologies={"toy": _toy()}, efficiency=_EXACT)
    assert rec["measured_ms"] is None
    assert rec["model_error_ratio"] is None
    assert "run_rounds" not in rec


def test_sweep_cost_record_hand_computed():
    """$/sweep (ISSUE 11): the compiled program priced once, multiplied
    by the sweep's experiment-round occupancy — device work does not
    amortize, only the compile does (the reuse fraction records it)."""
    from distributed_learning_simulator_tpu.telemetry.costmodel import (
        sweep_cost_record,
    )

    topos = {"toy": _toy(), "toy-4": _toy(chips=4)}
    rec = sweep_cost_record(
        _ledger(), points=8, rounds_total=48, programs_compiled=1,
        anchor="toy", topologies=topos, efficiency=_EXACT,
    )
    assert rec["anchor_topology"] == "toy"
    assert rec["points"] == 8 and rec["rounds_total"] == 48
    # 8 points, 1 program: 7/8 of points rode a warm program — the
    # acceptance bookkeeping.
    assert rec["compile_reuse_fraction"] == pytest.approx(7 / 8)
    # toy: 1 GiB over 1 GiB/s = 1 s/round -> 0.001 USD/round at 3.6
    # USD/chip-hour; the sweep occupies 48 experiment-rounds.
    toy = rec["per_topology"]["toy"]
    assert toy["usd_per_sweep"] == pytest.approx(0.048)
    assert toy["usd_per_point"] == pytest.approx(0.006)
    # 4 chips split the bytes 4x but cost 4x the chip-hours: same $.
    assert rec["per_topology"]["toy-4"]["usd_per_sweep"] == (
        pytest.approx(0.048)
    )
    with pytest.raises(ValueError, match="points"):
        sweep_cost_record(_ledger(), points=0, rounds_total=1,
                          programs_compiled=0, topologies=topos)


def test_costmodel_record_validates_against_metrics_schema():
    """The record the builder emits IS the schema-v6 sub-object — pin it
    against the same checked-in JSON schema the metrics tests use."""
    with open(os.path.join(os.path.dirname(__file__), "data",
                           "metrics_record.schema.json")) as f:
        schema = json.load(f)
    rec = costmodel_record(_ledger(), anchor="v5e-1", measured_ms=123.4,
                           run_rounds=150)
    record = build_round_record(
        {"round": 1, "test_accuracy": 0.5, "test_loss": 1.0,
         "round_seconds": 0.1}, None, None, None, None, rec,
    )
    assert record["schema_version"] == 6
    jsonschema.validate(record, schema)


# ------------------------------------------------- trace fixture -> model


def _write_trace(root, events):
    d = os.path.join(root, "plugins", "profile", "run1")
    os.makedirs(d, exist_ok=True)
    with gzip.open(os.path.join(d, "host.trace.json.gz"), "wt") as f:
        json.dump({"traceEvents": events}, f)


def test_trace_fixture_to_prediction_end_to_end(tmp_path):
    """Gzipped fixture -> categorize_ops -> costmodel_record: classes
    land where classify_op says, wrapper frames stay excluded (the rule
    shared with the bench proxy), and the roofline sums per category."""
    from distributed_learning_simulator_tpu.utils.tracing import (
        categorize_ops,
    )

    def op(name, dur_us, nbytes, long_name="", flops=None):
        args = {"raw_bytes_accessed": nbytes, "long_name": long_name}
        if flops is not None:
            args["flops"] = flops
        return {"ph": "X", "name": name, "dur": dur_us, "args": args}

    _write_trace(str(tmp_path), [
        op("convolution.1", 100.0, GIB, "convolution", flops=2e9),
        op("fusion.2", 50.0, GIB // 2, "loop fusion root"),
        op("copy.3", 10.0, GIB // 4),
        op("all-reduce.4", 10.0, GIB // 4),
        # Wrapper frames must not reach the ledger (double counting).
        op("while", 1000.0, 100 * GIB),
        op("jit(round_fn)", 1000.0, 100 * GIB, "jit frame"),
    ])
    ledger = categorize_ops(str(tmp_path))
    assert set(ledger) == {"matmul_conv", "elementwise", "copy_layout",
                           "collective"}
    assert ledger["matmul_conv"]["bytes_gb"] == 1.0
    assert ledger["matmul_conv"]["flops_g"] == pytest.approx(2.0)
    assert ledger["elementwise"]["bytes_gb"] == 0.5
    assert ledger_totals(ledger)["bytes_gb"] == 2.0

    rec = costmodel_record(ledger, anchor="toy",
                           topologies={"toy": _toy(chips=1)},
                           efficiency=_EXACT)
    # All four categories are memory-bound at these sizes (2 GFLOP vs
    # 1 GFLOP/s loses to nothing here: 1 GiB / 1 GiB/s = 1 s < 2 s —
    # compute wins for matmul_conv), so: matmul 2 s + 0.5 + 0.25 + 0.25.
    assert rec["predicted_ms"] == pytest.approx(3000.0)
    assert rec["categories"]["matmul_conv"]["bottleneck"] == "compute"
    assert rec["bottleneck"] == "compute"


# ------------------------------------------------- simulator integration


def test_simulator_attaches_v6_record_on_last_round(tmp_path, tiny_config,
                                                    tiny_dataset):
    """cost_model_trace: the LAST record carries the schema-v6 costmodel
    sub-object (validating against the checked-in schema), earlier
    records keep their pre-v6 layout, and the result dict mirrors it."""
    from distributed_learning_simulator_tpu.simulator import run_simulation

    _write_trace(str(tmp_path), [{
        "ph": "X", "name": "fusion.1", "dur": 100.0,
        "args": {"raw_bytes_accessed": GIB, "long_name": "loop fusion"},
    }])
    config = dataclasses.replace(
        tiny_config, cost_model_trace=str(tmp_path),
        cost_model_trace_rounds=1, cost_model_topology="v5e-1",
    )
    result = run_simulation(config, dataset=tiny_dataset,
                            setup_logging=False)
    history = result["history"]
    assert len(history) == config.round
    assert "costmodel" not in history[0]
    last = history[-1]
    assert last["schema_version"] == 6
    cm = last["costmodel"]
    assert cm == result["costmodel"]
    assert cm["anchor_topology"] == "v5e-1"
    assert cm["predicted_ms"] > 0
    assert cm["measured_ms"] > 0
    assert cm["model_error_ratio"] is not None
    assert cm["run_rounds"] == config.round
    assert "v4-32" in cm["per_topology"]
    with open(os.path.join(os.path.dirname(__file__), "data",
                           "metrics_record.schema.json")) as f:
        jsonschema.validate(last, json.load(f))


def test_simulator_default_stays_pre_v6(tiny_config, tiny_dataset):
    """cost_model_trace=None (default): no record carries a costmodel
    sub-object and schema versions stay at v5 or below."""
    from distributed_learning_simulator_tpu.simulator import run_simulation

    result = run_simulation(tiny_config, dataset=tiny_dataset,
                            setup_logging=False)
    assert result["costmodel"] is None
    for record in result["history"]:
        assert "costmodel" not in record
        assert record.get("schema_version", 1) <= 5


def test_simulator_empty_trace_degrades(tmp_path, tiny_config,
                                        tiny_dataset):
    """A missing/empty trace dir disables the model with a warning
    instead of emitting a fabricated zero-cost record."""
    from distributed_learning_simulator_tpu.simulator import run_simulation

    config = dataclasses.replace(
        tiny_config, cost_model_trace=str(tmp_path / "nope"),
    )
    result = run_simulation(config, dataset=tiny_dataset,
                            setup_logging=False)
    assert result["costmodel"] is None
    assert "costmodel" not in result["history"][-1]


# ------------------------------------------------- report_run rendering


def test_report_run_renders_cost_at_scale_section():
    """The offline reporter's "cost at scale" section (jax-free): the
    measured anchor row leads, every topology-table entry gets a
    predicted row with chip count + bottleneck + $/run, and the
    model-error ratio line names the compare_bench gate."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "report_run",
        os.path.join(os.path.dirname(__file__), os.pardir, "scripts",
                     "report_run.py"),
    )
    report_run = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(report_run)

    cm = costmodel_record(_ledger(), anchor="v5e-1", measured_ms=123.4,
                          run_rounds=150)
    records = [
        {"round": 0, "round_seconds": 0.13, "accuracy": 0.4,
         "schema_version": 1},
        {"round": 1, "round_seconds": 0.12, "accuracy": 0.5,
         "schema_version": 6, "costmodel": cm},
    ]
    summary = report_run.summarize_run(records)
    # The LAST record carrying a costmodel wins (the simulator attaches
    # it to the run's final record).
    assert summary["costmodel"] == cm
    text = "\n".join(report_run.render_summary(summary))
    assert "cost at scale" in text
    assert "measured   v5e-1" in text
    for name, topo in TOPOLOGIES.items():
        assert f"predicted  {name}" in text
        assert f"x{topo.chips}" in text
    assert "/run" in text
    assert "model error: predicted/measured" in text
    assert "--model-drift-threshold" in text


# ----------------------------------------------------------- config knobs


def test_cost_model_knobs_do_not_move_config_hash(tiny_config):
    """Pure host-side analysis must not make runs incomparable."""
    priced = dataclasses.replace(
        tiny_config, cost_model_trace="/tmp/trace",
        cost_model_trace_rounds=3, cost_model_topology="v4-8",
    )
    assert config_hash(priced) == config_hash(tiny_config)


def test_config_validates_cost_model_knobs(tiny_config):
    with pytest.raises(ValueError, match="topology"):
        dataclasses.replace(
            tiny_config, cost_model_topology="v99-bogus"
        ).validate()
    with pytest.raises(ValueError, match="cost_model_trace_rounds"):
        dataclasses.replace(
            tiny_config, cost_model_trace_rounds=0
        ).validate()
