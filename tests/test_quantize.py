"""Quantization round-trip error bounds + unbiasedness (SURVEY §4)."""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_learning_simulator_tpu.ops.payload import (
    compression_ratio,
    payload_bytes,
    quantized_payload_bytes,
)
from distributed_learning_simulator_tpu.ops.quantize import (
    dequantize,
    dequantize_tree,
    fake_quant,
    stochastic_quantize,
    stochastic_quantize_tree,
)


def test_roundtrip_error_bound(rng):
    """|x - dq(q(x))| <= scale (one quantization step) elementwise."""
    x = jnp.asarray(rng.normal(size=(128,)).astype(np.float32)) * 5.0
    q = stochastic_quantize(x, levels=256, key=jax.random.key(0))
    err = np.abs(np.asarray(dequantize(q)) - np.asarray(x))
    assert err.max() <= float(q.scale) + 1e-6


def test_codes_in_range(rng):
    x = jnp.asarray(rng.normal(size=(64,)).astype(np.float32))
    q = stochastic_quantize(x, levels=16, key=jax.random.key(1))
    codes = np.asarray(q.codes)
    assert codes.min() >= 0 and codes.max() <= 15
    np.testing.assert_allclose(codes, np.round(codes))  # integer-valued


def test_stochastic_rounding_unbiased():
    """E[dequantize(quantize(x))] == x across keys."""
    x = jnp.asarray([0.1, 0.25, 0.5, 0.77, 0.9], dtype=jnp.float32)
    keys = jax.random.split(jax.random.key(2), 2000)
    dqs = jax.vmap(lambda k: dequantize(stochastic_quantize(x, 5, k)))(keys)
    np.testing.assert_allclose(np.asarray(dqs).mean(axis=0), np.asarray(x),
                               atol=0.01)


def test_constant_tensor_safe():
    x = jnp.full((8,), 3.14)
    q = stochastic_quantize(x, 256, jax.random.key(0))
    np.testing.assert_allclose(np.asarray(dequantize(q)), 3.14, rtol=1e-5)


def test_tree_roundtrip(rng):
    tree = {
        "a": jnp.asarray(rng.normal(size=(4, 4)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(7,)).astype(np.float32)),
    }
    q = stochastic_quantize_tree(tree, 256, jax.random.key(3))
    dq = dequantize_tree(q)
    for k in tree:
        assert np.abs(np.asarray(dq[k]) - np.asarray(tree[k])).max() < 0.1


def test_fake_quant_straight_through_gradient():
    """STE: d/dx sum(fake_quant(x)) == 1 everywhere."""
    x = jnp.linspace(-2.0, 2.0, 31)
    g = jax.grad(lambda v: jnp.sum(fake_quant(v, 16)))(x)
    np.testing.assert_allclose(np.asarray(g), 1.0)


def test_fake_quant_forward_quantizes():
    x = jnp.linspace(0.0, 1.0, 100)
    y = fake_quant(x, 4)
    assert len(np.unique(np.asarray(y).round(6))) <= 4


def test_payload_accounting():
    tree = {"w": jnp.zeros((100, 10), jnp.float32)}
    raw = payload_bytes(tree)
    assert raw == 1000 * 4
    q8 = quantized_payload_bytes(tree, 256)
    assert q8 == 1000 + 8  # 1 byte/elem + scale/zp metadata
    assert 3.9 < compression_ratio(raw, q8) < 4.0
