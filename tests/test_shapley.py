"""Exact Shapley on toy games with known values (SURVEY §4 test strategy)."""

from itertools import combinations

import numpy as np
import pytest

from distributed_learning_simulator_tpu.algorithms.shapley import (
    shapley_from_utilities,
)


def _all_subsets(n):
    ids = list(range(n))
    for size in range(n + 1):
        for combo in combinations(ids, size):
            yield frozenset(combo)


def test_additive_game():
    """u(S) = sum of member values -> SV_i = value_i exactly."""
    values = np.array([1.0, 2.0, 3.0, 4.0])
    utilities = {s: float(sum(values[i] for i in s)) for s in _all_subsets(4)}
    sv = shapley_from_utilities(utilities, 4)
    np.testing.assert_allclose(sv, values, rtol=1e-9)


def test_glove_game():
    """Classic 3-player glove game: players {0,1} hold left gloves, {2} right;
    u(S)=1 iff S contains a left and the right. Known SVs: (1/6, 1/6, 2/3)."""
    def u(s):
        return 1.0 if (2 in s and (0 in s or 1 in s)) else 0.0

    utilities = {s: u(s) for s in _all_subsets(3)}
    sv = shapley_from_utilities(utilities, 3)
    np.testing.assert_allclose(sv, [1 / 6, 1 / 6, 2 / 3], rtol=1e-9)


def test_efficiency_property():
    """sum(SV) == u(grand coalition) - u(empty) for any game."""
    rng = np.random.default_rng(0)
    utilities = {s: float(rng.normal()) for s in _all_subsets(5)}
    sv = shapley_from_utilities(utilities, 5)
    np.testing.assert_allclose(
        sv.sum(),
        utilities[frozenset(range(5))] - utilities[frozenset()],
        rtol=1e-9,
    )


def test_symmetry_property():
    """Symmetric players get identical SVs."""
    utilities = {s: float(len(s) ** 2) for s in _all_subsets(4)}
    sv = shapley_from_utilities(utilities, 4)
    np.testing.assert_allclose(sv, sv[0])


def test_shapley_eval_chunk_invariant(tiny_config):
    """shapley_eval_chunk is pure batching: per-round SVs must be identical
    across chunk sizes — including one that doesn't divide the subset count
    and the production values the N=1000 GTG measurements use (64, 128 —
    docs/PERFORMANCE.md § Scale validation)."""
    import dataclasses

    from distributed_learning_simulator_tpu.simulator import run_simulation

    svs = []
    for chunk in (16, 5, 64, 128):
        cfg = dataclasses.replace(
            tiny_config, distributed_algorithm="multiround_shapley_value",
            round=2, shapley_eval_chunk=chunk,
        )
        res = run_simulation(cfg, setup_logging=False)
        svs.append([h["shapley_values"] for h in res["history"]])
    for other in svs[1:]:
        for h0, h1 in zip(svs[0], other):
            np.testing.assert_allclose(
                [h0[i] for i in sorted(h0)], [h1[i] for i in sorted(h1)],
                rtol=1e-6, atol=1e-9,
            )


def test_shapley_eval_dtype_agreement(tiny_config):
    """shapley_eval_dtype='bfloat16' (default: halved stack reads) must
    produce SVs within a small tolerance of the f32 evaluator on the same
    round — utilities feed an argmax accuracy, and the weighted mean still
    accumulates f32, so the perturbation is per-subset bf16 rounding of
    the client params only. Also covers the GTG walk: truncation decisions
    may differ at the eps boundary, so GTG compares the SV VECTOR with a
    loose tolerance rather than requiring identical walks."""
    import dataclasses

    from distributed_learning_simulator_tpu.simulator import run_simulation

    for algo, tol in (
        ("multiround_shapley_value", 0.02),
        ("GTG_shapley_value", 0.05),
    ):
        out = {}
        for dtype in ("float32", "bfloat16"):
            cfg = dataclasses.replace(
                tiny_config, distributed_algorithm=algo, round=2,
                shapley_eval_dtype=dtype,
            )
            res = run_simulation(cfg, setup_logging=False)
            out[dtype] = [h["shapley_values"] for h in res["history"]]
        for h32, h16 in zip(out["float32"], out["bfloat16"]):
            v32 = np.array([h32[i] for i in sorted(h32)])
            v16 = np.array([h16[i] for i in sorted(h16)])
            assert np.all(np.isfinite(v16))
            np.testing.assert_allclose(v16, v32, atol=tol)


def test_exact_refuses_large_n(tiny_config):
    from distributed_learning_simulator_tpu.algorithms.shapley import (
        MultiRoundShapley,
    )
    from distributed_learning_simulator_tpu.algorithms.base import RoundContext

    # Up-front: the build-time check refuses against the TRUE client count
    # before any training could run. The constructor merely warns — a
    # caller-supplied ClientData may have fewer clients than worker_number
    # (ADVICE r4), so worker_number=17 with 12 actual clients must build.
    tiny_config.worker_number = 17
    algo = MultiRoundShapley(tiny_config)  # warns, does not raise
    algo.check_cohort(12)  # override cohort within bounds: allowed
    with pytest.raises(ValueError, match="2\\^N"):
        algo.check_cohort(17)
    # Backstop: a round whose actual client count exceeds 16 (heterogeneous
    # client_data overrides bypass worker_number) still refuses in post_round.
    tiny_config.worker_number = 4
    algo = MultiRoundShapley(tiny_config)
    ctx = RoundContext(
        round_idx=0, global_params=None, prev_global_params=None,
        sizes=np.ones(17), aux={}, metrics={"accuracy": 0.5},
        prev_metrics=None, eval_batches=(), log_dir=None,
    )
    with pytest.raises(ValueError, match="2\\^N"):
        algo.post_round(ctx)


def test_exact_refuses_large_n_at_round_fn_build(tiny_config):
    """The vmap path's make_round_fn carries the check: worker_number > 16
    with a matching client count fails at build time, before training."""
    import dataclasses

    import optax

    from distributed_learning_simulator_tpu.algorithms.shapley import (
        MultiRoundShapley,
    )

    cfg = dataclasses.replace(
        tiny_config, distributed_algorithm="multiround_shapley_value",
        worker_number=17,
    )
    algo = MultiRoundShapley(cfg)
    with pytest.raises(ValueError, match="2\\^N"):
        algo.make_round_fn(lambda p, x: x, optax.sgd(0.1), 17)


def test_gtg_cap_below_n_refused(tiny_config):
    """An explicit gtg_max_permutations below the client count can never be
    honored (one sampling iteration draws N permutations) nor converge
    (needs > max(30, N) records): refuse at build time (VERDICT r4 weak #2
    — previously the cap was silently overrun and convergence silently
    unreachable)."""
    from distributed_learning_simulator_tpu.algorithms.shapley import GTGShapley

    tiny_config.gtg_max_permutations = 3
    algo = GTGShapley(tiny_config)  # constructor warns only
    with pytest.raises(ValueError, match="gtg_max_permutations"):
        algo.check_cohort(tiny_config.worker_number)
    # A cap >= N passes the build check.
    tiny_config.gtg_max_permutations = 500
    GTGShapley(tiny_config).check_cohort(tiny_config.worker_number)


def test_gtg_default_cap_is_convergence_capable(tiny_config):
    """Unset cap resolves to max(500, 2N): at N=1000 two sampling
    iterations fit, so the > max(30, N) record requirement is reachable."""
    from distributed_learning_simulator_tpu.algorithms.shapley import GTGShapley

    tiny_config.gtg_max_permutations = None
    algo = GTGShapley(tiny_config)
    algo.check_cohort(1000)  # auto cap never refuses
    assert algo._effective_cap(4) == 500
    assert algo._effective_cap(1000) == 2000


def test_materializing_stack_feasibility_guard(tiny_config):
    """keep_client_params algorithms must refuse with a sized error when
    the [n_clients, params] stack cannot fit (mirrors the exact-Shapley
    N>16 refusal), instead of a generic device OOM deep in dispatch."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from distributed_learning_simulator_tpu.simulator import (
        _assert_client_stack_feasible,
    )

    cfg = dataclasses.replace(
        tiny_config, distributed_algorithm="GTG_shapley_value"
    )
    # ~104 MB of params x 1000 clients = ~104 GB >> any device budget.
    big_params = {"w": jax.ShapeDtypeStruct((26_000_000,), jnp.float32)}
    with pytest.raises(ValueError, match="parameter stack"):
        _assert_client_stack_feasible(cfg, big_params, 1000)
    # The tiny real config passes untouched.
    small = {"w": jnp.zeros((100,), jnp.float32)}
    _assert_client_stack_feasible(cfg, small, 4)


def test_shapley_eval_samples_cap(tiny_config):
    """shapley_eval_samples evaluates subset utilities on a test subsample
    (the round metric stays full-set); SVs stay close to the full-set run
    and the efficiency property holds against the CAPPED utilities."""
    import dataclasses

    import jax.numpy as jnp

    from distributed_learning_simulator_tpu.algorithms.shapley import (
        cap_eval_batches,
    )
    from distributed_learning_simulator_tpu.simulator import run_simulation

    xb = jnp.arange(12.0).reshape(2, 6)
    yb = jnp.arange(12).reshape(2, 6)
    mb = jnp.ones((2, 6))
    # Cap below the batch size: one SMALLER batch (masked padding would
    # still compute; a smaller batch is strictly under the memory envelope).
    cxb, cyb, cmb = cap_eval_batches((xb, yb, mb), 4)
    assert cxb.shape == (1, 4) and cyb.shape == (1, 4)
    np.testing.assert_allclose(np.asarray(cxb[0]), [0, 1, 2, 3])
    # None = untouched passthrough (reference behavior): same objects out.
    passthrough = cap_eval_batches((xb, yb, mb), None)
    assert passthrough[0] is xb and passthrough[1] is yb
    # Cap preserves the eval_batch_size scan granularity (memory envelope):
    # 8 of 12 samples at batch size 6 -> 2 batches of 6, mask-trimmed to 8.
    x2, y2, m2 = cap_eval_batches((xb, yb, mb), 8)
    assert x2.shape == (2, 6)
    np.testing.assert_allclose(np.asarray(m2).sum(), 8)

    base = dataclasses.replace(
        tiny_config, distributed_algorithm="GTG_shapley_value", round=2,
        round_trunc_threshold=0.0,
    )
    full = run_simulation(base, setup_logging=False)
    capped = run_simulation(
        dataclasses.replace(base, shapley_eval_samples=128),
        setup_logging=False,
    )
    sv_f = full["history"][0]["shapley_values"]
    sv_c = capped["history"][0]["shapley_values"]
    assert set(sv_c) == set(sv_f)
    for i in sv_f:
        assert np.isfinite(sv_c[i])
        assert abs(sv_c[i] - sv_f[i]) < 0.15, (i, sv_c[i], sv_f[i])


def test_gtg_convergence_is_distance_to_final(tiny_config):
    """Reference formula (GTG_shapley_value_server.py:82-91): each of the
    last_k running means is compared to the FINAL running mean, not to its
    successor. A running mean drifting steadily — small per-step change,
    large cumulative distance — must NOT converge (a diff-based test would
    stop sampling here; this input is where the two formulas disagree)."""
    from distributed_learning_simulator_tpu.algorithms.shapley import GTGShapley

    tiny_config.gtg_last_k = 10
    tiny_config.gtg_converge_criteria = 0.05
    algo = GTGShapley(tiny_config)

    # Build records whose running means are constant [3, 3] for the first
    # 31 samples, then drift down by 0.04 per sample for 10 samples.
    means = [np.array([3.0, 3.0])] * 31
    for step in range(1, 11):
        means.append(np.array([3.0 - 0.04 * step] * 2))
    records = []
    for t, m in enumerate(means, start=1):
        prev = means[t - 2] if t > 1 else np.zeros(2)
        records.append(t * m - (t - 1) * prev)
    running = np.cumsum(np.stack(records), 0) / np.arange(1, 42)[:, None]
    np.testing.assert_allclose(running, np.stack(means), rtol=1e-12)

    # Per-step relative change is ~0.0154 (< 0.05): a successive-diff test
    # would declare convergence...
    recent = running[-11:]
    per_step = np.mean(
        np.abs(np.diff(recent, axis=0)) / (np.abs(recent[-1]) + 1e-12), axis=1
    )
    assert per_step.max() < 0.05
    # ...but the distance of the oldest of the last 10 running means to the
    # final one is ~0.138 (> 0.05), so the reference keeps sampling.
    assert algo._converged(records, n=2) is False

    # Once the running mean actually flattens, it converges.
    flat = records + [means[-1]] * 15
    assert algo._converged(flat, n=2) is True


def test_gtg_convergence_respects_converge_min(tiny_config):
    """index <= max(30, n) never converges (GTG_shapley_value_server.py:15)."""
    from distributed_learning_simulator_tpu.algorithms.shapley import GTGShapley

    algo = GTGShapley(tiny_config)
    records = [np.ones(2)] * 30  # perfectly flat, but too few samples
    assert algo._converged(records, n=2) is False
    assert algo._converged(records + [np.ones(2)], n=2) is True


def test_subset_evaluator_oom_hint(tiny_config):
    """A device OOM inside the subset evaluator must re-raise with the
    actionable knobs (shapley_eval_chunk / shapley_eval_samples) named —
    the same sized-hint treatment the simulator's round-level OOMs get.
    Non-OOM runtime errors must pass through untouched."""
    import jax
    import numpy as np

    from distributed_learning_simulator_tpu.algorithms.shapley import (
        _SubsetEvaluator,
    )

    ev = _SubsetEvaluator(lambda *a: {"accuracy": 0.0}, chunk=8)
    masks = np.ones((4, 3), np.float32)
    batches = (np.zeros((2, 4, 2)), np.zeros((2, 4), np.int32),
               np.ones((2, 4)))

    def boom(*a, **k):
        raise jax.errors.JaxRuntimeError("RESOURCE_EXHAUSTED: out of memory")

    ev._eval_chunk = boom
    with pytest.raises(RuntimeError, match="shapley_eval_chunk"):
        ev(None, None, masks, None, batches)

    def other(*a, **k):
        raise jax.errors.JaxRuntimeError("INTERNAL: something else")

    ev._eval_chunk = other
    with pytest.raises(jax.errors.JaxRuntimeError, match="something else"):
        ev(None, None, masks, None, batches)


def test_subset_evaluator_oom_hint_minimal_chunk():
    """At an already-minimal chunk the hint must not suggest the same
    chunk back — it points at the eval-sample cap instead."""
    import jax
    import numpy as np

    from distributed_learning_simulator_tpu.algorithms.shapley import (
        _SubsetEvaluator,
    )

    ev = _SubsetEvaluator(lambda *a: {"accuracy": 0.0}, chunk=1)

    def boom(*a, **k):
        raise jax.errors.JaxRuntimeError("RESOURCE_EXHAUSTED: out of memory")

    ev._eval_chunk = boom
    masks = np.ones((2, 3), np.float32)
    batches = (np.zeros((1, 4, 2)), np.zeros((1, 4), np.int32),
               np.ones((1, 4)))
    with pytest.raises(RuntimeError, match="already minimal"):
        ev(None, None, masks, None, batches)


def _run_gtg(cfg, **overrides):
    import dataclasses

    from distributed_learning_simulator_tpu.simulator import run_simulation

    cfg = dataclasses.replace(
        cfg, distributed_algorithm="GTG_shapley_value", **overrides
    )
    return run_simulation(cfg, setup_logging=False)["history"]


def _sv_vec(h):
    sv = h["shapley_values"]
    return np.array([sv[i] for i in sorted(sv)])


def test_gtg_prefix_mode_equivalence(tiny_config):
    """gtg_prefix_mode is pure implementation: cumsum (one streamed
    weighted cumulative sum per permutation walk) and masked (per-prefix
    mask-weighted reductions, the oracle) draw identical permutations from
    the fixed seed and must produce IDENTICAL Shapley values, permutation
    counts, convergence flags and subset-eval counts on the f32
    exact-parity path — both aggregations compute the same real value to
    f32 rounding, and the utilities feed an argmax accuracy that absorbs
    last-ulp differences."""
    out = {
        mode: _run_gtg(
            tiny_config, round=2, round_trunc_threshold=0.0,
            shapley_eval_dtype="float32", gtg_prefix_mode=mode,
        )
        for mode in ("cumsum", "masked")
    }
    assert len(out["cumsum"]) == 2
    for h_c, h_m in zip(out["cumsum"], out["masked"]):
        np.testing.assert_array_equal(_sv_vec(h_c), _sv_vec(h_m))
        assert h_c["gtg_permutations"] == h_m["gtg_permutations"]
        assert h_c["gtg_subset_evals"] == h_m["gtg_subset_evals"]
        assert h_c["gtg_converged"] == h_m["gtg_converged"]


def test_gtg_truncated_walk_cumsum_matches_oracle(tiny_config):
    """Eps-truncation under cumsum mode: a truncated walk stops streaming
    its cumulative sum mid-permutation (later blocks are never computed,
    nothing is recomputed) and must still reproduce the masked oracle's
    values exactly. N=20 forces multi-block walks (block 16 + short final
    block 4), so the carried running sums, the group padding of a wave's
    last group, AND the mid-walk truncation slicing are all on the path."""
    import dataclasses

    base = dataclasses.replace(tiny_config, worker_number=20)
    runs = {
        mode: _run_gtg(
            base, round=1, round_trunc_threshold=0.0,
            shapley_eval_dtype="float32", gtg_eps=0.02, gtg_prefix_mode=mode,
        )
        for mode in ("cumsum", "masked")
    }
    h_c, h_m = runs["cumsum"][0], runs["masked"][0]
    np.testing.assert_array_equal(_sv_vec(h_c), _sv_vec(h_m))
    assert h_c["gtg_permutations"] == h_m["gtg_permutations"]
    assert h_c["gtg_subset_evals"] == h_m["gtg_subset_evals"]
    # Truncation must actually have engaged, or this test proves nothing:
    # gtg_eps=0 disables it (|ref - v| < 0 never holds), so the truncated
    # run must evaluate strictly fewer subsets.
    full = _run_gtg(
        base, round=1, round_trunc_threshold=0.0,
        shapley_eval_dtype="float32", gtg_eps=0.0,
        gtg_max_permutations=20, gtg_prefix_mode="cumsum",
    )[0]
    assert h_c["gtg_subset_evals"] < full["gtg_subset_evals"]


def test_shapley_eval_dtype_auto_resolution(tiny_config):
    """shapley_eval_dtype='auto' (the default) resolves per algorithm
    (ADVICE r5): f32 for exact multi-round Shapley — its documented
    exact-parity path has no Monte-Carlo noise to hide bf16 rounding in —
    bf16 for GTG, where the halved stack read is measured fidelity-free.
    An explicit value wins for both."""
    import dataclasses

    import jax.numpy as jnp

    from distributed_learning_simulator_tpu.algorithms.shapley import (
        GTGShapley,
        MultiRoundShapley,
    )

    assert tiny_config.shapley_eval_dtype == "auto"
    eval_fn = lambda *a: {"accuracy": 0.0}  # noqa: E731
    exact = MultiRoundShapley(tiny_config)
    exact.prepare(None, eval_fn)
    assert exact._evaluator.eval_dtype == jnp.float32
    gtg = GTGShapley(tiny_config)
    gtg.prepare(None, eval_fn)
    assert gtg._evaluator.eval_dtype == jnp.bfloat16
    forced = dataclasses.replace(tiny_config, shapley_eval_dtype="float32")
    gtg_f32 = GTGShapley(forced)
    gtg_f32.prepare(None, eval_fn)
    assert gtg_f32._evaluator.eval_dtype == jnp.float32


def test_gtg_trunc_ref_same_estimator_for_bf16(tiny_config, tmp_path):
    """With a non-f32 evaluator the eps-truncation reference must come
    from the SAME estimator's grand-coalition utility, not the f32 round
    metric (ADVICE r5) — bf16 rounding is ~eps-sized, so comparing across
    estimators would bias truncation. Observable: with gtg_eps huge every
    walk truncates at step 0, so the metric pickle holds exactly the
    subsets evaluated up front — {empty, grand} when the branch takes the
    evaluator's grand utility, {empty} when it (wrongly) reuses the round
    metric."""
    import dataclasses
    import glob
    import pickle

    from distributed_learning_simulator_tpu.simulator import run_simulation

    n = tiny_config.worker_number
    results = {}
    for dtype in ("bfloat16", "float32"):
        cfg = dataclasses.replace(
            tiny_config, distributed_algorithm="GTG_shapley_value", round=1,
            gtg_eps=10.0, shapley_eval_dtype=dtype,
            log_root=str(tmp_path / dtype),
        )
        run_simulation(cfg, setup_logging=True)
        (path,) = glob.glob(
            str(tmp_path / dtype / "**" / "metric_0.pkl"), recursive=True
        )
        with open(path, "rb") as f:
            results[dtype] = set(pickle.load(f))
    assert tuple(range(n)) in results["bfloat16"]
    # f32 with no eval-sample cap keeps the reference's round-metric
    # comparison — no extra grand-coalition evaluation happens.
    assert tuple(range(n)) not in results["float32"]
    assert () in results["float32"]


def test_gtg_prefix_mode_validation(tiny_config):
    import dataclasses

    with pytest.raises(ValueError, match="gtg_prefix_mode"):
        dataclasses.replace(tiny_config, gtg_prefix_mode="bogus").validate()


def test_prefix_wave_oom_hint_respects_block_floor():
    """The cumsum path's minimum call width is one prefix block (16
    models), so at the default chunk=16 an OOM must NOT suggest a smaller
    chunk — following that hint would dispatch the identical 16-model
    call and crash again. The hint points at the eval-sample cap instead."""
    import jax
    import jax.numpy as jnp

    from distributed_learning_simulator_tpu.algorithms.shapley import (
        _CumsumPrefixWalker,
        _SubsetEvaluator,
    )

    ev = _SubsetEvaluator(lambda *a: {"accuracy": 0.0}, chunk=16)

    def boom(*a, **k):
        raise jax.errors.JaxRuntimeError("RESOURCE_EXHAUSTED: out of memory")

    ev._prefix_wave = boom
    n = 20
    stack = {"w": jnp.zeros((n, 3), jnp.float32)}
    batches = (jnp.zeros((2, 4, 2)), jnp.zeros((2, 4), jnp.int32),
               jnp.ones((2, 4)))
    walker = _CumsumPrefixWalker(
        ev, stack, jnp.ones((n,)), {"w": jnp.zeros((3,))}, batches, n,
    )
    walker.reset()
    perms = [list(range(n))] * n
    with pytest.raises(RuntimeError, match="already minimal"):
        walker.eval_block(perms, list(range(n)), 0, 16, {})
