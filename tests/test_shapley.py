"""Exact Shapley on toy games with known values (SURVEY §4 test strategy)."""

from itertools import combinations

import numpy as np
import pytest

from distributed_learning_simulator_tpu.algorithms.shapley import (
    shapley_from_utilities,
)


def _all_subsets(n):
    ids = list(range(n))
    for size in range(n + 1):
        for combo in combinations(ids, size):
            yield frozenset(combo)


def test_additive_game():
    """u(S) = sum of member values -> SV_i = value_i exactly."""
    values = np.array([1.0, 2.0, 3.0, 4.0])
    utilities = {s: float(sum(values[i] for i in s)) for s in _all_subsets(4)}
    sv = shapley_from_utilities(utilities, 4)
    np.testing.assert_allclose(sv, values, rtol=1e-9)


def test_glove_game():
    """Classic 3-player glove game: players {0,1} hold left gloves, {2} right;
    u(S)=1 iff S contains a left and the right. Known SVs: (1/6, 1/6, 2/3)."""
    def u(s):
        return 1.0 if (2 in s and (0 in s or 1 in s)) else 0.0

    utilities = {s: u(s) for s in _all_subsets(3)}
    sv = shapley_from_utilities(utilities, 3)
    np.testing.assert_allclose(sv, [1 / 6, 1 / 6, 2 / 3], rtol=1e-9)


def test_efficiency_property():
    """sum(SV) == u(grand coalition) - u(empty) for any game."""
    rng = np.random.default_rng(0)
    utilities = {s: float(rng.normal()) for s in _all_subsets(5)}
    sv = shapley_from_utilities(utilities, 5)
    np.testing.assert_allclose(
        sv.sum(),
        utilities[frozenset(range(5))] - utilities[frozenset()],
        rtol=1e-9,
    )


def test_symmetry_property():
    """Symmetric players get identical SVs."""
    utilities = {s: float(len(s) ** 2) for s in _all_subsets(4)}
    sv = shapley_from_utilities(utilities, 4)
    np.testing.assert_allclose(sv, sv[0])


def test_exact_refuses_large_n(tiny_config):
    from distributed_learning_simulator_tpu.algorithms.shapley import (
        MultiRoundShapley,
    )
    from distributed_learning_simulator_tpu.algorithms.base import RoundContext

    tiny_config.worker_number = 17
    algo = MultiRoundShapley(tiny_config)
    ctx = RoundContext(
        round_idx=0, global_params=None, prev_global_params=None,
        sizes=np.ones(17), aux={}, metrics={"accuracy": 0.5},
        prev_metrics=None, eval_batches=(), log_dir=None,
    )
    with pytest.raises(ValueError, match="2\\^N"):
        algo.post_round(ctx)
