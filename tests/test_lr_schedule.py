"""Round-level lr schedules (config.lr_schedule; exceeds the reference,
whose lr is fixed for the whole run — simulator.sh:1).

The factor multiplies the final optax update inside the jitted round
program, which is exactly equivalent to rebuilding the optimizer with
lr * factor (lr sits outside the sgd momentum buffer and outside adam's
normalization) — so a schedule that stays at factor 1.0 must be
bit-identical to the constant run, and a factor-0 tail must freeze the
model.
"""

import dataclasses

import numpy as np
import pytest

from distributed_learning_simulator_tpu.config import ExperimentConfig
from distributed_learning_simulator_tpu.simulator import (
    _lr_factor,
    run_simulation,
)


def test_lr_factor_values():
    cfg = ExperimentConfig(
        lr_schedule="cosine", round=11, lr_min_factor=0.1
    )
    assert _lr_factor(cfg, 0) == pytest.approx(1.0)
    assert _lr_factor(cfg, 10) == pytest.approx(0.1)
    assert _lr_factor(cfg, 5) == pytest.approx(0.55)  # midpoint
    # Horizon override + clamp past the horizon.
    cfg2 = dataclasses.replace(cfg, lr_schedule_rounds=6)
    assert _lr_factor(cfg2, 5) == pytest.approx(0.1)
    assert _lr_factor(cfg2, 9) == pytest.approx(0.1)
    step = ExperimentConfig(
        lr_schedule="step", lr_step_size=3, lr_step_gamma=0.5
    )
    assert [_lr_factor(step, r) for r in (0, 2, 3, 6)] == [
        1.0, 1.0, 0.5, 0.25,
    ]


def test_unit_factor_schedule_is_bit_identical(tiny_config):
    """step with step_size > rounds keeps factor 1.0 throughout — must be
    bit-identical to the constant-schedule run (the scale multiply is the
    only code-path difference)."""
    base = run_simulation(tiny_config, setup_logging=False)
    cfg = dataclasses.replace(
        tiny_config, lr_schedule="step", lr_step_size=100
    )
    sched = run_simulation(cfg, setup_logging=False)
    for a, b in zip(base["history"], sched["history"]):
        assert a["test_accuracy"] == b["test_accuracy"]
        assert a["test_loss"] == b["test_loss"]
    assert sched["history"][-1]["lr_factor"] == 1.0


def test_zero_factor_tail_freezes_model(tiny_config):
    """step with gamma=0 after round lr_step_size: later rounds train with
    lr 0, so the global model — and the test metrics — stop moving."""
    cfg = dataclasses.replace(
        tiny_config, round=5, lr_schedule="step", lr_step_size=2,
        lr_step_gamma=0.0,
    )
    res = run_simulation(cfg, setup_logging=False)
    accs = [h["test_accuracy"] for h in res["history"]]
    losses = [h["test_loss"] for h in res["history"]]
    # Rounds 2..4 run at factor 0 -> metrics frozen at the round-1 value.
    assert accs[2] == accs[3] == accs[4]
    assert losses[2] == losses[3] == losses[4]
    # And the schedule actually trained before the freeze.
    assert losses[1] < losses[0] + 1e-9
    assert res["history"][0]["lr_factor"] == 1.0
    assert res["history"][4]["lr_factor"] == 0.0


def test_cosine_schedule_learns(tiny_config):
    cfg = dataclasses.replace(
        tiny_config, round=6, lr_schedule="cosine", lr_min_factor=0.05
    )
    res = run_simulation(cfg, setup_logging=False)
    accs = [h["test_accuracy"] for h in res["history"]]
    assert accs[-1] > accs[0]
    factors = [h["lr_factor"] for h in res["history"]]
    assert factors[0] == pytest.approx(1.0)
    assert factors[-1] == pytest.approx(0.05)
    assert all(a >= b for a, b in zip(factors, factors[1:]))  # monotone


def test_schedule_rejections(tiny_config):
    with pytest.raises(ValueError, match="lr_schedule"):
        dataclasses.replace(tiny_config, lr_schedule="poly").validate()
    with pytest.raises(ValueError, match="sign_SGD"):
        dataclasses.replace(
            tiny_config, distributed_algorithm="sign_SGD",
            lr_schedule="cosine",
        ).validate()
    from distributed_learning_simulator_tpu.execution.threaded import (
        run_threaded_simulation,
    )

    with pytest.raises(ValueError, match="lr_schedule"):
        run_threaded_simulation(
            dataclasses.replace(tiny_config, lr_schedule="cosine")
        )


def test_schedule_requires_algorithm_capability(tiny_config, monkeypatch):
    """The capability lives on the Algorithm class: an algorithm whose
    round program lacks the lr_scale operand fails with the cause, not an
    arity TypeError at first dispatch."""
    from distributed_learning_simulator_tpu.algorithms.fedavg import FedAvg

    monkeypatch.setattr(FedAvg, "supports_lr_schedule", False)
    cfg = dataclasses.replace(tiny_config, lr_schedule="cosine")
    with pytest.raises(ValueError, match="lr_scale operand"):
        run_simulation(cfg, setup_logging=False)


def test_resume_rejects_model_structure_mismatch(tiny_config, tmp_path):
    """A checkpoint written with a different model (or model layout
    version) must fail at resume with the cause, not mid-apply."""
    cfg = dataclasses.replace(
        tiny_config, checkpoint_dir=str(tmp_path), checkpoint_every=1,
    )
    run_simulation(cfg, setup_logging=False)
    other = dataclasses.replace(cfg, model_name="cnn_tpu", resume=True)
    with pytest.raises(ValueError, match="parameter structure"):
        run_simulation(other, setup_logging=False)


def test_schedule_composes_with_bf16_and_chunking(tiny_config):
    """The scale multiply sits inside the SR store path too."""
    cfg = dataclasses.replace(
        tiny_config, round=4, lr_schedule="cosine",
        local_compute_dtype="bfloat16", client_chunk_size=2,
    )
    res = run_simulation(cfg, setup_logging=False)
    assert np.isfinite(res["history"][-1]["test_loss"])
    assert res["history"][-1]["lr_factor"] < 1.0
