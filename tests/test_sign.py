"""Sign-vote on crafted gradients (SURVEY §4 test strategy)."""

import jax.numpy as jnp
import numpy as np

from distributed_learning_simulator_tpu.ops.sign import majority_vote, sign_compress


def test_sign_compress_matches_torch_sign_convention():
    tree = {"w": jnp.asarray([-2.0, 0.0, 3.0])}
    out = sign_compress(tree)
    np.testing.assert_array_equal(np.asarray(out["w"]), [-1.0, 0.0, 1.0])


def test_majority_vote_crafted():
    # 3 clients, elementwise: [+,+,-] -> +, [-,-,+] -> -, [+,-,0] -> 0
    signs = jnp.asarray(
        [
            [1.0, -1.0, 1.0],
            [1.0, -1.0, -1.0],
            [-1.0, 1.0, 0.0],
        ]
    )
    out = majority_vote({"g": signs})
    np.testing.assert_array_equal(np.asarray(out["g"]), [1.0, -1.0, 0.0])


def test_majority_vote_tie_is_zero():
    signs = jnp.asarray([[1.0], [-1.0]])
    out = majority_vote({"g": signs})
    np.testing.assert_array_equal(np.asarray(out["g"]), [0.0])
