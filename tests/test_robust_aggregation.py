"""Byzantine-robust aggregation (median / trimmed mean) — ops + end-to-end.

Extension beyond the reference, motivated by its own poisoning experiment
(reference simulator_backup.py:71-77 swaps worker 0's data): the reference
can inject a poisoned client but only aggregate with a weighted mean.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_learning_simulator_tpu.ops.aggregate import (
    coordinate_median,
    krum,
    trimmed_mean,
    weighted_mean,
)
from distributed_learning_simulator_tpu.simulator import run_simulation


def _stack_with_outlier():
    """9 honest clients near 1.0, one adversarial client at 1000."""
    honest = np.random.default_rng(0).normal(1.0, 0.01, size=(9, 4, 3))
    evil = np.full((1, 4, 3), 1000.0)
    return {"w": jnp.asarray(np.concatenate([honest, evil]), jnp.float32)}


def test_median_ignores_outlier():
    stacked = _stack_with_outlier()
    med = coordinate_median(stacked)["w"]
    mean = weighted_mean(stacked, np.ones(10))["w"]
    assert np.abs(np.asarray(med) - 1.0).max() < 0.05
    assert np.asarray(mean).min() > 50.0  # the mean is wrecked


def test_trimmed_mean_ignores_outlier():
    stacked = _stack_with_outlier()
    out = trimmed_mean(stacked, 0.1)["w"]  # k=1: drops the outlier
    assert np.abs(np.asarray(out) - 1.0).max() < 0.05


def test_trimmed_mean_matches_numpy():
    x = np.random.default_rng(1).normal(size=(10, 5)).astype(np.float32)
    out = np.asarray(trimmed_mean({"w": jnp.asarray(x)}, 0.2)["w"])
    s = np.sort(x, axis=0)
    np.testing.assert_allclose(out, s[2:-2].mean(axis=0), rtol=1e-5)


def test_median_survives_nan_upload():
    """A client whose local training diverged to NaN (the strongest form of
    poisoning) must not poison the median aggregate."""
    honest = np.random.default_rng(2).normal(1.0, 0.01, size=(4, 3))
    stack = {"w": jnp.asarray(
        np.concatenate([honest, np.full((1, 3), np.nan)]), jnp.float32
    )}
    out = np.asarray(coordinate_median(stack)["w"])
    assert np.all(np.isfinite(out))
    assert np.abs(out - 1.0).max() < 0.05


def test_trimmed_mean_rejects_full_trim():
    with pytest.raises(ValueError, match="removes all"):
        trimmed_mean({"w": jnp.zeros((4, 2))}, 0.5)


def test_krum_picks_honest_client():
    stacked = _stack_with_outlier()
    out = np.asarray(krum(stacked, n_byzantine=1)["w"])
    assert np.abs(out - 1.0).max() < 0.05  # one of the honest clients


def test_krum_survives_nan_upload():
    honest = np.random.default_rng(3).normal(1.0, 0.01, size=(4, 3))
    stack = {"w": jnp.asarray(
        np.concatenate([honest, np.full((1, 3), np.nan)]), jnp.float32
    )}
    out = np.asarray(krum(stack, n_byzantine=1)["w"])
    assert np.all(np.isfinite(out))
    assert np.abs(out - 1.0).max() < 0.05


def test_end_to_end_krum(tiny_config):
    res = run_simulation(
        dataclasses.replace(tiny_config, round=3, aggregation="krum"),
        setup_logging=False,
    )
    accs = [h["test_accuracy"] for h in res["history"]]
    assert all(np.isfinite(h["test_loss"]) for h in res["history"])
    assert accs[-1] > 0.15  # a single client's params still learn


def test_end_to_end_median(tiny_config):
    res = run_simulation(
        dataclasses.replace(tiny_config, round=4, aggregation="median"),
        setup_logging=False,
    )
    accs = [h["test_accuracy"] for h in res["history"]]
    assert accs[-1] > 0.25  # learns (median of IID clients ~ mean)


def test_shapley_rejects_robust_aggregation(tiny_config):
    with pytest.raises(ValueError, match="aggregation"):
        run_simulation(
            dataclasses.replace(
                tiny_config, round=1,
                distributed_algorithm="multiround_shapley_value",
                aggregation="median",
            ),
            setup_logging=False,
        )
