"""Byzantine-robust aggregation (median / trimmed mean) — ops + end-to-end.

Extension beyond the reference, motivated by its own poisoning experiment
(reference simulator_backup.py:71-77 swaps worker 0's data): the reference
can inject a poisoned client but only aggregate with a weighted mean.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_learning_simulator_tpu.ops.aggregate import (
    coordinate_median,
    krum,
    trimmed_mean,
    weighted_mean,
)
from distributed_learning_simulator_tpu.simulator import run_simulation


def _stack_with_outlier():
    """9 honest clients near 1.0, one adversarial client at 1000."""
    honest = np.random.default_rng(0).normal(1.0, 0.01, size=(9, 4, 3))
    evil = np.full((1, 4, 3), 1000.0)
    return {"w": jnp.asarray(np.concatenate([honest, evil]), jnp.float32)}


def test_median_ignores_outlier():
    stacked = _stack_with_outlier()
    med = coordinate_median(stacked)["w"]
    mean = weighted_mean(stacked, np.ones(10))["w"]
    assert np.abs(np.asarray(med) - 1.0).max() < 0.05
    assert np.asarray(mean).min() > 50.0  # the mean is wrecked


def test_trimmed_mean_ignores_outlier():
    stacked = _stack_with_outlier()
    out = trimmed_mean(stacked, 0.1)["w"]  # k=1: drops the outlier
    assert np.abs(np.asarray(out) - 1.0).max() < 0.05


def test_trimmed_mean_matches_numpy():
    x = np.random.default_rng(1).normal(size=(10, 5)).astype(np.float32)
    out = np.asarray(trimmed_mean({"w": jnp.asarray(x)}, 0.2)["w"])
    s = np.sort(x, axis=0)
    np.testing.assert_allclose(out, s[2:-2].mean(axis=0), rtol=1e-5)


def test_median_survives_nan_upload():
    """A client whose local training diverged to NaN (the strongest form of
    poisoning) must not poison the median aggregate."""
    honest = np.random.default_rng(2).normal(1.0, 0.01, size=(4, 3))
    stack = {"w": jnp.asarray(
        np.concatenate([honest, np.full((1, 3), np.nan)]), jnp.float32
    )}
    out = np.asarray(coordinate_median(stack)["w"])
    assert np.all(np.isfinite(out))
    assert np.abs(out - 1.0).max() < 0.05


def test_trimmed_mean_rejects_full_trim():
    with pytest.raises(ValueError, match="removes all"):
        trimmed_mean({"w": jnp.zeros((4, 2))}, 0.5)


def test_krum_picks_honest_client():
    stacked = _stack_with_outlier()
    out = np.asarray(krum(stacked, n_byzantine=1)["w"])
    assert np.abs(out - 1.0).max() < 0.05  # one of the honest clients


def test_krum_survives_nan_upload():
    honest = np.random.default_rng(3).normal(1.0, 0.01, size=(4, 3))
    stack = {"w": jnp.asarray(
        np.concatenate([honest, np.full((1, 3), np.nan)]), jnp.float32
    )}
    out = np.asarray(krum(stack, n_byzantine=1)["w"])
    assert np.all(np.isfinite(out))
    assert np.abs(out - 1.0).max() < 0.05


def test_krum_excludes_zero_weight_clients():
    """Empty-shard clients return the broadcast params bit-identical; two of
    them must not win krum with pairwise distance 0 (frozen model bug)."""
    honest = np.random.default_rng(4).normal(1.0, 0.01, size=(3, 3))
    stale = np.zeros((3, 3))  # three identical zero-sample uploads
    stack = {"w": jnp.asarray(np.concatenate([honest, stale]), jnp.float32)}
    weights = np.array([10.0, 10.0, 10.0, 0.0, 0.0, 0.0])
    out = np.asarray(krum(stack, n_byzantine=0, weights=weights)["w"])
    assert np.abs(out - 1.0).max() < 0.05  # an honest client, not the stale 0s


def test_krum_survives_many_nan_uploads():
    """More NaN uploads than the assumed f must still never be selected."""
    honest = np.random.default_rng(5).normal(1.0, 0.01, size=(4, 3))
    nans = np.full((3, 3), np.nan)
    stack = {"w": jnp.asarray(np.concatenate([honest, nans]), jnp.float32)}
    out = np.asarray(krum(stack, n_byzantine=0)["w"])
    assert np.all(np.isfinite(out))
    assert np.abs(out - 1.0).max() < 0.05


def test_all_diverged_cohort_keeps_previous_model(tiny_config):
    """If every client uploads NaN in the same round, robust rules keep the
    previous global model instead of a NaN aggregate (jit-level check via
    the round function)."""
    import jax

    from distributed_learning_simulator_tpu.factory import get_algorithm
    from distributed_learning_simulator_tpu.parallel.engine import (
        make_eval_fn,
        make_optimizer,
    )

    cfg = dataclasses.replace(
        tiny_config, aggregation="median", learning_rate=1e30,  # diverges
        n_train=128, worker_number=4, batch_size=16,
    )
    from distributed_learning_simulator_tpu.data.registry import get_dataset
    from distributed_learning_simulator_tpu.models.registry import (
        get_model,
        init_params,
    )
    from distributed_learning_simulator_tpu.simulator import build_client_data

    ds = get_dataset("synthetic", n_train=128, n_test=64, seed=0)
    cd = build_client_data(cfg, ds)
    model = get_model("mlp", num_classes=ds.num_classes)
    gp = init_params(model, ds.x_train[:1], seed=0)
    opt = make_optimizer("sgd", cfg.learning_rate)
    algo = get_algorithm("fed", cfg)
    algo.prepare(model.apply, make_eval_fn(model.apply))
    round_fn = algo.make_round_fn(model.apply, opt, cd.n_clients)
    import jax.numpy as _jnp

    new_global, _, _ = jax.jit(round_fn)(
        gp, None, _jnp.asarray(cd.x), _jnp.asarray(cd.y),
        _jnp.asarray(cd.mask), _jnp.asarray(cd.sizes), jax.random.key(0),
    )
    for got, prev in zip(jax.tree_util.tree_leaves(new_global),
                         jax.tree_util.tree_leaves(gp)):
        # every client NaN'd out (lr=1e30), so the fallback must return the
        # previous global model bit-exactly
        np.testing.assert_array_equal(np.asarray(got), np.asarray(prev))


def test_krum_infeasible_config_fails_fast(tiny_config):
    import pytest as _pytest

    with _pytest.raises(ValueError, match="2f \\+ 3"):
        dataclasses.replace(
            tiny_config, aggregation="krum", worker_number=5, trim_ratio=0.4
        ).validate()


def test_end_to_end_krum(tiny_config):
    res = run_simulation(
        dataclasses.replace(tiny_config, round=3, aggregation="krum"),
        setup_logging=False,
    )
    accs = [h["test_accuracy"] for h in res["history"]]
    assert all(np.isfinite(h["test_loss"]) for h in res["history"])
    assert accs[-1] > 0.15  # a single client's params still learn


def test_end_to_end_median(tiny_config):
    res = run_simulation(
        dataclasses.replace(tiny_config, round=4, aggregation="median"),
        setup_logging=False,
    )
    accs = [h["test_accuracy"] for h in res["history"]]
    assert accs[-1] > 0.25  # learns (median of IID clients ~ mean)


def test_shapley_rejects_robust_aggregation(tiny_config):
    with pytest.raises(ValueError, match="aggregation"):
        run_simulation(
            dataclasses.replace(
                tiny_config, round=1,
                distributed_algorithm="multiround_shapley_value",
                aggregation="median",
            ),
            setup_logging=False,
        )
