"""Byzantine-robust aggregation (median / trimmed mean) — ops + end-to-end.

Extension beyond the reference, motivated by its own poisoning experiment
(reference simulator_backup.py:71-77 swaps worker 0's data): the reference
can inject a poisoned client but only aggregate with a weighted mean.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_learning_simulator_tpu.ops.aggregate import (
    coordinate_median,
    krum,
    trimmed_mean,
    weighted_mean,
)
from distributed_learning_simulator_tpu.simulator import run_simulation


def _stack_with_outlier():
    """9 honest clients near 1.0, one adversarial client at 1000."""
    honest = np.random.default_rng(0).normal(1.0, 0.01, size=(9, 4, 3))
    evil = np.full((1, 4, 3), 1000.0)
    return {"w": jnp.asarray(np.concatenate([honest, evil]), jnp.float32)}


def test_median_ignores_outlier():
    stacked = _stack_with_outlier()
    med = coordinate_median(stacked)["w"]
    mean = weighted_mean(stacked, np.ones(10))["w"]
    assert np.abs(np.asarray(med) - 1.0).max() < 0.05
    assert np.asarray(mean).min() > 50.0  # the mean is wrecked


def test_trimmed_mean_ignores_outlier():
    stacked = _stack_with_outlier()
    out = trimmed_mean(stacked, 0.1)["w"]  # k=1: drops the outlier
    assert np.abs(np.asarray(out) - 1.0).max() < 0.05


def test_trimmed_mean_matches_numpy():
    x = np.random.default_rng(1).normal(size=(10, 5)).astype(np.float32)
    out = np.asarray(trimmed_mean({"w": jnp.asarray(x)}, 0.2)["w"])
    s = np.sort(x, axis=0)
    np.testing.assert_allclose(out, s[2:-2].mean(axis=0), rtol=1e-5)


def test_median_survives_nan_upload():
    """A client whose local training diverged to NaN (the strongest form of
    poisoning) must not poison the median aggregate."""
    honest = np.random.default_rng(2).normal(1.0, 0.01, size=(4, 3))
    stack = {"w": jnp.asarray(
        np.concatenate([honest, np.full((1, 3), np.nan)]), jnp.float32
    )}
    out = np.asarray(coordinate_median(stack)["w"])
    assert np.all(np.isfinite(out))
    assert np.abs(out - 1.0).max() < 0.05


def test_trimmed_mean_rejects_full_trim():
    with pytest.raises(ValueError, match="removes all"):
        trimmed_mean({"w": jnp.zeros((4, 2))}, 0.5)


def test_krum_picks_honest_client():
    stacked = _stack_with_outlier()
    out = np.asarray(krum(stacked, n_byzantine=1)["w"])
    assert np.abs(out - 1.0).max() < 0.05  # one of the honest clients


def test_krum_survives_nan_upload():
    honest = np.random.default_rng(3).normal(1.0, 0.01, size=(4, 3))
    stack = {"w": jnp.asarray(
        np.concatenate([honest, np.full((1, 3), np.nan)]), jnp.float32
    )}
    out = np.asarray(krum(stack, n_byzantine=1)["w"])
    assert np.all(np.isfinite(out))
    assert np.abs(out - 1.0).max() < 0.05


def test_krum_excludes_zero_weight_clients():
    """Empty-shard clients return the broadcast params bit-identical; two of
    them must not win krum with pairwise distance 0 (frozen model bug)."""
    honest = np.random.default_rng(4).normal(1.0, 0.01, size=(3, 3))
    stale = np.zeros((3, 3))  # three identical zero-sample uploads
    stack = {"w": jnp.asarray(np.concatenate([honest, stale]), jnp.float32)}
    weights = np.array([10.0, 10.0, 10.0, 0.0, 0.0, 0.0])
    out = np.asarray(krum(stack, n_byzantine=0, weights=weights)["w"])
    assert np.abs(out - 1.0).max() < 0.05  # an honest client, not the stale 0s


def test_krum_survives_many_nan_uploads():
    """More NaN uploads than the assumed f must still never be selected."""
    honest = np.random.default_rng(5).normal(1.0, 0.01, size=(4, 3))
    nans = np.full((3, 3), np.nan)
    stack = {"w": jnp.asarray(np.concatenate([honest, nans]), jnp.float32)}
    out = np.asarray(krum(stack, n_byzantine=0)["w"])
    assert np.all(np.isfinite(out))
    assert np.abs(out - 1.0).max() < 0.05


def test_all_diverged_cohort_keeps_previous_model(tiny_config):
    """If every client uploads NaN in the same round, robust rules keep the
    previous global model instead of a NaN aggregate (jit-level check via
    the round function)."""
    import jax

    from distributed_learning_simulator_tpu.factory import get_algorithm
    from distributed_learning_simulator_tpu.parallel.engine import (
        make_eval_fn,
        make_optimizer,
    )

    cfg = dataclasses.replace(
        tiny_config, aggregation="median", learning_rate=1e30,  # diverges
        n_train=128, worker_number=4, batch_size=16,
    )
    from distributed_learning_simulator_tpu.data.registry import get_dataset
    from distributed_learning_simulator_tpu.models.registry import (
        get_model,
        init_params,
    )
    from distributed_learning_simulator_tpu.simulator import build_client_data

    ds = get_dataset("synthetic", n_train=128, n_test=64, seed=0)
    cd = build_client_data(cfg, ds)
    model = get_model("mlp", num_classes=ds.num_classes)
    gp = init_params(model, ds.x_train[:1], seed=0)
    opt = make_optimizer("sgd", cfg.learning_rate)
    algo = get_algorithm("fed", cfg)
    algo.prepare(model.apply, make_eval_fn(model.apply))
    round_fn = algo.make_round_fn(model.apply, opt, cd.n_clients)
    import jax.numpy as _jnp

    new_global, _, _ = jax.jit(round_fn)(
        gp, None, _jnp.asarray(cd.x), _jnp.asarray(cd.y),
        _jnp.asarray(cd.mask), _jnp.asarray(cd.sizes), jax.random.key(0),
    )
    for got, prev in zip(jax.tree_util.tree_leaves(new_global),
                         jax.tree_util.tree_leaves(gp)):
        # every client NaN'd out (lr=1e30), so the fallback must return the
        # previous global model bit-exactly
        np.testing.assert_array_equal(np.asarray(got), np.asarray(prev))


def test_krum_infeasible_config_fails_fast(tiny_config):
    import pytest as _pytest

    with _pytest.raises(ValueError, match="2f \\+ 3"):
        dataclasses.replace(
            tiny_config, aggregation="krum", worker_number=5, trim_ratio=0.4
        ).validate()


def test_end_to_end_krum(tiny_config):
    res = run_simulation(
        dataclasses.replace(tiny_config, round=3, aggregation="krum"),
        setup_logging=False,
    )
    accs = [h["test_accuracy"] for h in res["history"]]
    assert all(np.isfinite(h["test_loss"]) for h in res["history"])
    assert accs[-1] > 0.15  # a single client's params still learn


def test_end_to_end_median(tiny_config):
    res = run_simulation(
        dataclasses.replace(tiny_config, round=4, aggregation="median"),
        setup_logging=False,
    )
    accs = [h["test_accuracy"] for h in res["history"]]
    assert accs[-1] > 0.25  # learns (median of IID clients ~ mean)


def test_shapley_rejects_robust_aggregation(tiny_config):
    with pytest.raises(ValueError, match="aggregation"):
        run_simulation(
            dataclasses.replace(
                tiny_config, round=1,
                distributed_algorithm="multiround_shapley_value",
                aggregation="median",
            ),
            setup_logging=False,
        )


def test_median_excludes_zero_weight_clients():
    """Empty-shard clients (weight 0) return the broadcast params
    bit-identical; a majority of them must not vote the median back to the
    previous model (ADVICE r1 #3)."""
    honest = np.random.default_rng(6).normal(1.0, 0.01, size=(3, 4))
    stale = np.zeros((5, 4))  # five zero-sample copies of the broadcast
    stack = {"w": jnp.asarray(np.concatenate([honest, stale]), jnp.float32)}
    weights = np.array([10.0, 10.0, 10.0, 0, 0, 0, 0, 0])
    out = np.asarray(coordinate_median(stack, weights=weights)["w"])
    assert np.abs(out - 1.0).max() < 0.05  # honest median, not the stale 0s
    # Unweighted call keeps the old behavior (stale majority wins).
    out_u = np.asarray(coordinate_median(stack)["w"])
    assert np.abs(out_u).max() < 0.05


def test_trimmed_mean_excludes_zero_weight_clients():
    honest = np.random.default_rng(7).normal(1.0, 0.01, size=(5, 4))
    stale = np.zeros((5, 4))
    stack = {"w": jnp.asarray(np.concatenate([honest, stale]), jnp.float32)}
    weights = np.concatenate([np.full(5, 10.0), np.zeros(5)])
    out = np.asarray(trimmed_mean(stack, 0.2, weights=weights)["w"])
    # k = floor(0.2*5) = 1: mean of the middle 3 honest clients.
    s = np.sort(honest, axis=0)
    np.testing.assert_allclose(out, s[1:-1].mean(axis=0), rtol=1e-5)


def test_trimmed_mean_weighted_matches_unweighted_when_all_valid():
    x = np.random.default_rng(8).normal(size=(10, 6)).astype(np.float32)
    stack = {"w": jnp.asarray(x)}
    out_u = np.asarray(trimmed_mean(stack, 0.2)["w"])
    out_w = np.asarray(trimmed_mean(stack, 0.2, weights=np.ones(10))["w"])
    np.testing.assert_allclose(out_w, out_u, rtol=1e-5)


def test_weighted_robust_rules_all_zero_cohort_stall():
    """All-zero-weight cohort: every row is the identical broadcast model,
    and the masked statistic must degrade to exactly that model (the
    correct stall), not zeros or NaN."""
    bcast = np.full((6, 4), 0.7, np.float32)
    stack = {"w": jnp.asarray(bcast)}
    weights = np.zeros(6)
    med = np.asarray(coordinate_median(stack, weights=weights)["w"])
    tm = np.asarray(trimmed_mean(stack, 0.1, weights=weights)["w"])
    np.testing.assert_allclose(med, 0.7, rtol=1e-6)
    np.testing.assert_allclose(tm, 0.7, rtol=1e-6)


def test_trimmed_mean_weighted_nan_poison_propagates_when_k_zero_effective():
    """With more NaN uploads than k among the valid clients, the statistic
    goes NaN (round-level fallback then keeps the previous model)."""
    honest = np.random.default_rng(9).normal(1.0, 0.01, size=(4, 3))
    poison = np.full((2, 3), np.nan)
    stack = {"w": jnp.asarray(np.concatenate([honest, poison]), jnp.float32)}
    weights = np.ones(6)  # k = floor(0.1*6) = 0 < 2 NaN rows
    out = np.asarray(trimmed_mean(stack, 0.1, weights=weights)["w"])
    assert np.isnan(out).all()


def test_trimmed_mean_infeasible_config_fails_fast(tiny_config):
    """k = floor(trim_ratio * cohort) == 0 is a plain mean with zero
    robustness; validate() must reject it (ADVICE r1 #1)."""
    with pytest.raises(ValueError, match="trim_ratio \\* cohort"):
        dataclasses.replace(
            tiny_config, aggregation="trimmed_mean", worker_number=8,
            trim_ratio=0.1,
        ).validate()
    # Feasible once the cohort is large enough for one trim.
    dataclasses.replace(
        tiny_config, aggregation="trimmed_mean", worker_number=10,
        trim_ratio=0.1,
    ).validate()


def test_threaded_robust_fallback_matches_vmap(tiny_config):
    """ThreadedServer must apply the same finite-or-previous-model guard as
    the vmap round (ADVICE r1 #2): an all-diverged cohort keeps the
    previous global model."""
    from distributed_learning_simulator_tpu.execution.threaded import (
        ThreadedServer,
    )

    cfg = dataclasses.replace(tiny_config, worker_number=2,
                              aggregation="median")
    prev = {"w": jnp.asarray(np.full((3,), 0.5, np.float32))}
    server = ThreadedServer(
        cfg, lambda p, *b: {"accuracy": 0.0, "loss": 0.0}, (), prev
    )
    try:
        nan_params = {"w": np.full((3,), np.nan, np.float32)}
        server._process_worker_data((0, 1.0, nan_params), None)
        server._process_worker_data((1, 1.0, nan_params), None)
        np.testing.assert_array_equal(
            np.asarray(server.prev_model["w"]), np.asarray(prev["w"])
        )
    finally:
        server.stop()


def test_trim_count_consistent_across_paths():
    """The weighted (traced) and unweighted (static) trimmed-mean paths and
    config validation must trim the SAME k for the same ratio — float32 vs
    float64 representation of the ratio must never split them (e.g.
    0.29 * 100 floors differently in f32 and f64)."""
    import jax.numpy as jnp
    import numpy as np

    from distributed_learning_simulator_tpu.ops.aggregate import (
        trim_count,
        trimmed_mean,
    )

    for ratio, m in [(0.29, 100), (0.42, 150), (0.1, 8), (0.25, 12),
                     (0.3333, 9)]:
        k_static = trim_count(m, ratio)
        k_traced = int(trim_count(jnp.asarray(m, jnp.int32), ratio))
        assert k_static == k_traced, (ratio, m, k_static, k_traced)

    # end-to-end: a stack where one extra trimmed client changes the result
    rng = np.random.default_rng(0)
    stack = {"w": jnp.asarray(rng.normal(size=(100, 7)), jnp.float32)}
    ones = jnp.ones(100)
    a = trimmed_mean(stack, 0.29)
    b = trimmed_mean(stack, 0.29, weights=ones)
    np.testing.assert_allclose(
        np.asarray(a["w"]), np.asarray(b["w"]), atol=1e-5
    )
