"""Small utility modules: tracing no-ops, multihost init, payload math."""

import numpy as np

from distributed_learning_simulator_tpu.utils.tracing import (
    annotate,
    profile_session,
)


def test_profile_session_noop_and_annotate():
    with profile_session(None):
        with annotate("test_region"):
            x = np.arange(4).sum()
    assert x == 6


def test_profile_session_writes_trace(tmp_path):
    import jax.numpy as jnp

    with profile_session(str(tmp_path / "trace")):
        _ = jnp.ones(8).sum()
    assert (tmp_path / "trace").exists()


def test_multihost_initialize_single_process():
    """On a single process, initialize is a no-op that reports devices."""
    from distributed_learning_simulator_tpu.parallel.multihost import (
        initialize_multihost,
    )

    n = initialize_multihost()
    assert n >= 1


def test_oom_hint_rewrites_device_oom():
    import jax
    import jax.numpy as jnp
    import pytest

    from distributed_learning_simulator_tpu.config import ExperimentConfig
    from distributed_learning_simulator_tpu.simulator import _oom_hint

    cfg = ExperimentConfig(worker_number=1000, client_chunk_size=250)
    params = {"w": jnp.zeros((1000, 100), jnp.float32)}
    with pytest.raises(RuntimeError, match="client_chunk_size="):
        with _oom_hint(cfg, params, 1000):
            raise jax.errors.JaxRuntimeError("RESOURCE_EXHAUSTED: Ran out of memory in memory space hbm")
    # non-OOM errors pass through untouched
    with pytest.raises(jax.errors.JaxRuntimeError, match="something else"):
        with _oom_hint(cfg, params, 1000):
            raise jax.errors.JaxRuntimeError("something else")


def test_payload_accounting():
    import jax.numpy as jnp

    from distributed_learning_simulator_tpu.ops.payload import (
        compression_ratio,
        payload_bytes,
        quantized_payload_bytes,
        sign_payload_bytes,
    )

    tree = {"a": jnp.zeros((10, 10), jnp.float32), "b": jnp.zeros((50,), jnp.float32)}
    raw = payload_bytes(tree)
    assert raw == 150 * 4
    q = quantized_payload_bytes(tree, 256)
    assert q < raw
    s = sign_payload_bytes(tree)
    assert s < q
    assert compression_ratio(raw, q) > 1.0
