"""Small utility modules: tracing no-ops, multihost init, payload math."""

import numpy as np

from distributed_learning_simulator_tpu.utils.tracing import (
    annotate,
    profile_session,
)


def test_profile_session_noop_and_annotate():
    with profile_session(None):
        with annotate("test_region"):
            x = np.arange(4).sum()
    assert x == 6


def test_profile_session_writes_trace(tmp_path):
    import jax.numpy as jnp

    with profile_session(str(tmp_path / "trace")):
        _ = jnp.ones(8).sum()
    assert (tmp_path / "trace").exists()


def test_parse_device_trace_shape_and_robustness(tmp_path):
    """parse_device_trace returns the proxy dict for a real trace dir and
    zeros (not an exception) for an empty one."""
    import jax
    import jax.numpy as jnp

    from distributed_learning_simulator_tpu.utils.tracing import (
        parse_device_trace,
    )

    with profile_session(str(tmp_path / "trace")):
        _ = jax.jit(lambda x: (x * 2).sum())(jnp.ones(64)).block_until_ready()
    stats = parse_device_trace(str(tmp_path / "trace"))
    assert set(stats) == {"device_ms", "bytes_gb", "op_count"}
    assert stats["device_ms"] >= 0.0 and stats["bytes_gb"] >= 0.0
    empty = parse_device_trace(str(tmp_path / "nonexistent"))
    assert empty == {"device_ms": 0.0, "bytes_gb": 0.0, "op_count": 0}


def test_multihost_initialize_single_process():
    """On a single process, initialize is a no-op that reports devices."""
    from distributed_learning_simulator_tpu.parallel.multihost import (
        initialize_multihost,
    )

    n = initialize_multihost()
    assert n >= 1


def test_oom_hint_rewrites_device_oom():
    import jax
    import jax.numpy as jnp
    import pytest

    from distributed_learning_simulator_tpu.config import ExperimentConfig
    from distributed_learning_simulator_tpu.simulator import _oom_hint

    cfg = ExperimentConfig(worker_number=1000, client_chunk_size=250)
    params = {"w": jnp.zeros((1000, 100), jnp.float32)}
    with pytest.raises(RuntimeError, match="client_chunk_size="):
        with _oom_hint(cfg, params, 1000):
            raise jax.errors.JaxRuntimeError("RESOURCE_EXHAUSTED: Ran out of memory in memory space hbm")
    # non-OOM errors pass through untouched
    with pytest.raises(jax.errors.JaxRuntimeError, match="something else"):
        with _oom_hint(cfg, params, 1000):
            raise jax.errors.JaxRuntimeError("something else")


def test_payload_accounting():
    import jax.numpy as jnp

    from distributed_learning_simulator_tpu.ops.payload import (
        compression_ratio,
        payload_bytes,
        quantized_payload_bytes,
        sign_payload_bytes,
    )

    tree = {"a": jnp.zeros((10, 10), jnp.float32), "b": jnp.zeros((50,), jnp.float32)}
    raw = payload_bytes(tree)
    assert raw == 150 * 4
    q = quantized_payload_bytes(tree, 256)
    assert q < raw
    s = sign_payload_bytes(tree)
    assert s < q
    assert compression_ratio(raw, q) > 1.0


def test_stochastic_round_bf16_unbiased():
    """_sr_to_bf16's hash dither must be unbiased: averaged over many
    salts, E[rounded] recovers values BETWEEN bf16 grid points (the
    property bf16 local training's accuracy rests on), and grid points
    round exactly."""
    import jax.numpy as jnp
    import numpy as np

    from distributed_learning_simulator_tpu.parallel.engine import _sr_to_bf16

    # values straddling bf16 grid points at several magnitudes
    base = np.array([1.0, 0.1, 0.01, -1.0, -0.25, 3.7], np.float32)
    ulp = np.float32(2.0) ** (np.floor(np.log2(np.abs(base))) - 7)
    x = jnp.asarray(base + 0.37 * ulp)  # 37% of the way to the next point

    acc = np.zeros_like(base, np.float64)
    n_salts = 4096
    salt = jnp.uint32(12345)
    for _ in range(n_salts):
        r, salt = _sr_to_bf16(x, salt)
        acc += np.asarray(r, np.float64)
    mean = acc / n_salts
    # mean must sit within a few percent of one ulp from the true value
    err_ulps = np.abs(mean - np.asarray(x, np.float64)) / ulp
    assert np.all(err_ulps < 0.05), err_ulps

    # exact bf16 grid values are returned exactly (dither only touches the
    # truncated low bits, which are zero on the grid)
    grid = np.asarray(
        jnp.asarray(base).astype(jnp.bfloat16).astype(jnp.float32)
    )
    r, _ = _sr_to_bf16(jnp.asarray(grid), jnp.uint32(7))
    np.testing.assert_array_equal(np.asarray(r, np.float32), grid)


def test_stochastic_round_decorrelated_across_salts():
    """Different salts (= different clients) must make independent rounding
    decisions for the same input value — the aggregate's unbiasedness
    rests on this (see engine._sr_to_bf16)."""
    import jax.numpy as jnp
    import numpy as np

    from distributed_learning_simulator_tpu.parallel.engine import _sr_to_bf16

    ulp = np.float32(2.0 ** -7)
    # mid-gap values with per-element sub-ulp jitter: real weights never
    # collide bit-exactly, and the hash dithers per VALUE — identical
    # bit patterns round identically within one salt (unlike a counter
    # PRNG), which is fine for continuous-valued weights
    jitter = (np.arange(256, dtype=np.float32) - 128) * np.float32(2e-5)
    x = jnp.asarray(1.0 + (0.5 + jitter) * ulp, jnp.float32)
    r1, _ = _sr_to_bf16(x, jnp.uint32(1))
    r2, _ = _sr_to_bf16(x, jnp.uint32(2))
    up1 = np.asarray(r1, np.float32) > 1.0
    up2 = np.asarray(r2, np.float32) > 1.0
    # each salt mixes up/down across elements, and salts disagree often
    assert 0.2 < up1.mean() < 0.8
    assert 0.2 < up2.mean() < 0.8
    assert (up1 != up2).mean() > 0.2


def test_package_main_entry_help():
    """`python -m distributed_learning_simulator_tpu` exposes the same CLI
    as the .simulator module (reference's `python3 simulator.py` entry)."""
    import os
    import subprocess
    import sys

    repo = os.path.join(os.path.dirname(__file__), "..")
    proc = subprocess.run(
        [sys.executable, "-m", "distributed_learning_simulator_tpu",
         "--help"],
        cwd=repo, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0
    assert "--distributed_algorithm" in proc.stdout


def test_profile_from_round_defers_trace(tmp_path, tiny_config):
    """config.profile_from_round starts the trace mid-run (bench.py's
    flagship proxy uses it to keep round-0 compile host events out of
    the profiler buffer — they silently drop device events on tunneled
    chips). The trace dir must exist and parse; a from_round past the
    last round must produce NO trace session (the stack never enters)."""
    import dataclasses
    import os

    from distributed_learning_simulator_tpu.simulator import run_simulation

    traced = str(tmp_path / "tr")
    cfg = dataclasses.replace(
        tiny_config, round=3, profile_dir=traced, profile_from_round=1,
    )
    res = run_simulation(cfg, setup_logging=False)
    assert len(res["history"]) == 3
    assert os.path.isdir(traced)
    # The deferral must be visible in the captured events: the per-round
    # `annotate(f"fl_round_N")` regions for rounds >= from_round are in
    # the trace, round 0's is NOT (a regression that starts the trace at
    # round 0 would put fl_round_0 in here).
    import glob
    import gzip
    import json

    names = set()
    for path in glob.glob(
        os.path.join(traced, "**", "*.trace.json.gz"), recursive=True
    ):
        with gzip.open(path, "rt") as f:
            for ev in json.load(f).get("traceEvents", []):
                if str(ev.get("name", "")).startswith("fl_round_"):
                    names.add(ev["name"])
    assert "fl_round_1" in names and "fl_round_2" in names, names
    assert "fl_round_0" not in names, names

    never = str(tmp_path / "never")
    cfg2 = dataclasses.replace(
        tiny_config, round=2, profile_dir=never, profile_from_round=99,
    )
    res2 = run_simulation(cfg2, setup_logging=False)
    assert len(res2["history"]) == 2
    assert not os.path.isdir(never)  # trace never started


def test_run_artifact_paths_unique_same_second(tmp_path):
    """Two runs starting within the same second (even the same
    microsecond, forced via an identical explicit timestamp) must get
    DISTINCT log files and artifacts dirs — the collision that used to
    overwrite logs and interleave metrics.jsonl (utils/logging.py keyed
    paths on int(timestamp))."""
    import logging as _logging
    import os

    from distributed_learning_simulator_tpu.utils.logging import (
        get_logger,
        set_file_handler,
        set_run_artifacts,
    )

    ts = 1700000000.123456
    p1 = set_file_handler(str(tmp_path), "fed", "mnist", "lenet5",
                          timestamp=ts)
    p2 = set_file_handler(str(tmp_path), "fed", "mnist", "lenet5",
                          timestamp=ts)
    assert p1 != p2
    assert os.path.exists(p1) and os.path.exists(p2)
    # Sub-second precision + pid land in the run id.
    base = os.path.basename(p1)
    assert "123456" in base and str(os.getpid()) in base

    a1 = set_run_artifacts(str(tmp_path), "fed", "mnist", "lenet5")
    a2 = set_run_artifacts(str(tmp_path), "fed", "mnist", "lenet5")
    assert a1[0] != a2[0] and a1[1] != a2[1]
    assert os.path.isdir(a1[1]) and os.path.isdir(a2[1])

    # Detach the file sink this test attached (other tests share the
    # process-global logger).
    logger = get_logger()
    for h in [h for h in logger.handlers
              if isinstance(h, _logging.FileHandler)]:
        logger.removeHandler(h)
        h.close()


def test_profile_from_round_rejects_negative(tiny_config):
    """profile_from_round < 0 is a config error (caught in validate()
    alongside the other Shapley/profiling knob checks), not a silent
    never-starts-tracing run."""
    import dataclasses

    import pytest

    cfg = dataclasses.replace(tiny_config, profile_from_round=-1)
    with pytest.raises(ValueError, match="profile_from_round"):
        cfg.validate()
    # 0 (trace from the first round) stays valid.
    dataclasses.replace(tiny_config, profile_from_round=0).validate()
