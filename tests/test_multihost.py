"""Multi-host (DCN) initialization: CLI wiring + jax.distributed smoke.

The reference's closest analogue is the dormant multi-process queue path
(reference servers/server.py:11-13, hard-disabled at simulator.py:56).
Here the capability is live: ``--multihost`` brings up jax.distributed
before device discovery, after which the ordinary mesh/sharding code spans
every process's devices.
"""

import dataclasses
import os
import socket
import subprocess
import sys

import textwrap

from distributed_learning_simulator_tpu.config import get_config
from distributed_learning_simulator_tpu.parallel.multihost import (
    initialize_multihost,
)
from distributed_learning_simulator_tpu.simulator import run_simulation


def test_single_process_noop_path():
    """With no coordinator configured, initialization degrades to a logged
    no-op and reports this process's devices."""
    n = initialize_multihost()
    assert n == len(__import__("jax").devices())


def test_multihost_flag_reaches_simulation(tiny_config):
    """--multihost routes through initialize_multihost before any device
    query; in a single-process environment the run proceeds normally."""
    cfg = dataclasses.replace(tiny_config, multihost=True, round=1)
    res = run_simulation(cfg, setup_logging=False)
    assert len(res["history"]) == 1


def test_multihost_cli_flags_parse():
    cfg = get_config([
        "--multihost", "true",
        "--coordinator_address", "localhost:9999",
        "--num_processes", "2",
        "--process_id", "0",
    ])
    assert cfg.multihost is True
    assert cfg.coordinator_address == "localhost:9999"
    assert cfg.num_processes == 2
    assert cfg.process_id == 0


def test_explicit_flags_make_failure_fatal():
    """Explicit multi-process flags with a broken configuration must raise,
    not silently degrade into an independent single-process run."""
    import pytest

    with pytest.raises(RuntimeError, match="refusing to degrade"):
        # num_processes=2 without a coordinator address is unresolvable.
        initialize_multihost(num_processes=2, process_id=0)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch_two(code: str, extra_args=None, timeout: int = 300,
                env_overrides=None):
    """THE 2-process launch helper (the three hand-copied Popen blocks
    this file used to carry): run ``code`` in two fresh interpreters
    against a fresh coordinator port. ``extra_args(i)`` (or a plain
    list shared by both) supplies per-process argv after the standard
    ``addr process_id`` pair; ``env_overrides[i]`` merges per-process
    env (the chaos tests SIGKILL one host only). Returns
    ``[(returncode, stdout, stderr), ...]`` — callers assert rc
    themselves because the chaos variants EXPECT nonzero exits; a
    process that outlives ``timeout`` (a host blocked on a collective
    whose peer died) is killed and reported with its partial output.
    """
    port = _free_port()
    addr = f"127.0.0.1:{port}"
    repo = os.path.join(os.path.dirname(__file__), "..")
    procs = []
    for i in range(2):
        env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
        env["JAX_PLATFORMS"] = "cpu"
        if env_overrides and env_overrides[i]:
            env.update(env_overrides[i])
        args = (
            extra_args(i) if callable(extra_args)
            else list(extra_args or [])
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-c", code, addr, str(i), *args],
            cwd=repo, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
        ))
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            out, err = p.communicate()
        outs.append((p.returncode, out, err))
    return outs


def _assert_ok(outs):
    """Assert both processes exited cleanly; return their stdouts."""
    for i, (rc, out, err) in enumerate(outs):
        assert rc == 0, (i, out, err)
    return [out for _, out, _ in outs]


_WORKER_CODE = textwrap.dedent("""
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from distributed_learning_simulator_tpu.parallel.multihost import (
        initialize_multihost,
    )
    n = initialize_multihost(
        coordinator_address=sys.argv[1],
        num_processes=2,
        process_id=int(sys.argv[2]),
    )
    assert jax.process_count() == 2, jax.process_count()
    assert n == 2, n  # one cpu device per process, both visible globally
    # Re-calling with explicit flags in an already-initialized process is
    # a logged no-op, not a fatal error (a second run in one driver).
    assert initialize_multihost(
        coordinator_address=sys.argv[1], num_processes=2,
        process_id=int(sys.argv[2]),
    ) == 2
    # The mesh code needs no multihost-specific branch: a mesh over the
    # global device list spans both processes.
    from distributed_learning_simulator_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(2)
    assert mesh.devices.shape == (2,)
    print("MULTIHOST_OK", int(sys.argv[2]))
""")


_TRAIN_CODE = textwrap.dedent("""
    import json
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from distributed_learning_simulator_tpu.config import ExperimentConfig
    from distributed_learning_simulator_tpu.simulator import run_simulation

    extra = json.loads(sys.argv[3]) if len(sys.argv) > 3 else {}
    config = ExperimentConfig(
        dataset_name="synthetic", model_name="mlp",
        distributed_algorithm=extra.pop("distributed_algorithm", "fed"),
        worker_number=8, round=2, epoch=1,
        learning_rate=extra.pop("learning_rate", 0.1),
        n_train=256, n_test=128, log_level="ERROR",
        multihost=True, coordinator_address=sys.argv[1], num_processes=2,
        process_id=int(sys.argv[2]), mesh_devices=2, **extra,
    )
    res = run_simulation(config, setup_logging=False)
    accs = [h["test_accuracy"] for h in res["history"]]
    assert len(accs) == 2 and all(a == a for a in accs)
    svs = [h.get("shapley_values") for h in res["history"]]
    if any(sv is not None for sv in svs):
        flat = [round(sv[i], 6) for sv in svs for i in sorted(sv)]
        assert all(v == v for v in flat), flat  # finite
        print("SV_OK", sys.argv[2], ",".join(map(str, flat)))
    print("TRAIN_OK", sys.argv[2], accs[-1])
""")


def _run_two_process_train(extra: dict | None = None) -> list[str]:
    """Launch the SPMD simulation in two processes; return their stdouts
    (both asserted rc=0)."""
    import json

    args = [json.dumps(extra)] if extra else []
    return _assert_ok(_launch_two(_TRAIN_CODE, args))


def _final_accs(outs: list[str]) -> list[str]:
    return [
        [ln for ln in out.splitlines() if ln.startswith("TRAIN_OK")][0]
        .split()[2]
        for out in outs
    ]


def _sv_values(outs: list[str]) -> list[str]:
    """Per-process SV_OK payloads (asserts the shapley path produced
    values in every process)."""
    svs = []
    for out in outs:
        lines = [ln for ln in out.splitlines() if ln.startswith("SV_OK")]
        assert lines, out
        svs.append(lines[0].split()[2])
    return svs


def test_two_process_full_simulation():
    """The ENTIRE simulation runs SPMD across two processes: client axis
    sharded over a 2-device mesh spanning both, aggregation riding the
    cross-process (DCN-analog) path, identical metrics on both sides."""
    finals = _final_accs(_run_two_process_train())
    assert finals[0] == finals[1]  # SPMD: both processes see the same model


def test_two_process_sign_sgd():
    """sign_SGD's per-OPTIMIZER-STEP majority vote (reference
    workers/sign_sgd_worker.py:44-46 — the system's highest-frequency sync)
    across a process boundary: the sign/sum/sign reduction rides the
    cross-process collective every local step, and both processes must
    land on the same model."""
    finals = _final_accs(_run_two_process_train(
        {"distributed_algorithm": "sign_SGD", "learning_rate": 0.01}
    ))
    assert finals[0] == finals[1]


def test_two_process_fed_quant():
    """fed_quant's per-client payload RNG (hash-dither stochastic quantize
    of both exchange directions) under cross-process sharding: the dither
    is a pure function of value bits + per-client salt, so placement
    cannot change it — both processes must agree."""
    finals = _final_accs(_run_two_process_train(
        {"distributed_algorithm": "fed_quant", "client_eval": False}
    ))
    assert finals[0] == finals[1]


def test_two_process_multiround_shapley():
    """Exact-Shapley post_round consuming a client-params stack SHARDED
    ACROSS PROCESSES: subset weighted means are einsums over the
    cross-process client axis, and the resulting per-round SVs must be
    finite and identical on both sides."""
    outs = _run_two_process_train(
        {"distributed_algorithm": "multiround_shapley_value"}
    )
    finals = _final_accs(outs)
    assert finals[0] == finals[1]
    svs = _sv_values(outs)
    assert svs[0] == svs[1]


def test_two_process_gtg_shapley():
    """GTG's DATA-DEPENDENT permutation walk across processes: both hosts
    drive the walk from utilities fetched off cross-process collectives,
    and every eps-truncation / convergence decision must agree bitwise —
    a divergent walk issues different batched evaluator calls and the
    mismatched SPMD programs deadlock (which the subprocess timeout
    converts into a visible failure). SVs must come out identical."""
    outs = _run_two_process_train({
        "distributed_algorithm": "GTG_shapley_value",
        "shapley_eval_samples": 64,
    })
    finals = _final_accs(outs)
    assert finals[0] == finals[1]
    svs = _sv_values(outs)
    assert svs[0] == svs[1]


_RESUME_CODE = textwrap.dedent("""
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from distributed_learning_simulator_tpu.config import ExperimentConfig
    from distributed_learning_simulator_tpu.simulator import run_simulation

    # sys.argv: addr, process_id, ckpt_dir_for_this_process, expect
    config = ExperimentConfig(
        dataset_name="synthetic", model_name="mlp",
        distributed_algorithm="fed", worker_number=8, round=3, epoch=1,
        learning_rate=0.1, n_train=256, n_test=128, log_level="ERROR",
        multihost=True, coordinator_address=sys.argv[1], num_processes=2,
        process_id=int(sys.argv[2]), mesh_devices=2,
        checkpoint_dir=sys.argv[3], checkpoint_every=1, resume=True,
    )
    if sys.argv[4] == "ok":
        res = run_simulation(config, setup_logging=False)
        print("RESUME_OK", sys.argv[2], len(res["history"]))
    else:
        try:
            run_simulation(config, setup_logging=False)
        except RuntimeError as e:
            assert "multihost resume mismatch" in str(e), e
            print("MISMATCH_CAUGHT", sys.argv[2])
""")


def _write_seed_checkpoint(ckpt_dir: str) -> None:
    """Single-process short run that leaves a checkpoint in ckpt_dir."""
    code = textwrap.dedent(f"""
        import jax
        jax.config.update("jax_platforms", "cpu")
        from distributed_learning_simulator_tpu.config import ExperimentConfig
        from distributed_learning_simulator_tpu.simulator import run_simulation
        config = ExperimentConfig(
            dataset_name="synthetic", model_name="mlp",
            distributed_algorithm="fed", worker_number=8, round=1, epoch=1,
            learning_rate=0.1, n_train=256, n_test=128, log_level="ERROR",
            checkpoint_dir={ckpt_dir!r}, checkpoint_every=1,
        )
        run_simulation(config, setup_logging=False)
    """)
    repo = os.path.join(os.path.dirname(__file__), "..")
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    out = subprocess.run(
        [sys.executable, "-c", code], cwd=repo, env=env,
        capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, (out.stdout, out.stderr)


def _run_two_process_resume(dirs: list[str], expect: str) -> list[str]:
    return _assert_ok(
        _launch_two(_RESUME_CODE, lambda i: [dirs[i], expect])
    )


def test_two_process_resume_shared_dir_ok(tmp_path):
    """Resume under multihost with a SHARED checkpoint dir: both processes
    restore the same round; agreement check passes and the run completes."""
    ckpt = str(tmp_path / "shared_ckpt")
    _write_seed_checkpoint(ckpt)
    lines = _run_two_process_resume([ckpt, ckpt], "ok")
    for i, out in enumerate(lines):
        assert f"RESUME_OK {i}" in out, (i, out)


def test_two_process_resume_divergent_dirs_fatal(tmp_path):
    """One process sees a checkpoint, the other an empty dir: the agreement
    check must raise on BOTH sides instead of dispatching mismatched SPMD
    programs (hang/silent split — ADVICE r2 medium)."""
    ckpt = str(tmp_path / "proc0_ckpt")
    empty = str(tmp_path / "empty_ckpt")
    os.makedirs(empty, exist_ok=True)
    _write_seed_checkpoint(ckpt)
    lines = _run_two_process_resume([ckpt, empty], "mismatch")
    for i, out in enumerate(lines):
        assert f"MISMATCH_CAUGHT {i}" in out, (i, out)


def test_two_process_cpu_distributed_smoke():
    """Real 2-process jax.distributed bring-up over localhost: the actual
    DCN code path (coordinator service + global device enumeration), on the
    CPU backend."""
    outs = _assert_ok(_launch_two(_WORKER_CODE, timeout=240))
    for i, out in enumerate(outs):
        assert f"MULTIHOST_OK {i}" in out, (i, out)


# --- distributed shard store (streamed x multihost; ISSUE 15) ---------------

_STREAM_CODE = textwrap.dedent("""
    import json
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from distributed_learning_simulator_tpu.config import ExperimentConfig
    from distributed_learning_simulator_tpu.simulator import run_simulation

    extra = json.loads(sys.argv[3]) if len(sys.argv) > 3 else {}
    # The span tests need the primary's artifacts dir (metrics.jsonl
    # with v12 records), which only materializes under setup_logging.
    setup = extra.pop("setup_logging", False)
    config = ExperimentConfig(
        dataset_name="synthetic", model_name="mlp",
        distributed_algorithm=extra.pop("distributed_algorithm", "fed"),
        worker_number=8, round=extra.pop("round", 3), epoch=1,
        learning_rate=extra.pop("learning_rate", 0.1),
        n_train=256, n_test=128, log_level="ERROR",
        multihost=True, coordinator_address=sys.argv[1], num_processes=2,
        process_id=int(sys.argv[2]), mesh_devices=2,
        client_residency="streamed", **extra,
    )
    try:
        res = run_simulation(config, setup_logging=setup)
    except RuntimeError as e:
        # The topology-mismatch variant expects a cause-named refusal.
        print("REFUSED", sys.argv[2], str(e)[:200].replace("\\n", " "))
        sys.exit(0)
    keep = [
        {k: h[k]
         for k in ("round", "test_accuracy", "test_loss",
                   "mean_client_loss", "cohort_hash")
         if k in h}
        for h in res["history"]
    ]
    print("HIST", sys.argv[2], json.dumps(keep))
    print("MHSUM", sys.argv[2], json.dumps(res["multihost_summary"]))
    if res.get("span_summary") is not None:
        print("SPANSUM", sys.argv[2], json.dumps(res["span_summary"]))
""")

_STATEFUL = {
    # Persistent per-client optimizer state: the composition that
    # exercises BOTH exchange directions (state spill-in at gather,
    # owner return at writeback) and gives the checkpoint shards real
    # per-host content.
    "momentum": 0.9, "reset_client_optimizer": False,
    "participation_fraction": 0.5, "participation_sampler": "hashed",
}


def _stream_two(extra: dict, env_overrides=None, expect_rc=True):
    import json

    outs = _launch_two(_STREAM_CODE, [json.dumps(extra)],
                       env_overrides=env_overrides, timeout=420)
    if expect_rc:
        return _assert_ok(outs)
    return outs


def _hist_of(out: str) -> list[dict]:
    import json

    lines = [ln for ln in out.splitlines() if ln.startswith("HIST")]
    assert lines, out
    return json.loads(lines[0].split(" ", 2)[2])


def _solo_streamed_history(extra: dict) -> list[dict]:
    """The 1-process reference at the SAME fixed global mesh (2 devices
    from the conftest's virtual-CPU pool), run in-process."""
    from distributed_learning_simulator_tpu.config import (
        ExperimentConfig as _EC,
    )

    extra = dict(extra)
    cfg = _EC(
        dataset_name="synthetic", model_name="mlp",
        distributed_algorithm=extra.pop("distributed_algorithm", "fed"),
        worker_number=8, round=extra.pop("round", 3), epoch=1,
        learning_rate=extra.pop("learning_rate", 0.1),
        n_train=256, n_test=128, log_level="ERROR", mesh_devices=2,
        **extra,
    )
    return run_simulation(cfg, setup_logging=False)["history"]


def _assert_histories_close(mh_hist, ref_hist, bit_exact=False):
    """The PR 7 contract at the distributed layout: identical cohort
    sequence (cohort_hash bitwise) and identical metrics — bit-exact
    where promised (sign_SGD), else to the documented resident-vs-mesh
    reduction-order tolerance (the owner permutation only moves the
    aggregation's summation order)."""
    assert len(mh_hist) == len(ref_hist)
    for a, b in zip(mh_hist, ref_hist):
        assert a["round"] == b["round"]
        if "cohort_hash" in b:
            assert a["cohort_hash"] == b["cohort_hash"], (a, b)
        for k in ("test_accuracy", "test_loss", "mean_client_loss"):
            if bit_exact:
                assert a[k] == b[k], (k, a, b)
            else:
                assert abs(a[k] - b[k]) <= 1e-4 * max(abs(b[k]), 1.0), (
                    k, a, b,
                )


def test_two_process_distributed_store_matches_single_process():
    """THE composition ISSUE 15 exists for: streamed residency across 2
    host processes — each owning half the clients, serving its members
    of the owner-permuted cohort into its addressable shards, with
    persistent per-client state riding the spill exchange — produces
    the SAME run as the 1-process streamed program and the resident
    program at the same fixed global mesh."""
    import json

    outs = _stream_two(dict(_STATEFUL))
    h0, h1 = _hist_of(outs[0]), _hist_of(outs[1])
    assert h0 == h1  # SPMD: both processes see the same run
    # Per-host shard summary: complementary halves of the population.
    sums = []
    for out in outs:
        ln = [ln for ln in out.splitlines() if ln.startswith("MHSUM")][0]
        sums.append(json.loads(ln.split(" ", 2)[2]))
    assert {s["host_id"] for s in sums} == {0, 1}
    assert all(s["hosts"] == 2 for s in sums)
    assert sum(s["owned_clients"] for s in sums) == 8
    ref_streamed = _solo_streamed_history(
        dict(_STATEFUL, client_residency="streamed")
    )
    ref_resident = _solo_streamed_history(
        dict(_STATEFUL, client_residency="resident")
    )
    _assert_histories_close(h0, ref_streamed)
    _assert_histories_close(h0, ref_resident)


def test_two_process_distributed_store_sign_sgd_bit_exact():
    """Full-cohort regime (sign_SGD trains everyone): owner bounds ARE
    the device blocks, the permutation is the identity, zero bytes
    cross DCN — and the 2-process run must match the 1-process streamed
    run BIT-exactly."""
    import json

    extra = {"distributed_algorithm": "sign_SGD", "learning_rate": 0.01}
    outs = _stream_two(dict(extra))
    h0 = _hist_of(outs[0])
    assert h0 == _hist_of(outs[1])
    for out in outs:
        ln = [ln for ln in out.splitlines() if ln.startswith("MHSUM")][0]
        s = json.loads(ln.split(" ", 2)[2])
        assert s["spill_rows"] == 0 and s["dcn_bytes"] == 0, s
    ref = _solo_streamed_history(
        dict(extra, client_residency="streamed")
    )
    _assert_histories_close(h0, ref, bit_exact=True)


def test_two_process_sharded_checkpoint_sigkill_resume(tmp_path):
    """Per-host checkpoint shards + manifest survive a SIGKILL of one
    host mid-run: the resumed 2-process run stitches BIT-identically to
    the uninterrupted 2-process run (the PR 2 chaos contract at shard
    granularity)."""
    ckpt = str(tmp_path / "shards")
    base = dict(_STATEFUL, round=4, checkpoint_dir=ckpt,
                checkpoint_every=1)
    # Uninterrupted reference (its checkpoint dir is separate).
    ref_dir = str(tmp_path / "ref_shards")
    ref_hist = _hist_of(_stream_two(
        dict(base, checkpoint_dir=ref_dir)
    )[0])
    # Crash: host 1 SIGKILLs itself right after round 1's shard landed
    # (robustness/chaos.py fires after the checkpoint block); host 0
    # then dies on the broken collective — both exits are expected.
    outs = _stream_two(
        dict(base),
        env_overrides=(None, {"DLS_CRASH_AT_ROUND": "1",
                              "DLS_CRASH_KIND": "sigkill"}),
        expect_rc=False,
    )
    assert any(rc != 0 for rc, _, _ in outs), outs
    manifests = sorted(
        f for f in os.listdir(ckpt) if f.endswith("manifest.json")
    )
    assert manifests, os.listdir(ckpt)
    # Resume: restores the newest committed round on BOTH hosts and
    # finishes the run; stitched rounds equal the reference bit-for-bit.
    outs = _stream_two(dict(base, resume=True))
    resumed = _hist_of(outs[0])
    assert resumed == _hist_of(outs[1])
    start = resumed[0]["round"]
    assert 0 < start < 4  # genuinely resumed mid-run
    assert resumed == ref_hist[start:], (resumed, ref_hist)


def test_two_process_resume_topology_mismatch_refused(tmp_path):
    """A manifest cut for a different host topology refuses resume with
    the cause named, on BOTH processes, instead of restoring shards
    into the wrong owners."""
    import json

    ckpt = str(tmp_path / "shards")
    base = dict(_STATEFUL, round=2, checkpoint_dir=ckpt,
                checkpoint_every=1)
    _stream_two(dict(base))
    # Rewrite the newest manifest as if written by a 3-host run.
    manifests = sorted(
        f for f in os.listdir(ckpt) if f.endswith("manifest.json")
    )
    path = os.path.join(ckpt, manifests[-1])
    m = json.load(open(path))
    m["n_hosts"] = 3
    json.dump(m, open(path, "w"))
    outs = _stream_two(dict(base, resume=True))
    for i, out in enumerate(outs):
        lines = [ln for ln in out.splitlines() if ln.startswith("REFUSED")]
        assert lines, (i, out)
        assert "topology mismatch" in lines[0], lines[0]


def test_single_process_resume_of_sharded_dir_refused(tmp_path):
    """A single-process run pointed at a sharded checkpoint dir refuses
    with the cause named instead of silently starting from scratch.
    In-process (no subprocesses): the refusal fires at discovery."""
    import pytest

    from distributed_learning_simulator_tpu.config import ExperimentConfig
    from distributed_learning_simulator_tpu.utils.checkpoint import (
        write_manifest,
    )

    ckpt = str(tmp_path / "shards")
    os.makedirs(ckpt)
    write_manifest(ckpt, 0, {"n_hosts": 2, "n_clients": 8,
                             "owner_bounds": [0, 4, 8]})
    cfg = ExperimentConfig(
        dataset_name="synthetic", model_name="mlp",
        distributed_algorithm="fed", worker_number=8, round=1, epoch=1,
        learning_rate=0.1, n_train=256, n_test=128, log_level="ERROR",
        client_residency="streamed", participation_fraction=0.5,
        participation_sampler="hashed",
        checkpoint_dir=ckpt, checkpoint_every=1, resume=True,
    )
    with pytest.raises(RuntimeError, match="sharded checkpoints"):
        run_simulation(cfg, setup_logging=False)


# ---------------------------------------------------------------------------
# Distributed tracing (telemetry/spans.py + scripts/trace_timeline.py):
# the REAL 2-process acceptance runs — a deliberately slowed host named
# by the stitched timeline, and a SIGKILL postmortem naming both hosts'
# in-flight spans. The arithmetic of the stitcher itself is pinned by
# the synthetic-journal tests in tests/test_spans.py.


def _load_stitcher():
    import importlib.util

    path = os.path.join(os.path.dirname(__file__), "..", "scripts",
                        "trace_timeline.py")
    spec = importlib.util.spec_from_file_location("trace_timeline", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_two_process_span_trace_straggler_attribution(tmp_path):
    """Slow ONE host's arrival at the spill exchange (DLS_STRAGGLE_S)
    with span_trace on: the stitched journals must attribute every
    spill barrier to the slowed host, measure a skew of the order of
    the injected delay, show the FAST host carrying the wait time, and
    the primary's metrics.jsonl must stamp schema v12 with the same
    skew — while the run itself still matches SPMD across hosts."""
    import glob
    import json

    span_dir = str(tmp_path / "spans")
    art = str(tmp_path / "art")
    outs = _stream_two(
        dict(_STATEFUL, span_trace="on", span_dir=span_dir, log_root=art,
             setup_logging=True),
        env_overrides=(None, {"DLS_STRAGGLE_S": "0.2"}),
    )
    assert _hist_of(outs[0]) == _hist_of(outs[1])
    # Both hosts return a run-total span summary in the result dict.
    sums = {}
    for out in outs:
        ln = [ln for ln in out.splitlines() if ln.startswith("SPANSUM")][0]
        s = json.loads(ln.split(" ", 2)[2])
        sums[s["host_id"]] = s
    assert set(sums) == {0, 1}
    assert all(s["count"] > 0 for s in sums.values())
    assert sums[0]["spill_skew_ms_max"] > 100.0  # ~200 ms injected

    tt = _load_stitcher()
    journals = [tt.load_journal(p)
                for p in tt.find_journals([span_dir])]
    assert [j["header"]["host_id"] for j in journals] == [0, 1]
    summary = tt.summarize(journals)
    spill = [entry for rnd in summary["rounds"].values()
             for name, entry in rnd.items() if name == "spill_wait"]
    assert spill, summary["rounds"]
    # The straggler arrived last at EVERY barrier => shortest wait.
    assert all(e["slowest_host"] == 1 for e in spill), spill
    assert max(e["skew_ms"] for e in spill) > 100.0, spill
    # ...and the fast host is the one that accumulated the DCN wait.
    assert (summary["totals"]["0"]["dcn_wait_s"]
            > summary["totals"]["1"]["dcn_wait_s"]), summary["totals"]

    # Primary's records: v12-stamped, spans sub-object carrying the skew.
    mfiles = glob.glob(os.path.join(art, "**", "metrics.jsonl"),
                       recursive=True)
    assert mfiles, os.listdir(art)
    recs = [json.loads(ln) for ln in open(mfiles[0])]
    assert recs and all(r["schema_version"] == 12 for r in recs)
    skews = [r["spans"].get("spill_skew_ms") for r in recs]
    assert any(s is not None and s > 100.0 for s in skews), skews


def test_two_process_span_flight_recorder_sigkill_postmortem(tmp_path):
    """SIGKILL one host mid-run with span_trace on: no cleanup code runs
    on the victim, yet the stitched postmortem names BOTH hosts'
    in-flight spans — the victim via the eager open-line of the round
    envelope it died inside, the survivor via its crash flush (or its
    own eager open-line if it too dies hard on the broken collective)."""
    span_dir = str(tmp_path / "spans")
    outs = _stream_two(
        dict(_STATEFUL, round=4, span_trace="on", span_dir=span_dir),
        env_overrides=(None, {"DLS_CRASH_AT_ROUND": "1",
                              "DLS_CRASH_KIND": "sigkill"}),
        expect_rc=False,
    )
    assert any(rc != 0 for rc, _, _ in outs), outs

    tt = _load_stitcher()
    journals = [tt.load_journal(p)
                for p in tt.find_journals([span_dir])]
    assert len(journals) == 2, [j["path"] for j in journals]
    postmortem = tt.summarize(journals)["postmortem"]
    by_host: dict[int, list] = {}
    for p in postmortem:
        by_host.setdefault(p["host_id"], []).append(p)
    assert set(by_host) == {0, 1}, postmortem
    # Victim (host 1): maybe_crash fires inside the eager 'finalize'
    # envelope, so its journal's unmatched open names that span.
    assert any(
        p.get("name") == "finalize"
        and p["kind"] in ("died_inside", "inflight")
        for p in by_host[1]
    ), postmortem
    # Survivor (host 0): whatever way it went down, a NAMED span marks
    # where it was stuck when the federation broke.
    assert any(p.get("name") for p in by_host[0]), postmortem
