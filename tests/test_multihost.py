"""Multi-host (DCN) initialization: CLI wiring + jax.distributed smoke.

The reference's closest analogue is the dormant multi-process queue path
(reference servers/server.py:11-13, hard-disabled at simulator.py:56).
Here the capability is live: ``--multihost`` brings up jax.distributed
before device discovery, after which the ordinary mesh/sharding code spans
every process's devices.
"""

import dataclasses
import os
import socket
import subprocess
import sys

import textwrap

from distributed_learning_simulator_tpu.config import get_config
from distributed_learning_simulator_tpu.parallel.multihost import (
    initialize_multihost,
)
from distributed_learning_simulator_tpu.simulator import run_simulation


def test_single_process_noop_path():
    """With no coordinator configured, initialization degrades to a logged
    no-op and reports this process's devices."""
    n = initialize_multihost()
    assert n == len(__import__("jax").devices())


def test_multihost_flag_reaches_simulation(tiny_config):
    """--multihost routes through initialize_multihost before any device
    query; in a single-process environment the run proceeds normally."""
    cfg = dataclasses.replace(tiny_config, multihost=True, round=1)
    res = run_simulation(cfg, setup_logging=False)
    assert len(res["history"]) == 1


def test_multihost_cli_flags_parse():
    cfg = get_config([
        "--multihost", "true",
        "--coordinator_address", "localhost:9999",
        "--num_processes", "2",
        "--process_id", "0",
    ])
    assert cfg.multihost is True
    assert cfg.coordinator_address == "localhost:9999"
    assert cfg.num_processes == 2
    assert cfg.process_id == 0


def test_explicit_flags_make_failure_fatal():
    """Explicit multi-process flags with a broken configuration must raise,
    not silently degrade into an independent single-process run."""
    import pytest

    with pytest.raises(RuntimeError, match="refusing to degrade"):
        # num_processes=2 without a coordinator address is unresolvable.
        initialize_multihost(num_processes=2, process_id=0)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


_WORKER_CODE = textwrap.dedent("""
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from distributed_learning_simulator_tpu.parallel.multihost import (
        initialize_multihost,
    )
    n = initialize_multihost(
        coordinator_address=sys.argv[1],
        num_processes=2,
        process_id=int(sys.argv[2]),
    )
    assert jax.process_count() == 2, jax.process_count()
    assert n == 2, n  # one cpu device per process, both visible globally
    # Re-calling with explicit flags in an already-initialized process is
    # a logged no-op, not a fatal error (a second run in one driver).
    assert initialize_multihost(
        coordinator_address=sys.argv[1], num_processes=2,
        process_id=int(sys.argv[2]),
    ) == 2
    # The mesh code needs no multihost-specific branch: a mesh over the
    # global device list spans both processes.
    from distributed_learning_simulator_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(2)
    assert mesh.devices.shape == (2,)
    print("MULTIHOST_OK", int(sys.argv[2]))
""")


_TRAIN_CODE = textwrap.dedent("""
    import json
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from distributed_learning_simulator_tpu.config import ExperimentConfig
    from distributed_learning_simulator_tpu.simulator import run_simulation

    extra = json.loads(sys.argv[3]) if len(sys.argv) > 3 else {}
    config = ExperimentConfig(
        dataset_name="synthetic", model_name="mlp",
        distributed_algorithm=extra.pop("distributed_algorithm", "fed"),
        worker_number=8, round=2, epoch=1,
        learning_rate=extra.pop("learning_rate", 0.1),
        n_train=256, n_test=128, log_level="ERROR",
        multihost=True, coordinator_address=sys.argv[1], num_processes=2,
        process_id=int(sys.argv[2]), mesh_devices=2, **extra,
    )
    res = run_simulation(config, setup_logging=False)
    accs = [h["test_accuracy"] for h in res["history"]]
    assert len(accs) == 2 and all(a == a for a in accs)
    svs = [h.get("shapley_values") for h in res["history"]]
    if any(sv is not None for sv in svs):
        flat = [round(sv[i], 6) for sv in svs for i in sorted(sv)]
        assert all(v == v for v in flat), flat  # finite
        print("SV_OK", sys.argv[2], ",".join(map(str, flat)))
    print("TRAIN_OK", sys.argv[2], accs[-1])
""")


def _run_two_process_train(extra: dict | None = None) -> list[str]:
    """Launch the SPMD simulation in two processes; return their stdouts
    (both asserted rc=0)."""
    import json

    port = _free_port()
    addr = f"127.0.0.1:{port}"
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    repo = os.path.join(os.path.dirname(__file__), "..")
    args = [json.dumps(extra)] if extra else []
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _TRAIN_CODE, addr, str(i), *args],
            cwd=repo, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=300) for p in procs]
    for i, (p, (out, err)) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (i, out, err)
    return [out for out, _ in outs]


def _final_accs(outs: list[str]) -> list[str]:
    return [
        [ln for ln in out.splitlines() if ln.startswith("TRAIN_OK")][0]
        .split()[2]
        for out in outs
    ]


def _sv_values(outs: list[str]) -> list[str]:
    """Per-process SV_OK payloads (asserts the shapley path produced
    values in every process)."""
    svs = []
    for out in outs:
        lines = [ln for ln in out.splitlines() if ln.startswith("SV_OK")]
        assert lines, out
        svs.append(lines[0].split()[2])
    return svs


def test_two_process_full_simulation():
    """The ENTIRE simulation runs SPMD across two processes: client axis
    sharded over a 2-device mesh spanning both, aggregation riding the
    cross-process (DCN-analog) path, identical metrics on both sides."""
    finals = _final_accs(_run_two_process_train())
    assert finals[0] == finals[1]  # SPMD: both processes see the same model


def test_two_process_sign_sgd():
    """sign_SGD's per-OPTIMIZER-STEP majority vote (reference
    workers/sign_sgd_worker.py:44-46 — the system's highest-frequency sync)
    across a process boundary: the sign/sum/sign reduction rides the
    cross-process collective every local step, and both processes must
    land on the same model."""
    finals = _final_accs(_run_two_process_train(
        {"distributed_algorithm": "sign_SGD", "learning_rate": 0.01}
    ))
    assert finals[0] == finals[1]


def test_two_process_fed_quant():
    """fed_quant's per-client payload RNG (hash-dither stochastic quantize
    of both exchange directions) under cross-process sharding: the dither
    is a pure function of value bits + per-client salt, so placement
    cannot change it — both processes must agree."""
    finals = _final_accs(_run_two_process_train(
        {"distributed_algorithm": "fed_quant", "client_eval": False}
    ))
    assert finals[0] == finals[1]


def test_two_process_multiround_shapley():
    """Exact-Shapley post_round consuming a client-params stack SHARDED
    ACROSS PROCESSES: subset weighted means are einsums over the
    cross-process client axis, and the resulting per-round SVs must be
    finite and identical on both sides."""
    outs = _run_two_process_train(
        {"distributed_algorithm": "multiround_shapley_value"}
    )
    finals = _final_accs(outs)
    assert finals[0] == finals[1]
    svs = _sv_values(outs)
    assert svs[0] == svs[1]


def test_two_process_gtg_shapley():
    """GTG's DATA-DEPENDENT permutation walk across processes: both hosts
    drive the walk from utilities fetched off cross-process collectives,
    and every eps-truncation / convergence decision must agree bitwise —
    a divergent walk issues different batched evaluator calls and the
    mismatched SPMD programs deadlock (which the subprocess timeout
    converts into a visible failure). SVs must come out identical."""
    outs = _run_two_process_train({
        "distributed_algorithm": "GTG_shapley_value",
        "shapley_eval_samples": 64,
    })
    finals = _final_accs(outs)
    assert finals[0] == finals[1]
    svs = _sv_values(outs)
    assert svs[0] == svs[1]


_RESUME_CODE = textwrap.dedent("""
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from distributed_learning_simulator_tpu.config import ExperimentConfig
    from distributed_learning_simulator_tpu.simulator import run_simulation

    # sys.argv: addr, process_id, ckpt_dir_for_this_process, expect
    config = ExperimentConfig(
        dataset_name="synthetic", model_name="mlp",
        distributed_algorithm="fed", worker_number=8, round=3, epoch=1,
        learning_rate=0.1, n_train=256, n_test=128, log_level="ERROR",
        multihost=True, coordinator_address=sys.argv[1], num_processes=2,
        process_id=int(sys.argv[2]), mesh_devices=2,
        checkpoint_dir=sys.argv[3], checkpoint_every=1, resume=True,
    )
    if sys.argv[4] == "ok":
        res = run_simulation(config, setup_logging=False)
        print("RESUME_OK", sys.argv[2], len(res["history"]))
    else:
        try:
            run_simulation(config, setup_logging=False)
        except RuntimeError as e:
            assert "multihost resume mismatch" in str(e), e
            print("MISMATCH_CAUGHT", sys.argv[2])
""")


def _write_seed_checkpoint(ckpt_dir: str) -> None:
    """Single-process short run that leaves a checkpoint in ckpt_dir."""
    code = textwrap.dedent(f"""
        import jax
        jax.config.update("jax_platforms", "cpu")
        from distributed_learning_simulator_tpu.config import ExperimentConfig
        from distributed_learning_simulator_tpu.simulator import run_simulation
        config = ExperimentConfig(
            dataset_name="synthetic", model_name="mlp",
            distributed_algorithm="fed", worker_number=8, round=1, epoch=1,
            learning_rate=0.1, n_train=256, n_test=128, log_level="ERROR",
            checkpoint_dir={ckpt_dir!r}, checkpoint_every=1,
        )
        run_simulation(config, setup_logging=False)
    """)
    repo = os.path.join(os.path.dirname(__file__), "..")
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    out = subprocess.run(
        [sys.executable, "-c", code], cwd=repo, env=env,
        capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, (out.stdout, out.stderr)


def _run_two_process_resume(dirs: list[str], expect: str) -> list[str]:
    port = _free_port()
    addr = f"127.0.0.1:{port}"
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    repo = os.path.join(os.path.dirname(__file__), "..")
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _RESUME_CODE, addr, str(i), dirs[i],
             expect],
            cwd=repo, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=300) for p in procs]
    lines = []
    for i, (p, (out, err)) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (i, out, err)
        lines.append(out)
    return lines


def test_two_process_resume_shared_dir_ok(tmp_path):
    """Resume under multihost with a SHARED checkpoint dir: both processes
    restore the same round; agreement check passes and the run completes."""
    ckpt = str(tmp_path / "shared_ckpt")
    _write_seed_checkpoint(ckpt)
    lines = _run_two_process_resume([ckpt, ckpt], "ok")
    for i, out in enumerate(lines):
        assert f"RESUME_OK {i}" in out, (i, out)


def test_two_process_resume_divergent_dirs_fatal(tmp_path):
    """One process sees a checkpoint, the other an empty dir: the agreement
    check must raise on BOTH sides instead of dispatching mismatched SPMD
    programs (hang/silent split — ADVICE r2 medium)."""
    ckpt = str(tmp_path / "proc0_ckpt")
    empty = str(tmp_path / "empty_ckpt")
    os.makedirs(empty, exist_ok=True)
    _write_seed_checkpoint(ckpt)
    lines = _run_two_process_resume([ckpt, empty], "mismatch")
    for i, out in enumerate(lines):
        assert f"MISMATCH_CAUGHT {i}" in out, (i, out)


def test_two_process_cpu_distributed_smoke():
    """Real 2-process jax.distributed bring-up over localhost: the actual
    DCN code path (coordinator service + global device enumeration), on the
    CPU backend."""
    port = _free_port()
    addr = f"127.0.0.1:{port}"
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.join(os.path.dirname(__file__), "..")
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER_CODE, addr, str(i)],
            cwd=repo, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=240) for p in procs]
    for i, (p, (out, err)) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (i, out, err)
        assert f"MULTIHOST_OK {i}" in out, (i, out, err)
