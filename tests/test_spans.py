"""Distributed tracing unit tests: recorder, journals, stitcher.

Fast and device-free (telemetry/spans.py and scripts/trace_timeline.py
deliberately import no jax): the span ring's bounds, the journal line
taxonomy, the flight-recorder guarantees (eager open-lines survive a
kill; ``flush_inflight`` names still-open spans), the off-gate
``config_hash`` invariance, and the cross-host stitcher on SYNTHETIC
two-host journals with a known clock offset — so the alignment math
((t - epoch_mono) + epoch_wall - clock_offset_s) is pinned by
arithmetic, not by a live 2-process run. The live integration (real
straggler attribution, real SIGKILL postmortem) is
tests/test_multihost.py's 2-process harness.
"""

import importlib.util
import json
import os
import sys

import pytest

from distributed_learning_simulator_tpu.config import ExperimentConfig
from distributed_learning_simulator_tpu.telemetry.spans import (
    SpanRecorder,
    journal_filename,
)
from distributed_learning_simulator_tpu.utils.reporting import config_hash

_STITCHER = os.path.join(
    os.path.dirname(__file__), "..", "scripts", "trace_timeline.py"
)


@pytest.fixture(scope="module")
def tt():
    spec = importlib.util.spec_from_file_location(
        "trace_timeline", _STITCHER
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ----------------------------------------------------------------------
# recorder


def test_recorder_validates_bounds():
    with pytest.raises(ValueError):
        SpanRecorder(capacity=0)
    with pytest.raises(ValueError):
        SpanRecorder(flush_last_k=0)


def test_ring_is_bounded_and_counts_drops():
    rec = SpanRecorder(capacity=4)
    for _ in range(10):
        sid = rec.begin("s", "phase", round_idx=0)
        rec.end(sid)
    assert len(rec._ring) == 4
    summary = rec.round_summary(0)
    # Every end aggregated (the summary is not bounded by the ring) and
    # the overflow is reported, never silent.
    assert summary["count"] == 10
    assert summary["dropped"] == 6
    # Unattached flushes are safe no-ops.
    assert rec.flush() == 0
    assert rec.flush_inflight("sigterm") == 0


def test_journal_lines_and_round_summary(tmp_path):
    rec = SpanRecorder(host_id=3, n_hosts=4)
    path = rec.attach(str(tmp_path), clock_offset_s=0.25,
                      clock_uncertainty_s=0.001)
    assert os.path.basename(path) == journal_filename(3) == "spans_3.jsonl"
    with rec.span("client_step", "phase", round_idx=7) as extra:
        extra["bytes"] = 123
    rec.event("round_fn", "compile", round_idx=7, seconds=0.5)
    rec.note_skew(7, "spill_skew_ms", 12.5)
    rec.note_skew(7, "spill_skew_ms", 8.0)  # max-aggregated: keeps 12.5
    rec.note_pending_skew("ckpt_skew_ms", 3.25)
    assert rec.flush() == 2
    rec.close()

    lines = [json.loads(l) for l in open(path)]
    header = lines[0]
    assert header["kind"] == "header"
    assert header["journal_version"] == 1
    assert header["host_id"] == 3 and header["n_hosts"] == 4
    assert header["clock_offset_s"] == 0.25
    assert header["clock_uncertainty_s"] == 0.001
    assert header["epoch_wall"] > 0 and header["epoch_mono"] >= 0
    kinds = [l["kind"] for l in lines[1:]]
    assert kinds == ["span", "event"]
    span = lines[1]
    assert span["name"] == "client_step" and span["cat"] == "phase"
    assert span["round"] == 7 and span["dur"] >= 0
    assert span["attrs"]["bytes"] == 123

    summary = rec.round_summary(7)
    assert summary["host_id"] == 3 and summary["hosts"] == 4
    assert summary["count"] == 2  # span + event
    assert summary["seconds_by_cat"]["phase"] >= 0
    assert summary["spill_skew_ms"] == 12.5
    # Pending (post-emit checkpoint barrier) skew merged in here.
    assert summary["ckpt_skew_ms"] == 3.25
    # ...and popped: the next round doesn't re-report it.
    assert "ckpt_skew_ms" not in rec.round_summary(8)


def test_eager_open_line_survives_kill(tmp_path, tt):
    """The hard-kill guarantee: an eager begin's open-line is on disk
    BEFORE the span body runs, so a SIGKILL'd process still names the
    span it died inside — no cleanup code required."""
    rec = SpanRecorder(host_id=0)
    path = rec.attach(str(tmp_path))
    rec.begin("finalize", "round", round_idx=2, eager=True)
    # No end(), no flush(), no close(): the process "dies" here. Emulate
    # the torn tail a kill mid-write can leave behind, too.
    with open(path, "a") as f:
        f.write('{"kind": "span", "truncated')

    j = tt.load_journal(path)
    assert len(j["unmatched_opens"]) == 1
    assert j["unmatched_opens"][0]["name"] == "finalize"
    assert j["unmatched_opens"][0]["round"] == 2
    summary = tt.summarize([j])
    dead = [p for p in summary["postmortem"] if p["kind"] == "died_inside"]
    assert [p["name"] for p in dead] == ["finalize"]


def test_flush_inflight_names_open_spans(tmp_path, tt):
    """The soft-failure path (SIGTERM / quorum rejection / crash):
    last-K completed spans + a flight marker + one inflight line per
    still-open span."""
    rec = SpanRecorder(host_id=1, flush_last_k=2)
    path = rec.attach(str(tmp_path))
    for i in range(5):
        sid = rec.begin(f"done_{i}", "phase", round_idx=0)
        rec.end(sid)
    rec.begin("spill_wait", "dcn_wait", round_idx=0, eager=True)
    n = rec.flush_inflight("quorum_rejected")
    # last-K completed (2) + flight marker + 1 inflight line.
    assert n == 4
    lines = [json.loads(l) for l in open(path)]
    flights = [l for l in lines if l["kind"] == "flight"]
    assert flights and flights[0]["reason"] == "quorum_rejected"
    inflight = [l for l in lines if l["kind"] == "inflight"]
    assert [l["name"] for l in inflight] == ["spill_wait"]
    assert inflight[0]["inflight"] is True
    # The ring drained: only the last-K completed spans made it out.
    spans = [l for l in lines if l["kind"] == "span"]
    assert [s["name"] for s in spans] == ["done_3", "done_4"]

    summary = tt.summarize([tt.load_journal(path)])
    got = [p for p in summary["postmortem"] if p["kind"] == "inflight"]
    assert [p["name"] for p in got] == ["spill_wait"]


def test_run_summary_totals(tmp_path):
    rec = SpanRecorder(host_id=0, n_hosts=2)
    rec.attach(str(tmp_path))
    for rnd in range(3):
        sid = rec.begin("spill_wait", "dcn_wait", round_idx=rnd)
        rec.end(sid)
        rec.note_skew(rnd, "spill_skew_ms", 10.0 * (rnd + 1))
        rec.round_summary(rnd)
        rec.flush()
    run = rec.run_summary()
    rec.close()
    assert run["count"] == 3
    assert run["spill_skew_ms_max"] == 30.0
    assert run["ckpt_skew_ms_max"] is None
    assert run["journal_path"] == os.path.join(
        str(tmp_path), "spans_0.jsonl"
    )


# ----------------------------------------------------------------------
# off-gate: span knobs must not move config_hash at their off defaults


def test_span_trace_off_gate_config_hash():
    base = config_hash(ExperimentConfig())
    # Off-gated knobs at non-default values change nothing while the
    # feature is off — the exact pre-feature hash (byte-identity
    # contract, utils/reporting.config_hash).
    assert config_hash(ExperimentConfig(span_buffer_size=7)) == base
    assert config_hash(ExperimentConfig(span_flush_last_k=2)) == base
    # span_dir is a non-program output path: hash-exempt even when on.
    on = config_hash(ExperimentConfig(span_trace="on"))
    assert on != base
    assert config_hash(
        ExperimentConfig(span_trace="on", span_dir="/tmp/elsewhere")
    ) == on


def test_span_config_validation():
    with pytest.raises(ValueError, match="span_trace"):
        ExperimentConfig(span_trace="banana").validate()
    with pytest.raises(ValueError, match="span_buffer_size"):
        ExperimentConfig(span_buffer_size=0).validate()
    with pytest.raises(ValueError, match="span_flush_last_k"):
        ExperimentConfig(span_flush_last_k=0).validate()


# ----------------------------------------------------------------------
# stitcher on synthetic two-host journals with a KNOWN clock offset


def _write_journal(path, host_id, epoch_wall, epoch_mono, offset,
                   lines):
    with open(path, "w") as f:
        f.write(json.dumps({
            "kind": "header", "journal_version": 1, "host_id": host_id,
            "n_hosts": 2, "pid": 1000 + host_id,
            "epoch_wall": epoch_wall, "epoch_mono": epoch_mono,
            "clock_offset_s": offset, "clock_uncertainty_s": 0.0002,
            "span_trace": "on",
        }) + "\n")
        for line in lines:
            f.write(json.dumps(line) + "\n")


@pytest.fixture()
def two_host_dir(tmp_path):
    """Two synthetic journals describing the SAME true timeline.

    Host 0: wall epoch 1000.0 at monotonic 50.0, offset 0 (it IS the
    reference). Host 1: its wall clock runs 3.5 s AHEAD of host 0's
    (offset +3.5) and its monotonic epoch is 20.0 at its wall 1003.5 —
    i.e. the same true instant as host 0's epoch. A true host-0-wall
    time T is therefore monotonic T-950 on host 0 and T-983.5 on host 1,
    and both must align back to T exactly.

    The round-0 spill barrier: host 1 arrives 0.4 s late, so host 0's
    wait span is 0.5 s long vs host 1's 0.1 s, and both record the
    measured 400 ms skew. Host 1 also carries 3x host 0's busy time
    (the critical-path signal) and an unmatched open (it "died" inside
    round 1's finalize).
    """

    def h0(t):  # host-0 monotonic stamp for true wall time t
        return (t - 1000.0) + 50.0

    def h1(t):  # host-1 monotonic stamp for the same true instant
        return (t + 3.5 - 1003.5) + 20.0

    _write_journal(
        tmp_path / "spans_0.jsonl", 0, 1000.0, 50.0, 0.0,
        [
            {"kind": "span", "id": 0, "name": "client_step",
             "cat": "phase", "round": 0, "t0": h0(1008.0), "dur": 1.0},
            {"kind": "span", "id": 1, "name": "spill_wait",
             "cat": "dcn_wait", "round": 0, "t0": h0(1009.5), "dur": 0.5,
             "attrs": {"skew_ms": 400.0}},
            {"kind": "span", "id": 2, "name": "spill_xfer", "cat": "dcn",
             "round": 0, "t0": h0(1010.0), "dur": 0.05,
             "attrs": {"bytes": 4096}},
            {"kind": "event", "name": "dispatch", "cat": "dispatch",
             "round": 0, "t": h0(1008.0)},
        ],
    )
    _write_journal(
        tmp_path / "spans_1.jsonl", 1, 1003.5, 20.0, 3.5,
        [
            {"kind": "span", "id": 0, "name": "client_step",
             "cat": "phase", "round": 0, "t0": h1(1006.5), "dur": 3.0},
            {"kind": "span", "id": 1, "name": "spill_wait",
             "cat": "dcn_wait", "round": 0, "t0": h1(1009.9), "dur": 0.1,
             "attrs": {"skew_ms": 400.0}},
            {"kind": "open", "id": 2, "name": "finalize", "cat": "round",
             "round": 1, "t0": h1(1010.2)},
        ],
    )
    return tmp_path


def test_stitcher_aligns_known_offset(two_host_dir, tt):
    paths = tt.find_journals([str(two_host_dir)])
    assert [os.path.basename(p) for p in paths] == [
        "spans_0.jsonl", "spans_1.jsonl"
    ]
    journals = [tt.load_journal(p) for p in paths]
    a0 = tt.aligner(journals[0]["header"])
    a1 = tt.aligner(journals[1]["header"])
    # Both hosts' stamps of the same true instant align identically
    # despite different monotonic epochs AND the 3.5 s wall offset.
    t0_wait_end = journals[0]["spans"][1]  # host 0 spill_wait
    t1_wait_end = journals[1]["spans"][1]  # host 1 spill_wait
    h0_arrival = a0(t0_wait_end["t0"])
    h1_arrival = a1(t1_wait_end["t0"])
    assert h0_arrival == pytest.approx(1009.5, abs=1e-9)
    assert h1_arrival == pytest.approx(1009.9, abs=1e-9)
    # Without the offset correction host 1 would land 3.5 s wrong.
    naive = (t1_wait_end["t0"] - journals[1]["header"]["epoch_mono"]) \
        + journals[1]["header"]["epoch_wall"]
    assert naive == pytest.approx(1013.4, abs=1e-9)


def test_stitcher_summary_attributes_straggler(two_host_dir, tt):
    journals = [tt.load_journal(p)
                for p in tt.find_journals([str(two_host_dir)])]
    summary = tt.summarize(journals)
    # Barrier skew: both hosts measured the same 400 ms allgather skew;
    # the slowest host is the one that waited LEAST (it arrived last).
    entry = summary["rounds"]["0"]["spill_wait"]
    assert entry["skew_ms"] == 400.0
    assert entry["slowest_host"] == 1
    assert entry["waits"] == {0: 0.5, 1: 0.1}
    # Critical-path share: host 1 carries 3.0 of the 4.05 busy seconds.
    t0, t1 = summary["totals"]["0"], summary["totals"]["1"]
    assert t0["busy_s"] == pytest.approx(1.05)
    assert t1["busy_s"] == pytest.approx(3.0)
    assert t1["critical_path_share"] == pytest.approx(3.0 / 4.05, abs=1e-3)
    assert t0["dcn_wait_s"] == pytest.approx(0.5)
    # Postmortem: host 1's unmatched open names the span it died inside.
    dead = [p for p in summary["postmortem"]
            if p["kind"] == "died_inside"]
    assert [(p["host_id"], p["name"]) for p in dead] == [(1, "finalize")]
    # --host filter keeps the summary single-host.
    only0 = tt.summarize(journals, host=0)
    assert [h["host_id"] for h in only0["hosts"]] == [0]
    assert only0["postmortem"] == []


def test_stitcher_chrome_trace(two_host_dir, tt):
    journals = [tt.load_journal(p)
                for p in tt.find_journals([str(two_host_dir)])]
    trace = tt.chrome_trace(journals)
    evs = trace["traceEvents"]
    spans = [e for e in evs if e["ph"] == "X" and not (
        e.get("args") or {}).get("inflight")]
    # Cross-host ordering on the merged timeline: host 1's client_step
    # starts 1.5 s before host 0's (true times 1006.5 vs 1008.0) even
    # though its RAW monotonic stamp is smaller by a different amount.
    cs = {e["pid"]: e["ts"] for e in spans if e["name"] == "client_step"}
    assert cs[0] - cs[1] == pytest.approx(1.5e6, abs=1.0)
    # The trace origin is the earliest aligned stamp -> ts >= 0 always.
    assert min(e["ts"] for e in evs if "ts" in e) >= 0
    # Host 1's unmatched open renders as an explicitly-marked inflight
    # slice so the kill moment is visible in perfetto.
    inflight = [e for e in evs if (e.get("args") or {}).get("inflight")]
    assert [e["name"] for e in inflight] == ["finalize"]
    # Instant events keep their scope marker.
    marks = [e for e in evs if e["ph"] == "i"]
    assert marks and all(e["s"] == "t" for e in marks)


def test_stitcher_cli(two_host_dir, tt, tmp_path):
    import subprocess

    out = tmp_path / "trace.json"
    proc = subprocess.run(
        [sys.executable, _STITCHER, str(two_host_dir),
         "--out", str(out), "--json"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    summary = json.loads(proc.stdout)
    assert summary["rounds"]["0"]["spill_wait"]["slowest_host"] == 1
    trace = json.loads(out.read_text())
    assert trace["traceEvents"]
    # No journals -> exit 2, not a stack trace.
    empty = tmp_path / "empty"
    empty.mkdir()
    proc = subprocess.run(
        [sys.executable, _STITCHER, str(empty)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 2


def test_flight_marker_names_errored_span(tmp_path, tt):
    """A crash that unwinds through span context managers closes them
    before the flight flush — the flight marker must still name the
    innermost span the exception escaped from."""
    rec = SpanRecorder(host_id=0)
    path = rec.attach(str(tmp_path))
    with pytest.raises(RuntimeError):
        with rec.span("finalize", "round", round_idx=3):
            with rec.span("spill_xfer", "dcn", round_idx=3):
                raise RuntimeError("peer died")
    rec.flush_inflight("crash")
    lines = [json.loads(ln) for ln in open(path)]
    flight = [ln for ln in lines if ln["kind"] == "flight"][0]
    assert flight["in_span"] == {"name": "spill_xfer", "cat": "dcn",
                                 "error": "RuntimeError", "round": 3}
    summary = tt.summarize([tt.load_journal(path)])
    fl = [p for p in summary["postmortem"] if p["kind"] == "flight"][0]
    assert fl["name"] == "spill_xfer" and fl["round"] == 3
    assert fl["error"] == "RuntimeError"
    assert "spill_xfer" in tt.render_text(summary)
