"""telemetry/ subsystem: recompile counter, phase timing, records, report.

Acceptance pins (ISSUE 3): a deliberately shape-unstable run trips the
recompilation counter with the offending function name surfaced in the
log; a stable run reports 0 post-warmup compiles; report_run renders a
real run's artifacts dir; telemetry_level='off' leaves metrics.jsonl
records in the legacy (v1) layout.
"""

import dataclasses
import glob
import json
import logging
import os

import jax
import jax.numpy as jnp
import pytest

from distributed_learning_simulator_tpu.telemetry import (
    NullPhaseTimer,
    PhaseTimer,
    RecompileMonitor,
    device_memory_stats,
    hbm_limit_bytes,
    log_round_compiles,
    make_phase_timer,
    peak_hbm_bytes,
)
from distributed_learning_simulator_tpu.utils.reporting import (
    METRICS_SCHEMA_VERSION,
    build_round_record,
    config_hash,
)

# ---------------------------------------------------------------- recompile


def test_recompile_monitor_shape_unstable_run():
    """A deliberately shape-unstable jitted function trips the counter —
    with its name — while the cached-shape call counts zero."""
    mon = RecompileMonitor()
    with mon:
        @jax.jit
        def wobbly_step(x):
            return x * 2.0

        wobbly_step(jnp.ones(8)).block_until_ready()
        mon.attribute(0)  # warmup: first shape compiles
        wobbly_step(jnp.ones(8)).block_until_ready()
        mon.attribute(1)  # cached: no compile
        wobbly_step(jnp.ones(9)).block_until_ready()  # NEW shape: recompile
        mon.attribute(2)
    warmup, stable, unstable = mon.take(0), mon.take(1), mon.take(2)
    assert any("wobbly_step" in name for name, _ in warmup)
    assert stable == []
    assert any("wobbly_step" in name for name, _ in unstable)
    # take() pops: a second read is empty.
    assert mon.take(2) == []


def test_recompile_monitor_restores_global_state():
    """start/stop must restore jax_log_compiles and the compile loggers'
    propagation — the monitor owns process-global state only while
    active."""
    dispatch = logging.getLogger("jax._src.dispatch")
    before_flag = bool(jax.config.jax_log_compiles)
    before_prop = dispatch.propagate
    before_handlers = list(dispatch.handlers)
    mon = RecompileMonitor().start()
    assert bool(jax.config.jax_log_compiles) is True
    assert dispatch.propagate is False
    mon.stop()
    assert bool(jax.config.jax_log_compiles) == before_flag
    assert dispatch.propagate == before_prop
    assert dispatch.handlers == before_handlers
    mon.stop()  # idempotent


def test_log_round_compiles_surfaces_offender_name():
    """Post-warmup compiles WARN with the offending function name; warmup
    compiles stay at INFO."""
    logger = logging.getLogger("test_telemetry_compiles")
    logger.propagate = True
    records = []

    class _Cap(logging.Handler):
        def emit(self, r):
            records.append(r)

    h = _Cap()
    logger.addHandler(h)
    logger.setLevel(logging.INFO)
    try:
        n = log_round_compiles(
            logger, 7, [("round_fn", 12.5)], warmup=False
        )
        assert n == 1
        warn = [r for r in records if r.levelno == logging.WARNING]
        assert len(warn) == 1
        msg = warn[0].getMessage()
        assert "round_fn" in msg and "round 7" in msg
        assert "AFTER warmup" in msg
        records.clear()
        log_round_compiles(logger, 0, [("round_fn", 12.5)], warmup=True)
        assert all(r.levelno == logging.INFO for r in records)
        assert log_round_compiles(logger, 3, [], warmup=False) == 0
    finally:
        logger.removeHandler(h)


# ------------------------------------------------------------- phase timer


def test_phase_timer_accumulates_and_pops():
    t = PhaseTimer(fence=False)
    with t.phase(0, "client_step"):
        pass
    with t.phase(0, "client_step"):  # same phase accumulates
        pass
    with t.phase(0, "eval"):
        pass
    with t.phase(1, "client_step"):
        pass
    r0 = t.take(0)
    assert set(r0) == {"client_step", "eval"}
    assert all(v >= 0.0 for v in r0.values())
    assert t.take(0) == {}  # popped
    assert set(t.take(1)) == {"client_step"}


def test_phase_timer_fences_on_device_value():
    """With fence=True the phase blocks on the parked output before the
    clock stops (block_until_ready on the fenced tree must not raise on
    nested containers)."""
    t = PhaseTimer(fence=True)
    with t.phase(0, "client_step") as ph:
        out = jax.jit(lambda x: x * 3.0)(jnp.ones((64, 64)))
        ph.fence((out, {"aux": out}))
    assert t.take(0)["client_step"] > 0.0


def test_make_phase_timer_levels():
    assert isinstance(make_phase_timer("off"), NullPhaseTimer)
    assert not make_phase_timer("off").enabled
    basic = make_phase_timer("basic")
    assert isinstance(basic, PhaseTimer) and not basic._fence
    assert make_phase_timer("detailed")._fence
    null = make_phase_timer("off")
    with null.phase(0, "x") as ph:
        ph.fence(jnp.ones(2))
    assert null.take(0) is None


# ------------------------------------------------------------ memory probe


def test_memory_probe_graceful_on_cpu():
    """CPU reports no memory stats: every helper must return None, never
    raise (the graceful-None contract the watermark/budget callers
    rely on)."""
    stats = device_memory_stats()
    if stats is None:  # CPU backend (the CI case)
        assert peak_hbm_bytes() is None
        assert hbm_limit_bytes() is None
    else:  # a real accelerator: values are positive ints when present
        for v in (peak_hbm_bytes(), hbm_limit_bytes()):
            assert v is None or (isinstance(v, int) and v > 0)


# ----------------------------------------------------------- record builder


def test_build_round_record_off_is_identity():
    """telemetry=None returns the base record UNTOUCHED — the
    byte-identical-at-'off' guarantee reduces to this plus the
    integration test below."""
    base = {"round": 3, "test_accuracy": 0.5, "round_seconds": 1.0}
    out = build_round_record(base, None)
    assert out is base  # not even a copy: nothing can have changed
    assert json.dumps(out) == json.dumps(base)


def test_build_round_record_v2_layout():
    """A telemetry-only record stays at the v2 stamp byte-for-byte —
    the v3 layout exists only when a client_stats sub-object is present
    (tests/test_client_stats.py, tests/test_metrics_schema.py)."""
    base = {"round": 3, "test_accuracy": 0.5}
    tel = {"phase_seconds": {"eval": 0.1}, "compiles": 0}
    out = build_round_record(base, tel)
    assert out is not base and "telemetry" not in base
    assert out["schema_version"] == 2
    assert out["telemetry"] == tel
    assert out["round"] == 3
    v3 = build_round_record(base, tel, {"n_clients": 4})
    assert v3["schema_version"] == 3
    assert v3["client_stats"] == {"n_clients": 4}
    v4 = build_round_record(base, tel, None, {"on_time": 4})
    assert v4["schema_version"] == 4
    assert v4["async"] == {"on_time": 4}
    v5 = build_round_record(base, tel, None, None, {"h2d_bytes": 8})
    # Lowest-version stamping: a stream-carrying record stays v5 even
    # though the CURRENT top version has moved on (v6 costmodel, v7
    # valuation — their own tests pin those stamps).
    assert v5["schema_version"] == 5 <= METRICS_SCHEMA_VERSION
    assert v5["stream"] == {"h2d_bytes": 8}


def test_config_hash_tracks_program_knobs_only(tiny_config):
    h = config_hash(tiny_config)
    assert len(h) == 12
    same = dataclasses.replace(
        tiny_config, round=99, log_level="DEBUG",
        checkpoint_dir="/tmp/x", profile_dir="/tmp/y",
    )
    assert config_hash(same) == h
    assert config_hash(
        dataclasses.replace(tiny_config, model_name="lenet5")
    ) != h
    assert config_hash(
        dataclasses.replace(tiny_config, failure_mode="dropout")
    ) != h
    # 'detailed' fences every phase (not a comparable cost point), so
    # telemetry_level is a program-defining knob for the hash.
    assert config_hash(
        dataclasses.replace(tiny_config, telemetry_level="detailed")
    ) != h


def test_config_validates_telemetry_level(tiny_config):
    dataclasses.replace(tiny_config, telemetry_level="detailed").validate()
    with pytest.raises(ValueError, match="telemetry_level"):
        dataclasses.replace(tiny_config, telemetry_level="verbose").validate()


# ------------------------------------------------------------- integration


def _run_with_artifacts(cfg):
    from distributed_learning_simulator_tpu.simulator import run_simulation

    result = run_simulation(cfg)
    metrics = glob.glob(
        os.path.join(cfg.log_root, "**", "metrics.jsonl"), recursive=True
    )
    assert len(metrics) == 1
    with open(metrics[0]) as f:
        records = [json.loads(line) for line in f]
    return result, records, os.path.dirname(metrics[0])


def test_simulator_telemetry_stable_run(tiny_config, tmp_path):
    """A shape-stable vmap run: warmup compiles land in the first round's
    record, every later round reports 0 compiles, phase timings cover the
    round loop's regions, and the result dict's post_warmup_compiles
    gate is 0."""
    cfg = dataclasses.replace(
        tiny_config, round=3, telemetry_level="basic",
        compilation_cache_dir=None, log_root=str(tmp_path / "log"),
    )
    result, records, artifacts = _run_with_artifacts(cfg)
    assert result["post_warmup_compiles"] == 0
    assert result["telemetry_level"] == "basic"
    assert len(records) == 3
    # client_stats off (the default): telemetry-only records keep v2.
    assert all(r["schema_version"] == 2 for r in records)
    warmup = records[0]["telemetry"]
    assert warmup["compiles"] > 0
    assert any("round_fn" in n for n in warmup["compiled"])
    for r in records[1:]:
        assert r["telemetry"]["compiles"] == 0
        assert "compiled" not in r["telemetry"]
    for r in records:
        phases = r["telemetry"]["phase_seconds"]
        assert {"client_step", "eval", "host_sync", "post_round"} <= set(
            phases
        )
        assert all(v >= 0.0 for v in phases.values())

    # Offline reporter over the real artifacts dir (acceptance pin).
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "report_run",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "report_run.py"),
    )
    report_run = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(report_run)
    summary = report_run.summarize_run(
        report_run.load_metrics(artifacts)
    )
    assert summary["rounds"] == 3
    assert summary["compiles"]["post_warmup"] == 0
    assert summary["compiles"]["warmup"] > 0
    assert summary["final_accuracy"] == records[-1]["test_accuracy"]
    assert set(summary["phases"]) >= {"client_step", "eval"}
    assert summary["rejected_rounds"]["count"] == 0
    rendered = "\n".join(report_run.render_summary(summary))
    assert "post-warmup recompiles: none" in rendered
    assert "client_step" in rendered and "accuracy" in rendered


def test_simulator_telemetry_off_keeps_v1_records(tiny_config, tmp_path):
    """telemetry_level='off' (the default) emits the legacy v1 record —
    exactly the pre-telemetry key set, no schema_version, no telemetry
    sub-object."""
    cfg = dataclasses.replace(
        tiny_config, round=2, log_root=str(tmp_path / "log"),
    )
    assert cfg.telemetry_level == "off"
    result, records, _ = _run_with_artifacts(cfg)
    assert result["post_warmup_compiles"] is None
    for r in records:
        assert set(r) == {
            "round", "test_accuracy", "test_loss", "mean_client_loss",
            "round_seconds",
        }


def test_threaded_telemetry_basic(tmp_path):
    """The threaded oracle reports through the same builder: schema-v2
    records with server-side phase timings, and a run-level compile
    count in the result dict."""
    from distributed_learning_simulator_tpu.config import ExperimentConfig
    from distributed_learning_simulator_tpu.simulator import run_simulation

    cfg = ExperimentConfig(
        dataset_name="synthetic", model_name="mlp",
        distributed_algorithm="fed", worker_number=2, round=2, epoch=1,
        learning_rate=0.1, batch_size=32, n_train=128, n_test=64,
        log_level="WARNING", dataset_args={"difficulty": 0.5},
        execution_mode="threaded", telemetry_level="basic",
        compilation_cache_dir=None, log_root=str(tmp_path / "log"),
    )
    result = run_simulation(cfg)
    assert result["xla_compiles"] > 0
    assert result["telemetry_level"] == "basic"
    metrics = glob.glob(
        os.path.join(cfg.log_root, "**", "metrics.jsonl"), recursive=True
    )
    with open(metrics[0]) as f:
        records = [json.loads(line) for line in f]
    assert len(records) == 2
    for r in records:
        assert r["schema_version"] == 2
        assert {"aggregate", "eval", "post_round"} <= set(
            r["telemetry"]["phase_seconds"]
        )
