"""Native C++ runtime: queue semantics, pool execution, threaded mode.

Covers the reference's L1 runtime surface contract (SURVEY §2.4 rows 1-3:
ThreadPool, blocking TaskQueue with worker_fun, RepeatedResult broadcast)
as real unit tests — the reference has none.
"""

import dataclasses
import threading
import time

import numpy as np
import pytest

from distributed_learning_simulator_tpu.runtime.native import (
    NativeTaskQueue,
    NativeThreadPool,
    RepeatedResult,
    native_available,
)

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native runtime not buildable"
)


def test_queue_task_roundtrip():
    q = NativeTaskQueue()
    q.add_task({"worker": 1, "payload": [1, 2, 3]})
    assert q.get_task() == {"worker": 1, "payload": [1, 2, 3]}
    q.stop()


def test_queue_broadcast():
    """RepeatedResult: one put_result(copies=N) feeds N get_result calls."""
    q = NativeTaskQueue()
    q.put_result("params", copies=3)
    assert [q.get_result() for _ in range(3)] == ["params"] * 3
    q.stop()


def test_queue_blocking_get_result():
    q = NativeTaskQueue()
    got = []

    def consumer():
        got.append(q.get_result())

    t = threading.Thread(target=consumer)
    t.start()
    time.sleep(0.1)
    assert got == []  # still blocked
    q.put_result(42)
    t.join(timeout=5)
    assert got == [42]
    q.stop()


def test_queue_stop_unblocks_and_raises():
    q = NativeTaskQueue()
    errors = []

    def consumer():
        try:
            q.get_result()
        except RuntimeError as e:
            errors.append(str(e))

    t = threading.Thread(target=consumer)
    t.start()
    time.sleep(0.05)
    q.stop()
    t.join(timeout=5)
    assert errors == ["queue is stopped"]
    with pytest.raises(RuntimeError):
        q.add_task(1)


def test_queue_worker_fun_barrier():
    """worker_fun contract: None until all N arrive, then broadcast
    (reference servers/server.py:11-17 + fed_server.py:68-91)."""
    n = 4

    class Server:
        def __init__(self):
            self.buffer = []

        def worker_fun(self, task, extra):
            self.buffer.append(task)
            if len(self.buffer) < n:
                return None
            total = sum(self.buffer)
            self.buffer.clear()
            return RepeatedResult(total, n)

    server = Server()
    q = NativeTaskQueue(worker_fun=server.worker_fun)
    for i in range(n):
        q.add_task(i + 1)
    results = [q.get_result() for _ in range(n)]
    assert results == [10] * n
    q.stop()


def test_pool_executes_all_tasks():
    pool = NativeThreadPool(4)
    seen = []
    lock = threading.Lock()

    def work(i):
        with lock:
            seen.append(i)
        return i * i

    ids = [pool.exec(work, i) for i in range(20)]
    pool.join_pending()
    results = pool.results()
    assert sorted(seen) == list(range(20))
    assert all(results[tid] == i * i for tid, i in zip(ids, range(20)))
    pool.stop()


def test_pool_propagates_errors():
    pool = NativeThreadPool(2)

    def boom():
        raise ValueError("client exploded")

    pool.exec(boom)
    pool.join_pending()
    with pytest.raises(ValueError, match="client exploded"):
        pool.results()
    pool.stop()


def test_threaded_simulation_learns(tiny_config):
    """Thread-per-client mode (native queue + pool) reaches the same
    learning behavior as the vmap fast path."""
    from distributed_learning_simulator_tpu.execution.threaded import (
        run_threaded_simulation,
    )

    cfg = dataclasses.replace(tiny_config, round=3)
    res = run_threaded_simulation(cfg, setup_logging=False)
    assert len(res["history"]) == 3
    accs = [h["test_accuracy"] for h in res["history"]]
    assert accs[-1] > 0.2
    assert accs[-1] > accs[0] - 0.05


def test_threaded_median_aggregation(tiny_config):
    """The thread-per-client server honors the robust aggregation config."""
    from distributed_learning_simulator_tpu.execution.threaded import (
        run_threaded_simulation,
    )

    cfg = dataclasses.replace(tiny_config, round=2, aggregation="median")
    res = run_threaded_simulation(cfg, setup_logging=False)
    import numpy as np

    assert all(np.isfinite(h["test_loss"]) for h in res["history"])


def test_threaded_mode_via_config_flag(tiny_config):
    """execution_mode='threaded' routes run_simulation (hence every entry
    point) through the native-runtime thread-per-client path."""
    from distributed_learning_simulator_tpu.simulator import run_simulation

    cfg = dataclasses.replace(tiny_config, round=2,
                              execution_mode="threaded")
    res = run_simulation(cfg, setup_logging=False)
    assert len(res["history"]) == 2


def test_threaded_rejects_unknown_algorithms(tiny_config):
    from distributed_learning_simulator_tpu.execution.threaded import (
        run_threaded_simulation,
    )

    cfg = dataclasses.replace(tiny_config, distributed_algorithm="bogus")
    with pytest.raises(ValueError, match="threaded"):
        run_threaded_simulation(cfg)


def test_threaded_exact_shapley_rejects_large_cohort_up_front(tiny_config):
    """worker_number > 16 with exact Shapley must fail BEFORE any threads
    spawn (ADVICE r3: previously it surfaced only inside the round-0 server
    callback, after a full round of local training)."""
    from distributed_learning_simulator_tpu.execution.threaded import (
        run_threaded_simulation,
    )

    cfg = dataclasses.replace(
        tiny_config, distributed_algorithm="multiround_shapley_value",
        worker_number=17,
    )
    with pytest.raises(ValueError, match="2\\^N"):
        run_threaded_simulation(cfg)


def test_threaded_shapley_scores_clients(tiny_config):
    """Shapley through the queue architecture (reference extends the
    queue-owning FedServer for both Shapley servers): per-round SVs in the
    history, produced by the SAME strategy objects as the vmap path."""
    from distributed_learning_simulator_tpu.execution.threaded import (
        run_threaded_simulation,
    )

    cfg = dataclasses.replace(
        tiny_config, distributed_algorithm="multiround_shapley_value",
        round=2,
    )
    res = run_threaded_simulation(cfg, setup_logging=False)
    assert len(res["history"]) == 2
    for h in res["history"]:
        sv = h["shapley_values"]
        assert set(sv) == set(range(cfg.worker_number))
        assert all(abs(v) < 10 for v in sv.values())


def test_threaded_gtg_matches_vmap_statistically(tiny_config):
    """Differential oracle for the 5th family: GTG through the queue vs
    the vmap path — accuracy trajectories agree statistically and both
    produce finite per-round SVs."""
    from distributed_learning_simulator_tpu.execution.threaded import (
        run_threaded_simulation,
    )
    from distributed_learning_simulator_tpu.simulator import run_simulation

    cfg = dataclasses.replace(
        tiny_config, distributed_algorithm="GTG_shapley_value", round=3,
    )
    threaded = run_threaded_simulation(cfg, setup_logging=False)
    vmapped = run_simulation(cfg, setup_logging=False)
    a_t = threaded["history"][-1]["test_accuracy"]
    a_v = vmapped["history"][-1]["test_accuracy"]
    assert abs(a_t - a_v) < 0.15, (a_t, a_v)
    import numpy as np

    for res in (threaded, vmapped):
        sv = res["history"][0]["shapley_values"]
        assert all(np.isfinite(v) for v in sv.values())


def test_threaded_multiround_shapley_matches_vmap(tiny_config, tmp_path):
    """Differential oracle for the 5th family (exact multi-round Shapley):

    * trajectories agree statistically (batch orders differ between modes,
      so trained client params are not bitwise equal);
    * the SV COMPUTATION is exact on both paths: each mode's per-round SVs
      are recomputed in this test from that mode's own logged subset-utility
      table (metric_<round>.pkl, the reference's artifact) with an
      INDEPENDENT permutation-form Shapley implementation, and must match
      to float tolerance — plus the efficiency axiom
      sum_i SV_i = U(grand) - U(empty).
    """
    import glob
    import itertools
    import pickle as pkl

    from distributed_learning_simulator_tpu.execution.threaded import (
        run_threaded_simulation,
    )
    from distributed_learning_simulator_tpu.simulator import run_simulation

    def perm_shapley(utilities, n):
        """Independent exact SV: average marginal over all n! orderings."""
        sv = np.zeros(n)
        perms = list(itertools.permutations(range(n)))
        for perm in perms:
            pre = frozenset()
            for i in perm:
                u_pre = utilities[tuple(sorted(pre))]
                u_post = utilities[tuple(sorted(pre | {i}))]
                sv[i] += u_post - u_pre
                pre = pre | {i}
        return sv / len(perms)

    results = {}
    for mode, runner in (("threaded", run_threaded_simulation),
                         ("vmap", run_simulation)):
        cfg = dataclasses.replace(
            tiny_config, distributed_algorithm="multiround_shapley_value",
            round=2, log_root=str(tmp_path / mode), log_level="WARNING",
        )
        res = runner(cfg, setup_logging=True)
        pickles = sorted(glob.glob(
            str(tmp_path / mode / "**" / "metric_*.pkl"), recursive=True
        ))
        assert len(pickles) == 2, pickles
        for path in pickles:
            round_idx = int(path.rsplit("_", 1)[1].split(".")[0])
            with open(path, "rb") as f:
                utilities = pkl.load(f)
            assert len(utilities) == 2 ** cfg.worker_number
            sv_logged = res["history"][round_idx]["shapley_values"]
            sv_ref = perm_shapley(utilities, cfg.worker_number)
            np.testing.assert_allclose(
                [sv_logged[i] for i in range(cfg.worker_number)], sv_ref,
                rtol=1e-8, atol=1e-10,
            )
            grand = utilities[tuple(range(cfg.worker_number))]
            empty = utilities[()]
            np.testing.assert_allclose(
                sum(sv_logged.values()), grand - empty, rtol=1e-6, atol=1e-9
            )
        results[mode] = res
    a_t = results["threaded"]["history"][-1]["test_accuracy"]
    a_v = results["vmap"]["history"][-1]["test_accuracy"]
    assert abs(a_t - a_v) < 0.15, (a_t, a_v)


def test_threaded_rejects_bf16_local_state(tiny_config):
    """The bf16/SR local state lives in the vmap engine; threaded mode must
    reject it rather than silently run f32 (oracle same-semantics claim)."""
    from distributed_learning_simulator_tpu.execution.threaded import (
        run_threaded_simulation,
    )

    cfg = dataclasses.replace(tiny_config, local_compute_dtype="bfloat16")
    with pytest.raises(ValueError, match="local_compute_dtype"):
        run_threaded_simulation(cfg)


def test_threaded_rejects_client_eval(tiny_config):
    """client_eval telemetry is produced by the vmap path's stacked params;
    threaded mode must reject rather than silently drop it."""
    from distributed_learning_simulator_tpu.execution.threaded import (
        run_threaded_simulation,
    )

    cfg = dataclasses.replace(tiny_config, client_eval=True)
    with pytest.raises(ValueError, match="client_eval"):
        run_threaded_simulation(cfg)


def test_threaded_rejects_multihost_directly(tiny_config):
    """The multihost rejection must live in run_threaded_simulation itself
    (a documented programmatic entry point), not only in run_simulation's
    dispatch — else each process silently runs a full independent sim."""
    from distributed_learning_simulator_tpu.execution.threaded import (
        run_threaded_simulation,
    )

    cfg = dataclasses.replace(tiny_config, multihost=True)
    with pytest.raises(ValueError, match="multihost"):
        run_threaded_simulation(cfg)


def test_threaded_sign_sgd_learns(tiny_config):
    """Per-step sign-vote sync over the native queue (the reference's
    finest-grained communication pattern, sign_sgd_worker.py:44-47)."""
    from distributed_learning_simulator_tpu.execution.threaded import (
        run_threaded_simulation,
    )

    cfg = dataclasses.replace(tiny_config, distributed_algorithm="sign_SGD",
                              learning_rate=0.01, round=3)
    res = run_threaded_simulation(cfg, setup_logging=False)
    assert len(res["history"]) == 3
    accs = [h["test_accuracy"] for h in res["history"]]
    assert accs[-1] > 0.25
    assert res["history"][-1]["uplink_compression_ratio"] > 30
    assert res["history"][-1]["sync_steps"] >= 1


def test_threaded_worker_failure_raises_not_hangs(tiny_config, monkeypatch):
    """If one worker dies, the run must re-raise its error promptly instead
    of deadlocking on a barrier that can never fill (the error-aware wait
    stops the rendezvous queues to unblock the surviving workers)."""
    import distributed_learning_simulator_tpu.execution.threaded as thr

    original = thr.ThreadedWorker.train

    def sabotaged(self):
        if self.worker_id == 2:
            raise RuntimeError("client exploded mid-round")
        return original(self)

    monkeypatch.setattr(thr.ThreadedWorker, "train", sabotaged)
    cfg = dataclasses.replace(tiny_config, round=3)
    import time as _time

    t0 = _time.perf_counter()
    with pytest.raises(RuntimeError, match="client exploded"):
        thr.run_threaded_simulation(cfg, setup_logging=False)
    assert _time.perf_counter() - t0 < 60  # promptly, not a hang


def test_threaded_server_final_callback_failure_raises(tiny_config,
                                                       monkeypatch):
    """A failure in the LAST round's server callback happens after every
    worker has exited (workers end on add_task), so it only surfaces once
    stop() joins the serve thread — the run must still re-raise it rather
    than return success with the final record missing."""
    import distributed_learning_simulator_tpu.execution.threaded as thr

    cfg = dataclasses.replace(tiny_config, round=2)
    original = thr.ThreadedServer._process_worker_data
    total_uploads = cfg.round * cfg.worker_number
    calls = {"n": 0}

    def sabotaged(self, data, extra_args):
        calls["n"] += 1
        if calls["n"] == total_uploads:  # the barrier-completing last upload
            raise RuntimeError("final eval exploded")
        return original(self, data, extra_args)

    monkeypatch.setattr(thr.ThreadedServer, "_process_worker_data",
                        sabotaged)
    with pytest.raises(RuntimeError, match="final eval exploded"):
        thr.run_threaded_simulation(cfg, setup_logging=False)


def test_threaded_fed_matches_vmap(tiny_config):
    """Differential oracle for FedAvg: thread-per-client over the native
    queue vs the fused vmap round program must agree statistically
    (batch orders differ, so not bitwise)."""
    from distributed_learning_simulator_tpu.execution.threaded import (
        run_threaded_simulation,
    )
    from distributed_learning_simulator_tpu.simulator import run_simulation

    cfg = dataclasses.replace(tiny_config, round=4)
    threaded = run_threaded_simulation(cfg, setup_logging=False)
    vmapped = run_simulation(cfg, setup_logging=False)
    a_t = threaded["history"][-1]["test_accuracy"]
    a_v = vmapped["history"][-1]["test_accuracy"]
    assert abs(a_t - a_v) < 0.15, (a_t, a_v)


def test_threaded_fed_quant_learns(tiny_config):
    """fed_quant through the queue architecture: QAT local training, a
    genuinely quantized uplink payload, dequantize-aggregate-requantize at
    the server (reference servers/fed_quant_server.py:25-50)."""
    from distributed_learning_simulator_tpu.execution.threaded import (
        run_threaded_simulation,
    )

    cfg = dataclasses.replace(
        tiny_config, distributed_algorithm="fed_quant", round=3
    )
    res = run_threaded_simulation(cfg, setup_logging=False)
    assert len(res["history"]) == 3
    assert res["history"][-1]["test_accuracy"] > 0.4
    # 8-bit exchange: ~4x smaller than f32 params.
    assert res["history"][-1]["uplink_compression_ratio"] > 3.0


def test_threaded_fed_quant_matches_vmap(tiny_config):
    """Differential oracle for the quantized exchange path: thread-per-
    client (quantized uplink decoded server-side) vs the fused vmap
    quantize->dequantize round program must agree statistically."""
    from distributed_learning_simulator_tpu.execution.threaded import (
        run_threaded_simulation,
    )
    from distributed_learning_simulator_tpu.simulator import run_simulation

    cfg = dataclasses.replace(
        tiny_config, distributed_algorithm="fed_quant", round=4,
        client_eval=False,
    )
    threaded = run_threaded_simulation(cfg, setup_logging=False)
    vmapped = run_simulation(cfg, setup_logging=False)
    a_t = threaded["history"][-1]["test_accuracy"]
    a_v = vmapped["history"][-1]["test_accuracy"]
    assert abs(a_t - a_v) < 0.15, (a_t, a_v)
    # Same analytic compression telemetry on both paths.
    r_t = threaded["history"][-1]["uplink_compression_ratio"]
    r_v = vmapped["history"][-1]["uplink_compression_ratio"]
    assert abs(r_t - r_v) < 1e-6, (r_t, r_v)


def test_threaded_sign_sgd_many_steps_no_deadlock(tiny_config):
    """Scheduling-stress regression for the per-worker downlink routing:
    many per-step rendezvous across 8 workers must complete (the shared
    N-copy result pool this replaced could deadlock via copy stealing)."""
    from distributed_learning_simulator_tpu.execution.threaded import (
        run_threaded_simulation,
    )

    cfg = dataclasses.replace(
        tiny_config, distributed_algorithm="sign_SGD", worker_number=8,
        learning_rate=0.01, round=3, epoch=2, batch_size=8,
    )
    res = run_threaded_simulation(cfg, setup_logging=False)
    assert len(res["history"]) == 3
    assert res["history"][-1]["sync_steps"] >= 8  # many rendezvous ran


def test_threaded_sign_sgd_matches_vmap(tiny_config):
    """Differential oracle: thread-per-client per-step voting vs the fused
    in-program vote must agree statistically (batch orders differ)."""
    from distributed_learning_simulator_tpu.execution.threaded import (
        run_threaded_simulation,
    )
    from distributed_learning_simulator_tpu.simulator import run_simulation

    cfg = dataclasses.replace(tiny_config, distributed_algorithm="sign_SGD",
                              learning_rate=0.01, round=3)
    threaded = run_threaded_simulation(cfg, setup_logging=False)
    vmapped = run_simulation(cfg, setup_logging=False)
    a_t = threaded["history"][-1]["test_accuracy"]
    a_v = vmapped["history"][-1]["test_accuracy"]
    assert abs(a_t - a_v) < 0.15, (a_t, a_v)


def test_threaded_sign_sgd_momentum_matches_vmap(tiny_config):
    """Same differential check with momentum: exercises the torch buf=grad
    first-step semantics on both paths."""
    from distributed_learning_simulator_tpu.execution.threaded import (
        run_threaded_simulation,
    )
    from distributed_learning_simulator_tpu.simulator import run_simulation

    cfg = dataclasses.replace(tiny_config, distributed_algorithm="sign_SGD",
                              learning_rate=0.01, momentum=0.9, round=2)
    threaded = run_threaded_simulation(cfg, setup_logging=False)
    vmapped = run_simulation(cfg, setup_logging=False)
    a_t = threaded["history"][-1]["test_accuracy"]
    a_v = vmapped["history"][-1]["test_accuracy"]
    assert abs(a_t - a_v) < 0.15, (a_t, a_v)


def test_threaded_server_callback_failure_raises_not_hangs(tiny_config,
                                                           monkeypatch):
    """A server-callback failure (eval OOM, full disk) must tear the
    rendezvous down and re-raise the ORIGINAL error — not kill the serve
    thread silently and leave the coordinator spinning forever."""
    import time as _time

    import distributed_learning_simulator_tpu.execution.threaded as thr

    original = thr.ThreadedServer._process_worker_data
    calls = {"n": 0}

    def sabotaged(self, data, extra_args):
        calls["n"] += 1
        if calls["n"] == 3:
            raise RuntimeError("server eval exploded")
        return original(self, data, extra_args)

    monkeypatch.setattr(thr.ThreadedServer, "_process_worker_data",
                        sabotaged)
    cfg = dataclasses.replace(tiny_config, round=3)
    t0 = _time.perf_counter()
    with pytest.raises(RuntimeError, match="server eval exploded"):
        thr.run_threaded_simulation(cfg, setup_logging=False)
    assert _time.perf_counter() - t0 < 60
