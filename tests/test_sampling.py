"""Participation sampling (config.participation_sampler; ops/sampling.py).

The contract under test, per mode:

* ``exact`` (default) is THE pre-feature draw: the shared helper returns
  ``jax.random.choice(replace=False)`` bit-for-bit, run histories and
  ``config_hash`` are unchanged for pre-feature configs, and the
  streamed host replay still equals the in-program draw.
* ``hashed`` is a NEW O(cohort) mode: statistically uniform (chi-square
  over many rounds at small N), duplicate-free, deterministic from the
  round key, and — the load-bearing property — the jitted in-program
  draw and the numpy host mirror select IDENTICAL indices, which is
  what keeps streamed residency bit-identical to resident under the new
  sampler without any O(N) host work.
"""

import dataclasses

import jax
import numpy as np
import pytest

from distributed_learning_simulator_tpu.config import ExperimentConfig
from distributed_learning_simulator_tpu.algorithms.fedavg import (
    _hashed_part_key_words,
    round_key_splits,
)
from distributed_learning_simulator_tpu.ops.sampling import (
    draw_cohort,
    draw_cohort_host,
    hashed_cohort,
    hashed_cohort_np,
    overdraw_block,
    threefry2x32,
)
from distributed_learning_simulator_tpu.simulator import run_simulation
from distributed_learning_simulator_tpu.utils.reporting import config_hash


def _part_key(i: int = 0):
    return jax.random.split(jax.random.fold_in(jax.random.key(42), i))[0]


def _key_words_np(part_key) -> np.ndarray:
    return np.asarray(jax.random.key_data(part_key)).ravel()


# ------------------------------------------------------------- validation


def test_config_validation():
    ExperimentConfig(participation_sampler="hashed").validate()
    ExperimentConfig(participation_sampler="exact").validate()
    with pytest.raises(ValueError, match="participation_sampler"):
        ExperimentConfig(participation_sampler="reservoir").validate()


def test_default_is_exact():
    assert ExperimentConfig().participation_sampler == "exact"


# ------------------------------------------------- the hashed draw itself


def test_hashed_jit_equals_numpy_mirror():
    """The in-program draw and the host replay must select identical
    indices — the property streamed-residency bit-identity rests on."""
    for i, (n, k) in enumerate([
        (50, 10), (1000, 256), (8, 4), (20, 19), (7, 7), (100_000, 64),
        # ~1/3 of stream values hit the modulo-bias rejection here
        # (2^32 // n == 2), so the -1-marking path is exercised hard in
        # BOTH backends and must still agree.
        (2**32 // 3 + 1, 8),
    ]):
        pk = _part_key(i)
        jitted = np.asarray(
            jax.jit(hashed_cohort, static_argnums=(1, 2))(pk, n, k)
        )
        mirror = hashed_cohort_np(_key_words_np(pk), n, k)
        np.testing.assert_array_equal(jitted, mirror)


def test_hashed_no_duplicates_in_range():
    for i, (n, k) in enumerate([(30, 29), (1000, 500), (10_000, 256)]):
        idx = hashed_cohort_np(_key_words_np(_part_key(i)), n, k)
        assert idx.shape == (k,)
        assert len(np.unique(idx)) == k
        assert idx.min() >= 0 and idx.max() < n


def test_hashed_deterministic_and_key_sensitive():
    kw = _key_words_np(_part_key(3))
    a = hashed_cohort_np(kw, 5000, 64)
    b = hashed_cohort_np(kw, 5000, 64)
    np.testing.assert_array_equal(a, b)
    c = hashed_cohort_np(_key_words_np(_part_key(4)), 5000, 64)
    assert not np.array_equal(a, c)


def test_hashed_block_size_independent():
    """'First k distinct of the counter stream' is the definition, so
    the over-draw block size must not change the selection — the
    guarantee that the jitted fixed-shape loop and any mirror block
    size agree."""
    kw = _key_words_np(_part_key(5))
    a = hashed_cohort_np(kw, 1000, 256, block=70)
    b = hashed_cohort_np(kw, 1000, 256, block=4096)
    np.testing.assert_array_equal(a, b)


def test_overdraw_block_bounds():
    assert overdraw_block(256, 1_000_000) < 4 * 256 + 65
    assert overdraw_block(256, 1_000_000) > 256
    # Near-1 fractions stay capped (the while loop absorbs the rest).
    assert overdraw_block(999, 1000) <= 4 * 999 + 64


def test_threefry_numpy_matches_jnp():
    import jax.numpy as jnp

    ctr = np.arange(128, dtype=np.uint32)
    a0, a1 = threefry2x32(np, np.uint32(7), np.uint32(9), ctr,
                          np.zeros(128, np.uint32))
    b0, b1 = threefry2x32(jnp, jnp.uint32(7), jnp.uint32(9),
                          jnp.asarray(ctr), jnp.zeros(128, jnp.uint32))
    np.testing.assert_array_equal(a0, np.asarray(b0))
    np.testing.assert_array_equal(a1, np.asarray(b1))


def test_part_key_words_match_eager_split():
    """The jitted round_key_splits+key_data chain (the O(cohort)
    replay's fast path) must produce the eager chain's bits exactly —
    jit moves where the threefry runs, never what it computes. Built
    FROM round_key_splits, so both fault-gating flavors are the one
    split-chain definition."""
    key = jax.random.key(11)
    for with_faults in (False, True):
        fast = _hashed_part_key_words(key, with_faults)
        eager = np.asarray(
            jax.random.key_data(round_key_splits(key, with_faults)[0])
        ).ravel()
        np.testing.assert_array_equal(fast, eager)


def test_hashed_uniformity_chi_square():
    """Inclusion counts over many independent round keys at small N:
    each client must appear with probability k/N. Chi-square over N=50
    cells; the 0.999 quantile of chi2(df=49) is 85.4 — a generous
    one-shot bound for a deterministic test (the draw stream is fixed
    by the seed, so this can never flake)."""
    n, k, rounds = 50, 10, 2000
    counts = np.zeros(n)
    base = jax.random.key(0)
    for r in range(rounds):
        pk = jax.random.split(jax.random.fold_in(base, r))[0]
        counts[hashed_cohort_np(_key_words_np(pk), n, k)] += 1
    expected = rounds * k / n
    chi2 = float(((counts - expected) ** 2 / expected).sum())
    assert chi2 < 85.4, f"chi2={chi2} over df={n - 1}"


# ------------------------------------------------------ shared-helper pins


def test_exact_mode_is_bit_identical_to_choice():
    """The deduped helper must return jax.random.choice's draw
    bit-for-bit in both the traced and host entries — the pre-feature
    pin the 'exact' default rests on."""
    pk = _part_key(6)
    reference = np.asarray(
        jax.random.choice(pk, 100, (10,), replace=False)
    )
    np.testing.assert_array_equal(
        np.asarray(draw_cohort(pk, 100, 10, "exact")), reference
    )
    np.testing.assert_array_equal(
        draw_cohort_host(pk, 100, 10, "exact"), reference
    )


def test_unknown_sampler_rejected():
    pk = _part_key(7)
    with pytest.raises(ValueError, match="participation_sampler"):
        draw_cohort(pk, 10, 2, "reservoir")
    with pytest.raises(ValueError, match="participation_sampler"):
        draw_cohort_host(pk, 10, 2, "reservoir")


def test_config_hash_off_gate():
    """'exact' IS the pre-feature program, so it drops out of
    config_hash (pre-feature bench hashes survive the knob landing);
    'hashed' changes the drawn cohorts and auto-lands."""
    cfg = ExperimentConfig(participation_fraction=0.5)
    h = config_hash(cfg)
    assert h == config_hash(
        dataclasses.replace(cfg, participation_sampler="exact")
    )
    assert h != config_hash(
        dataclasses.replace(cfg, participation_sampler="hashed")
    )


# ------------------------------------------------------- end-to-end pins


def _series(result, *keys):
    return {k: [h.get(k) for h in result["history"]] for k in keys}


_BIT_KEYS = ("test_accuracy", "test_loss", "mean_client_loss",
             "cohort_hash")


def test_exact_default_history_unchanged(tiny_config):
    """participation_sampler='exact' (and the default) run the exact
    pre-feature program: identical histories, cohort hashes included."""
    cfg = dataclasses.replace(
        tiny_config, worker_number=8, round=3, participation_fraction=0.5,
    )
    base = _series(run_simulation(cfg, setup_logging=False), *_BIT_KEYS)
    explicit = _series(
        run_simulation(
            dataclasses.replace(cfg, participation_sampler="exact"),
            setup_logging=False,
        ),
        *_BIT_KEYS,
    )
    assert base == explicit
    assert None not in base["cohort_hash"]


def test_hashed_streamed_matches_resident(tiny_config):
    """The hashed mode's self-consistency contract: streamed residency
    (host numpy mirror replay) is bit-identical to resident (in-program
    jitted draw) — with faults active, so the 5-way key split is
    exercised too — while drawing DIFFERENT cohorts than exact (it is a
    new sampling mode, not a bit-compatible one)."""
    cfg = dataclasses.replace(
        tiny_config, worker_number=8, round=3, participation_fraction=0.5,
        participation_sampler="hashed",
        failure_mode="dropout", failure_prob=0.3, min_survivors=1,
    )
    resident = _series(run_simulation(cfg, setup_logging=False), *_BIT_KEYS,
                       "survivor_count")
    streamed = _series(
        run_simulation(
            dataclasses.replace(cfg, client_residency="streamed"),
            setup_logging=False,
        ),
        *_BIT_KEYS, "survivor_count",
    )
    assert resident == streamed
    exact = _series(
        run_simulation(
            dataclasses.replace(cfg, participation_sampler="exact"),
            setup_logging=False,
        ),
        *_BIT_KEYS,
    )
    assert exact["cohort_hash"] != resident["cohort_hash"]


def test_hashed_batched_dispatch_matches_per_round(tiny_config):
    """rounds_per_dispatch>1 under the hashed sampler: the streamed
    scan's host-replayed cohorts equal the K=1 loop's bit-for-bit (the
    key-chain replay discipline is sampler-independent)."""
    cfg = dataclasses.replace(
        tiny_config, worker_number=8, round=4, participation_fraction=0.5,
        participation_sampler="hashed", client_residency="streamed",
    )
    k1 = _series(run_simulation(cfg, setup_logging=False), *_BIT_KEYS)
    k3 = _series(
        run_simulation(
            dataclasses.replace(cfg, rounds_per_dispatch=3),
            setup_logging=False,
        ),
        *_BIT_KEYS,
    )
    assert k1 == k3
