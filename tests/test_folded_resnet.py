"""W-folded stage 1 of the ResNet (models/resnet.py): exact-math layout
transform, not an architecture change. The folded model must compute the
SAME function as the unfolded one given the same parameters."""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_learning_simulator_tpu.models.resnet import (
    ResNet18,
    pack_folded_kernel,
)


def test_pack_folded_kernel_exact():
    """Folded conv == plain conv on the folded/unfolded views (f32)."""
    key = jax.random.key(0)
    x = jax.random.normal(key, (2, 8, 8, 4), jnp.float32)
    w = jax.random.normal(jax.random.fold_in(key, 1), (3, 3, 4, 4),
                          jnp.float32)

    def conv(xx, ww):
        return jax.lax.conv_general_dilated(
            xx, ww, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )

    y_ref = conv(x, w)
    xf = x.reshape(2, 8, 4, 8)
    y_fold = conv(xf, pack_folded_kernel(w)).reshape(y_ref.shape)
    np.testing.assert_allclose(
        np.asarray(y_fold), np.asarray(y_ref), rtol=1e-5, atol=1e-5
    )


def _transplant(unfolded, folded):
    """Copy the unfolded model's params into the folded model's tree."""
    out = jax.tree_util.tree_map(lambda x: x, folded)  # deep-ish copy
    n_folded = len([k for k in folded if k.startswith("FoldedResidualBlock")])
    for i in range(n_folded):
        src = unfolded[f"ResidualBlock_{i}"]
        dst = out[f"FoldedResidualBlock_{i}"]
        for j in range(2):
            dst[f"FoldedConv3x3_{j}"]["kernel"] = src[f"Conv_{j}"]["kernel"]
            dst[f"FoldedGroupNorm_{j}"]["scale"] = src[f"GroupNorm_{j}"][
                "scale"
            ]
            dst[f"FoldedGroupNorm_{j}"]["bias"] = src[f"GroupNorm_{j}"][
                "bias"
            ]
    # Transition block (stage-2 entry): unfolded ResidualBlock_{n_folded}
    # with a projection shortcut (Conv_2/GroupNorm_2).
    trans = unfolded[f"ResidualBlock_{n_folded}"]
    ftb = out["FoldedTransitionBlock_0"]
    ftb["conv1_kernel"] = trans["Conv_0"]["kernel"]
    ftb["Conv_0"]["kernel"] = trans["Conv_1"]["kernel"]
    ftb["proj_kernel"] = trans["Conv_2"]["kernel"]
    for j in range(3):
        ftb[f"GroupNorm_{j}"] = trans[f"GroupNorm_{j}"]
    n_rest = len([k for k in folded if k.startswith("ResidualBlock")])
    for k in range(n_rest):
        out[f"ResidualBlock_{k}"] = unfolded[
            f"ResidualBlock_{k + n_folded + 1}"
        ]
    for shared in ("Conv_0", "GroupNorm_0", "Dense_0"):
        out[shared] = unfolded[shared]
    return out


def test_folded_resnet_matches_unfolded():
    """Same params -> same logits (f32 exact up to accumulation order;
    bf16 within a couple of output ulps)."""
    x = np.asarray(
        jax.random.normal(jax.random.key(2), (4, 32, 32, 3), jnp.float32)
    )
    for dtype, tol in ((jnp.float32, 1e-4), (jnp.bfloat16, 0.15)):
        unfolded_model = ResNet18(fold_stage1=False, dtype=dtype)
        folded_model = ResNet18(fold_stage1=True, dtype=dtype)
        pu = unfolded_model.init(jax.random.key(0), x[:1])["params"]
        pf = folded_model.init(jax.random.key(0), x[:1])["params"]
        pf = _transplant(pu, pf)
        yu = unfolded_model.apply({"params": pu}, x)
        yf = folded_model.apply({"params": pf}, x)
        np.testing.assert_allclose(
            np.asarray(yf), np.asarray(yu), rtol=tol, atol=tol,
        ), dtype


def test_folded_resnet_gradients_match_unfolded():
    """The packing transpose (autodiff of the concat/stack kernel build)
    must route gradients back to the SAME unpacked parameters: compare
    d loss / d params between folded and unfolded models in f32.
    Forward equality alone would not catch a scatter/duplication bug in
    the backward of pack_folded_kernel.

    Comparison metric: the two models compute the same math with
    different op orders (packed vs plain conv contractions, 6D vs 5D
    GroupNorm stat reduces), so forward activations differ by ~1 f32
    ulp — and a ulp-scale perturbation that lands exactly on a ReLU
    threshold flips that element's backward mask, producing isolated
    O(1e-3) gradient diffs that elementwise rtol cannot distinguish
    from real bugs (measured round 5: swapping ReLU for softplus in
    BOTH models collapses the worst per-leaf relative L2 from 4.3e-3
    to 6.3e-6). So this test runs two legs: a STRICT leg with a smooth
    activation (pure routing check, no flip noise — a scatter bug moves
    O(1) relative mass) and a loose leg on the real ReLU model."""
    import distributed_learning_simulator_tpu.models.resnet as resnet_mod

    x = np.asarray(
        jax.random.normal(jax.random.key(5), (4, 32, 32, 3), jnp.float32)
    )
    y = np.asarray(
        jax.random.randint(jax.random.key(6), (4,), 0, 10)
    )

    def worst_rel_l2():
        unfolded_model = ResNet18(fold_stage1=False, dtype=jnp.float32)
        folded_model = ResNet18(fold_stage1=True, dtype=jnp.float32)
        pu = unfolded_model.init(jax.random.key(0), x[:1])["params"]
        pf = _transplant(
            pu, folded_model.init(jax.random.key(0), x[:1])["params"]
        )

        def loss(model, p):
            logits = model.apply({"params": p}, x)
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

        gu = jax.grad(lambda p: loss(unfolded_model, p))(pu)
        gf = jax.grad(lambda p: loss(folded_model, p))(pf)
        # Compare via the same transplant mapping, in the folded tree's
        # shape.
        gu_in_folded = _transplant(gu, gf)
        worst = ("", 0.0)
        for (ku, lu), (kf, lf) in zip(
            sorted(jax.tree_util.tree_leaves_with_path(gu_in_folded),
                   key=lambda kv: str(kv[0])),
            sorted(jax.tree_util.tree_leaves_with_path(gf),
                   key=lambda kv: str(kv[0])),
        ):
            assert str(ku) == str(kf)
            a, b = np.asarray(lf), np.asarray(lu)
            rel = np.linalg.norm(a - b) / max(np.linalg.norm(b), 1e-12)
            if rel > worst[1]:
                worst = (str(ku), float(rel))
        return worst

    # Strict leg: smooth activation in BOTH models — no ReLU-flip noise,
    # so any routing/duplication bug in the packing transpose shows as
    # O(1) relative mass against a ~1e-5 float noise floor.
    orig_relu = resnet_mod.nn.relu
    resnet_mod.nn.relu = jax.nn.softplus
    try:
        key, rel = worst_rel_l2()
        assert rel < 1e-4, (key, rel)
    finally:
        resnet_mod.nn.relu = orig_relu
    # Loose leg: the real ReLU model — bounds flip noise (isolated
    # elements at ~1e-3) while still far below a packing bug's O(1).
    key, rel = worst_rel_l2()
    assert rel < 2e-2, (key, rel)


def test_plain_group_norm_matches_flax():
    """PlainGroupNorm (closed-form backward) must match nn.GroupNorm in
    forward AND gradients (f32, tight tolerance) — it replaces it
    throughout the unfolded blocks under the same parameter names."""
    import flax.linen as nn

    from distributed_learning_simulator_tpu.models.resnet import (
        PlainGroupNorm,
    )

    x = jax.random.normal(jax.random.key(0), (4, 8, 8, 64), jnp.float32)
    y = np.asarray(jax.random.randint(jax.random.key(1), (4,), 0, 10))
    # bf16 (production dtype): agreement within output ulps — our affine
    # runs in f32 with ONE output cast, flax casts operands to bf16 first.
    ours16 = PlainGroupNorm(num_groups=32, dtype=jnp.bfloat16)
    ref16 = nn.GroupNorm(num_groups=32, dtype=jnp.bfloat16)
    p16 = ref16.init(jax.random.key(2), x)["params"]
    np.testing.assert_allclose(
        np.asarray(ours16.apply({"params": p16}, x), dtype=np.float32),
        np.asarray(ref16.apply({"params": p16}, x), dtype=np.float32),
        rtol=0.02, atol=0.02,
    )
    import pytest

    with pytest.raises(ValueError, match="must divide"):
        PlainGroupNorm(num_groups=32, dtype=jnp.float32).init(
            jax.random.key(0), jnp.zeros((1, 4, 4, 48), jnp.float32)
        )
    ours = PlainGroupNorm(num_groups=32, dtype=jnp.float32)
    ref = nn.GroupNorm(num_groups=32, dtype=jnp.float32)
    p_ours = ours.init(jax.random.key(2), x)["params"]
    p_ref = ref.init(jax.random.key(2), x)["params"]
    assert jax.tree_util.tree_structure(p_ours) == (
        jax.tree_util.tree_structure(p_ref)
    )
    # randomize params so grads through scale/bias are non-trivial
    p = jax.tree_util.tree_map(
        lambda l: l + 0.3 * jax.random.normal(jax.random.key(3), l.shape),
        p_ref,
    )
    np.testing.assert_allclose(
        np.asarray(ours.apply({"params": p}, x)),
        np.asarray(ref.apply({"params": p}, x)),
        rtol=1e-5, atol=1e-5,
    )

    def loss(module, params, inp):
        out = module.apply({"params": params}, inp)
        return jnp.sum(out * out) + jnp.sum(out[..., y])

    g_ours = jax.grad(lambda pp, xx: loss(ours, pp, xx), argnums=(0, 1))(p, x)
    g_ref = jax.grad(lambda pp, xx: loss(ref, pp, xx), argnums=(0, 1))(p, x)
    for a, b in zip(jax.tree_util.tree_leaves(g_ours),
                    jax.tree_util.tree_leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_gn_custom_backward_matches_autodiff():
    """The closed-form GN backward vs XLA autodiff of the SAME forward,
    through the whole folded model: gradients must agree tightly in f32
    (gn_custom_backward=False is the escape hatch --model_args exposes)."""
    x = np.asarray(
        jax.random.normal(jax.random.key(8), (2, 32, 32, 3), jnp.float32)
    )
    y = np.asarray(jax.random.randint(jax.random.key(9), (2,), 0, 10))
    custom = ResNet18(dtype=jnp.float32, gn_custom_backward=True)
    auto = ResNet18(dtype=jnp.float32, gn_custom_backward=False)
    p = custom.init(jax.random.key(0), x[:1])["params"]

    def loss(model, params):
        logits = model.apply({"params": params}, x)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

    g_c = jax.grad(lambda pp: loss(custom, pp))(p)
    g_a = jax.grad(lambda pp: loss(auto, pp))(p)
    for a, b in zip(jax.tree_util.tree_leaves(g_c),
                    jax.tree_util.tree_leaves(g_a)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_folded_param_count_unchanged():
    """Folding changes layout only: identical total parameter count."""
    x = jnp.zeros((1, 32, 32, 3), jnp.float32)
    pu = ResNet18(fold_stage1=False).init(jax.random.key(0), x)["params"]
    pf = ResNet18(fold_stage1=True).init(jax.random.key(0), x)["params"]
    count = lambda t: sum(  # noqa: E731
        l.size for l in jax.tree_util.tree_leaves(t)
    )
    assert count(pu) == count(pf)


def test_model_args_escape_hatch_disables_fold(tiny_config):
    """config.model_args={"fold_stage1": False} reaches the constructor
    through run_simulation — the escape hatch that keeps pre-fold
    checkpoints resumable (ADVICE r3 medium)."""
    import dataclasses

    from distributed_learning_simulator_tpu.simulator import run_simulation

    cfg = dataclasses.replace(
        tiny_config, model_name="resnet18", worker_number=2, round=1,
        batch_size=8, n_train=64, n_test=32,
        dataset_args={"difficulty": 0.5, "shape": (32, 32, 3)},
        model_args={"fold_stage1": False},
    )
    res = run_simulation(cfg, setup_logging=False)
    assert not any("Folded" in k for k in res["global_params"])
    assert np.isfinite(res["history"][-1]["test_loss"])


def test_model_args_cli_json():
    """--model_args parses a JSON object from the CLI."""
    from distributed_learning_simulator_tpu.config import get_config

    cfg = get_config(
        ["--model_args", '{"fold_stage1": false}', "--log_level", "WARNING"]
    )
    assert cfg.model_args == {"fold_stage1": False}


def test_folded_resnet_trains(tiny_config):
    """End-to-end: the folded flagship model learns under the engine."""
    import dataclasses

    from distributed_learning_simulator_tpu.simulator import run_simulation

    cfg = dataclasses.replace(
        tiny_config, model_name="resnet18", worker_number=2, round=2,
        batch_size=8, n_train=64, n_test=32,
        dataset_args={"difficulty": 0.5, "shape": (32, 32, 3)},
    )
    res = run_simulation(cfg, setup_logging=False)
    assert np.isfinite(res["history"][-1]["test_loss"])


def test_pallas_gn_matches_jnp():
    """Pallas GroupNorm forward (ops/gn_pallas.py) vs the jnp form: stats
    to f32-reduction tolerance, outputs within one bf16 ulp. The suite
    pins the CPU backend (conftest), where the Mosaic kernels don't
    exist — this test runs when invoked on a TPU host directly:
    ``JAX_PLATFORMS= python -m pytest tests/test_folded_resnet.py -k pallas``.
    """
    import pytest

    if jax.default_backend() != "tpu":
        pytest.skip("pallas GN kernels are Mosaic-only (suite runs on CPU)")
    import distributed_learning_simulator_tpu.models.resnet as R

    rng = np.random.default_rng(0)
    xf = jnp.asarray(
        rng.normal(size=(25, 32, 16, 128)).astype(np.float32) * 2 + 1.5,
        jnp.bfloat16,
    )
    scale = jnp.asarray(rng.normal(size=(64,)).astype(np.float32))
    bias = jnp.asarray(rng.normal(size=(64,)).astype(np.float32))
    # DLS_GN_PALLAS is frozen into a module constant at import (flipping
    # the env var mid-process could never outrun the jit cache); toggling
    # the constant is the supported way to exercise both kernels in-process.
    prev = R._GN_PALLAS_ENABLED
    try:
        R._GN_PALLAS_ENABLED = False
        y0, m0, r0 = R._fgn_forward(xf, scale, bias, 32, 1e-6, jnp.bfloat16)
        R._GN_PALLAS_ENABLED = True
        y1, m1, r1 = R._fgn_forward(xf, scale, bias, 32, 1e-6, jnp.bfloat16)
    finally:
        R._GN_PALLAS_ENABLED = prev
    np.testing.assert_allclose(
        np.asarray(m1.reshape(-1)), np.asarray(m0.reshape(-1)), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(r1.reshape(-1)), np.asarray(r0.reshape(-1)), rtol=1e-5
    )
    d = np.abs(
        np.asarray(y1, np.float32) - np.asarray(y0, np.float32)
    )
    # one output ulp at these magnitudes
    assert d.max() <= 0.0625, d.max()
