"""Archive-format parsers (data/formats.py) against crafted fixtures.

The real downloads can't run in the offline CI container, so the parsers
are exercised on synthetic archives built in-memory with the exact official
layouts (IDX for MNIST, pickled CHW batches in a tar.gz for CIFAR-10).
"""

import gzip
import io
import pickle
import struct
import tarfile

import numpy as np
import pytest

from distributed_learning_simulator_tpu.data.formats import (
    cifar10_arrays,
    mnist_arrays,
    parse_idx,
)


def _idx_bytes(arr: np.ndarray) -> bytes:
    codes = {np.dtype(np.uint8): 0x08, np.dtype(">i4"): 0x0C}
    header = bytes([0, 0, codes[arr.dtype], arr.ndim])
    header += struct.pack(f">{arr.ndim}I", *arr.shape)
    return header + arr.tobytes()


def test_parse_idx_roundtrip():
    arr = np.arange(2 * 3 * 4, dtype=np.uint8).reshape(2, 3, 4)
    out = parse_idx(_idx_bytes(arr))
    np.testing.assert_array_equal(out, arr)


def test_parse_idx_rejects_bad_magic():
    with pytest.raises(ValueError, match="magic"):
        parse_idx(b"\x01\x00\x08\x01" + b"\x00" * 8)


def test_parse_idx_rejects_truncated():
    arr = np.zeros((4, 4), dtype=np.uint8)
    with pytest.raises(ValueError, match="mismatch"):
        parse_idx(_idx_bytes(arr)[:-3])


def test_mnist_arrays():
    rng = np.random.default_rng(0)
    xtr = rng.integers(0, 256, (6, 28, 28), dtype=np.uint8)
    ytr = rng.integers(0, 10, (6,)).astype(np.uint8)
    xte = rng.integers(0, 256, (3, 28, 28), dtype=np.uint8)
    yte = rng.integers(0, 10, (3,)).astype(np.uint8)
    gz = lambda a: gzip.compress(_idx_bytes(a))
    out = mnist_arrays(gz(xtr), gz(ytr), gz(xte), gz(yte))
    np.testing.assert_array_equal(out["x_train"], xtr)
    np.testing.assert_array_equal(out["y_test"], yte.astype(np.int32))
    assert out["y_train"].dtype == np.int32


def _cifar_targz(batches: dict[str, tuple[np.ndarray, list[int]]]) -> bytes:
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        for name, (x_chw_flat, labels) in batches.items():
            payload = pickle.dumps(
                {b"data": x_chw_flat, b"labels": labels}, protocol=2
            )
            info = tarfile.TarInfo(name=f"cifar-10-batches-py/{name}")
            info.size = len(payload)
            tf.addfile(info, io.BytesIO(payload))
    return buf.getvalue()


def test_cifar10_arrays():
    rng = np.random.default_rng(1)

    def batch(n):
        x = rng.integers(0, 256, (n, 3072), dtype=np.uint8)
        y = rng.integers(0, 10, (n,)).tolist()
        return x, y

    batches = {f"data_batch_{i}": batch(4) for i in range(1, 6)}
    batches["test_batch"] = batch(2)
    out = cifar10_arrays(_cifar_targz(batches))
    assert out["x_train"].shape == (20, 32, 32, 3)
    assert out["x_test"].shape == (2, 32, 32, 3)
    # CHW -> HWC transpose correctness: red plane of sample 0 of batch 1
    x0_flat, _ = batches["data_batch_1"]
    np.testing.assert_array_equal(
        out["x_train"][0, :, :, 0], x0_flat[0, :1024].reshape(32, 32)
    )
    assert out["y_train"].dtype == np.int32


def test_cifar10_arrays_rejects_empty():
    with pytest.raises(ValueError, match="no CIFAR batches"):
        cifar10_arrays(_cifar_targz({}))
