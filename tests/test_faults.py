"""Failure-model subsystem: mask statistics, corruption semantics, survivor
renormalization across both FedAvg execution paths, quorum rejection, and
the algorithm-level refusals (docs/ROBUSTNESS.md)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_learning_simulator_tpu.config import ExperimentConfig
from distributed_learning_simulator_tpu.robustness.faults import (
    CORRUPT_SCALE,
    FailureModel,
)
from distributed_learning_simulator_tpu.simulator import run_simulation


def _fm(mode="dropout", prob=0.3, correlation=0.0, seed=0):
    return FailureModel(mode=mode, prob=prob, correlation=correlation,
                        seed=seed)


def test_from_config_inactive_when_none_or_zero_prob():
    assert FailureModel.from_config(ExperimentConfig()) is None
    assert FailureModel.from_config(
        ExperimentConfig(failure_mode="dropout", failure_prob=0.0)
    ) is None
    assert FailureModel.from_config(
        ExperimentConfig(failure_mode="dropout", failure_prob=0.5)
    ) is not None


def test_failure_mask_marginal_rate():
    fm = _fm(prob=0.3)
    draws = jax.vmap(lambda k: fm.draw_failed(k, 64))(
        jax.random.split(jax.random.key(0), 200)
    )
    rate = float(jnp.mean(draws))
    assert abs(rate - 0.3) < 0.02


def test_failure_correlation_one_is_all_or_nothing():
    fm = _fm(prob=0.3, correlation=1.0)
    draws = np.asarray(jax.vmap(lambda k: fm.draw_failed(k, 32))(
        jax.random.split(jax.random.key(1), 100)
    ))
    per_round = draws.mean(axis=1)
    assert set(np.unique(per_round)) <= {0.0, 1.0}
    assert abs(per_round.mean() - 0.3) < 0.15


def test_failure_seed_rerolls_mask():
    key = jax.random.key(2)
    a = np.asarray(_fm(seed=0, prob=0.5).draw_failed(key, 256))
    b = np.asarray(_fm(seed=1, prob=0.5).draw_failed(key, 256))
    assert (a != b).any()
    # same seed = same draw (resume determinism at the op level)
    c = np.asarray(_fm(seed=0, prob=0.5).draw_failed(key, 256))
    assert (a == c).all()


def test_corrupt_stack_modes():
    stack = {"w": jnp.ones((4, 3)), "b": jnp.arange(8.0).reshape(4, 2)}
    failed = jnp.asarray([True, False, True, False])
    nan = _fm("corrupt_nan").corrupt_stack(stack, failed)
    assert np.isnan(np.asarray(nan["w"][0])).all()
    assert np.isnan(np.asarray(nan["b"][2])).all()
    assert (np.asarray(nan["w"][1]) == 1.0).all()
    scaled = _fm("corrupt_scale").corrupt_stack(stack, failed)
    assert np.allclose(np.asarray(scaled["w"][0]), CORRUPT_SCALE)
    assert np.allclose(np.asarray(scaled["b"][3]), np.asarray(stack["b"][3]))


def test_validate_rejections():
    with pytest.raises(ValueError, match="failure_mode"):
        ExperimentConfig(failure_mode="lightning").validate()
    with pytest.raises(ValueError, match="failure_prob"):
        ExperimentConfig(failure_mode="dropout", failure_prob=1.5).validate()
    with pytest.raises(ValueError, match="min_survivors"):
        ExperimentConfig(worker_number=4, min_survivors=5).validate()
    with pytest.raises(ValueError, match="threaded"):
        ExperimentConfig(
            execution_mode="threaded",
            failure_mode="dropout", failure_prob=0.1,
        ).validate()
    with pytest.raises(ValueError, match="checkpoint_keep_last"):
        ExperimentConfig(checkpoint_keep_last=0).validate()


def test_signsgd_rejects_corrupt_modes(tiny_config):
    from distributed_learning_simulator_tpu.factory import get_algorithm

    cfg = dataclasses.replace(
        tiny_config, distributed_algorithm="sign_SGD",
        failure_mode="corrupt_nan", failure_prob=0.2,
    )
    with pytest.raises(ValueError, match="dropout/straggler"):
        get_algorithm("sign_SGD", cfg)


@pytest.mark.parametrize(
    "algo", ["multiround_shapley_value", "GTG_shapley_value"]
)
def test_shapley_constructor_refuses_failures(tiny_config, algo):
    from distributed_learning_simulator_tpu.factory import get_algorithm

    cfg = dataclasses.replace(
        tiny_config, distributed_algorithm=algo,
        failure_mode="straggler", failure_prob=0.2,
    )
    with pytest.raises(ValueError, match="fixed cohort"):
        get_algorithm(algo, cfg)


def test_corrupt_nan_median_quorum_end_to_end(tiny_config):
    """Acceptance: corrupt_nan + median + quorum finishes with finite
    accuracy and nonzero survivor_count telemetry."""
    cfg = dataclasses.replace(
        tiny_config, worker_number=8, round=3,
        failure_mode="corrupt_nan", failure_prob=0.4,
        aggregation="median", min_survivors=3,
    )
    r = run_simulation(cfg, setup_logging=False)
    assert np.isfinite(r["final_accuracy"])
    assert all(np.isfinite(h["test_accuracy"]) for h in r["history"])
    assert all("survivor_count" in h for h in r["history"])
    assert any(h["survivor_count"] > 0 for h in r["history"])
    assert r["mean_survivor_count"] > 0


def test_corrupt_nan_plain_mean_quorum_rejects_not_propagates(tiny_config):
    """Acceptance: under the plain mean, any round where a corrupt upload
    would have produced a non-finite aggregate is REJECTED (previous
    global retained) instead of NaN-propagating into every later round."""
    cfg = dataclasses.replace(
        tiny_config, worker_number=8, round=4,
        failure_mode="corrupt_nan", failure_prob=0.5,
        aggregation="mean", min_survivors=1,
    )
    r = run_simulation(cfg, setup_logging=False)
    # A NaN upload makes the plain-mean aggregate all-NaN, so rejection is
    # exactly "some client was corrupt this round".
    for h in r["history"]:
        assert h["round_rejected"] == (h["survivor_count"] < 8)
    assert r["rounds_rejected"] >= 1, "prob=0.5 x 8 clients x 4 rounds"
    assert all(np.isfinite(h["test_accuracy"]) for h in r["history"])
    finite = all(
        np.isfinite(np.asarray(leaf)).all()
        for leaf in jax.tree_util.tree_leaves(r["global_params"])
    )
    assert finite
    # A rejected round keeps the previous global model, so its eval is
    # bit-identical to the previous round's.
    hist = r["history"]
    for prev, cur in zip(hist, hist[1:]):
        if cur["round_rejected"]:
            assert cur["test_accuracy"] == prev["test_accuracy"]
            assert cur["test_loss"] == prev["test_loss"]


@pytest.mark.parametrize("mode", ["dropout", "corrupt_scale"])
def test_fused_and_materializing_paths_agree(tiny_config, mode):
    """The fused (chunked partial-sum) path and the materializing path
    (client_eval forces the full stack) must inject the SAME faults:
    dropout via zeroed weights, corruption on the raw pre-payload upload."""
    base = dataclasses.replace(
        tiny_config, round=2, failure_mode=mode, failure_prob=0.4,
        min_survivors=0,
    )
    fused = run_simulation(
        dataclasses.replace(base, client_eval=False), setup_logging=False
    )
    materialized = run_simulation(
        dataclasses.replace(base, client_eval=True), setup_logging=False
    )
    for a, b in zip(fused["history"], materialized["history"]):
        assert a["survivor_count"] == b["survivor_count"]
        assert np.isclose(a["test_accuracy"], b["test_accuracy"])
        assert np.isclose(a["test_loss"], b["test_loss"], rtol=1e-5)
    ga = jax.tree_util.tree_leaves(fused["global_params"])
    gb = jax.tree_util.tree_leaves(materialized["global_params"])
    for la, lb in zip(ga, gb):
        np.testing.assert_allclose(
            np.asarray(la), np.asarray(lb), rtol=2e-5, atol=1e-6
        )


def test_dropout_vs_straggler_state_semantics(tiny_config):
    """With persistent client optimizers, dropout freezes a failed
    client's state (it never trained) while a straggler's advances (it
    trained; only the upload was lost). prob=1 makes every client fail
    every round, so the distinction is directly observable."""
    base = dataclasses.replace(
        tiny_config, round=2, momentum=0.9, reset_client_optimizer=False,
        failure_prob=1.0, failure_mode="dropout",
    )
    dropped = run_simulation(base, setup_logging=False)
    momenta = [
        np.asarray(leaf)
        for leaf in jax.tree_util.tree_leaves(dropped["client_state"])
        if np.asarray(leaf).dtype == np.float32
    ]
    assert all((m == 0).all() for m in momenta), "dropout must freeze state"
    straggled = run_simulation(
        dataclasses.replace(base, failure_mode="straggler"),
        setup_logging=False,
    )
    s_momenta = [
        np.asarray(leaf)
        for leaf in jax.tree_util.tree_leaves(straggled["client_state"])
        if np.asarray(leaf).dtype == np.float32
    ]
    assert any((m != 0).any() for m in s_momenta), (
        "straggler state must advance"
    )
    # Either way nobody's update landed: the global model never moved.
    for r in (dropped, straggled):
        accs = [h["test_accuracy"] for h in r["history"]]
        assert len(set(accs)) == 1


def test_signsgd_dropout_excludes_votes_and_freezes_state(tiny_config):
    cfg = dataclasses.replace(
        tiny_config, distributed_algorithm="sign_SGD", learning_rate=0.01,
        momentum=0.9, round=2,
        failure_mode="dropout", failure_prob=1.0, failure_correlation=1.0,
        min_survivors=1,
    )
    r = run_simulation(cfg, setup_logging=False)
    # Everyone failed every round: all rounds rejected, no step taken.
    assert r["rounds_rejected"] == 2
    assert all(h["survivor_count"] == 0 for h in r["history"])
    state = r["client_state"]
    assert (np.asarray(state["steps"]) == 0).all()
    assert all(
        (np.asarray(leaf) == 0).all()
        for leaf in jax.tree_util.tree_leaves(state["momenta"])
    )
    accs = [h["test_accuracy"] for h in r["history"]]
    assert len(set(accs)) == 1


def test_rejected_round_frozen_under_server_optimizer(tiny_config):
    """A rejected round must retain the previous global EXACTLY even with
    a server optimizer: the pseudo-gradient is 0, but an unguarded
    momentum trace from prior rounds would still move the params and
    advance the optimizer state."""
    cfg = dataclasses.replace(
        tiny_config, worker_number=8, round=4,
        failure_mode="corrupt_nan", failure_prob=0.5,
        aggregation="mean", min_survivors=1,
        server_optimizer_name="sgd", server_learning_rate=1.0,
        server_momentum=0.9,
    )
    r = run_simulation(cfg, setup_logging=False)
    assert r["rounds_rejected"] >= 1
    hist = r["history"]
    for prev, cur in zip(hist, hist[1:]):
        if cur["round_rejected"]:
            assert cur["test_accuracy"] == prev["test_accuracy"]
            assert cur["test_loss"] == prev["test_loss"]
    assert all(
        np.isfinite(np.asarray(leaf)).all()
        for leaf in jax.tree_util.tree_leaves(r["global_params"])
    )


def test_rejected_round_frozen_under_fed_quant_downlink(tiny_config):
    """fed_quant re-quantizes every broadcast; on a REJECTED round the
    retained model must skip that (fresh quantization noise would move
    the 'retained' params)."""
    cfg = dataclasses.replace(
        tiny_config, distributed_algorithm="fed_quant", worker_number=8,
        round=4, failure_mode="corrupt_nan", failure_prob=0.5,
        aggregation="median", min_survivors=7,
    )
    r = run_simulation(cfg, setup_logging=False)
    assert r["rounds_rejected"] >= 1
    hist = r["history"]
    for prev, cur in zip(hist, hist[1:]):
        if cur["round_rejected"]:
            assert cur["test_accuracy"] == prev["test_accuracy"]
            assert cur["test_loss"] == prev["test_loss"]


def test_failure_free_history_unchanged_by_feature(tiny_config):
    """failure_mode='none' must keep the pre-feature RNG streams: the
    quorum/telemetry machinery is entirely trace-time gated."""
    a = run_simulation(tiny_config, setup_logging=False)
    assert "survivor_count" not in a["history"][0]
    assert "round_rejected" not in a["history"][0]
    assert a["rounds_rejected"] == 0
    assert a["mean_survivor_count"] is None
    # min_survivors alone (no failure model) activates the quorum guard
    # with the full cohort surviving every round.
    b = run_simulation(
        dataclasses.replace(tiny_config, min_survivors=2),
        setup_logging=False,
    )
    assert all(
        h["survivor_count"] == tiny_config.worker_number
        and not h["round_rejected"]
        for h in b["history"]
    )
    assert [h["test_accuracy"] for h in a["history"]] == [
        h["test_accuracy"] for h in b["history"]
    ]
