"""Aggregation math vs closed form (SURVEY §4 test strategy)."""

import jax.numpy as jnp
import numpy as np

from distributed_learning_simulator_tpu.ops.aggregate import (
    subset_masks_all,
    subset_weighted_mean,
    weighted_mean,
)


def _stacked_tree(rng, n_clients=4):
    return {
        "w": jnp.asarray(rng.normal(size=(n_clients, 3, 2)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(n_clients, 5)).astype(np.float32)),
    }


def test_weighted_mean_closed_form(rng):
    tree = _stacked_tree(rng)
    weights = jnp.asarray([1.0, 2.0, 3.0, 4.0])
    out = weighted_mean(tree, weights)
    w = np.asarray(weights) / 10.0
    for k in tree:
        expect = np.tensordot(w, np.asarray(tree[k]), axes=(0, 0))
        np.testing.assert_allclose(np.asarray(out[k]), expect, rtol=1e-5)


def test_weighted_mean_equal_weights_is_mean(rng):
    tree = _stacked_tree(rng)
    out = weighted_mean(tree, jnp.ones(4))
    for k in tree:
        np.testing.assert_allclose(
            np.asarray(out[k]), np.asarray(tree[k]).mean(axis=0), rtol=1e-5
        )


def test_subset_weighted_mean_matches_manual(rng):
    tree = _stacked_tree(rng)
    fallback = {k: jnp.zeros_like(v[0]) for k, v in tree.items()}
    weights = jnp.asarray([10.0, 20.0, 30.0, 40.0])
    mask = jnp.asarray([1.0, 0.0, 1.0, 0.0])
    out = subset_weighted_mean(tree, weights, mask, fallback)
    for k in tree:
        arr = np.asarray(tree[k])
        expect = (10 * arr[0] + 30 * arr[2]) / 40.0
        np.testing.assert_allclose(np.asarray(out[k]), expect, rtol=1e-5)


def test_subset_weighted_mean_empty_falls_back(rng):
    """Empty subset -> previous global model (reference fed_server.py:45-47)."""
    tree = _stacked_tree(rng)
    fallback = {
        "w": jnp.full((3, 2), 7.0),
        "b": jnp.full((5,), -1.0),
    }
    out = subset_weighted_mean(tree, jnp.ones(4), jnp.zeros(4), fallback)
    for k in tree:
        np.testing.assert_allclose(np.asarray(out[k]), np.asarray(fallback[k]))


def test_subset_masks_all_counts():
    masks = subset_masks_all(4)
    assert masks.shape == (16, 4)
    assert (masks.sum(axis=1) == 0).sum() == 1  # one empty subset
    # every subset unique
    assert len({tuple(row) for row in masks.astype(int)}) == 16
    no_empty = subset_masks_all(4, include_empty=False)
    assert no_empty.shape == (15, 4)


def _prefix_mask(n, perm, j):
    mask = np.zeros((n,), np.float32)
    mask[perm[: j + 1]] = 1.0
    return jnp.asarray(mask)


def test_block_prefix_cumsum_bitwise_matches_masked():
    """The GTG cumsum path vs the per-mask oracle, BIT-FOR-BIT in f32.

    Weights double along the walk order, so every prefix total is a power
    of two and every normalized weight a dyadic rational; with small
    integer-valued params both paths' f32 arithmetic is exact, so they
    must compute the identical real value — any bit difference is a real
    defect in one of the two aggregation paths, not rounding."""
    from distributed_learning_simulator_tpu.ops.aggregate import (
        block_prefix_cumsum,
        prefix_means_from_cumsum,
    )

    rng = np.random.default_rng(3)
    n = 12
    perm = rng.permutation(n)
    weights = np.zeros((n,), np.float32)
    weights[perm[0]] = 1.0
    for k in range(1, n):
        weights[perm[k]] = 2.0 ** (k - 1)  # prefix totals: 1, 2, 4, ...
    tree = {
        "w": jnp.asarray(
            rng.integers(-8, 9, size=(n, 3, 2)).astype(np.float32)
        ),
        "b": jnp.asarray(rng.integers(-8, 9, size=(n, 5)).astype(np.float32)),
    }
    fallback = {k: jnp.zeros_like(v[0]) for k, v in tree.items()}
    cs, totals = block_prefix_cumsum(tree, weights, perm[None, :])
    means = prefix_means_from_cumsum(cs, totals, fallback)
    for j in range(n):
        oracle = subset_weighted_mean(
            tree, weights, _prefix_mask(n, perm, j), fallback
        )
        for k in tree:
            np.testing.assert_array_equal(
                np.asarray(means[k][0, j]), np.asarray(oracle[k]), strict=True
            )


def test_block_prefix_cumsum_carry_continuation():
    """Streaming blocks with a carry must agree with one full-walk cumsum:
    the carried running sum IS the sliceable cumsum, block by block (same
    exact-arithmetic construction as the bitwise test, so equality is
    bit-for-bit, not tolerance)."""
    from distributed_learning_simulator_tpu.ops.aggregate import (
        block_prefix_cumsum,
    )

    rng = np.random.default_rng(5)
    n, b = 10, 4
    perm = rng.permutation(n)
    weights = np.zeros((n,), np.float32)
    weights[perm[0]] = 1.0
    for k in range(1, n):
        weights[perm[k]] = 2.0 ** (k - 1)
    tree = {"w": jnp.asarray(
        rng.integers(-8, 9, size=(n, 6)).astype(np.float32)
    )}
    cs_full, tot_full = block_prefix_cumsum(tree, weights, perm[None, :])
    carry, carry_t = None, None
    for j0 in range(0, n, b):
        j1 = min(j0 + b, n)
        block = np.zeros((1, b), np.int64)
        block[0, : j1 - j0] = perm[j0:j1]  # short final block pads client 0
        cs, tot = block_prefix_cumsum(tree, weights, block, carry, carry_t)
        np.testing.assert_array_equal(
            np.asarray(cs["w"][0, : j1 - j0]),
            np.asarray(cs_full["w"][0, j0:j1]),
        )
        np.testing.assert_array_equal(
            np.asarray(tot[0, : j1 - j0]), np.asarray(tot_full[0, j0:j1])
        )
        carry = {"w": cs["w"][:, -1]}
        carry_t = tot[:, -1]


def test_block_prefix_cumsum_close_on_float_data(rng):
    """General float weights/params: cumsum prefix aggregates track the
    masked oracle to f32 rounding (the two paths associate differently,
    so exact equality is only owed on exact-arithmetic inputs)."""
    from distributed_learning_simulator_tpu.ops.aggregate import (
        block_prefix_cumsum,
        prefix_means_from_cumsum,
    )

    n = 16
    perm = rng.permutation(n)
    weights = rng.uniform(0.5, 3.0, size=n).astype(np.float32)
    tree = _stacked_tree(rng, n_clients=n)
    fallback = {k: jnp.zeros_like(v[0]) for k, v in tree.items()}
    # Batch of 2 permutations exercises the [G, B] path.
    perms = np.stack([perm, np.roll(perm, 3)])
    cs, totals = block_prefix_cumsum(tree, weights, perms)
    means = prefix_means_from_cumsum(cs, totals, fallback)
    for g in range(2):
        for j in range(n):
            oracle = subset_weighted_mean(
                tree, weights, _prefix_mask(n, perms[g], j), fallback
            )
            for k in tree:
                np.testing.assert_allclose(
                    np.asarray(means[k][g, j]), np.asarray(oracle[k]),
                    rtol=2e-6, atol=2e-6,
                )


def test_prefix_means_zero_weight_falls_back(rng):
    """A zero-total prefix (all-zero client weights) returns the fallback
    model — the same empty-subset semantics as subset_weighted_mean."""
    from distributed_learning_simulator_tpu.ops.aggregate import (
        block_prefix_cumsum,
        prefix_means_from_cumsum,
    )

    n = 4
    tree = _stacked_tree(rng, n_clients=n)
    fallback = {"w": jnp.full((3, 2), 7.0), "b": jnp.full((5,), -1.0)}
    weights = np.array([0.0, 0.0, 1.0, 1.0], np.float32)
    perm = np.array([[0, 1, 2, 3]])
    cs, totals = block_prefix_cumsum(tree, weights, perm)
    means = prefix_means_from_cumsum(cs, totals, fallback)
    for k in tree:
        # positions 0 and 1 carry zero cumulative weight -> fallback
        np.testing.assert_allclose(np.asarray(means[k][0, 0]),
                                   np.asarray(fallback[k]))
        np.testing.assert_allclose(np.asarray(means[k][0, 1]),
                                   np.asarray(fallback[k]))
    # position 2 is the weight-1 client 2 alone
    np.testing.assert_allclose(np.asarray(means["w"][0, 2]),
                               np.asarray(tree["w"][2]), rtol=1e-6)
