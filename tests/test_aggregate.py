"""Aggregation math vs closed form (SURVEY §4 test strategy)."""

import jax.numpy as jnp
import numpy as np

from distributed_learning_simulator_tpu.ops.aggregate import (
    subset_masks_all,
    subset_weighted_mean,
    weighted_mean,
)


def _stacked_tree(rng, n_clients=4):
    return {
        "w": jnp.asarray(rng.normal(size=(n_clients, 3, 2)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(n_clients, 5)).astype(np.float32)),
    }


def test_weighted_mean_closed_form(rng):
    tree = _stacked_tree(rng)
    weights = jnp.asarray([1.0, 2.0, 3.0, 4.0])
    out = weighted_mean(tree, weights)
    w = np.asarray(weights) / 10.0
    for k in tree:
        expect = np.tensordot(w, np.asarray(tree[k]), axes=(0, 0))
        np.testing.assert_allclose(np.asarray(out[k]), expect, rtol=1e-5)


def test_weighted_mean_equal_weights_is_mean(rng):
    tree = _stacked_tree(rng)
    out = weighted_mean(tree, jnp.ones(4))
    for k in tree:
        np.testing.assert_allclose(
            np.asarray(out[k]), np.asarray(tree[k]).mean(axis=0), rtol=1e-5
        )


def test_subset_weighted_mean_matches_manual(rng):
    tree = _stacked_tree(rng)
    fallback = {k: jnp.zeros_like(v[0]) for k, v in tree.items()}
    weights = jnp.asarray([10.0, 20.0, 30.0, 40.0])
    mask = jnp.asarray([1.0, 0.0, 1.0, 0.0])
    out = subset_weighted_mean(tree, weights, mask, fallback)
    for k in tree:
        arr = np.asarray(tree[k])
        expect = (10 * arr[0] + 30 * arr[2]) / 40.0
        np.testing.assert_allclose(np.asarray(out[k]), expect, rtol=1e-5)


def test_subset_weighted_mean_empty_falls_back(rng):
    """Empty subset -> previous global model (reference fed_server.py:45-47)."""
    tree = _stacked_tree(rng)
    fallback = {
        "w": jnp.full((3, 2), 7.0),
        "b": jnp.full((5,), -1.0),
    }
    out = subset_weighted_mean(tree, jnp.ones(4), jnp.zeros(4), fallback)
    for k in tree:
        np.testing.assert_allclose(np.asarray(out[k]), np.asarray(fallback[k]))


def test_subset_masks_all_counts():
    masks = subset_masks_all(4)
    assert masks.shape == (16, 4)
    assert (masks.sum(axis=1) == 0).sum() == 1  # one empty subset
    # every subset unique
    assert len({tuple(row) for row in masks.astype(int)}) == 16
    no_empty = subset_masks_all(4, include_empty=False)
    assert no_empty.shape == (15, 4)
