"""Streamed client-state residency (config.client_residency='streamed';
data/residency.py + parallel/streaming.py): the full-N per-client arrays
live in a host shard store and only the sampled cohort's slice is
uploaded per dispatch, double-buffered so the next dispatch's cohort
transfers while the current one computes. The contract under test: the
streamed history is BIT-identical to the resident one — cohort hashes,
failure draws, and training metrics included — across the FedAvg family,
sign_SGD, fed_quant, rounds_per_dispatch>1, and checkpoint/resume, while
'resident' (the default) keeps the exact pre-feature program.

The HostShardStore unit tests are jax-free by design (the module imports
only numpy): the host gather/scatter index math mirrors the resident
program's ops/cohort.py device ops, and pinning it without a backend is
what keeps the two implementations semantically paired.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from distributed_learning_simulator_tpu.config import ExperimentConfig
from distributed_learning_simulator_tpu.data.residency import (
    HostShardStore,
    synthetic_stream_shards,
    tree_bytes,
    tree_map_np,
)
from distributed_learning_simulator_tpu.simulator import run_simulation


def _run(cfg, **overrides):
    cfg = dataclasses.replace(cfg, **overrides)
    return run_simulation(cfg, setup_logging=False)


def _series(result, *keys):
    return {k: [h.get(k) for h in result["history"]] for k in keys}


def _read_metrics(log_root):
    import glob

    paths = glob.glob(
        os.path.join(str(log_root), "**", "metrics.jsonl"), recursive=True
    )
    assert len(paths) == 1
    with open(paths[0]) as f:
        return [json.loads(line) for line in f]


_BIT_KEYS = ("test_accuracy", "test_loss", "mean_client_loss",
             "cohort_hash", "survivor_count", "round_rejected")


# ------------------------------------------------------------- validation


def test_config_validation():
    with pytest.raises(ValueError, match="client_residency"):
        ExperimentConfig(client_residency="paged").validate()
    with pytest.raises(ValueError, match="vmap execution mode"):
        ExperimentConfig(
            client_residency="streamed", execution_mode="threaded"
        ).validate()
    # Single-host mesh sharding COMPOSES with streamed residency (the
    # streamer uploads straight into the client-axis PartitionSpec
    # layout); multi-host still refuses naming the cause (the host
    # shard store is single-process).
    ExperimentConfig(client_residency="streamed", mesh_devices=2).validate()
    with pytest.raises(ValueError, match="multihost"):
        ExperimentConfig(
            client_residency="streamed", multihost=True
        ).validate()
    ExperimentConfig(client_residency="streamed").validate()


def test_default_is_resident():
    assert ExperimentConfig().client_residency == "resident"


def test_shapley_refuses_streamed(tiny_config):
    """The Shapley family's subset re-evaluation assumes a resident
    per-client stack; the simulator refuses before any dispatch, naming
    the flag."""
    with pytest.raises(ValueError, match="client_residency"):
        _run(tiny_config, distributed_algorithm="multiround_shapley_value",
             client_residency="streamed")


def test_streamed_batched_persistent_state_refused(tiny_config):
    """Cohorts inside one fused dispatch may overlap and the host store
    cannot scatter mid-dispatch — streamed + rounds_per_dispatch>1 +
    persistent per-client state is refused with the cause."""
    with pytest.raises(ValueError, match="rounds_per_dispatch"):
        _run(tiny_config, worker_number=8, participation_fraction=0.5,
             reset_client_optimizer=False, client_residency="streamed",
             rounds_per_dispatch=2)


# ------------------------------------------ host shard store (jax-free)


def _store(n=6, shard=4, dim=3, state=False):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, shard, dim)).astype(np.float32)
    y = rng.integers(0, 10, size=(n, shard)).astype(np.int32)
    mask = np.ones((n, shard), dtype=np.float32)
    sizes = np.full(n, float(shard), dtype=np.float32)
    st = None
    if state:
        st = {"mom": rng.normal(size=(n, dim)).astype(np.float32),
              "count": np.zeros(n, dtype=np.int32)}
    return HostShardStore(x, y, mask, sizes, state=st)


def test_store_gather_matches_fancy_index():
    store = _store(state=True)
    idx = np.array([4, 1, 3])
    gx, gy, gm, gs = store.gather_data(idx)
    np.testing.assert_array_equal(gx, store.x[idx])
    np.testing.assert_array_equal(gy, store.y[idx])
    np.testing.assert_array_equal(gm, store.mask[idx])
    np.testing.assert_array_equal(gs, store.sizes[idx])
    gst = store.gather_state(idx)
    np.testing.assert_array_equal(gst["mom"], store.state["mom"][idx])


def test_store_gather_none_is_whole_population():
    store = _store()
    gx, gy, gm, gs = store.gather_data(None)
    assert gx is store.x and gs is store.sizes  # no copy
    assert store.gather_state(None) is None  # stateless store


def test_store_scatter_roundtrip_preserves_unselected_rows():
    store = _store(state=True)
    before = {k: v.copy() for k, v in store.state.items()}
    idx = np.array([0, 5, 2])
    update = {"mom": np.full((3, 3), 7.0, np.float32),
              "count": np.array([1, 2, 3], np.int32)}
    store.scatter_state(idx, update)
    np.testing.assert_array_equal(store.state["mom"][idx], update["mom"])
    np.testing.assert_array_equal(store.state["count"][idx], update["count"])
    untouched = np.setdiff1d(np.arange(6), idx)
    np.testing.assert_array_equal(
        store.state["mom"][untouched], before["mom"][untouched]
    )


def test_store_index_out_of_range_rejected():
    store = _store(state=True)
    with pytest.raises(IndexError, match="out of range"):
        store.gather_data(np.array([0, 6]))
    with pytest.raises(IndexError, match="out of range"):
        store.scatter_state(np.array([-1]), store.gather_state(np.array([0])))


def test_store_axis_mismatch_rejected():
    x = np.zeros((4, 2, 3), np.float32)
    with pytest.raises(ValueError, match="length mismatch"):
        HostShardStore(x, np.zeros((3, 2), np.int32),
                       np.ones((4, 2), np.float32), np.ones(4, np.float32))
    with pytest.raises(ValueError, match="client-axis length"):
        HostShardStore(x, np.zeros((4, 2), np.int32),
                       np.ones((4, 2), np.float32), np.ones(4, np.float32),
                       state={"mom": np.zeros((5, 3), np.float32)})


def test_tree_map_np_handles_namedtuples():
    import collections

    Opt = collections.namedtuple("Opt", ["mu", "nu"])
    tree = {"o": Opt(np.ones(2), np.zeros(2)), "none": None,
            "l": [np.full(2, 3.0)]}
    doubled = tree_map_np(lambda a: a * 2, tree)
    assert isinstance(doubled["o"], Opt)
    np.testing.assert_array_equal(doubled["o"].mu, np.full(2, 2.0))
    assert doubled["none"] is None
    np.testing.assert_array_equal(doubled["l"][0], np.full(2, 6.0))
    assert tree_bytes(tree) == 3 * 2 * 8  # three f64[2] leaves


def test_store_bytes_accounting_scales_by_cohort():
    store = _store(n=6, shard=4, dim=3)
    assert store.data_bytes() == (store.x.nbytes + store.y.nbytes
                                  + store.mask.nbytes + store.sizes.nbytes)
    assert store.cohort_data_bytes(2) * 3 == store.data_bytes()


def test_synthetic_stream_shards_layout():
    """The vectorized population generator must produce the packed
    ClientData layout (uint8-compact x, int32 y, full masks) at any N —
    pack_client_shards' Python loop is what it replaces at the million
    scale."""
    rng = np.random.default_rng(0)
    x_train = rng.uniform(size=(32, 2, 2, 1)).astype(np.float32)
    y_train = rng.integers(0, 10, size=32)
    cd = synthetic_stream_shards(x_train, y_train, n_clients=50,
                                 shard_size=8, seed=1)
    assert cd.x.shape == (50, 8, 4) and cd.x.dtype == np.uint8
    assert cd.y.shape == (50, 8) and cd.y.dtype == np.int32
    assert cd.mask.shape == (50, 8) and float(cd.mask.min()) == 1.0
    assert cd.sample_shape == (2, 2, 1)
    # Deterministic in the seed.
    cd2 = synthetic_stream_shards(x_train, y_train, 50, 8, seed=1)
    np.testing.assert_array_equal(cd.x, cd2.x)
    # Out-of-[0,1] pools keep float32 + sample shape, like
    # pack_client_shards' range fallback (uint8 would clip the data).
    gauss = rng.normal(size=(32, 2, 2, 1)).astype(np.float32)
    cd3 = synthetic_stream_shards(gauss, y_train, 10, 4, seed=1)
    assert cd3.x.dtype == np.float32 and cd3.x.shape == (10, 4, 2, 2, 1)


# -------------------------------------------------- budget model refusals


def test_residency_feasibility_names_the_flag(monkeypatch):
    """An over-budget resident run must refuse up front naming
    client_residency (not die as an opaque allocation failure); the
    streamed check sizes by the double-buffered cohort instead."""
    import distributed_learning_simulator_tpu.simulator as sim

    monkeypatch.setattr(sim, "_device_budget_bytes", lambda cfg: 1024.0)
    cfg = ExperimentConfig(worker_number=8, participation_fraction=0.25)
    params = {"w": np.zeros((4, 4), np.float32)}
    with pytest.raises(ValueError, match="client_residency='streamed'"):
        sim._assert_residency_feasible(cfg, params, 8, data_bytes=1 << 20)
    cfg_s = dataclasses.replace(cfg, client_residency="streamed")
    with pytest.raises(ValueError, match="cohort footprint"):
        sim._assert_residency_feasible(cfg_s, params, 8, data_bytes=1 << 20)
    # The streamed budget is 2 x cohort x per-client bytes — a population
    # far over budget passes once the cohort slice fits.
    monkeypatch.setattr(sim, "_device_budget_bytes", lambda cfg: 600_000.0)
    sim._assert_residency_feasible(cfg_s, params, 8, data_bytes=1 << 20)
    with pytest.raises(ValueError, match="client_residency='resident'"):
        sim._assert_residency_feasible(cfg, params, 8, data_bytes=1 << 20)
    # Full-cohort streamed (participation 1.0, e.g. sign_SGD): ONE
    # startup upload, no double buffer — 1x data must fit, not 2x.
    cfg_full = dataclasses.replace(cfg_s, participation_fraction=1.0)
    monkeypatch.setattr(
        sim, "_device_budget_bytes", lambda cfg: 1.5 * (1 << 20)
    )
    sim._assert_residency_feasible(cfg_full, params, 8, data_bytes=1 << 20)
    monkeypatch.setattr(sim, "_device_budget_bytes", lambda cfg: 900_000.0)
    with pytest.raises(ValueError, match="full-cohort"):
        sim._assert_residency_feasible(cfg_full, params, 8,
                                       data_bytes=1 << 20)


# ------------------------------------------------------------ bit identity


def test_streamed_matches_resident_fedavg_full_feature(tiny_config):
    """FedAvg with participation sampling, dropout faults, quorum, and a
    cosine schedule: the streamed history reproduces the resident one
    bit-for-bit — cohort hashes (the sampling draws) and failure draws
    included."""
    cfg = dataclasses.replace(
        tiny_config, worker_number=8, round=3, participation_fraction=0.5,
        failure_mode="dropout", failure_prob=0.3, min_survivors=1,
        lr_schedule="cosine",
    )
    base = _series(_run(cfg), *_BIT_KEYS, "lr_factor")
    streamed = _series(
        _run(cfg, client_residency="streamed"), *_BIT_KEYS, "lr_factor"
    )
    assert base == streamed
    assert None not in base["cohort_hash"]  # sampling actually exercised


def test_streamed_matches_resident_batched_k3(tiny_config):
    """rounds_per_dispatch=3 over 4 rounds (remainder dispatch included):
    the streamed scan consumes stacked [k, cohort, ...] uploads whose
    cohorts were host-replayed from the key chain — bit-identical to the
    resident batched program AND to the K=1 loop."""
    cfg = dataclasses.replace(
        tiny_config, worker_number=8, round=4, participation_fraction=0.5,
        server_optimizer_name="sgd", server_learning_rate=1.0,
        server_momentum=0.9,
    )
    base = _series(_run(cfg), *_BIT_KEYS)
    assert base == _series(
        _run(cfg, client_residency="streamed", rounds_per_dispatch=3),
        *_BIT_KEYS,
    )


def test_streamed_matches_resident_sign_sgd_momentum(tiny_config):
    """sign_SGD's per-step vote synchronizes the whole population — the
    full-cohort streamed regime (one startup upload, resident program
    shape) including persistent momentum buffers."""
    cfg = dataclasses.replace(
        tiny_config, distributed_algorithm="sign_SGD", learning_rate=0.01,
        momentum=0.9, round=3,
    )
    keys = ("test_accuracy", "test_loss", "mean_client_loss",
            "uplink_compression_ratio")
    assert _series(_run(cfg), *keys) == _series(
        _run(cfg, client_residency="streamed"), *keys
    )


def test_streamed_matches_resident_fed_quant(tiny_config):
    cfg = dataclasses.replace(
        tiny_config, distributed_algorithm="fed_quant", worker_number=8,
        round=3, participation_fraction=0.5,
    )
    keys = ("test_accuracy", "test_loss", "cohort_hash",
            "uplink_compression_ratio")
    assert _series(_run(cfg), *keys) == _series(
        _run(cfg, client_residency="streamed"), *keys
    )


def test_streamed_matches_resident_persistent_client_state(tiny_config):
    """reset_client_optimizer=False under sampling: the cohort's
    optimizer state gathers from the host store and scatters back each
    round — the writeback path — and must still match the resident
    in-program gather/scatter bit-for-bit."""
    cfg = dataclasses.replace(
        tiny_config, worker_number=8, round=4, participation_fraction=0.5,
        reset_client_optimizer=False,
    )
    base = _series(_run(cfg), *_BIT_KEYS)
    assert base == _series(_run(cfg, client_residency="streamed"),
                           *_BIT_KEYS)


def test_streamed_checkpoint_resume_mid_run(tiny_config, tmp_path):
    """Kill/resume mid-run with persistent per-client state: the host
    store is the checkpoint source of truth, and the stitched streamed
    history equals the uninterrupted RESIDENT run bit-for-bit."""
    cfg = dataclasses.replace(
        tiny_config, worker_number=8, round=5, participation_fraction=0.5,
        reset_client_optimizer=False,
    )
    golden = [h["test_accuracy"] for h in _run(cfg)["history"]]

    ckpt = str(tmp_path / "ckpt")
    first = _run(cfg, round=3, client_residency="streamed",
                 checkpoint_dir=ckpt, checkpoint_every=2)
    resumed = _run(cfg, client_residency="streamed", checkpoint_dir=ckpt,
                   checkpoint_every=2, resume=True)
    # Last checkpoint is round_1.ckpt: the resumed run replays round 2
    # (the chaos-resume replay discipline) then continues to 4.
    assert [h["round"] for h in resumed["history"]] == [2, 3, 4]
    stitched = [h["test_accuracy"] for h in first["history"][:2]] + [
        h["test_accuracy"] for h in resumed["history"]
    ]
    assert stitched == golden


# ------------------------------------------- mesh composition (ISSUE 10)
#
# Streamed residency composes with single-host mesh sharding: the
# streamer uploads each cohort slice directly into the client-axis
# PartitionSpec layout (per-shard host->device transfers addressed by
# the mesh's client-axis ownership; parallel/streaming.py). The pins:
# cohort draws (the round-key replay) are BIT-identical across every
# residency x mesh combination, and for a FIXED mesh the streamed run
# equals the resident run — streaming is a residency detail, never a
# semantics change. Mesh-vs-single-device metric equality is to
# reduction-order tolerance, the same contract the resident mesh tests
# (test_multichip.py) have always pinned: sharding the f32 client-axis
# reduction reorders the sum.


def _mesh_series(cfg, *keys, **overrides):
    res = _run(cfg, **overrides)
    return {k: [h.get(k) for h in res["history"]] for k in keys}


def test_streamed_mesh_matches_resident_mesh_fedavg(tiny_config):
    """FedAvg sampled cohort, same 4-device mesh: streamed (uploaded
    pre-gathered sharded slices) vs resident (in-program gather from
    the sharded population) — bit-equal cohort draws, metrics equal to
    reduction-order tolerance."""
    cfg = dataclasses.replace(
        tiny_config, worker_number=16, round=3, participation_fraction=0.5,
        mesh_devices=4,
    )
    resident = _mesh_series(cfg, *_BIT_KEYS)
    streamed = _mesh_series(cfg, *_BIT_KEYS, client_residency="streamed")
    assert streamed["cohort_hash"] == resident["cohort_hash"]
    assert None not in streamed["cohort_hash"]
    np.testing.assert_allclose(
        streamed["test_loss"], resident["test_loss"], atol=1e-4
    )
    np.testing.assert_allclose(
        streamed["test_accuracy"], resident["test_accuracy"], atol=1e-3
    )


def test_streamed_mesh_matches_streamed_single_device(tiny_config):
    """Same streamed program, mesh vs one device: cohort draws
    bit-equal (the host replay never touches the mesh), metrics to the
    mesh reduction-order tolerance — and the hashed O(cohort) sampler
    composes identically."""
    for sampler in ("exact", "hashed"):
        cfg = dataclasses.replace(
            tiny_config, worker_number=16, round=3,
            participation_fraction=0.5, client_residency="streamed",
            participation_sampler=sampler,
        )
        single = _mesh_series(cfg, *_BIT_KEYS)
        mesh = _mesh_series(cfg, *_BIT_KEYS, mesh_devices=4)
        assert mesh["cohort_hash"] == single["cohort_hash"], sampler
        np.testing.assert_allclose(
            mesh["test_loss"], single["test_loss"], atol=1e-4
        )


def test_streamed_mesh_sign_sgd_full_cohort(tiny_config):
    """sign_SGD (full-cohort streamed regime: one startup upload,
    population-shaped and mesh-sharded): bit-identical to the resident
    mesh run — the discrete per-step vote quantizes away reduction
    noise."""
    cfg = dataclasses.replace(
        tiny_config, distributed_algorithm="sign_SGD", learning_rate=0.01,
        momentum=0.9, worker_number=16, round=2, mesh_devices=4,
    )
    keys = ("test_accuracy", "test_loss", "mean_client_loss")
    assert _mesh_series(cfg, *keys) == _mesh_series(
        cfg, *keys, client_residency="streamed"
    )


def test_streamed_mesh_fed_quant(tiny_config):
    """fed_quant, same mesh: bit-equal cohorts; the stochastic
    quantizer DISCRETIZES reduction-order ulps into visible (but
    bounded) metric deltas, so the tolerance is looser than plain
    fed's."""
    cfg = dataclasses.replace(
        tiny_config, distributed_algorithm="fed_quant", worker_number=16,
        round=3, participation_fraction=0.5, mesh_devices=4,
        client_eval=False,
    )
    resident = _mesh_series(cfg, *_BIT_KEYS)
    streamed = _mesh_series(cfg, *_BIT_KEYS, client_residency="streamed")
    assert streamed["cohort_hash"] == resident["cohort_hash"]
    np.testing.assert_allclose(
        streamed["test_loss"], resident["test_loss"], atol=5e-3
    )


def test_streamed_mesh_batched_and_persistent_state(tiny_config):
    """The remaining composition axes on one mesh: K>1 batched scan
    dispatches (stacked [K, cohort, ...] sharded uploads) and the
    persistent-state writeback path (sharded cohort state gathered
    from and scattered back to the host store)."""
    base = dataclasses.replace(
        tiny_config, worker_number=16, round=4, participation_fraction=0.5,
        mesh_devices=4, client_residency="streamed",
    )
    for overrides in (
        {"rounds_per_dispatch": 2},
        {"reset_client_optimizer": False},
    ):
        cfg = dataclasses.replace(base, **overrides)
        streamed = _mesh_series(cfg, *_BIT_KEYS)
        resident = _mesh_series(cfg, *_BIT_KEYS,
                                client_residency="resident")
        assert streamed["cohort_hash"] == resident["cohort_hash"], overrides
        np.testing.assert_allclose(
            streamed["test_loss"], resident["test_loss"], atol=1e-4,
        )


def test_streamed_mesh_cohort_divisibility_refused(tiny_config):
    """Unsupported combination still refuses naming the cause: the
    COHORT (not the population) is the device-resident client axis
    under streamed sampling, so it must divide the mesh."""
    cfg = dataclasses.replace(
        tiny_config, worker_number=16, round=2, participation_fraction=0.5,
        client_residency="streamed", mesh_devices=3,
    )
    with pytest.raises(ValueError, match="cohort size"):
        _run(cfg)


# ------------------------------------------------------ stream telemetry


def test_stream_records_and_result_fields(tiny_config, tmp_path):
    """Streamed runs emit the schema-v5 stream sub-object (validated
    against the checked-in JSON schema) and the result dict's transfer
    totals; resident runs stay pinned at the pre-feature layout with no
    stream fields."""
    jsonschema = pytest.importorskip("jsonschema")
    cfg = dataclasses.replace(
        tiny_config, worker_number=8, round=3, participation_fraction=0.5,
        # momentum gives the persistent client state real bytes (plain
        # sgd's optax state is empty — nothing to write back).
        reset_client_optimizer=False, momentum=0.9,
    )
    res = run_simulation(dataclasses.replace(
        cfg, client_residency="streamed", log_root=str(tmp_path / "s")
    ))
    assert res["client_residency"] == "streamed"
    assert 0.0 <= res["stream_overlap_ratio"] <= 1.0
    assert res["stream_h2d_bytes"] > 0
    assert res["stream_d2h_bytes"] > 0  # persistent state wrote back
    records = _read_metrics(tmp_path / "s")
    schema = json.load(open(
        os.path.join(os.path.dirname(__file__), "data",
                     "metrics_record.schema.json")
    ))
    assert len(records) == 3
    for rec in records:
        assert rec["schema_version"] == 5
        jsonschema.validate(rec, schema)
        assert rec["stream"]["h2d_bytes"] > 0

    resident = run_simulation(
        dataclasses.replace(cfg, log_root=str(tmp_path / "r"))
    )
    assert resident["stream_overlap_ratio"] is None
    for rec in _read_metrics(tmp_path / "r"):
        assert "stream" not in rec and "schema_version" not in rec


def test_sample_phase_and_stream_sampler_fields(tiny_config, tmp_path):
    """The cohort-draw replay cost is visible end to end: `sample` in
    the telemetry phase table (carved out of the client_step window it
    overlaps), sampler/sample_ms in the schema-v5 stream record, and
    the run total in the result dict — for both sampler modes."""
    jsonschema = pytest.importorskip("jsonschema")
    schema = json.load(open(
        os.path.join(os.path.dirname(__file__), "data",
                     "metrics_record.schema.json")
    ))
    for sampler in ("exact", "hashed"):
        root = tmp_path / sampler
        res = run_simulation(dataclasses.replace(
            tiny_config, worker_number=8, round=3,
            participation_fraction=0.5, client_residency="streamed",
            participation_sampler=sampler, telemetry_level="basic",
            log_root=str(root),
        ))
        assert res["participation_sampler"] == sampler
        assert res["stream_sample_seconds"] > 0
        records = _read_metrics(root)
        for rec in records:
            jsonschema.validate(rec, schema)
            assert rec["stream"]["sampler"] == sampler
            assert rec["stream"]["sample_ms"] >= 0
        # Every round with a prefetched next cohort records the draw in
        # its own `sample` phase (the final round draws nothing).
        phases = [rec["telemetry"]["phase_seconds"] for rec in records]
        assert all("sample" in p for p in phases[:-1])
        # Full-cohort streamed (no draw): no sampler fields, no phase.
        res_full = run_simulation(dataclasses.replace(
            tiny_config, worker_number=8, round=2,
            client_residency="streamed", participation_sampler=sampler,
            telemetry_level="basic", log_root=str(tmp_path / ("f" + sampler)),
        ))
        assert res_full["stream_sample_seconds"] == 0.0
        for rec in _read_metrics(tmp_path / ("f" + sampler)):
            assert "sampler" not in rec.get("stream", {})


def test_report_run_renders_transfer_row(tiny_config, tmp_path):
    """report_run.py over a streamed run's artifacts: the stream summary
    aggregates per-dispatch transfer stats and the terminal rendering
    carries the h2d transfer row."""
    import importlib.util

    cfg = dataclasses.replace(
        tiny_config, worker_number=8, round=3, participation_fraction=0.5,
        reset_client_optimizer=False, momentum=0.9,
        telemetry_level="basic", log_root=str(tmp_path / "art"),
        client_residency="streamed",
    )
    run_simulation(cfg)
    records = _read_metrics(tmp_path / "art")

    spec = importlib.util.spec_from_file_location(
        "report_run",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "report_run.py"),
    )
    report_run = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(report_run)
    summary = report_run.summarize_run(records)
    s = summary["stream"]
    assert s["uploads"] == 3 and s["h2d_bytes"] > 0 and s["d2h_bytes"] > 0
    assert 0.0 <= s["overlap_ratio"] <= 1.0
    text = "\n".join(report_run.render_summary(summary))
    assert "h2d_stream" in text and "streamed transfers: 3 upload(s)" in text


def test_streamed_batched_stream_record_on_last_round(tiny_config,
                                                      tmp_path):
    """K>1: ONE upload per dispatch; its stream record lands on the
    dispatch's last round (like the phase timings) stamped with
    dispatch_rounds."""
    cfg = dataclasses.replace(
        tiny_config, worker_number=8, round=4, participation_fraction=0.5,
        rounds_per_dispatch=2, client_residency="streamed",
        log_root=str(tmp_path / "b"),
    )
    run_simulation(cfg)
    records = _read_metrics(tmp_path / "b")
    assert [("stream" in r) for r in records] == [False, True, False, True]
    assert records[1]["stream"]["dispatch_rounds"] == 2
