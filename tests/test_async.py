"""Asynchronous federation (config.async_mode; robustness/arrivals.py).

The determinism contracts this file pins:

* ``async_mode='off'`` (the default) never constructs the machinery
  (``AsyncFederation.from_config`` is None even with arrival knobs set).
* The COMPILED async program at ``round_deadline=inf`` is bit-identical
  to synchronous FedAvg — participation sampling, failure draws, quorum
  verdicts, and cohort hashes included (the degenerate-equivalence
  contract).
* The staleness discount and the buffer insert/trigger/apply math match
  a hand-computed 3-client trace.
* ``rounds_per_dispatch`` carries the buffer state as a scan carry:
  K>1 history equals K=1 bit-for-bit.
* Checkpoint/resume replays the buffer bit-exactly; config/checkpoint
  async mismatches are refused with the cause.
* sign_SGD, the Shapley servers, and the threaded oracle refuse
  ``async_mode='on'`` with a single-line error naming the flag.
"""

import dataclasses
import glob
import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_learning_simulator_tpu.config import ExperimentConfig
from distributed_learning_simulator_tpu.robustness.arrivals import (
    AsyncFederation,
    staleness_discount,
)
from distributed_learning_simulator_tpu.simulator import run_simulation


def _run(cfg, **overrides):
    cfg = dataclasses.replace(cfg, **overrides)
    return run_simulation(cfg, setup_logging=False)


def _series(result, *keys):
    return {k: [h.get(k) for h in result["history"]] for k in keys}


_ASYNC_ON = dict(
    async_mode="on", arrival_model="bimodal", arrival_slow_fraction=0.4,
    arrival_slow_factor=8.0, round_deadline=1.5, async_buffer_size=3,
    staleness_alpha=0.5,
)


# ------------------------------------------------------------- validation


def test_config_validation():
    ExperimentConfig(**_ASYNC_ON).validate()
    with pytest.raises(ValueError, match="async_mode"):
        ExperimentConfig(async_mode="sometimes").validate()
    with pytest.raises(ValueError, match="arrival_model"):
        ExperimentConfig(
            async_mode="on", arrival_model="gaussian"
        ).validate()
    with pytest.raises(ValueError, match="arrival_model"):
        ExperimentConfig(async_mode="on").validate()  # none + on
    with pytest.raises(ValueError, match="round_deadline"):
        ExperimentConfig(
            async_mode="on", arrival_model="bimodal", round_deadline=0.0
        ).validate()
    with pytest.raises(ValueError, match="async_buffer_size"):
        ExperimentConfig(
            async_mode="on", arrival_model="bimodal", async_buffer_size=0
        ).validate()
    with pytest.raises(ValueError, match="staleness_alpha"):
        ExperimentConfig(
            async_mode="on", arrival_model="bimodal", staleness_alpha=-0.1
        ).validate()
    with pytest.raises(ValueError, match="arrival_slow_fraction"):
        ExperimentConfig(
            async_mode="on", arrival_model="bimodal",
            arrival_slow_fraction=1.5,
        ).validate()


def test_off_mode_constructs_nothing():
    """The off-gate: arrival knobs set but async_mode='off' never builds
    the machinery — the round program is the exact pre-feature one."""
    cfg = ExperimentConfig(
        arrival_model="bimodal", round_deadline=1.0, async_buffer_size=2
    ).validate()
    assert cfg.async_mode == "off"
    assert AsyncFederation.from_config(cfg) is None


def test_refusals(tiny_config):
    """sign_SGD, Shapley, and the threaded oracle refuse with the flag
    named — same style as supports_round_batching."""
    with pytest.raises(ValueError, match="async_mode"):
        _run(tiny_config, distributed_algorithm="sign_SGD",
             learning_rate=0.01, **_ASYNC_ON)
    from distributed_learning_simulator_tpu.algorithms.shapley import (
        GTGShapley,
        MultiRoundShapley,
    )

    for cls in (MultiRoundShapley, GTGShapley):
        with pytest.raises(ValueError, match="async_mode"):
            cls(dataclasses.replace(tiny_config, **_ASYNC_ON))
    with pytest.raises(ValueError, match="async_mode"):
        _run(tiny_config, execution_mode="threaded", **_ASYNC_ON)


# ------------------------------------------- hand-computed staleness math


def test_staleness_discount_hand_computed():
    """classify() against hand math: latency 0.5 is on time (s=0),
    1.7 is one round late ((1+1)^-0.5), 3.2 is three rounds late
    ((1+3)^-0.5); a forced straggler is late at s >= 1 even when its
    drawn latency beat the deadline."""
    af = AsyncFederation(
        arrival_model="bimodal", slow_fraction=0.2, slow_factor=8.0,
        sigma=0.5, seed=0, deadline=1.0, buffer_size=2, alpha=0.5,
    )
    lat = jnp.asarray([0.5, 1.7, 3.2])
    on_time, s, disc, eff = af.classify(lat)
    assert on_time.tolist() == [True, False, False]
    assert s.tolist() == [0.0, 1.0, 3.0]
    assert eff.tolist() == lat.tolist()  # nothing forced: drawn latencies
    np.testing.assert_allclose(
        np.asarray(disc), [1.0, 2.0 ** -0.5, 4.0 ** -0.5], rtol=1e-6
    )
    forced = jnp.asarray([True, False, False])
    on_time_f, s_f, disc_f, eff_f = af.classify(lat, forced)
    assert on_time_f.tolist() == [False, False, False]
    assert s_f.tolist() == [1.0, 1.0, 3.0]
    np.testing.assert_allclose(float(disc_f[0]), 2.0 ** -0.5, rtol=1e-6)
    # The routed straggler's upload is delayed one full deadline, so the
    # simulated clock pays for it: the sync counterfactual now waits 1.5
    # (vs its 0.5 drawn arrival), not the on-time latency.
    np.testing.assert_allclose(np.asarray(eff_f), [1.5, 1.7, 3.2], rtol=1e-6)
    # deadline=inf: nobody is naturally late, staleness 0 across the board;
    # forced clients keep their drawn latency (finite telemetry).
    af_inf = dataclasses.replace(af, deadline=float("inf"))
    on_inf, s_inf, _, eff_inf = af_inf.classify(lat)
    assert on_inf.all() and not s_inf.any()
    _, s_inf_f, _, eff_inf_f = af_inf.classify(lat, forced)
    assert s_inf_f.tolist() == [1.0, 0.0, 0.0]
    assert eff_inf_f.tolist() == lat.tolist()
    np.testing.assert_allclose(
        float(staleness_discount(jnp.float32(3.0), 1.0)), 0.25, rtol=1e-6
    )


def test_buffer_trace_hand_computed_3_clients():
    """absorb_and_apply against a hand-computed 3-client scalar trace.

    Client A (size 3) beats the deadline with params 12; B (size 2,
    one round late, discount 1/2) uploads 16; C (size 1, three rounds
    late, discount 1/4) uploads 6. Global is 10, so the discounted late
    sum is 1.0*16 + 0.25*6 = 17.5 at weight 1.25 — a buffered delta of
    17.5 - 1.25*10 = 5.0. With K=2 the trigger fires immediately:
    beta = 1.25/(3 + 1.25) = 5/17 and the mix is
    10 + (12/17)*(12-10) + (5/17)*(5/1.25) = 10 + 44/17.
    """
    g = {"w": jnp.float32(10.0)}
    fresh = {"w": jnp.float32(12.0)}
    late_sum = {"w": jnp.float32(17.5)}
    a_tot = jnp.float32(3.0)
    b_tot = jnp.float32(1.25)
    n_late = jnp.int32(2)

    def make(K):
        return AsyncFederation(
            arrival_model="bimodal", slow_fraction=0.2, slow_factor=8.0,
            sigma=0.5, seed=0, deadline=1.0, buffer_size=K, alpha=1.0,
        )

    # K=2: insert + trigger in one round.
    af = make(2)
    state = af.init_state(g)
    new_g, applied, ins, nxt = af.absorb_and_apply(
        state, g, fresh, a_tot, late_sum, b_tot, n_late, jnp.float32(1.0)
    )
    assert bool(applied)
    np.testing.assert_allclose(
        float(new_g["w"]), 10.0 + 44.0 / 17.0, rtol=1e-6
    )
    # Inserted-but-not-reset state (what a rejected round keeps) holds
    # the hand-computed buffer; the normal next state reset it.
    np.testing.assert_allclose(float(ins["buf_sum"]["w"]), 5.0, rtol=1e-6)
    np.testing.assert_allclose(float(ins["buf_weight"]), 1.25, rtol=1e-6)
    assert int(ins["buf_count"]) == 2
    assert float(nxt["buf_sum"]["w"]) == 0.0
    assert float(nxt["buf_weight"]) == 0.0 and int(nxt["buf_count"]) == 0
    assert float(nxt["clock"]) == 1.0

    # K=3: same insert, no trigger — the fresh aggregate passes through
    # BIT-exactly and the buffer carries.
    af3 = make(3)
    new_g, applied, ins, nxt = af3.absorb_and_apply(
        af3.init_state(g), g, fresh, a_tot, late_sum, b_tot, n_late,
        jnp.float32(1.0),
    )
    assert not bool(applied)
    assert float(new_g["w"]) == 12.0
    np.testing.assert_allclose(float(nxt["buf_sum"]["w"]), 5.0, rtol=1e-6)
    assert int(nxt["buf_count"]) == 2

    # Second round on the carried buffer: one more late upload (size 2,
    # discount 1/2, params 20 vs global 12) tips the count to 3: buffer
    # becomes 5 + (20 - 12) = 13 at weight 2.25; beta = 2.25/(3 + 2.25).
    fresh2 = {"w": jnp.float32(14.0)}
    new_g2, applied2, _, nxt2 = af3.absorb_and_apply(
        nxt, {"w": jnp.float32(12.0)}, fresh2, a_tot,
        {"w": jnp.float32(1.0 * 20.0)}, jnp.float32(1.0), jnp.int32(1),
        jnp.float32(1.0),
    )
    assert bool(applied2)
    beta = 2.25 / 5.25
    expect = 12.0 + (1 - beta) * 2.0 + beta * (13.0 / 2.25)
    np.testing.assert_allclose(float(new_g2["w"]), expect, rtol=1e-6)
    assert int(nxt2["buf_count"]) == 0 and float(nxt2["clock"]) == 2.0

    # Non-finite late batch: dropped whole at insertion, buffer intact.
    new_g3, applied3, _, nxt3 = af3.absorb_and_apply(
        af3.init_state(g), g, fresh, a_tot, {"w": jnp.float32(float("nan"))},
        b_tot, n_late, jnp.float32(1.0),
    )
    assert not bool(applied3)
    assert float(new_g3["w"]) == 12.0
    assert float(nxt3["buf_sum"]["w"]) == 0.0 and int(nxt3["buf_count"]) == 0


# ------------------------------------------------ degenerate equivalence


def test_deadline_inf_bit_identical_to_sync(tiny_config):
    """The COMPILED async program at round_deadline=inf reproduces sync
    FedAvg bit-for-bit — participation sampling, dropout failure draws,
    quorum verdicts, and cohort hashes included — and its records say
    nothing was ever late or buffered."""
    cfg = dataclasses.replace(
        tiny_config, worker_number=8, round=3,
        participation_fraction=0.5, failure_mode="dropout",
        failure_prob=0.3, min_survivors=1,
    )
    keys = ("test_accuracy", "test_loss", "mean_client_loss",
            "survivor_count", "round_rejected", "cohort_hash")
    sync = _run(cfg)
    base = _series(sync, *keys)
    assert None not in base["cohort_hash"]  # sampling actually exercised
    a = _run(cfg, **{**_ASYNC_ON, "round_deadline": float("inf"),
                     "async_buffer_size": 4})
    assert _series(a, *keys) == base
    for h in a["history"]:
        rec = h["async"]
        assert rec["late"] == 0 and rec["buffer"] == 0
        assert not rec["applied"] and rec["mean_staleness"] is None
        # Closing at max latency == the sync counterfactual: no simulated
        # speedup to claim.
        assert rec["sim_round_s"] == rec["sim_round_sync_s"]
    assert a["async_speedup_ratio"] == 1.0
    assert sync["async_speedup_ratio"] is None  # off-mode result key


# ------------------------------------------------- deadline + buffer runs


def test_all_slow_cohort_buffers_then_applies(tiny_config, tmp_path):
    """arrival_slow_fraction=1 at deadline 1.0 makes EVERY upload late
    (slow factor 8, jitter >= 0.5 -> latency >= 4): rounds buffer 4
    uploads each; with K=6 the trigger first fires in round 1. The
    model must not move before the first apply, records must carry the
    v4 async sub-object (schema-validated), and report_run must render
    the staleness section."""
    import importlib.util

    import jsonschema

    cfg = dataclasses.replace(
        tiny_config, round=3, log_root=str(tmp_path / "log"),
        **{**_ASYNC_ON, "arrival_slow_fraction": 1.0,
           "round_deadline": 1.0, "async_buffer_size": 6},
    )
    result = run_simulation(cfg)
    recs = [h["async"] for h in result["history"]]
    assert [r["on_time"] for r in recs] == [0, 0, 0]
    assert [r["late"] for r in recs] == [4, 4, 4]
    assert [r["applied"] for r in recs] == [False, True, False]
    assert [r["buffer"] for r in recs] == [4, 0, 4]
    assert all(r["mean_staleness"] >= 3.0 for r in recs)
    # Deadline rounds close at 1.0 simulated second; sync would wait for
    # the slowest (>= 4.0) — the measured simulated-throughput win.
    assert all(r["sim_round_s"] == 1.0 for r in recs)
    assert result["async_speedup_ratio"] > 3.0
    assert result["sim_clock_seconds"] == pytest.approx(3.0)
    assert result["mean_buffer_occupancy"] == pytest.approx(8.0 / 3.0)
    # Model frozen until the buffer first applies (round 0 has no fresh
    # uploads and no trigger), then moves.
    accs = [h["test_accuracy"] for h in result["history"]]
    losses = [h["test_loss"] for h in result["history"]]
    assert losses[1] != losses[0] or accs[1] != accs[0]

    paths = glob.glob(os.path.join(cfg.log_root, "**", "metrics.jsonl"),
                      recursive=True)
    with open(paths[0]) as f:
        records = [json.loads(line) for line in f]
    with open(os.path.join(os.path.dirname(__file__), "data",
                           "metrics_record.schema.json")) as f:
        schema = json.load(f)
    for r in records:
        assert r["schema_version"] == 4
        jsonschema.validate(r, schema)

    spec = importlib.util.spec_from_file_location(
        "report_run",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "report_run.py"),
    )
    report_run = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(report_run)
    summary = report_run.summarize_run(records)
    asy = summary["async_federation"]
    assert asy["rounds_reported"] == 3 and asy["applied_rounds"] == 1
    assert asy["late_total"] == 12 and asy["speedup_vs_sync"] > 3.0
    assert asy["staleness_histogram"]  # non-empty integer buckets
    rendered = "\n".join(report_run.render_summary(summary))
    assert "async federation" in rendered
    assert "staleness histogram" in rendered
    assert "speedup" in rendered


def test_straggler_fault_routes_into_buffer(tiny_config):
    """Satellite contract (robustness/faults.py): with the arrival model
    on, straggler-failed clients arrive AFTER the deadline — buffered,
    applied later, counted as survivors — instead of being discarded.
    The sync straggler run at failure_prob=1 never moves the model; the
    async run does once the buffer fires, and no round is rejected even
    with min_survivors at the full cohort."""
    cfg = dataclasses.replace(
        tiny_config, round=3, failure_mode="straggler", failure_prob=1.0,
        min_survivors=4,
    )
    sync = _run(cfg)
    assert len({h["test_loss"] for h in sync["history"]}) == 1  # frozen
    a = _run(cfg, **{**_ASYNC_ON, "round_deadline": float("inf"),
                     "async_buffer_size": 5})
    recs = [h["async"] for h in a["history"]]
    # Forced-late stragglers: staleness floored at 1 even at deadline=inf.
    assert [r["late"] for r in recs] == [4, 4, 4]
    assert all(r["mean_staleness"] == 1.0 for r in recs)
    assert [r["applied"] for r in recs] == [False, True, False]
    assert [h["survivor_count"] for h in a["history"]] == [4, 4, 4]
    assert not any(h["round_rejected"] for h in a["history"])
    assert len({h["test_loss"] for h in a["history"]}) > 1  # model moved


# ------------------------------------------------- composition contracts


def test_k2_matches_k1_with_faults_and_sampling(tiny_config):
    """rounds_per_dispatch carries the buffer as the scan carry: K=2
    (dispatch sizes 2 then 1) reproduces the K=1 async history
    bit-for-bit under sampling + dropout faults + quorum."""
    cfg = dataclasses.replace(
        tiny_config, worker_number=8, round=3,
        participation_fraction=0.5, failure_mode="dropout",
        failure_prob=0.3, min_survivors=1, **_ASYNC_ON,
    )
    keys = ("test_accuracy", "test_loss", "mean_client_loss",
            "survivor_count", "round_rejected", "cohort_hash", "async")
    assert _series(_run(cfg), *keys) == _series(
        _run(cfg, rounds_per_dispatch=2), *keys
    )


def test_checkpoint_resume_replays_buffer(tiny_config, tmp_path):
    """The buffer carry is checkpointed: an interrupted async run
    resumes bit-identically to the uninterrupted one (buffer occupancy
    and apply rounds included), and async on/off mismatches between
    config and checkpoint are refused with the cause."""
    cfg = dataclasses.replace(
        tiny_config, round=4,
        **{**_ASYNC_ON, "arrival_slow_fraction": 1.0,
           "round_deadline": 1.0, "async_buffer_size": 6},
    )
    golden = _series(_run(cfg), "test_accuracy", "async")

    ckpt = str(tmp_path / "ckpt")
    first = _run(cfg, round=2, checkpoint_dir=ckpt, checkpoint_every=2)
    resumed = _run(cfg, checkpoint_dir=ckpt, checkpoint_every=2,
                   resume=True)
    stitched = {
        k: [h.get(k) for h in first["history"]]
        + [h.get(k) for h in resumed["history"]]
        for k in ("test_accuracy", "async")
    }
    assert stitched == golden

    with pytest.raises(ValueError, match="async_mode"):
        _run(tiny_config, checkpoint_dir=ckpt, resume=True)
    sync_ckpt = str(tmp_path / "sync_ckpt")
    _run(tiny_config, round=2, checkpoint_dir=sync_ckpt, checkpoint_every=2)
    with pytest.raises(ValueError, match="staleness-buffer"):
        _run(cfg, checkpoint_dir=sync_ckpt, resume=True)
