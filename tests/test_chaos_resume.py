"""Chaos harness: crash at a chosen round, resume, assert the stitched
history is BIT-identical to the uninterrupted run — including cohort
sampling (participation_fraction < 1) and failure-model draws after
resume (the checkpointed rng_key chain is what makes this hold)."""

import dataclasses
import importlib.util
import json
import os
import signal
import subprocess
import sys

import pytest

from distributed_learning_simulator_tpu.robustness.chaos import InjectedCrash
from distributed_learning_simulator_tpu.simulator import run_simulation

_SCRIPT = os.path.join(
    os.path.dirname(__file__), "..", "scripts", "chaos_resume.py"
)
_spec = importlib.util.spec_from_file_location("chaos_resume", _SCRIPT)
chaos = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(chaos)


def _chaos_config(tmp_path, leg, rounds=5, **overrides):
    return chaos.chaos_config(str(tmp_path), leg, rounds, **overrides)


def _child_env():
    """Fresh-interpreter env: pin CPU (the conftest pins via jax.config,
    which a child doesn't inherit) and drop the 8-virtual-device flag."""
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    return env


def test_inprocess_crash_resume_bit_identical(tmp_path, monkeypatch):
    straight = chaos.normalize(
        run_simulation(_chaos_config(tmp_path, "straight"))["history"]
    )
    cfg = _chaos_config(
        tmp_path, "crash",
        checkpoint_dir=str(tmp_path / "crash" / "ckpt"), checkpoint_every=1,
    )
    monkeypatch.setenv("DLS_CRASH_AT_ROUND", "2")
    monkeypatch.setenv("DLS_CRASH_KIND", "raise")
    with pytest.raises(InjectedCrash):
        run_simulation(cfg)
    monkeypatch.delenv("DLS_CRASH_AT_ROUND")
    crashed = chaos.read_metrics_jsonl(cfg.log_root)
    assert crashed, "crashed run flushed no metrics records"
    resumed = chaos.run_resumed(cfg)
    verdict = chaos.stitch_and_compare(straight, crashed, resumed)
    assert verdict["bit_identical"], verdict
    # The workload's records carry the resume-sensitive telemetry, so
    # bit-identity above really did compare sampling + failure draws.
    assert all(
        "cohort_hash" in r and "survivor_count" in r for r in straight
    )


def test_subprocess_sigkill_resume_bit_identical(tmp_path):
    straight = chaos.normalize(
        run_simulation(_chaos_config(tmp_path, "straight"))["history"]
    )
    # checkpoint_every=2 with the kill at round 2: resume must also
    # bit-exactly REPLAY a round past the newest surviving checkpoint.
    cfg = _chaos_config(
        tmp_path, "sigkill",
        checkpoint_dir=str(tmp_path / "sigkill" / "ckpt"), checkpoint_every=2,
    )
    proc = subprocess.run(
        [sys.executable, _SCRIPT, "--child",
         "--config", json.dumps(vars(cfg))],
        env={**_child_env(), "DLS_CRASH_AT_ROUND": "2",
             "DLS_CRASH_KIND": "sigkill"},
        capture_output=True, text=True, timeout=420,
        cwd=os.path.join(os.path.dirname(__file__), ".."),
    )
    assert proc.returncode == -signal.SIGKILL, (proc.returncode, proc.stderr)
    crashed = chaos.read_metrics_jsonl(cfg.log_root)
    assert crashed, "SIGKILLed run flushed no metrics records"
    resumed = chaos.run_resumed(cfg)
    verdict = chaos.stitch_and_compare(straight, crashed, resumed)
    assert verdict["bit_identical"], verdict


def test_sigterm_grace_checkpoint_and_resume(tmp_path):
    """SIGTERM (TPU preemption notice): finish the in-flight round, write a
    final checkpoint even with checkpoint_every=0, log 'preempted at round
    N', exit 0 — and the resumed tail must match the straight run."""
    straight = chaos.normalize(
        run_simulation(_chaos_config(tmp_path, "straight"))["history"]
    )
    ckpt_dir = tmp_path / "sigterm" / "ckpt"
    cfg = _chaos_config(
        tmp_path, "sigterm",
        checkpoint_dir=str(ckpt_dir), checkpoint_every=0,
    )
    proc = subprocess.run(
        [sys.executable, _SCRIPT, "--child",
         "--config", json.dumps(vars(cfg))],
        env={**_child_env(), "DLS_CRASH_AT_ROUND": "2",
             "DLS_CRASH_KIND": "sigterm"},
        capture_output=True, text=True, timeout=420,
        cwd=os.path.join(os.path.dirname(__file__), ".."),
    )
    assert proc.returncode == 0, (proc.returncode, proc.stderr[-800:])
    assert "preempted at round" in proc.stderr
    child_result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert child_result["preempted_at"] is not None
    # checkpoint_every=0: the ONLY checkpoint is the forced preemption one.
    ckpts = [f for f in os.listdir(ckpt_dir) if f.endswith(".ckpt")]
    assert ckpts == [f"round_{child_result['preempted_at']}.ckpt"]
    crashed = chaos.read_metrics_jsonl(cfg.log_root)
    resumed = chaos.run_resumed(cfg)
    verdict = chaos.stitch_and_compare(straight, crashed, resumed)
    assert verdict["bit_identical"], verdict


def test_subprocess_sigkill_mid_growth_resume_bit_identical(tmp_path):
    """Dynamic-population chaos variant (ISSUE 13): SIGKILL between
    checkpoints DURING active joins/departures/drift, resume, and the
    stitched history must be bit-identical to the uninterrupted dynamic
    run — the registration-stream cursor, alive mask, and grown shard
    store restored from the checkpoint, and the round past the newest
    checkpoint replayed (its events re-drawn from the restored key
    chain)."""
    dyn = dict(
        population="dynamic", join_rate=2.0, depart_rate=0.1,
        drift_fraction=0.5, drift_factor=0.8,
        participation_sampler="hashed", client_residency="streamed",
        min_survivors=1,
        # The chaos workload's dropout faults compose with churn; keep
        # them (the stitched comparison then covers fault draws, the
        # masked cohort stream, registration events, and drift at once).
    )
    straight = chaos.normalize(
        run_simulation(_chaos_config(tmp_path, "straight_dyn", **dyn))[
            "history"
        ]
    )
    assert any(r["population"]["joins"] for r in straight), (
        "workload drew no joins — the variant would not cover growth"
    )
    # checkpoint_every=2 with the kill at round 2: resume restores the
    # round-1 checkpoint (population cursor=1) and must bit-exactly
    # REPLAY round 2's events before continuing.
    cfg = _chaos_config(
        tmp_path, "sigkill_dyn",
        checkpoint_dir=str(tmp_path / "sigkill_dyn" / "ckpt"),
        checkpoint_every=2, **dyn,
    )
    proc = subprocess.run(
        [sys.executable, _SCRIPT, "--child",
         "--config", json.dumps(vars(cfg))],
        env={**_child_env(), "DLS_CRASH_AT_ROUND": "2",
             "DLS_CRASH_KIND": "sigkill"},
        capture_output=True, text=True, timeout=420,
        cwd=os.path.join(os.path.dirname(__file__), ".."),
    )
    assert proc.returncode == -signal.SIGKILL, (proc.returncode, proc.stderr)
    crashed = chaos.read_metrics_jsonl(cfg.log_root)
    assert crashed, "SIGKILLed dynamic run flushed no metrics records"
    resumed = chaos.run_resumed(cfg)
    verdict = chaos.stitch_and_compare(straight, crashed, resumed)
    assert verdict["bit_identical"], verdict
    # The comparison really covered churn: records carry the v9
    # population sub-object and the run grew.
    assert straight[-1]["population"]["n_registered"] > 6


def test_cohort_sampling_resume_determinism(tiny_config, tmp_path):
    """With participation_fraction < 1 and no failure model, the per-round
    sampled cohorts after resume must match the uninterrupted run — the
    rng_key checkpoint path the chaos harness depends on."""
    base = dataclasses.replace(tiny_config, participation_fraction=0.5,
                               worker_number=6)
    straight = run_simulation(
        dataclasses.replace(base, round=4), setup_logging=False
    )
    ckdir = str(tmp_path / "ck")
    run_simulation(
        dataclasses.replace(base, round=2, checkpoint_dir=ckdir,
                            checkpoint_every=1),
        setup_logging=False,
    )
    resumed = run_simulation(
        dataclasses.replace(base, round=4, checkpoint_dir=ckdir, resume=True),
        setup_logging=False,
    )
    straight_hashes = [h["cohort_hash"] for h in straight["history"]]
    resumed_hashes = [h["cohort_hash"] for h in resumed["history"]]
    assert resumed_hashes == straight_hashes[2:]
    assert [h["test_accuracy"] for h in resumed["history"]] == [
        h["test_accuracy"] for h in straight["history"][2:]
    ]
