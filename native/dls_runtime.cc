// dls_runtime: native runtime for the TPU federated-learning simulator.
//
// TPU-native equivalent of the reference's external L1 runtime surface
// (reference servers/server.py:1-3 imports ThreadTaskQueue /
// TorchProcessTaskQueue; simulator.py:5-6 imports ThreadPool/ProcessPool;
// servers/fed_server.py:3 imports RepeatedResult): a C++17 blocking
// byte-payload rendezvous queue with one-to-N result broadcast, and a
// thread pool that invokes Python callbacks from native threads.
//
// The fast path of the framework never touches this — synchronous FL is one
// XLA program (see parallel/engine.py). This runtime backs the *threaded
// execution mode* (execution/threaded.py): architecture parity with the
// reference's thread-per-client design for workloads with per-client Python
// logic that cannot be vmapped.
//
// C ABI only (consumed via ctypes); payloads are opaque byte buffers.

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <functional>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct Buffer {
  char* data;
  size_t len;
};

Buffer copy_in(const char* data, size_t len) {
  char* p = static_cast<char*>(::malloc(len ? len : 1));
  if (len) std::memcpy(p, data, len);
  return Buffer{p, len};
}

// A two-channel rendezvous queue:
//   task channel:   workers -> server (add_task / get_task)
//   result channel: server -> workers (put_result xN / get_result)
// Mirrors the reference queue's contract: workers block on get_result,
// the server broadcasts by enqueueing N copies (RepeatedResult semantics,
// reference fed_server.py:19-24,88-91).
class RendezvousQueue {
 public:
  ~RendezvousQueue() {
    stop();
    std::lock_guard<std::mutex> lk(mu_);
    for (auto& b : tasks_) ::free(b.data);
    for (auto& b : results_) ::free(b.data);
  }

  int add_task(const char* data, size_t len) {
    std::lock_guard<std::mutex> lk(mu_);
    if (stopped_) return -1;
    tasks_.push_back(copy_in(data, len));
    task_cv_.notify_one();
    return 0;
  }

  int get_task(char** out, size_t* out_len) {
    std::unique_lock<std::mutex> lk(mu_);
    task_cv_.wait(lk, [&] { return stopped_ || !tasks_.empty(); });
    if (tasks_.empty()) return -1;  // stopped
    Buffer b = tasks_.front();
    tasks_.pop_front();
    *out = b.data;
    *out_len = b.len;
    return 0;
  }

  int put_result(const char* data, size_t len, int copies) {
    std::lock_guard<std::mutex> lk(mu_);
    if (stopped_) return -1;
    for (int i = 0; i < copies; ++i) results_.push_back(copy_in(data, len));
    result_cv_.notify_all();
    return 0;
  }

  int get_result(char** out, size_t* out_len) {
    std::unique_lock<std::mutex> lk(mu_);
    result_cv_.wait(lk, [&] { return stopped_ || !results_.empty(); });
    if (results_.empty()) return -1;  // stopped
    Buffer b = results_.front();
    results_.pop_front();
    *out = b.data;
    *out_len = b.len;
    return 0;
  }

  void stop() {
    std::lock_guard<std::mutex> lk(mu_);
    stopped_ = true;
    task_cv_.notify_all();
    result_cv_.notify_all();
  }

 private:
  std::mutex mu_;
  std::condition_variable task_cv_;
  std::condition_variable result_cv_;
  std::deque<Buffer> tasks_;
  std::deque<Buffer> results_;
  bool stopped_ = false;
};

// Thread pool executing opaque callbacks (Python functions via ctypes
// CFUNCTYPE, which re-acquires the GIL per call). Reference surface:
// ThreadPool.exec / .stop (simulator.py:60-71).
using Callback = void (*)(uint64_t);

class ThreadPool {
 public:
  explicit ThreadPool(int n_threads) {
    for (int i = 0; i < n_threads; ++i) {
      threads_.emplace_back([this] { run(); });
    }
  }

  ~ThreadPool() { stop(); }

  int submit(Callback cb, uint64_t arg) {
    std::lock_guard<std::mutex> lk(mu_);
    if (stopped_) return -1;
    work_.push_back({cb, arg});
    cv_.notify_one();
    return 0;
  }

  // Blocks until every submitted task has finished.
  void join_pending() {
    std::unique_lock<std::mutex> lk(mu_);
    done_cv_.wait(lk, [&] { return work_.empty() && active_ == 0; });
  }

  void stop() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (stopped_) return;
      stopped_ = true;
      cv_.notify_all();
    }
    for (auto& t : threads_) {
      if (t.joinable()) t.join();
    }
  }

 private:
  void run() {
    for (;;) {
      std::pair<Callback, uint64_t> item;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [&] { return stopped_ || !work_.empty(); });
        if (work_.empty()) return;  // stopped
        item = work_.front();
        work_.pop_front();
        ++active_;
      }
      item.first(item.second);
      {
        std::lock_guard<std::mutex> lk(mu_);
        --active_;
        if (work_.empty() && active_ == 0) done_cv_.notify_all();
      }
    }
  }

  std::mutex mu_;
  std::condition_variable cv_;
  std::condition_variable done_cv_;
  std::deque<std::pair<Callback, uint64_t>> work_;
  std::vector<std::thread> threads_;
  int active_ = 0;
  bool stopped_ = false;
};

}  // namespace

extern "C" {

// ---- queue ----------------------------------------------------------------
void* dlsq_create() { return new RendezvousQueue(); }
void dlsq_destroy(void* q) { delete static_cast<RendezvousQueue*>(q); }
int dlsq_add_task(void* q, const char* data, size_t len) {
  return static_cast<RendezvousQueue*>(q)->add_task(data, len);
}
int dlsq_get_task(void* q, char** out, size_t* out_len) {
  return static_cast<RendezvousQueue*>(q)->get_task(out, out_len);
}
int dlsq_put_result(void* q, const char* data, size_t len, int copies) {
  return static_cast<RendezvousQueue*>(q)->put_result(data, len, copies);
}
int dlsq_get_result(void* q, char** out, size_t* out_len) {
  return static_cast<RendezvousQueue*>(q)->get_result(out, out_len);
}
void dlsq_stop(void* q) { static_cast<RendezvousQueue*>(q)->stop(); }
void dlsq_free(char* p) { ::free(p); }

// ---- thread pool ----------------------------------------------------------
void* dlsp_create(int n_threads) { return new ThreadPool(n_threads); }
void dlsp_destroy(void* p) { delete static_cast<ThreadPool*>(p); }
int dlsp_submit(void* p, Callback cb, uint64_t arg) {
  return static_cast<ThreadPool*>(p)->submit(cb, arg);
}
void dlsp_join_pending(void* p) {
  static_cast<ThreadPool*>(p)->join_pending();
}
void dlsp_stop(void* p) { static_cast<ThreadPool*>(p)->stop(); }

}  // extern "C"
