"""Benchmark: simulated-clients x rounds / sec (BASELINE.md north star).

Workload: FedAvg, CIFAR-10-shaped data (local .npz if present, deterministic
surrogate otherwise — same shapes/FLOPs either way), CNN, IID clients, 1 local
epoch per round — the reference's headline configuration
(BASELINE.json configs[0]) at benchmark scale.

North star: 1000 clients x 100 rounds < 5 min on a v5e-8 pod, i.e.
333.3 clients*rounds/sec across 8 chips. ``vs_baseline`` reports this
bench's rate against the FULL 333.3 pod-rate even when running on a single
chip (so >1.0 on one chip means the pod target is beaten 8x over).

Prints ONE JSON line. Env overrides: BENCH_CLIENTS, BENCH_ROUNDS,
BENCH_MODEL, BENCH_BATCH, BENCH_CHUNK (client_chunk_size), BENCH_DTYPE
(local_compute_dtype). The flagship large-model configuration that hits
the pod-rate on one chip (docs/PERFORMANCE.md):
BENCH_MODEL=resnet18 BENCH_CHUNK=40 BENCH_DTYPE=bfloat16.
"""

from __future__ import annotations

import json
import os


def main():
    from distributed_learning_simulator_tpu.config import ExperimentConfig
    from distributed_learning_simulator_tpu.data.registry import get_dataset
    from distributed_learning_simulator_tpu.simulator import (
        build_client_data,
        run_simulation,
    )

    n_clients = int(os.environ.get("BENCH_CLIENTS", "1000"))
    n_rounds = int(os.environ.get("BENCH_ROUNDS", "50"))
    # cnn_tpu: the MXU-aligned CIFAR CNN (models/cnn.py::TpuCifarCNN) —
    # same capability slot as the reference's CIFAR CNN, ~5.7x faster per
    # round than the 3->32->64->128 NHWC variant on TPU (layout note there).
    model = os.environ.get("BENCH_MODEL", "cnn_tpu")
    # 50k CIFAR samples / 1000 clients = 50 per shard; batch 25 -> two full
    # steps per local epoch with zero padding waste.
    batch = int(os.environ.get("BENCH_BATCH", "25"))
    chunk = int(os.environ.get("BENCH_CHUNK", "250"))
    # Per-client local-state dtype (see config.local_compute_dtype): bf16
    # halves the dominant HBM traffic at ResNet scale; f32 default.
    dtype = os.environ.get("BENCH_DTYPE", "float32")

    config = ExperimentConfig(
        dataset_name="cifar10",
        model_name=model,
        distributed_algorithm="fed",
        worker_number=n_clients,
        round=n_rounds + 1,  # round 0 carries the XLA compile; dropped below
        epoch=1,
        learning_rate=0.1,
        momentum=0.9,
        batch_size=batch,
        log_level="WARNING",
        # Whole test set as one eval batch: the per-iteration overhead of a
        # 10-step eval scan costs more than the memory a single 10k-sample
        # forward needs (measured 19ms vs 28-34ms per round on one chip).
        eval_batch_size=10000,
        client_chunk_size=chunk,
        local_compute_dtype=dtype,
    )
    dataset = get_dataset(config.dataset_name, seed=config.seed)
    client_data = build_client_data(config, dataset)

    result = run_simulation(config, dataset=dataset, client_data=client_data,
                            setup_logging=False)
    # Steady-state rate: drop round 0 (jit compile of the round + eval
    # programs happens there, inside the same jitted callables the later
    # rounds reuse). Wall-clock including compile is reported alongside so
    # the steady-state claim is auditable (VERDICT r1 weak #7).
    steady = [h["round_seconds"] for h in result["history"][1:]]
    elapsed = sum(steady)
    total_wall = result["total_seconds"]
    compile_s = result["history"][0]["round_seconds"] - (
        elapsed / max(len(steady), 1)
    )

    value = n_clients * n_rounds / elapsed
    north_star = 1000 * 100 / 300.0  # 333.3 clients*rounds/sec on v5e-8
    print(json.dumps({
        "metric": "simulated_clients_x_rounds_per_sec",
        "value": round(value, 2),
        "unit": "clients*rounds/s",
        "vs_baseline": round(value / north_star, 3),
        "clients": n_clients,
        "rounds": n_rounds,
        "elapsed_s": round(elapsed, 2),
        "total_wall_s": round(total_wall, 2),
        "compile_s": round(max(compile_s, 0.0), 2),
        "wall_clients_x_rounds_per_sec": round(
            n_clients * (n_rounds + 1) / total_wall, 2
        ),
        "final_accuracy": result["final_accuracy"],
    }))


if __name__ == "__main__":
    main()
