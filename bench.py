"""Benchmark: simulated-clients x rounds / sec (BASELINE.md north star).

Workload: FedAvg, CIFAR-10-shaped data (local .npz if present, deterministic
surrogate otherwise — same shapes/FLOPs either way), CNN, IID clients, 1 local
epoch per round — the reference's headline configuration
(BASELINE.json configs[0]) at benchmark scale.

North star: 1000 clients x 100 rounds < 5 min on a v5e-8 pod, i.e.
333.3 clients*rounds/sec across 8 chips. ``vs_baseline`` reports this
bench's rate against the FULL 333.3 pod-rate even when running on a single
chip (so >1.0 on one chip means the pod target is beaten 8x over).

Robustness: the steady-state rate uses the MEDIAN per-round time (rounds
1..N; round 0 carries compile/trace). The chip sits behind a shared tunnel,
so individual rounds can catch contention spikes; the mean-based rate over
50 rounds was measured to swing 8485-9152 on identical code (5 driver-style
runs, docs/PERFORMANCE.md). The median is stable against those spikes —
that is the regression signal. The mean-based rate and the per-round spread
are reported alongside for auditability.

Prints ONE JSON line, provenance-stamped with ``schema_version`` +
``config_hash`` (utils/reporting.py) so ``scripts/compare_bench.py`` can
refuse to diff incomparable runs and gate the tracked metrics against
regressions (docs/OBSERVABILITY.md). Env overrides: BENCH_CLIENTS, BENCH_ROUNDS,
BENCH_MODEL, BENCH_BATCH, BENCH_CHUNK (client_chunk_size), BENCH_DTYPE
(local_compute_dtype). BENCH_FAILURE_MODE/BENCH_FAILURE_PROB/
BENCH_MIN_SURVIVORS activate a failure model on the headline leg and add
a ``robustness`` sub-object (rounds_rejected, mean_survivor_count) so
perf rounds can't silently trade robustness for speed (docs/ROBUSTNESS.md). The flagship large-model configuration
(resnet18 + chunk 40 + bf16-SR local state, docs/PERFORMANCE.md) is
measured automatically into the ``flagship`` sub-object on default runs;
BENCH_FLAGSHIP=0 skips it, BENCH_FLAGSHIP_ROUNDS sets its length. The
converged-GTG round cost at N=1000 (the ``gtg`` sub-object, tracked since
ISSUE 1's cumulative prefix aggregation) follows the same pattern:
BENCH_GTG=0 skips, BENCH_GTG_ROUNDS sets its length, BENCH_GTG_DEVICES > 1
shards the walk's subset/group axis over the mesh (bit-identical to the
serial walk — algorithms/shapley.py). The gtg sub-object also records
``gtg_evals_per_s``, ``mesh_devices``, and a D=2/D=1 subset-eval
``scaling`` microbench (subprocess with forced host devices on CPU
hosts; BENCH_GTG_SCALING=0 skips) whose ratio compare_bench.py gates
absolutely (--gtg-scaling-threshold) when the host could honestly
measure it (>= 2 usable cores). The ``client_stats``
sub-object re-runs the headline program with ``client_stats='on'``
(telemetry/client_stats.py) and records the relative round-time
``overhead_ratio`` against the off-mode headline from the SAME bench run
— scripts/compare_bench.py gates it (--stats-overhead-threshold);
BENCH_CLIENT_STATS=0 skips, BENCH_CLIENT_STATS_ROUNDS sets its length.
The client-stats knobs land in ``config_hash`` like every other
program-defining field. The ``spans`` sub-object follows the same
shape for the distributed tracer (telemetry/spans.py): the headline
program re-run with ``span_trace='on'`` and its on-vs-off
``overhead_ratio`` — gated absolutely by compare_bench.py
(--span-overhead-threshold, default 0.05); BENCH_SPANS=0 skips,
BENCH_SPANS_ROUNDS sets its length. The ``mhost`` leg additionally
runs ONE spans-on 2-process pair at its largest population (the timed
sweep stays span-off) and records ``barrier_skew_ms`` — the worst
spill-exchange arrival skew either host saw — plus per-host DCN
wait/transfer splits; BENCH_MHOST_SPANS=0 skips.
The ``round_batch`` sub-object sweeps
``rounds_per_dispatch`` K in {1, BENCH_ROUND_BATCH_K} on the headline
program and records the wall-based K-vs-1 ``amortization_ratio``
(docs/PERFORMANCE.md § Round batching) — compare_bench.py gates it
absolutely (--batch-amortization-threshold); BENCH_ROUND_BATCH=0 skips.
The ``async`` sub-object runs the headline program under the 80/20
fast/slow arrival population (async_mode='on', docs/ROBUSTNESS.md §
Asynchronous federation) and records the simulated-clock
``async_speedup_ratio`` — compare_bench.py gates it absolutely
(--async-speedup-threshold); BENCH_ASYNC=0 skips,
BENCH_ASYNC_ROUNDS sets its length. The ``stream`` sub-object sweeps
synthetic populations (10k -> 1M by default) x
``participation_sampler`` modes (exact, hashed — ops/sampling.py)
under ``client_residency='streamed'`` (docs/PERFORMANCE.md § Streamed
client state) recording per-entry cohort rates, per-round cohort-draw
``sample_ms``, and the prefetch ``overlap_ratio`` — compare_bench.py
gates the largest N's ratio and cohort rate absolutely
(--stream-overlap-threshold / --stream-cohort-rate-threshold, both
read at the fastest-supported sampler); BENCH_STREAM=0 skips,
BENCH_STREAM_SWEEP/_SAMPLERS/_COHORT/_SHARD/_ROUNDS set the sweep. The
``costmodel`` sub-object (telemetry/costmodel.py) evaluates the proxy
legs' categorized op ledgers through the roofline model: predicted
per-round time for every topology-table entry, per-category bottleneck
attribution, a >= v4-32 pod projection with $/converged-run, and
``model_error_ratio`` (predicted vs this run's measured median) —
gated absolutely by compare_bench.py (--model-drift-threshold);
BENCH_COSTMODEL=0 skips, BENCH_COSTMODEL_TOPOLOGY sets the anchor,
BENCH_COSTMODEL_RUN_ROUNDS the $/run horizon. The ``valuation``
sub-object (telemetry/valuation.py) measures the streaming
client-valuation estimator twice: its round-time ``overhead_ratio``
against the same run's client_stats-on leg at the 1000-client
headline, and its ``audit_spearman`` fidelity against cumulative exact
GTG audit SVs on the small-N graded-label differential — gated
absolutely by compare_bench.py (--valuation-corr-threshold);
BENCH_VALUATION=0 skips, BENCH_VALUATION_ROUNDS /
BENCH_VALUATION_FIDELITY_N/_ROUNDS set the two measurements. The
``churn`` sub-object (robustness/population.py) runs a 10x
population-growth ``population='dynamic'`` leg against the same
program static on the headline data (streamed + hashed + sampled) and
records ``churn_overhead_ratio`` — gated absolutely by
compare_bench.py (--churn-overhead-threshold, default 0.10);
BENCH_CHURN=0 skips, BENCH_CHURN_ROUNDS / BENCH_CHURN_GROWTH set the
horizon and growth target. The
``sweep`` sub-object (sweep/engine.py) measures the multi-experiment
sweep engine: an N-point vmapped seed fleet vs N serial solo runs
(``sweep_amortization_ratio`` = serial/fleet wall, gated absolutely by
compare_bench.py --sweep-amortization-threshold; ``bit_identical``
asserts the fleet reproduced every solo history exactly) plus the
heterogeneous scheduler's ``compile_reuse_fraction`` on a 2-hash
8-point sweep; BENCH_SWEEP=0 skips, BENCH_SWEEP_POINTS/_ROUNDS/_CLIENTS
set the shape. Lean-compatible legs route through ONE
sweep.SweepScheduler (``warm_programs`` in the record), so same-program
legs (headline / round_batch K=1) pay trace+compile once.
"""

from __future__ import annotations

import json
import os
import statistics
import time

# Warm-program scheduler shared by every lean-compatible leg (ISSUE 11
# small fix): bench used to re-pay trace+compile for every leg even when
# two legs ran the SAME program (the headline and the round_batch K=1
# leg differ only in round count — identical config_hash). Routing
# repeated same-program runs through one sweep.SweepScheduler pays the
# warmup once and records the reuse explicitly (``warm_programs`` in
# the bench JSON). Legs outside the lean envelope (telemetry, async,
# streamed, Shapley, K>1, profiling) fall back to run_simulation inside
# the scheduler — recorded as fallback_points, never silent.
_SCHEDULER = None


def _run(config, *, dataset=None, client_data=None):
    """One simulation; returns (per-round-seconds list, result dict)."""
    global _SCHEDULER
    from distributed_learning_simulator_tpu.data.registry import get_dataset
    from distributed_learning_simulator_tpu.sweep import SweepScheduler
    from distributed_learning_simulator_tpu.simulator import (
        build_client_data,
    )

    if dataset is None:
        dataset = get_dataset(config.dataset_name, seed=config.seed)
    if client_data is None:
        client_data = build_client_data(config, dataset)
    if _SCHEDULER is None:
        _SCHEDULER = SweepScheduler()
    result = _SCHEDULER.run(config, dataset=dataset, client_data=client_data)
    times = [h["round_seconds"] for h in result["history"]]
    return times, result


def _rates(times: list[float], n_clients: int) -> dict:
    """Steady-state rates from per-round times (round 0 = compile/trace)."""
    steady = times[1:]
    elapsed = sum(steady)
    median_rt = statistics.median(steady)
    return {
        "median_rate": n_clients / median_rt,
        "mean_rate": n_clients * len(steady) / elapsed,
        "elapsed_s": elapsed,
        "round_ms": {
            "median": median_rt * 1e3,
            "min": min(steady) * 1e3,
            "max": max(steady) * 1e3,
        },
        "compile_s": max(times[0] - elapsed / max(len(steady), 1), 0.0),
    }


def _proxy_stats(config, dataset, client_data, rounds: int = 3) -> dict:
    """Traced run of ``rounds`` rounds -> deterministic byte/op totals.

    ``trace_rounds`` reports the rounds the trace actually covers
    (``rounds`` minus any ``profile_from_round`` warm-up rounds the
    config excludes to keep compile host events out of the profiler
    buffer). ``categories`` breaks the same totals down by HLO op class
    (utils/tracing.categorize_ops — matmul/conv, elementwise,
    copy/layout, collective, decode), each as deterministic as the
    grand total, so CATEGORY drift (a lost conv fusion turning into
    elementwise+copy traffic at constant total bytes) is visible across
    BENCH files; ``collective_gb`` surfaces the cross-chip volume the
    cost model charges to ICI (zero on single-chip traces)."""
    import dataclasses
    import tempfile

    from distributed_learning_simulator_tpu.telemetry.costmodel import (
        ledger_totals,
    )
    from distributed_learning_simulator_tpu.utils.tracing import (
        categorize_ops,
    )

    with tempfile.TemporaryDirectory() as td:
        p_config = dataclasses.replace(config, round=rounds, profile_dir=td)
        _run(p_config, dataset=dataset, client_data=client_data)
        # One gzip pass: the ledger's totals reconcile exactly with
        # parse_device_trace (pinned by tests/test_tracing.py), so the
        # headline proxy numbers derive from it instead of a second
        # scan of the ~128k-op flagship trace.
        ledger = categorize_ops(td)
        stats = ledger_totals(ledger)
    return {
        "traced_bytes_gb": round(stats["bytes_gb"], 3),
        "traced_device_ms": round(stats["device_ms"], 1),
        "traced_op_count": stats["op_count"],
        "trace_rounds": rounds - getattr(config, "profile_from_round", 0),
        "categories": {
            cat: {
                "bytes_gb": round(entry["bytes_gb"], 3),
                "device_ms": round(entry["device_ms"], 1),
                "flops_g": round(entry["flops_g"], 1),
                "op_count": entry["op_count"],
            }
            for cat, entry in sorted(ledger.items())
        },
        "collective_gb": round(
            ledger.get("collective", {}).get("bytes_gb", 0.0), 3
        ),
    }


def _gtg_scaling_child() -> dict:
    """In-process half of the GTG mesh-scaling microbench (run in a
    SUBPROCESS with >= 2 devices — forced host-CPU devices when the
    parent sees fewer; the tests/test_multichip.py idiom).

    Measures subset-eval throughput through the REAL ``_SubsetEvaluator``
    on a synthetic stack + MLP-shaped eval twice: serial (D=1) and with
    the model-batch axis partitioned over 2 devices (D=2, the serial
    chunk per device — algorithms/shapley.py). Same mask list, same call
    count per eval, one warm call each before timing. The ratio is the
    number compare_bench gates (--gtg-scaling-threshold) — on a
    multi-core/multi-chip host D=2 approaches 2x; a one-core cgroup
    cannot overlap the two devices' compute, so the record arms the gate
    only when >= 2 cores were usable (never fabricate — the costmodel
    leg's degrade precedent)."""
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_learning_simulator_tpu.algorithms.shapley import (
        _SubsetEvaluator,
    )

    n = int(os.environ.get("BENCH_GTG_SCALING_CLIENTS", "64"))
    p = int(os.environ.get("BENCH_GTG_SCALING_PARAMS", "50000"))
    n_masks = int(os.environ.get("BENCH_GTG_SCALING_MASKS", "512"))
    reps = int(os.environ.get("BENCH_GTG_SCALING_REPS", "3"))
    rng = np.random.default_rng(0)
    stack = {"w": jnp.asarray(rng.standard_normal((n, p)), jnp.float32)}
    sizes = jnp.asarray(rng.integers(1, 9, n), jnp.float32)
    prev = {"w": jnp.asarray(rng.standard_normal(p), jnp.float32)}
    xb = jnp.asarray(rng.standard_normal((4, 64, p)), jnp.float32)
    yb = jnp.asarray(rng.integers(0, 10, (4, 64)), jnp.int32)
    mb = jnp.ones((4, 64), jnp.float32)
    masks = (rng.random((n_masks, n)) < 0.5).astype(np.float32)

    def eval_fn(params, xb, yb, mb):
        h = jnp.tanh(xb @ params["w"])
        acc = jnp.sum(h * mb) / jnp.sum(mb)
        return {"accuracy": acc, "loss": 0.0}

    def throughput(devices):
        ev = _SubsetEvaluator(
            eval_fn, chunk=16,
            mesh_devices=devices if devices > 1 else None,
        )
        batches = (xb, yb, mb)
        ev(stack, sizes, masks[:16], prev, batches)  # compile warm-up
        best = None
        for _ in range(reps):
            t0 = _time.perf_counter()
            ev(stack, sizes, masks, prev, batches)
            dt = _time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return n_masks / best

    d1 = throughput(1)
    d2 = throughput(2)
    try:
        cores = len(os.sched_getaffinity(0))
    except AttributeError:  # non-Linux fallback
        cores = os.cpu_count() or 1
    return {
        "d1_evals_per_s": round(d1, 1),
        "d2_evals_per_s": round(d2, 1),
        "d2_over_d1": round(d2 / d1, 3),
        "host_cores": cores,
        "devices_visible": len(jax.devices()),
        "clients": n, "params": p, "masks": n_masks,
    }


def _gtg_scaling_stats() -> dict | None:
    """Subprocess driver of the D=2/D=1 subset-eval scaling microbench
    (bench.py re-exec with BENCH_GTG_SCALING_MODE=child — the flagship
    proxy's fresh-interpreter discipline; the child forces 2 host-CPU
    devices when the parent sees fewer than 2 real ones). Returns the
    child's JSON stats, an {"error": ...} record on failure, or None
    when BENCH_GTG_SCALING=0 skipped it."""
    import subprocess
    import sys

    if os.environ.get("BENCH_GTG_SCALING", "1") == "0":
        return None
    import jax

    env = dict(os.environ, BENCH_GTG_SCALING_MODE="child")
    if len(jax.devices()) < 2:
        # CPU-host idiom (tests/test_multichip.py): virtual host devices
        # stand in for the mesh; pin the platform so an accelerator
        # plugin can't grab the forced-device run.
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=2"
        )
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            capture_output=True, text=True, timeout=900,
        )
        if out.returncode != 0:
            return {"error": (out.stderr or out.stdout).strip()[-500:]}
        return json.loads(out.stdout.strip().splitlines()[-1])
    except Exception as e:  # noqa: BLE001 — degrade, never crash the bench
        return {"error": f"{type(e).__name__}: {e}"}


def _stream_leg() -> dict:
    """Streamed-residency N x sampler sweep (see run_stream in main()).

    Uses the synthetic dataset so the POPULATION axis scales without a
    50k-sample cap: every client's shard is drawn from a small pool by
    ``data/residency.synthetic_stream_shards`` (the vectorized generator
    — ``pack_client_shards``'s per-client Python loop takes minutes at
    N=1e6). The pool is min-max scaled into [0, 1] so the shards keep
    the uint8-compact layout (1 byte/feature: a million 16-sample
    shards of the 8x8x1 synthetic stay ~1 GB host-side).

    Each population is run once per ``participation_sampler`` mode
    (``BENCH_STREAM_SAMPLERS``, default "exact,hashed" —
    ops/sampling.py): ``exact``'s O(N log N) cohort replay is the
    measured host-bound ceiling at N=1e6 and ``hashed``'s O(cohort)
    draw is what removes it; each entry records the steady
    ``cohort_rate`` and the mean per-round ``sample_ms`` so the draw
    cost is visible next to the throughput it binds. The gate numbers
    (``overlap_ratio``, ``cohort_rate``) come from the LARGEST
    population under its FASTEST-supported sampler — hashed when swept,
    the operating point the sampler exists for
    (scripts/compare_bench.py --stream-overlap-threshold /
    --stream-cohort-rate-threshold).
    """
    from distributed_learning_simulator_tpu.config import ExperimentConfig
    from distributed_learning_simulator_tpu.data.registry import get_dataset
    from distributed_learning_simulator_tpu.data.residency import (
        synthetic_stream_shards,
    )
    from distributed_learning_simulator_tpu.utils.reporting import config_hash

    sweep = sorted(
        int(s) for s in os.environ.get(
            "BENCH_STREAM_SWEEP", "10000,100000,1000000"
        ).split(",") if s.strip()
    )
    if not sweep:
        return {"error": "BENCH_STREAM_SWEEP is empty"}
    samplers = [
        s.strip() for s in os.environ.get(
            "BENCH_STREAM_SAMPLERS", "exact,hashed"
        ).split(",") if s.strip()
    ]
    if not samplers:
        return {"error": "BENCH_STREAM_SAMPLERS is empty"}
    cohort = int(os.environ.get("BENCH_STREAM_COHORT", "256"))
    shard = int(os.environ.get("BENCH_STREAM_SHARD", "16"))
    s_rounds = int(os.environ.get("BENCH_STREAM_ROUNDS", "8"))

    ds = get_dataset("synthetic", n_train=4096, n_test=512, seed=0)
    lo, hi = float(ds.x_train.min()), float(ds.x_train.max())
    scale = lambda x: (x - lo) / (hi - lo)  # noqa: E731
    ds_scaled = type(ds)(
        ds.name, scale(ds.x_train), ds.y_train, scale(ds.x_test),
        ds.y_test, ds.num_classes,
    )

    out = {"cohort": cohort, "shard_size": shard, "rounds": s_rounds,
           "sweep": []}
    for n in sweep:
        client_data = synthetic_stream_shards(
            ds_scaled.x_train, ds_scaled.y_train, n, shard, seed=0
        )
        for sampler in samplers:
            s_config = ExperimentConfig(
                dataset_name="synthetic", model_name="mlp",
                distributed_algorithm="fed", worker_number=n,
                round=s_rounds + 1, epoch=1, learning_rate=0.1,
                batch_size=shard, eval_batch_size=512,
                participation_fraction=cohort / n,
                participation_sampler=sampler,
                client_residency="streamed", log_level="WARNING",
            )
            times, result = _run(
                s_config, dataset=ds_scaled, client_data=client_data
            )
            steady = times[1:]
            # Steady per-round cohort-draw replay cost — the host time
            # the sampler knob exists to shrink (~1-2 s/round for exact
            # at N=1e6 vs sub-ms hashed). Median over the steady
            # rounds' stream records: round 0's draw carries the
            # replay-path jit warmup, which is startup cost, not the
            # per-round cost being tracked.
            sample_steady = [
                h["stream"]["sample_ms"] for h in result["history"][1:]
                if "sample_ms" in h.get("stream", {})
            ]
            out["sweep"].append({
                "n_clients": n,
                "sampler": sampler,
                "config_hash": config_hash(s_config),
                # Only the cohort trains per round: cohort*rounds/s is
                # the honest throughput unit for a sampled population.
                "cohort_rate": round(cohort * len(steady) / sum(steady), 2),
                "round_ms": round(
                    statistics.median(steady) * 1e3, 2
                ),
                "sample_ms": round(
                    statistics.median(sample_steady), 3
                ) if sample_steady else None,
                "overlap_ratio": round(result["stream_overlap_ratio"], 4),
                "h2d_mb": round(result["stream_h2d_bytes"] / 2**20, 2),
                "host_store_mb": round(
                    (client_data.x.nbytes + client_data.y.nbytes
                     + client_data.mask.nbytes + client_data.sizes.nbytes)
                    / 2**20, 1
                ),
            })
    # The gates read the LARGEST population under its fastest-supported
    # sampler — the operating point the feature exists for.
    gate_sampler = "hashed" if "hashed" in samplers else samplers[-1]
    gate_entry = [
        e for e in out["sweep"]
        if e["n_clients"] == sweep[-1] and e["sampler"] == gate_sampler
    ][-1]
    out["overlap_ratio"] = gate_entry["overlap_ratio"]
    out["cohort_rate"] = gate_entry["cohort_rate"]
    out["sampler"] = gate_sampler
    out["max_n"] = sweep[-1]
    return out


_MHOST_CHILD = """
import json
import statistics
import sys
import jax
jax.config.update("jax_platforms", "cpu")
from distributed_learning_simulator_tpu.config import ExperimentConfig
from distributed_learning_simulator_tpu.data.registry import get_dataset
from distributed_learning_simulator_tpu.data.residency import (
    synthetic_stream_shards,
)
from distributed_learning_simulator_tpu.simulator import run_simulation

addr, pid, n, cohort, shard, rounds = (
    sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]),
    int(sys.argv[5]), int(sys.argv[6]),
)
span_dir = sys.argv[7] if len(sys.argv) > 7 else "-"
span_knobs = (
    {"span_trace": "on", "span_dir": span_dir} if span_dir != "-" else {}
)
ds = get_dataset("synthetic", n_train=4096, n_test=512, seed=0)
lo, hi = float(ds.x_train.min()), float(ds.x_train.max())
scale = lambda x: (x - lo) / (hi - lo)
ds = type(ds)(ds.name, scale(ds.x_train), ds.y_train, scale(ds.x_test),
              ds.y_test, ds.num_classes)
client_data = synthetic_stream_shards(ds.x_train, ds.y_train, n, shard,
                                      seed=0)
config = ExperimentConfig(
    dataset_name="synthetic", model_name="mlp",
    distributed_algorithm="fed", worker_number=n, round=rounds + 1,
    epoch=1, learning_rate=0.1, batch_size=shard, eval_batch_size=512,
    participation_fraction=cohort / n, participation_sampler="hashed",
    client_residency="streamed", log_level="ERROR",
    multihost=True, coordinator_address=addr, num_processes=2,
    process_id=pid, mesh_devices=2, **span_knobs,
)
res = run_simulation(config, dataset=ds, client_data=client_data)
steady = [h["round_seconds"] for h in res["history"][1:]]
print("MHOST_JSON", json.dumps({
    "round_ms": round(statistics.median(steady) * 1e3, 2),
    "cohort_rate": round(cohort * len(steady) / sum(steady), 2),
    "overlap_ratio": round(res["stream_overlap_ratio"], 4),
    "dcn_bytes": res["stream_dcn_bytes"],
    "summary": res["multihost_summary"],
    "span_summary": res["span_summary"],
}))
"""


def _mhost_pair(n: int, cohort: int, shard: int, rounds: int,
                span_dir: str | None = None):
    """Launch one 2-process localhost pair; returns (per-host MHOST_JSON
    dicts, error string or None). ``span_dir`` turns on span_trace in
    both children with a shared journal directory."""
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        addr = f"127.0.0.1:{s.getsockname()[1]}"
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _MHOST_CHILD, addr, str(i),
             str(n), str(cohort), str(shard), str(rounds),
             span_dir or "-"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
        )
        for i in range(2)
    ]
    try:
        outs = [p.communicate(timeout=1800) for p in procs]
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        return None, "timeout"
    per_host = []
    for i, (p, (o, e)) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            return None, f"proc {i}: {(e or o).strip()[-400:]}"
        line = [ln for ln in o.splitlines()
                if ln.startswith("MHOST_JSON")]
        if not line:
            return None, f"proc {i}: no MHOST_JSON line"
        per_host.append(json.loads(line[0].split(" ", 1)[1]))
    return per_host, None


def _mhost_leg() -> dict:
    """2-process distributed-shard-store N-sweep (ISSUE 15).

    The composed axes: streamed million-client populations AND
    multi-process mesh scale in ONE run. Two real jax.distributed
    processes over localhost (the tests/test_multihost.py harness's
    topology), each owning half the synthetic population in its
    DistributedShardStore and serving its members of every round's
    owner-permuted cohort into its addressable shards
    (parallel/streaming.DistributedCohortStreamer); the N-sweep mirrors
    the single-process ``stream`` leg (same synthetic generator, cohort,
    shard size) so the two legs' cohort rates are directly comparable.
    Records per-N ``cohort_rate`` plus each host's overlap/spill/DCN
    accounting; the gate value (compare_bench.py
    --mhost-cohort-rate-threshold, absolute in-record floor) is the
    LARGEST population's rate — armed only on hosts with >= 2 usable
    cores (the PR 14 precedent: a 1-core cgroup cannot overlap two
    processes' compute; the honest number stays in the record unarmed).
    BENCH_MHOST=0 skips; BENCH_MHOST_SWEEP / _COHORT / _SHARD / _ROUNDS
    set the sweep. Memory note: each process transiently materializes
    the full-N synthetic view before the store keeps its slice, so the
    leg peaks at ~1.5x the single-process stream leg's host RAM per
    process.
    """
    sweep = sorted(
        int(s) for s in os.environ.get(
            "BENCH_MHOST_SWEEP", "10000,100000,1000000"
        ).split(",") if s.strip()
    )
    if not sweep:
        return {"error": "BENCH_MHOST_SWEEP is empty"}
    cohort = int(os.environ.get("BENCH_MHOST_COHORT", "256"))
    shard = int(os.environ.get("BENCH_MHOST_SHARD", "16"))
    rounds = int(os.environ.get("BENCH_MHOST_ROUNDS", "8"))
    try:
        cores = len(os.sched_getaffinity(0))
    except AttributeError:  # non-Linux fallback
        cores = os.cpu_count() or 1
    out = {"processes": 2, "cohort": cohort, "shard_size": shard,
           "rounds": rounds, "host_cores": cores, "sweep": []}
    for n in sweep:
        entry = {"n_clients": n}
        per_host, err = _mhost_pair(n, cohort, shard, rounds)
        if err is not None:
            entry["error"] = err
        else:
            entry.update({
                k: per_host[0][k]
                for k in ("round_ms", "cohort_rate", "dcn_bytes")
            })
            # Per-host overlap + shard summaries: BOTH processes'
            # numbers (the satellite's per-host h2d/overlap face).
            entry["per_host"] = [
                {"overlap_ratio": h["overlap_ratio"], **h["summary"]}
                for h in per_host
            ]
        out["sweep"].append(entry)
    good = [e for e in out["sweep"] if "error" not in e]
    if not good:
        out["error"] = "every sweep point failed"
        return out
    gate_entry = [e for e in good if e["n_clients"] == good[-1]["n_clients"]][-1]
    out["max_n"] = gate_entry["n_clients"]
    out["cohort_rate"] = gate_entry["cohort_rate"]
    if cores >= 2:
        # The gated key (compare_bench.py reads mhost.mhost_cohort_rate)
        # is armed only when the two processes' compute can genuinely
        # overlap — the PR 14 honest-number-unarmed precedent.
        out["mhost_cohort_rate"] = gate_entry["cohort_rate"]
    # Barrier-skew attribution run (ISSUE 19, telemetry/spans.py): one
    # EXTRA 2-process run at the largest population with span_trace='on'
    # and a shared journal dir. The timed sweep above stays span-OFF —
    # its rates keep measuring the exact pre-feature program (off-gate);
    # this run's numbers are attribution only, never rate-gated.
    if os.environ.get("BENCH_MHOST_SPANS", "1") != "0":
        import shutil
        import tempfile

        sp_dir = tempfile.mkdtemp(prefix="bench_mhost_spans_")
        per_host, err = _mhost_pair(out["max_n"], cohort, shard, rounds,
                                    span_dir=sp_dir)
        if err is not None:
            out["spans_error"] = err
        else:
            sums = [h.get("span_summary") or {} for h in per_host]
            skews = [s.get("spill_skew_ms_max") for s in sums
                     if s.get("spill_skew_ms_max") is not None]
            # The worst spill-exchange arrival skew either host saw over
            # the run — the cross-host imbalance number (max-min host
            # arrival at the allgather, docs/OBSERVABILITY.md).
            out["barrier_skew_ms"] = (
                round(max(skews), 3) if skews else None
            )
            out["span_hosts"] = [
                {"host_id": s.get("host_id"),
                 "spans": s.get("count"),
                 "dcn_wait_s": s.get("dcn_wait_s"),
                 "dcn_transfer_s": s.get("dcn_transfer_s")}
                for s in sums
            ]
        shutil.rmtree(sp_dir, ignore_errors=True)
    return out


def _sweep_leg() -> dict:
    """Multi-experiment sweep engine leg (ISSUE 11, sweep/engine.py).

    Two measurements in one leg, both within this bench run:

    (a) AMORTIZATION — an N-point vmapped seed fleet vs N serial solo
    runs of the same points on the same shared data (each solo run pays
    its own trace+compile — the pre-sweep cost of a seed sweep).
    ``sweep_amortization_ratio`` = serial wall / fleet wall; the
    acceptance operating point is >= 2 (fleet under half the serial
    wall — compile paid once is the multiplier: BENCH_r05 measured
    9.5 s compile vs 5.7 s useful run on the headline). The leg also
    verifies each fleet point's metric history is BIT-IDENTICAL to its
    solo counterpart (``bit_identical``) — the fleet is a packing of
    the same experiments, never an approximation of them.

    (b) COMPILE REUSE — the heterogeneous-group scheduler on a 2-hash
    8-point sweep (seeds {0,1} x four round horizons: two distinct
    config_hashes, eight distinct points). The seed is a pure operand,
    so the seed-normalized program cache serves all 8 points from ONE
    compiled program: ``compile_reuse_fraction`` = 7/8.

    compare_bench.py gates the amortization ratio absolutely
    (--sweep-amortization-threshold, default 2.0 — PR 4/5/10
    precedent: in-record ratios are never relatively tracked).
    BENCH_SWEEP=0 skips; BENCH_SWEEP_POINTS/_ROUNDS/_CLIENTS set the
    shape. The persistent compile cache is DISABLED inside this leg on
    both sides — the serial baseline must honestly pay the per-run
    compile the fleet amortizes, not read it back from disk.
    """
    import dataclasses

    from distributed_learning_simulator_tpu.config import ExperimentConfig
    from distributed_learning_simulator_tpu.data.registry import get_dataset
    from distributed_learning_simulator_tpu.simulator import (
        build_client_data,
        run_simulation,
    )
    from distributed_learning_simulator_tpu.sweep import SweepSpec, run_sweep
    from distributed_learning_simulator_tpu.utils.reporting import (
        config_hash,
    )

    n_points = int(os.environ.get("BENCH_SWEEP_POINTS", "8"))
    s_rounds = int(os.environ.get("BENCH_SWEEP_ROUNDS", "6"))
    s_clients = int(os.environ.get("BENCH_SWEEP_CLIENTS", "32"))
    base = ExperimentConfig(
        dataset_name="synthetic", model_name="mlp",
        distributed_algorithm="fed", worker_number=s_clients,
        round=s_rounds, epoch=1, learning_rate=0.1, batch_size=16,
        n_train=s_clients * 32, n_test=512, log_level="WARNING",
        dataset_args={"difficulty": 0.5},
        compilation_cache_dir=None,
    )
    ds = get_dataset("synthetic", n_train=base.n_train, n_test=base.n_test,
                     seed=base.seed, difficulty=0.5)
    cd = build_client_data(base, ds)
    seeds = list(range(n_points))

    # (a) serial solo baseline: one fresh run_simulation per seed on the
    # shared data — the counterfactual a researcher runs today.
    t0 = time.perf_counter()
    solo_histories = []
    for s in seeds:
        res = run_simulation(
            dataclasses.replace(base, seed=s), dataset=ds, client_data=cd,
            setup_logging=False,
        )
        solo_histories.append(res["history"])
    serial_wall = time.perf_counter() - t0

    t0 = time.perf_counter()
    fleet = run_sweep(
        SweepSpec(base, [{"seed": s} for s in seeds], strategy="vmapped"),
        dataset=ds, client_data=cd,
    )
    fleet_wall = time.perf_counter() - t0

    keys = ("test_accuracy", "test_loss", "mean_client_loss")
    bit_identical = all(
        len(sh) == len(p["history"]) and all(
            all(hs.get(k) == hf.get(k) for k in keys)
            for hs, hf in zip(sh, p["history"])
        )
        for sh, p in zip(solo_histories, fleet["points"])
    )

    # (b) scheduler compile reuse on the 2-hash 8-point sweep.
    sched_points = [
        {"seed": s, "round": r}
        for s in (0, 1)
        for r in range(s_rounds, s_rounds + 4)
    ]
    sched = run_sweep(
        SweepSpec(base, sched_points, strategy="scheduled"),
        dataset=ds, client_data=cd,
    )
    sched_hashes = {p["config_hash"] for p in sched["points"]}

    return {
        "points": n_points,
        "rounds": s_rounds,
        "clients": s_clients,
        "config_hash": config_hash(base),
        "serial_wall_s": round(serial_wall, 3),
        "fleet_wall_s": round(fleet_wall, 3),
        # The gate's number (absolute floor, default 2.0): how many
        # serial-sweep seconds one fleet second buys.
        "sweep_amortization_ratio": round(serial_wall / fleet_wall, 4),
        "experiments_per_hour": round(n_points / fleet_wall * 3600.0, 1),
        "bit_identical": bool(bit_identical),
        # The acceptance bookkeeping: 2 hashes, 8 points, 1 program.
        "compile_reuse_fraction": sched["compile_reuse_fraction"],
        "scheduler": {
            "points": len(sched_points),
            "hashes": len(sched_hashes),
            "programs_compiled": sched["programs_compiled"],
            "compile_reuse_fraction": sched["compile_reuse_fraction"],
        },
    }


def main():
    from distributed_learning_simulator_tpu.config import ExperimentConfig

    if os.environ.get("BENCH_GTG_SCALING_MODE") == "child":
        # Subprocess leg (see _gtg_scaling_stats): measure D=1 vs D=2
        # subset-eval throughput in a fresh interpreter (forced host
        # devices on CPU hosts) and print ONLY its stats line.
        print(json.dumps(_gtg_scaling_child()))
        return

    n_clients = int(os.environ.get("BENCH_CLIENTS", "1000"))
    n_rounds = int(os.environ.get("BENCH_ROUNDS", "50"))
    # cnn_tpu: the MXU-aligned CIFAR CNN (models/cnn.py::TpuCifarCNN) —
    # same capability slot as the reference's CIFAR CNN, ~5.7x faster per
    # round than the 3->32->64->128 NHWC variant on TPU (layout note there).
    model = os.environ.get("BENCH_MODEL", "cnn_tpu")
    # 50k CIFAR samples / 1000 clients = 50 per shard; batch 25 -> two full
    # steps per local epoch with zero padding waste.
    batch = int(os.environ.get("BENCH_BATCH", "25"))
    chunk = int(os.environ.get("BENCH_CHUNK", "250"))
    # Per-client local-state dtype (see config.local_compute_dtype): bf16
    # halves the dominant HBM traffic at ResNet scale; f32 default.
    dtype = os.environ.get("BENCH_DTYPE", "float32")
    # Opt-in failure model on the HEADLINE leg (docs/ROBUSTNESS.md): when
    # active, rounds_rejected and the mean survivor count land in the
    # bench JSON so future perf rounds can't silently trade robustness for
    # speed. The flagship/gtg/proxy legs stay failure-free — their numbers
    # track the unperturbed programs.
    fail_mode = os.environ.get("BENCH_FAILURE_MODE", "none")
    fail_prob = float(os.environ.get("BENCH_FAILURE_PROB", "0.1"))
    min_survivors = int(os.environ.get("BENCH_MIN_SURVIVORS", "1"))
    failure_knobs = {}
    if fail_mode != "none":
        failure_knobs = dict(
            failure_mode=fail_mode, failure_prob=fail_prob,
            min_survivors=min_survivors,
        )

    common = dict(
        dataset_name="cifar10",
        distributed_algorithm="fed",
        worker_number=n_clients,
        epoch=1,
        learning_rate=0.1,
        momentum=0.9,
        batch_size=batch,
        log_level="WARNING",
        # Whole test set as one eval batch: the per-iteration overhead of a
        # 10-step eval scan costs more than the memory a single 10k-sample
        # forward needs (measured 19ms vs 28-34ms per round on one chip).
        eval_batch_size=10000,
        # Persistent XLA compile cache (repo-local): the config default
        # resolves relative to the CWD — pin it next to this file so the
        # driver's repeat runs hit the same cache wherever they start from.
        compilation_cache_dir=os.path.join(
            os.path.dirname(os.path.abspath(__file__)), ".jax_cache"
        ),
    )
    config = ExperimentConfig(
        model_name=model,
        round=n_rounds + 1,  # round 0 carries the XLA compile; dropped below
        client_chunk_size=chunk,
        local_compute_dtype=dtype,
        **failure_knobs,
        **common,
    )
    from distributed_learning_simulator_tpu.data.registry import get_dataset
    from distributed_learning_simulator_tpu.simulator import build_client_data

    dataset = get_dataset(config.dataset_name, seed=config.seed)
    client_data = build_client_data(config, dataset)

    # ONE definition of the flagship leg's program knobs, shared by the
    # wall-clock flagship run below and the traced-proxy subprocess — the
    # proxy exists to detect program changes, so the two must not drift.
    flagship_knobs = dict(
        model_name="resnet18", client_chunk_size=40,
        local_compute_dtype="bfloat16",
    )

    if os.environ.get("BENCH_PROXY_MODE") == "flagship":
        # Subprocess leg (see the proxy_flagship block below): trace the
        # flagship program in a fresh interpreter and print ONLY its
        # stats line. rounds=2 with profile_from_round=1: round 0 carries
        # the XLA compile OUTSIDE the trace (compile host events flood
        # the tunnel profiler's buffer and device events get dropped —
        # measured: whole-loop flagship traces came back empty or
        # truncated at a run-varying point), round 1 is the fully
        # captured steady-state round.
        pf_config = ExperimentConfig(
            round=2, profile_from_round=1, **flagship_knobs, **common,
        )
        print(json.dumps(
            _proxy_stats(pf_config, dataset, client_data, rounds=2)
        ))
        return

    times, result = _run(config, dataset=dataset, client_data=client_data)
    r = _rates(times, n_clients)

    from distributed_learning_simulator_tpu.utils.reporting import (
        BENCH_SCHEMA_VERSION,
        config_hash,
    )

    north_star = 1000 * 100 / 300.0  # 333.3 clients*rounds/sec on v5e-8
    record = {
        # Provenance stamp (utils/reporting.py): schema_version + a hash
        # of the program-defining config knobs, so compare_bench.py can
        # refuse to diff runs whose numbers are not comparable (different
        # model/population/chunk/dtype/failure knobs).
        "schema_version": BENCH_SCHEMA_VERSION,
        "config_hash": config_hash(config),
        "metric": "simulated_clients_x_rounds_per_sec",
        "value": round(r["median_rate"], 2),
        "unit": "clients*rounds/s",
        "vs_baseline": round(r["median_rate"] / north_star, 3),
        "clients": n_clients,
        "rounds": n_rounds,
        "mean_rate": round(r["mean_rate"], 2),
        "round_ms": {k: round(v, 1) for k, v in r["round_ms"].items()},
        "elapsed_s": round(r["elapsed_s"], 2),
        "total_wall_s": round(result["total_seconds"], 2),
        "compile_s": round(r["compile_s"], 2),
        "wall_clients_x_rounds_per_sec": round(
            n_clients * (n_rounds + 1) / result["total_seconds"], 2
        ),
        "final_accuracy": result["final_accuracy"],
    }
    if failure_knobs:
        record["robustness"] = {
            **failure_knobs,
            "rounds_rejected": result["rounds_rejected"],
            "mean_survivor_count": result["mean_survivor_count"],
        }

    # Flagship: the large-model config that holds the pod-rate on one chip.
    # Driver-captured here (VERDICT r2 weak #3) — cheap because the steady
    # rounds are ~3 s and the compile comes from the persistent cache.
    run_flagship = (
        os.environ.get("BENCH_FLAGSHIP", "1") != "0"
        and model == "cnn_tpu"
        and n_clients == 1000
    )
    if run_flagship:
        f_rounds = int(os.environ.get("BENCH_FLAGSHIP_ROUNDS", "5"))
        f_config = ExperimentConfig(
            round=f_rounds + 1, **flagship_knobs, **common,
        )
        # Reuse the already-loaded dataset + client shards: the flagship
        # leg differs only in model/chunk/dtype, not data.
        f_times, f_result = _run(
            f_config, dataset=dataset, client_data=client_data
        )
        fr = _rates(f_times, n_clients)
        record["flagship"] = {
            "model": "resnet18",
            "value": round(fr["median_rate"], 2),
            "vs_baseline": round(fr["median_rate"] / north_star, 3),
            "rounds": f_rounds,
            "mean_rate": round(fr["mean_rate"], 2),
            "round_ms": {k: round(v, 1) for k, v in fr["round_ms"].items()},
            "compile_s": round(fr["compile_s"], 2),
        }

    # client_stats=on overhead (ISSUE 4): the SAME headline program plus
    # the in-round per-client statistics, so overhead_ratio is an
    # apples-to-apples on-vs-off round-time ratio measured in one bench
    # run on one machine — the number compare_bench.py's
    # --stats-overhead-threshold gates.
    run_cstats = (
        os.environ.get("BENCH_CLIENT_STATS", "1") != "0"
        and model == "cnn_tpu"
        and n_clients == 1000
    )
    if run_cstats:
        cs_rounds = int(os.environ.get("BENCH_CLIENT_STATS_ROUNDS", "5"))
        cs_config = ExperimentConfig(
            model_name=model, round=cs_rounds + 1, client_chunk_size=chunk,
            local_compute_dtype=dtype, client_stats="on",
            **failure_knobs, **common,
        )
        cs_times, cs_result = _run(
            cs_config, dataset=dataset, client_data=client_data
        )
        cr = _rates(cs_times, n_clients)
        record["client_stats"] = {
            "value": round(cr["median_rate"], 2),
            "rounds": cs_rounds,
            "round_ms": {k: round(v, 1) for k, v in cr["round_ms"].items()},
            "overhead_ratio": round(
                cr["round_ms"]["median"] / r["round_ms"]["median"] - 1.0, 4
            ),
            "clients_flagged": cs_result["clients_flagged"],
        }

    # Span-trace overhead (ISSUE 19, telemetry/spans.py): the SAME
    # headline program with span_trace='on', so overhead_ratio is an
    # apples-to-apples on-vs-off round-time ratio measured in one bench
    # run on one machine — the number compare_bench.py's
    # --span-overhead-threshold gates as an ABSOLUTE ceiling (default
    # 0.05: the recorder's promise is "cheap enough to leave on in
    # production"; a near-zero ratio must never be tracked relatively —
    # the PR 4/5 precedent). BENCH_SPANS=0 skips.
    run_spans = (
        os.environ.get("BENCH_SPANS", "1") != "0"
        and model == "cnn_tpu"
        and n_clients == 1000
    )
    if run_spans:
        import shutil
        import tempfile

        sp_rounds = int(os.environ.get("BENCH_SPANS_ROUNDS", "5"))
        sp_dir = tempfile.mkdtemp(prefix="bench_spans_")
        sp_config = ExperimentConfig(
            model_name=model, round=sp_rounds + 1, client_chunk_size=chunk,
            local_compute_dtype=dtype, span_trace="on", span_dir=sp_dir,
            **failure_knobs, **common,
        )
        sp_times, sp_result = _run(
            sp_config, dataset=dataset, client_data=client_data
        )
        sr = _rates(sp_times, n_clients)
        ssum = sp_result["span_summary"] or {}
        record["spans"] = {
            "value": round(sr["median_rate"], 2),
            "rounds": sp_rounds,
            "round_ms": {k: round(v, 1) for k, v in sr["round_ms"].items()},
            "overhead_ratio": round(
                sr["round_ms"]["median"] / r["round_ms"]["median"] - 1.0, 4
            ),
            "span_count": ssum.get("count"),
            "dropped": ssum.get("dropped"),
        }
        shutil.rmtree(sp_dir, ignore_errors=True)

    # Round batching (ISSUE 5, config.rounds_per_dispatch): the SAME
    # headline program dispatched K rounds at a time, so the
    # amortization_ratio is an apples-to-apples K-vs-1 rate ratio measured
    # in one bench run on one machine. Rates are WALL-based over the
    # steady rounds (clients * rounds / elapsed): within a dispatch the
    # per-round wall lands on the dispatch's first record, so the K=1
    # median would be meaningless against K>1 — the elapsed-time rate is
    # the honest common unit. The first K rounds are dropped on both legs
    # (the first dispatch carries the scan program's compile). Gated by
    # scripts/compare_bench.py --batch-amortization-threshold as an
    # in-record ABSOLUTE floor, same pattern as the client_stats overhead
    # gate. rounds_per_dispatch lands in config_hash like every other
    # program-defining knob, so K-batched and unbatched headline runs
    # can never be silently diffed. BENCH_ROUND_BATCH=0 skips;
    # BENCH_ROUND_BATCH_K / BENCH_ROUND_BATCH_ROUNDS set the sweep.
    run_rbatch = (
        os.environ.get("BENCH_ROUND_BATCH", "1") != "0"
        and model == "cnn_tpu"
        and n_clients == 1000
    )
    if run_rbatch:
        rb_k = int(os.environ.get("BENCH_ROUND_BATCH_K", "8"))
        rb_rounds = int(os.environ.get("BENCH_ROUND_BATCH_ROUNDS", "16"))
        # Round UP to a multiple of K: a trailing remainder dispatch is a
        # different scan program whose compile would land inside the
        # measured window and deflate the ratio with pure compile time.
        rb_rounds = -(-rb_rounds // rb_k) * rb_k
        rb_rates = {}
        for k_ in (1, rb_k):
            rb_config = ExperimentConfig(
                model_name=model, round=rb_rounds + k_,
                client_chunk_size=chunk, local_compute_dtype=dtype,
                rounds_per_dispatch=k_,
                **failure_knobs, **common,
            )
            rb_times, _ = _run(
                rb_config, dataset=dataset, client_data=client_data
            )
            steady = rb_times[k_:]
            rb_rates[k_] = n_clients * len(steady) / sum(steady)
        record["round_batch"] = {
            "k": rb_k,
            "rounds": rb_rounds,
            "k1_rate": round(rb_rates[1], 2),
            "k_rate": round(rb_rates[rb_k], 2),
            # >= 1.0 means batching pays: K rounds per dispatch move at
            # least as fast as one-round dispatches.
            "amortization_ratio": round(rb_rates[rb_k] / rb_rates[1], 4),
        }

    # Asynchronous federation (ISSUE 6, config.async_mode): the headline
    # program under the documented 80/20 fast/slow population with
    # deadline rounds + the staleness buffer (docs/ROBUSTNESS.md §
    # Asynchronous federation). Records the run's simulated-clock
    # async_speedup_ratio (deadline rounds vs the wait-for-everyone sync
    # counterfactual, computed from the SAME arrival draws — a
    # deterministic program property, not wall-clock), gated by
    # scripts/compare_bench.py --async-speedup-threshold as an in-record
    # ABSOLUTE floor, same pattern as the round_batch gate. The async
    # knobs land in config_hash like every other program-defining field,
    # so async and sync headline runs can never be silently diffed.
    # BENCH_ASYNC=0 skips; BENCH_ASYNC_ROUNDS sets the length.
    run_async = (
        os.environ.get("BENCH_ASYNC", "1") != "0"
        and model == "cnn_tpu"
        and n_clients == 1000
    )
    if run_async:
        a_rounds = int(os.environ.get("BENCH_ASYNC_ROUNDS", "8"))
        a_config = ExperimentConfig(
            model_name=model, round=a_rounds + 1, client_chunk_size=chunk,
            local_compute_dtype=dtype,
            async_mode="on", arrival_model="bimodal",
            arrival_slow_fraction=0.2, arrival_slow_factor=8.0,
            round_deadline=1.5, async_buffer_size=8, staleness_alpha=0.5,
            **failure_knobs, **common,
        )
        a_times, a_result = _run(
            a_config, dataset=dataset, client_data=client_data
        )
        ar = _rates(a_times, n_clients)
        record["async"] = {
            "value": round(ar["median_rate"], 2),
            "rounds": a_rounds,
            "round_ms": {k: round(v, 1) for k, v in ar["round_ms"].items()},
            "async_speedup_ratio": round(a_result["async_speedup_ratio"], 4),
            "sim_clock_s": round(a_result["sim_clock_seconds"], 3),
            "mean_buffer_occupancy": round(
                a_result["mean_buffer_occupancy"], 3
            ),
            "final_accuracy": a_result["final_accuracy"],
        }

    # Always-on client valuation (ISSUE 9, config.client_valuation;
    # telemetry/valuation.py). Two measurements in one leg: (a) OVERHEAD
    # — the SAME headline program with client_stats='on' +
    # client_valuation='on' (no audits), overhead_ratio measured against
    # this run's own client_stats leg so the number isolates what
    # valuation adds ON TOP of the stats machinery it rides; (b)
    # FIDELITY — the small-N graded-quality differential
    # (telemetry/valuation.grade_client_labels: client i gets i/(N-1) of
    # its labels randomized, a monotonic ground-truth quality gradient)
    # with sparse GTG audits, recording the final audit's Spearman
    # correlation between the streaming vector and the cumulative exact-
    # SV estimate. compare_bench.py gates the correlation ABSOLUTELY
    # (--valuation-corr-threshold, default 0.8 — an in-record floor like
    # the other near-fixed-operating-point ratios, never relatively
    # tracked). Knobs land in config_hash (at 'off' they drop out, so
    # pre-feature hashes are unchanged — utils/reporting.config_hash).
    # BENCH_VALUATION=0 skips; BENCH_VALUATION_ROUNDS,
    # BENCH_VALUATION_FIDELITY_N/_ROUNDS set the two measurements.
    run_valuation = (
        os.environ.get("BENCH_VALUATION", "1") != "0"
        and model == "cnn_tpu"
        and n_clients == 1000
    )
    if run_valuation:
        from distributed_learning_simulator_tpu.telemetry.valuation import (
            grade_client_labels,
        )

        v_rounds = int(os.environ.get("BENCH_VALUATION_ROUNDS", "5"))
        v_config = ExperimentConfig(
            model_name=model, round=v_rounds + 1, client_chunk_size=chunk,
            local_compute_dtype=dtype, client_stats="on",
            client_valuation="on",
            **failure_knobs, **common,
        )
        v_times, v_result = _run(
            v_config, dataset=dataset, client_data=client_data
        )
        vr = _rates(v_times, n_clients)
        valuation_rec = {
            "value": round(vr["median_rate"], 2),
            "rounds": v_rounds,
            "round_ms": {k: round(v, 1) for k, v in vr["round_ms"].items()},
        }
        cs_leg = record.get("client_stats")
        if isinstance(cs_leg, dict):
            valuation_rec["overhead_ratio"] = round(
                vr["round_ms"]["median"] / cs_leg["round_ms"]["median"]
                - 1.0, 4,
            )
        # Fidelity: the measured differential (docs/OBSERVABILITY.md §
        # Client valuation holds the calibration record).
        f_n = int(os.environ.get("BENCH_VALUATION_FIDELITY_N", "8"))
        f_rounds = int(
            os.environ.get("BENCH_VALUATION_FIDELITY_ROUNDS", "9")
        )
        from distributed_learning_simulator_tpu.utils.reporting import (
            config_hash as _chash,
        )

        f_config = ExperimentConfig(
            dataset_name="synthetic", model_name="mlp",
            distributed_algorithm="fed", worker_number=f_n,
            round=f_rounds, epoch=1, learning_rate=0.1, batch_size=32,
            n_train=1024, n_test=2048, log_level="WARNING",
            dataset_args={"difficulty": 0.5},
            client_stats="on", client_valuation="on",
            valuation_audit_every=2, valuation_audit_permutations=500,
            gtg_eps=1e-4,
            compilation_cache_dir=common["compilation_cache_dir"],
        )
        f_ds = get_dataset(
            "synthetic", n_train=1024, n_test=2048, seed=0, difficulty=0.5
        )
        f_cd = build_client_data(f_config, f_ds)
        f_cd.y[:] = grade_client_labels(f_cd.y, f_ds.num_classes, seed=1)
        _, f_result = _run(f_config, dataset=f_ds, client_data=f_cd)
        last = (f_result["valuation"] or {}).get("last_audit") or {}
        valuation_rec["fidelity"] = {
            "n_clients": f_n,
            "rounds": f_rounds,
            "config_hash": _chash(f_config),
            "audits": last.get("audits"),
            "permutations": last.get("permutations"),
            "converged": last.get("converged"),
            "audit_pearson": last.get("pearson"),
        }
        # The gate's number, top-level in the leg (compare_bench.py
        # --valuation-corr-threshold reads valuation.audit_spearman).
        valuation_rec["audit_spearman"] = last.get("spearman")
        record["valuation"] = valuation_rec

    # Open-world churn (ISSUE 13, config.population;
    # robustness/population.py): a 10x population-growth dynamic run on
    # the 1000-client headline data vs the SAME program static. Both
    # legs run the streamed + hashed + sampled composition (the one
    # dynamic populations require — the cohort stays pinned while N
    # grows), so churn_overhead_ratio isolates exactly what the
    # registration stream adds: the masked cohort draw, per-round event
    # draws over the alive population, join-shard packing + store
    # growth, drift label mutation, and the synchronous (non-prefetched)
    # cohort gather. Gated by scripts/compare_bench.py
    # --churn-overhead-threshold as an in-record ABSOLUTE ceiling
    # (default 0.10, never relatively tracked — the PR 4 overhead-gate
    # precedent). The population knobs are program-defining config
    # fields, so the dynamic leg's config_hash differs from the static
    # leg's automatically (at 'static' they drop out — pre-feature
    # hashes unchanged). BENCH_CHURN=0 skips; BENCH_CHURN_ROUNDS /
    # BENCH_CHURN_GROWTH set the horizon and the growth target.
    run_churn = (
        os.environ.get("BENCH_CHURN", "1") != "0"
        and model == "cnn_tpu"
        and n_clients == 1000
    )
    if run_churn:
        ch_rounds = int(os.environ.get("BENCH_CHURN_ROUNDS", "10"))
        ch_growth = float(os.environ.get("BENCH_CHURN_GROWTH", "10"))
        churn_knobs = dict(
            model_name=model, round=ch_rounds + 1,
            client_chunk_size=chunk, local_compute_dtype=dtype,
            client_residency="streamed", participation_sampler="hashed",
            participation_fraction=0.25,
        )
        chs_config = ExperimentConfig(**churn_knobs, **common)
        chs_times, _ = _run(
            chs_config, dataset=dataset, client_data=client_data
        )
        chs_r = _rates(chs_times, n_clients)
        # Integer join rate -> a deterministic growth schedule landing
        # ~on the target population at the horizon. The run executes
        # ch_rounds + 1 rounds (round 0 carries the compile, like every
        # leg) and the registration stream joins clients in EVERY
        # executed round, so the rate is sized over ch_rounds + 1.
        join_rate = round(
            (ch_growth - 1.0) * n_clients / (ch_rounds + 1)
        )
        chd_config = ExperimentConfig(
            population="dynamic", join_rate=float(join_rate),
            depart_rate=0.01, drift_fraction=0.02, drift_factor=0.5,
            **churn_knobs, **common,
        )
        chd_times, chd_result = _run(
            chd_config, dataset=dataset, client_data=client_data
        )
        chd_r = _rates(chd_times, n_clients)
        record["churn"] = {
            "rounds": ch_rounds,
            "growth_target": ch_growth,
            "join_rate": join_rate,
            "static_round_ms": round(chs_r["round_ms"]["median"], 1),
            "dynamic_round_ms": round(chd_r["round_ms"]["median"], 1),
            # The gate's number (compare_bench.py reads
            # churn.churn_overhead_ratio): dynamic-vs-static median
            # round time, minus one.
            "churn_overhead_ratio": round(
                chd_r["round_ms"]["median"] / chs_r["round_ms"]["median"]
                - 1.0, 4,
            ),
            "population": chd_result["population_summary"],
        }

    # Streamed client residency (ISSUE 7, config.client_residency): the
    # population-scale leg. An N-sweep of synthetic populations (cohort
    # fixed, participation_fraction = cohort/N) under
    # client_residency='streamed', where HBM sizes by the COHORT and the
    # full-N shard store lives host-side (data/residency.py +
    # parallel/streaming.py) — the axis the resident headline cannot
    # scale past device memory. Each entry records the steady cohort
    # rate (cohort*rounds/s — only the cohort trains per round, so
    # population c*r/s would be a vanity number) and the run's
    # stream_overlap_ratio (hidden transfer seconds / total transfer
    # seconds — how much of the host->HBM upload the double-buffered
    # prefetch hid behind compute). compare_bench.py gates the LARGEST
    # N's overlap ratio absolutely (--stream-overlap-threshold), the
    # same in-record pattern as the round_batch/async gates: the ratio
    # sits near a fixed operating point, where a relative gate would
    # flap. The residency/sampling knobs are program-defining config
    # fields, so they land in each entry's config_hash automatically.
    # BENCH_STREAM=0 skips; BENCH_STREAM_SWEEP (comma-separated N list),
    # BENCH_STREAM_COHORT, BENCH_STREAM_SHARD, BENCH_STREAM_ROUNDS set
    # the sweep.
    run_stream = (
        os.environ.get("BENCH_STREAM", "1") != "0"
        and model == "cnn_tpu"
        and n_clients == 1000
    )
    if run_stream:
        record["stream"] = _stream_leg()

    # Distributed shard store (ISSUE 15): the 2-process streamed N-sweep
    # — million-client populations COMPOSED with multi-process mesh
    # scale, the composition the config refusal used to block. Gated
    # absolutely by compare_bench.py --mhost-cohort-rate-threshold
    # (armed only on >= 2-core hosts — see _mhost_leg); BENCH_MHOST=0
    # skips.
    run_mhost = (
        os.environ.get("BENCH_MHOST", "1") != "0"
        and model == "cnn_tpu"
        and n_clients == 1000
    )
    if run_mhost:
        record["mhost"] = _mhost_leg()

    # Multi-experiment sweep engine (ISSUE 11, sweep/engine.py): the
    # experiments-per-chip leg — a vmapped seed fleet vs serial solo
    # runs, plus the heterogeneous scheduler's compile-reuse bookkeeping
    # (see _sweep_leg). Gated absolutely by compare_bench.py
    # --sweep-amortization-threshold; BENCH_SWEEP=0 skips. The sweep
    # knobs are config fields, so active sweeps land in config_hash
    # automatically (utils/reporting.config_hash off-gates them at
    # their None defaults).
    run_sweep_leg = (
        os.environ.get("BENCH_SWEEP", "1") != "0"
        and model == "cnn_tpu"
        and n_clients == 1000
    )
    if run_sweep_leg:
        record["sweep"] = _sweep_leg()
        # The sweep leg disabled the persistent compile cache for its
        # honest serial baseline; restore the bench-wide setting for any
        # later leg in this process.
        import jax as _jax

        _jax.config.update(
            "jax_compilation_cache_dir", common["compilation_cache_dir"]
        )

    # Converged-GTG round wall-clock at the north-star population (ISSUE 1:
    # the round-5 verdict's open evidence frontier). Tracked like the
    # flagship leg: BENCH_GTG=0 skips, BENCH_GTG_ROUNDS sets the length.
    # round_trunc_threshold=0 keeps the steady round from being
    # round-truncated (a 0.2 s truncated round is not the cost being
    # tracked); round 0 carries the walk's compile, so the reported value
    # is the LAST round's wall-clock. Knobs pin the documented measurement
    # point (samples 2000 / chunk 64, gtg_prefix_mode from the config
    # default) — docs/PERFORMANCE.md § GTG at scale holds the
    # cumsum-vs-masked comparison.
    run_gtg = (
        os.environ.get("BENCH_GTG", "1") != "0"
        and model == "cnn_tpu"
        and n_clients == 1000
    )
    if run_gtg:
        from distributed_learning_simulator_tpu.utils.reporting import (
            gtg_round_record,
        )

        g_rounds = int(os.environ.get("BENCH_GTG_ROUNDS", "2"))
        # BENCH_GTG_DEVICES > 1 runs the leg with the walk's subset/group
        # axis sharded over the mesh (algorithms/shapley.py — requires
        # that many visible devices; bit-identical to the serial walk).
        g_devices = int(os.environ.get("BENCH_GTG_DEVICES", "1"))
        g_config = ExperimentConfig(
            model_name=model, round=g_rounds, client_chunk_size=chunk,
            round_trunc_threshold=0.0, shapley_eval_samples=2000,
            shapley_eval_chunk=64,
            mesh_devices=g_devices if g_devices > 1 else None,
            **{**common, "distributed_algorithm": "GTG_shapley_value"},
        )
        _, g_result = _run(g_config, dataset=dataset, client_data=client_data)
        record["gtg"] = gtg_round_record(
            g_result["history"],
            prefix_mode=g_config.gtg_prefix_mode, rounds=g_rounds,
            mesh_devices=g_devices,
        )
        # ``evals_per_s`` (the shared constructor computed it from the
        # reported round) is the leg's tracked throughput face; the
        # explicit key keeps the metric name stable for longitudinal
        # tooling even if the record layout above grows.
        if record["gtg"] is not None:
            record["gtg"]["gtg_evals_per_s"] = record["gtg"]["evals_per_s"]
            # D=2/D=1 scaling microbench (subprocess, forced host devices
            # on CPU hosts): compare_bench gates gtg_scaling_ratio
            # absolutely (--gtg-scaling-threshold, default 1.5). The
            # gated key is armed only when the child had >= 2 usable
            # cores — a 1-core cgroup cannot overlap two devices'
            # compute, and an unarmed honest measurement beats a
            # fabricated pass (the costmodel degrade precedent).
            scaling = _gtg_scaling_stats()
            if scaling is not None:
                record["gtg"]["scaling"] = scaling
                ratio = scaling.get("d2_over_d1")
                if ratio is not None and scaling.get("host_cores", 1) >= 2:
                    record["gtg"]["gtg_scaling_ratio"] = ratio

    # Deterministic regression proxy (VERDICT r3 weak #6): the cnn headline's
    # wall-clock band on identical code spans 8.3-11.2k c*r/s (host jitter on
    # ~100 ms rounds through the shared tunnel), hiding sub-25% regressions.
    # XLA's raw_bytes_accessed, summed over a short traced run, is a pure
    # function of the compiled program — identical across runs, moved only
    # by real program changes (lost fusion, extra copies, layout padding).
    run_proxy = (
        os.environ.get("BENCH_PROXY", "1") != "0"
        and model == "cnn_tpu"
        and n_clients == 1000
    )
    if run_proxy:
        record["proxy"] = _proxy_stats(config, dataset, client_data)

    # Same proxy for the flagship ResNet program (VERDICT r4 weak #4): all
    # the round-4 perf work (folded stem, GN custom vjp) lives in this
    # program, and its wall-clock signal is only +-0.2% — a lost fusion
    # costing <2% would be invisible without the byte/op totals. Runs in a
    # SUBPROCESS (bench.py re-exec with BENCH_PROXY_MODE=flagship): a
    # second jax.profiler trace session in one process comes back empty
    # (measured: 5 events, 0 bytes), so each traced program needs a fresh
    # interpreter; the persistent compile cache keeps the re-exec cheap.
    if run_proxy and run_flagship:
        import subprocess
        import sys

        env = dict(os.environ, BENCH_PROXY_MODE="flagship")
        try:
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__)], env=env,
                capture_output=True, text=True, timeout=1800,
            )
            record["proxy_flagship"] = json.loads(
                out.stdout.strip().splitlines()[-1]
            )
        except subprocess.TimeoutExpired:
            # A hung child must not discard the record already measured
            # above (headline + flagship + cnn proxy).
            record["proxy_flagship"] = {"error": "subprocess timeout"}
        except (json.JSONDecodeError, IndexError):
            record["proxy_flagship"] = {
                "error": (out.stderr or out.stdout)[-400:],
            }

    # Predictive cost model (ISSUE 8, telemetry/costmodel.py): evaluate
    # the proxy legs' categorized ledgers through the roofline model —
    # predicted per-round time per topology-table entry, bottleneck
    # attribution, $/converged-run — anchored on BENCH_COSTMODEL_TOPOLOGY
    # (default v5e-1, the measured chip class; docs/PERFORMANCE.md
    # § Predicted pod-scale cost). model_error_ratio (anchor-predicted /
    # this run's measured median round) is gated ABSOLUTELY by
    # scripts/compare_bench.py --model-drift-threshold as a band around
    # 1.0 — the in-record pattern of the other ratio gates: the model is
    # refit deliberately, never by silent drift. BENCH_COSTMODEL=0
    # skips; BENCH_COSTMODEL_RUN_ROUNDS sets the $/run horizon.
    run_cost = (
        os.environ.get("BENCH_COSTMODEL", "1") != "0"
        and isinstance(record.get("proxy"), dict)
        and record["proxy"].get("categories")
    )
    if run_cost:
        from distributed_learning_simulator_tpu.telemetry.costmodel import (
            CONVERGED_RUN_ROUNDS,
            DEFAULT_ANCHOR,
            costmodel_record,
            ledger_totals,
        )

        anchor = os.environ.get("BENCH_COSTMODEL_TOPOLOGY", DEFAULT_ANCHOR)
        cm_rounds = int(os.environ.get(
            "BENCH_COSTMODEL_RUN_ROUNDS", str(CONVERGED_RUN_ROUNDS)
        ))

        def _cm(proxy: dict, measured_ms: float) -> dict:
            if ledger_totals(proxy["categories"])["bytes_gb"] <= 0:
                # CPU traces carry no raw_bytes_accessed: a zero-byte
                # ledger predicts nothing — degrade, don't fabricate.
                return {"error": "trace carries no byte annotations"}
            return costmodel_record(
                proxy["categories"], trace_rounds=proxy["trace_rounds"],
                anchor=anchor, measured_ms=measured_ms,
                run_rounds=cm_rounds,
            )

        record["costmodel"] = {
            "cnn": _cm(record["proxy"], r["round_ms"]["median"]),
        }
        fl_proxy = record.get("proxy_flagship")
        if (
            isinstance(fl_proxy, dict) and fl_proxy.get("categories")
            and "flagship" in record
        ):
            cm_fl = _cm(fl_proxy, record["flagship"]["round_ms"]["median"])
            record["costmodel"]["flagship"] = cm_fl
            pod = (cm_fl.get("per_topology") or {}).get("v4-32")
            if pod:
                # The acceptance projection: the flagship config priced
                # at pod scale before a single v4 chip-hour is spent.
                record["costmodel"]["pod_projection"] = {
                    "program": "flagship",
                    "topology": "v4-32",
                    "run_rounds": cm_rounds,
                    "predicted_round_ms": pod["predicted_ms"],
                    "chip_hours_per_run": round(
                        pod["predicted_ms"] / 3.6e6 * pod["chips"]
                        * cm_rounds, 4
                    ),
                    "usd_per_run": pod.get("usd_per_run"),
                }

    # Warm-program accounting for the legs that ran through the shared
    # scheduler (see _run): programs_compiled < points means at least
    # one leg rode another leg's warm program (the headline's serves
    # the round_batch K=1 leg — same config_hash, different horizon).
    if _SCHEDULER is not None:
        record["warm_programs"] = {
            "points": _SCHEDULER.points_run,
            "programs_compiled": _SCHEDULER.programs_compiled,
            "fallback_points": _SCHEDULER.fallback_points,
        }

    print(json.dumps(record))


if __name__ == "__main__":
    main()
