"""Algorithm registry: string -> strategy class.

Parity with the reference's ``factory.py`` dispatch (factory.py:14-35): the
same five registry names select the same five algorithms; unknown names raise
(factory.py:25,35). Where the reference returns separate server/worker
classes, here one strategy object owns both sides of the round (see
algorithms/base.py).
"""

from __future__ import annotations

from distributed_learning_simulator_tpu.algorithms.fed_quant import FedQuant
from distributed_learning_simulator_tpu.algorithms.fedavg import FedAvg
from distributed_learning_simulator_tpu.algorithms.shapley import (
    GTGShapley,
    MultiRoundShapley,
)
from distributed_learning_simulator_tpu.algorithms.sign_sgd import SignSGD

_ALGORITHMS = {
    "fed": FedAvg,
    "sign_SGD": SignSGD,
    "fed_quant": FedQuant,
    "multiround_shapley_value": MultiRoundShapley,
    "GTG_shapley_value": GTGShapley,
}


def registered_algorithms():
    return sorted(_ALGORITHMS)


def get_algorithm(name: str, config):
    """Instantiate the algorithm strategy for ``name`` (reference registry
    names, factory.py:14-35)."""
    if name not in _ALGORITHMS:
        raise RuntimeError(
            f"unknown distributed algorithm {name!r}; "
            f"registered: {registered_algorithms()}"
        )
    return _ALGORITHMS[name](config)
