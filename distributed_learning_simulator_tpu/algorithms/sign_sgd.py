"""SignSGD with majority vote — per-step synchronized 1-bit SGD.

Replaces the reference's SignSGDServer/SignSGDWorker pair
(servers/sign_sgd_server.py, workers/sign_sgd_worker.py). Reference
semantics (per SURVEY 3.3): every optimizer step, each worker computes its
effective SGD update direction (momentum/dampening/nesterov math replicated
at sign_sgd_worker.py:22-42), signs it (1-bit compression, :44), ships it to
the server, which sums signs elementwise and re-signs (majority vote,
sign_sgd_server.py:16-18); workers then apply weight decay plus
``p <- p - lr * voted_sign`` (:47-58). (The reference server is mis-wired —
its vote method is never invoked — so this implements the intended, fixed
behavior, SURVEY 2.1#13.)

TPU-native formulation: because every worker applies the same voted update,
all workers hold identical params at every step. So the round function keeps
ONE shared params pytree; per-step "communication" is a sign + sum + sign
over the client axis *inside* the step scan — the highest-frequency
communication pattern in the system becomes a fused reduction in a single
XLA program (an ICI psum when the client axis is sharded), instead of a
GPU->CPU->queue round-trip per optimizer step (sign_sgd_worker.py:44-46).

SGD is required, parity with the reference's assertion
(sign_sgd_worker.py:14).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from distributed_learning_simulator_tpu.algorithms.base import Algorithm
from distributed_learning_simulator_tpu.ops.sign import (
    direction_leaf,
    majority_vote,
    momentum_leaf,
    sign_compress,
    vote_apply_leaf,
)
from distributed_learning_simulator_tpu.parallel.engine import make_loss_fn


class SignSGD(Algorithm):
    name = "sign_SGD"

    def __init__(self, config):
        super().__init__(config)
        if config.optimizer_name.lower() != "sgd":
            raise ValueError(
                "sign_SGD requires the SGD optimizer "
                "(parity with reference sign_sgd_worker.py:14)"
            )
        if getattr(config, "augment", "none").lower() not in ("none", ""):
            # sign_SGD builds its own per-step sync loop that doesn't plumb
            # augmentation; reject rather than silently train un-augmented.
            raise ValueError(
                "sign_SGD does not support data augmentation; set "
                "augment='none'"
            )
        if getattr(config, "aggregation", "mean").lower() != "mean":
            # Aggregation IS the sign majority vote here; a robust-mean
            # setting would be silently meaningless.
            raise ValueError(
                "sign_SGD aggregates by sign majority vote; set "
                "aggregation='mean'"
            )
        if getattr(config, "local_compute_dtype", "float32") != "float32":
            # sign_SGD keeps ONE shared params tree (no per-client diverged
            # state to compress); reject rather than silently ignore.
            raise ValueError(
                "sign_SGD does not use local_compute_dtype; set it to "
                "'float32'"
            )

    def init_client_state(self, optimizer, global_params, n_clients):
        """Per-client momentum buffers + step counters (reference replicates
        torch-SGD momentum state per worker, sign_sgd_worker.py:22-42; the
        counter reproduces torch's buf-initialized-to-raw-gradient first
        step)."""
        zeros = jax.tree_util.tree_map(jnp.zeros_like, global_params)
        momenta = jax.tree_util.tree_map(
            lambda z: jnp.broadcast_to(z, (n_clients,) + z.shape), zeros
        )
        return {"momenta": momenta, "steps": jnp.zeros(n_clients, jnp.int32)}

    def make_round_fn(self, apply_fn, optimizer, n_clients: int,
                      preprocess=None):
        cfg = self.config
        lr = cfg.learning_rate
        mu = cfg.momentum
        dampening = getattr(cfg, "dampening", 0.0)
        nesterov = getattr(cfg, "nesterov", False)
        wd = cfg.weight_decay
        batch_size = cfg.batch_size
        epochs = cfg.epoch
        loss_fn = make_loss_fn(apply_fn)
        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        def round_fn(global_params, client_state, cx, cy, cmask, sizes, key):
            del sizes  # vote is unweighted, parity with sign_sgd_server.py:16-18
            shard_size = cx.shape[1]
            steps_per_epoch = shard_size // batch_size

            def epoch_body(carry, epoch_key):
                params, momenta, step_counts = carry
                perm_keys = jax.random.split(epoch_key, n_clients)
                perms = jax.vmap(
                    lambda k: jax.random.permutation(k, shard_size)
                )(perm_keys)  # [C, S]

                def step_body(carry, step):
                    params, momenta, step_counts = carry
                    idx = jax.lax.dynamic_slice_in_dim(
                        perms, step * batch_size, batch_size, axis=1
                    )  # [C, B]
                    bx = jax.vmap(lambda x, i: jnp.take(x, i, axis=0))(cx, idx)
                    by = jax.vmap(lambda y, i: jnp.take(y, i, axis=0))(cy, idx)
                    bm = jax.vmap(lambda m, i: jnp.take(m, i, axis=0))(cmask, idx)
                    if preprocess is not None:
                        bx = jax.vmap(preprocess)(bx)
                    # Per-client gradients at the SHARED params.
                    (losses, _), grads = jax.vmap(
                        grad_fn, in_axes=(None, 0, 0, 0)
                    )(params, bx, by, bm)
                    # torch-SGD step math: ops/sign.py leaf formulas, the
                    # single source shared with the threaded oracle.
                    is_first = step_counts == 0  # [C]

                    momenta_new = jax.tree_util.tree_map(
                        lambda m, g: momentum_leaf(
                            m, g,
                            is_first.reshape((-1,) + (1,) * (g.ndim - 1)),
                            mu, dampening,
                        ),
                        momenta, grads,
                    )
                    direction = jax.tree_util.tree_map(
                        lambda g, m: direction_leaf(g, m, mu, nesterov),
                        grads, momenta_new,
                    )
                    # sign -> sum over clients -> sign: the majority vote.
                    voted = majority_vote(sign_compress(direction))
                    params = jax.tree_util.tree_map(
                        lambda p, v: vote_apply_leaf(p, v, lr, wd),
                        params, voted,
                    )
                    return (params, momenta_new, step_counts + 1), jnp.mean(losses)

                (params, momenta, step_counts), step_losses = jax.lax.scan(
                    step_body, (params, momenta, step_counts),
                    jnp.arange(steps_per_epoch),
                )
                return (params, momenta, step_counts), jnp.mean(step_losses)

            epoch_keys = jax.random.split(key, epochs)
            carry0 = (
                global_params, client_state["momenta"], client_state["steps"]
            )
            (params, momenta, step_counts), epoch_losses = jax.lax.scan(
                epoch_body, carry0, epoch_keys
            )
            aux = {
                "mean_client_loss": epoch_losses[-1],
                "sync_steps": jnp.asarray(epochs * steps_per_epoch),
            }
            new_state = {"momenta": momenta, "steps": step_counts}
            return params, new_state, aux

        return round_fn

    def post_round(self, ctx):
        from distributed_learning_simulator_tpu.ops.payload import (
            compression_ratio,
            payload_bytes,
            sign_payload_bytes,
        )

        raw = payload_bytes(ctx.global_params)
        signed = sign_payload_bytes(ctx.global_params)
        return {
            "uplink_compression_ratio": compression_ratio(raw, signed),
            "payload_bytes_sign": signed,
        }
