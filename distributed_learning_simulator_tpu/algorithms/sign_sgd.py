"""SignSGD with majority vote — per-step synchronized 1-bit SGD.

Replaces the reference's SignSGDServer/SignSGDWorker pair
(servers/sign_sgd_server.py, workers/sign_sgd_worker.py). Reference
semantics (per SURVEY 3.3): every optimizer step, each worker computes its
effective SGD update direction (momentum/dampening/nesterov math replicated
at sign_sgd_worker.py:22-42), signs it (1-bit compression, :44), ships it to
the server, which sums signs elementwise and re-signs (majority vote,
sign_sgd_server.py:16-18); workers then apply weight decay plus
``p <- p - lr * voted_sign`` (:47-58). (The reference server is mis-wired —
its vote method is never invoked — so this implements the intended, fixed
behavior, SURVEY 2.1#13.)

TPU-native formulation: because every worker applies the same voted update,
all workers hold identical params at every step. So the round function keeps
ONE shared params pytree; per-step "communication" is a sign + sum + sign
over the client axis *inside* the step scan — the highest-frequency
communication pattern in the system becomes a fused reduction in a single
XLA program (an ICI psum when the client axis is sharded), instead of a
GPU->CPU->queue round-trip per optimizer step (sign_sgd_worker.py:44-46).

SGD is required, parity with the reference's assertion
(sign_sgd_worker.py:14).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from distributed_learning_simulator_tpu.algorithms.base import (
    Algorithm,
    adapt_full_cohort_streamed,
)
from distributed_learning_simulator_tpu.ops.cohort import batched_take
from distributed_learning_simulator_tpu.ops.sign import (
    direction_leaf,
    momentum_leaf,
    vote_apply_leaf,
)
from distributed_learning_simulator_tpu.parallel.engine import (
    chunked_accumulate,
    make_loss_fn,
)
from distributed_learning_simulator_tpu.robustness.faults import (
    FailureModel,
    all_finite,
)
from distributed_learning_simulator_tpu.telemetry.client_stats import (
    ClientStats,
)


class SignSGD(Algorithm):
    name = "sign_SGD"
    # Round batching (config.rounds_per_dispatch): the round keeps ONE
    # shared params tree and scalar aux, and post_round's payload-byte
    # accounting is a pure shape function — nothing needs per-round
    # parameter state, so K rounds scan cleanly into one dispatch.
    supports_round_batching = True
    # Streamed residency (config.client_residency='streamed'): the
    # per-step vote synchronizes EVERY client (the constructor rejects
    # participation_fraction < 1), so the "cohort" is always the whole
    # population — the round adapts to the streamed calling convention
    # via adapt_full_cohort_streamed and the data upload happens once.
    supports_streamed_residency = True

    def __init__(self, config):
        super().__init__(config)
        if config.optimizer_name.lower() != "sgd":
            raise ValueError(
                "sign_SGD requires the SGD optimizer "
                "(parity with reference sign_sgd_worker.py:14)"
            )
        if getattr(config, "augment", "none").lower() not in ("none", ""):
            # sign_SGD builds its own per-step sync loop that doesn't plumb
            # augmentation; reject rather than silently train un-augmented.
            raise ValueError(
                "sign_SGD does not support data augmentation; set "
                "augment='none'"
            )
        if getattr(config, "aggregation", "mean").lower() != "mean":
            # Aggregation IS the sign majority vote here; a robust-mean
            # setting would be silently meaningless.
            raise ValueError(
                "sign_SGD aggregates by sign majority vote; set "
                "aggregation='mean'"
            )
        if getattr(config, "local_compute_dtype", "float32") != "float32":
            # sign_SGD keeps ONE shared params tree (no per-client diverged
            # state to compress); reject rather than silently ignore.
            raise ValueError(
                "sign_SGD does not use local_compute_dtype; set it to "
                "'float32'"
            )
        if getattr(config, "participation_fraction", 1.0) < 1.0:
            # Per-step votes are over the FULL population (the reference
            # barrier, sign_sgd_server.py:13-18); reject rather than
            # silently train everyone.
            raise ValueError(
                "sign_SGD votes over every client each step; "
                "participation_fraction < 1 is not supported"
            )
        if FailureModel.from_config(config) is not None and getattr(
            config, "failure_mode", "none"
        ) in ("corrupt_nan", "corrupt_scale"):
            # The uplink here is a 1-bit sign vote — there is no
            # parameter-space payload to corrupt (sign(NaN) would poison
            # the vote sum itself, which models a broken SERVER, not a
            # faulty client). Dropout/straggler apply: a failed client's
            # votes are excluded and the threshold counts survivors only.
            raise ValueError(
                "sign_SGD supports failure_mode dropout/straggler only "
                "(its 1-bit vote has no parameter payload to corrupt); "
                f"got {config.failure_mode!r}"
            )

    def init_client_state(self, optimizer, global_params, n_clients):
        """Per-client momentum buffers + step counters (reference replicates
        torch-SGD momentum state per worker, sign_sgd_worker.py:22-42; the
        counter reproduces torch's buf-initialized-to-raw-gradient first
        step). With momentum 0 there is NO buffer (torch never allocates
        one) — at 1000 clients x ResNet-18 the buffers alone would be
        ~44 GB, so skipping them is what makes momentum-free sign_SGD run
        at large-model scale on one chip."""
        if self.config.momentum == 0.0:
            return None
        zeros = jax.tree_util.tree_map(jnp.zeros_like, global_params)
        momenta = jax.tree_util.tree_map(
            lambda z: jnp.broadcast_to(z, (n_clients,) + z.shape), zeros
        )
        return {"momenta": momenta, "steps": jnp.zeros(n_clients, jnp.int32)}

    def make_round_fn(self, apply_fn, optimizer, n_clients: int,
                      preprocess=None, client_sizes=None):
        # client_sizes (size-aware scheduling) is accepted but unused: the
        # per-step majority vote synchronizes EVERY client at every
        # optimizer step, so all clients must run the same step count.
        cfg = self.config
        lr = cfg.learning_rate
        mu = cfg.momentum
        dampening = getattr(cfg, "dampening", 0.0)
        nesterov = getattr(cfg, "nesterov", False)
        wd = cfg.weight_decay
        batch_size = cfg.batch_size
        epochs = cfg.epoch
        loss_fn = make_loss_fn(apply_fn)
        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        chunk = cfg.client_chunk_size
        has_momentum = mu != 0.0
        # Failure model (robustness/faults.py): dropout/straggler only (the
        # constructor rejects corrupt modes). Drawn ONCE per round from the
        # round key — a failed client misses the whole round's lockstep:
        # its per-step votes are excluded (the majority threshold counts
        # surviving voters only) and its momentum/step state freezes.
        # Every fm-gated branch is trace-time, so failure-free runs compile
        # the exact pre-feature program.
        fm = FailureModel.from_config(cfg)
        min_survivors = getattr(cfg, "min_survivors", 0)
        quorum = fm is not None or min_survivors > 0
        # Per-client stats (telemetry/client_stats.py): sign_SGD keeps ONE
        # shared params tree, so there is no per-client delta to score —
        # instead expose the per-step majority-vote agreement fraction
        # (computed and thrown away inside the vote until now) as a round
        # statistic. Trace-time gated like the failure model: 'off'
        # compiles the exact pre-feature program.
        cs = ClientStats.from_config(cfg)

        def round_fn(global_params, client_state, cx, cy, cmask, sizes, key,
                     lr_scale=1.0):
            # lr_scale: accepted for round-program signature uniformity;
            # config.validate() rejects non-constant schedules for sign_SGD
            # (the lr lives in the vote-apply, torch-parity semantics).
            del lr_scale
            del sizes  # vote is unweighted, parity with sign_sgd_server.py:16-18
            shard_size = cx.shape[1]
            steps_per_epoch = shard_size // batch_size
            if fm is not None:
                key, fault_key = jax.random.split(key)
                failed = fm.draw_failed(fault_key, n_clients)
                surv_f = (~failed).astype(jnp.float32)  # [C]
                n_surv = jnp.sum(surv_f).astype(jnp.int32)
                any_surv = n_surv > 0
            else:
                surv_f = None

            def chunk_compute(params, momenta_c, is_first_c, bx, by, bm,
                              surv_c=None):
                """Per-chunk: grads at the shared params -> torch-SGD
                direction -> partial sign-sum over the chunk's clients.
                ``surv_c`` (f32 0/1 per client; None when no failure model)
                zeroes excluded voters' signs, freezes their momenta, and
                drops them from the loss sum.
                Returns (vote partial sums, new momenta, summed loss)."""
                if preprocess is not None:
                    bx = jax.vmap(preprocess)(bx)
                (losses, _), grads = jax.vmap(
                    grad_fn, in_axes=(None, 0, 0, 0)
                )(params, bx, by, bm)
                if has_momentum:
                    # torch-SGD step math: ops/sign.py leaf formulas, the
                    # single source shared with the threaded oracle.
                    momenta_new = jax.tree_util.tree_map(
                        lambda m, g: momentum_leaf(
                            m, g,
                            is_first_c.reshape((-1,) + (1,) * (g.ndim - 1)),
                            mu, dampening,
                        ),
                        momenta_c, grads,
                    )
                    direction = jax.tree_util.tree_map(
                        lambda g, m: direction_leaf(g, m, mu, nesterov),
                        grads, momenta_new,
                    )
                else:
                    # torch allocates no buffer at momentum 0: the
                    # direction IS the raw gradient (nesterov with mu=0
                    # reduces to it too).
                    momenta_new = momenta_c
                    direction = grads
                if surv_c is None:
                    partial = jax.tree_util.tree_map(
                        lambda d: jnp.sum(jnp.sign(d), axis=0), direction
                    )
                    loss_sum = jnp.sum(losses)
                else:
                    partial = jax.tree_util.tree_map(
                        lambda d: jnp.sum(
                            jnp.sign(d)
                            * surv_c.reshape((-1,) + (1,) * (d.ndim - 1)),
                            axis=0,
                        ),
                        direction,
                    )
                    loss_sum = jnp.sum(losses * surv_c)
                    if has_momentum:
                        momenta_new = jax.tree_util.tree_map(
                            lambda old, new: jnp.where(
                                surv_c.reshape(
                                    (-1,) + (1,) * (new.ndim - 1)
                                ) > 0,
                                new, old,
                            ),
                            momenta_c, momenta_new,
                        )
                return partial, momenta_new, loss_sum

            def epoch_body(carry, epoch_key):
                params, momenta, step_counts = carry
                perm_keys = jax.random.split(epoch_key, n_clients)
                perms = jax.vmap(
                    lambda k: jax.random.permutation(k, shard_size)
                )(perm_keys)  # [C, S]

                def step_body(carry, step):
                    params, momenta, step_counts = carry
                    idx = jax.lax.dynamic_slice_in_dim(
                        perms, step * batch_size, batch_size, axis=1
                    )  # [C, B]
                    # Per-client minibatch gather over the client axis:
                    # ops/cohort.batched_take, the ONE copy shared with
                    # the FedAvg-family cohort index ops.
                    bx = batched_take(cx, idx)
                    by = batched_take(cy, idx)
                    bm = batched_take(cmask, idx)
                    is_first = step_counts == 0  # [C]

                    if chunk is None or chunk >= n_clients:
                        vote_sum, momenta_new, loss_sum = chunk_compute(
                            params, momenta, is_first, bx, by, bm, surv_f
                        )
                    else:
                        # Chunked vote: per-client gradients exist only
                        # chunk-at-a-time; partial sign-sums accumulate into
                        # the vote so the full [n_clients, n_params] gradient
                        # stack never materializes (at 1000 clients x
                        # ResNet-18 it would be ~44 GB). chunked_accumulate
                        # (parallel/engine.py) holds the reshape/scan/
                        # remainder discipline — any chunk size works.
                        def compute(chunk_trees, _pc):
                            if surv_f is None:
                                m_c, f_c, bx_c, by_c, bm_c = chunk_trees
                                s_c = None
                            else:
                                m_c, f_c, bx_c, by_c, bm_c, s_c = chunk_trees
                            partial, m_new, l_sum = chunk_compute(
                                params, m_c, f_c, bx_c, by_c, bm_c, s_c
                            )
                            return (partial, l_sum), m_new

                        acc0 = (
                            jax.tree_util.tree_map(
                                lambda p: jnp.zeros_like(p, jnp.float32),
                                params,
                            ),
                            jnp.float32(0.0),
                        )
                        trees = (momenta, is_first, bx, by, bm)
                        if surv_f is not None:
                            trees = trees + (surv_f,)
                        (vote_sum, loss_sum), momenta_new = (
                            chunked_accumulate(
                                trees, chunk,
                                compute, acc0,
                            )
                        )
                    # sign of the summed signs: the majority vote
                    # (sign_sgd_server.py:16-18) — over surviving voters
                    # only when a failure model is active (excluded signs
                    # contribute 0 to the sum).
                    voted = jax.tree_util.tree_map(jnp.sign, vote_sum)
                    new_params = jax.tree_util.tree_map(
                        lambda p, v: vote_apply_leaf(p, v, lr, wd),
                        params, voted,
                    )
                    if surv_f is not None:
                        # A zero-survivor round must not silently apply
                        # weight decay (no client stepped at all); steps
                        # advance only for clients that participated.
                        new_params = jax.tree_util.tree_map(
                            lambda nw, od: jnp.where(any_surv, nw, od),
                            new_params, params,
                        )
                        step_inc = surv_f.astype(jnp.int32)
                        denom = jnp.maximum(n_surv, 1).astype(jnp.float32)
                    else:
                        step_inc = 1
                        denom = n_clients
                    step_out = loss_sum / denom
                    if cs is not None:
                        # Majority-vote agreement fraction: a coordinate
                        # with vote sum v over V voters has (V + |v|) / 2
                        # voters agreeing with the majority, so the mean
                        # agreement over all P coordinates is
                        # 1/2 + mean|v| / (2V). 1.0 = unanimous step,
                        # 0.5 = coin-flip gradient directions.
                        n_params_total = sum(
                            v.size
                            for v in jax.tree_util.tree_leaves(vote_sum)
                        )
                        abs_sum = sum(
                            jnp.sum(jnp.abs(v).astype(jnp.float32))
                            for v in jax.tree_util.tree_leaves(vote_sum)
                        )
                        agree = 0.5 + abs_sum / (
                            2.0 * denom * n_params_total
                        )
                        step_out = (step_out, agree)
                    return (
                        new_params, momenta_new, step_counts + step_inc
                    ), step_out

                (params, momenta, step_counts), step_outs = jax.lax.scan(
                    step_body, (params, momenta, step_counts),
                    jnp.arange(steps_per_epoch),
                )
                if cs is not None:
                    step_losses, step_agree = step_outs
                    return (params, momenta, step_counts), (
                        jnp.mean(step_losses), jnp.mean(step_agree)
                    )
                return (params, momenta, step_counts), jnp.mean(step_outs)

            epoch_keys = jax.random.split(key, epochs)
            if has_momentum:
                momenta0 = client_state["momenta"]
                steps0 = client_state["steps"]
            else:
                momenta0 = None
                steps0 = jnp.zeros(n_clients, jnp.int32)
            carry0 = (global_params, momenta0, steps0)
            (params, momenta, step_counts), epoch_outs = jax.lax.scan(
                epoch_body, carry0, epoch_keys
            )
            if cs is not None:
                epoch_losses, epoch_agree = epoch_outs
            else:
                epoch_losses = epoch_outs
            aux = {
                "mean_client_loss": epoch_losses[-1],
                "sync_steps": jnp.asarray(epochs * steps_per_epoch),
            }
            if cs is not None:
                # Round-mean vote agreement (per-step fractions averaged
                # over the round's epochs x steps).
                aux["vote_agreement"] = jnp.mean(epoch_agree)
            if quorum:
                # Quorum policy (mirrors fedavg.round_fn): reject the round
                # — revert to the round-start params — when survivors fall
                # below min_survivors or the voted params went non-finite.
                # Momentum/step state keeps its per-client masking (failed
                # clients froze themselves above); rejection only refuses
                # the SHARED model the round produced.
                survivor_count = (
                    n_surv if fm is not None
                    else jnp.asarray(n_clients, jnp.int32)
                )
                finite = all_finite(params)
                rejected = (~finite) | (survivor_count < min_survivors)
                params = jax.tree_util.tree_map(
                    lambda nw, od: jnp.where(rejected, od.astype(nw.dtype), nw),
                    params, global_params,
                )
                aux["survivor_count"] = survivor_count
                aux["round_rejected"] = rejected
            new_state = (
                {"momenta": momenta, "steps": step_counts}
                if has_momentum else None
            )
            return params, new_state, aux

        if (
            getattr(cfg, "client_residency", "resident").lower()
            == "streamed"
        ):
            # Full-cohort streamed convention: identical program, the
            # idx operand (always None here) absorbed by the adapter.
            return adapt_full_cohort_streamed(round_fn)
        return round_fn

    def post_round(self, ctx):
        from distributed_learning_simulator_tpu.ops.payload import (
            compression_ratio,
            payload_bytes,
            sign_payload_bytes,
        )

        raw = payload_bytes(ctx.global_params)
        signed = sign_payload_bytes(ctx.global_params)
        return {
            "uplink_compression_ratio": compression_ratio(raw, signed),
            "payload_bytes_sign": signed,
        }
