"""FedAvg: dataset-size-weighted federated averaging.

Replaces the reference's FedServer/FedWorker pair (servers/fed_server.py,
workers/fed_worker.py). One round = one jitted program:

  broadcast global params (vmap in_axes=None — the RepeatedResult broadcast of
  fed_server.py:19-24) -> vmap'd local training, E epochs each
  (fed_worker.py:25-27) -> dataset-size-weighted average over the client axis
  (fed_server.py:44-66,81) -> hooks.

The queue barrier (fed_server.py:75-77) is implicit: a jitted program's
aggregation consumes all clients' outputs by construction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_learning_simulator_tpu.algorithms.base import Algorithm
from distributed_learning_simulator_tpu.ops.aggregate import (
    aggregate,
    weighted_mean,
)
from distributed_learning_simulator_tpu.ops.cohort import (
    cohort_scatter,
    cohort_take,
)
from distributed_learning_simulator_tpu.ops.sampling import (
    draw_cohort,
    draw_cohort_host,
)
from distributed_learning_simulator_tpu.parallel.engine import (
    chunked_accumulate,
    make_local_train_fn,
)
from distributed_learning_simulator_tpu.robustness.arrivals import (
    AsyncFederation,
)
from distributed_learning_simulator_tpu.robustness.faults import (
    FailureModel,
    all_finite,
)
from distributed_learning_simulator_tpu.telemetry.client_stats import (
    ClientStats,
)
from distributed_learning_simulator_tpu.telemetry.valuation import (
    ClientValuation,
)


def round_key_splits(key, with_faults: bool):
    """The round key's split chain — the ONE copy shared by the round
    program (resident and streamed entries), the host-side cohort replay
    (:meth:`FedAvg.cohort_indices`), and the valuation auditor's
    training replay (telemetry/valuation.py), so none of them can drift.
    The extra fault split is gated so failure-free runs keep the exact
    pre-feature RNG streams (bit-compatible histories). Returns
    ``(part_key, train_key, payload_key, agg_key, fault_key)`` with
    ``fault_key=None`` when no failure model is active."""
    if with_faults:
        part_key, train_key, payload_key, agg_key, fault_key = (
            jax.random.split(key, 5)
        )
    else:
        part_key, train_key, payload_key, agg_key = (
            jax.random.split(key, 4)
        )
        fault_key = None
    return part_key, train_key, payload_key, agg_key, fault_key


#: One jitted program per fault-gating flavor of the round-key split:
#: ``round_key -> key_data(round_key_splits(round_key, wf)[0])``. The
#: hashed host replay runs once per round; composing the split +
#: key_data EAGERLY costs ~10 ms of per-op dispatch overhead — 50x the
#: O(cohort) draw itself — so the chain is compiled once and dispatched
#: as one call. Built FROM :func:`round_key_splits` (never a re-spelled
#: split width) so a future change to the split chain flows into the
#: hashed replay automatically — the one-copy discipline.
_HASHED_PART_WORDS_JIT: dict = {}


def _hashed_part_key_words(round_key, with_faults: bool):
    fn = _HASHED_PART_WORDS_JIT.get(with_faults)
    if fn is None:
        def _words(key, _wf=with_faults):
            return jax.random.key_data(round_key_splits(key, _wf)[0])

        fn = jax.jit(_words)
        _HASHED_PART_WORDS_JIT[with_faults] = fn
    return np.asarray(fn(round_key)).ravel()


class FedAvg(Algorithm):
    name = "fed"
    supports_lr_schedule = True  # round_fn accepts the lr_scale operand
    # Asynchronous federation (config.async_mode; robustness/arrivals.py):
    # the round program implements deadline rounds + the staleness buffer
    # (carried via the async_state operand / aux key). fed_quant inherits
    # — its payload transform applies to fresh and late uploads alike.
    supports_async = True
    # Streamed residency (config.client_residency='streamed'): the round
    # builder emits the streamed calling convention natively — the cohort
    # slice arrives as already-gathered operands, the in-program gather/
    # scatter drops out, and the shared cohort_round body keeps the two
    # programs bit-identical. fed_quant inherits.
    supports_streamed_residency = True

    def __init__(self, config):
        super().__init__(config)
        # Per-round per-client evaluation (config.client_eval): every
        # client's uploaded model evaluated on the test set BEFORE
        # aggregation, plus the post-aggregation global accuracy — the
        # reference logs this for fed_quant (fed_quant_worker.py:55-69);
        # here it is FedAvg-family machinery any subclass can enable.
        # None = auto: on only for fed_quant at reference-like cohort
        # sizes (<= 32); explicit True forces it (and the materializing
        # path), False disables.
        ce = getattr(config, "client_eval", None)
        if ce is None:
            ce = self.name == "fed_quant" and config.cohort_size() <= 32
            if self.name == "fed_quant" and not ce:
                from distributed_learning_simulator_tpu.utils.logging import (
                    get_logger,
                )

                get_logger().info(
                    "client_eval auto-disabled: cohort size %d > 32 (the "
                    "per-client eval needs the materializing path); pass "
                    "client_eval=True to force it",
                    config.cohort_size(),
                )
        # client_eval materializes the RAW per-client stack through this
        # private flag — NOT by setting keep_client_params, which is the
        # documented subclass contract for receiving the payload-processed
        # stack in aux['client_params'] (base.Algorithm.keep_client_params).
        self._client_eval_enabled = bool(ce)
        self._eval_fn = None
        self._client_eval_jit = None

    def prepare(self, apply_fn, eval_fn):
        self._eval_fn = eval_fn

    @property
    def materializes_client_stack(self) -> bool:
        # Single source for "does the round hold the full cohort stack":
        # make_round_fn allocates by it, the simulator feasibility-checks it.
        return (
            self.keep_client_params
            or self._client_eval_enabled
            or self.config.aggregation.lower() != "mean"
        )

    @property
    def supports_round_batching(self) -> bool:
        # Round batching (config.rounds_per_dispatch) scan-stacks every
        # aux output over K rounds: keep_client_params would materialize
        # K cohort-sized parameter stacks, and client_eval's post_round
        # must evaluate each round's raw stack — per-round data a
        # batched dispatch cannot provide. Robust aggregation rules are
        # fine: their stack is transient inside each scan iteration.
        return not (self.keep_client_params or self._client_eval_enabled)

    # jax-level template hooks, parity with fed_server.py:38-42 -------------
    def process_client_payload(self, client_params, key):
        """Per-client payload transform before aggregation (identity here;
        FedQuant overrides with quantize->dequantize)."""
        return client_params, {}

    def post_round(self, ctx):
        if not self._client_eval_enabled:
            return {}
        client_params = ctx.aux.get("client_params_raw")
        if client_params is None:
            # No silent fallback to the payload-transformed stack: that
            # would quietly revert the telemetry to evaluating the
            # quantized upload (the deviation this field exists to avoid).
            raise RuntimeError(
                "client_eval is enabled but the round produced no raw "
                "per-client parameter stack (wiring bug in the round "
                "program)"
            )
        import numpy as np

        if self._client_eval_jit is None:
            # One inference program evaluates every client's model: vmap
            # over the stacked params, the padded test batches broadcast.
            # Inference runs through client_param_transform (fed_quant's QAT
            # fake-quant) — the reference evaluates the QAT-INSTRUMENTED
            # model, i.e. fake-quant stays active in its eval forward pass
            # (fed_quant_worker.py:55-58); for plain fed the transform is
            # None and this is the raw eval.
            transform = self.client_param_transform()
            eval_fn = self._eval_fn

            def eval_one(params, *batches):
                if transform is not None:
                    params = transform(params)
                return eval_fn(params, *batches)

            in_axes = (0,) + (None,) * len(ctx.eval_batches)
            self._client_eval_jit = jax.jit(
                jax.vmap(eval_one, in_axes=in_axes)
            )
        m = self._client_eval_jit(client_params, *ctx.eval_batches)
        accs = np.asarray(m["accuracy"], dtype=np.float64)
        from distributed_learning_simulator_tpu.utils.logging import get_logger

        get_logger().info(
            "round %d: pre-agg client acc mean=%.4f min=%.4f max=%.4f; "
            "post-agg global acc=%.4f",
            ctx.round_idx, accs.mean(), accs.min(), accs.max(),
            ctx.metrics["accuracy"],
        )
        return {
            "client_eval": {
                "pre_agg_accuracy_mean": float(accs.mean()),
                "pre_agg_accuracy_min": float(accs.min()),
                "pre_agg_accuracy_max": float(accs.max()),
                "post_agg_accuracy": float(ctx.metrics["accuracy"]),
            }
        }

    def process_aggregated(self, global_params, key):
        """Aggregated-params transform (identity; FedQuant quantizes the
        broadcast). Returns (params, extra_aux)."""
        return global_params, {}

    def cohort_indices(self, round_key, n_clients: int, alive=None,
                       n_participants=None):
        """Host-replay of the round program's cohort draw (base contract).

        MUST mirror ``split_round_key`` + the in-program
        ``ops/sampling.draw_cohort`` in ``make_round_fn`` exactly:
        part_key is split index 0 of the 4-way (or, with a failure
        model, 5-way) round-key split, and both call sites consume the
        ONE sampler implementation, so they can never drift. Under the
        ``exact`` sampler the streamer runs this on the CPU backend and
        jax PRNG draws are backend-deterministic (the streamed cohort
        is the resident cohort bit-for-bit); under ``hashed`` the
        replay is the O(cohort) numpy mirror of the same keyed-hash
        stream — identical indices by construction, no full-N work.

        ``alive``/``n_participants`` serve ``population='dynamic'``
        (robustness/population.py): the draw runs over the CURRENT
        registered index space (``n_clients`` grows) with departed
        indices masked out of the hashed stream, and the cohort size is
        PINNED at the startup population's (so the round program's
        shapes never change) instead of re-derived from the growing N.
        """
        cfg = self.config
        if n_participants is None:
            n_participants = cfg.cohort_size(n_clients)
        if n_participants == n_clients:
            return None
        with_faults = FailureModel.from_config(cfg) is not None
        sampler = getattr(cfg, "participation_sampler", "exact").lower()
        if sampler == "hashed":
            # O(cohort) replay end to end: the round_key_splits +
            # key_data chain runs as ONE jitted call
            # (_hashed_part_key_words — eager per-op dispatch costs
            # more than the whole hashed draw); the draw itself stays
            # in draw_cohort_host, the one host entry. Bit-identical
            # indices to the in-program draw_cohort by construction.
            return draw_cohort_host(
                None, n_clients, n_participants, sampler,
                key_words=_hashed_part_key_words(round_key, with_faults),
                alive=alive,
            )
        part_key = round_key_splits(round_key, with_faults)[0]
        return draw_cohort_host(part_key, n_clients, n_participants,
                                sampler, alive=alive)

    def make_round_fn(self, apply_fn, optimizer, n_clients: int,
                      preprocess=None, client_sizes=None):
        from distributed_learning_simulator_tpu.ops.augment import get_augment

        # Count-dependent feasibility (exact Shapley's 2^N bound, GTG's
        # permutation cap) fires here against the TRUE client count —
        # before any training — rather than in the constructor, which only
        # sees config.worker_number (a caller-supplied ClientData may
        # legitimately differ; ADVICE r4).
        self.check_cohort(n_clients)
        cfg = self.config
        # Streamed residency (config.client_residency): the builder emits
        # the streamed calling convention — cohort slices as operands,
        # no in-program gather/scatter — sharing cohort_round with the
        # resident entry so the two programs cannot drift.
        streamed = (
            getattr(cfg, "client_residency", "resident").lower()
            == "streamed"
        )
        compute_dtype = None
        if getattr(cfg, "local_compute_dtype", "float32") == "bfloat16":
            compute_dtype = jnp.bfloat16
        # Per-client statistics (telemetry/client_stats.py): every cs-gated
        # branch below is a TRACE-TIME conditional — client_stats='off'
        # (the default) compiles the exact pre-feature program, and 'on'
        # consumes no extra RNG, so the two modes train bit-identically.
        cs = ClientStats.from_config(cfg)
        # Always-on client valuation (telemetry/valuation.py): like cs, a
        # TRACE-TIME gate — client_valuation='off' (the default) compiles
        # the exact pre-feature program (no extra output, no extra RNG);
        # 'on' (validated to require client_stats='on') adds one tiny
        # per-cohort score vector derived from the stats matrix the round
        # already computes.
        cv = ClientValuation.from_config(cfg)
        local_train = make_local_train_fn(
            apply_fn,
            optimizer,
            local_epochs=cfg.epoch,
            batch_size=cfg.batch_size,
            param_transform=self.client_param_transform(),
            reset_optimizer=cfg.reset_client_optimizer,
            preprocess=preprocess,
            augment=get_augment(cfg.augment),
            compute_dtype=compute_dtype,
            collect_stats=cs is not None,
        )
        vtrain = jax.vmap(local_train, in_axes=(None, 0, 0, 0, 0, 0, None))
        # keep_client_params (class OR instance level) = the documented
        # contract: post_round receives the payload-processed stack as
        # aux['client_params']. client_eval's raw-stack request rides the
        # private _client_eval_enabled channel instead.
        keep_processed = self.keep_client_params
        aggregation = cfg.aggregation.lower()
        # Robust rules need every client's params at once (a median has no
        # chunkwise partial sum), so they share the materializing path.
        # The property is the single source — the simulator's feasibility
        # budget checks the same predicate the round program allocates by.
        materialize = self.materializes_client_stack
        chunk = cfg.client_chunk_size
        frac = cfg.participation_fraction
        n_participants = cfg.cohort_size(n_clients)
        # Failure model + quorum policy (robustness/faults.py): every
        # fm-gated branch below is a TRACE-TIME conditional, so failure-free
        # runs compile the exact pre-feature program (same RNG stream, same
        # HLO). min_survivors without a failure model still activates the
        # quorum guard (survivors are then just the sampled cohort).
        fm = FailureModel.from_config(cfg)
        min_survivors = getattr(cfg, "min_survivors", 0)
        quorum = fm is not None or min_survivors > 0
        # Asynchronous federation (robustness/arrivals.py): like fm/cs,
        # every af-gated branch below is a TRACE-TIME conditional —
        # async_mode='off' (the default) compiles the exact pre-feature
        # program, and the arrival stream is fold_in-decoupled from the
        # round key's splits, so async draws re-roll nothing else. The
        # persistent population speeds are a build-time constant table.
        af = AsyncFederation.from_config(cfg)
        arrival_speeds = (
            af.speed_table(n_clients) if af is not None else None
        )

        # --- size-aware work scheduling (config.bucket_client_work) --------
        # The packed-shard discipline makes every client scan
        # shard_size/batch steps — the GLOBAL maximum — even when its real
        # shard is tiny (Dirichlet skew: the BASELINE configs[4] flagship
        # has a 5x spread). Host-side, the per-client sample counts are
        # static data, so the schedule can be static too: sort clients by
        # needed step count, form chunks in that order, and group chunks by
        # the steps their largest member needs; each group slices the slot
        # axis to its own length and runs its own (statically-shaped)
        # chunked scan. Real-sample coverage per epoch is unchanged — a
        # client's samples occupy its first slots, always inside the
        # group's slice — and empty clients are skipped outright (their
        # aggregation weight is 0 and their metrics are 0 either way).
        #
        # Optimizer-step-count caveat (ADVICE r4): a small client's skipped
        # masked-slot steps are real optimizer steps in the unscheduled
        # path — zero-grad steps still apply weight decay, and with
        # reset_client_optimizer=False they decay momentum. So with
        # weight_decay > 0 or persistent client optimizers, scheduling ON
        # vs OFF differs beyond batch-composition reshuffle noise: each
        # client now takes exactly the steps its own data needs. That is
        # the REFERENCE's semantics — each of its workers trains on its
        # own dataset (workers/worker.py:22 delegates to a per-worker
        # Trainer over that worker's loader), so a small client takes
        # fewer steps per epoch there too; the padded-slot steps are
        # this simulator's packing artifact, not behavior to preserve.
        # Runs that need bit-comparability with the unscheduled path under
        # those settings should set bucket_client_work=False.
        bucket_sizes = None
        if (
            client_sizes is not None
            and getattr(cfg, "bucket_client_work", True)
            and not materialize
            and frac >= 1.0
            and chunk is not None
            and chunk > 0
        ):
            bucket_sizes = np.asarray(client_sizes, dtype=np.int64)

        def _bucket_plan(total_steps: int):
            """Static schedule: {steps -> client indices} with every nonzero
            group a union of whole sorted-order chunks (at most the final
            chunk is partial). Empty clients go straight to the s=0 group —
            never into a training chunk. Built at trace time (shapes are
            static under jit)."""
            steps_c = np.minimum(
                -(-bucket_sizes // cfg.batch_size), total_steps
            )
            groups: dict[int, list[np.ndarray]] = {}
            empty = np.flatnonzero(steps_c == 0)
            if empty.size:
                groups[0] = [empty]
            nonzero = np.flatnonzero(steps_c > 0)
            order = nonzero[np.argsort(-steps_c[nonzero], kind="stable")]
            for start in range(0, order.size, chunk):
                sl = order[start : start + chunk]
                groups.setdefault(int(steps_c[sl[0]]), []).append(sl)
            return {s: np.concatenate(g) for s, g in groups.items()}

        def train_clients(global_params, state, x, y, m, keys, lr_scale):
            """Materializing path: returns every client's params stacked
            (needed by Shapley, which re-averages arbitrary subsets)."""
            if chunk is None or chunk >= keys.shape[0]:
                return vtrain(global_params, state, x, y, m, keys, lr_scale)

            # Sequential-over-chunks, vmap-within-chunk (lax.map's batch_size
            # does exactly this): bounds HBM use (per-client param/grad/
            # momentum copies + activations) at chunk size while keeping the
            # whole round one XLA program.
            def one_client(args):
                s, xi, yi, mi, k = args
                return local_train(global_params, s, xi, yi, mi, k, lr_scale)

            return jax.lax.map(
                one_client, (state, x, y, m, keys), batch_size=chunk
            )

        def make_compute(global_params, lr_scale):
            """Per-chunk train+reduce body shared by the plain and bucketed
            fused paths (chunked_accumulate's compute contract). With a
            failure model the chunk trees carry a per-client failed flag:
            corrupt modes damage the RAW upload before the payload
            transform (the same point the materializing path corrupts at),
            dropout freezes the chunk's persistent state."""

            def compute(chunk_trees, pk):
                # Tree layout: (state, x, y, m, keys, w[, late_w][, failed])
                # — the optional members appear in that order exactly when
                # their trace-time feature (af / fm) is active.
                state_c, x_c, y_c, m_c, keys_c, w_c = chunk_trees[:6]
                rest = list(chunk_trees[6:])
                lw_c = rest.pop(0) if af is not None else None
                f_c = rest.pop(0) if fm is not None else None
                cp, ns, tm = vtrain(global_params, state_c, x_c, y_c, m_c,
                                    keys_c, lr_scale)
                if f_c is not None and fm.corrupts_upload:
                    cp = fm.corrupt_stack(cp, f_c)
                if f_c is not None and fm.freezes_client_state:
                    ns = fm.freeze_failed_state(f_c, state_c, ns)
                if cs is not None:
                    # Streaming per-chunk upload stats (O(1) scalars + the
                    # delta probe per client — never the stack), AFTER
                    # corruption: they describe what the server received.
                    tm = cs.add_upload_stats(tm, global_params, cp)
                return reduce_chunk(cp, w_c, pk, lw_c), (ns, tm)

            return compute

        def reduce_chunk(cp, w, pk, lw=None):
            cp, _ = self.process_client_payload(cp, pk)

            # Weighted partial sum accumulated in f32 even when client
            # params are bf16 (local_compute_dtype): a sum over up to
            # 1000 small weighted terms must not round at 8 bits of
            # mantissa. The MXU takes bf16 inputs with an f32
            # accumulator natively.
            def wsum(weights):
                return jax.tree_util.tree_map(
                    lambda p: jnp.tensordot(
                        weights.astype(jnp.float32), p, axes=(0, 0),
                        preferred_element_type=jnp.float32,
                    ),
                    cp,
                )

            if lw is None:
                return wsum(w)
            # Async federation: the late row is a SECOND weighted sum over
            # the same payload-processed chunk (raw discounted weights —
            # normalized at buffer-apply time), kept as a separate
            # tensordot so the fresh row's ops stay identical to the
            # synchronous program (the round_deadline=inf bit-identity
            # contract).
            return (wsum(w), wsum(lw))

        def zero_acc(global_params):
            """Zero accumulator matching reduce_chunk's output: one tree
            for the synchronous reduction, a (fresh, late) pair under
            async federation."""
            z = jax.tree_util.tree_map(jnp.zeros_like, global_params)
            if af is None:
                return z
            return (z, jax.tree_util.tree_map(jnp.zeros_like, global_params))

        def train_and_reduce(global_params, state, x, y, m, keys, norm_w,
                             late_w, failed, payload_key, lr_scale):
            """Fused path: per-chunk weighted partial sums accumulate into
            the aggregate directly, so the full [n_clients, n_params] stack
            never materializes — at 1000 clients x ResNet-18 that stack
            would be ~44 GB, far beyond HBM. ``failed`` is the failure
            model's per-client mask, ``late_w`` the async late-upload
            weights (None when the feature is inactive). Returns
            (aggregate[, late_sum], new_state, train_metrics)."""
            k = keys.shape[0]

            if chunk is None or chunk >= k:
                cp, ns, tm = train_clients(
                    global_params, state, x, y, m, keys, lr_scale
                )
                if failed is not None and fm.corrupts_upload:
                    cp = fm.corrupt_stack(cp, failed)
                if failed is not None and fm.freezes_client_state:
                    ns = fm.freeze_failed_state(failed, state, ns)
                if cs is not None:
                    tm = cs.add_upload_stats(tm, global_params, cp)
                return reduce_chunk(cp, norm_w, payload_key, late_w), ns, tm

            # chunked_accumulate handles the reshape/scan/remainder
            # discipline (remainder participants get their own vmap call so
            # the memory-safe path never silently degrades to materializing
            # the full per-client param stack) and splits payload_key into
            # per-chunk keys itself.
            trees = (state, x, y, m, keys, norm_w)
            if af is not None:
                trees = trees + (late_w,)
            if fm is not None:
                trees = trees + (failed,)
            agg, (ns, tm) = chunked_accumulate(
                trees, chunk,
                make_compute(global_params, lr_scale),
                zero_acc(global_params),
                per_chunk=payload_key,
            )
            return agg, ns, tm

        def train_and_reduce_bucketed(plan, global_params, state, x, y, m,
                                      keys, norm_w, late_w, failed,
                                      payload_key, lr_scale):
            """Fused path with the size-aware schedule: one chunked scan per
            step-count group, each slicing the slot axis to the group's own
            length. Groups accumulate into the same f32 aggregate; per-client
            metrics (and persistent state, if any) scatter back to original
            client positions."""
            n = keys.shape[0]
            agg = zero_acc(global_params)
            # Per-client metrics scatter back to original client positions;
            # the dict is keyed by whatever the compute body reports (loss/
            # accuracy always; the client_stats probe and scalars when on),
            # with skipped empty clients keeping all-zero rows — identical
            # to "training" them on fully masked slots.
            metrics_full = None
            new_state = state
            group_keys = jax.random.split(payload_key, len(plan))
            bsz = cfg.batch_size
            compute = make_compute(global_params, lr_scale)

            # Descending step count: deterministic group order, big work
            # first.
            for gk, (s, idx_np) in zip(
                group_keys, sorted(plan.items(), reverse=True)
            ):
                if s == 0:
                    # Empty clients: zero aggregation weight and zero
                    # metrics — identical to "training" them on fully
                    # masked slots, without the wasted scan.
                    continue
                idx = jnp.asarray(idx_np)
                trees_g = (
                    cohort_take(state, idx),
                    cohort_take(x, idx)[:, : s * bsz],
                    cohort_take(y, idx)[:, : s * bsz],
                    cohort_take(m, idx)[:, : s * bsz],
                    keys[idx],
                    cohort_take(norm_w, idx),
                )
                if af is not None:
                    trees_g = trees_g + (cohort_take(late_w, idx),)
                if fm is not None:
                    trees_g = trees_g + (cohort_take(failed, idx),)
                if idx_np.size <= chunk:
                    partial, (ns_g, tm_g) = compute(trees_g, gk)
                else:
                    partial, (ns_g, tm_g) = chunked_accumulate(
                        trees_g, chunk, compute,
                        zero_acc(global_params),
                        per_chunk=gk,
                    )
                agg = jax.tree_util.tree_map(jnp.add, agg, partial)
                if metrics_full is None:
                    metrics_full = jax.tree_util.tree_map(
                        lambda a: jnp.zeros((n,) + a.shape[1:], a.dtype),
                        tm_g,
                    )
                metrics_full = cohort_scatter(metrics_full, idx, tm_g)
                if state is not None:
                    new_state = cohort_scatter(new_state, idx, ns_g)
            # At least one nonzero group always ran: an all-empty cohort
            # collapses the plan to the single s=0 group, which round_fn
            # routes to the plain path (len(plan) <= 1 -> plan = None).
            assert metrics_full is not None
            return agg, new_state, metrics_full

        def split_round_key(key):
            """Module-level ``round_key_splits`` with this build's fault
            gating baked in (the one split-chain definition — see its
            docstring)."""
            return round_key_splits(key, fm is not None)

        def cohort_round(global_params, state_k, x_k, y_k, m_k, part_sizes,
                         idx, key, keys, lr_scale, async_state,
                         departed=None, draw_pos=None):
            """The round body AFTER the cohort gather — shared verbatim by
            the resident entry (which gathered in-program) and the
            streamed entry (whose operands arrived pre-gathered from the
            host store), which is what makes the two residency modes
            bit-identical by construction. ``idx`` is the cohort's true
            client ids (None = whole population); the returned
            ``new_state_k`` is cohort-sliced and NOT yet scattered.
            ``departed`` (bool[cohort]; population='dynamic' only) marks
            members that depart THIS round — zero contribution, counted
            against the quorum floor. ``draw_pos`` (int[cohort];
            multihost streamed residency only) says which DRAW position
            the client at each cohort row came from: the distributed
            shard store's owner-sharded assembly permutes the cohort
            into owner-contiguous row groups (data/residency
            .plan_owner_assembly), and permuting the per-POSITION draws
            below (training keys, fault flags) by the same map keeps
            every client's training bit-identical to the draw-order
            program — only the aggregation's summation order moves,
            which is the documented resident-vs-mesh tolerance."""
            _, train_key, payload_key, agg_key, fault_key = keys
            if fm is not None:
                failed = fm.draw_failed(fault_key, n_participants)
                if draw_pos is not None:
                    # The fault stream is positional in DRAW order; the
                    # client at row p sat at draw position draw_pos[p].
                    failed = jnp.take(failed, draw_pos, axis=0)
                survival = ~failed
            else:
                failed = None
            if departed is not None:
                # Dynamic population (robustness/population.py): a
                # member that departs mid-round contributes nothing —
                # its weight zeroes and the remaining cohort
                # renormalizes, exactly the dropout-fault discipline;
                # the quorum policy counts it against min_survivors
                # below.
                part_sizes = part_sizes * (~departed).astype(
                    part_sizes.dtype
                )
            client_keys = jax.random.split(train_key, n_participants)
            if draw_pos is not None:
                # Same permutation for the per-position training keys: the
                # client at row p trains with the key of its draw
                # position, exactly as in the draw-order program.
                client_keys = client_keys[draw_pos]
            routed_late = None
            if failed is not None and fm.excludes_update:
                if af is not None and fm.routes_to_buffer:
                    # Straggler fault + arrival model: the upload "arrives
                    # after the deadline" — routed into the staleness
                    # buffer (weight kept; forced late below) instead of
                    # silently discarded, and the client counts as a
                    # survivor (nothing was lost, only delayed). Sync-mode
                    # straggler semantics are untouched.
                    routed_late = failed
                    survival = jnp.ones_like(failed)
                else:
                    # Dropout/straggler: zero aggregation weight. The
                    # weighted mean renormalizes over the SURVIVING
                    # part_sizes (total below shrinks too), and the robust
                    # rules' weights>0 participation mask excludes failed
                    # clients from the per-coordinate statistic.
                    part_sizes = part_sizes * survival.astype(part_sizes.dtype)
            late_w = None
            if af is not None:
                # Arrival model (robustness/arrivals.py): latencies from
                # the fold_in-decoupled stream keyed by TRUE client index
                # — the splits above are untouched, so the deadline=inf
                # degenerate case replays the synchronous run bit-exactly.
                ids = idx if idx is not None else jnp.arange(n_participants)
                latency = af.draw_latency(
                    key, ids, jnp.take(arrival_speeds, ids, axis=0)
                )
                on_time, staleness, discount, eff_latency = af.classify(
                    latency, routed_late
                )
                # Effective latencies: fault-routed stragglers are
                # delayed one deadline, so the simulated clock and the
                # staleness telemetry describe the same arrivals.
                sim_duration, sim_duration_sync = af.durations(eff_latency)
                late_mask = (~on_time) & (part_sizes > 0)
                late_w = (
                    part_sizes.astype(jnp.float32)
                    * discount
                    * late_mask.astype(jnp.float32)
                )
                b_tot = jnp.sum(late_w)
                n_late = jnp.sum(late_mask.astype(jnp.int32))
                mean_staleness = jnp.sum(
                    staleness * late_mask.astype(jnp.float32)
                ) / jnp.maximum(n_late.astype(jnp.float32), 1.0)
                # Fresh cohort = on-time clients only; late weights keep
                # the pre-deadline sizes, so a client contributes through
                # exactly one row.
                part_sizes = part_sizes * on_time.astype(part_sizes.dtype)
            total_size = jnp.sum(part_sizes)
            norm_w = part_sizes / jnp.maximum(total_size, 1e-12)
            if af is not None:
                on_time_count = jnp.sum((part_sizes > 0).astype(jnp.int32))

            aux = {}
            if materialize:
                client_params, new_state_k, train_metrics = train_clients(
                    global_params, state_k, x_k, y_k, m_k, client_keys,
                    lr_scale,
                )
                if compute_dtype is not None:
                    # Robust rules / Shapley consume the full stack; restore
                    # f32 so their statistics don't run at 8-bit mantissa
                    # (materializing cohorts are small by construction).
                    client_params = jax.tree_util.tree_map(
                        lambda p: p.astype(jnp.float32), client_params
                    )
                if self._client_eval_enabled:
                    # Per-client telemetry evaluates the raw LOCAL params —
                    # the reference's observable (each worker thread
                    # evaluates its own trained model BEFORE the quantized
                    # upload, fed_quant_worker.py:55-58) — not the payload-
                    # transformed upload. The eval program itself applies
                    # client_param_transform (post_round), matching the
                    # reference's QAT-instrumented eval forward exactly.
                    # For plain fed both are identities. Stored BEFORE
                    # upload corruption: the local model trained fine; the
                    # fault hits what the server receives.
                    aux["client_params_raw"] = client_params
                if failed is not None and fm.corrupts_upload:
                    client_params = fm.corrupt_stack(client_params, failed)
                if failed is not None and fm.freezes_client_state:
                    new_state_k = fm.freeze_failed_state(
                        failed, state_k, new_state_k
                    )
                if cs is not None:
                    # Same functions as the fused/bucketed chunks, applied
                    # to the already-resident stack at the same point
                    # (post-corruption, pre-payload) — the paths stay a
                    # differential pair for the stats too.
                    train_metrics = cs.add_upload_stats(
                        train_metrics, global_params, client_params
                    )
                client_params, payload_aux = self.process_client_payload(
                    client_params, payload_key
                )
                late_sum = None
                if af is not None:
                    # Same post-payload point as the fused path's late row
                    # (a late fed_quant client quantizes its own upload
                    # before it reaches the buffer).
                    late_sum = jax.tree_util.tree_map(
                        lambda p: jnp.tensordot(
                            late_w, p, axes=(0, 0),
                            preferred_element_type=jnp.float32,
                        ),
                        client_params,
                    )
                new_global = aggregate(
                    client_params, part_sizes, aggregation, cfg.trim_ratio
                )
                if aggregation != "mean" and not quorum:
                    # Robust rules promise a usable model even under
                    # poisoning; if EVERY client diverged in the same round
                    # (all candidates masked), keep the previous global
                    # instead of a NaN aggregate. The plain mean keeps
                    # propagate-NaN semantics (reference parity). With the
                    # quorum guard active this fallback is subsumed by the
                    # rejection logic below — which also RECORDS the event.
                    finite = all_finite(new_global)
                    new_global = jax.tree_util.tree_map(
                        lambda agg, prev: jnp.where(
                            finite, agg, prev.astype(agg.dtype)
                        ),
                        new_global, global_params,
                    )
                if keep_processed:
                    # Shapley's subset re-averaging consumes the processed
                    # stack. client_eval does NOT also store it — one
                    # resident stack, matching what
                    # _assert_client_stack_feasible budgets for.
                    aux["client_params"] = client_params
            else:
                plan = None
                if bucket_sizes is not None:
                    plan = _bucket_plan(x_k.shape[1] // cfg.batch_size)
                    if len(plan) <= 1:
                        # Uniform work: scheduling is a no-op; keep the
                        # plain path (bit-identical to scheduling-off).
                        plan = None
                if plan is not None:
                    agg_out, new_state_k, train_metrics = (
                        train_and_reduce_bucketed(
                            plan, global_params, state_k, x_k, y_k, m_k,
                            client_keys, norm_w, late_w, failed,
                            payload_key, lr_scale,
                        )
                    )
                else:
                    agg_out, new_state_k, train_metrics = train_and_reduce(
                        global_params, state_k, x_k, y_k, m_k, client_keys,
                        norm_w, late_w, failed, payload_key, lr_scale,
                    )
                if af is not None:
                    new_global, late_sum = agg_out
                else:
                    new_global = agg_out
                payload_aux = {}
            keep_round = total_size > 0
            if af is not None:
                # Staleness buffer (robustness/arrivals.py): insert this
                # round's late batch, fire the K-of-N trigger, mix the
                # buffered mean delta into the aggregate at its weight
                # share. A non-triggering round returns the fresh
                # aggregate through a bit-exact select.
                (new_global, buffer_applied, astate_ins,
                 astate_next) = af.absorb_and_apply(
                    async_state, global_params, new_global, total_size,
                    late_sum, b_tot, n_late, sim_duration,
                )
                # A buffer-only round (whole cohort late) is a real
                # update, not an empty round.
                keep_round = keep_round | buffer_applied
            # Empty effective cohort (all sampled clients have zero samples,
            # possible under extreme Dirichlet skew — or the whole cohort
            # dropped out / missed the deadline): keep the previous global
            # model, parity with fed_server.py:45-47.
            new_global = jax.tree_util.tree_map(
                lambda agg, prev: jnp.where(
                    keep_round, agg, prev.astype(agg.dtype)
                ),
                new_global, global_params,
            )
            if cs is not None:
                # [N, S] per-client stats (telemetry/client_stats.py):
                # the aggregate-delta probe uses the RAW round aggregate —
                # before the server optimizer, the downlink transform, and
                # any quorum rejection select — i.e. the same quantity the
                # clients' uploads averaged into.
                aux["client_stats"] = cs.stats_matrix(
                    train_metrics,
                    cs.probe_delta(global_params, new_global),
                )
                if cv is not None:
                    # Streaming valuation scores (telemetry/valuation.py):
                    # cosine-vs-aggregate x update-norm per cohort client,
                    # normalized to unit L1 — the in-program half of the
                    # estimator; the host folds in the server loss-delta
                    # and the exponential decay. Derived from the stats
                    # matrix above, so it shares the probe, the
                    # post-corruption measurement point, and the
                    # fused/bucketed/materializing-path parity for free.
                    aux["valuation_scores"] = cv.scores(aux["client_stats"])
            if quorum:
                # Quorum policy: a round is REJECTED — previous global
                # retained, the event recorded — when honest survivors fall
                # below min_survivors OR the aggregate is non-finite (the
                # plain mean otherwise NaN-propagates a corrupt upload into
                # the global model forever). Checked after the empty-cohort
                # fallback (an empty round is a survivor-floor event, not a
                # NaN event) and INSTEAD of the robust-rule finite guard,
                # which it subsumes; in-program jnp.where keeps the whole
                # round one XLA program (no host sync to decide).
                if failed is not None and departed is not None:
                    survived = survival & (~departed)
                elif failed is not None:
                    survived = survival
                elif departed is not None:
                    # Dynamic population, no failure model: departures
                    # alone can push a round below the quorum floor —
                    # the graceful-degradation contract.
                    survived = ~departed
                else:
                    survived = None
                survivor_count = (
                    jnp.sum(survived.astype(jnp.int32))
                    if survived is not None
                    else jnp.asarray(n_participants, jnp.int32)
                )
                finite = all_finite(new_global)
                rejected = (~finite) | (survivor_count < min_survivors)
                aux["survivor_count"] = survivor_count
                aux["round_rejected"] = rejected
            new_global, agg_aux = self.process_aggregated(new_global, agg_key)
            if quorum:
                # The rejection select runs AFTER process_aggregated so a
                # rejected round retains the previous global EXACTLY: the
                # round's input params already went through the downlink
                # transform last round (fed_quant re-quantizing the
                # "retained" model with fresh noise would move it).
                new_global = jax.tree_util.tree_map(
                    lambda agg, prev: jnp.where(
                        rejected, prev.astype(agg.dtype), agg
                    ),
                    new_global, global_params,
                )
            if af is not None:
                if quorum:
                    # A rejected round keeps its buffer INSERTS (the late
                    # uploads really arrived) but reverts any trigger/reset
                    # — the refused aggregate never consumed them; the
                    # trigger re-fires next round.
                    new_async_state = jax.tree_util.tree_map(
                        lambda ins, nxt: jnp.where(rejected, ins, nxt),
                        astate_ins, astate_next,
                    )
                    applied_eff = buffer_applied & ~rejected
                else:
                    new_async_state = astate_next
                    applied_eff = buffer_applied
                # The buffer carry rides aux: the host loop (and the
                # batched scan) pops it and feeds it back as the next
                # round's async_state operand.
                aux["async_state"] = new_async_state
                aux.update({
                    "on_time_count": on_time_count,
                    "late_count": n_late,
                    "buffer_count": new_async_state["buf_count"],
                    "buffer_applied": applied_eff,
                    "mean_staleness": mean_staleness,
                    "sim_duration": sim_duration,
                    "sim_duration_sync": sim_duration_sync,
                    "sim_clock": new_async_state["clock"],
                })
            aux.update({
                "client_loss": train_metrics["loss"],
                "client_accuracy": train_metrics["accuracy"],
                "mean_client_loss": jnp.mean(train_metrics["loss"]),
                **payload_aux,
                **agg_aux,
            })
            return new_global, new_state_k, aux

        def round_fn(global_params, client_state, cx, cy, cmask, sizes, key,
                     lr_scale=1.0, async_state=None):
            if af is not None and async_state is None:
                # Trace-time wiring check: the simulator owns the buffer
                # carry; a direct caller forgetting it would otherwise
                # train with a silently-fresh buffer every round.
                raise ValueError(
                    "async_mode='on' round program needs the async_state "
                    "operand (AsyncFederation.init_state)"
                )
            keys = split_round_key(key)
            idx = None
            if n_participants == n_clients:
                state_k, x_k, y_k, m_k = client_state, cx, cy, cmask
                part_sizes = sizes
            else:
                # Client sampling: train only the sampled cohort (fixed size
                # -> one compilation); non-participants keep their state and
                # contribute nothing to aggregation. The draw is the ONE
                # sampler implementation (ops/sampling.py) shared with the
                # host replay in cohort_indices — exact = the pre-feature
                # choice(replace=False), hashed = the O(cohort) keyed draw.
                idx = draw_cohort(
                    keys[0], n_clients, n_participants,
                    getattr(cfg, "participation_sampler", "exact").lower(),
                )
                state_k = cohort_take(client_state, idx)
                x_k, y_k, m_k = (
                    cohort_take(cx, idx),
                    cohort_take(cy, idx),
                    cohort_take(cmask, idx),
                )
                part_sizes = cohort_take(sizes, idx)
            new_global, new_state_k, aux = cohort_round(
                global_params, state_k, x_k, y_k, m_k, part_sizes, idx,
                key, keys, lr_scale, async_state,
            )
            if idx is not None:
                # Sampled cohort indices: third-party post_round attribution
                # and the host loop's cohort_hash resume-determinism
                # telemetry.
                aux["participants"] = idx
                new_state = cohort_scatter(client_state, idx, new_state_k)
            else:
                new_state = new_state_k
            return new_global, new_state, aux

        if not streamed:
            return round_fn

        # Dynamic population (config.population; robustness/population.py):
        # a trace-time gate like fm/cs/af — 'static' (the default)
        # compiles the exact pre-feature streamed program; 'dynamic'
        # adds the per-cohort ``departed`` operand (validated streamed-
        # only, so the resident entry never grows it).
        dyn = (
            getattr(cfg, "population", "static") or "static"
        ).lower() == "dynamic"

        def round_fn_streamed(global_params, state_k, x_k, y_k, m_k,
                              part_sizes, idx, key, lr_scale=1.0,
                              async_state=None, departed=None,
                              draw_pos=None):
            """Streamed calling convention (base.Algorithm docstring): the
            cohort slice arrives pre-gathered from the host shard store,
            ``idx`` is its true client ids (None = whole population), and
            the post-round cohort state is RETURNED, not scattered — the
            streamer writes it back into the host store. The round key is
            split exactly as in the resident program (part_key is
            consumed by the host's cohort replay instead of an in-program
            choice), so every downstream draw is unchanged. ``departed``
            (population='dynamic') is the host registration stream's
            this-round departure mask over the cohort."""
            if af is not None and async_state is None:
                raise ValueError(
                    "async_mode='on' round program needs the async_state "
                    "operand (AsyncFederation.init_state)"
                )
            if dyn and departed is None:
                # Trace-time wiring check, like the async one above: the
                # simulator owns the registration stream; a direct
                # caller forgetting the mask would silently train
                # departed clients at full weight.
                raise ValueError(
                    "population='dynamic' round program needs the "
                    "departed operand "
                    "(PopulationModel.cohort_departed_mask)"
                )
            keys = split_round_key(key)
            new_global, new_state_k, aux = cohort_round(
                global_params, state_k, x_k, y_k, m_k, part_sizes, idx,
                key, keys, lr_scale, async_state,
                departed=departed if dyn else None,
                draw_pos=draw_pos,
            )
            if idx is not None:
                aux["participants"] = idx
            return new_global, new_state_k, aux

        return round_fn_streamed

    def make_valuation_audit_fn(self, apply_fn, optimizer, preprocess=None):
        """Build the valuation auditor's cohort-stack replay program.

        ``audit_stack(global_params, x_k, y_k, m_k, client_keys,
        payload_key, lr_scale) -> [cohort, ...] payload-processed
        params`` — the EXACT per-client uploads the round aggregated,
        re-materialized for the truncated GTG audit walk
        (telemetry/valuation.py). The replay trains the cohort from the
        round's pre-round global params with the same per-client keys
        (``round_key_splits``' train_key fan-out — the caller derives
        them host-side) and the same local-train build knobs; the only
        difference from the live round is ``collect_stats=False``, which
        changes metric outputs, never the trained params (the PR 4
        off-gate contract). Audit preconditions (plain ``fed`` only, no
        faults, no async, no persistent client state —
        config.validate()) keep the replay this simple AND exact:
        ``process_client_payload`` is fed's identity here (fed_quant is
        refused — its live fused path quantizes with per-chunk payload
        keys that a whole-stack replay cannot reproduce), so the
        replayed stack is bit-for-bit the uploads the round aggregated
        on single-device runs. One documented softening under
        single-host ``mesh_devices > 1`` (composes since PR 14): the
        LIVE round trains the cohort client-axis-sharded while this
        replay runs at full width on one placement, and per-device
        batch tiling can move trained params by last-ulp amounts — far
        below the audit walk's Monte-Carlo noise (the graded-
        differential Spearman floor is pinned under mesh,
        tests/test_gtg_mesh.py), but "bit-for-bit" is a serial-run
        statement.
        """
        from distributed_learning_simulator_tpu.ops.augment import get_augment

        cfg = self.config
        compute_dtype = None
        if getattr(cfg, "local_compute_dtype", "float32") == "bfloat16":
            compute_dtype = jnp.bfloat16
        local_train = make_local_train_fn(
            apply_fn,
            optimizer,
            local_epochs=cfg.epoch,
            batch_size=cfg.batch_size,
            param_transform=self.client_param_transform(),
            reset_optimizer=cfg.reset_client_optimizer,
            preprocess=preprocess,
            augment=get_augment(cfg.augment),
            compute_dtype=compute_dtype,
            collect_stats=False,
        )
        vtrain = jax.vmap(local_train, in_axes=(None, 0, 0, 0, 0, 0, None))
        chunk = cfg.client_chunk_size

        def audit_stack(global_params, x_k, y_k, m_k, client_keys,
                        payload_key, lr_scale=1.0):
            if chunk is None or chunk >= client_keys.shape[0]:
                cp, _, _ = vtrain(
                    global_params, None, x_k, y_k, m_k, client_keys,
                    lr_scale,
                )
            else:
                # Same memory envelope as the round itself: chunk clients
                # in flight (lax.map's batch_size), never the whole
                # cohort's training transients at once.
                def one_client(args):
                    xi, yi, mi, k = args
                    cp_i, _, _ = local_train(
                        global_params, None, xi, yi, mi, k, lr_scale
                    )
                    return cp_i

                cp = jax.lax.map(
                    one_client, (x_k, y_k, m_k, client_keys),
                    batch_size=chunk,
                )
            if compute_dtype is not None:
                # The subset evaluator consumes the stack like the
                # materializing round path does: f32.
                cp = jax.tree_util.tree_map(
                    lambda p: p.astype(jnp.float32), cp
                )
            cp, _ = self.process_client_payload(cp, payload_key)
            return cp

        return audit_stack

    def client_param_transform(self):
        """Param transform inside the client loss (QAT hook; None here)."""
        return None

    # ---- server optimizer (FedOpt family; exceeds the reference) ----------
    def make_server_update(self):
        """Optional server-side optimizer step applied to the round aggregate.

        Returns ``(init_fn, update_fn)`` or ``None`` (plain FedAvg — the
        reference's fixed behavior, fed_server.py:81-84, where the aggregate
        becomes the next global model directly). With a server optimizer the
        pseudo-gradient ``prev_global - aggregate`` is fed to optax:
        sgd+momentum = FedAvgM, adam = FedAdam (Reddi et al., "Adaptive
        Federated Optimization"). sgd(lr=1, momentum=0) reduces exactly to
        FedAvg: ``prev - 1.0 * (prev - agg) = agg``.
        """
        cfg = self.config
        name = cfg.server_optimizer_name.lower()
        if name in ("none", ""):
            return None
        if name == "sgd":
            tx = optax.sgd(
                cfg.server_learning_rate, momentum=cfg.server_momentum or None
            )
        elif name == "adam":
            tx = optax.adam(cfg.server_learning_rate)
        else:  # pre-validated in ExperimentConfig.validate
            raise ValueError(
                f"unknown server optimizer {name!r}; known: none, sgd, adam"
            )

        def update(prev_global, aggregate, opt_state, rejected=None):
            pseudo_grad = jax.tree_util.tree_map(
                lambda p, a: (p - a.astype(p.dtype)), prev_global, aggregate
            )
            updates, new_opt_state = tx.update(
                pseudo_grad, opt_state, prev_global
            )
            stepped = optax.apply_updates(prev_global, updates)
            if rejected is None:
                return stepped, new_opt_state
            # Quorum rejection (the simulator passes the round's rejected
            # flag whenever the round program produced one): a rejected
            # round's pseudo-gradient is 0, but a momentum trace / Adam
            # moments from PRIOR rounds would still move the params and
            # advance the optimizer state — "previous global retained"
            # must mean exactly that, so both are frozen.
            params = jax.tree_util.tree_map(
                lambda s, p: jnp.where(rejected, p, s), stepped, prev_global
            )
            frozen_opt = jax.tree_util.tree_map(
                lambda n, o: jnp.where(rejected, o, n),
                new_opt_state, opt_state,
            )
            return params, frozen_opt

        return tx.init, update
