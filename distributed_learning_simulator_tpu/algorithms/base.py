"""Algorithm strategy interface.

What survives of the reference's server/worker class split
(reference servers/*.py + workers/*.py + factory.py:14-35): an algorithm is a
strategy object that

  * builds a jitted **round function** — the whole synchronous round
    (broadcast -> N local trainings -> gather -> aggregate) as ONE XLA
    program over client-stacked arrays; and
  * optionally runs a host-side **post_round** hook — for work that is
    genuinely data-dependent control flow (Shapley convergence loops,
    reference GTG_shapley_value_server.py:36) or pure logging/persistence.

The reference's template-method hooks ``_process_client_parameter`` /
``_process_aggregated_parameter`` (servers/fed_server.py:38-42) survive as
the jax-level hooks ``process_client_payload`` / ``process_aggregated`` on
:class:`~distributed_learning_simulator_tpu.algorithms.fedavg.FedAvg`.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

import jax


@dataclass
class RoundContext:
    """Everything a host-side post_round hook may need for one round."""

    round_idx: int  # 0-based
    global_params: Any  # aggregated params after this round
    prev_global_params: Any  # global params before this round
    sizes: Any  # [n_clients] aggregation weights
    aux: dict  # round_fn diagnostics (may hold 'client_params')
    metrics: dict  # server-side eval of global_params {'loss','accuracy'}
    prev_metrics: dict | None  # eval of prev_global_params (previous round)
    eval_batches: tuple  # (xb, yb, mb) padded test set on device
    log_dir: str | None
    extra: dict = field(default_factory=dict)


class Algorithm:
    """Base strategy. Subclasses set ``name`` (registry key, parity with
    reference factory.py:14-35) and implement ``make_round_fn``."""

    name: str = ""
    # Public contract: truthy ``keep_client_params`` — set at CLASS level
    # (Shapley) or on an INSTANCE (third-party subclasses) — makes the round
    # program materialize every client's parameters and expose the
    # payload-PROCESSED stack as ``aux['client_params']`` for post_round.
    # (FedAvg's client_eval telemetry does NOT use this flag: it requests
    # the RAW pre-payload stack through a private channel, so enabling it
    # never changes what ``aux['client_params']`` holds.)
    keep_client_params: bool = False
    # Whether the host round loop may defer this algorithm's metric fetch +
    # post_round by one round (hides device->host latency behind the next
    # round's compute). Safe when post_round is analytic/logging-only; the
    # Shapley algorithms set False — their post_round drives data-dependent
    # subset evaluation that must see the round's metrics synchronously.
    supports_round_pipelining: bool = True
    # Whether round_fn accepts the optional trailing ``lr_scale`` operand
    # (config.lr_schedule): the simulator passes it only when a schedule
    # is active AND the algorithm declares support — an algorithm without
    # the operand still works with the constant default.
    supports_lr_schedule: bool = False
    # Whether the host loop may fuse K rounds into one dispatched program
    # (config.rounds_per_dispatch > 1; parallel/engine.py
    # make_batched_round_fn). The batched dispatch scan-stacks every aux
    # leaf ``[K, ...]`` and hands post_round dispatch-granular params
    # (RoundContext.global_params is the dispatch-FINAL model,
    # prev_global_params the dispatch-initial one), so algorithms whose
    # aux carries per-round parameter stacks or whose post_round consumes
    # per-round parameter state must say False. Conservative default
    # False — a third-party post_round reading ctx.global_params would
    # silently get wrong values; FedAvg/SignSGD opt in.
    supports_round_batching: bool = False
    # Whether the algorithm's round program can run under
    # ``config.client_residency='streamed'`` (data/residency.py +
    # parallel/streaming.py): per-client arrays live in a host shard
    # store and the round fn takes the STREAMED calling convention —
    # ``round_fn(global_params, state_k, x_k, y_k, m_k, part_sizes, idx,
    # key[, lr_scale][, async_state])`` where the cohort slices are
    # already-gathered operands and ``idx`` is the cohort's true client
    # ids (None when the cohort is the whole population). Conservative
    # default False — the simulator refuses with the cause; FedAvg
    # builds the streamed program natively, sign_SGD adapts its
    # full-population round via ``adapt_full_cohort_streamed``, the
    # Shapley servers refuse (their subset re-evaluation assumes a
    # resident stack).
    supports_streamed_residency: bool = False
    # Whether the round program implements asynchronous federation
    # (config.async_mode='on'; robustness/arrivals.py): deadline rounds,
    # the staleness buffer carried as round state, and the extra
    # ``async_state`` round_fn operand. Conservative default False — the
    # simulator refuses async_mode='on' with the cause instead of
    # silently running the algorithm synchronously; the FedAvg family
    # opts in (sign_SGD's shared-vote round has no parameter-space
    # buffer to hold late updates; the Shapley servers refuse in their
    # constructors — subset utilities assume a synchronous cohort).
    supports_async: bool = False
    # Whether the algorithm's post_round subset evaluation partitions its
    # vmapped model-batch axis over a single-host mesh (mesh_devices > 1;
    # algorithms/shapley.eval_mesh_devices + _SubsetEvaluator). A
    # CAPABILITY flag, not a gate: False just means post_round ignores
    # the mesh (the round program's client-axis sharding is independent
    # of it). The Shapley servers set True — their subset utilities are
    # independent, so sharding the evaluation batch is pure throughput,
    # bit-identical to the serial walk by construction.
    shards_subset_eval: bool = False

    def __init__(self, config):
        self.config = config

    def check_cohort(self, n_clients: int) -> None:
        """Validate the ACTUAL client count before any training runs.

        Called with the true ``n_clients`` (which a caller-supplied
        ``ClientData`` may make different from ``config.worker_number``)
        from every execution path's build step: the simulator calls it
        right before building the round fn on the vmap path (so every
        algorithm is covered regardless of its ``make_round_fn``
        inheritance; ``FedAvg.make_round_fn`` also calls it for direct
        library users), and the threaded runner before its pool spawns. The
        constructor can only see ``worker_number``, so count-dependent
        feasibility checks (exact Shapley's 2^N bound, GTG's permutation
        cap) live here and merely warn at construction."""

    @property
    def materializes_client_stack(self) -> bool:
        """Whether the round program holds the full [n_clients, params]
        stack resident (drives the simulator's up-front feasibility check;
        FedAvg widens this with its client_eval / robust-aggregation
        materializers)."""
        return bool(self.keep_client_params)

    # ---- jit side ----------------------------------------------------------
    def make_round_fn(
        self, apply_fn: Callable, optimizer, n_clients: int,
        preprocess: Callable | None = None,
        client_sizes=None,
    ) -> Callable:
        """Return ``round_fn(global_params, client_state, cx, cy, cmask,
        sizes, key[, lr_scale]) -> (new_global, new_client_state, aux)``.

        ``client_sizes`` (optional host numpy ``[n_clients]`` of real
        per-client sample counts) enables STATIC size-aware work
        scheduling where the algorithm supports it (FedAvg fused path,
        config.bucket_client_work); pass None when the client axis is
        sharded over a mesh (the static regrouping would fight the
        sharding layout) or when counts aren't known up front.
        ``client_sizes`` is captured at BUILD time into the static slice
        plan, while aggregation weights use the per-round ``sizes``
        operand — the two must describe the same clients. Mutating the
        client data (e.g. ``ClientData.override_client``) after building
        the round fn leaves a stale plan that silently truncates any
        client grown past its group's step budget: inject data BEFORE
        construction, as ``run_simulation`` and ``simulator_heterogeneous``
        do (ADVICE r4).

        ``client_state`` is whatever per-client state persists across rounds
        (optimizer/momentum buffers) as a client-stacked pytree; ``aux`` is a
        dict of diagnostics (device arrays). ``lr_scale`` (a traced f32
        scalar, default 1.0) is passed only when ``supports_lr_schedule``
        is True and a non-constant ``config.lr_schedule`` is active.
        """
        raise NotImplementedError

    def init_client_state(self, optimizer, global_params, n_clients):
        """Initial per-client persistent state (client-stacked pytree).

        None when client optimizers reset every round (the default): no
        state persists, and carrying a per-client optimizer-state pytree at
        1000-client scale would cost a model-size buffer per client.
        """
        if getattr(self.config, "reset_client_optimizer", True):
            return None
        return jax.vmap(lambda _: optimizer.init(global_params))(
            jax.numpy.arange(n_clients)
        )

    def make_server_update(self):
        """Optional server-side optimizer: ``(init_fn, update_fn)`` or None.

        See FedAvg.make_server_update (FedOpt family). None (the default)
        means the round aggregate becomes the next global model unchanged.
        """
        return None

    # ---- streamed residency (config.client_residency='streamed') -----------
    def cohort_indices(self, round_key, n_clients: int, alive=None,
                       n_participants=None):
        """Host-replay of the round program's cohort draw.

        ``alive``/``n_participants`` are the dynamic-population hooks
        (``population='dynamic'``, robustness/population.py): a draw
        over the current registered index space with departed indices
        masked, at the pinned startup cohort size. Algorithms that
        support dynamic populations honor them (FedAvg); the default
        whole-population replay ignores them.

        Under streamed residency the host must know WHICH clients round
        ``round_key`` trains BEFORE dispatch (to gather their slice from
        the shard store — and to prefetch the next dispatch's slice while
        this one computes). The contract: given the same ``round_key``
        the host loop hands the round program, return exactly the client
        ids the RESIDENT program would draw in-program, as a host numpy
        array — or None when the cohort is the whole population (no
        sampling). The caller runs this on the CPU backend; jax PRNG
        values are backend-deterministic, which is what makes the replay
        exact (the PR 2/PR 6 round-key-chain discipline).
        """
        return None

    def gather_client_state(self, store, idx):
        """Cohort slice of the host store's persistent per-client state.

        The streamed-residency mirror of the resident program's
        in-program state gather (ops/cohort.cohort_take). The default
        delegates to the store's numpy index math; algorithms with
        exotic state layouts may override.
        """
        return store.gather_state(idx)

    def scatter_client_state(self, store, idx, cohort_state) -> None:
        """Write post-round cohort state back into the host store.

        Mirror of ops/cohort.cohort_scatter; called with HOST (numpy)
        values — the streamer fetches device state before scattering.
        """
        store.scatter_state(idx, cohort_state)

    # ---- host side ---------------------------------------------------------
    def prepare(self, apply_fn, eval_fn) -> None:
        """One-time setup after the engine is built (e.g. jit subset-eval)."""

    def post_round(self, ctx: RoundContext) -> dict:
        """Host-side per-round hook; returns extra metrics to record/log."""
        return {}


def adapt_full_cohort_streamed(round_fn):
    """Wrap a resident-convention round fn into the streamed convention.

    For algorithms whose cohort is always the whole population
    (sign_SGD: the per-step vote synchronizes everyone), the streamed
    operands ARE the full arrays and the conventions differ only by the
    ``idx`` operand — always None here — sitting before the key.
    """

    def streamed_fn(global_params, state_k, x_k, y_k, m_k, part_sizes, idx,
                    key, *args, **kwargs):
        assert idx is None, "full-cohort streamed round fn got a cohort index"
        return round_fn(
            global_params, state_k, x_k, y_k, m_k, part_sizes, key,
            *args, **kwargs
        )

    return streamed_fn
