"""Algorithm strategy interface.

What survives of the reference's server/worker class split
(reference servers/*.py + workers/*.py + factory.py:14-35): an algorithm is a
strategy object that

  * builds a jitted **round function** — the whole synchronous round
    (broadcast -> N local trainings -> gather -> aggregate) as ONE XLA
    program over client-stacked arrays; and
  * optionally runs a host-side **post_round** hook — for work that is
    genuinely data-dependent control flow (Shapley convergence loops,
    reference GTG_shapley_value_server.py:36) or pure logging/persistence.

The reference's template-method hooks ``_process_client_parameter`` /
``_process_aggregated_parameter`` (servers/fed_server.py:38-42) survive as
the jax-level hooks ``process_client_payload`` / ``process_aggregated`` on
:class:`~distributed_learning_simulator_tpu.algorithms.fedavg.FedAvg`.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

import jax


@dataclass
class RoundContext:
    """Everything a host-side post_round hook may need for one round."""

    round_idx: int  # 0-based
    global_params: Any  # aggregated params after this round
    prev_global_params: Any  # global params before this round
    sizes: Any  # [n_clients] aggregation weights
    aux: dict  # round_fn diagnostics (may hold 'client_params')
    metrics: dict  # server-side eval of global_params {'loss','accuracy'}
    prev_metrics: dict | None  # eval of prev_global_params (previous round)
    eval_batches: tuple  # (xb, yb, mb) padded test set on device
    log_dir: str | None
    extra: dict = field(default_factory=dict)


class Algorithm:
    """Base strategy. Subclasses set ``name`` (registry key, parity with
    reference factory.py:14-35) and implement ``make_round_fn``."""

    name: str = ""
    # Public contract: truthy ``keep_client_params`` — set at CLASS level
    # (Shapley) or on an INSTANCE (third-party subclasses) — makes the round
    # program materialize every client's parameters and expose the
    # payload-PROCESSED stack as ``aux['client_params']`` for post_round.
    # (FedAvg's client_eval telemetry does NOT use this flag: it requests
    # the RAW pre-payload stack through a private channel, so enabling it
    # never changes what ``aux['client_params']`` holds.)
    keep_client_params: bool = False
    # Whether the host round loop may defer this algorithm's metric fetch +
    # post_round by one round (hides device->host latency behind the next
    # round's compute). Safe when post_round is analytic/logging-only; the
    # Shapley algorithms set False — their post_round drives data-dependent
    # subset evaluation that must see the round's metrics synchronously.
    supports_round_pipelining: bool = True
    # Whether round_fn accepts the optional trailing ``lr_scale`` operand
    # (config.lr_schedule): the simulator passes it only when a schedule
    # is active AND the algorithm declares support — an algorithm without
    # the operand still works with the constant default.
    supports_lr_schedule: bool = False
    # Whether the host loop may fuse K rounds into one dispatched program
    # (config.rounds_per_dispatch > 1; parallel/engine.py
    # make_batched_round_fn). The batched dispatch scan-stacks every aux
    # leaf ``[K, ...]`` and hands post_round dispatch-granular params
    # (RoundContext.global_params is the dispatch-FINAL model,
    # prev_global_params the dispatch-initial one), so algorithms whose
    # aux carries per-round parameter stacks or whose post_round consumes
    # per-round parameter state must say False. Conservative default
    # False — a third-party post_round reading ctx.global_params would
    # silently get wrong values; FedAvg/SignSGD opt in.
    supports_round_batching: bool = False
    # Whether the round program implements asynchronous federation
    # (config.async_mode='on'; robustness/arrivals.py): deadline rounds,
    # the staleness buffer carried as round state, and the extra
    # ``async_state`` round_fn operand. Conservative default False — the
    # simulator refuses async_mode='on' with the cause instead of
    # silently running the algorithm synchronously; the FedAvg family
    # opts in (sign_SGD's shared-vote round has no parameter-space
    # buffer to hold late updates; the Shapley servers refuse in their
    # constructors — subset utilities assume a synchronous cohort).
    supports_async: bool = False

    def __init__(self, config):
        self.config = config

    def check_cohort(self, n_clients: int) -> None:
        """Validate the ACTUAL client count before any training runs.

        Called with the true ``n_clients`` (which a caller-supplied
        ``ClientData`` may make different from ``config.worker_number``)
        from every execution path's build step: the simulator calls it
        right before building the round fn on the vmap path (so every
        algorithm is covered regardless of its ``make_round_fn``
        inheritance; ``FedAvg.make_round_fn`` also calls it for direct
        library users), and the threaded runner before its pool spawns. The
        constructor can only see ``worker_number``, so count-dependent
        feasibility checks (exact Shapley's 2^N bound, GTG's permutation
        cap) live here and merely warn at construction."""

    @property
    def materializes_client_stack(self) -> bool:
        """Whether the round program holds the full [n_clients, params]
        stack resident (drives the simulator's up-front feasibility check;
        FedAvg widens this with its client_eval / robust-aggregation
        materializers)."""
        return bool(self.keep_client_params)

    # ---- jit side ----------------------------------------------------------
    def make_round_fn(
        self, apply_fn: Callable, optimizer, n_clients: int,
        preprocess: Callable | None = None,
        client_sizes=None,
    ) -> Callable:
        """Return ``round_fn(global_params, client_state, cx, cy, cmask,
        sizes, key[, lr_scale]) -> (new_global, new_client_state, aux)``.

        ``client_sizes`` (optional host numpy ``[n_clients]`` of real
        per-client sample counts) enables STATIC size-aware work
        scheduling where the algorithm supports it (FedAvg fused path,
        config.bucket_client_work); pass None when the client axis is
        sharded over a mesh (the static regrouping would fight the
        sharding layout) or when counts aren't known up front.
        ``client_sizes`` is captured at BUILD time into the static slice
        plan, while aggregation weights use the per-round ``sizes``
        operand — the two must describe the same clients. Mutating the
        client data (e.g. ``ClientData.override_client``) after building
        the round fn leaves a stale plan that silently truncates any
        client grown past its group's step budget: inject data BEFORE
        construction, as ``run_simulation`` and ``simulator_heterogeneous``
        do (ADVICE r4).

        ``client_state`` is whatever per-client state persists across rounds
        (optimizer/momentum buffers) as a client-stacked pytree; ``aux`` is a
        dict of diagnostics (device arrays). ``lr_scale`` (a traced f32
        scalar, default 1.0) is passed only when ``supports_lr_schedule``
        is True and a non-constant ``config.lr_schedule`` is active.
        """
        raise NotImplementedError

    def init_client_state(self, optimizer, global_params, n_clients):
        """Initial per-client persistent state (client-stacked pytree).

        None when client optimizers reset every round (the default): no
        state persists, and carrying a per-client optimizer-state pytree at
        1000-client scale would cost a model-size buffer per client.
        """
        if getattr(self.config, "reset_client_optimizer", True):
            return None
        return jax.vmap(lambda _: optimizer.init(global_params))(
            jax.numpy.arange(n_clients)
        )

    def make_server_update(self):
        """Optional server-side optimizer: ``(init_fn, update_fn)`` or None.

        See FedAvg.make_server_update (FedOpt family). None (the default)
        means the round aggregate becomes the next global model unchanged.
        """
        return None

    # ---- host side ---------------------------------------------------------
    def prepare(self, apply_fn, eval_fn) -> None:
        """One-time setup after the engine is built (e.g. jit subset-eval)."""

    def post_round(self, ctx: RoundContext) -> dict:
        """Host-side per-round hook; returns extra metrics to record/log."""
        return {}
