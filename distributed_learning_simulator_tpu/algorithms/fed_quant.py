"""FedQuant: quantized FedAvg (QAT locally, 8-bit stochastic exchange).

Replaces the reference's FedQuantServer/FedQuantWorker pair
(servers/fed_quant_server.py, workers/fed_quant_worker.py), whose *intent*
(per SURVEY 2.1#11-12 — both classes are broken as written against a stale
API) is: QAT local training + quantized bidirectional parameter exchange +
compression-ratio reporting. Here:

  * local training applies straight-through fake-quant to params inside the
    loss (ops/quantize.py fake_quant_tree) — the JAX-native QAT, replacing
    PyTorch's QuantizationAwareTraining attach (fed_quant_worker.py:19-20);
  * client uploads are stochastically quantized to ``levels`` levels then
    dequantized at the server before the weighted average (parity with
    fed_quant_server.py:25-39); the server's aggregated params are
    re-quantized for the downlink broadcast;
  * compression ratios are computed analytically (ops/payload.py) and
    reported every round, parity with the serialized-size logs at
    fed_quant_server.py:41-48;
  * every client's model is evaluated on the test set before aggregation
    and the global model after, per round (parity with the pre/post-
    aggregation accuracy logs at fed_quant_worker.py:55-69 — there each
    worker thread evaluates its own local model; here the per-client evals
    batch under one vmapped inference program). The evaluated model is the
    local QAT model BEFORE the quantized upload — the reference's
    observable (fed_quant_worker.py:55-58) — and the inference forward
    applies the QAT fake-quant transform, matching the reference's
    QAT-instrumented model at eval time. Disable with
    ``client_eval=False`` (the per-client stack must materialize, which
    caps feasible cohort size for large models).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from distributed_learning_simulator_tpu.algorithms.fedavg import FedAvg
from distributed_learning_simulator_tpu.ops.payload import (
    compression_ratio,
    payload_bytes,
    quantized_payload_bytes,
)
from distributed_learning_simulator_tpu.ops.quantize import (
    dequantize_tree,
    fake_quant_tree,
    stochastic_quantize_tree,
)
from distributed_learning_simulator_tpu.telemetry.client_stats import (
    ClientStats,
)


class FedQuant(FedAvg):
    name = "fed_quant"

    # Per-client eval telemetry (reference fed_quant_worker.py:55-69) is
    # FedAvg-family machinery now — FedAvg.__init__ auto-enables it for
    # this algorithm at reference-like cohort sizes. Round batching
    # (config.rounds_per_dispatch) rides FedAvg.supports_round_batching:
    # available whenever client_eval is off, so batching fed_quant at
    # reference-like cohorts (<= 32, where client_eval auto-enables)
    # needs an explicit client_eval=False. The quant_mse round scalar
    # scan-stacks like any other aux leaf.

    @property
    def levels(self) -> int:
        # 256 levels = 8-bit, the reference's choice (fed_quant_server.py:37).
        return getattr(self.config, "quant_levels", 256)

    def client_param_transform(self):
        levels = self.levels
        if not getattr(self.config, "qat", True):
            return None
        return lambda params: fake_quant_tree(params, levels)

    def process_client_payload(self, client_params, key):
        """Simulate the quantized uplink: per-client stochastic quantize ->
        dequantize. Unbiased, so aggregation statistics match a real 8-bit
        wire exchange."""
        levels = self.levels
        n_clients = jax.tree_util.tree_leaves(client_params)[0].shape[0]
        keys = jax.random.split(key, n_clients)

        def one(params, k):
            return dequantize_tree(stochastic_quantize_tree(params, levels, k))

        return jax.vmap(one)(client_params, keys), {}

    def process_aggregated(self, global_params, key):
        """Simulate the quantized downlink broadcast.

        With ``client_stats`` on, also report the per-round mean-squared
        quantization error of that broadcast (device-side scalar; lands
        in the ``client_stats`` sub-object of the metrics record) — the
        payload-compression loss the analytic byte ratios cannot show.
        Trace-time gated: 'off' compiles the exact pre-feature program.
        """
        q = stochastic_quantize_tree(global_params, self.levels, key)
        deq = dequantize_tree(q)
        aux = {}
        if ClientStats.from_config(self.config) is not None:
            se = sum(
                jnp.sum((d.astype(jnp.float32) - g.astype(jnp.float32)) ** 2)
                for g, d in zip(
                    jax.tree_util.tree_leaves(global_params),
                    jax.tree_util.tree_leaves(deq),
                )
            )
            count = sum(
                g.size for g in jax.tree_util.tree_leaves(global_params)
            )
            aux["quant_mse"] = se / count
        return deq, aux

    def post_round(self, ctx):
        raw = payload_bytes(ctx.global_params)
        comp = quantized_payload_bytes(ctx.global_params, self.levels)
        ratio = compression_ratio(raw, comp)
        out = {
            "uplink_compression_ratio": ratio,
            "downlink_compression_ratio": ratio,
            "payload_bytes_raw": raw,
            "payload_bytes_quantized": comp,
        }
        out.update(super().post_round(ctx))  # client_eval telemetry
        return out
