from distributed_learning_simulator_tpu.algorithms.base import Algorithm, RoundContext
from distributed_learning_simulator_tpu.algorithms.fedavg import FedAvg
from distributed_learning_simulator_tpu.algorithms.sign_sgd import SignSGD
from distributed_learning_simulator_tpu.algorithms.fed_quant import FedQuant
from distributed_learning_simulator_tpu.algorithms.shapley import (
    MultiRoundShapley,
    GTGShapley,
    shapley_from_utilities,
)

__all__ = [
    "Algorithm",
    "RoundContext",
    "FedAvg",
    "SignSGD",
    "FedQuant",
    "MultiRoundShapley",
    "GTGShapley",
    "shapley_from_utilities",
]
