"""Shapley-value contribution scoring: exact multi-round + GTG Monte-Carlo.

Replaces the reference's three Shapley servers (servers/shapley_value_server.py,
servers/multiround_shapley_value_server.py, servers/GTG_shapley_value_server.py).
Both algorithms run FedAvg rounds and then score each client's contribution to
the round's test metric.

TPU-first transformation (SURVEY 3.4): the reference evaluates one Python
subset at a time — a weighted average + a full test inference per subset
(multiround_shapley_value_server.py:34-40). Here a subset is a fixed-shape 0/1
mask; ``subset_weighted_mean`` is an einsum over (mask x client-params), and a
*batch* of subsets evaluates under one ``vmap`` — 2^N model materializations +
test inferences fused into chunked batched XLA calls.

Reference defects fixed, not replicated:
  * ``round_trunc_threshold`` is actually plumbed through config (the
    reference reads it from kwargs that factory.py:21-22 never passes,
    SURVEY 2.1#9).
  * GTG's contribution records are appended as *copies* — the reference
    appends the same mutable list N times per permutation, skewing both the
    convergence test and the final average (SURVEY 2.1#10).
  * GTG prefix evaluation is batched: a permutation's prefixes are fetched
    in fused blocks of ``_PREFIX_BLOCK`` (memoized), and the walk stops
    requesting blocks once eps-truncated — the reference's lazy skip
    semantics at a fraction of its N-sequential-host-round-trips cost.
    ``metric_<round>.pkl`` therefore holds only the prefixes actually
    evaluated (as the reference's lazy walk does), not every prefix.
  * GTG prefix AGGREGATION is cumulative (``gtg_prefix_mode='cumsum'``,
    the default): a permutation's prefix models come from one streamed
    weighted cumulative sum over its clients in walk order
    (ops/aggregate.block_prefix_cumsum via _CumsumPrefixWalker), so a
    length-L walk moves O(L*P) HBM bytes where the per-prefix masked
    reduction moved O(L*N*P/chunk) — the N-fold structural win at the
    north-star N=1000 (docs/PERFORMANCE.md § GTG at scale).
    ``gtg_prefix_mode='masked'`` keeps the mask-weighted path as the
    differential-testing oracle.
"""

from __future__ import annotations

import math
import os
import pickle
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from distributed_learning_simulator_tpu.algorithms.base import RoundContext
from distributed_learning_simulator_tpu.algorithms.fedavg import FedAvg
from distributed_learning_simulator_tpu.utils.errors import is_device_oom
from distributed_learning_simulator_tpu.ops.aggregate import (
    block_prefix_cumsum,
    prefix_means_from_cumsum,
    subset_masks_all,
    subset_weighted_mean,
)
from distributed_learning_simulator_tpu.telemetry.client_stats import (
    ClientStats,
    attribution_crosscheck,
)
from distributed_learning_simulator_tpu.telemetry.valuation import (
    cohort_crc,
)
from distributed_learning_simulator_tpu.utils.logging import get_logger

_EVAL_CHUNK = 16  # subset models evaluated per batched XLA call
_PREFIX_BLOCK = 16  # GTG permutation prefixes fetched per fused call

#: Mesh axis the subset evaluator partitions its MODEL-BATCH dimension
#: over (mesh-sharded GTG, ROADMAP item 5). Distinct from the round
#: program's "clients" axis: the round shards the client stack, the
#: evaluator shards the vmapped subset/permutation-group axis with the
#: stack REPLICATED — each device evaluates its own slice of the wave's
#: subset models with no cross-device reduction anywhere.
SUBSET_AXIS = "subsets"


def eval_mesh_devices(config) -> int | None:
    """How many devices the Shapley subset evaluators shard their batch
    axis over: ``config.mesh_devices`` when a single process owns the
    whole mesh, else None (the serial evaluator). Multihost stays
    unsharded — the GTG walk is data-dependent HOST control flow, and a
    multi-process walk would need every process to replay identical
    truncation/convergence decisions against collectively-fetched
    utilities; single-host mesh sharding is the supported capability."""
    d = getattr(config, "mesh_devices", None) or 1
    if d <= 1 or getattr(config, "multihost", False):
        return None
    if getattr(config, "execution_mode", "vmap").lower() == "threaded":
        # The threaded oracle ignores mesh_devices everywhere else; its
        # record writer also predates the v10 gtg sub-object routing.
        return None
    return int(d)


class SubsetMemo(dict):
    """Subset-utility memo with cross-round reuse accounting.

    A plain dict everywhere the walk machinery is concerned (it only does
    ``s in memo`` / ``memo[s]`` / ``memo[s] = v``), plus bookkeeping for
    the cross-round reuse feature (``config.gtg_cross_round_memo``,
    ROADMAP item 4b): entries present at construction are the SEED —
    utilities carried over from an earlier round with the same cohort —
    and :meth:`hit_rate` reports what fraction of the subsets this walk
    actually requested were served from the seed instead of evaluated.
    Reused utilities describe the *earlier* round's client params; the
    reuse premise (GTG-Shapley's between-round truncation) is that subset
    utilities drift slowly once the model converges — the regime where
    round truncation fires anyway. The hit rate (and, for audit walks,
    the recorded fidelity correlation) is the self-policing measurement
    of that premise.

    What a hit SAVES depends on the prefix mode: under ``masked`` the
    deduplication in :func:`eval_subsets` skips the seeded subsets'
    evaluator calls outright (realized device savings); under the
    default ``cumsum`` the prefix walker must stream every position to
    maintain its carries, so a seeded prefix is still computed inside
    the fused wave and only its memo write is skipped — the hit rate
    then measures utility REUSE/stability, not device work avoided
    (the same caveat the walker's own docstring makes for within-round
    hits).
    """

    def __init__(self, seed: dict | None = None):
        super().__init__(seed or {})
        self._seeded = frozenset(self)
        self._hits: set = set()
        self._inserted = 0

    def __contains__(self, key) -> bool:
        present = super().__contains__(key)
        if present and key in self._seeded:
            self._hits.add(key)
        return present

    def __setitem__(self, key, value) -> None:
        if not super().__contains__(key):
            self._inserted += 1
        super().__setitem__(key, value)

    @property
    def evaluated(self) -> int:
        """Subsets actually evaluated into this memo (seeded entries
        excluded) — the honest ``gtg_subset_evals`` cost unit; equals
        ``len(self)`` when unseeded."""
        return self._inserted

    def hit_rate(self) -> float | None:
        """Fraction of requested subsets served from the cross-round seed
        (None when the walk requested nothing)."""
        requested = len(self._hits) + self._inserted
        if requested == 0:
            return None
        return len(self._hits) / requested


def eval_subsets(evaluator, client_params, sizes, prev_global,
                 eval_batches, n: int, memo, subset_sets) -> None:
    """Evaluate the listed subsets (frozensets of client indices) into
    ``memo``, deduplicating against it — the ONE mask-building path shared
    by the masked walk mode, the grand/empty-coalition seeds, and the
    valuation auditor (telemetry/valuation.py)."""
    todo = list(dict.fromkeys(s for s in subset_sets if s not in memo))
    if not todo:
        return
    mask_rows = np.zeros((len(todo), n), dtype=np.float32)
    for r, s in enumerate(todo):
        mask_rows[r, list(s)] = 1.0
    vals = evaluator(
        client_params, sizes, mask_rows, prev_global, eval_batches
    )
    for s, v in zip(todo, vals):
        memo[s] = float(v)


def _gtg_converged(records: list[np.ndarray], n: int, last_k: int,
                   converge_criteria: float) -> bool:
    converge_min = max(30, n)  # GTG_shapley_value_server.py:15
    # last_k + 1 records minimum: with a configurable last_k above the
    # reference's 30-record floor, running_means[-last_k:] would silently
    # truncate and a mean flat over fewer samples than the user asked to
    # compare could fire convergence early.
    if len(records) <= max(converge_min, last_k):
        return False
    # Reference semantics (GTG_shapley_value_server.py:82-91): each of
    # the last_k running means is compared to the FINAL running mean —
    # relative error averaged over the worker axis — and sampling stops
    # when the largest of those k errors is within converge_criteria.
    # (NOT successive diffs: a running mean drifting steadily has small
    # per-step changes but large distance-to-final, and the reference
    # keeps sampling in that regime.) Note the last_k window INCLUDES
    # the final mean itself (its error is trivially 0, so last_k-1
    # comparisons are informative) — that is the reference's exact
    # slice, kept verbatim for parity.
    all_arr = np.stack(records)
    cumsum = np.cumsum(all_arr, axis=0)
    counts = np.arange(1, len(records) + 1)[:, None]
    running_means = (cumsum / counts)[-last_k:]
    final = running_means[-1:]
    errors = np.mean(
        np.abs(running_means - final) / (np.abs(final) + 1e-12), axis=1
    )
    return bool(np.max(errors) <= converge_criteria)


def gtg_walk(evaluator, client_params, sizes, prev_global, eval_batches,
             n: int, rng, *, eps: float, cap: int, last_k: int,
             converge_criteria: float, trunc_ref: float,
             prefix_mode: str = "cumsum", memo=None,
             starts_per_iteration: int | None = None):
    """One round's GTG permutation-sampling walk over an ``n``-client
    cohort: Monte-Carlo marginal records with eps-truncation, shared
    waves, and the cross-walk subset memo.

    Extracted from ``GTGShapley.post_round`` so the valuation auditor
    (telemetry/valuation.py) runs the EXACT same estimator on the current
    round's cohort — one walk implementation, no drift between the
    offline scorer and the in-line audit. Returns
    ``(sv_arr, n_perms, converged)``; utilities accumulate into ``memo``
    (a fresh dict when None — pass a :class:`SubsetMemo` seeded from an
    earlier round for cross-round reuse).

    ``starts_per_iteration`` truncates a sampling iteration to that many
    permutations (first elements drawn without replacement from ``rng``
    instead of "one per worker") — the audit walk's budget knob; None
    keeps the reference's one-permutation-per-worker iteration.
    """
    if memo is None:
        memo = {}
    eval_subsets(
        evaluator, client_params, sizes, prev_global, eval_batches, n,
        memo, [frozenset()],
    )  # u(empty): every walk's starting value
    walker = None
    if prefix_mode == "cumsum":
        walker = _CumsumPrefixWalker(
            evaluator, client_params, sizes, prev_global, eval_batches, n,
        )
    records: list[np.ndarray] = []
    n_perms = 0
    converged = False
    while not converged and n_perms < cap:
        # One permutation starting with each worker (:42-49) — or, for a
        # budgeted audit walk, with each of a sampled subset of workers.
        # The whole sampling iteration is evaluated in shared WAVES: wave
        # w requests prefix block [wB, wB+B) for EVERY still-active
        # permutation in one batched evaluator call (the memo dedups
        # shared prefixes), instead of walking the permutations one at a
        # time — at N=128 this cuts the sequential host dispatch+fetch
        # cycles per iteration from O(n * n/B) to n/B. The
        # per-permutation walk (eps-truncation semantics :51-61,
        # truncated step keeps v_prev so its marginal is exactly 0) is
        # unchanged, so within one sampling iteration the records — and
        # therefore SVs, permutation counts and the convergence point —
        # match a sequential walk over the same permutations. Two
        # bookkeeping differences vs walking one permutation at a time:
        # prefixes evaluated past a mid-iteration convergence are extra
        # (they land in the memo/metric pickle), and all shuffles are
        # drawn up front, so on mid-iteration convergence the RNG stream
        # position differs from a lazily-drawing walk (later rounds
        # sample different — equally valid — permutations).
        if starts_per_iteration is None or starts_per_iteration >= n:
            starts = list(range(n))
        else:
            starts = [
                int(s) for s in
                rng.choice(n, size=starts_per_iteration, replace=False)
            ]
        m = len(starts)
        perms = []
        for first in starts:
            rest = [i for i in range(n) if i != first]
            rng.shuffle(rest)
            perms.append([first] + rest)
        if walker is not None:
            walker.reset()  # fresh zero carries for this iteration
        marginals = np.zeros((m, n), dtype=np.float64)
        v_prev = [memo[frozenset()]] * m
        truncated = [False] * m
        for j0 in range(0, n, _PREFIX_BLOCK):
            j1 = min(j0 + _PREFIX_BLOCK, n)
            active: list[int] = []
            for p_idx in range(m):
                if truncated[p_idx] or (
                    abs(trunc_ref - v_prev[p_idx]) < eps
                ):
                    truncated[p_idx] = True
                else:
                    active.append(p_idx)
            if not active:
                break  # every permutation truncated
            if walker is not None:
                walker.eval_block(perms, active, j0, j1, memo)
            else:
                eval_subsets(
                    evaluator, client_params, sizes, prev_global,
                    eval_batches, n, memo,
                    [
                        frozenset(perms[p][: j + 1])
                        for p in active for j in range(j0, j1)
                    ],
                )
            for p_idx in active:
                perm = perms[p_idx]
                vp = v_prev[p_idx]
                for j in range(j0, j1):
                    if abs(trunc_ref - vp) >= eps:
                        v_j = memo[frozenset(perm[: j + 1])]
                    else:
                        v_j = vp  # truncated: marginal exactly 0
                    marginals[p_idx, perm[j]] = v_j - vp
                    vp = v_j
                v_prev[p_idx] = vp
        for p_idx in range(m):
            records.append(marginals[p_idx].copy())  # SURVEY 2.1#10
            n_perms += 1
            if _gtg_converged(records, n, last_k, converge_criteria):
                converged = True
                break
    return np.mean(np.stack(records), axis=0), n_perms, converged


def _sv_crosscheck_extra(ctx: RoundContext, sv_arr, config) -> dict:
    """Utility-attribution cross-check (telemetry/client_stats.py): when
    the round carried per-client stats, report the correlation between
    the expensive Shapley attribution and the cheap in-round signal
    (local loss improvement). Reads the matrix the host loop ALREADY
    fetched (ctx.extra, populated only on client_stats_every cadence
    rounds — no second device transfer, and off-cadence rounds don't
    grow a v3-era field in their un-upgraded record); falls back to the
    device array for direct post_round callers, cadence-gated the same
    way. Empty dict when stats are off, off-cadence, or the correlation
    is degenerate."""
    stats = ctx.extra.get("client_stats_np")
    if stats is None:
        stats_dev = ctx.aux.get("client_stats")
        cs = ClientStats.from_config(config)
        if (
            stats_dev is None
            or cs is None
            or not cs.fetch_round(ctx.round_idx)
        ):
            return {}
        stats = np.asarray(stats_dev)
    corr = attribution_crosscheck(sv_arr, stats)
    return {} if corr is None else {"sv_stats_corr": corr}


def _resolve_eval_dtype(config, default: str) -> str:
    """Per-algorithm ``shapley_eval_dtype='auto'`` resolution (ADVICE r5):
    exact multi-round Shapley reads the stack in f32 — it is the documented
    exact-parity path with no Monte-Carlo noise to hide bf16 rounding in —
    while GTG keeps bf16, where halving the dominant stack-read traffic is
    measured fidelity-free. An explicit config value wins for both."""
    dtype = getattr(config, "shapley_eval_dtype", "auto")
    return default if dtype == "auto" else dtype


def shapley_from_utilities(utilities: dict[frozenset, float], n: int) -> np.ndarray:
    """Exact Shapley values from a complete 2^n utility table.

    SV_i = sum over S not containing i of
    ``(u(S + {i}) - u(S)) / (n * C(n-1, |S|))`` — the marginal-contribution
    weighting of multiround_shapley_value_server.py:42-55.
    """
    sv = np.zeros(n, dtype=np.float64)
    ids = list(range(n))
    for size in range(n):
        weight = 1.0 / (n * math.comb(n - 1, size))
        from itertools import combinations

        for combo in combinations(ids, size):
            s = frozenset(combo)
            for i in ids:
                if i in s:
                    continue
                sv[i] += weight * (utilities[s | {i}] - utilities[s])
    return sv


def cap_eval_batches(eval_batches, max_samples: int | None):
    """First ``max_samples`` test samples as one padded batch (mask-exact).

    Subset-utility evaluations only — the round's reported metric always
    sees the full set. The flatten+slice happens once per round on device;
    the evaluator's jitted program then runs on the smaller static shape.
    """
    if max_samples is None:
        return eval_batches
    xb, yb, mb = eval_batches
    bs = xb.shape[1]
    total = xb.shape[0] * bs
    k = min(max_samples, total)
    flat = lambda a: a.reshape((total,) + a.shape[2:])  # noqa: E731
    if k < bs:
        # One smaller batch: strictly below the eval_batch_size activation
        # envelope, and masked-out samples cost no compute (the cap's whole
        # point — padding to bs would run the full batch masked).
        return (flat(xb)[:k][None], flat(yb)[:k][None], flat(mb)[:k][None])
    # k spans batches: keep the eval_batch_size scan granularity (the
    # subset evaluator vmaps _EVAL_CHUNK models over each batch, so one
    # giant [1, k] batch would blow the memory envelope bs exists to
    # bound); trim the remainder via the mask.
    n_batches = min((k + bs - 1) // bs, xb.shape[0])
    take = n_batches * bs
    reshape = lambda a: a[:take].reshape(  # noqa: E731
        (n_batches, bs) + a.shape[1:]
    )
    keep = jnp.asarray(np.arange(take) < k, mb.dtype)
    return (
        reshape(flat(xb)),
        reshape(flat(yb)),
        (flat(mb)[:take] * keep).reshape((n_batches, bs) + mb.shape[2:]),
    )


class _SubsetEvaluator:
    """Chunked, memoized evaluation of subset-model test metrics.

    ``chunk`` (config.shapley_eval_chunk) sets how many subset models one
    batched XLA call materializes+evaluates. Each call re-reads the full
    ``[n_clients, params]`` stack for its weighted means, so a larger
    chunk amortizes that read across more subsets — at N=1000 (1.8 GB
    stack) chunk 16 re-reads ~30 TB over a 266k-subset round; chunk 64
    cuts it 4x. The ceiling is activation memory: chunk models x
    eval-batch activations live at once.

    **Mesh sharding** (``mesh_devices > 1``, single host): the vmapped
    model-batch axis of each fused call is partitioned over a
    ``SUBSET_AXIS`` device mesh with the client stack, sizes, previous
    global and eval batches REPLICATED — one call then evaluates
    ``chunk x D`` subset models, ``chunk`` per device, in ~the serial
    call's wall time. Per-device call shapes are IDENTICAL to the
    serial evaluator's (the width scales with D exactly so each
    device's local program is the serial program), which is what makes
    sharded utilities — and therefore SVs, permutation counts, eval
    counts, and the memo contents — bit-identical to the serial walk
    (tests/test_gtg_mesh.py pins this at forced D=2). There are no
    cross-device reductions anywhere: a subset's weighted mean contracts
    over the REPLICATED client axis on whichever device owns that subset
    row, in the serial reduction order.
    """

    def __init__(self, eval_fn, chunk: int = _EVAL_CHUNK,
                 eval_dtype: str = "float32",
                 mesh_devices: int | None = None):
        self._chunk = int(chunk)
        self._eval_dtype = jnp.dtype(eval_dtype)
        self._mesh = None
        self._devices = 1
        if mesh_devices is not None and mesh_devices > 1:
            from distributed_learning_simulator_tpu.parallel.mesh import (
                make_mesh,
            )

            self._mesh = make_mesh(int(mesh_devices), axis_name=SUBSET_AXIS)
            self._devices = int(mesh_devices)
            self._rep = NamedSharding(self._mesh, PartitionSpec())
            self._shd = NamedSharding(
                self._mesh, PartitionSpec(SUBSET_AXIS)
            )
        # One-slot identity caches for the per-round replicated operands:
        # the walk calls the evaluator hundreds of times per round with
        # the SAME stack/sizes/prev/batches objects, and re-running the
        # placement tree_map per call would pay leaves x calls of no-op
        # device_puts.
        self._role_cache: dict[str, tuple] = {}

        # eval_fn(params, xb, yb, mb) -> {'loss','accuracy'}
        def eval_one(client_params, sizes, mask, prev_global, xb, yb, mb):
            params = subset_weighted_mean(client_params, sizes, mask, prev_global)
            return eval_fn(params, xb, yb, mb)["accuracy"]

        self._eval_chunk = jax.jit(
            jax.vmap(eval_one, in_axes=(None, None, 0, None, None, None, None))
        )

        # GTG cumsum path (gtg_prefix_mode='cumsum'): ONE fused XLA call per
        # group of G permutations advances their walks by a whole prefix
        # block — gather the block's clients, extend the carried running
        # sums (block_prefix_cumsum), materialize the G*B prefix models by a
        # cheap divide, and evaluate them — so each evaluated prefix reads
        # O(P) gathered bytes instead of the masked path's O(N*P/chunk)
        # stack re-read, and the C*N*P mask-contraction MACs per call
        # disappear outright. ``carry``/``carry_t`` hold exactly this
        # group's G running sums ([G, ...] leaves — the walker compacts the
        # wave's active rows host-side), so a call's carry traffic is
        # O(G*P), an eighth of the block models it evaluates; a
        # whole-cohort slot array with scatter updates was measured 6x
        # SLOWER than the masked path on backends without in-place buffer
        # donation (each call copied all N carries).
        def prefix_wave(client_params, sizes, carry, carry_t, perm_block,
                        prev_global, xb, yb, mb):
            cs_tree, totals = block_prefix_cumsum(
                client_params, sizes, perm_block, carry, carry_t,
            )
            new_carry = jax.tree_util.tree_map(
                lambda cs: cs[:, -1], cs_tree
            )
            params = prefix_means_from_cumsum(cs_tree, totals, prev_global)
            g, b = perm_block.shape
            flat = jax.tree_util.tree_map(
                lambda p: p.reshape((g * b,) + p.shape[2:]), params
            )
            accs = jax.vmap(
                lambda pp: eval_fn(pp, xb, yb, mb)["accuracy"]
            )(flat)
            return accs.reshape(g, b), new_carry, totals[:, -1]

        self._prefix_wave = jax.jit(prefix_wave)

    @property
    def eval_dtype(self):
        return self._eval_dtype

    @property
    def devices(self) -> int:
        """Devices the model-batch axis is partitioned over (1 = serial)."""
        return self._devices

    @property
    def call_width(self) -> int:
        """Nominal subset models per fused call: the configured chunk
        times the mesh width (each device keeps the serial chunk's
        activation envelope — and the serial call's exact shapes)."""
        return self._chunk * self._devices

    def _place_rep(self, role, tree):
        """Replicate a per-round operand over the subset mesh ONCE
        (identity-cached per role; serial mode passes through untouched).
        """
        if self._mesh is None:
            return tree
        cached = self._role_cache.get(role)
        if cached is not None and cached[0] is tree:
            return cached[1]
        placed = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, self._rep), tree
        )
        self._role_cache[role] = (tree, placed)
        return placed

    def _shard_rows(self, tree):
        """Partition a per-call tree's LEADING (model-batch) axis over
        the subset mesh; the serial path keeps today's jnp.asarray."""
        if self._mesh is None:
            return jax.tree_util.tree_map(jnp.asarray, tree)
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(a, self._shd), tree
        )

    def release_round(self):
        """Drop the per-round placement cache at the END of a walk. In
        mesh mode the cache holds BOTH the caller's stack and its D-way
        replicated copy; without this release those buffers would stay
        pinned through the NEXT round's training — an extra full-stack
        HBM footprint the serial evaluator never held. Every walk driver
        (GTG/multiround post_round, the valuation auditor) calls it when
        its round's evaluations are done; a serial evaluator's cache is
        never populated, so this is a no-op there."""
        self._role_cache.clear()

    def _reraise_oom(self, e, n_models: int, eval_batches,
                     min_chunk: int = 1):
        """Shared actionable-hint treatment for device OOMs in both the
        masked-chunk and cumsum prefix-wave paths: the envelope is
        ``n_models`` subset models x eval-batch activations resident at
        once (measured: the full-10k-sample set at chunk 64 exceeds one
        chip on cnn_tpu while chunk 16 fits — docs/PERFORMANCE.md § Scale
        validation). ``min_chunk`` is the path's floor on the call width:
        the cumsum prefix wave cannot go below one block of
        ``_PREFIX_BLOCK`` models, so suggesting a smaller chunk there
        would send the user into the identical crash."""
        xb = eval_batches[0]
        n_eval = int(xb.shape[0]) * int(xb.shape[1])
        suggestion = max(self._chunk // 4, min_chunk)
        chunk_advice = (
            f"Lower shapley_eval_chunk (e.g. {suggestion}) or cap "
            if suggestion < self._chunk
            # Mirrors _oom_hint's exceeded-even-at-minimum branch: when a
            # smaller chunk cannot shrink the call (chunk <= 4 on the
            # masked path, chunk <= one prefix block on the cumsum path),
            # the only lever left is the eval-sample cap.
            else f"shapley_eval_chunk={self._chunk} is already minimal — cap "
        )
        raise RuntimeError(
            "device OOM inside the Shapley subset evaluator: "
            f"{n_models} subset models x ~{n_eval} "
            "eval samples of activations were resident at once. "
            + chunk_advice +
            "shapley_eval_samples (subset utilities only; the "
            "round metric keeps the full test set)."
        ) from e

    def prepare_stack(self, client_params):
        """Cast the [n_clients, ...] stack to the evaluator read dtype ONCE
        per round (config.shapley_eval_dtype). Each batched call re-reads
        the whole stack for its subset weighted means — the dominant HBM
        traffic of a large-N GTG round — so a bf16 stack halves it; the
        tensordot still accumulates f32 (ops/aggregate.subset_weighted_mean)
        and the subset model handed to eval is f32-ranged."""
        if self._eval_dtype == jnp.float32:
            # Under a mesh, also re-place the (possibly client-axis-
            # sharded) stack REPLICATED over the subset mesh once per
            # round — one all-gather, amortized over every fused call.
            return self._place_rep("stack", client_params)
        cast = jax.tree_util.tree_map(
            lambda a: a.astype(self._eval_dtype), client_params
        )
        # Materialize now: the cast must happen once, not get re-fused into
        # every downstream evaluator call by lazy dispatch.
        return self._place_rep("stack", jax.block_until_ready(cast))

    def __call__(self, client_params, sizes, masks, prev_global, eval_batches):
        """masks: [M, n] numpy 0/1. Returns [M] numpy accuracies.

        All chunks are dispatched first and fetched with ONE device_get:
        per-chunk fetches each pay a full device->host round-trip (~100 ms
        through a tunnel), which dominated GTG rounds at large N. Under a
        subset mesh each call carries ``chunk x D`` mask rows sharded over
        the devices (``chunk`` per device — the serial call's shapes), so
        the loop makes D-fold fewer dispatches over the same mask list in
        the same order; padded garbage rows are discarded host-side as
        before.
        """
        client_params = self._place_rep("stack", client_params)
        sizes = self._place_rep("sizes", sizes)
        prev_global = self._place_rep("prev_global", prev_global)
        xb, yb, mb = self._place_rep("batches", tuple(eval_batches))
        size = self.call_width
        pending = []
        try:
            for start in range(0, len(masks), size):
                chunk = masks[start : start + size]
                pad = size - len(chunk)
                if pad:
                    chunk = np.concatenate(
                        [chunk, np.zeros((pad, chunk.shape[1]), np.float32)]
                    )
                vals = self._eval_chunk(
                    client_params, sizes, self._shard_rows(chunk),
                    prev_global, xb, yb, mb,
                )
                pending.append(vals[: size - pad] if pad else vals)
            return np.concatenate(jax.device_get(pending))
        except jax.errors.JaxRuntimeError as e:
            if not is_device_oom(e):
                raise
            # Per-DEVICE width: the resident-activation envelope the hint
            # sizes against is each device's slice, not the call total.
            self._reraise_oom(e, self._chunk, eval_batches)


class _CumsumPrefixWalker:
    """Device-side state of one GTG sampling iteration's permutation walks
    under ``gtg_prefix_mode='cumsum'``.

    Per active permutation, a carry row holds the f32 running weighted sum
    (and total weight) of the walked prefix — compacted each wave to just
    the still-active walks; :meth:`eval_block` advances a wave of them by
    one prefix block, batching ``group`` permutations' block-cumsums per
    fused evaluator call (this replaces the masked path's ``_PREFIX_BLOCK``
    wave gather: same wave-major structure, same single fetch per wave, but
    each evaluated prefix costs O(P) gathered bytes instead of an
    O(N*P/chunk) share of a full stack re-read). Nothing is ever
    recomputed: the carry IS the sliceable cumsum, streamed block by block,
    and an eps-truncated walk simply never touches the blocks past its
    stopping point.

    Bookkeeping parity with the masked path: the same prefix sets land in
    the memo (memo-first on duplicates, so a set evaluated twice — e.g. the
    grand coalition, reached by every full-length walk — keeps one
    deterministic value), so ``metric_<round>.pkl`` and the walk's
    truncation/marginal decisions see identical keys. Device-side work may
    exceed the masked path's on memo HITS (a hit still computes inside the
    fused call and is discarded host-side); at large N a walk re-visits
    almost no sets, so the waste is a handful of inferences per iteration.
    """

    def __init__(self, evaluator, client_params, sizes, prev_global,
                 eval_batches, n: int):
        self._ev = evaluator
        # Per-round operands replicated over the subset mesh once (no-op
        # pass-through for the serial evaluator).
        self._stack = evaluator._place_rep("stack", client_params)
        self._sizes = evaluator._place_rep("sizes", sizes)
        self._prev_global = evaluator._place_rep("prev_global", prev_global)
        self._eval_batches = evaluator._place_rep(
            "batches", tuple(eval_batches)
        )
        self._n = n
        self._block = min(_PREFIX_BLOCK, n)
        # Group size: the fused call evaluates group x block prefix models,
        # so group*block matches the masked path's shapley_eval_chunk
        # activation envelope (floor one group — cumsum mode's minimum call
        # width is one block of models). Under a subset mesh the group
        # scales by the device count: each device then advances the
        # SERIAL group's worth of permutations — per-device call shapes
        # identical to the serial walker's, which is the bit-identity
        # mechanism (class docstring of _SubsetEvaluator).
        self._group = (
            max(1, evaluator._chunk // self._block) * evaluator.devices
        )
        self._carry = None
        self._carry_t = None
        self._row_of: dict[int, int] = {}

    def reset(self):
        """Drop the carries for a fresh sampling iteration (every walk
        restarts at the empty prefix — materialized lazily as zero rows on
        the first wave)."""
        self._carry = None
        self._carry_t = None
        self._row_of = {}

    def _wave_carries(self, active):
        """Compact the carry rows of this wave's active permutations into
        one contiguous [ceil(A/G)*G, ...] tree (row k = active[k]; the tail
        pads by repeating a row so every group slice is exactly [G, ...] —
        one traced shape, garbage results discarded host-side). ONE gather
        per wave: truncated permutations' rows are dropped here, which is
        all the 'slicing' an eps-truncated walk ever needs — its cumsum
        simply stops being carried, nothing is recomputed."""
        g_size = self._group
        padded = -(-len(active) // g_size) * g_size
        if self._carry is None:  # first wave: every carry is the empty sum
            carry = jax.tree_util.tree_map(
                lambda x: jnp.zeros((padded,) + x.shape[1:], jnp.float32),
                self._stack,
            )
            return carry, jnp.zeros((padded,), jnp.float32)
        rows = np.asarray(
            [self._row_of[p] for p in active], dtype=np.int32
        )
        rows = np.concatenate(
            [rows, np.full((padded - len(rows),), rows[-1], np.int32)]
        )
        return (
            jax.tree_util.tree_map(lambda c: c[rows], self._carry),
            self._carry_t[rows],
        )

    def eval_block(self, perms, active, j0: int, j1: int, memo) -> None:
        """Advance every permutation in ``active`` through prefix positions
        [j0, j1), filling ``memo`` with the block's utilities. All groups
        are dispatched first and fetched with ONE device_get (the same
        tunnel-latency discipline as the masked evaluator)."""
        g_size, b_size = self._group, self._block
        carry, carry_t = self._wave_carries(active)
        pending = []
        new_carries = []
        try:
            for start in range(0, len(active), g_size):
                group = active[start : start + g_size]
                # A short final block (j1 - j0 < block) pads its trailing
                # positions with client 0 — that corrupts the carry past
                # position n-1, which no later block exists to read.
                block = np.zeros((g_size, b_size), np.int32)
                for g, p in enumerate(group):
                    block[g, : j1 - j0] = perms[p][j0:j1]
                # Per-call carries/indices partition over the subset mesh
                # (group-axis rows; serial mode = today's jnp.asarray /
                # pass-through): a short final group was already padded
                # by _wave_carries, so the group axis always splits
                # evenly over the devices.
                c_g = self._ev._shard_rows(jax.tree_util.tree_map(
                    lambda c: c[start : start + g_size], carry
                ))
                accs, nc, nct = self._ev._prefix_wave(
                    self._stack, self._sizes, c_g,
                    self._ev._shard_rows(carry_t[start : start + g_size]),
                    self._ev._shard_rows(block), self._prev_global,
                    *self._eval_batches,
                )
                pending.append((group, accs))
                new_carries.append((nc, nct))
            fetched = jax.device_get([a for _, a in pending])
        except jax.errors.JaxRuntimeError as e:
            if not is_device_oom(e):
                raise
            self._ev._reraise_oom(
                # Per-DEVICE width: each device holds its group slice's
                # models; g_size is a multiple of the device count.
                e, (g_size // self._ev.devices) * b_size,
                self._eval_batches, min_chunk=b_size,
            )
        if len(new_carries) == 1:
            self._carry, self._carry_t = new_carries[0]
        else:
            self._carry = jax.tree_util.tree_map(
                lambda *xs: jnp.concatenate(xs),
                *[nc for nc, _ in new_carries],
            )
            self._carry_t = jnp.concatenate([t for _, t in new_carries])
        self._row_of = {p: k for k, p in enumerate(active)}
        for (group, _), acc in zip(pending, fetched):
            for g, p in enumerate(group):
                perm = perms[p]
                for b in range(j1 - j0):
                    s = frozenset(perm[: j0 + b + 1])
                    if s not in memo:
                        memo[s] = float(acc[g, b])


def _check_shapley_config(config) -> None:
    """Shared preconditions for both Shapley servers.

    Subset utilities are plain weighted means of client params, so every
    client must participate and no server optimizer may reshape the global
    model (else the grand coalition's utility disagrees with the round
    metric and the Shapley values are silently wrong).
    """
    if getattr(config, "participation_fraction", 1.0) < 1.0:
        raise ValueError(
            "Shapley scoring needs every client's update each round; "
            "participation_fraction < 1 is not supported"
        )
    server_opt = getattr(config, "server_optimizer_name", "none") or "none"
    if server_opt.lower() not in ("none", ""):
        raise ValueError(
            "Shapley scoring assumes plain FedAvg aggregation; set "
            "server_optimizer_name='none'"
        )
    if getattr(config, "aggregation", "mean").lower() != "mean":
        raise ValueError(
            "Shapley scoring assumes the weighted-mean aggregator (subset "
            "utilities are weighted means); set aggregation='mean'"
        )
    from distributed_learning_simulator_tpu.robustness.faults import (
        FailureModel,
    )

    if FailureModel.from_config(config) is not None:
        # The subset-utility memo keys subsets of a FIXED cohort whose
        # every update is honest; a client that drops out or uploads
        # garbage silently invalidates every memoized utility that
        # includes it — refuse rather than score garbage.
        raise ValueError(
            "Shapley scoring refuses failure injection: the subset-utility "
            "memo assumes a fixed cohort of honest updates; set "
            "failure_mode='none'"
        )
    if getattr(config, "async_mode", "off").lower() == "on":
        # Same fixed-cohort assumption against the time axis: a late
        # upload applied rounds later (robustness/arrivals.py) has no
        # place in a subset utility evaluated against THIS round's
        # metric — refuse rather than attribute stale updates.
        raise ValueError(
            "Shapley scoring refuses async_mode='on': subset utilities "
            "assume a synchronous fixed cohort; set async_mode='off'"
        )


class MultiRoundShapley(FedAvg):
    """Exact multi-round Shapley: full-powerset utility per round.

    Parity with servers/multiround_shapley_value_server.py. 2^N subsets per
    round — exact only for small N (the reference's canonical run is N=4,
    simulator.sh:1); refuse N > 16.
    """

    name = "multiround_shapley_value"
    keep_client_params = True
    supports_round_pipelining = False  # post_round consumes round metrics
    # Round batching would hand post_round dispatch-final params and a
    # K-stacked aux['client_params']; SV attribution needs each round's
    # stack + metrics synchronously (same reason pipelining is off).
    supports_round_batching = False
    # Streamed residency (config.client_residency='streamed'): subset
    # re-evaluation consumes the RESIDENT aux['client_params'] stack —
    # overrides the FedAvg-family opt-in; the simulator refuses with
    # the cause.
    supports_streamed_residency = False
    # Mesh capability (ROADMAP item 5): post_round's subset evaluation
    # partitions its vmapped mask-batch axis over a single-host mesh
    # (mesh_devices > 1) with the client stack replicated — subset
    # utilities are independent, so sharding is pure throughput.
    # Multihost keeps the serial evaluator (eval_mesh_devices).
    shards_subset_eval = True

    def __init__(self, config):
        super().__init__(config)
        _check_shapley_config(config)
        if config.worker_number > 16:
            # The ACTUAL client count may be smaller than worker_number
            # (caller-supplied ClientData, ADVICE r4), so the constructor
            # only warns; the hard 2^N refusal fires in check_cohort —
            # still before any training, from make_round_fn (vmap path)
            # and the threaded runner's pre-spawn check.
            get_logger().warning(
                "exact Shapley needs 2^N subset evaluations and "
                "worker_number=%d > 16; this run will be refused at build "
                "time unless the injected client data has <= 16 clients",
                config.worker_number,
            )
        self.shapley_values: dict[int, dict[int, float]] = {}
        self._evaluator = None

    def check_cohort(self, n_clients: int) -> None:
        if n_clients > 16:
            raise ValueError(
                "exact Shapley needs 2^N subset evaluations; "
                f"N={n_clients} > 16. "
                "Use GTG_shapley_value for large client counts."
            )

    def prepare(self, apply_fn, eval_fn):
        self._evaluator = _SubsetEvaluator(
            eval_fn,
            chunk=getattr(self.config, "shapley_eval_chunk", _EVAL_CHUNK),
            eval_dtype=_resolve_eval_dtype(self.config, default="float32"),
            mesh_devices=eval_mesh_devices(self.config),
        )

    def post_round(self, ctx: RoundContext) -> dict:
        n = int(ctx.sizes.shape[0])
        if n > 16:
            # Backstop for non-worker_number client counts (heterogeneous
            # client_data overrides); normally caught in __init__.
            raise ValueError(
                f"exact Shapley needs 2^N subset evaluations; N={n} > 16. "
                "Use GTG_shapley_value for large client counts."
            )
        logger = get_logger()
        round_idx = ctx.round_idx
        threshold = getattr(self.config, "round_trunc_threshold", None)
        metric_now = float(ctx.metrics["accuracy"])
        metric_prev = (
            float(ctx.prev_metrics["accuracy"]) if ctx.prev_metrics else None
        )
        # Round truncation (multiround_shapley_value_server.py:17-32), with
        # the threshold actually plumbed (fixes SURVEY 2.1#9).
        if (
            threshold is not None
            and metric_prev is not None
            and abs(metric_now - metric_prev) <= threshold
        ):
            sv = {i: 0.0 for i in range(n)}
            self.shapley_values[round_idx] = sv
            logger.info("round %d: truncated, shapley values all 0", round_idx)
            return {"shapley_values": sv}

        masks = subset_masks_all(n, include_empty=True)
        utilities_arr = self._evaluator(
            self._evaluator.prepare_stack(ctx.aux["client_params"]),
            ctx.sizes, masks,
            ctx.prev_global_params,
            cap_eval_batches(
                ctx.eval_batches,
                getattr(self.config, "shapley_eval_samples", None),
            ),
        )
        self._evaluator.release_round()
        utilities = {
            frozenset(np.flatnonzero(m).tolist()): float(u)
            for m, u in zip(masks, utilities_arr)
        }
        sv_arr = shapley_from_utilities(utilities, n)
        sv = {i: float(v) for i, v in enumerate(sv_arr)}
        self.shapley_values[round_idx] = sv
        # Artifact parity: pickle per-round subset metrics
        # (multiround_shapley_value_server.py:56-57 writes ./metric_<round>).
        if ctx.log_dir:
            path = os.path.join(ctx.log_dir, f"metric_{round_idx}.pkl")
            with open(path, "wb") as f:
                pickle.dump({tuple(sorted(k)): v for k, v in utilities.items()}, f)
        logger.info("round %d shapley values: %s", round_idx, sv)
        return {
            "shapley_values": sv,
            **_sv_crosscheck_extra(ctx, sv_arr, self.config),
        }


class GTGShapley(FedAvg):
    """GTG-Shapley: Monte-Carlo permutation sampling with guided truncation.

    Parity with servers/GTG_shapley_value_server.py (hyperparameter defaults
    at :11-18): per sampling iteration, one permutation starting with each
    worker (:42-49); within a permutation, prefix utilities are only
    "refreshed" while the running value is at least ``eps`` away from the
    full-aggregation metric (:51-61), with subset metrics memoized across the
    round; convergence when each of the last ``last_k`` running-mean SV
    estimates sits within ``converge_criteria`` relative distance of the
    current estimate (:79-100).
    """

    name = "GTG_shapley_value"
    keep_client_params = True
    supports_round_pipelining = False  # post_round consumes round metrics
    supports_round_batching = False  # same: per-round stacks + metrics
    # Same as MultiRoundShapley: the permutation walk's subset utilities
    # assume a resident per-client stack; streamed residency is refused.
    supports_streamed_residency = False
    # Mesh capability (ROADMAP item 5): permutation walks are
    # independent given the memo, so the walk's prefix waves shard
    # their group axis over a single-host mesh — bit-identical to the
    # serial walk (per-device call shapes are the serial call's; see
    # _SubsetEvaluator). Sharded rounds record the schema-v10 ``gtg``
    # sub-object (devices, evals_per_s, wave width, walk seconds).
    shards_subset_eval = True

    def __init__(self, config):
        super().__init__(config)
        _check_shapley_config(config)
        self.shapley_values: dict[int, dict[int, float]] = {}
        self._evaluator = None
        self.eps = getattr(config, "gtg_eps", 1e-3)
        self.round_trunc_threshold = getattr(config, "round_trunc_threshold", None)
        if self.round_trunc_threshold is None:
            self.round_trunc_threshold = 0.01  # GTG default (:14)
        self.last_k = getattr(config, "gtg_last_k", 10)
        self.converge_criteria = getattr(config, "gtg_converge_criteria", 0.05)
        # None = auto max(500, 2N) at the actual client count (resolved in
        # _effective_cap): one sampling iteration draws N permutations and
        # convergence needs > max(30, N) records, so a cap below 2N can
        # never produce a converged estimate — it silently degrades to a
        # one-iteration Monte-Carlo run (VERDICT r4 weak #2).
        self.max_permutations = getattr(config, "gtg_max_permutations", None)
        # Cross-round subset-utility reuse (config.gtg_cross_round_memo):
        # {cohort crc32 -> the last walk's utility dict}; the latest
        # round's values replace older ones (freshest params win).
        self._memo_store: dict[int, dict] = {}
        self.gtg_memo_hit_rate: float | None = None
        if (
            self.max_permutations is not None
            and self.max_permutations < config.worker_number
        ):
            get_logger().warning(
                "gtg_max_permutations=%d < worker_number=%d: one sampling "
                "iteration draws one permutation per client, so the cap "
                "would be exceeded before it is ever checked; this run "
                "will be refused at build time unless the actual client "
                "count is <= the cap",
                self.max_permutations, config.worker_number,
            )
        self._rng = np.random.default_rng(getattr(config, "seed", 0) + 17)

    def check_cohort(self, n_clients: int) -> None:
        if self.max_permutations is None:
            return
        # Convergence needs MORE than max(30, N, last_k) marginal records
        # (one per permutation, _converged's gate), and one sampling
        # iteration draws N permutations.
        converge_floor = max(30, n_clients, self.last_k)
        if self.max_permutations < n_clients:
            raise ValueError(
                f"gtg_max_permutations={self.max_permutations} < "
                f"N={n_clients}: one GTG sampling iteration draws N "
                "permutations (one starting with each worker), so this "
                "cap cannot be honored — raise it to >= "
                f"{n_clients} (> {converge_floor} for a convergence-"
                "capable run) or leave it unset for auto max(500, 2N)"
            )
        if self.max_permutations <= converge_floor and not getattr(
            self, "_warned_mc_budget", False
        ):
            # Honorable but convergence can never fire: an explicit
            # small budget is a legitimate fixed-cost Monte-Carlo run —
            # allow it, but say what it is. (check_cohort runs from both
            # the simulator and make_round_fn — warn once.)
            self._warned_mc_budget = True
            get_logger().warning(
                "gtg_max_permutations=%d <= max(30, N=%d, last_k=%d): the "
                "convergence test needs more records than that, so every "
                "round will report a fixed-budget Monte-Carlo estimate "
                "with converged=False",
                self.max_permutations, n_clients, self.last_k,
            )

    def _effective_cap(self, n_clients: int) -> int:
        if self.max_permutations is not None:
            return self.max_permutations
        return max(500, 2 * n_clients)

    def prepare(self, apply_fn, eval_fn):
        self._evaluator = _SubsetEvaluator(
            eval_fn,
            chunk=getattr(self.config, "shapley_eval_chunk", _EVAL_CHUNK),
            eval_dtype=_resolve_eval_dtype(self.config, default="bfloat16"),
            mesh_devices=eval_mesh_devices(self.config),
        )

    def _converged(self, records: list[np.ndarray], n: int) -> bool:
        # Thin delegate: the convergence rule lives in _gtg_converged so
        # gtg_walk (and the valuation auditor riding it) shares it.
        return _gtg_converged(records, n, self.last_k, self.converge_criteria)

    def post_round(self, ctx: RoundContext) -> dict:
        n = int(ctx.sizes.shape[0])
        logger = get_logger()
        round_idx = ctx.round_idx
        metric_now = float(ctx.metrics["accuracy"])
        metric_prev = (
            float(ctx.prev_metrics["accuracy"]) if ctx.prev_metrics else None
        )
        if (
            metric_prev is not None
            and abs(metric_now - metric_prev) <= self.round_trunc_threshold
        ):
            sv = {i: 0.0 for i in range(n)}
            self.shapley_values[round_idx] = sv
            logger.info("round %d: truncated, shapley values all 0", round_idx)
            return {"shapley_values": sv, "gtg_permutations": 0}

        t_walk = time.perf_counter()
        client_params = self._evaluator.prepare_stack(ctx.aux["client_params"])
        # Cross-round memo (config.gtg_cross_round_memo, ROADMAP item 4b):
        # seed this round's subset-utility memo from the last round with
        # the SAME cohort (GTG requires full participation, so the cohort
        # — and its hash — is constant across rounds). Off (the default)
        # keeps the exact pre-feature per-round memo. Reused utilities
        # describe the earlier round's params (SubsetMemo docstring);
        # the recorded hit rate measures how much was reused.
        cohort_key = cohort_crc(None, n)
        cross_round = bool(
            getattr(self.config, "gtg_cross_round_memo", False)
        )
        seed = self._memo_store.get(cohort_key) if cross_round else None
        if seed:
            # The empty and grand coalitions anchor the walk (every
            # v_prev chain and the eps-truncation reference) — always
            # re-evaluate them against THIS round's params; only interior
            # subsets are reuse candidates.
            seed = {
                k: v for k, v in seed.items() if 0 < len(k) < n
            }
        memo = SubsetMemo(seed)
        eval_batches = cap_eval_batches(
            ctx.eval_batches,
            getattr(self.config, "shapley_eval_samples", None),
        )

        def utilities_for(masks_sets: list[frozenset]) -> None:
            eval_subsets(
                self._evaluator, client_params, ctx.sizes,
                ctx.prev_global_params, eval_batches, n, memo, masks_sets,
            )

        utilities_for([frozenset()])  # u(empty) = prev-global metric
        # eps-truncation reference: "running value close to the full-
        # aggregation metric" (:51-61). With shapley_eval_samples the
        # subset utilities come from a SUBSAMPLED estimator whose grand-
        # coalition value differs from the full-set round metric by
        # subsample noise >> eps — comparing across estimators would make
        # truncation fire never (or spuriously). The same cross-estimator
        # mismatch exists when the evaluator reads a non-f32 stack (ADVICE
        # r5): the bf16 estimator's grand-coalition utility sits bf16
        # rounding (~1e-3, the scale of eps itself) away from the f32
        # round metric. In either case use the grand-coalition utility
        # from the SAME estimator as the walked prefixes.
        if (
            getattr(self.config, "shapley_eval_samples", None) is not None
            or self._evaluator.eval_dtype != jnp.float32
        ):
            grand = frozenset(range(n))
            utilities_for([grand])
            trunc_ref = memo[grand]
        else:
            trunc_ref = metric_now
        cap = self._effective_cap(n)
        if cap < n:
            # Reachable only when post_round is driven without the build-
            # time check_cohort (direct API use); same semantics problem,
            # surfaced loudly instead of silently overrunning the cap.
            logger.warning(
                "gtg_max_permutations=%d < N=%d: the first sampling "
                "iteration alone draws N permutations; the cap will be "
                "exceeded and convergence cannot fire", cap, n,
            )
        # The walk itself — permutation sampling, shared waves,
        # eps-truncation, convergence — is module-level ``gtg_walk``
        # (shared verbatim with the valuation auditor,
        # telemetry/valuation.py). Prefix-aggregation mode
        # (config.gtg_prefix_mode): 'cumsum' (the default) streams each
        # permutation's weighted running sum block by block; 'masked' is
        # the per-prefix mask-weighted oracle
        # (tests/test_shapley.py::test_gtg_prefix_mode_equivalence). Both
        # modes share the RNG stream, the wave structure, the memo, and
        # the truncation/marginal bookkeeping, so a fixed seed yields the
        # same permutations and — utilities agreeing — identical records.
        sv_arr, n_perms, converged = gtg_walk(
            self._evaluator, client_params, ctx.sizes,
            ctx.prev_global_params, eval_batches, n, self._rng,
            eps=self.eps, cap=cap, last_k=self.last_k,
            converge_criteria=self.converge_criteria, trunc_ref=trunc_ref,
            prefix_mode=getattr(self.config, "gtg_prefix_mode", "cumsum"),
            memo=memo,
        )
        walk_seconds = time.perf_counter() - t_walk
        self._evaluator.release_round()
        sv = {i: float(v) for i, v in enumerate(sv_arr)}
        self.shapley_values[round_idx] = sv
        memo_extra = {}
        if self._evaluator.devices > 1:
            # Mesh-sharded walk provenance: the schema-v10 ``gtg``
            # sub-object (the simulator routes it through the shared
            # record builder). Attached ONLY when the walk actually
            # sharded, so serial GTG runs keep their pre-feature records
            # byte-identical — the established off-gate discipline.
            memo_extra["gtg"] = {
                "devices": self._evaluator.devices,
                "evals_per_s": (
                    round(memo.evaluated / walk_seconds, 1)
                    if walk_seconds > 0 and memo.evaluated else None
                ),
                # Walk parallelism: subset models per fused evaluator
                # call, partitioned over the devices (the serial chunk's
                # envelope per device).
                "wave_width": self._evaluator.call_width,
                "walk_seconds": round(walk_seconds, 3),
            }
        if cross_round:
            self._memo_store[cohort_key] = dict(memo)
            self.gtg_memo_hit_rate = memo.hit_rate()
            if self.gtg_memo_hit_rate is not None:
                # ROADMAP item 4b's tracked number: what fraction of this
                # walk's subset utilities earlier rounds already paid for.
                memo_extra["gtg_memo_hit_rate"] = round(
                    self.gtg_memo_hit_rate, 4
                )
        if ctx.log_dir:
            path = os.path.join(ctx.log_dir, f"metric_{round_idx}.pkl")
            with open(path, "wb") as f:
                pickle.dump(
                    {tuple(sorted(k)): v for k, v in memo.items()}, f
                )
        logger.info(
            "round %d shapley values (GTG, %d permutations, %d subset evals, "
            "converged=%s): %s",
            round_idx, n_perms, memo.evaluated, converged, sv,
        )
        return {
            "shapley_values": sv,
            "gtg_permutations": n_perms,
            # Evaluations THIS round paid for: cross-round memo hits are
            # excluded (they are the saving, not the cost); equals the
            # memo size exactly when gtg_cross_round_memo is off.
            "gtg_subset_evals": memo.evaluated,
            # Tracked by bench.py's gtg leg / scripts/measure_gtg_scale.py:
            # a converged round is the honest cost unit (a fixed-budget
            # Monte-Carlo round is cheaper but a different estimator).
            "gtg_converged": converged,
            **memo_extra,
            **_sv_crosscheck_extra(ctx, sv_arr, self.config),
        }
