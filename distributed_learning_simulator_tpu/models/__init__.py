from distributed_learning_simulator_tpu.models.registry import (
    get_model,
    registered_models,
    init_params,
)

__all__ = ["get_model", "registered_models", "init_params"]
