"""LeNet-5 for 28x28 grayscale inputs.

The reference's canonical smoke-test model (``--model_name LeNet5`` in
reference simulator.sh:1, provided there by the external model registry).
NHWC layout, ReLU activations, bfloat16-friendly conv/dense sizes.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class LeNet5(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(features=6, kernel_size=(5, 5), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, window_shape=(2, 2), strides=(2, 2))
        x = nn.Conv(features=16, kernel_size=(5, 5), padding="VALID")(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, window_shape=(2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(features=120)(x)
        x = nn.relu(x)
        x = nn.Dense(features=84)(x)
        x = nn.relu(x)
        x = nn.Dense(features=self.num_classes)(x)
        return x.astype(jnp.float32)
