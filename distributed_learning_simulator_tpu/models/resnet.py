"""ResNet-18 with GroupNorm, NHWC, for 32x32 inputs.

Flagship model for the scale config "non-IID Dirichlet(0.1), 1000 clients,
ResNet-18" (BASELINE.json configs[4]). Deliberate TPU/FL design choice:
GroupNorm instead of BatchNorm — BatchNorm's running statistics are mutable
non-parameter state that (a) breaks the pure client-stacked-params discipline
under ``vmap`` and (b) is known to degrade under federated averaging of
per-client statistics; GroupNorm keeps the model a pure function of params.
Convs run in bfloat16 on the MXU; logits returned float32.

W-folded stage 1 (federated-vmap TPU layout): 64-channel tensors tile
(8, 128) with the lane dim padded 64 -> 128 — 2x HBM inflation on exactly
the stage that dominates the per-client-weights round (flagship profile:
64-ch ops moved 423 GiB at ~278 GB/s vs ~660 GB/s for 128+-ch ops).
Folding W-pairs into channels — ``[B, H, W, 64] -> [B, H, W/2, 128]``, a
PURE reshape of the trailing dims — fills the lanes. A stride-1 3x3 conv
on the folded form is a 3x3 conv with a packed kernel built from the
ordinary ``[3, 3, cin, cout]`` parameter by six static slice-assignments
(:func:`pack_folded_kernel`; 50% fill -> 2x MXU FLOPs, paid from idle MXU
capacity since the op is bandwidth-bound). The math is exact (the packing
transpose discards zero-slot gradients), parameters are identical to the
unfolded model, and GroupNorm statistics are computed on the unfolded
VIEW (a fused reshape). Measured fwd+bwd per conv at chunk 40 x batch 25:
88 -> 10.6 ms isolated (scripts/exp_folded_conv.py); whole-round effect in
docs/PERFORMANCE.md.

Round-5 negative results (kept so nobody re-tries them): (1) re-orienting
the folded stage HWNC (batch second-minor, so the standard layout matches
the conv backend's preferred {3,0,2,1}) measured 3.7x faster on an
ISOLATED stage-1 block chain (scripts/exp_stage1_layout.py) but made the
real sign_SGD round 7% SLOWER (2.72 -> 2.91 s) while leaving the bf16
fed/fed_quant rounds flat — in context the round's other consumers
re-introduce relayouts elsewhere. (2) `lax.optimization_barrier` between
conv outputs and the GroupNorm f32 convert (to stop XLA writing conv
outputs f32 via `convolution_convert_fusion` epilogues and re-reading
them at 2x bytes in the wgrad fusions) costs more fusion than it saves:
2.72 -> 3.17 s. Only in-context measurement is valid evidence here (the
round-3 tap-einsum lesson, re-learned twice).
"""

from __future__ import annotations

import functools
import os
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from distributed_learning_simulator_tpu.ops.gn_pallas import pallas_group_norm


# Read ONCE at import (ADVICE r5): the flag selects which GroupNorm forward
# gets COMPILED into the round program, so flipping the env var after the
# first compile could not take effect anyway — the jit cache would keep
# serving the stale path silently. A module constant makes the
# first-read-wins semantics explicit; in-process tests that genuinely need
# both kernels toggle the constant itself (test_folded_resnet.py).
_GN_PALLAS_ENABLED = os.environ.get("DLS_GN_PALLAS", "0") == "1"


def _use_pallas_gn() -> bool:
    """Opt-in Pallas GroupNorm forward (``DLS_GN_PALLAS=1``, TPU only).

    MEASURED NEGATIVE RESULT (round 5): the kernels (ops/gn_pallas.py)
    do exactly what the trace analysis asked for — the conv emits bf16
    (a Pallas call is an opaque consumer, so XLA cannot fuse the stats'
    f32 convert into the conv epilogue), stats read the activations once
    with in-register converts, normalize reads them once more — and the
    REAL rounds got slower anyway: sign_SGD 2.72 -> 3.37 s/round, fed
    flagship 2.22 -> 2.84. The f32-activation "tax" the jnp path pays is
    XLA's price for fusing normalize/relu/residual/wgrad-recompute into
    neighboring ops, and that fusion is worth more than the saved
    bytes. Third structural attack on the stage-1 f32 sharing (after
    HWNC orientation and optimization_barrier, module docstring), third
    in-context rejection — the jnp path stands as the measured floor."""
    if not _GN_PALLAS_ENABLED:
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # backend not initialized yet
        return False


def pack_folded_kernel(w):
    """``[3, 3, cin, cout] -> [3, 3, 2cin, 2cout]`` for the W-folded conv.

    Output fold position ``sx`` and input fold position ``tx``: an original
    tap ``dx`` at output column ``2J+sx`` reads input column
    ``2J + (sx+dx-1) = 2(J+V) + tx`` — six (sx, dx) placements, zeros
    elsewhere. Exact; autodiff's transpose scatters gradients back to the
    six slots and discards the zero slots.
    """
    cin, cout = w.shape[2], w.shape[3]
    zero = jnp.zeros((3, cin, cout), w.dtype)

    # Trailing-dim block assembly ONLY (concat over the ci/co axes, stack
    # over the leading tap axis): an .at[].set build lowers to ~20 GB/s
    # dynamic-update-slice chains, and a stack+6D-transpose materializes
    # the full packed tensor twice — both measured as real round costs.
    def tap(v, tx, sx):
        dx = 2 * v + tx - sx + 1
        return w[:, dx] if 0 <= dx <= 2 else zero

    vs = []
    for v in (-1, 0, 1):
        rows = [
            jnp.concatenate([tap(v, tx, 0), tap(v, tx, 1)], axis=-1)
            for tx in range(2)
        ]
        vs.append(jnp.concatenate(rows, axis=-2))  # [3(dy), 2cin, 2cout]
    return jnp.stack(vs, axis=1)  # [3(dy), 3(v), 2cin, 2cout]


def pack_folded_stride2_kernel(w):
    """``[3, 3, cin, cout] -> [3, 2, 2cin, cout]``: stride-2 3x3 conv
    consuming the folded layout, producing the UNFOLDED downsampled map.

    SAME padding at stride 2 pads (low 0, high 1), so unfolded output
    column j reads input columns ``2j+dx = 2(j+V)+tx``, V in {0, 1}: a
    (3, 2)-tap conv on folded cols with strides (2, 1) and explicit
    padding ((0, 1), (0, 1)). 3 of 4 (V, tx) slots are live.
    """
    cin, cout = w.shape[2], w.shape[3]
    zero = jnp.zeros((3, cin, cout), w.dtype)

    def tap(v, tx):
        dx = 2 * v + tx
        return w[:, dx] if 0 <= dx <= 2 else zero

    vs = [
        jnp.concatenate([tap(v, 0), tap(v, 1)], axis=-2)  # [3, 2cin, cout]
        for v in (0, 1)
    ]
    return jnp.stack(vs, axis=1)  # [3(dy), 2(v), 2cin, cout]


def pack_folded_pointwise_stride2(w):
    """``[1, 1, cin, cout] -> [1, 1, 2cin, cout]``: the 1x1 stride-2
    projection reads only even columns = the tx=0 half of a folded pixel."""
    return jnp.concatenate([w, jnp.zeros_like(w)], axis=2)


def pack_folded_stem_kernel(w):
    """``[3, 3, cin, cout] -> [3, 4, cin, 2cout]``: stride-1 SAME 3x3 conv
    on the UNFOLDED input emitting the FOLDED layout directly.

    Folded output pixel (J, tx in {0, 1}) holds unfolded column 2J+tx in
    channel block tx*cout; tap dx reads input column 2J + (tx+dx-1) =
    2J + (k-1) with k = tx+dx in {0..3} — a (3, 4)-tap conv at column
    stride 2 with explicit (1, 1) column padding. Six live placements in
    twelve slots; with it, no unfolded stage-1 activation ever
    materializes (the fold 'reshape' at the stem boundary is physically a
    relayout copy, and its f32 GroupNorm-backward intermediates were
    measured at 348-420 GB/s on lane-padded [.., W, 64] tensors —
    docs/PERFORMANCE.md round 4)."""
    zero = jnp.zeros(w.shape[:1] + w.shape[2:], w.dtype)  # [3, cin, cout]

    def tap(k, tx):
        dx = k - tx
        return w[:, dx] if 0 <= dx <= 2 else zero

    ks = [
        jnp.concatenate([tap(k, 0), tap(k, 1)], axis=-1)  # [3, cin, 2cout]
        for k in range(4)
    ]
    return jnp.stack(ks, axis=1)  # [3(ky), 4(k), cin, 2cout]


class FoldedStemConv(nn.Module):
    """CIFAR stem conv producing the W-folded stage-1 layout directly.

    The parameter is the ordinary unfolded ``[3, 3, cin, features]`` kernel
    under the same auto-name/shape/init as the ``nn.Conv`` stem it replaces
    (instantiate with ``name="Conv_0"`` for checkpoint-identical trees)."""

    features: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (3, 3, x.shape[-1], self.features), jnp.float32,
        )
        wp = pack_folded_stem_kernel(kernel.astype(self.dtype))
        return jax.lax.conv_general_dilated(
            x.astype(self.dtype), wp, (1, 2), ((1, 1), (1, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )


class FoldedConv3x3(nn.Module):
    """Stride-1 SAME 3x3 conv on the W-folded layout ``[B, H, W/2, 2cin]``.

    The parameter is the ordinary unfolded ``[3, 3, cin, cout]`` kernel
    (same name/shape/init as ``nn.Conv``); packing happens per forward.
    """

    features: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, xf):
        cin = xf.shape[-1] // 2
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (3, 3, cin, self.features), jnp.float32,
        )
        wp = pack_folded_kernel(kernel.astype(self.dtype))
        return jax.lax.conv_general_dilated(
            xf.astype(self.dtype), wp, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )


def _fgn_forward(xf, scale, bias, g: int, eps: float, out_dtype):
    """Folded-layout GroupNorm forward; returns (y, mean, rstd).

    Coefficient form (round 5): the normalize is ``y = x*a + b`` with
    per-(sample, tx, group) f32 coefficients folded from
    (mean, rstd, scale, bias) — so the only big-tensor consumers are ONE
    inline-convert stats reduce and ONE bf16-in/bf16-out elementwise
    pass. The earlier ``((x - mean) * rstd) * scale + bias`` form made
    XLA materialize a relayouted f32 copy of every stage-1 GN input
    (resnet.py:175 in the r4 HLO): the copy itself cost ~0.6 ms/use and
    the conv weight-grad fusions then re-read activations at f32 (2x)
    bytes — together ~20% of the sign_SGD round (measured, HLO-verified:
    the copies' consumers were the transpose(jvp) conv wgrad fusions).
    """
    b, h, wf, c2 = xf.shape
    c = c2 // 2
    cpg = c // g
    if _use_pallas_gn():
        y, mean_g, rstd_g = pallas_group_norm(
            xf, jnp.tile(scale, 2), jnp.tile(bias, 2), g, eps, out_dtype,
            folds=2,
        )
        return (
            y,
            mean_g.reshape(b, 1, 1, 1, g, 1),
            rstd_g.reshape(b, 1, 1, 1, g, 1),
        )
    x6 = xf.reshape(b, h, wf, 2, g, cpg)
    x32 = x6.astype(jnp.float32)
    # One-pass statistics (E[x^2] - E[x]^2, flax's use_fast_variance):
    # the two-pass (x - mean)^2 form reads the activations twice and
    # measurably halves this fusion's effective bandwidth. (An
    # indicator-matrix matmul formulation of the group reduction was
    # also tried — identical round time, so the simpler form stays.)
    mean = jnp.mean(x32, axis=(1, 2, 3, 5), keepdims=True)
    mean2 = jnp.mean(jnp.square(x32), axis=(1, 2, 3, 5), keepdims=True)
    var = jnp.maximum(mean2 - jnp.square(mean), 0.0)
    rstd = jax.lax.rsqrt(var + eps)
    scale2 = jnp.tile(scale, 2).reshape(2, g, cpg)
    bias2 = jnp.tile(bias, 2).reshape(2, g, cpg)
    a = rstd * scale2          # [b, 1, 1, 2, g, cpg] — b x 2c floats
    # Subtract-first, then one multiply: folding mean into the additive
    # coefficient (y = x*a + (bias - mean*a)) cancels catastrophically
    # when |x - mean| << |x| (measured: 1% stem-wgrad error at f32).
    y = ((x6 - mean) * a + bias2).astype(out_dtype).reshape(b, h, wf, c2)
    return y, mean, rstd


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _folded_group_norm(xf, scale, bias, g: int, eps: float, out_dtype):
    return _fgn_forward(xf, scale, bias, g, eps, out_dtype)[0]


def _fgn_fwd(xf, scale, bias, g, eps, out_dtype):
    y, mean, rstd = _fgn_forward(xf, scale, bias, g, eps, out_dtype)
    # bias rides along only for its dtype (cotangents must match primal
    # dtypes); it is a [C] vector, so the residual cost is nil.
    return y, (xf, scale, bias, mean, rstd)


def _fgn_bwd(g: int, eps: float, out_dtype, res, dy):
    """Canonical closed-form GN backward, two activation passes.

    XLA autodiff of the E[x^2]-E[x]^2 forward emits a chain of separate
    stat reduces over the stage-1 activations (measured 243 GB/s,
    ~211 ms/round on the flagship — docs/PERFORMANCE.md round 4); the
    closed form needs one fused reduce pass (m1, m2, dscale, dbias share
    the same two inputs) and one elementwise pass for dx:

      dx = rstd * (dy*scale - mean_grp(dy*scale)
                   - xhat * mean_grp(dy*scale * xhat))
    """
    xf, scale, bias, mean, rstd = res
    b, h, wf, c2 = xf.shape
    c = c2 // 2
    cpg = c // g
    x6 = xf.reshape(b, h, wf, 2, g, cpg)
    dy6 = dy.reshape(b, h, wf, 2, g, cpg)
    x32 = x6.astype(jnp.float32)
    dy32 = dy6.astype(jnp.float32)
    scale2 = jnp.tile(scale, 2).reshape(2, g, cpg)
    xhat = (x32 - mean) * rstd
    dyg = dy32 * scale2
    m1 = jnp.mean(dyg, axis=(1, 2, 3, 5), keepdims=True)
    m2 = jnp.mean(dyg * xhat, axis=(1, 2, 3, 5), keepdims=True)
    # The dx pass re-reads dy6/x6 directly (xhat recomputed in-register
    # from the bf16 x6) with per-(sample, group) f32 coefficients — no
    # materialized f32 xhat/dyg shared with the reduces (see
    # _fgn_forward's rationale). Same subtract-first numerics as the old
    # form; only the read dtype of the big tensors changed.
    dx = ((dyg - m1 - xhat * m2) * rstd).astype(xf.dtype)
    dx = dx.reshape(b, h, wf, c2)
    # Per-channel param grads: both tx placements of channel c accumulate
    # (sum over the tx axis of the [g, cpg] reduce). Cotangent dtypes must
    # match the incoming params' dtypes (bf16 when the engine runs
    # local_compute_dtype=bfloat16).
    dscale = jnp.sum(dy32 * xhat, axis=(0, 1, 2, 3))
    dscale = dscale.reshape(c).astype(scale.dtype)
    dbias = jnp.sum(dy32, axis=(0, 1, 2, 3)).reshape(c).astype(bias.dtype)
    return dx, dscale, dbias


_folded_group_norm.defvjp(_fgn_fwd, _fgn_bwd)


def _gn_forward(x, scale, bias, g: int, eps: float, out_dtype):
    """Unfolded NHWC GroupNorm forward; returns (y, mean, rstd).

    Same coefficient form as :func:`_fgn_forward` (y = x*a + b with small
    per-(sample, group) f32 coefficients): the activations are read in
    their stored dtype by exactly one reduce and one elementwise pass, so
    no relayouted f32 activation copy materializes for the conv
    weight-grad recompute to re-read at 2x bytes."""
    b, h, w, c = x.shape
    cpg = c // g
    if _use_pallas_gn():
        y, mean_g, rstd_g = pallas_group_norm(
            x, scale, bias, g, eps, out_dtype, folds=1,
        )
        return (
            y,
            mean_g.reshape(b, 1, 1, g, 1),
            rstd_g.reshape(b, 1, 1, g, 1),
        )
    x5 = x.reshape(b, h, w, g, cpg)
    x32 = x5.astype(jnp.float32)
    mean = jnp.mean(x32, axis=(1, 2, 4), keepdims=True)
    mean2 = jnp.mean(jnp.square(x32), axis=(1, 2, 4), keepdims=True)
    var = jnp.maximum(mean2 - jnp.square(mean), 0.0)
    rstd = jax.lax.rsqrt(var + eps)
    scale5 = scale.reshape(g, cpg)
    a = rstd * scale5
    # Subtract-first (same rationale as _fgn_forward): folding mean into
    # the additive coefficient cancels catastrophically when
    # |x - mean| << |x|.
    y = ((x5 - mean) * a + bias.reshape(g, cpg)).astype(out_dtype)
    y = y.reshape(b, h, w, c)
    return y, mean, rstd


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _plain_group_norm(x, scale, bias, g: int, eps: float, out_dtype):
    return _gn_forward(x, scale, bias, g, eps, out_dtype)[0]


def _pgn_fwd(x, scale, bias, g, eps, out_dtype):
    y, mean, rstd = _gn_forward(x, scale, bias, g, eps, out_dtype)
    return y, (x, scale, bias, mean, rstd)


def _pgn_bwd(g: int, eps: float, out_dtype, res, dy):
    """Closed-form GN backward for the unfolded layout (same derivation
    as :func:`_fgn_bwd`, without the tx fold)."""
    x, scale, bias, mean, rstd = res
    b, h, w, c = x.shape
    cpg = c // g
    x5 = x.reshape(b, h, w, g, cpg)
    dy5 = dy.reshape(b, h, w, g, cpg)
    x32 = x5.astype(jnp.float32)
    dy32 = dy5.astype(jnp.float32)
    scale5 = scale.reshape(g, cpg)
    xhat = (x32 - mean) * rstd
    dyg = dy32 * scale5
    m1 = jnp.mean(dyg, axis=(1, 2, 4), keepdims=True)
    m2 = jnp.mean(dyg * xhat, axis=(1, 2, 4), keepdims=True)
    # Same subtract-first numerics as _fgn_bwd: bf16 reads, f32 register
    # math, xhat recomputed in-register rather than folding mean into an
    # additive coefficient (cancellation — see _fgn_forward).
    dx = ((dyg - m1 - xhat * m2) * rstd).astype(x.dtype).reshape(b, h, w, c)
    dscale = jnp.sum(dy32 * xhat, axis=(0, 1, 2)).reshape(c).astype(scale.dtype)
    dbias = jnp.sum(dy32, axis=(0, 1, 2)).reshape(c).astype(bias.dtype)
    return dx, dscale, dbias


_plain_group_norm.defvjp(_pgn_fwd, _pgn_bwd)


class PlainGroupNorm(nn.Module):
    """GroupNorm with the closed-form backward (:func:`_pgn_bwd`).

    Replaces ``nn.GroupNorm`` in the unfolded blocks — same parameter
    names/shapes/init (instantiate with ``name="GroupNorm_N"`` to keep
    flax auto-named trees identical), same one-pass E[x^2]-E[x]^2
    statistics. Numerics: f32-exact against flax; under bf16 the affine
    is applied in f32 and cast ONCE at the output (flax casts operands to
    bf16 first), so bf16 outputs agree within an output ulp rather than
    bitwise — tests/test_folded_resnet.py covers both. Exists because XLA
    autodiff of the statistics emits separate VPU-bound stat-reduce
    passes per GroupNorm (docs/PERFORMANCE.md round 4);
    ``custom_backward=False`` restores autodiff of the same forward.
    """

    num_groups: int
    dtype: jnp.dtype = jnp.bfloat16
    epsilon: float = 1e-6
    custom_backward: bool = True

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        if c % self.num_groups:
            # nn.GroupNorm raises this clearly at call time; keep the
            # clear error rather than a reshape failure inside jit.
            raise ValueError(
                f"number of groups ({self.num_groups}) must divide the "
                f"channel count ({c})"
            )
        scale = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        if self.custom_backward:
            return _plain_group_norm(
                x, scale, bias, self.num_groups, self.epsilon, self.dtype
            )
        y, _, _ = _gn_forward(
            x, scale, bias, self.num_groups, self.epsilon, self.dtype
        )
        return y


class FoldedGroupNorm(nn.Module):
    """GroupNorm computed directly ON the folded layout.

    GroupNorm over folded channels naively would pool the two folded
    columns' channel ranges into wrong groups. Unfolding for an inner
    ``nn.GroupNorm`` is correct but breaks XLA fusion at the reshape
    boundary (measured: the stats re-read the activations as separate
    ~380 GB/s reduces, ~0.5 s/round). Instead: folded channel
    ``c' = tx*C + g*cpg + i``, so a trailing-dim reshape to
    ``[.., 2(tx), G, cpg]`` exposes the group axis and the statistics
    reduce over ``(H, Wf, tx, cpg)`` — same elements as the unfolded
    norm, never leaving the folded layout. scale/bias are per-channel
    ``[C]`` (identical to ``nn.GroupNorm``'s params), tiled across tx.
    The backward is the hand-written closed form (:func:`_fgn_bwd`);
    ``custom_backward=False`` restores plain autodiff.
    """

    num_groups: int
    dtype: jnp.dtype = jnp.bfloat16
    epsilon: float = 1e-6
    custom_backward: bool = True

    @nn.compact
    def __call__(self, xf):
        c = xf.shape[-1] // 2
        if c % self.num_groups:
            raise ValueError(
                f"number of groups ({self.num_groups}) must divide the "
                f"channel count ({c})"
            )
        scale = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        if self.custom_backward:
            return _folded_group_norm(
                xf, scale, bias, self.num_groups, self.epsilon, self.dtype
            )
        y, _, _ = _fgn_forward(
            xf, scale, bias, self.num_groups, self.epsilon, self.dtype
        )
        return y


class FoldedResidualBlock(nn.Module):
    """Stage-1 basic block on the W-folded layout (stride 1, no
    projection — exactly the shape regime where folding applies)."""

    features: int
    dtype: jnp.dtype = jnp.bfloat16
    gn_custom_backward: bool = True

    @nn.compact
    def __call__(self, xf):
        residual = xf
        y = FoldedConv3x3(self.features, dtype=self.dtype)(xf)
        y = FoldedGroupNorm(
            num_groups=min(32, self.features), dtype=self.dtype,
            custom_backward=self.gn_custom_backward,
        )(y)
        y = nn.relu(y)
        y = FoldedConv3x3(self.features, dtype=self.dtype)(y)
        y = FoldedGroupNorm(
            num_groups=min(32, self.features), dtype=self.dtype,
            custom_backward=self.gn_custom_backward,
        )(y)
        return nn.relu(y + residual)


class FoldedTransitionBlock(nn.Module):
    """Stage-2 entry block (stride-2, with projection shortcut) consuming
    the FOLDED stage-1 output directly: the stride-2 convs read folded
    (lane-full) inputs and produce the unfolded downsampled map, so the
    explicit unfold reshape — and the padded stride-2 convs on
    ``[.., 32, 32, 64]`` it fed — disappear entirely."""

    features: int
    dtype: jnp.dtype = jnp.bfloat16
    gn_custom_backward: bool = True

    @nn.compact
    def __call__(self, xf):
        cin = xf.shape[-1] // 2
        w1 = self.param(
            "conv1_kernel", nn.initializers.lecun_normal(),
            (3, 3, cin, self.features), jnp.float32,
        )
        y = jax.lax.conv_general_dilated(
            xf.astype(self.dtype),
            pack_folded_stride2_kernel(w1.astype(self.dtype)),
            (2, 1), ((0, 1), (0, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        y = PlainGroupNorm(
            num_groups=min(32, self.features), dtype=self.dtype,
            name="GroupNorm_0",
            custom_backward=self.gn_custom_backward,
        )(y)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(y)
        y = PlainGroupNorm(
            num_groups=min(32, self.features), dtype=self.dtype,
            name="GroupNorm_1",
            custom_backward=self.gn_custom_backward,
        )(y)
        wp = self.param(
            "proj_kernel", nn.initializers.lecun_normal(),
            (1, 1, cin, self.features), jnp.float32,
        )
        residual = jax.lax.conv_general_dilated(
            xf.astype(self.dtype),
            pack_folded_pointwise_stride2(wp.astype(self.dtype)),
            (2, 1), ((0, 0), (0, 0)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        residual = PlainGroupNorm(
            num_groups=min(32, self.features), dtype=self.dtype,
            name="GroupNorm_2",
            custom_backward=self.gn_custom_backward,
        )(residual)
        return nn.relu(y + residual)


class ResidualBlock(nn.Module):
    features: int
    strides: int = 1
    dtype: jnp.dtype = jnp.bfloat16
    gn_custom_backward: bool = True

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(
            self.features, (3, 3), strides=(self.strides, self.strides),
            padding="SAME", use_bias=False, dtype=self.dtype,
        )(x)
        y = PlainGroupNorm(num_groups=min(32, self.features),
                           dtype=self.dtype, name="GroupNorm_0",
                           custom_backward=self.gn_custom_backward)(y)
        y = nn.relu(y)
        y = nn.Conv(
            self.features, (3, 3), padding="SAME", use_bias=False, dtype=self.dtype
        )(y)
        y = PlainGroupNorm(num_groups=min(32, self.features),
                           dtype=self.dtype, name="GroupNorm_1",
                           custom_backward=self.gn_custom_backward)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.features, (1, 1), strides=(self.strides, self.strides),
                use_bias=False, dtype=self.dtype,
            )(residual)
            residual = PlainGroupNorm(
                num_groups=min(32, self.features), dtype=self.dtype,
                name="GroupNorm_2",
                custom_backward=self.gn_custom_backward,
            )(residual)
        return nn.relu(y + residual)


class ResNet18(nn.Module):
    """Generic basic-block ResNet; default stage sizes give ResNet-18."""

    num_classes: int = 10
    stage_sizes: Sequence[int] = (2, 2, 2, 2)
    width: int = 64
    dtype: jnp.dtype = jnp.bfloat16
    # W-folded stage 1 (module docstring): lane-filling layout for the
    # 64-channel stage. Identical parameters and math; only the compute
    # layout changes. Applicable when the stage is stride-1 at width 64
    # with an even spatial W — the CIFAR-style configuration.
    fold_stage1: bool = True
    # Closed-form GroupNorm backward (custom_vjp) throughout; False
    # restores XLA autodiff of the same forward. Escape hatch reachable
    # via --model_args '{"gn_custom_backward": false}'.
    gn_custom_backward: bool = True

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        # Fold applicability: stage 0 is stride-1 at width 64 with even
        # spatial dims (even W: the fold pairs columns; even H: the
        # transition block's stride-2 row taps assume SAME's (0, 1)
        # padding). The stem preserves spatial dims, so the input decides.
        fold_ok = (
            self.fold_stage1
            and self.width == 64
            and x.shape[1] % 2 == 0
            and x.shape[2] % 2 == 0
        )
        # CIFAR-style stem (3x3, no initial downsample) for 32x32 inputs.
        # When folding, the stem itself emits the folded layout (name= pins
        # keep the parameter tree identical to the unfolded stem's): no
        # unfolded 64-channel activation — nor its lane-padded
        # GroupNorm-backward intermediates — ever materializes.
        folded = False
        if fold_ok:
            x = FoldedStemConv(
                self.width, dtype=self.dtype, name="Conv_0"
            )(x)
            x = FoldedGroupNorm(
                num_groups=min(32, self.width), dtype=self.dtype,
                name="GroupNorm_0",
                custom_backward=self.gn_custom_backward,
            )(x)
            x = nn.relu(x)
            folded = True
        else:
            x = nn.Conv(self.width, (3, 3), padding="SAME", use_bias=False,
                        dtype=self.dtype)(x)
            x = PlainGroupNorm(
                num_groups=min(32, self.width), dtype=self.dtype,
                name="GroupNorm_0",
                custom_backward=self.gn_custom_backward,
            )(x)
            x = nn.relu(x)
        for stage, n_blocks in enumerate(self.stage_sizes):
            features = self.width * (2**stage)
            if stage == 0 and folded:
                for block in range(n_blocks):
                    x = FoldedResidualBlock(
                        features, dtype=self.dtype,
                        gn_custom_backward=self.gn_custom_backward,
                    )(x)
                continue
            for block in range(n_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                if folded and block == 0:
                    # Stride-2 entry consumes the folded map directly and
                    # emits the unfolded downsampled one.
                    x = FoldedTransitionBlock(
                        features, dtype=self.dtype,
                        gn_custom_backward=self.gn_custom_backward,
                    )(x)
                    folded = False
                else:
                    x = ResidualBlock(
                        features, strides, dtype=self.dtype,
                        gn_custom_backward=self.gn_custom_backward,
                    )(x)
        if folded:  # single-stage configuration: unfold for the head
            b, h, wf, c2 = x.shape
            x = x.reshape(b, h, wf * 2, c2 // 2)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


def ResNet34(num_classes: int = 10, **kwargs):
    """ResNet-34 stage configuration of the same basic-block network."""
    kwargs.setdefault("stage_sizes", (3, 4, 6, 3))
    return ResNet18(num_classes=num_classes, **kwargs)
