"""ResNet-18 with GroupNorm, NHWC, for 32x32 inputs.

Flagship model for the scale config "non-IID Dirichlet(0.1), 1000 clients,
ResNet-18" (BASELINE.json configs[4]). Deliberate TPU/FL design choice:
GroupNorm instead of BatchNorm — BatchNorm's running statistics are mutable
non-parameter state that (a) breaks the pure client-stacked-params discipline
under ``vmap`` and (b) is known to degrade under federated averaging of
per-client statistics; GroupNorm keeps the model a pure function of params.
Convs run in bfloat16 on the MXU; logits returned float32.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class ResidualBlock(nn.Module):
    features: int
    strides: int = 1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(
            self.features, (3, 3), strides=(self.strides, self.strides),
            padding="SAME", use_bias=False, dtype=self.dtype,
        )(x)
        y = nn.GroupNorm(num_groups=min(32, self.features), dtype=self.dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(
            self.features, (3, 3), padding="SAME", use_bias=False, dtype=self.dtype
        )(y)
        y = nn.GroupNorm(num_groups=min(32, self.features), dtype=self.dtype)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.features, (1, 1), strides=(self.strides, self.strides),
                use_bias=False, dtype=self.dtype,
            )(residual)
            residual = nn.GroupNorm(
                num_groups=min(32, self.features), dtype=self.dtype
            )(residual)
        return nn.relu(y + residual)


class ResNet18(nn.Module):
    """Generic basic-block ResNet; default stage sizes give ResNet-18."""

    num_classes: int = 10
    stage_sizes: Sequence[int] = (2, 2, 2, 2)
    width: int = 64
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        # CIFAR-style stem (3x3, no initial downsample) for 32x32 inputs.
        x = nn.Conv(self.width, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.GroupNorm(num_groups=min(32, self.width), dtype=self.dtype)(x)
        x = nn.relu(x)
        for stage, n_blocks in enumerate(self.stage_sizes):
            features = self.width * (2**stage)
            for block in range(n_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = ResidualBlock(features, strides, dtype=self.dtype)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


def ResNet34(num_classes: int = 10, **kwargs):
    """ResNet-34 stage configuration of the same basic-block network."""
    kwargs.setdefault("stage_sizes", (3, 4, 6, 3))
    return ResNet18(num_classes=num_classes, **kwargs)
