"""Small CNN for 32x32 RGB inputs (CIFAR-10 class of workloads).

Covers the reference baseline config "FedAvg, 10 clients, CIFAR-10 CNN"
(BASELINE.json configs[0]). Also includes a tiny MLP used by tests.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class CifarCNN(nn.Module):
    """Conv-pool x3 with a global-average-pool head.

    Design notes for the 1000-client scale config: per-client parameter
    copies are the HBM bottleneck when the client axis is vmap-ed (params,
    grads, and momentum each materialize once per client), so the head is
    GAP + a tiny dense (~100k params total) rather than a flatten+wide-dense.
    Convs compute in bfloat16 (MXU-native); params stay float32 and logits
    are returned float32 for a stable softmax. Pooling after every conv keeps
    backprop-saved activations small.
    """

    num_classes: int = 10
    width: int = 32
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        w = self.width
        x = x.astype(self.dtype)
        x = nn.Conv(features=w, kernel_size=(3, 3), padding="SAME",
                    dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))
        x = nn.Conv(features=w * 2, kernel_size=(3, 3), padding="SAME",
                    dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))
        x = nn.Conv(features=w * 4, kernel_size=(3, 3), padding="SAME",
                    dtype=self.dtype)(x)
        x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(features=self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


class TpuCifarCNN(nn.Module):
    """MXU-aligned CNN for 32x32 RGB: patch-embed to >=128 channels first.

    Why a second CIFAR CNN: on TPU, arrays are tiled (8, 128) over the last
    two dims, so NHWC activations with 3/32 channels pad the lane dimension
    to 128 and inflate HBM traffic 4-40x — and federated local training is
    bandwidth-bound (per-client weights make every conv a grouped conv).
    This variant embeds 4x4 patches straight to ``width`` (>=128) channels,
    so every activation and every contraction dim in the network is already
    lane-aligned. Measured on one chip at 1000 clients: ~5.7x faster per
    round than :class:`CifarCNN` despite 4.5x more parameters.

    Same capability slot as the reference's CIFAR CNN (BASELINE.json
    configs[0]; the reference resolves models inside its external trainer,
    reference simulator.py:47) — architecture is free, so the TPU-native
    framework picks a TPU-native one.
    """

    num_classes: int = 10
    width: int = 128
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        w = self.width
        x = x.astype(self.dtype)
        # 4x4/4 patch embedding: 32x32x3 -> 8x8xW, channel dim MXU-aligned
        x = nn.Conv(features=w, kernel_size=(4, 4), strides=(4, 4),
                    padding="VALID", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Conv(features=w, kernel_size=(3, 3), padding="SAME",
                    dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))
        x = nn.Conv(features=2 * w, kernel_size=(3, 3), padding="SAME",
                    dtype=self.dtype)(x)
        x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(features=self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


class MLP(nn.Module):
    num_classes: int = 10
    hidden: int = 64

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(features=self.hidden)(x)
        x = nn.relu(x)
        x = nn.Dense(features=self.num_classes)(x)
        return x.astype(jnp.float32)
