"""Small CNN for 32x32 RGB inputs (CIFAR-10 class of workloads).

Covers the reference baseline config "FedAvg, 10 clients, CIFAR-10 CNN"
(BASELINE.json configs[0]). Also includes a tiny MLP used by tests.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class CifarCNN(nn.Module):
    """Conv-pool x3 with a global-average-pool head.

    Design notes for the 1000-client scale config: per-client parameter
    copies are the HBM bottleneck when the client axis is vmap-ed (params,
    grads, and momentum each materialize once per client), so the head is
    GAP + a tiny dense (~100k params total) rather than a flatten+wide-dense.
    Convs compute in bfloat16 (MXU-native); params stay float32 and logits
    are returned float32 for a stable softmax. Pooling after every conv keeps
    backprop-saved activations small.
    """

    num_classes: int = 10
    width: int = 32
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        w = self.width
        x = x.astype(self.dtype)
        x = nn.Conv(features=w, kernel_size=(3, 3), padding="SAME",
                    dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))
        x = nn.Conv(features=w * 2, kernel_size=(3, 3), padding="SAME",
                    dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))
        x = nn.Conv(features=w * 4, kernel_size=(3, 3), padding="SAME",
                    dtype=self.dtype)(x)
        x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(features=self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


class MLP(nn.Module):
    num_classes: int = 10
    hidden: int = 64

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(features=self.hidden)(x)
        x = nn.relu(x)
        x = nn.Dense(features=self.num_classes)(x)
        return x.astype(jnp.float32)
