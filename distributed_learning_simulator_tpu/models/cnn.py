"""Small CNN for 32x32 RGB inputs (CIFAR-10 class of workloads).

Covers the reference baseline config "FedAvg, 10 clients, CIFAR-10 CNN"
(BASELINE.json configs[0]). Also includes a tiny MLP used by tests.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class CifarCNN(nn.Module):
    num_classes: int = 10
    width: int = 32

    @nn.compact
    def __call__(self, x):
        w = self.width
        x = nn.Conv(features=w, kernel_size=(3, 3), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.Conv(features=w * 2, kernel_size=(3, 3), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))
        x = nn.Conv(features=w * 4, kernel_size=(3, 3), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(features=w * 8)(x)
        x = nn.relu(x)
        x = nn.Dense(features=self.num_classes)(x)
        return x.astype(jnp.float32)


class MLP(nn.Module):
    num_classes: int = 10
    hidden: int = 64

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(features=self.hidden)(x)
        x = nn.relu(x)
        x = nn.Dense(features=self.num_classes)(x)
        return x.astype(jnp.float32)
