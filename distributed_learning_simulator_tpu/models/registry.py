"""Model registry: name -> flax module.

TPU-native replacement of the external model registry the reference leans on
(``--model_name`` flag, reference simulator.sh:1, resolved inside the external
``DefaultConfig.create_trainer``, reference simulator.py:47). Names are
case-insensitive; "lenet5" matches the reference launch script.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from distributed_learning_simulator_tpu.models.cnn import (
    MLP,
    CifarCNN,
    TpuCifarCNN,
)
from distributed_learning_simulator_tpu.models.lenet import LeNet5
from distributed_learning_simulator_tpu.models.resnet import ResNet18, ResNet34

_MODELS = {
    "lenet5": LeNet5,
    "cnn": CifarCNN,
    "cifarcnn": CifarCNN,
    "cnntpu": TpuCifarCNN,
    "tpucnn": TpuCifarCNN,
    "resnet18": ResNet18,
    "resnet34": ResNet34,
    "mlp": MLP,
}


def registered_models():
    return sorted(set(_MODELS))


def get_model(name: str, num_classes: int = 10, **kwargs):
    """Instantiate a model by registry name."""
    key = name.lower().replace("-", "").replace("_", "")
    if key not in _MODELS:
        raise ValueError(
            f"unknown model {name!r}; registered: {registered_models()}"
        )
    return _MODELS[key](num_classes=num_classes, **kwargs)


def init_params(model, sample_input, seed: int = 0):
    """Initialize model params from a sample batch (pure-params models only)."""
    variables = model.init(jax.random.key(seed), jnp.asarray(sample_input))
    if set(variables.keys()) != {"params"}:
        raise ValueError(
            "models must be pure functions of params (no mutable collections); "
            f"got {sorted(variables.keys())}"
        )
    return variables["params"]
