"""Parsers for the standard dataset archive formats, stdlib + numpy only.

Used by scripts/fetch_datasets.py to convert the official MNIST (IDX) and
CIFAR (python-pickle batch) archives into the ``.npz`` layout the registry
loads (data/registry.py::_load_npz: keys x_train/y_train/x_test/y_test).
Kept separate from the download script so the parsing logic is unit-testable
in the offline CI environment.
"""

from __future__ import annotations

import gzip
import io
import pickle
import struct
import tarfile

import numpy as np

_IDX_DTYPES = {
    0x08: np.uint8,
    0x09: np.int8,
    0x0B: np.dtype(">i2"),
    0x0C: np.dtype(">i4"),
    0x0D: np.dtype(">f4"),
    0x0E: np.dtype(">f8"),
}


def parse_idx(raw: bytes) -> np.ndarray:
    """Parse an IDX-format buffer (the MNIST container format).

    Layout: 2 zero bytes, dtype code, ndim, then ndim big-endian uint32
    dims, then row-major data.
    """
    if len(raw) < 4 or raw[0] != 0 or raw[1] != 0:
        raise ValueError("not an IDX buffer (bad magic)")
    dtype_code, ndim = raw[2], raw[3]
    if dtype_code not in _IDX_DTYPES:
        raise ValueError(f"unknown IDX dtype code 0x{dtype_code:02x}")
    dims = struct.unpack(f">{ndim}I", raw[4 : 4 + 4 * ndim])
    dtype = _IDX_DTYPES[dtype_code]
    data = np.frombuffer(raw, dtype=dtype, offset=4 + 4 * ndim)
    expected = int(np.prod(dims))
    if data.size != expected:
        raise ValueError(
            f"IDX size mismatch: header says {expected}, buffer has {data.size}"
        )
    return data.reshape(dims)


def mnist_arrays(
    train_images_gz: bytes, train_labels_gz: bytes,
    test_images_gz: bytes, test_labels_gz: bytes,
) -> dict[str, np.ndarray]:
    """Gzipped IDX archives -> registry npz dict ([N, 28, 28] uint8 images)."""
    return {
        "x_train": parse_idx(gzip.decompress(train_images_gz)),
        "y_train": parse_idx(gzip.decompress(train_labels_gz)).astype(np.int32),
        "x_test": parse_idx(gzip.decompress(test_images_gz)),
        "y_test": parse_idx(gzip.decompress(test_labels_gz)).astype(np.int32),
    }


def cifar10_arrays(tar_gz: bytes) -> dict[str, np.ndarray]:
    """cifar-10-python.tar.gz -> registry npz dict (NHWC uint8 images).

    The archive holds pickled batches with ``data`` [N, 3072] uint8 in CHW
    order and ``labels``; 5 train batches + 1 test batch.
    """
    train_x, train_y, test_x, test_y = [], [], None, None
    with tarfile.open(fileobj=io.BytesIO(tar_gz), mode="r:gz") as tf:
        for member in tf.getmembers():
            name = member.name.rsplit("/", 1)[-1]
            if not (name.startswith("data_batch") or name == "test_batch"):
                continue
            batch = pickle.loads(tf.extractfile(member).read(),
                                 encoding="bytes")
            x = np.asarray(batch[b"data"], dtype=np.uint8)
            x = x.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)  # CHW -> HWC
            y = np.asarray(batch[b"labels"], dtype=np.int32)
            if name == "test_batch":
                test_x, test_y = x, y
            else:
                train_x.append((name, x))
                train_y.append((name, y))
    if not train_x or test_x is None:
        raise ValueError("archive holds no CIFAR batches")
    train_x.sort()
    train_y.sort()
    return {
        "x_train": np.concatenate([x for _, x in train_x]),
        "y_train": np.concatenate([y for _, y in train_y]),
        "x_test": test_x,
        "y_test": test_y,
    }
