from distributed_learning_simulator_tpu.data.registry import Dataset, get_dataset
from distributed_learning_simulator_tpu.data.partition import (
    iid_partition,
    dirichlet_partition,
    pack_client_shards,
    ClientData,
)

__all__ = [
    "Dataset",
    "get_dataset",
    "iid_partition",
    "dirichlet_partition",
    "pack_client_shards",
    "ClientData",
]
