"""Host-side client-state residency: the full-N shard store.

``config.client_residency='streamed'`` moves ownership of the per-client
arrays (data shards + persistent algorithm state) from "device stack
built at startup" to this host store: the full ``[n_clients, ...]``
arrays live in host RAM, and only the sampled cohort's slice is uploaded
to the accelerator per dispatch (parallel/streaming.py owns the upload /
prefetch pipeline; this module owns the arrays and the index math).

Deliberately jax-free: the gather/scatter index math here is the host
mirror of ``ops/cohort.py``'s device gather/scatter, and keeping it
importable without jax lets the unit tests (tests/test_streaming.py)
pin the index semantics without a backend. Pytree traversal is a
minimal local walk (dict / list / tuple / namedtuple / None) because
per-client state trees are plain containers of arrays (optax states are
namedtuples).
"""

from __future__ import annotations

import numpy as np


def tree_map_np(fn, *trees):
    """Minimal pytree map over dict/list/tuple/namedtuple containers.

    Mirrors ``jax.tree_util.tree_map`` for the container types per-client
    state actually uses, without importing jax. ``None`` is a leaf that
    passes through (absent momentum buffers). All ``trees`` must share
    structure; ``fn`` receives one leaf per tree.
    """
    t0 = trees[0]
    if isinstance(t0, dict):
        return {k: tree_map_np(fn, *(t[k] for t in trees)) for k in t0}
    if isinstance(t0, tuple) and hasattr(t0, "_fields"):  # namedtuple
        return type(t0)(
            *(tree_map_np(fn, *leaves) for leaves in zip(*trees))
        )
    if isinstance(t0, (list, tuple)):
        mapped = [tree_map_np(fn, *leaves) for leaves in zip(*trees)]
        return type(t0)(mapped)
    if t0 is None:
        return None
    return fn(*trees)


def tree_leaves_np(tree) -> list:
    """Flatten a tree (same container set as :func:`tree_map_np`) into
    its non-None leaves."""
    out: list = []

    def walk(t):
        if isinstance(t, dict):
            for k in t:
                walk(t[k])
        elif isinstance(t, (list, tuple)):
            for c in t:
                walk(c)
        elif t is not None:
            out.append(t)

    walk(tree)
    return out


def tree_bytes(tree) -> int:
    """Total bytes of every array leaf in ``tree``."""
    return sum(int(np.asarray(leaf).nbytes) for leaf in tree_leaves_np(tree))


class HostShardStore:
    """Full-population client arrays in host RAM, gathered per cohort.

    Owns the packed data shards (``x``/``y``/``mask``/``sizes``,
    data/partition.py layout) and, when the algorithm carries persistent
    per-client state under participation sampling, the full-N state tree.
    The store is the source of truth between dispatches: checkpoints read
    it, and post-round cohort state scatters back into it.
    """

    def __init__(self, x, y, mask, sizes, state=None):
        self.x = np.ascontiguousarray(x)
        self.y = np.ascontiguousarray(y)
        self.mask = np.ascontiguousarray(mask)
        self.sizes = np.ascontiguousarray(sizes)
        self.state = state
        # Growth backing (population='dynamic', :meth:`grow`): empty
        # until the first append — the static path pays nothing. Once
        # growing, every grown array (data shards, state-tree leaves,
        # the valuation vector) becomes a view of a capacity-doubling
        # backing buffer keyed here by name/leaf position, so resident
        # rows are not re-copied on every join round (amortized O(rows
        # appended)).
        self._grow_backing: dict = {}
        # Per-client valuation vector (telemetry/valuation.py): attached
        # by ValuationState when client_valuation='on' under streamed
        # residency, so the store stays the ONE owner of every full-N
        # per-client array between dispatches. None otherwise.
        self.valuation = None
        n = self.x.shape[0]
        if not (self.y.shape[0] == self.mask.shape[0]
                == self.sizes.shape[0] == n):
            raise ValueError(
                "client-axis length mismatch: "
                f"x={n}, y={self.y.shape[0]}, mask={self.mask.shape[0]}, "
                f"sizes={self.sizes.shape[0]}"
            )
        for leaf in tree_leaves_np(state):
            if np.asarray(leaf).ndim >= 1 and np.asarray(leaf).shape[0] != n:
                raise ValueError(
                    "per-client state leaf has client-axis length "
                    f"{np.asarray(leaf).shape[0]}, store has {n}"
                )

    @property
    def n_clients(self) -> int:
        return self.x.shape[0]

    def _check_idx(self, idx: np.ndarray) -> np.ndarray:
        idx = np.asarray(idx)
        if idx.size and (idx.min() < 0 or idx.max() >= self.n_clients):
            raise IndexError(
                f"cohort index out of range [0, {self.n_clients}): "
                f"[{idx.min()}, {idx.max()}]"
            )
        return idx

    def gather_data(self, idx=None):
        """Cohort slice of the data shards: ``(x, y, mask, sizes)``.

        ``idx=None`` returns the full arrays (the degenerate
        cohort-is-everyone case — no copy, the store arrays themselves).
        """
        if idx is None:
            return self.x, self.y, self.mask, self.sizes
        idx = self._check_idx(idx)
        return (
            np.take(self.x, idx, axis=0),
            np.take(self.y, idx, axis=0),
            np.take(self.mask, idx, axis=0),
            np.take(self.sizes, idx, axis=0),
        )

    def gather_state(self, idx=None):
        """Cohort slice of the persistent per-client state tree."""
        if self.state is None:
            return None
        if idx is None:
            return self.state
        idx = self._check_idx(idx)
        return tree_map_np(
            lambda a: np.take(np.asarray(a), idx, axis=0), self.state
        )

    def scatter_state(self, idx, cohort_state) -> None:
        """Write post-round cohort state back at rows ``idx`` (in place).

        The host mirror of ``ops/cohort.cohort_scatter``: non-selected
        rows keep their values; ``idx`` must be duplicate-free
        (participation sampling draws without replacement). ``idx=None``
        replaces the whole state tree.
        """
        if self.state is None:
            if cohort_state is not None and tree_leaves_np(cohort_state):
                raise ValueError(
                    "scatter_state on a store with no per-client state"
                )
            return
        if idx is None:
            self.state = tree_map_np(np.asarray, cohort_state)
            return
        idx = self._check_idx(idx)

        def put(full, part):
            full = np.asarray(full)
            full[idx] = np.asarray(part)
            return full

        self.state = tree_map_np(put, self.state, cohort_state)

    def attach_state(self, state) -> None:
        """Adopt a full-N per-client state tree after construction (the
        dynamic-population resume path builds the store before the
        checkpointed — possibly grown — state is ready). Length-checked
        like the constructor does."""
        for leaf in tree_leaves_np(state):
            arr = np.asarray(leaf)
            if arr.ndim >= 1 and arr.shape[0] != self.n_clients:
                raise ValueError(
                    "per-client state leaf has client-axis length "
                    f"{arr.shape[0]}, store has {self.n_clients}"
                )
        self.state = state

    def grow(self, x, y, mask, sizes, state_rows=None) -> int:
        """Append joined clients' rows (population='dynamic',
        robustness/population.py); returns the first new client index.

        Every grown array — the data shards, the per-client state tree's
        leaves (when the algorithm carries any; ``state_rows`` supplies
        the joiners' rows, same tree structure), and the attached
        valuation vector (zeros: a joiner starts with no contribution
        evidence) — moves to capacity-doubling backing buffers on its
        first growth, so resident rows are copied at most O(log N) times
        over any growth schedule — never per join round.
        """
        x = np.asarray(x)
        n_new = x.shape[0]
        if n_new == 0:
            return self.n_clients
        rows = {
            "x": x, "y": np.asarray(y), "mask": np.asarray(mask),
            "sizes": np.asarray(sizes),
        }
        for name, new_rows in rows.items():
            cur = getattr(self, name)
            if new_rows.shape[0] != n_new or (
                new_rows.shape[1:] != cur.shape[1:]
            ):
                raise ValueError(
                    f"joined {name} rows have shape {new_rows.shape}, "
                    f"store rows are {cur.shape[1:]} x {n_new} clients"
                )
        # Validate the state pairing BEFORE touching any array: a grow
        # that raises must leave the store exactly as it found it.
        if self.state is not None and state_rows is None:
            raise ValueError(
                "store carries per-client state; grow() needs "
                "state_rows for the joined clients"
            )
        if self.state is None and state_rows is not None and (
            tree_leaves_np(state_rows)
        ):
            raise ValueError("grow() got state_rows on a stateless store")
        first = self.n_clients
        need = first + n_new

        def grow_one(key, cur, new_rows):
            """Capacity-doubled append for ONE grown array — the single
            growth mechanism every array goes through (data shards,
            state-tree leaves, the valuation vector): a stateful
            million-client run with joins every round must not re-copy
            any full-N array per round."""
            cur = np.asarray(cur)
            new_rows = np.asarray(new_rows)
            buf = self._grow_backing.get(key)
            if buf is None or need > buf.shape[0] or (
                buf.dtype != cur.dtype or buf.shape[1:] != cur.shape[1:]
            ):
                buf = np.empty(
                    (max(2 * cur.shape[0], need),) + cur.shape[1:],
                    cur.dtype,
                )
                buf[: cur.shape[0]] = cur
                self._grow_backing[key] = buf
            elif cur.base is not buf:
                # The array was replaced since the last grow
                # (attach_valuation/attach_state on resume, a whole-tree
                # scatter): refresh the resident rows, or the view below
                # would resurrect stale pre-replacement values.
                buf[: cur.shape[0]] = cur
            buf[first:need] = new_rows.astype(buf.dtype, copy=False)
            return buf[:need]

        for name, new_rows in rows.items():
            setattr(self, name, grow_one((name,), getattr(self, name),
                                         new_rows))
        if self.state is not None:
            counter = iter(range(1_000_000))
            # tree_map_np traverses deterministically, so leaf position
            # is a stable backing key across grows.
            self.state = tree_map_np(
                lambda a, r: grow_one(
                    ("state", next(counter)), a, r
                ),
                self.state, state_rows,
            )
        if self.valuation is not None:
            self.valuation = grow_one(
                ("valuation",), self.valuation,
                np.zeros(n_new, dtype=np.float64),
            )
        return first

    def attach_valuation(self, values) -> None:
        """Adopt the per-client valuation vector (telemetry/valuation.py)
        as a store-owned full-N array — length-checked like every other
        client-axis array the store holds."""
        values = np.asarray(values, dtype=np.float64)
        if values.shape != (self.n_clients,):
            raise ValueError(
                f"valuation vector has shape {values.shape}, store has "
                f"{self.n_clients} clients"
            )
        self.valuation = values

    def data_bytes(self) -> int:
        """Host bytes of the full-N data shards."""
        return (self.x.nbytes + self.y.nbytes + self.mask.nbytes
                + self.sizes.nbytes)

    def cohort_data_bytes(self, cohort: int) -> int:
        """Device bytes of ONE uploaded cohort data slice."""
        n = self.n_clients
        per_client = self.data_bytes() / max(n, 1)
        return int(per_client * min(cohort, n))

    def state_bytes(self) -> int:
        return tree_bytes(self.state)

    def cohort_state_bytes(self, cohort: int) -> int:
        n = self.n_clients
        return int(self.state_bytes() / max(n, 1) * min(cohort, n))


# --- distributed shard store (multihost streamed residency) ----------------
#
# ``client_residency='streamed'`` + ``multihost``: the full-N client
# arrays no longer live in ONE process's RAM — each host process owns a
# contiguous N/num_hosts slice (data + persistent algorithm state), and
# the per-round cohort is assembled owner-sharded: every host replays
# the same round-key-deterministic cohort, permutes it into
# owner-contiguous groups aligned with its addressable shards of the
# client-axis PartitionSpec, and serves its own members directly
# (parallel/streaming.DistributedCohortStreamer owns the device side).
# Everything here is jax-free index math, so the assembly-plan semantics
# are pinned by tests without a backend (tests/test_distributed_store.py).


def host_axis_bounds(length: int, devices_per_host) -> np.ndarray:
    """Contiguous per-host boundaries of a sharded axis.

    ``devices_per_host[h]`` is how many of the mesh's devices process h
    contributes (parallel/multihost.mesh_host_blocks derives it from the
    mesh's device order). Host h covers rows
    ``[bounds[h], bounds[h+1])`` — proportional to its device share, so
    when the axis length divides the device count the host blocks are
    exactly the union of the host's device shards (the full-cohort
    upload case); otherwise the floor split keeps every boundary
    deterministic from (length, device counts) alone, which is what the
    checkpoint manifest records and re-validates at resume.
    """
    devs = np.asarray(devices_per_host, dtype=np.int64)
    if devs.size < 1 or (devs <= 0).any():
        raise ValueError(
            f"devices_per_host must be positive, got {devs.tolist()}"
        )
    cum = np.concatenate([[0], np.cumsum(devs)])
    return (length * cum) // cum[-1]


def owner_of(idx, bounds) -> np.ndarray:
    """Owning host of each global client id under ``bounds``
    (:func:`host_axis_bounds` layout)."""
    return np.searchsorted(
        np.asarray(bounds)[1:-1], np.asarray(idx), side="right"
    )


class AssemblyPlan:
    """One round's owner-sharded cohort assembly (pure index math).

    Every host computes the SAME plan from the same replayed cohort, so
    the spill exchange needs no negotiation: each field below is global
    knowledge.

    * ``idx`` — the cohort's global client ids in DRAW order (the order
      the 1-process program trains them in).
    * ``draw_pos`` — for each cohort ROW p (the device layout's
      position), the draw position of the client placed there. The
      round program uses it to permute its per-POSITION draws (training
      keys, fault flags) back to the draw-order assignment, which is
      what keeps the owner-permuted run equal to the draw-order run
      per client (algorithms/fedavg.cohort_round).
    * ``row_of`` — inverse of ``draw_pos``.
    * spill_* — the members whose assigned row lies in ANOTHER host's
      block (assignment fills each host's block with its OWN members
      first, so spill is only the per-round ownership imbalance,
      expected O(sqrt(cohort)) rows — the only client data that ever
      crosses DCN). Canonical order: ascending destination row, shared
      by the send and receive sides of both exchange directions.
    """

    def __init__(self, idx, owners, draw_pos, row_of, spill_q,
                 spill_rows, spill_owner, spill_block, owner_bounds,
                 block_bounds):
        self.idx = idx
        self.owners = owners
        self.draw_pos = draw_pos
        self.row_of = row_of
        self.spill_q = spill_q            # draw positions, canonical order
        self.spill_rows = spill_rows      # their destination rows
        self.spill_owner = spill_owner    # who owns (sends) each entry
        self.spill_block = spill_block    # whose block receives it
        self.owner_bounds = np.asarray(owner_bounds, np.int64)
        self.block_bounds = np.asarray(block_bounds, np.int64)
        # Slot of each spill entry within its sender's send list and its
        # receiver's block list — the padded-exchange addressing both
        # transfer directions share (forward: owner -> block host;
        # writeback: block host -> owner).
        self.slot_in_owner = _cumcount(spill_owner)
        self.slot_in_block = _cumcount(spill_block)
        self.spill_ids = idx[spill_q]

    @property
    def n_hosts(self) -> int:
        return len(self.owner_bounds) - 1

    @property
    def cohort(self) -> int:
        return self.idx.size

    @property
    def idx_perm(self) -> np.ndarray:
        """Cohort ids in ROW order (owner-grouped) — the round program's
        ``idx`` operand under the distributed layout."""
        return self.idx[self.draw_pos]

    def send_counts(self) -> np.ndarray:
        return np.bincount(self.spill_owner, minlength=self.n_hosts)

    def recv_counts(self) -> np.ndarray:
        return np.bincount(self.spill_block, minlength=self.n_hosts)


def _cumcount(groups: np.ndarray) -> np.ndarray:
    """Occurrence rank of each element within its group value (stable)."""
    out = np.zeros(groups.size, dtype=np.int64)
    for g in np.unique(groups):
        m = groups == g
        out[m] = np.arange(int(m.sum()))
    return out


def plan_owner_assembly(idx, owner_bounds, block_bounds) -> AssemblyPlan:
    """Assign each cohort member a device-layout row, own-block first.

    ``idx``: global cohort ids in draw order. ``owner_bounds``: the
    store's client-space ownership split. ``block_bounds``: the cohort
    row-space per-host addressable blocks (same shape, cohort length).
    Each host's block is filled with its own members in draw order;
    members beyond a block's capacity (the per-round ownership
    imbalance) take the remaining free rows in ascending row order —
    those are the spill entries the hosts exchange. Deterministic pure
    function of its inputs; H=1 reduces to the identity assignment
    (``draw_pos == arange``, no spill) — the num_hosts==1 zero-cost
    contract.
    """
    idx = np.asarray(idx, dtype=np.int64)
    owner_bounds = np.asarray(owner_bounds, dtype=np.int64)
    block_bounds = np.asarray(block_bounds, dtype=np.int64)
    c = idx.size
    if block_bounds[-1] != c or block_bounds[0] != 0:
        raise ValueError(
            f"block bounds {block_bounds.tolist()} do not cover the "
            f"cohort (size {c})"
        )
    if len(owner_bounds) != len(block_bounds):
        raise ValueError(
            "owner and block bounds disagree on the host count: "
            f"{len(owner_bounds) - 1} vs {len(block_bounds) - 1}"
        )
    owners = owner_of(idx, owner_bounds)
    n_hosts = len(owner_bounds) - 1
    row_of = np.full(c, -1, dtype=np.int64)
    overflow_parts: list[np.ndarray] = []
    free_parts: list[np.ndarray] = []
    for h in range(n_hosts):
        lo, hi = int(block_bounds[h]), int(block_bounds[h + 1])
        mine = np.flatnonzero(owners == h)
        take = mine[: hi - lo]
        row_of[take] = lo + np.arange(take.size)
        overflow_parts.append(mine[hi - lo:])
        if take.size < hi - lo:
            free_parts.append(np.arange(lo + take.size, hi))
    overflow = (
        np.concatenate(overflow_parts) if overflow_parts
        else np.empty(0, np.int64)
    )
    free = (
        np.concatenate(free_parts) if free_parts else np.empty(0, np.int64)
    )
    # A host has either overflow or free rows, never both, so every
    # overflow assignment is cross-host by construction; sizes match
    # because both count C minus the in-own-block placements.
    row_of[overflow] = free[: overflow.size]
    draw_pos = np.empty(c, dtype=np.int64)
    draw_pos[row_of] = np.arange(c)
    order = np.argsort(row_of[overflow], kind="stable")
    spill_q = overflow[order]
    spill_rows = row_of[spill_q]
    return AssemblyPlan(
        idx, owners, draw_pos, row_of, spill_q, spill_rows,
        owners[spill_q], owner_of(spill_rows, block_bounds),
        owner_bounds, block_bounds,
    )


class DistributedShardStore(HostShardStore):
    """The host shard store's owner-indexed multihost view.

    Process ``host_id`` of ``n_hosts`` owns the contiguous global client
    slice ``[bounds[host_id], bounds[host_id+1])``; the constructor takes
    the FULL arrays every process materializes at startup (the dataset
    partition is deterministic, so all hosts derive the same full-N
    view) and keeps ONLY its owned slice — per-host RAM scales as
    N/num_hosts, which is what lets a million-client population span
    hosts none of which could hold it alone. All index arguments stay
    GLOBAL client ids; the store maps them to local rows and refuses
    ids it does not own (an out-of-slice gather is an assembly-plan bug,
    never something to serve silently). jax-free like the base class.
    """

    def __init__(self, x, y, mask, sizes, state=None, *, host_id: int,
                 owner_bounds):
        owner_bounds = np.asarray(owner_bounds, dtype=np.int64)
        n_global = int(owner_bounds[-1])
        if np.asarray(x).shape[0] != n_global:
            raise ValueError(
                f"owner bounds cover {n_global} clients but x has "
                f"{np.asarray(x).shape[0]} rows"
            )
        if not 0 <= host_id < len(owner_bounds) - 1:
            raise ValueError(
                f"host_id {host_id} out of range for "
                f"{len(owner_bounds) - 1} hosts"
            )
        self.host_id = int(host_id)
        self.owner_bounds = owner_bounds
        self.n_global = n_global
        self.lo = int(owner_bounds[host_id])
        self.hi = int(owner_bounds[host_id + 1])
        # np.array(..., copy=True): own the slice outright so the caller
        # can free the full-N arrays — the memory claim of the feature.
        super().__init__(
            np.array(np.asarray(x)[self.lo:self.hi]),
            np.array(np.asarray(y)[self.lo:self.hi]),
            np.array(np.asarray(mask)[self.lo:self.hi]),
            np.array(np.asarray(sizes)[self.lo:self.hi]),
            state=state,
        )

    @property
    def n_hosts(self) -> int:
        return len(self.owner_bounds) - 1

    @property
    def n_owned(self) -> int:
        return self.hi - self.lo

    def to_local(self, idx) -> np.ndarray:
        """Map global client ids to local rows; refuse non-owned ids."""
        idx = np.asarray(idx)
        if idx.size and (idx.min() < self.lo or idx.max() >= self.hi):
            raise IndexError(
                f"host {self.host_id} owns clients [{self.lo}, {self.hi})"
                f" but was asked for ids in [{idx.min()}, {idx.max()}] — "
                "owner-sharded assembly must route these through their "
                "owning host's spill exchange"
            )
        return idx - self.lo

    def gather_data(self, idx=None):
        """``idx=None`` returns the OWNED slice (the host's share of a
        full-population upload); otherwise global ids -> owned rows."""
        if idx is None:
            return self.x, self.y, self.mask, self.sizes
        return super().gather_data(self.to_local(idx))

    def gather_state(self, idx=None):
        if idx is None or self.state is None:
            return self.state
        return super().gather_state(self.to_local(idx))

    def scatter_state(self, idx, cohort_state) -> None:
        if self.state is None or idx is None:
            super().scatter_state(idx, cohort_state)
            return
        super().scatter_state(self.to_local(idx), cohort_state)

    def grow(self, *args, **kwargs):
        raise NotImplementedError(
            "population='dynamic' does not compose with the distributed "
            "shard store (config.validate names the refusal): growth "
            "would re-partition ownership mid-run"
        )

    def attach_valuation(self, values) -> None:
        raise NotImplementedError(
            "client_valuation='on' does not compose with the distributed "
            "shard store (config.validate names the refusal): the "
            "valuation vector is a full-N host array with one owner"
        )


def synthetic_stream_shards(x_train, y_train, n_clients: int,
                            shard_size: int, seed: int = 0):
    """Vectorized synthetic ``ClientData`` for population-scale benches.

    ``pack_client_shards`` walks a Python loop per client — fine at
    thousands, minutes at a million. This draws every client's shard as
    one fancy-index gather from a small sample pool (with replacement
    across clients): uint8-compact layout (float32 fallback outside the
    [0, 1] range, like pack_client_shards), full masks, identical decode
    semantics to the packed path. The pool being small is the point —
    the POPULATION axis is what the stream bench scales, not the
    dataset.
    """

    from distributed_learning_simulator_tpu.data.partition import (
        ClientData,
        _compact_encode,
        _unit_range,
    )

    n_pool = x_train.shape[0]
    sample_shape = tuple(x_train.shape[1:])
    dim = int(np.prod(sample_shape))
    ok, _, _ = _unit_range(x_train)
    if ok:
        # Same range contract as pack_client_shards: uint8 encoding
        # assumes [0, 1] inputs; out-of-range pools keep float32 (the
        # decode path dispatches on dtype either way).
        pool = _compact_encode(
            x_train.reshape(n_pool, dim).astype(np.float32), n_pool, dim
        )
    else:
        pool = np.asarray(x_train, dtype=np.float32)
    rng = np.random.default_rng(seed)
    ix = rng.integers(0, n_pool, size=(n_clients, shard_size))
    return ClientData(
        x=pool[ix],
        y=np.asarray(y_train, dtype=np.int32)[ix],
        mask=np.ones((n_clients, shard_size), dtype=np.float32),
        sizes=np.full(n_clients, float(shard_size), dtype=np.float32),
        sample_shape=sample_shape,
    )
