"""Host-side client-state residency: the full-N shard store.

``config.client_residency='streamed'`` moves ownership of the per-client
arrays (data shards + persistent algorithm state) from "device stack
built at startup" to this host store: the full ``[n_clients, ...]``
arrays live in host RAM, and only the sampled cohort's slice is uploaded
to the accelerator per dispatch (parallel/streaming.py owns the upload /
prefetch pipeline; this module owns the arrays and the index math).

Deliberately jax-free: the gather/scatter index math here is the host
mirror of ``ops/cohort.py``'s device gather/scatter, and keeping it
importable without jax lets the unit tests (tests/test_streaming.py)
pin the index semantics without a backend. Pytree traversal is a
minimal local walk (dict / list / tuple / namedtuple / None) because
per-client state trees are plain containers of arrays (optax states are
namedtuples).
"""

from __future__ import annotations

import numpy as np


def tree_map_np(fn, *trees):
    """Minimal pytree map over dict/list/tuple/namedtuple containers.

    Mirrors ``jax.tree_util.tree_map`` for the container types per-client
    state actually uses, without importing jax. ``None`` is a leaf that
    passes through (absent momentum buffers). All ``trees`` must share
    structure; ``fn`` receives one leaf per tree.
    """
    t0 = trees[0]
    if isinstance(t0, dict):
        return {k: tree_map_np(fn, *(t[k] for t in trees)) for k in t0}
    if isinstance(t0, tuple) and hasattr(t0, "_fields"):  # namedtuple
        return type(t0)(
            *(tree_map_np(fn, *leaves) for leaves in zip(*trees))
        )
    if isinstance(t0, (list, tuple)):
        mapped = [tree_map_np(fn, *leaves) for leaves in zip(*trees)]
        return type(t0)(mapped)
    if t0 is None:
        return None
    return fn(*trees)


def tree_leaves_np(tree) -> list:
    """Flatten a tree (same container set as :func:`tree_map_np`) into
    its non-None leaves."""
    out: list = []

    def walk(t):
        if isinstance(t, dict):
            for k in t:
                walk(t[k])
        elif isinstance(t, (list, tuple)):
            for c in t:
                walk(c)
        elif t is not None:
            out.append(t)

    walk(tree)
    return out


def tree_bytes(tree) -> int:
    """Total bytes of every array leaf in ``tree``."""
    return sum(int(np.asarray(leaf).nbytes) for leaf in tree_leaves_np(tree))


class HostShardStore:
    """Full-population client arrays in host RAM, gathered per cohort.

    Owns the packed data shards (``x``/``y``/``mask``/``sizes``,
    data/partition.py layout) and, when the algorithm carries persistent
    per-client state under participation sampling, the full-N state tree.
    The store is the source of truth between dispatches: checkpoints read
    it, and post-round cohort state scatters back into it.
    """

    def __init__(self, x, y, mask, sizes, state=None):
        self.x = np.ascontiguousarray(x)
        self.y = np.ascontiguousarray(y)
        self.mask = np.ascontiguousarray(mask)
        self.sizes = np.ascontiguousarray(sizes)
        self.state = state
        # Growth backing (population='dynamic', :meth:`grow`): empty
        # until the first append — the static path pays nothing. Once
        # growing, every grown array (data shards, state-tree leaves,
        # the valuation vector) becomes a view of a capacity-doubling
        # backing buffer keyed here by name/leaf position, so resident
        # rows are not re-copied on every join round (amortized O(rows
        # appended)).
        self._grow_backing: dict = {}
        # Per-client valuation vector (telemetry/valuation.py): attached
        # by ValuationState when client_valuation='on' under streamed
        # residency, so the store stays the ONE owner of every full-N
        # per-client array between dispatches. None otherwise.
        self.valuation = None
        n = self.x.shape[0]
        if not (self.y.shape[0] == self.mask.shape[0]
                == self.sizes.shape[0] == n):
            raise ValueError(
                "client-axis length mismatch: "
                f"x={n}, y={self.y.shape[0]}, mask={self.mask.shape[0]}, "
                f"sizes={self.sizes.shape[0]}"
            )
        for leaf in tree_leaves_np(state):
            if np.asarray(leaf).ndim >= 1 and np.asarray(leaf).shape[0] != n:
                raise ValueError(
                    "per-client state leaf has client-axis length "
                    f"{np.asarray(leaf).shape[0]}, store has {n}"
                )

    @property
    def n_clients(self) -> int:
        return self.x.shape[0]

    def _check_idx(self, idx: np.ndarray) -> np.ndarray:
        idx = np.asarray(idx)
        if idx.size and (idx.min() < 0 or idx.max() >= self.n_clients):
            raise IndexError(
                f"cohort index out of range [0, {self.n_clients}): "
                f"[{idx.min()}, {idx.max()}]"
            )
        return idx

    def gather_data(self, idx=None):
        """Cohort slice of the data shards: ``(x, y, mask, sizes)``.

        ``idx=None`` returns the full arrays (the degenerate
        cohort-is-everyone case — no copy, the store arrays themselves).
        """
        if idx is None:
            return self.x, self.y, self.mask, self.sizes
        idx = self._check_idx(idx)
        return (
            np.take(self.x, idx, axis=0),
            np.take(self.y, idx, axis=0),
            np.take(self.mask, idx, axis=0),
            np.take(self.sizes, idx, axis=0),
        )

    def gather_state(self, idx=None):
        """Cohort slice of the persistent per-client state tree."""
        if self.state is None:
            return None
        if idx is None:
            return self.state
        idx = self._check_idx(idx)
        return tree_map_np(
            lambda a: np.take(np.asarray(a), idx, axis=0), self.state
        )

    def scatter_state(self, idx, cohort_state) -> None:
        """Write post-round cohort state back at rows ``idx`` (in place).

        The host mirror of ``ops/cohort.cohort_scatter``: non-selected
        rows keep their values; ``idx`` must be duplicate-free
        (participation sampling draws without replacement). ``idx=None``
        replaces the whole state tree.
        """
        if self.state is None:
            if cohort_state is not None and tree_leaves_np(cohort_state):
                raise ValueError(
                    "scatter_state on a store with no per-client state"
                )
            return
        if idx is None:
            self.state = tree_map_np(np.asarray, cohort_state)
            return
        idx = self._check_idx(idx)

        def put(full, part):
            full = np.asarray(full)
            full[idx] = np.asarray(part)
            return full

        self.state = tree_map_np(put, self.state, cohort_state)

    def attach_state(self, state) -> None:
        """Adopt a full-N per-client state tree after construction (the
        dynamic-population resume path builds the store before the
        checkpointed — possibly grown — state is ready). Length-checked
        like the constructor does."""
        for leaf in tree_leaves_np(state):
            arr = np.asarray(leaf)
            if arr.ndim >= 1 and arr.shape[0] != self.n_clients:
                raise ValueError(
                    "per-client state leaf has client-axis length "
                    f"{arr.shape[0]}, store has {self.n_clients}"
                )
        self.state = state

    def grow(self, x, y, mask, sizes, state_rows=None) -> int:
        """Append joined clients' rows (population='dynamic',
        robustness/population.py); returns the first new client index.

        Every grown array — the data shards, the per-client state tree's
        leaves (when the algorithm carries any; ``state_rows`` supplies
        the joiners' rows, same tree structure), and the attached
        valuation vector (zeros: a joiner starts with no contribution
        evidence) — moves to capacity-doubling backing buffers on its
        first growth, so resident rows are copied at most O(log N) times
        over any growth schedule — never per join round.
        """
        x = np.asarray(x)
        n_new = x.shape[0]
        if n_new == 0:
            return self.n_clients
        rows = {
            "x": x, "y": np.asarray(y), "mask": np.asarray(mask),
            "sizes": np.asarray(sizes),
        }
        for name, new_rows in rows.items():
            cur = getattr(self, name)
            if new_rows.shape[0] != n_new or (
                new_rows.shape[1:] != cur.shape[1:]
            ):
                raise ValueError(
                    f"joined {name} rows have shape {new_rows.shape}, "
                    f"store rows are {cur.shape[1:]} x {n_new} clients"
                )
        # Validate the state pairing BEFORE touching any array: a grow
        # that raises must leave the store exactly as it found it.
        if self.state is not None and state_rows is None:
            raise ValueError(
                "store carries per-client state; grow() needs "
                "state_rows for the joined clients"
            )
        if self.state is None and state_rows is not None and (
            tree_leaves_np(state_rows)
        ):
            raise ValueError("grow() got state_rows on a stateless store")
        first = self.n_clients
        need = first + n_new

        def grow_one(key, cur, new_rows):
            """Capacity-doubled append for ONE grown array — the single
            growth mechanism every array goes through (data shards,
            state-tree leaves, the valuation vector): a stateful
            million-client run with joins every round must not re-copy
            any full-N array per round."""
            cur = np.asarray(cur)
            new_rows = np.asarray(new_rows)
            buf = self._grow_backing.get(key)
            if buf is None or need > buf.shape[0] or (
                buf.dtype != cur.dtype or buf.shape[1:] != cur.shape[1:]
            ):
                buf = np.empty(
                    (max(2 * cur.shape[0], need),) + cur.shape[1:],
                    cur.dtype,
                )
                buf[: cur.shape[0]] = cur
                self._grow_backing[key] = buf
            elif cur.base is not buf:
                # The array was replaced since the last grow
                # (attach_valuation/attach_state on resume, a whole-tree
                # scatter): refresh the resident rows, or the view below
                # would resurrect stale pre-replacement values.
                buf[: cur.shape[0]] = cur
            buf[first:need] = new_rows.astype(buf.dtype, copy=False)
            return buf[:need]

        for name, new_rows in rows.items():
            setattr(self, name, grow_one((name,), getattr(self, name),
                                         new_rows))
        if self.state is not None:
            counter = iter(range(1_000_000))
            # tree_map_np traverses deterministically, so leaf position
            # is a stable backing key across grows.
            self.state = tree_map_np(
                lambda a, r: grow_one(
                    ("state", next(counter)), a, r
                ),
                self.state, state_rows,
            )
        if self.valuation is not None:
            self.valuation = grow_one(
                ("valuation",), self.valuation,
                np.zeros(n_new, dtype=np.float64),
            )
        return first

    def attach_valuation(self, values) -> None:
        """Adopt the per-client valuation vector (telemetry/valuation.py)
        as a store-owned full-N array — length-checked like every other
        client-axis array the store holds."""
        values = np.asarray(values, dtype=np.float64)
        if values.shape != (self.n_clients,):
            raise ValueError(
                f"valuation vector has shape {values.shape}, store has "
                f"{self.n_clients} clients"
            )
        self.valuation = values

    def data_bytes(self) -> int:
        """Host bytes of the full-N data shards."""
        return (self.x.nbytes + self.y.nbytes + self.mask.nbytes
                + self.sizes.nbytes)

    def cohort_data_bytes(self, cohort: int) -> int:
        """Device bytes of ONE uploaded cohort data slice."""
        n = self.n_clients
        per_client = self.data_bytes() / max(n, 1)
        return int(per_client * min(cohort, n))

    def state_bytes(self) -> int:
        return tree_bytes(self.state)

    def cohort_state_bytes(self, cohort: int) -> int:
        n = self.n_clients
        return int(self.state_bytes() / max(n, 1) * min(cohort, n))


def synthetic_stream_shards(x_train, y_train, n_clients: int,
                            shard_size: int, seed: int = 0):
    """Vectorized synthetic ``ClientData`` for population-scale benches.

    ``pack_client_shards`` walks a Python loop per client — fine at
    thousands, minutes at a million. This draws every client's shard as
    one fancy-index gather from a small sample pool (with replacement
    across clients): uint8-compact layout (float32 fallback outside the
    [0, 1] range, like pack_client_shards), full masks, identical decode
    semantics to the packed path. The pool being small is the point —
    the POPULATION axis is what the stream bench scales, not the
    dataset.
    """

    from distributed_learning_simulator_tpu.data.partition import (
        ClientData,
        _compact_encode,
        _unit_range,
    )

    n_pool = x_train.shape[0]
    sample_shape = tuple(x_train.shape[1:])
    dim = int(np.prod(sample_shape))
    ok, _, _ = _unit_range(x_train)
    if ok:
        # Same range contract as pack_client_shards: uint8 encoding
        # assumes [0, 1] inputs; out-of-range pools keep float32 (the
        # decode path dispatches on dtype either way).
        pool = _compact_encode(
            x_train.reshape(n_pool, dim).astype(np.float32), n_pool, dim
        )
    else:
        pool = np.asarray(x_train, dtype=np.float32)
    rng = np.random.default_rng(seed)
    ix = rng.integers(0, n_pool, size=(n_clients, shard_size))
    return ClientData(
        x=pool[ix],
        y=np.asarray(y_train, dtype=np.int32)[ix],
        mask=np.ones((n_clients, shard_size), dtype=np.float32),
        sizes=np.full(n_clients, float(shard_size), dtype=np.float32),
        sample_shape=sample_shape,
    )
