"""Dataset registry: name -> train/test arrays.

TPU-native replacement for the external ``DatasetCollection.get_by_name``
registry the reference uses (reference simulator_backup.py:10,51-53 and the
``--dataset_name`` flag, simulator.sh:1). Datasets are plain NHWC numpy
arrays — the whole training set for all clients lives in HBM as one array
(CIFAR-10 is 180 MB in float32; trivial for a TPU), so there is no per-batch
host->device transfer in the training loop at all.

Offline policy: this environment has zero network egress, so ``mnist`` and
``cifar10`` first look for local ``.npz`` files (``<data_dir>/<name>.npz``
with keys x_train/y_train/x_test/y_test); if absent they fall back to a
*deterministic synthetic surrogate* with identical shapes/classes (Gaussian
class prototypes + noise — learnable, so accuracy curves behave like real
training). The surrogate is clearly logged.

``dataset_args`` parity (reference simulator_backup.py:50): ``to_grayscale``
collapses RGB to 1 channel — used by the heterogeneity experiment where
worker 0 receives a grayscale "bad" dataset.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np

from distributed_learning_simulator_tpu.utils.logging import get_logger


@dataclass
class Dataset:
    name: str
    x_train: np.ndarray  # [N, H, W, C] float32 in [0, 1]
    y_train: np.ndarray  # [N] int32
    x_test: np.ndarray
    y_test: np.ndarray
    num_classes: int

    @property
    def input_shape(self):
        return self.x_train.shape[1:]


_SHAPES = {
    "mnist": ((28, 28, 1), 10, 60000, 10000),
    "cifar10": ((32, 32, 3), 10, 50000, 10000),
    "cifar100": ((32, 32, 3), 100, 50000, 10000),
}


def _synthetic_classification(
    name: str,
    shape,
    num_classes: int,
    n_train: int,
    n_test: int,
    seed: int = 0,
    difficulty: float = 0.75,
) -> Dataset:
    """Deterministic learnable surrogate: per-class Gaussian prototypes.

    sample = clip(0.5 + 0.5*(prototype * (1-difficulty) + noise * difficulty)).
    Lower difficulty -> higher achievable accuracy.
    """
    rng = np.random.default_rng(seed)
    dim = int(np.prod(shape))
    prototypes = rng.normal(0.0, 1.0, size=(num_classes, dim)).astype(np.float32)

    def make(n, label_seed):
        lrng = np.random.default_rng(label_seed)
        y = lrng.integers(0, num_classes, size=n).astype(np.int32)
        noise = lrng.normal(0.0, 1.0, size=(n, dim)).astype(np.float32)
        x = prototypes[y] * (1.0 - difficulty) + noise * difficulty
        x = np.clip(0.5 + 0.5 * x, 0.0, 1.0).astype(np.float32)
        return x.reshape((n,) + tuple(shape)), y

    x_train, y_train = make(n_train, seed + 1)
    x_test, y_test = make(n_test, seed + 2)
    return Dataset(name, x_train, y_train, x_test, y_test, num_classes)


def _load_npz(path: str, name: str, num_classes: int) -> Dataset:
    with np.load(path) as z:
        x_train = z["x_train"].astype(np.float32)
        y_train = z["y_train"].astype(np.int32)
        x_test = z["x_test"].astype(np.float32)
        y_test = z["y_test"].astype(np.int32)
    if x_train.ndim == 3:  # [N, H, W] -> NHWC
        x_train = x_train[..., None]
        x_test = x_test[..., None]
    if x_train.max() > 1.5:  # raw uint8 range
        x_train = x_train / 255.0
        x_test = x_test / 255.0
    return Dataset(name, x_train, y_train, x_test, y_test, num_classes)


def _load_digits(name: str, seed: int) -> Dataset:
    """REAL pixels with no network: scikit-learn's bundled handwritten-digits
    set (1797 8x8 grayscale images, the UCI/NIST optdigits test subsample,
    shipped inside sklearn itself). This is the offline container's genuine
    real-data path — every other real dataset needs a download (see
    scripts/fetch_datasets.py and docs/ACCURACY.md). Deterministic seeded
    1500/297 train/test split; pixels rescaled from the 0-16 integer range
    to [0, 1]."""
    from sklearn.datasets import load_digits

    d = load_digits()
    x = (d.images / 16.0).astype(np.float32)[..., None]
    y = d.target.astype(np.int32)
    rng = np.random.default_rng(seed)
    perm = rng.permutation(len(y))
    x, y = x[perm], y[perm]
    n_tr = 1500
    return Dataset(name, x[:n_tr], y[:n_tr], x[n_tr:], y[n_tr:], 10)


def _to_grayscale(ds: Dataset) -> Dataset:
    def gray(x):
        if x.shape[-1] == 1:
            return x
        w = np.array([0.299, 0.587, 0.114], dtype=np.float32)
        return (x @ w)[..., None]

    return Dataset(
        ds.name + "_gray", gray(ds.x_train), ds.y_train, gray(ds.x_test),
        ds.y_test, ds.num_classes,
    )


def get_dataset(
    name: str,
    data_dir: str | None = None,
    seed: int = 0,
    n_train: int | None = None,
    n_test: int | None = None,
    to_grayscale: bool = False,
    **synthetic_kwargs,
) -> Dataset:
    """Fetch a dataset by name.

    Names: ``mnist`` / ``cifar10`` / ``cifar100`` (local .npz or synthetic
    surrogate), ``digits`` (REAL handwritten-digit pixels bundled with
    scikit-learn — works fully offline), and ``synthetic`` (explicitly
    synthetic; accepts ``shape``, ``num_classes``, ``difficulty``).
    ``n_train``/``n_test`` subsample for fast tests. ``to_grayscale`` is the
    reference's ``dataset_args`` heterogeneity knob (simulator_backup.py:50).
    """
    key = name.lower()
    data_dir = data_dir or os.environ.get("DLS_DATA_DIR", "/root/data")
    if key == "digits":
        ds = _load_digits(key, seed=seed)
    elif key == "synthetic":
        shape = tuple(synthetic_kwargs.pop("shape", (8, 8, 1)))
        num_classes = synthetic_kwargs.pop("num_classes", 10)
        ds = _synthetic_classification(
            key, shape, num_classes, n_train or 4096, n_test or 1024,
            seed=seed, **synthetic_kwargs,
        )
    elif key in _SHAPES:
        shape, num_classes, full_train, full_test = _SHAPES[key]
        npz = os.path.join(data_dir, f"{key}.npz")
        if os.path.exists(npz):
            ds = _load_npz(npz, key, num_classes)
        else:
            get_logger().warning(
                "dataset %r not found at %s (offline environment); using a "
                "deterministic synthetic surrogate with identical shapes",
                key, npz,
            )
            ds = _synthetic_classification(
                key, shape, num_classes, n_train or full_train,
                n_test or full_test, seed=seed, **synthetic_kwargs,
            )
    else:
        raise ValueError(
            f"unknown dataset {name!r}; known: "
            f"{sorted(_SHAPES) + ['digits', 'synthetic']}"
        )
    if n_train is not None:
        ds.x_train, ds.y_train = ds.x_train[:n_train], ds.y_train[:n_train]
    if n_test is not None:
        ds.x_test, ds.y_test = ds.x_test[:n_test], ds.y_test[:n_test]
    if to_grayscale:
        ds = _to_grayscale(ds)
    return ds
