"""Client data partitioning: IID, Dirichlet non-IID, per-client override.

Replaces the reference's ``DatasetUtil.iid_split`` (reference
simulator.py:48-50: equal IID shards, one per worker) and its per-client
dataset-override experiment (reference simulator_backup.py:71-77: worker 0's
shard replaced with a "bad" grayscale dataset).

TPU-first representation: all client shards are packed into ONE fixed-shape
array ``[n_clients, shard_size, ...]`` plus a 0/1 sample mask
``[n_clients, shard_size]``. Fixed shapes are what make the client axis
``vmap``/``shard_map``-able with a single compilation; variable per-client
dataset sizes (Dirichlet) are expressed through the mask and through the
per-client ``sizes`` vector that drives weighted aggregation
(reference fed_server.py:58-66 weights by ``len(trainer.dataset)``).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class ClientData:
    """Packed per-client training shards (the client axis, materialized).

    Two storage layouts:
      * float32, sample shape preserved (``compact=False``);
      * uint8, samples flattened to ``[n_clients, shard_size, dim]``
        (``compact=True``, the simulator default) — 4x smaller in HBM and,
        critically, a 2-D trailing block that tiles cleanly on TPU: image
        shapes like ``[..., 32, 32, 3]`` waste up to 4x HBM in layout
        padding at 1000-client scale. Batches are decoded (cast + /255 +
        reshape) on the fly inside the training step.
    """

    x: np.ndarray  # [n_clients, shard_size, ...] float32, or uint8 flat
    y: np.ndarray  # [n_clients, shard_size] int32
    mask: np.ndarray  # [n_clients, shard_size] float32; 0 = padding
    sizes: np.ndarray  # [n_clients] float32 = mask.sum(1); aggregation weights
    sample_shape: tuple = ()  # original per-sample shape when compact

    @property
    def n_clients(self) -> int:
        return self.x.shape[0]

    @property
    def shard_size(self) -> int:
        return self.x.shape[1]

    @property
    def compact(self) -> bool:
        return self.x.dtype == np.uint8

    def override_client(self, client_id: int, x: np.ndarray, y: np.ndarray):
        """Replace one client's shard (heterogeneity/poisoning injection).

        Parity with reference simulator_backup.py:71-77 where worker 0's
        training set is swapped for a grayscale MNIST. The replacement is
        truncated/padded to ``shard_size``; channel counts must match the
        packed array (use dataset_args to_grayscale + channel tiling upstream
        if they don't).
        """
        n = min(len(x), self.shard_size)
        xr = x[:n]
        if self.compact:
            ok, xmin, xmax = _unit_range(xr)
            if not ok:
                raise ValueError(
                    "override_client on a compact-packed ClientData requires "
                    f"data in [0, 1]; got range [{xmin:.4g}, {xmax:.4g}]. "
                    "Rescale the override, or pack with compact=False."
                )
            xr = _compact_encode(xr, n, self.x.shape[-1])
        self.x[client_id] = 0
        self.y[client_id] = 0
        self.mask[client_id] = 0.0
        self.x[client_id, :n] = xr
        self.y[client_id, :n] = y[:n]
        self.mask[client_id, :n] = 1.0
        self.sizes[client_id] = float(n)
        return self


def _compact_encode(x: np.ndarray, n: int, dim: int) -> np.ndarray:
    """uint8 flatten for compact storage; inverse is cast * (1/255) + reshape
    (parallel/engine.py make_decoder)."""
    return np.round(np.clip(x, 0.0, 1.0) * 255.0).astype(np.uint8).reshape(n, dim)


def _unit_range(x: np.ndarray) -> tuple[bool, float, float]:
    """Single source of truth for the compact-storage [0, 1] range contract.

    Returns (within_range, min, max); empty arrays are trivially in range
    (nothing to encode).
    """
    if x.size == 0:
        return True, 0.0, 0.0
    xmin, xmax = float(x.min()), float(x.max())
    return xmin >= -1e-6 and xmax <= 1.0 + 1e-6, xmin, xmax


def iid_partition(n_samples: int, n_clients: int, seed: int = 0) -> list[np.ndarray]:
    """Equal-size IID shards (reference simulator.py:48-50, weights [1]*N)."""
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n_samples)
    shard = n_samples // n_clients
    return [perm[i * shard : (i + 1) * shard] for i in range(n_clients)]


def dirichlet_partition(
    labels: np.ndarray, n_clients: int, alpha: float, seed: int = 0,
    min_size: int = 0,
) -> list[np.ndarray]:
    """Label-skewed non-IID split: per-class Dirichlet(alpha) over clients.

    Standard federated non-IID benchmark split (BASELINE.json configs[4]:
    "non-IID Dirichlet(alpha=0.1), 1000 clients"). Smaller alpha = more skew.
    Empty clients are legal (min_size=0, the default): the packed-shard mask
    gives them zero aggregation weight and zero gradient contribution, so
    extreme skew at high client counts "just works". Set ``min_size`` > 0 to
    re-draw until every client has that many samples (can be unsatisfiable
    for small alpha x large n_clients).
    """
    rng = np.random.default_rng(seed)
    n_classes = int(labels.max()) + 1
    for _ in range(100):
        client_indices: list[list[int]] = [[] for _ in range(n_clients)]
        for c in range(n_classes):
            idx = np.flatnonzero(labels == c)
            rng.shuffle(idx)
            props = rng.dirichlet([alpha] * n_clients)
            cuts = (np.cumsum(props) * len(idx)).astype(int)[:-1]
            for client, part in enumerate(np.split(idx, cuts)):
                client_indices[client].extend(part.tolist())
        if min(len(ci) for ci in client_indices) >= min_size:
            return [
                np.array(sorted(ci), dtype=np.int64) for ci in client_indices
            ]
    raise RuntimeError(
        f"dirichlet_partition: could not satisfy min_size={min_size} "
        f"with alpha={alpha}, n_clients={n_clients}"
    )


def pack_client_shards(
    x: np.ndarray,
    y: np.ndarray,
    indices: list[np.ndarray],
    shard_size: int | None = None,
    batch_size: int | None = None,
    compact: bool = False,
) -> ClientData:
    """Pack per-client index lists into fixed-shape arrays + mask.

    ``shard_size`` defaults to the largest shard, rounded up to a multiple of
    ``batch_size`` (so every client's scan sees whole batches; padding rows
    carry mask 0 and contribute nothing to the loss). ``compact`` stores
    uint8-flattened samples (see :class:`ClientData`).
    """
    if compact:
        ok, xmin, xmax = _unit_range(x)
        if not ok:
            from distributed_learning_simulator_tpu.utils.logging import (
                get_logger,
            )

            get_logger().warning(
                "compact uint8 client storage assumes inputs in [0, 1] but "
                "data range is [%.4g, %.4g]; falling back to float32 storage "
                "(set compact_client_data=False to silence)",
                xmin, xmax,
            )
            compact = False
    n_clients = len(indices)
    max_n = max(len(ix) for ix in indices)
    size = shard_size or max_n
    if batch_size:
        size = ((size + batch_size - 1) // batch_size) * batch_size
    sample_shape = x.shape[1:]
    if compact:
        dim = int(np.prod(sample_shape))
        cx = np.zeros((n_clients, size, dim), dtype=np.uint8)
    else:
        cx = np.zeros((n_clients, size) + sample_shape, dtype=x.dtype)
    cy = np.zeros((n_clients, size), dtype=np.int32)
    mask = np.zeros((n_clients, size), dtype=np.float32)
    for i, ix in enumerate(indices):
        n = min(len(ix), size)
        xi = x[ix[:n]]
        if compact:
            xi = _compact_encode(xi, n, dim)
        cx[i, :n] = xi
        cy[i, :n] = y[ix[:n]]
        mask[i, :n] = 1.0
    return ClientData(
        x=cx, y=cy, mask=mask, sizes=mask.sum(axis=1).astype(np.float32),
        sample_shape=tuple(sample_shape),
    )
