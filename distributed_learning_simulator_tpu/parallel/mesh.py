"""Device mesh + sharding helpers for the client axis.

The reference's "distributed communication backend" is an in-process blocking
queue with broadcast (reference servers/server.py:10-17, fed_server.py:19-24,
88-91) plus a dormant multi-process path (simulator.py:56 hard-codes it off).
The TPU-native equivalent: simulated clients are a *mesh axis*. Client-stacked
arrays get ``PartitionSpec("clients", ...)``; every reduction over that axis
(FedAvg weighted mean, SignSGD vote) is lowered by XLA to an ICI collective,
and the broadcast back is just the replicated output sharding. Multi-host
(DCN) uses the same program after ``jax.distributed.initialize`` — the mesh
spans all processes' devices.
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

CLIENT_AXIS = "clients"


def make_mesh(num_devices: int | None = None, axis_name: str = CLIENT_AXIS) -> Mesh:
    """1-D mesh over local (or all, under multi-host) devices.

    ``num_devices=None`` uses every visible device. The client axis is sharded
    over this mesh; n_clients must be a multiple of the mesh size.
    """
    devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            # A TPU plugin may take platform priority over JAX_PLATFORMS=cpu;
            # the virtual-CPU devices (xla_force_host_platform_device_count)
            # are still reachable through the explicit cpu backend. Opt-in
            # only (DLS_ALLOW_CPU_MESH_FALLBACK=1): a production launch with
            # a device shortfall must fail fast, not quietly train on host
            # CPU. dryrun/sharding-validation entry points set the flag.
            allow_fallback = os.environ.get(
                "DLS_ALLOW_CPU_MESH_FALLBACK", ""
            ).lower() in ("1", "true")
            try:
                cpu_devices = jax.devices("cpu")
            except RuntimeError:
                cpu_devices = []
            if allow_fallback and num_devices <= len(cpu_devices):
                from distributed_learning_simulator_tpu.utils.logging import (
                    get_logger,
                )

                get_logger().warning(
                    "mesh fallback: %d devices requested but only %d on "
                    "platform %r; using %d virtual HOST-CPU devices "
                    "(orders of magnitude slower than accelerators — "
                    "intended for sharding validation, not production)",
                    num_devices, len(devices), devices[0].platform,
                    num_devices,
                )
                devices = cpu_devices
            else:
                hint = (
                    "raise XLA_FLAGS=--xla_force_host_platform_device_count"
                    if allow_fallback
                    else "set DLS_ALLOW_CPU_MESH_FALLBACK=1 to validate "
                    "sharding on virtual host-CPU devices"
                )
                raise ValueError(
                    f"requested {num_devices} mesh devices but only "
                    f"{len(devices)} visible "
                    f"(and {len(cpu_devices)} cpu devices; {hint})"
                )
        devices = devices[:num_devices]
    return Mesh(np.array(devices), (axis_name,))


def client_sharding(mesh: Mesh, ndim_tail: int = 0) -> NamedSharding:
    """Sharding for an array whose LEADING axis is the client axis."""
    spec = PartitionSpec(mesh.axis_names[0], *([None] * ndim_tail))
    return NamedSharding(mesh, spec)


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (global params, test set)."""
    return NamedSharding(mesh, PartitionSpec())


def shard_client_data(tree, mesh: Mesh):
    """device_put every leaf with its leading (client) axis over the mesh."""
    spec = PartitionSpec(mesh.axis_names[0])
    sharding = NamedSharding(mesh, spec)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)


def replicate(tree, mesh: Mesh):
    """device_put every leaf fully replicated over the mesh."""
    sharding = replicated_sharding(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)
