"""Multi-host (DCN) initialization.

The reference's dormant multi-process path (``TorchProcessTaskQueue``,
reference servers/server.py:11-13, hard-disabled at simulator.py:56) is the
closest it gets to multi-node. The TPU-native equivalent: initialize the JAX
distributed runtime, after which ``jax.devices()`` spans every host's chips
and the SAME mesh/sharding code (parallel/mesh.py) runs the client axis over
ICI within a slice and DCN across slices — no separate code path.
"""

from __future__ import annotations

import jax

from distributed_learning_simulator_tpu.utils.logging import get_logger


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> int:
    """Initialize jax.distributed; returns the global device count.

    With no arguments, relies on the TPU environment's auto-configuration
    (the standard path on Cloud TPU pods). Safe to call when already
    initialized (returns immediately).
    """
    logger = get_logger()
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        # Already initialized, or single-process environment.
        logger.info("jax.distributed.initialize skipped: %s", e)
    n = len(jax.devices())
    logger.info(
        "multihost: process %d/%d, %d global devices",
        jax.process_index(), jax.process_count(), n,
    )
    return n
