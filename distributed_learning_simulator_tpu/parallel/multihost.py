"""Multi-host (DCN) initialization.

The reference's dormant multi-process path (``TorchProcessTaskQueue``,
reference servers/server.py:11-13, hard-disabled at simulator.py:56) is the
closest it gets to multi-node. The TPU-native equivalent: initialize the JAX
distributed runtime, after which ``jax.devices()`` spans every host's chips
and the SAME mesh/sharding code (parallel/mesh.py) runs the client axis over
ICI within a slice and DCN across slices — no separate code path.
"""

from __future__ import annotations

import jax
import numpy as np

from distributed_learning_simulator_tpu.utils.logging import get_logger

# Coordinator this process successfully initialized against (None when
# jax.distributed was brought up elsewhere or auto-configured) — the JAX
# API doesn't expose it, so remember it to catch a re-call that names a
# DIFFERENT coordinator while counts happen to match.
_initialized_coordinator: str | None = None


def distributed_initialized() -> bool:
    """Whether jax.distributed is up in this process.

    ``jax.distributed.is_initialized`` exists only in some jax
    versions; where it is absent, the presence of the distributed
    coordination client (the state ``jax.distributed.initialize``
    creates) is the same fact.
    """
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    try:
        from jax._src import distributed as _dist

        state = _dist.global_state
        return (
            getattr(state, "client", None) is not None
            or getattr(state, "service", None) is not None
        )
    except Exception:  # pragma: no cover - exotic jax builds
        return False


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> int:
    """Initialize jax.distributed; returns the global device count.

    With no arguments, relies on the TPU environment's auto-configuration
    (the standard path on Cloud TPU pods); in a plain single-process
    environment that raises (nothing to auto-detect) and degrades to a
    logged no-op, so one binary serves pods and laptops. Safe to call when
    jax.distributed is already initialized (logged no-op, any flags). With
    EXPLICIT coordinator flags and no prior initialization, failures are
    fatal: a misconfigured 2-process launch must not silently split into
    two independent single-process runs that each write a full set of
    artifacts.
    """
    global _initialized_coordinator

    logger = get_logger()
    explicit = any(
        v is not None
        for v in (coordinator_address, num_processes, process_id)
    )
    if distributed_initialized():
        # Safe to re-call in an already-distributed process (a second
        # run_simulation in the same driver, a retry) — but explicit flags
        # must MATCH the live topology: reusing a single-process runtime
        # when the caller asked for process 1-of-2 is exactly the silent
        # split this function's contract forbids.
        if explicit:
            if (
                num_processes is not None
                and jax.process_count() != num_processes
            ) or (
                process_id is not None
                and jax.process_index() != process_id
            ):
                raise RuntimeError(
                    "jax.distributed is already initialized as process "
                    f"{jax.process_index()}/{jax.process_count()}, which "
                    "does not match the explicit multihost flags "
                    f"(num_processes={num_processes}, "
                    f"process_id={process_id}); refusing to proceed"
                )
            if coordinator_address is not None:
                if (
                    _initialized_coordinator is not None
                    and _initialized_coordinator != coordinator_address
                ):
                    raise RuntimeError(
                        "jax.distributed is already initialized against "
                        f"coordinator {_initialized_coordinator!r} but the "
                        f"caller asked for {coordinator_address!r}; "
                        "refusing to silently reuse a different cluster"
                    )
                if _initialized_coordinator is None:
                    logger.warning(
                        "jax.distributed was initialized outside "
                        "initialize_multihost; cannot verify it points at "
                        "the requested coordinator %r",
                        coordinator_address,
                    )
        logger.info("jax.distributed already initialized; reusing it")
    else:
        try:
            # CPU backend (tests, CPU clusters): cross-process
            # computations need a CPU collectives implementation —
            # without one, the first sharded dispatch dies with
            # "Multiprocess computations aren't implemented on the CPU
            # backend". Gloo ships in jaxlib; the knob must be set
            # BEFORE the backend initializes, which this call precedes
            # by contract (it runs before any device query). Guarded:
            # absent on exotic builds, and a no-op for TPU/GPU (their
            # collectives ride ICI/NCCL regardless).
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo"
                )
            except (AttributeError, ValueError):
                pass
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
            if coordinator_address is not None:
                _initialized_coordinator = coordinator_address
        except (RuntimeError, ValueError) as e:
            # No coordinator configured and none auto-detectable (plain
            # single-process environment).
            if explicit:
                raise RuntimeError(
                    "jax.distributed.initialize failed with explicit "
                    "multihost flags (coordinator_address="
                    f"{coordinator_address!r}, "
                    f"num_processes={num_processes}, "
                    f"process_id={process_id}); refusing to degrade to a "
                    "single-process run"
                ) from e
            logger.info(
                "jax.distributed.initialize skipped (single process): %s", e
            )
    n = len(jax.devices())
    logger.info(
        "multihost: process %d/%d, %d global devices",
        jax.process_index(), jax.process_count(), n,
    )
    return n


def allgather_wall_stamps(stamp: float) -> np.ndarray:
    """Gather one wall-clock stamp per host at full precision.

    The naive float gather is silently useless: with x64 disabled (the
    default) every float64 array crossing a collective is cast to
    float32, whose resolution at a ~1.8e9 s Unix epoch is 128 s —
    every host's stamp rounds to the SAME value and measured skews
    read exactly 0.0. Split each stamp into its float32 head plus the
    float64 remainder (|remainder| <= half the head's 128 s ulp, where
    float32 resolution is ~4 µs) and rebuild float64 after the gather:
    microsecond precision through a float32 pipe, well under the
    collective-latency uncertainty floor.

    Returns the ``[n_hosts]`` float64 stamp vector in process order.
    Collective — main thread only.
    """
    from jax.experimental import multihost_utils

    head = np.float32(stamp)
    rest = np.float32(stamp - np.float64(head))
    gathered = np.asarray(multihost_utils.process_allgather(
        np.asarray([head, rest], np.float32)
    )).reshape(-1, 2)
    return (gathered[:, 0].astype(np.float64)
            + gathered[:, 1].astype(np.float64))


def estimate_clock_alignment() -> tuple[float, float]:
    """Estimate this host's wall-clock offset vs host 0, for the span
    journals (telemetry/spans.py headers).

    Runs once, right after :func:`initialize_multihost` — the
    barrier-synchronized moment when every host is provably inside the
    same code region. Two back-to-back ``process_allgather`` barriers:
    each host stamps ``clock.wall()`` immediately after the FIRST
    barrier releases (all hosts release within one collective latency
    of each other), and the SECOND gather publishes the stamps. The
    offset is ``my_stamp - host0_stamp`` (positive = this host's wall
    clock reads ahead of host 0's); the uncertainty is the measured
    barrier release width — the round-trip this host observed across
    the two collectives, an upper bound on how non-simultaneous the
    stamps were. Good to ~collective-latency (µs on ICI, ms on DCN),
    which is exactly the resolution the cross-host timeline needs:
    barrier skews below the collective latency are not attributable
    to hosts anyway.

    Single-process (or uninitialized) runs return ``(0.0, 0.0)``.
    """
    if jax.process_count() <= 1:
        return 0.0, 0.0
    from jax.experimental import multihost_utils

    from distributed_learning_simulator_tpu.telemetry import clock

    # Barrier 1: align all hosts to within one collective latency.
    multihost_utils.process_allgather(np.zeros([1], dtype=np.int32))
    t_release = clock.monotonic()
    stamp = clock.wall()
    # Barrier 2: publish the post-release stamps (split-float gather —
    # a plain float gather collapses to float32 and reads all-equal).
    stamps = allgather_wall_stamps(stamp)
    rtt = clock.monotonic() - t_release
    offset = float(stamp - stamps[0])
    return offset, float(rtt)


def mesh_devices_per_host(mesh) -> list[int]:
    """Per-process device counts of a 1-D mesh, validated for the
    distributed shard store's contiguous-block layout.

    The owner-sharded cohort assembly (data/residency.plan_owner_assembly
    + parallel/streaming.DistributedCohortStreamer) needs each host's
    addressable shards of the client-axis ``PartitionSpec`` to be ONE
    contiguous row block, which holds exactly when the mesh's device
    order groups processes contiguously (true for ``jax.devices()`` on
    every backend — devices sort by process index — but verified here
    rather than assumed). Also requires the mesh to span EVERY process:
    a process with no addressable mesh device could never serve its
    owned clients' rows. Returns ``devices_per_host`` indexed by process
    id — the input :func:`data.residency.host_axis_bounds` turns into
    ownership/block boundaries.
    """
    procs = [d.process_index for d in np.ravel(mesh.devices)]
    n_hosts = jax.process_count()
    if sorted(procs) != procs:
        raise ValueError(
            "mesh device order interleaves processes "
            f"(process sequence {procs}); the distributed shard store "
            "needs each host's mesh shards contiguous — build the mesh "
            "from jax.devices() order"
        )
    counts = [0] * n_hosts
    for p in procs:
        counts[p] += 1
    missing = [h for h, c in enumerate(counts) if c == 0]
    if missing:
        raise ValueError(
            f"mesh spans {len(set(procs))} of {n_hosts} processes "
            f"(processes {missing} contribute no device); "
            "client_residency='streamed' under multihost needs every "
            "host addressable in the mesh — set mesh_devices to the "
            "global device count"
        )
    return counts
