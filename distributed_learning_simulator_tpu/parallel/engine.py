"""Client-axis training engine: local training as scan, clients as vmap.

Replaces the reference's per-worker ``Trainer`` objects driven by one OS
thread each (reference workers/fed_worker.py:19-27: block for global params,
run E local epochs, ship params). Here a client's local training run is a
pure function

    local_train(params, shard_x, shard_y, mask, key) -> (params', metrics)

built as ``lax.scan`` over epochs x steps (compiler-friendly: static shapes,
no Python control flow inside jit), and the whole client population is
``vmap(local_train)`` — N clients train in lockstep as one batched XLA
program, with every matmul carrying the client axis as an extra batch
dimension onto the MXU.

Padding discipline: shards are fixed-size with 0/1 sample masks
(data/partition.py); masked samples contribute zero loss and zero gradient,
so Dirichlet/heterogeneous shards need no recompilation.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_learning_simulator_tpu.ops.quantize import hash_mix


def make_optimizer(name: str, learning_rate: float, momentum: float = 0.0,
                   weight_decay: float = 0.0):
    """Optimizer registry, parity with the reference's ``--optimizer_name``
    flag (reference simulator.sh:1; SGD is the reference default and the
    required optimizer for SignSGD, sign_sgd_worker.py:14)."""
    key = name.lower()
    if key == "sgd":
        tx = optax.sgd(learning_rate, momentum=momentum or None)
    elif key == "adam":
        tx = optax.adam(learning_rate)
    elif key == "adamw":
        tx = optax.adamw(learning_rate, weight_decay=weight_decay)
    else:
        raise ValueError(f"unknown optimizer {name!r}")
    if weight_decay and key == "sgd":
        tx = optax.chain(optax.add_decayed_weights(weight_decay), tx)
    return tx


def make_loss_fn(apply_fn, param_transform: Callable | None = None):
    """Masked softmax cross-entropy + accuracy.

    ``param_transform`` hooks QAT: e.g. ``fake_quant_tree`` applied to params
    inside the loss gives straight-through-estimator quantization-aware
    training (replaces reference workers/fed_quant_worker.py:19-20).
    """

    def loss_fn(params, x, y, mask):
        p = param_transform(params) if param_transform is not None else params
        logits = apply_fn({"params": p}, x)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        nll = -jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        loss = jnp.sum(nll * mask) / denom
        acc = jnp.sum((jnp.argmax(logits, axis=1) == y) * mask) / denom
        return loss, acc

    return loss_fn


def make_decoder(sample_shape):
    """Batch decoder for compact (uint8-flattened) client storage: cast,
    rescale to [0, 1], restore the sample shape. See ClientData.compact."""

    def decode(b):
        return (b.astype(jnp.float32) / 255.0).reshape(
            (b.shape[0],) + tuple(sample_shape)
        )

    return decode


def _sr_to_bf16(x32, salt):
    """Stochastically round an f32 array to bf16 storage (hash dither).

    bf16 keeps the top 16 bits of the f32 pattern; adding a uniform random
    16-bit value below the cut before truncating rounds each weight up with
    probability equal to its truncated fraction — unbiased, so updates
    smaller than the weight's bf16 ulp survive in expectation. Without
    this, bf16 local state silently stalls long-horizon training: the
    round-to-nearest broadcast cast quantizes identically for every client
    and the per-step stores swallow the common-mode (mean-gradient)
    component of every update the same way on every client, so aggregation
    cannot recover it (measured: 0.49 vs 0.69 final accuracy at 50 bench
    rounds; per-client decorrelation is the load-bearing property).

    The dither is a multiplicative hash of the value bits mixed with a
    per-(client, call-site) salt — pure fused elementwise ALU, no PRNG
    tensor generated or moved. A real counter PRNG
    (``lax.rng_bit_generator``) costs ~15% of the ResNet-18 round in
    generation traffic alone; the hash is free (within noise) and
    empirically matches f32 final accuracy on every config tested, with
    statistical unbiasedness covered by tests/test_utils.py. Returns
    (bf16 array, advanced salt).
    """
    u = jax.lax.bitcast_convert_type(x32, jnp.uint32)
    h = hash_mix(u, salt)  # ops/quantize.py: the one copy of the mixing
    u = (u + (h & jnp.uint32(0xFFFF))) & jnp.uint32(0xFFFF0000)
    rounded = jax.lax.bitcast_convert_type(u, jnp.float32)
    return rounded.astype(jnp.bfloat16), salt + jnp.uint32(0x9E3779B9)


def _sr_tree_to_bf16(tree, salt):
    """Stochastically round every leaf of an f32 pytree to bf16, threading
    the dither salt through the leaves. Used for both SR sites (broadcast
    cast and per-step param store)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out = []
    for x in leaves:
        r, salt = _sr_to_bf16(x.astype(jnp.float32), salt)
        out.append(r)
    return jax.tree_util.tree_unflatten(treedef, out), salt


def make_local_train_fn(
    apply_fn,
    optimizer,
    local_epochs: int,
    batch_size: int,
    param_transform: Callable | None = None,
    reset_optimizer: bool = True,
    preprocess: Callable | None = None,
    augment: Callable | None = None,
    compute_dtype=None,
    collect_stats: bool = False,
):
    """Build ``local_train(params, opt_state, xs, ys, mask, key)``.

    E epochs over the client's fixed-size shard, fresh random permutation per
    epoch, minibatches of ``batch_size`` (shard_size must be a multiple —
    data/partition.py guarantees it). Matches the reference hot loop
    ``for _ in range(E): epoch of SGD`` (external Trainer.train called at
    fed_worker.py:25-27) but as two nested ``lax.scan``s.

    vmap over the client axis: ``jax.vmap(local_train, in_axes=(None, 0, 0,
    0, 0, 0))`` — global params broadcast (the init-model broadcast of
    fed_server.py:19-24), everything else per-client.

    ``compute_dtype`` (e.g. ``jnp.bfloat16``): store the per-client DIVERGED
    params/grads/momenta in this dtype for the duration of the local run.
    These buffers exist per in-flight client — at 1000 clients x ResNet-18
    they are the round's dominant HBM traffic — and only live within one
    round: the f32 global model is the broadcast source every round and the
    aggregation accumulates client params in f32 (fedavg.py reduce_chunk),
    so precision loss is confined to a few local SGD steps, the regime where
    bf16 training is standard practice.

    ``collect_stats`` (telemetry/client_stats.py): additionally report
    ``loss_first`` (the very first optimizer step's batch loss — the
    local loss at the incoming global params) and ``grad_sq_mean`` (mean
    per-step squared gradient L2 norm) in the metrics dict. A trace-time
    flag: False (the default) compiles the exact pre-feature program and
    consumes no extra RNG either way.
    """
    loss_fn = make_loss_fn(apply_fn, param_transform)
    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    sr_enabled = compute_dtype == jnp.bfloat16

    def local_train(params, opt_state, xs, ys, mask, key, lr_scale=1.0):
        sr_state = jnp.uint32(0)
        if sr_enabled:
            # Per-client dither salt from the client's key: independent
            # rounding decisions across clients under vmap (the property
            # the aggregate's unbiasedness rests on — see _sr_to_bf16).
            sr_state = jax.random.key_data(
                jax.random.fold_in(key, 7)
            ).reshape(-1)[0].astype(jnp.uint32)
            # The broadcast cast f32 global -> bf16 must be stochastic TOO:
            # round-to-nearest here is the same bias for every client, i.e.
            # the global model gets deterministically re-quantized to bf16
            # resolution every round and progress below one bf16 ulp is
            # erased. With per-client SR the 1000-client aggregate
            # preserves the f32 global to ~ulp/sqrt(N).
            params, sr_state = _sr_tree_to_bf16(params, sr_state)
        elif compute_dtype is not None:
            params = jax.tree_util.tree_map(
                lambda p: p.astype(compute_dtype), params
            )
        shard_size = xs.shape[0]
        steps_per_epoch = shard_size // batch_size
        aug_key = None
        if augment is not None:
            # Split only when augmenting so the un-augmented RNG stream
            # (shuffles) is unchanged by this feature.
            key, aug_key = jax.random.split(key)
        if reset_optimizer:
            # Fresh optimizer every round (standard FedAvg). The incoming
            # opt_state is ignored and None is returned in its place — at
            # 1000-client scale a returned per-client optimizer state would
            # be dead weight the size of the whole model per client.
            opt_state = optimizer.init(params)

        def epoch_body(carry, scan_in):
            epoch_key, epoch_idx = scan_in
            params, opt_state, sr_state = carry
            perm = jax.random.permutation(epoch_key, shard_size)

            def step_body(carry, step):
                params, opt_state, sr_state = carry
                idx = jax.lax.dynamic_slice_in_dim(
                    perm, step * batch_size, batch_size
                )
                bx = jnp.take(xs, idx, axis=0)
                by = jnp.take(ys, idx, axis=0)
                bm = jnp.take(mask, idx, axis=0)
                if preprocess is not None:
                    bx = preprocess(bx)
                if augment is not None:
                    # Fresh per-(epoch, step) augmentation randomness,
                    # independent of the shuffle keys.
                    bx = augment(
                        bx, jax.random.fold_in(jax.random.fold_in(
                            aug_key, epoch_idx), step),
                    )
                (loss, acc), grads = grad_fn(params, bx, by, bm)
                updates, opt_state = optimizer.update(grads, opt_state, params)
                # Round-level lr schedule (config.lr_schedule): the per-round
                # factor multiplies the final update, which is EXACT for
                # both sgd (lr sits outside the momentum buffer, torch
                # semantics) and adam (lr sits outside the normalization) —
                # equivalent to rebuilding the optimizer with lr*factor but
                # without retracing. f32 math, original dtype preserved.
                updates = jax.tree_util.tree_map(
                    lambda u: (
                        u.astype(jnp.float32) * lr_scale
                    ).astype(u.dtype),
                    updates,
                )
                if sr_enabled:
                    # f32 update math, stochastically-rounded bf16 storage:
                    # plain bf16 apply_updates swallows updates below the
                    # weight's bf16 ulp (see _sr_to_bf16).
                    summed = jax.tree_util.tree_map(
                        lambda p, u: (
                            p.astype(jnp.float32) + u.astype(jnp.float32)
                        ),
                        params, updates,
                    )
                    params, sr_state = _sr_tree_to_bf16(summed, sr_state)
                else:
                    params = optax.apply_updates(params, updates)
                step_out = (loss, acc)
                if collect_stats:
                    # Exact per-step gradient L2 norm (f32 even when the
                    # local run computes in bf16).
                    grad_sq = sum(
                        jnp.sum(g.astype(jnp.float32) ** 2)
                        for g in jax.tree_util.tree_leaves(grads)
                    )
                    step_out = (loss, acc, grad_sq)
                return (params, opt_state, sr_state), step_out

            (params, opt_state, sr_state), step_outs = jax.lax.scan(
                step_body, (params, opt_state, sr_state),
                jnp.arange(steps_per_epoch),
            )
            if collect_stats:
                losses, accs, grad_sqs = step_outs
                epoch_out = (
                    jnp.mean(losses), jnp.mean(accs),
                    losses[0], jnp.mean(grad_sqs),
                )
            else:
                losses, accs = step_outs
                epoch_out = (jnp.mean(losses), jnp.mean(accs))
            return (params, opt_state, sr_state), epoch_out

        epoch_keys = jax.random.split(key, local_epochs)
        (params, opt_state, sr_state), epoch_outs = (
            jax.lax.scan(
                epoch_body, (params, opt_state, sr_state),
                (epoch_keys, jnp.arange(local_epochs)),
            )
        )
        if collect_stats:
            epoch_losses, epoch_accs, first_losses, grad_means = epoch_outs
            metrics = {
                "loss": epoch_losses[-1],
                "accuracy": epoch_accs[-1],
                # First epoch's first step: the loss of the INCOMING
                # global params on this client's first batch.
                "loss_first": first_losses[0],
                "grad_sq_mean": jnp.mean(grad_means),
            }
        else:
            epoch_losses, epoch_accs = epoch_outs
            metrics = {"loss": epoch_losses[-1], "accuracy": epoch_accs[-1]}
        return params, (None if reset_optimizer else opt_state), metrics

    return local_train


def chunked_accumulate(trees, chunk: int, compute_fn, acc0, per_chunk=None):
    """Sequential-over-chunks client scan with remainder handling — the ONE
    copy of the slice/reshape/scan/concatenate discipline shared by the
    FedAvg fused reduction (algorithms/fedavg.py train_and_reduce) and the
    sign_SGD per-step vote (algorithms/sign_sgd.py): both bound HBM by
    processing ``chunk`` clients at a time while accumulating a reduction,
    and both must hand remainder clients (C % chunk) their own call so the
    memory bound never silently degrades.

    ``trees``: pytree of client-stacked arrays ``[C, ...]`` (None leaves
    allowed — e.g. absent momentum buffers). ``per_chunk``: optional PRNG
    key; the helper splits it into one key per chunk plus one for the
    remainder call (splitting happens HERE so callers can't mis-size the
    key array against this function's own chunk count).
    ``compute_fn(chunk_trees, per_chunk_key) -> (partial, per_client)``:
    ``partial`` is tree-added into ``acc0``; ``per_client`` (leading chunk
    axis, None allowed) is restacked to ``[C, ...]``. Returns
    ``(accumulated, per_client_full)``.
    """
    n = jax.tree_util.tree_leaves(trees)[0].shape[0]
    n_chunks, rem = divmod(n, chunk)
    head = jax.tree_util.tree_map(lambda a: a[: n - rem], trees)
    xs = jax.tree_util.tree_map(
        lambda a: a.reshape((n_chunks, chunk) + a.shape[1:]), head
    )
    keys = None
    if per_chunk is not None:
        keys = jax.random.split(per_chunk, n_chunks + 1)
    scan_xs = xs if keys is None else (xs, keys[:n_chunks])

    def body(acc, scan_in):
        if per_chunk is None:
            chunk_trees, pc = scan_in, None
        else:
            chunk_trees, pc = scan_in
        partial, per_client = compute_fn(chunk_trees, pc)
        return jax.tree_util.tree_map(jnp.add, acc, partial), per_client

    acc, stacked = jax.lax.scan(body, acc0, scan_xs)
    per_client = jax.tree_util.tree_map(
        lambda a: a.reshape((n - rem,) + a.shape[2:]), stacked
    )
    if rem:
        tail = jax.tree_util.tree_map(lambda a: a[n - rem:], trees)
        partial_t, per_client_t = compute_fn(
            tail, None if keys is None else keys[-1]
        )
        acc = jax.tree_util.tree_map(jnp.add, acc, partial_t)
        per_client = jax.tree_util.tree_map(
            lambda a, b: jnp.concatenate([a, b], axis=0),
            per_client, per_client_t,
        )
    return acc, per_client


def make_batched_round_fn(round_fn, server_update_fn, eval_fn, length: int,
                          lr_schedule: bool, async_mode: bool = False):
    """Fuse ``length`` federated rounds into ONE dispatchable program
    (config.rounds_per_dispatch; docs/PERFORMANCE.md § Round batching).

    The host round loop pays per-round dispatch, eval launch, and sync
    costs that a ~100 ms round cannot amortize (measured ~28% of the
    headline round is host-side). This builds a ``lax.scan`` whose body
    replays the host loop's per-round sequence EXACTLY — the
    ``key, round_key = jax.random.split(key)`` chain, the round program,
    the optional server-optimizer step (fed the round's quorum verdict,
    like the host path), and the server eval — so K>1 history is
    bit-identical to K=1; only where the sequencing runs moves. Per-round
    metrics and aux diagnostics come back stacked ``[length, ...]`` for
    one host fetch per dispatch.

    ``lr_schedule`` (trace-time): when True the returned function takes a
    ``[length]`` f32 vector of per-round schedule factors (simulator
    ``lr_factors``) and the scan consumes one per round; when False the
    round fn is called WITHOUT the operand so the constant default
    constant-folds exactly as in the unbatched program.

    ``async_mode`` (trace-time; config.async_mode='on'): the round fn's
    staleness-buffer state (robustness/arrivals.py) joins the scan carry
    — each iteration feeds the previous round's ``aux['async_state']``
    back as the ``async_state`` operand, exactly replaying the host
    loop's pop-and-refeed sequence, and the dispatch returns the final
    buffer state as a trailing output. The carried state is popped from
    aux BEFORE stacking (a param-sized buffer stacked K times would
    defeat the point of one accumulator).

    Returns ``batched(global_params, client_state, server_state, key,
    cx, cy, cmask, sizes, eval_batches[, lr_vec][, async_state]) ->
    (new_global, new_client_state, new_server_state, new_key, metrics_k,
    aux_k[, async_state])``. ``client_state``/``server_state`` may be
    None (absent state carries through the scan as an empty subtree).
    Algorithms opt in via ``Algorithm.supports_round_batching`` — the
    scan stacks every aux leaf, so aux must not carry per-round
    parameter STACKS, and post_round hooks only see dispatch-granular
    params.
    """

    def batched(global_params, client_state, server_state, key,
                cx, cy, cmask, sizes, eval_batches, lr_vec=None,
                async_state=None):
        def body(carry, lr_k):
            if async_mode:
                gp, cstate, sstate, k, astate = carry
                kw = {"async_state": astate}
            else:
                gp, cstate, sstate, k = carry
                kw = {}
            k, round_key = jax.random.split(k)
            if lr_schedule:
                new_gp, cstate, aux = round_fn(
                    gp, cstate, cx, cy, cmask, sizes, round_key, lr_k, **kw
                )
            else:
                new_gp, cstate, aux = round_fn(
                    gp, cstate, cx, cy, cmask, sizes, round_key, **kw
                )
            if async_mode:
                aux = dict(aux)
                astate = aux.pop("async_state")
            if server_update_fn is not None:
                srv_args = (gp, new_gp, sstate)
                if "round_rejected" in aux:
                    srv_args += (aux["round_rejected"],)
                new_gp, sstate = server_update_fn(*srv_args)
            metrics = eval_fn(new_gp, *eval_batches)
            carry = (
                (new_gp, cstate, sstate, k, astate) if async_mode
                else (new_gp, cstate, sstate, k)
            )
            return carry, (metrics, aux)

        carry0 = (global_params, client_state, server_state, key)
        if async_mode:
            carry0 = carry0 + (async_state,)
        carry_out, (metrics_k, aux_k) = jax.lax.scan(
            body, carry0,
            lr_vec if lr_schedule else None,
            length=None if lr_schedule else length,
        )
        if async_mode:
            gp, cstate, sstate, key, astate = carry_out
            return gp, cstate, sstate, key, metrics_k, aux_k, astate
        gp, cstate, sstate, key = carry_out
        return gp, cstate, sstate, key, metrics_k, aux_k

    return batched


def make_streamed_batched_round_fn(round_fn, server_update_fn, eval_fn,
                                   length: int, lr_schedule: bool,
                                   async_mode: bool = False):
    """Batched dispatch for the STREAMED calling convention with a
    sampled cohort (config.client_residency='streamed' +
    rounds_per_dispatch > 1; parallel/streaming.py).

    Mirrors :func:`make_batched_round_fn`'s scan — the same
    ``key, round_key = jax.random.split(key)`` chain, server-optimizer
    step, and fused eval, so K>1 streamed history is bit-identical to
    the K=1 loop — but the per-round client data arrives PRE-GATHERED:
    the K cohorts' slices are stacked ``[K, cohort, ...]`` scan operands
    (uploaded by the streamer, which host-replayed this scan's key chain
    to know the cohorts ahead of time) and each iteration consumes one
    slice. There is no client-state carry: the simulator refuses
    streamed batching with persistent per-client state — cohorts inside
    one dispatch may overlap, and a scan iteration cannot scatter into
    the host store mid-dispatch.

    Returns ``batched(global_params, server_state, key, xs_k, ys_k,
    ms_k, sizes_k, idx_k, eval_batches[, lr_vec][, async_state]) ->
    (new_global, new_server_state, new_key, metrics_k, aux_k
    [, async_state])``.
    """

    def batched(global_params, server_state, key, xs_k, ys_k, ms_k,
                sizes_k, idx_k, eval_batches, lr_vec=None,
                async_state=None):
        def body(carry, scan_in):
            if async_mode:
                gp, sstate, k, astate = carry
                kw = {"async_state": astate}
            else:
                gp, sstate, k = carry
                kw = {}
            if lr_schedule:
                x_r, y_r, m_r, s_r, i_r, lr_k = scan_in
            else:
                x_r, y_r, m_r, s_r, i_r = scan_in
            k, round_key = jax.random.split(k)
            args = (gp, None, x_r, y_r, m_r, s_r, i_r, round_key)
            if lr_schedule:
                args = args + (lr_k,)
            new_gp, _state, aux = round_fn(*args, **kw)
            if async_mode:
                aux = dict(aux)
                astate = aux.pop("async_state")
            if server_update_fn is not None:
                srv_args = (gp, new_gp, sstate)
                if "round_rejected" in aux:
                    srv_args += (aux["round_rejected"],)
                new_gp, sstate = server_update_fn(*srv_args)
            metrics = eval_fn(new_gp, *eval_batches)
            carry = (
                (new_gp, sstate, k, astate) if async_mode
                else (new_gp, sstate, k)
            )
            return carry, (metrics, aux)

        xs = (xs_k, ys_k, ms_k, sizes_k, idx_k)
        if lr_schedule:
            xs = xs + (lr_vec,)
        carry0 = (global_params, server_state, key)
        if async_mode:
            carry0 = carry0 + (async_state,)
        carry_out, (metrics_k, aux_k) = jax.lax.scan(body, carry0, xs)
        if async_mode:
            gp, sstate, key, astate = carry_out
            return gp, sstate, key, metrics_k, aux_k, astate
        gp, sstate, key = carry_out
        return gp, sstate, key, metrics_k, aux_k

    return batched


def make_experiment_round_fn(round_fn, lr_schedule: bool):
    """vmap a resident-convention round fn over a leading EXPERIMENT axis
    (the sweep engine's vmapped fleet, sweep/engine.py).

    Each experiment carries its own global params and RNG key chain
    (stacked ``[E, ...]`` / ``[E]`` operands); the client data, masks and
    sizes broadcast (``in_axes=None`` — one shared partition, the sweep
    data contract). The per-experiment body replays the solo host loop's
    round sequence exactly — ``key, round_key = jax.random.split(key)``
    then the round program — so experiment ``i``'s outputs are
    bit-identical to a solo run whose loop holds that key
    (tests/test_sweep.py pins it). ``jax.random.split`` is elementwise on
    the key data, so the vmapped split equals the solo eager split
    bit-for-bit; everything downstream is the same XLA ops with one more
    batch dimension.

    ``lr_schedule`` (trace-time, the PR 5 operand discipline): when True
    the returned function takes a ``[E]`` f32 vector — per-experiment lr
    factor x the round's schedule factor — consumed with ``in_axes=0``;
    when False the round fn is called WITHOUT the operand so the
    constant default constant-folds exactly like the solo program.

    Returns ``fleet(params_E, keys_E, cx, cy, cmask, sizes[, lr_vec]) ->
    (new_params_E, new_keys_E, aux_E)``. Per-client state is not carried
    (the sweep spec refuses persistent client state for fleets — E full
    per-client stacks would defeat the memory envelope).
    """

    def one(params, key, cx, cy, cmask, sizes, lr=None):
        key, round_key = jax.random.split(key)
        args = (params, None, cx, cy, cmask, sizes, round_key)
        if lr is not None:
            args = args + (lr,)
        new_params, _state, aux = round_fn(*args)
        return new_params, key, aux

    data_axes = (None, None, None, None)

    def fleet(params_e, keys_e, cx, cy, cmask, sizes, lr_vec=None):
        if lr_schedule:
            return jax.vmap(one, in_axes=(0, 0) + data_axes + (0,))(
                params_e, keys_e, cx, cy, cmask, sizes, lr_vec
            )
        return jax.vmap(one, in_axes=(0, 0) + data_axes)(
            params_e, keys_e, cx, cy, cmask, sizes
        )

    return fleet


def make_experiment_eval_fn(eval_fn, n_eval_operands: int):
    """vmap a server-eval fn over the experiment axis: stacked params,
    broadcast test batches — the fleet's one-dispatch evaluation of all
    E experiment models (pairs with :func:`make_experiment_round_fn`;
    kept a SEPARATE jitted program like the solo loop's ``evaluate``, so
    the fleet's program structure mirrors the solo round/eval pair)."""
    return jax.vmap(eval_fn, in_axes=(0,) + (None,) * n_eval_operands)


def make_reshaper(sample_shape):
    """Batch preprocess for flattened eval storage: restore sample shape.

    Feeding eval batches as ``[B, prod(shape)]`` instead of ``[B, H, W, C]``
    matters on TPU: device arrays are tiled (8, 128) over the trailing two
    dims, so an explicit 3-channel NHWC input buffer pads its lane dim
    3 -> 128 (a ~40x HBM inflation); a flat last dim has no such padding,
    and XLA picks good layouts for the in-program reshape.
    """

    def reshape(b):
        return b.reshape((b.shape[0],) + tuple(sample_shape))

    return reshape


def pad_eval_set(x, y, batch_size: int, flatten: bool = False):
    """Host-side: pad + reshape a test set to ``[n_batches, batch_size, ...]``
    with a mask, so evaluation is a fixed-shape ``lax.scan``.

    ``flatten=True`` stores samples flattened to 1-D (pair with
    ``make_reshaper`` as the eval preprocess — see its TPU layout note).
    """
    n = x.shape[0]
    if flatten:
        x = x.reshape(n, -1)
    n_batches = (n + batch_size - 1) // batch_size
    padded = n_batches * batch_size
    xp = np.zeros((padded,) + x.shape[1:], dtype=x.dtype)
    yp = np.zeros((padded,), dtype=np.int32)
    mp = np.zeros((padded,), dtype=np.float32)
    xp[:n], yp[:n], mp[:n] = x, y, 1.0
    return (
        xp.reshape((n_batches, batch_size) + x.shape[1:]),
        yp.reshape((n_batches, batch_size)),
        mp.reshape((n_batches, batch_size)),
    )


def make_eval_fn(apply_fn, preprocess: Callable | None = None,
                 name: str = "evaluate"):
    """Build ``evaluate(params, xb, yb, mb) -> {"loss", "accuracy"}``.

    Full-test-set inference as a scan over pre-padded batches; parity with the
    reference's per-round server-side evaluation (``get_metric`` ->
    ``tester.inference()``, fed_server.py:26-32,85-86). vmap-able over a
    params batch for Shapley subset evaluation. ``preprocess`` is applied to
    each x batch inside the scan (e.g. ``make_reshaper`` for flat storage).

    ``name`` becomes the jitted program's display name (compile logs, the
    telemetry recompile counter, profiler traces): several distinct
    programs are built from this factory per run (server eval, Shapley
    subset eval), and an anonymous shared "evaluate" would make a
    recompile warning unattributable.
    """
    def evaluate(params, xb, yb, mb):
        def body(carry, batch):
            x, y, m = batch
            if preprocess is not None:
                x = preprocess(x)
            logits = apply_fn({"params": params}, x)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            nll = -jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]
            correct = (jnp.argmax(logits, axis=1) == y).astype(jnp.float32)
            loss_sum, correct_sum, count = carry
            return (
                loss_sum + jnp.sum(nll * m),
                correct_sum + jnp.sum(correct * m),
                count + jnp.sum(m),
            ), None

        (loss_sum, correct_sum, count), _ = jax.lax.scan(
            body, (0.0, 0.0, 0.0), (xb, yb, mb)
        )
        count = jnp.maximum(count, 1.0)
        return {"loss": loss_sum / count, "accuracy": correct_sum / count}

    evaluate.__name__ = evaluate.__qualname__ = name
    return evaluate
