"""Double-buffered host->HBM cohort pipeline (client_residency='streamed').

The resident round program keeps every per-client array device-resident
for the whole run, so HBM sizes by the POPULATION even when
``participation_fraction`` samples a tiny cohort. Under streamed
residency the full-N arrays live in a host shard store
(data/residency.py) and this module owns the transfer pipeline:

  * **cohort replay** — the round program's cohort draw is re-derived
    HOST-side from the round-key chain (``Algorithm.cohort_indices``,
    the PR 2/PR 6 round-key discipline), so the streamer knows WHICH
    clients a dispatch trains before it runs — no device round-trip;
  * **upload** — the cohort's data slices are gathered from the store
    and ``jax.device_put`` as the round program's pre-gathered operands
    (the streamed calling convention, algorithms/base.py);
  * **prefetch** — the NEXT dispatch's upload runs on a worker thread
    while the current dispatch computes, so at steady state the
    transfer cost is hidden behind compute (``overlap_ratio`` measures
    exactly how much: hidden transfer seconds / total transfer
    seconds);
  * **writeback** — persistent per-client state returned by the round
    scatters back into the host store, which is the source of truth
    between dispatches (checkpoints read it).

Every transfer is timed and byte-counted; the per-dispatch stats become
the schema-v5 ``stream`` sub-object of the metrics record
(utils/reporting.py) and the run totals feed the result dict's
``stream_overlap_ratio`` (bench.py's ``stream`` leg gates it through
scripts/compare_bench.py --stream-overlap-threshold). The cohort-draw
replay is timed too (the ``sample`` phase + the stream record's
``sampler``/``sample_ms`` fields): at N=1e6 the exact replay is the
~1 s host cost that used to hide inside ``client_step``
(``participation_sampler='hashed'`` removes it — ops/sampling.py).

**Mesh composition** (``mesh_devices > 1`` + streamed, single host):
the streamer uploads each cohort slice directly into the client-axis
``PartitionSpec`` layout — one ``jax.device_put`` per array against a
``NamedSharding`` whose client axis is the slice's cohort axis (axis 0
per-round, axis 1 for a stacked ``[k, cohort, ...]`` batched
dispatch), so the host->device transfer is split per shard by the
mesh's client-axis ownership and the round program consumes the slice
without a resharding copy. Double buffering is unchanged (the worker
thread's device_put targets the sharded layout directly) and the
writeback ``device_get`` gathers shard-local cohort state back to the
host store.
"""

from __future__ import annotations

import contextlib
import os
import time
from concurrent.futures import ThreadPoolExecutor

import jax
import numpy as np

from distributed_learning_simulator_tpu.data.residency import (
    HostShardStore,
    plan_owner_assembly,
    tree_bytes,
)
from distributed_learning_simulator_tpu.telemetry import clock

# Straggler injection for the distributed-tracing tests (chaos-harness
# precedent, robustness/chaos.py): when set, this host sleeps that many
# seconds before each spill-exchange barrier — the OTHER hosts' measured
# allgather wait then attributes the stall to this host. Inert unless
# the environment variable is set; never set it in production.
ENV_STRAGGLE = "DLS_STRAGGLE_S"


def _maybe_straggle() -> None:
    s = os.environ.get(ENV_STRAGGLE)
    if s:
        time.sleep(float(s))


@contextlib.contextmanager
def _maybe_span(rec, name: str, cat: str, **kw):
    """Span context when a recorder is attached, no-op otherwise —
    keeps the off-gate path free of even a null context object chain."""
    if rec is None:
        yield None
    else:
        with rec.span(name, cat, **kw) as extra:
            yield extra


def _nbytes(arrays) -> int:
    return sum(
        int(np.asarray(a).nbytes) for a in arrays if a is not None
    )


class CohortStreamer:
    """Owns the host shard store's device side: upload, prefetch, writeback.

    One dispatch's upload is a tuple ``(x, y, m, sizes, idx)`` of device
    arrays — cohort-shaped for a single round (``[cohort, ...]``), or
    stacked ``[k, cohort, ...]`` for a batched dispatch
    (config.rounds_per_dispatch > 1). ``prefetch`` schedules the upload
    on the ONE worker thread (uploads are sequential by construction —
    double buffering needs exactly one in flight); ``acquire`` collects
    it, falling back to a synchronous upload when nothing (or the wrong
    cohort — e.g. after a preemption break) is pending.
    """

    def __init__(self, store: HostShardStore, algorithm, n_clients: int,
                 device=None, mesh=None):
        self.store = store
        self._algorithm = algorithm
        self._n = n_clients
        # device=None (the simulator's single-device runs) uploads
        # UNCOMMITTED to the backend's default device — matching the
        # resident program's jnp.asarray placement. Committedness is part
        # of the executable cache key: a committed round-0 upload turns
        # the round outputs committed, so round 1's params arrive with a
        # different sharding signature than round 0's and the round
        # program compiles twice (one spurious post-warmup compile).
        self._device = device
        # mesh (single-host client-axis mesh, parallel/mesh.py): uploads
        # device_put against a NamedSharding whose client axis is the
        # slice's cohort axis — the per-shard transfer addressed by the
        # mesh's client-axis ownership. Mutually exclusive with device.
        self._mesh = mesh
        # Per-round cohort-replay timing (ops/sampling.py modes): the
        # pending seconds drain into the next acquire's stats as
        # ``sample_ms``; ``last_sample_seconds`` lets the host loop carve
        # the draw out of the enclosing phase window (telemetry/phases).
        self._sampler = getattr(
            algorithm.config, "participation_sampler", "exact"
        ).lower()
        self._sample_pending = 0.0
        self.last_sample_seconds = 0.0
        # Cohort replay runs on the CPU backend when one exists: jax PRNG
        # draws are backend-deterministic, and tiny eager choice/split ops
        # must not interleave with the accelerator's round program. Must
        # be a LOCAL device: under multihost, jax.devices("cpu")[0] is
        # process 0's device globally, and committing the replay operand
        # to a remote device would turn the tiny replay jit into a
        # cross-process computation (observed as a deadlock on the
        # 2-process CPU harness).
        try:
            self._cpu = jax.local_devices(backend="cpu")[0]
        except (RuntimeError, IndexError):
            self._cpu = None
        self._pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="cohort-upload"
        )
        self._pending = None  # (idx_list, future) of the prefetched upload
        # Distributed tracing (telemetry/spans.py): the simulator
        # attaches a recorder when span_trace='on' (plus this host's
        # clock offset vs host 0 and the current round index for skew
        # attribution); None keeps every path below span-free.
        self.span_recorder = None
        self.clock_offset_s = 0.0
        self.span_round: int | None = None
        # Run totals (the result dict's stream_* fields).
        self.totals = {
            "h2d_bytes": 0, "h2d_seconds": 0.0, "hidden_seconds": 0.0,
            "d2h_bytes": 0, "d2h_seconds": 0.0, "sample_seconds": 0.0,
        }

    def _placed(self, a, client_axis: int):
        """device_put one upload array: uncommitted default device
        (single-device runs), the explicit device, or — under a mesh —
        the client-axis NamedSharding with the cohort axis at
        ``client_axis`` (0 for a per-round slice, 1 for a stacked
        ``[k, cohort, ...]`` batched dispatch)."""
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            spec = PartitionSpec(
                *([None] * client_axis), self._mesh.axis_names[0]
            )
            return jax.device_put(a, NamedSharding(self._mesh, spec))
        if self._device is not None:
            return jax.device_put(a, self._device)
        return jax.device_put(a)

    # ---- cohort replay -----------------------------------------------------
    def cohort_for(self, round_key, n=None, alive=None, k=None):
        """Host replay of the cohort the round program draws from
        ``round_key`` (Algorithm.cohort_indices contract): a host numpy
        index array, or None when the cohort is the whole population.
        Timed: the draw cost (the exact replay's O(N log N) permutation
        vs the hashed mode's O(cohort) hash — ops/sampling.py) lands in
        the next acquire's ``sample_ms`` and the ``sample`` phase.

        ``n``/``alive``/``k`` serve ``population='dynamic'``
        (robustness/population.py): the draw covers the CURRENT
        registered index space with departed indices masked out, at the
        pinned startup cohort size — defaults keep the static replay
        byte-for-byte."""
        t0 = clock.monotonic()
        if self._cpu is not None:
            round_key = jax.device_put(round_key, self._cpu)
        idx = self._algorithm.cohort_indices(
            round_key, self._n if n is None else n,
            alive=alive, n_participants=k,
        )
        dt = clock.monotonic() - t0
        self._sample_pending += dt
        self.last_sample_seconds = dt
        self.totals["sample_seconds"] += dt
        return None if idx is None else np.asarray(idx)

    # ---- upload / prefetch -------------------------------------------------
    def _upload(self, idx_list, stack: bool):
        """Worker-thread body: gather + device_put + block, timed.

        ``idx_list`` is one index array per round in the dispatch; a
        per-round dispatch (``stack=False``, one entry) uploads
        cohort-shaped arrays, a batched scan dispatch (``stack=True``)
        stacks them ``[k, cohort, ...]`` — even at k=1, where the
        remainder scan still consumes a leading round axis.
        """
        with _maybe_span(
            self.span_recorder, "prefetch_upload", "stream",
            round_idx=self.span_round,
        ) as _sp:
            return self._upload_body(idx_list, stack, _sp)

    def _upload_body(self, idx_list, stack: bool, _sp):
        t0 = clock.monotonic()
        slices = [self.store.gather_data(idx) for idx in idx_list]
        if not stack:
            x, y, m, s = slices[0]
            # idx None = the whole population (upload_full): the round
            # program's idx operand stays None too.
            idx_arr = (
                None if idx_list[0] is None
                else np.asarray(idx_list[0], dtype=np.int32)
            )
        else:
            x, y, m, s = (
                np.stack([sl[j] for sl in slices]) for j in range(4)
            )
            idx_arr = np.stack(
                [np.asarray(idx, dtype=np.int32) for idx in idx_list]
            )
        host_arrays = (x, y, m, s, idx_arr)
        # Cohort axis: leading for a per-round slice, axis 1 behind the
        # round axis for a stacked batched dispatch — the mesh placement
        # shards exactly that axis (PartitionSpec layout).
        client_axis = 1 if stack else 0
        arrays = tuple(
            None if a is None else self._placed(a, client_axis)
            for a in host_arrays
        )
        # device_put is asynchronous; the transfer is only DONE here —
        # which is the point: this block runs on the worker thread, so at
        # steady state the wait overlaps the main thread's dispatch.
        jax.block_until_ready(arrays)
        nbytes = _nbytes(host_arrays)
        if _sp is not None:
            _sp["bytes"] = nbytes
        return arrays, nbytes, clock.monotonic() - t0

    def prefetch(self, idx_list, stack: bool = False) -> None:
        """Schedule the upload for the NEXT dispatch's cohorts; returns
        immediately. At most one prefetch is in flight (a second call
        before acquire drains the first — the pipeline is strictly
        double-buffered)."""
        if self._pending is not None:
            # Shouldn't happen in the dispatch loop's sequencing; drain
            # rather than leak a future.
            self._pending[2].result()
            self._pending = None
        self._pending = (
            idx_list, stack, self._pool.submit(self._upload, idx_list, stack)
        )

    def acquire(self, idx_list, stack: bool = False):
        """Collect the upload for ``idx_list``, preferring the prefetched
        one. Returns ``((x, y, m, sizes, idx_dev), stats)`` where stats
        is this upload's contribution to the stream record."""
        arrays = None
        if self._pending is not None:
            pend_idx, pend_stack, fut = self._pending
            self._pending = None
            if (
                pend_stack == stack
                and len(pend_idx) == len(idx_list)
                and all(
                    np.array_equal(a, b)
                    for a, b in zip(pend_idx, idx_list)
                )
            ):
                t0 = clock.monotonic()
                arrays, nbytes, dt = fut.result()
                blocked = clock.monotonic() - t0
                hidden = max(dt - blocked, 0.0)
            else:
                # A cohort the loop no longer wants (resume/preemption
                # path changed the sequence): drain and re-upload. The
                # stale transfer still moved real bytes over the bus —
                # count it in the run totals (as unhidden time) so the
                # accounting never under-reports traffic.
                _, stale_bytes, stale_dt = fut.result()
                self.totals["h2d_bytes"] += stale_bytes
                self.totals["h2d_seconds"] += stale_dt
        if arrays is None:
            arrays, nbytes, dt = self._upload(idx_list, stack)
            hidden = 0.0
        self.totals["h2d_bytes"] += nbytes
        self.totals["h2d_seconds"] += dt
        self.totals["hidden_seconds"] += hidden
        stats = {
            "h2d_bytes": nbytes,
            "h2d_seconds": round(dt, 6),
            "hidden_seconds": round(hidden, 6),
            "overlap_ratio": round(hidden / dt, 4) if dt > 0 else 0.0,
        }
        if any(idx is not None for idx in idx_list):
            # Sampled cohorts: name the sampler and drain the pending
            # cohort-replay seconds into this dispatch's record (the
            # host cost the phase table's ``sample`` phase carries).
            stats["sampler"] = self._sampler
            stats["sample_ms"] = round(self._sample_pending * 1e3, 3)
            self._sample_pending = 0.0
        return arrays, stats

    def upload_full(self):
        """One-shot upload of the WHOLE population (the degenerate
        full-cohort regime: participation_fraction >= 1, e.g. sign_SGD's
        per-step vote over everyone). The arrays stay device-resident for
        the run — streamed residency then only moves WHERE the startup
        upload is accounted."""
        arrays, nbytes, dt = self._upload([None], stack=False)
        self.totals["h2d_bytes"] += nbytes
        self.totals["h2d_seconds"] += dt
        stats = {
            "h2d_bytes": nbytes,
            "h2d_seconds": round(dt, 6),
            "hidden_seconds": 0.0,
            "overlap_ratio": 0.0,
        }
        return arrays, stats

    # ---- writeback ---------------------------------------------------------
    def writeback(self, idx, new_state_k, stats: dict | None = None):
        """Fetch the round's cohort state to host and scatter it into the
        store (Algorithm.scatter_client_state). No-op for stateless
        algorithms. ``stats`` (an acquire stats dict) grows the d2h
        fields in place when given."""
        if self.store.state is None:
            return
        t0 = clock.monotonic()
        host_state = jax.device_get(new_state_k)
        self._algorithm.scatter_client_state(self.store, idx, host_state)
        dt = clock.monotonic() - t0
        nbytes = tree_bytes(host_state)
        self.totals["d2h_bytes"] += nbytes
        self.totals["d2h_seconds"] += dt
        if stats is not None:
            stats["d2h_bytes"] = nbytes
            stats["d2h_seconds"] = round(dt, 6)

    # ---- reporting ---------------------------------------------------------
    def overlap_ratio(self) -> float:
        """Run-total hidden-transfer fraction: how much of the host->HBM
        upload time the prefetch hid behind compute."""
        total = self.totals["h2d_seconds"]
        return self.totals["hidden_seconds"] / total if total > 0 else 0.0

    def close(self) -> None:
        if self._pending is not None:
            # Never leak a worker-thread upload past the run.
            try:
                self._pending[2].result()
            except Exception:
                pass
            self._pending = None
        self._pool.shutdown(wait=True)


# --- distributed shard store: the multihost streamer ------------------------


def _pad_rows(a: np.ndarray, rows: int) -> np.ndarray:
    """Zero-pad a row payload to the exchange's common row count."""
    if a.shape[0] == rows:
        return a
    out = np.zeros((rows,) + a.shape[1:], a.dtype)
    out[: a.shape[0]] = a
    return out


def _pad_bucket(n: int) -> int:
    """Round a spill row count up to the next power of two.

    The allgather compiles one tiny program per distinct payload shape;
    bucketing bounds the distinct shapes at log2(cohort) over a whole
    run instead of one per distinct per-round spill count.
    """
    return 1 << max(int(n) - 1, 0).bit_length()


class _ExecPlan:
    """One round's owner-sharded assembly, resolved for THIS host.

    Wraps the global :class:`data.residency.AssemblyPlan` (identical on
    every host) with this host's derived routing — which of its block
    rows hold its own members, where each spill-in row comes from in
    the forward exchange, and where each of its spilled-out members
    sits for the writeback return trip — plus the assembled host-side
    data block once :meth:`DistributedCohortStreamer.plan` has run the
    exchange.
    """

    def __init__(self, plan, host_id: int):
        self.plan = plan
        self.idx = plan.idx
        self.blo = int(plan.block_bounds[host_id])
        self.bhi = int(plan.block_bounds[host_id + 1])
        occupants_q = plan.draw_pos[self.blo:self.bhi]
        own = plan.owners[occupants_q] == host_id
        self.own_rows_rel = np.flatnonzero(own)
        self.own_ids = plan.idx[occupants_q[own]]
        # Spill-in: rows of MY block served by other hosts' members.
        sel_in = plan.spill_block == host_id
        self.in_rows_rel = plan.spill_rows[sel_in] - self.blo
        self.in_src_host = plan.spill_owner[sel_in]
        self.in_src_slot = plan.slot_in_owner[sel_in]
        # Spill-out: MY members placed in other hosts' blocks.
        sel_out = plan.spill_owner == host_id
        self.out_ids = plan.spill_ids[sel_out]
        self.out_block = plan.spill_block[sel_out]
        self.out_slot = plan.slot_in_block[sel_out]
        self.total_spill = int(plan.spill_q.size)
        self.pad_fwd = _pad_bucket(int(plan.send_counts().max()))
        self.pad_back = _pad_bucket(int(plan.recv_counts().max()))
        self.data_block = None  # filled by DistributedCohortStreamer.plan
        self.dcn_bytes = 0
        self.assemble_seconds = 0.0


class DistributedCohortStreamer(CohortStreamer):
    """Owner-sharded cohort assembly across host processes.

    The multihost face of streamed residency: the full-N client arrays
    live host-SHARDED (each process owns an N/num_hosts slice —
    data/residency.DistributedShardStore), the hashed sampler's
    round-key-determinism lets every host replay the FULL cohort
    independently, and each round's cohort is permuted into
    owner-contiguous groups aligned with the hosts' addressable shards
    of the client-axis ``PartitionSpec``
    (data/residency.plan_owner_assembly). Each host then serves its own
    members straight into its addressable shards via
    ``jax.make_array_from_single_device_arrays`` — no full-N array ever
    crosses DCN; the only cross-host client data is the per-round
    ownership-imbalance spill (expected O(sqrt(cohort)) rows), moved by
    a padded ``process_allgather`` and byte-counted into ``dcn_bytes``.
    The ``draw_pos`` operand the upload carries lets the round program
    permute its per-position draws back to the draw-order assignment
    (algorithms/fedavg.cohort_round), which is what keeps the
    owner-permuted run equal to the 1-process run per client.

    Threading contract: the spill exchange is a COLLECTIVE, so it runs
    on the MAIN thread (inside :meth:`plan`, which the round loop calls
    at the same point on every host); the worker thread only does the
    local ``device_put`` assembly — collective launch order therefore
    stays identical across processes, which is what keeps concurrent
    prefetch deadlock-free.
    """

    def __init__(self, store, algorithm, n_clients: int, mesh,
                 block_bounds):
        super().__init__(store, algorithm, n_clients, mesh=mesh)
        self._host = store.host_id
        self._n_hosts = store.n_hosts
        self._block_bounds = np.asarray(block_bounds, np.int64)
        self._cohort = int(self._block_bounds[-1])
        self.totals.update({"dcn_bytes": 0, "spill_rows": 0})

    # ---- exchange ----------------------------------------------------------
    def _allgather(self, leaves, pad: int, name: str = "spill"):
        """Padded all-to-all of per-host row payloads: every host
        contributes ``pad`` rows per leaf (zeros beyond its real send
        count — every host knows every count from the shared plan, so
        no negotiation); returns leaves of shape ``[n_hosts, pad, ...]``.
        Collective — main thread only.

        With a span recorder attached, the exchange splits into a
        ``<name>_wait`` span (a tiny arrival-stamp allgather: its
        duration is dominated by the SLOWEST host's arrival, and the
        gathered aligned stamps yield the round's measured barrier skew)
        and a ``<name>_xfer`` span (the payload allgather proper). The
        wait span is flight-recorder eager: a host stuck here during a
        peer's death leaves its open-line on disk for the postmortem.
        """
        from jax.experimental import multihost_utils

        from distributed_learning_simulator_tpu.parallel.multihost import (
            allgather_wall_stamps,
        )

        _maybe_straggle()
        rec = self.span_recorder
        if rec is not None:
            with rec.span(
                f"{name}_wait", "dcn_wait", round_idx=self.span_round,
                eager=True,
            ) as w:
                stamps = allgather_wall_stamps(
                    clock.wall() - self.clock_offset_s
                )
                skew_ms = float(stamps.max() - stamps.min()) * 1e3
                w["skew_ms"] = round(skew_ms, 3)
            if self.span_round is not None:
                rec.note_skew(self.span_round, "spill_skew_ms", skew_ms)
        padded = tuple(_pad_rows(np.asarray(a), pad) for a in leaves)
        with _maybe_span(
            rec, f"{name}_xfer", "dcn", round_idx=self.span_round,
        ) as x:
            gathered = multihost_utils.process_allgather(
                padded, tiled=False
            )
            nbytes = sum(int(g.nbytes) for g in gathered)
            if x is not None:
                x["bytes"] = nbytes
        self.totals["dcn_bytes"] += nbytes
        return list(gathered), nbytes

    def _assemble_block(self, ex: _ExecPlan, local_leaves):
        """Fill this host's block rows for each leaf: own members from
        the local shard, spill-in rows from the forward exchange."""
        own_local = self.store.to_local(ex.own_ids)
        send_local = self.store.to_local(
            ex.out_ids
        ) if ex.out_ids.size else np.empty(0, np.int64)
        gathered = None
        if ex.total_spill:
            send = [
                np.take(np.asarray(a), send_local, axis=0)
                for a in local_leaves
            ]
            gathered, nbytes = self._allgather(send, ex.pad_fwd)
            ex.dcn_bytes += nbytes
        out = []
        for li, a in enumerate(local_leaves):
            a = np.asarray(a)
            blk = np.empty(
                (ex.bhi - ex.blo,) + a.shape[1:], a.dtype
            )
            if ex.own_rows_rel.size:
                blk[ex.own_rows_rel] = np.take(a, own_local, axis=0)
            if ex.in_rows_rel.size:
                blk[ex.in_rows_rel] = gathered[li][
                    ex.in_src_host, ex.in_src_slot
                ]
            out.append(blk)
        return out

    # ---- planning ----------------------------------------------------------
    def plan(self, idx_np) -> _ExecPlan:
        """Resolve one round's owner-sharded assembly: the global
        row-assignment plan, plus this host's data block with spill-in
        rows exchanged. Main thread (the exchange is a collective)."""
        t0 = clock.monotonic()
        p = plan_owner_assembly(
            np.asarray(idx_np, np.int64), self.store.owner_bounds,
            self._block_bounds,
        )
        ex = _ExecPlan(p, self._host)
        ex.data_block = self._assemble_block(
            ex, [self.store.x, self.store.y, self.store.mask,
                 self.store.sizes],
        )
        ex.assemble_seconds = clock.monotonic() - t0
        self.totals["spill_rows"] += ex.total_spill
        return ex

    # ---- placement ---------------------------------------------------------
    def _place_block(self, block: np.ndarray, global_len: int, blo: int,
                     owned: bool = False):
        """This host's block rows -> its addressable shards of the
        client-axis PartitionSpec, assembled into one global array via
        jax.make_array_from_single_device_arrays (the only constructor
        that lets each process contribute exactly the rows it holds).

        ``owned=True`` forces XLA-owned shard buffers: device_put of a
        numpy slice is zero-copy on the CPU backend, and a DONATED
        operand backed by numpy-owned memory lets XLA write into (and
        free) host memory — the `_owned_device_tree` hazard, observed
        here as intermittent garbage part_sizes blowing up the round
        aggregate. Required for the state tree (round_jit donates it);
        the data blocks stay zero-copy (non-donated, and the plan keeps
        their numpy backing alive through the dispatch)."""
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec

        gshape = (global_len,) + block.shape[1:]
        spec = PartitionSpec(
            self._mesh.axis_names[0], *([None] * (block.ndim - 1))
        )
        sharding = NamedSharding(self._mesh, spec)
        arrs = []
        for d, idxs in sharding.addressable_devices_indices_map(
            gshape
        ).items():
            sl = idxs[0]
            start = 0 if sl.start is None else sl.start
            stop = global_len if sl.stop is None else sl.stop
            local = block[start - blo: stop - blo]
            if owned:
                with jax.default_device(d):
                    arrs.append(jnp.array(local, copy=True))
            else:
                arrs.append(jax.device_put(local, d))
        return jax.make_array_from_single_device_arrays(
            gshape, sharding, arrs
        )

    def _replicated(self, a):
        """Replicated placement WITHOUT jax.device_put's cross-process
        value check: device_put against a non-addressable sharding runs
        a hidden assert_equal COLLECTIVE, and this is called from the
        worker thread — a collective there would race the main thread's
        (round dispatch / exchange) collectives and deadlock the hosts.
        Each local device gets the full value (identical on every host
        by construction: the plan is a pure function of the replayed
        cohort), assembled locally."""
        from jax.sharding import NamedSharding, PartitionSpec

        a = np.asarray(a)
        sharding = NamedSharding(self._mesh, PartitionSpec())
        arrs = [
            jax.device_put(a, d) for d in sharding.addressable_devices
        ]
        return jax.make_array_from_single_device_arrays(
            a.shape, sharding, arrs
        )

    def _upload_plan(self, ex: _ExecPlan):
        """Worker-thread body: local device_put assembly only (the
        exchange already ran in plan(), on the main thread)."""
        with _maybe_span(
            self.span_recorder, "prefetch_upload", "stream",
            round_idx=self.span_round,
        ) as _sp:
            return self._upload_plan_body(ex, _sp)

    def _upload_plan_body(self, ex: _ExecPlan, _sp):
        t0 = clock.monotonic()
        blo = int(self._block_bounds[self._host])
        x, y, m, s = (
            self._place_block(b, self._cohort, blo) for b in ex.data_block
        )
        sidx = self._replicated(np.asarray(ex.plan.idx_perm, np.int32))
        dpos = self._replicated(np.asarray(ex.plan.draw_pos, np.int32))
        arrays = (x, y, m, s, sidx, dpos)
        jax.block_until_ready(arrays)
        nbytes = sum(int(b.nbytes) for b in ex.data_block) + int(
            ex.plan.idx_perm.nbytes + ex.plan.draw_pos.nbytes
        )
        if _sp is not None:
            _sp["bytes"] = nbytes
        return arrays, nbytes, clock.monotonic() - t0

    # ---- upload / prefetch (plan-keyed double buffering) -------------------
    def prefetch_plan(self, ex: _ExecPlan) -> None:
        if self._pending is not None:
            self._pending[1].result()
            self._pending = None
        self._pending = (ex, self._pool.submit(self._upload_plan, ex))

    def acquire_plan(self, ex: _ExecPlan):
        """Collect the upload for ``ex``, preferring the prefetched one
        (same double-buffer semantics as the base acquire, keyed by the
        plan's cohort)."""
        arrays = None
        if self._pending is not None:
            pend_ex, fut = self._pending
            self._pending = None
            if pend_ex is ex or np.array_equal(pend_ex.idx, ex.idx):
                t0 = clock.monotonic()
                arrays, nbytes, dt = fut.result()
                blocked = clock.monotonic() - t0
                hidden = max(dt - blocked, 0.0)
                ex = pend_ex
            else:
                _, stale_bytes, stale_dt = fut.result()
                self.totals["h2d_bytes"] += stale_bytes
                self.totals["h2d_seconds"] += stale_dt
        if arrays is None:
            arrays, nbytes, dt = self._upload_plan(ex)
            hidden = 0.0
        self.totals["h2d_bytes"] += nbytes
        self.totals["h2d_seconds"] += dt
        self.totals["hidden_seconds"] += hidden
        stats = {
            "h2d_bytes": nbytes,
            "h2d_seconds": round(dt, 6),
            "hidden_seconds": round(hidden, 6),
            "overlap_ratio": round(hidden / dt, 4) if dt > 0 else 0.0,
            "sampler": self._sampler,
            "sample_ms": round(self._sample_pending * 1e3, 3),
            "spill_rows": ex.total_spill,
            "dcn_bytes": ex.dcn_bytes,
        }
        self._sample_pending = 0.0
        return arrays, stats, ex

    # ---- persistent per-client state ---------------------------------------
    def gather_state_device(self, ex: _ExecPlan):
        """Assemble this host's block of the cohort's persistent state
        (own rows from the local shard, spill-in rows exchanged) and
        place it into the client-axis PartitionSpec layout. None for
        stateless algorithms. Main thread (collective)."""
        if self.store.state is None:
            return None
        from distributed_learning_simulator_tpu.data.residency import (
            tree_map_np,
        )

        leaves, treedef = jax.tree_util.tree_flatten(
            tree_map_np(np.asarray, self.store.state)
        )
        blocks = self._assemble_block(ex, leaves)
        blo = int(self._block_bounds[self._host])
        placed = [
            self._place_block(b, self._cohort, blo, owned=True)
            for b in blocks
        ]
        return jax.tree_util.tree_unflatten(treedef, placed)

    def writeback(self, ex, new_state_k, stats: dict | None = None):
        """Scatter the round's cohort state back to its OWNERS: each
        host fetches its addressable output shards, keeps its own
        members' rows, and returns the spill rows to their owning hosts
        through the reverse exchange. Main thread (collective)."""
        if self.store.state is None:
            return
        t0 = clock.monotonic()

        def local_rows(leaf):
            shards = sorted(
                leaf.addressable_shards,
                key=lambda s: s.index[0].start or 0,
            )
            return np.concatenate(
                [np.asarray(s.data) for s in shards], axis=0
            )

        host_state = jax.tree_util.tree_map(local_rows, new_state_k)
        leaves, treedef = jax.tree_util.tree_flatten(host_state)
        if ex.own_ids.size:
            own_tree = jax.tree_util.tree_unflatten(
                treedef, [l[ex.own_rows_rel] for l in leaves]
            )
            self._algorithm.scatter_client_state(
                self.store, ex.own_ids, own_tree
            )
        dcn = 0
        if ex.total_spill:
            send = [l[ex.in_rows_rel] for l in leaves]
            gathered, dcn = self._allgather(
                send, ex.pad_back, name="writeback"
            )
            if ex.out_ids.size:
                mine = [
                    g[ex.out_block, ex.out_slot] for g in gathered
                ]
                self._algorithm.scatter_client_state(
                    self.store, ex.out_ids,
                    jax.tree_util.tree_unflatten(treedef, mine),
                )
        dt = clock.monotonic() - t0
        nbytes = sum(int(l.nbytes) for l in leaves)
        self.totals["d2h_bytes"] += nbytes
        self.totals["d2h_seconds"] += dt
        if stats is not None:
            stats["d2h_bytes"] = nbytes
            stats["d2h_seconds"] = round(dt, 6)
            stats["dcn_bytes"] = stats.get("dcn_bytes", 0) + dcn

    # ---- full-cohort regime ------------------------------------------------
    def upload_full(self):
        """One-shot whole-population upload: each host places its OWNED
        slice into its addressable shards of the full-N client axis
        (owner bounds are the device blocks by construction —
        data/residency.host_axis_bounds). Zero DCN traffic."""
        t0 = clock.monotonic()
        x, y, m, s = self.store.gather_data(None)
        n = int(self.store.owner_bounds[-1])
        arrays = tuple(
            self._place_block(np.asarray(a), n, self.store.lo)
            for a in (x, y, m, s)
        ) + (None,)
        jax.block_until_ready([a for a in arrays if a is not None])
        nbytes = self.store.data_bytes()
        dt = clock.monotonic() - t0
        self.totals["h2d_bytes"] += nbytes
        self.totals["h2d_seconds"] += dt
        stats = {
            "h2d_bytes": nbytes,
            "h2d_seconds": round(dt, 6),
            "hidden_seconds": 0.0,
            "overlap_ratio": 0.0,
        }
        return arrays, stats

    # ---- reporting ---------------------------------------------------------
    def multihost_record(self, ex: _ExecPlan | None, stats: dict) -> dict:
        """The schema-v11 ``multihost`` record sub-object: this host's
        shard-ownership summary plus the round's assembly traffic
        (utils/reporting.build_round_record routes it)."""
        shard_bytes = self.store.data_bytes()
        if self.store.state is not None:
            shard_bytes += self.store.state_bytes()
        return {
            "hosts": self._n_hosts,
            "host_id": self._host,
            "owned_clients": self.store.n_owned,
            "shard_bytes": int(shard_bytes),
            "spill_rows": int(ex.total_spill) if ex is not None else 0,
            "dcn_bytes": int(stats.get("dcn_bytes", 0)),
            "h2d_seconds": stats.get("h2d_seconds", 0.0),
            "overlap_ratio": stats.get("overlap_ratio", 0.0),
        }
