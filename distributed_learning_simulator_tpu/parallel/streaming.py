"""Double-buffered host->HBM cohort pipeline (client_residency='streamed').

The resident round program keeps every per-client array device-resident
for the whole run, so HBM sizes by the POPULATION even when
``participation_fraction`` samples a tiny cohort. Under streamed
residency the full-N arrays live in a host shard store
(data/residency.py) and this module owns the transfer pipeline:

  * **cohort replay** — the round program's cohort draw is re-derived
    HOST-side from the round-key chain (``Algorithm.cohort_indices``,
    the PR 2/PR 6 round-key discipline), so the streamer knows WHICH
    clients a dispatch trains before it runs — no device round-trip;
  * **upload** — the cohort's data slices are gathered from the store
    and ``jax.device_put`` as the round program's pre-gathered operands
    (the streamed calling convention, algorithms/base.py);
  * **prefetch** — the NEXT dispatch's upload runs on a worker thread
    while the current dispatch computes, so at steady state the
    transfer cost is hidden behind compute (``overlap_ratio`` measures
    exactly how much: hidden transfer seconds / total transfer
    seconds);
  * **writeback** — persistent per-client state returned by the round
    scatters back into the host store, which is the source of truth
    between dispatches (checkpoints read it).

Every transfer is timed and byte-counted; the per-dispatch stats become
the schema-v5 ``stream`` sub-object of the metrics record
(utils/reporting.py) and the run totals feed the result dict's
``stream_overlap_ratio`` (bench.py's ``stream`` leg gates it through
scripts/compare_bench.py --stream-overlap-threshold). The cohort-draw
replay is timed too (the ``sample`` phase + the stream record's
``sampler``/``sample_ms`` fields): at N=1e6 the exact replay is the
~1 s host cost that used to hide inside ``client_step``
(``participation_sampler='hashed'`` removes it — ops/sampling.py).

**Mesh composition** (``mesh_devices > 1`` + streamed, single host):
the streamer uploads each cohort slice directly into the client-axis
``PartitionSpec`` layout — one ``jax.device_put`` per array against a
``NamedSharding`` whose client axis is the slice's cohort axis (axis 0
per-round, axis 1 for a stacked ``[k, cohort, ...]`` batched
dispatch), so the host->device transfer is split per shard by the
mesh's client-axis ownership and the round program consumes the slice
without a resharding copy. Double buffering is unchanged (the worker
thread's device_put targets the sharded layout directly) and the
writeback ``device_get`` gathers shard-local cohort state back to the
host store.
"""

from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor

import jax
import numpy as np

from distributed_learning_simulator_tpu.data.residency import (
    HostShardStore,
    tree_bytes,
)


def _nbytes(arrays) -> int:
    return sum(
        int(np.asarray(a).nbytes) for a in arrays if a is not None
    )


class CohortStreamer:
    """Owns the host shard store's device side: upload, prefetch, writeback.

    One dispatch's upload is a tuple ``(x, y, m, sizes, idx)`` of device
    arrays — cohort-shaped for a single round (``[cohort, ...]``), or
    stacked ``[k, cohort, ...]`` for a batched dispatch
    (config.rounds_per_dispatch > 1). ``prefetch`` schedules the upload
    on the ONE worker thread (uploads are sequential by construction —
    double buffering needs exactly one in flight); ``acquire`` collects
    it, falling back to a synchronous upload when nothing (or the wrong
    cohort — e.g. after a preemption break) is pending.
    """

    def __init__(self, store: HostShardStore, algorithm, n_clients: int,
                 device=None, mesh=None):
        self.store = store
        self._algorithm = algorithm
        self._n = n_clients
        # device=None (the simulator's single-device runs) uploads
        # UNCOMMITTED to the backend's default device — matching the
        # resident program's jnp.asarray placement. Committedness is part
        # of the executable cache key: a committed round-0 upload turns
        # the round outputs committed, so round 1's params arrive with a
        # different sharding signature than round 0's and the round
        # program compiles twice (one spurious post-warmup compile).
        self._device = device
        # mesh (single-host client-axis mesh, parallel/mesh.py): uploads
        # device_put against a NamedSharding whose client axis is the
        # slice's cohort axis — the per-shard transfer addressed by the
        # mesh's client-axis ownership. Mutually exclusive with device.
        self._mesh = mesh
        # Per-round cohort-replay timing (ops/sampling.py modes): the
        # pending seconds drain into the next acquire's stats as
        # ``sample_ms``; ``last_sample_seconds`` lets the host loop carve
        # the draw out of the enclosing phase window (telemetry/phases).
        self._sampler = getattr(
            algorithm.config, "participation_sampler", "exact"
        ).lower()
        self._sample_pending = 0.0
        self.last_sample_seconds = 0.0
        # Cohort replay runs on the CPU backend when one exists: jax PRNG
        # draws are backend-deterministic, and tiny eager choice/split ops
        # must not interleave with the accelerator's round program.
        try:
            self._cpu = jax.devices("cpu")[0]
        except RuntimeError:
            self._cpu = None
        self._pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="cohort-upload"
        )
        self._pending = None  # (idx_list, future) of the prefetched upload
        # Run totals (the result dict's stream_* fields).
        self.totals = {
            "h2d_bytes": 0, "h2d_seconds": 0.0, "hidden_seconds": 0.0,
            "d2h_bytes": 0, "d2h_seconds": 0.0, "sample_seconds": 0.0,
        }

    def _placed(self, a, client_axis: int):
        """device_put one upload array: uncommitted default device
        (single-device runs), the explicit device, or — under a mesh —
        the client-axis NamedSharding with the cohort axis at
        ``client_axis`` (0 for a per-round slice, 1 for a stacked
        ``[k, cohort, ...]`` batched dispatch)."""
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            spec = PartitionSpec(
                *([None] * client_axis), self._mesh.axis_names[0]
            )
            return jax.device_put(a, NamedSharding(self._mesh, spec))
        if self._device is not None:
            return jax.device_put(a, self._device)
        return jax.device_put(a)

    # ---- cohort replay -----------------------------------------------------
    def cohort_for(self, round_key, n=None, alive=None, k=None):
        """Host replay of the cohort the round program draws from
        ``round_key`` (Algorithm.cohort_indices contract): a host numpy
        index array, or None when the cohort is the whole population.
        Timed: the draw cost (the exact replay's O(N log N) permutation
        vs the hashed mode's O(cohort) hash — ops/sampling.py) lands in
        the next acquire's ``sample_ms`` and the ``sample`` phase.

        ``n``/``alive``/``k`` serve ``population='dynamic'``
        (robustness/population.py): the draw covers the CURRENT
        registered index space with departed indices masked out, at the
        pinned startup cohort size — defaults keep the static replay
        byte-for-byte."""
        t0 = time.perf_counter()
        if self._cpu is not None:
            round_key = jax.device_put(round_key, self._cpu)
        idx = self._algorithm.cohort_indices(
            round_key, self._n if n is None else n,
            alive=alive, n_participants=k,
        )
        dt = time.perf_counter() - t0
        self._sample_pending += dt
        self.last_sample_seconds = dt
        self.totals["sample_seconds"] += dt
        return None if idx is None else np.asarray(idx)

    # ---- upload / prefetch -------------------------------------------------
    def _upload(self, idx_list, stack: bool):
        """Worker-thread body: gather + device_put + block, timed.

        ``idx_list`` is one index array per round in the dispatch; a
        per-round dispatch (``stack=False``, one entry) uploads
        cohort-shaped arrays, a batched scan dispatch (``stack=True``)
        stacks them ``[k, cohort, ...]`` — even at k=1, where the
        remainder scan still consumes a leading round axis.
        """
        t0 = time.perf_counter()
        slices = [self.store.gather_data(idx) for idx in idx_list]
        if not stack:
            x, y, m, s = slices[0]
            # idx None = the whole population (upload_full): the round
            # program's idx operand stays None too.
            idx_arr = (
                None if idx_list[0] is None
                else np.asarray(idx_list[0], dtype=np.int32)
            )
        else:
            x, y, m, s = (
                np.stack([sl[j] for sl in slices]) for j in range(4)
            )
            idx_arr = np.stack(
                [np.asarray(idx, dtype=np.int32) for idx in idx_list]
            )
        host_arrays = (x, y, m, s, idx_arr)
        # Cohort axis: leading for a per-round slice, axis 1 behind the
        # round axis for a stacked batched dispatch — the mesh placement
        # shards exactly that axis (PartitionSpec layout).
        client_axis = 1 if stack else 0
        arrays = tuple(
            None if a is None else self._placed(a, client_axis)
            for a in host_arrays
        )
        # device_put is asynchronous; the transfer is only DONE here —
        # which is the point: this block runs on the worker thread, so at
        # steady state the wait overlaps the main thread's dispatch.
        jax.block_until_ready(arrays)
        return arrays, _nbytes(host_arrays), time.perf_counter() - t0

    def prefetch(self, idx_list, stack: bool = False) -> None:
        """Schedule the upload for the NEXT dispatch's cohorts; returns
        immediately. At most one prefetch is in flight (a second call
        before acquire drains the first — the pipeline is strictly
        double-buffered)."""
        if self._pending is not None:
            # Shouldn't happen in the dispatch loop's sequencing; drain
            # rather than leak a future.
            self._pending[2].result()
            self._pending = None
        self._pending = (
            idx_list, stack, self._pool.submit(self._upload, idx_list, stack)
        )

    def acquire(self, idx_list, stack: bool = False):
        """Collect the upload for ``idx_list``, preferring the prefetched
        one. Returns ``((x, y, m, sizes, idx_dev), stats)`` where stats
        is this upload's contribution to the stream record."""
        arrays = None
        if self._pending is not None:
            pend_idx, pend_stack, fut = self._pending
            self._pending = None
            if (
                pend_stack == stack
                and len(pend_idx) == len(idx_list)
                and all(
                    np.array_equal(a, b)
                    for a, b in zip(pend_idx, idx_list)
                )
            ):
                t0 = time.perf_counter()
                arrays, nbytes, dt = fut.result()
                blocked = time.perf_counter() - t0
                hidden = max(dt - blocked, 0.0)
            else:
                # A cohort the loop no longer wants (resume/preemption
                # path changed the sequence): drain and re-upload. The
                # stale transfer still moved real bytes over the bus —
                # count it in the run totals (as unhidden time) so the
                # accounting never under-reports traffic.
                _, stale_bytes, stale_dt = fut.result()
                self.totals["h2d_bytes"] += stale_bytes
                self.totals["h2d_seconds"] += stale_dt
        if arrays is None:
            arrays, nbytes, dt = self._upload(idx_list, stack)
            hidden = 0.0
        self.totals["h2d_bytes"] += nbytes
        self.totals["h2d_seconds"] += dt
        self.totals["hidden_seconds"] += hidden
        stats = {
            "h2d_bytes": nbytes,
            "h2d_seconds": round(dt, 6),
            "hidden_seconds": round(hidden, 6),
            "overlap_ratio": round(hidden / dt, 4) if dt > 0 else 0.0,
        }
        if any(idx is not None for idx in idx_list):
            # Sampled cohorts: name the sampler and drain the pending
            # cohort-replay seconds into this dispatch's record (the
            # host cost the phase table's ``sample`` phase carries).
            stats["sampler"] = self._sampler
            stats["sample_ms"] = round(self._sample_pending * 1e3, 3)
            self._sample_pending = 0.0
        return arrays, stats

    def upload_full(self):
        """One-shot upload of the WHOLE population (the degenerate
        full-cohort regime: participation_fraction >= 1, e.g. sign_SGD's
        per-step vote over everyone). The arrays stay device-resident for
        the run — streamed residency then only moves WHERE the startup
        upload is accounted."""
        arrays, nbytes, dt = self._upload([None], stack=False)
        self.totals["h2d_bytes"] += nbytes
        self.totals["h2d_seconds"] += dt
        stats = {
            "h2d_bytes": nbytes,
            "h2d_seconds": round(dt, 6),
            "hidden_seconds": 0.0,
            "overlap_ratio": 0.0,
        }
        return arrays, stats

    # ---- writeback ---------------------------------------------------------
    def writeback(self, idx, new_state_k, stats: dict | None = None):
        """Fetch the round's cohort state to host and scatter it into the
        store (Algorithm.scatter_client_state). No-op for stateless
        algorithms. ``stats`` (an acquire stats dict) grows the d2h
        fields in place when given."""
        if self.store.state is None:
            return
        t0 = time.perf_counter()
        host_state = jax.device_get(new_state_k)
        self._algorithm.scatter_client_state(self.store, idx, host_state)
        dt = time.perf_counter() - t0
        nbytes = tree_bytes(host_state)
        self.totals["d2h_bytes"] += nbytes
        self.totals["d2h_seconds"] += dt
        if stats is not None:
            stats["d2h_bytes"] = nbytes
            stats["d2h_seconds"] = round(dt, 6)

    # ---- reporting ---------------------------------------------------------
    def overlap_ratio(self) -> float:
        """Run-total hidden-transfer fraction: how much of the host->HBM
        upload time the prefetch hid behind compute."""
        total = self.totals["h2d_seconds"]
        return self.totals["hidden_seconds"] / total if total > 0 else 0.0

    def close(self) -> None:
        if self._pending is not None:
            # Never leak a worker-thread upload past the run.
            try:
                self._pending[2].result()
            except Exception:
                pass
            self._pending = None
        self._pool.shutdown(wait=True)
