from distributed_learning_simulator_tpu.parallel.mesh import (
    make_mesh,
    client_sharding,
    replicated_sharding,
    shard_client_data,
)
from distributed_learning_simulator_tpu.parallel.engine import (
    make_loss_fn,
    make_local_train_fn,
    make_eval_fn,
    pad_eval_set,
    make_optimizer,
)

__all__ = [
    "make_mesh",
    "client_sharding",
    "replicated_sharding",
    "shard_client_data",
    "make_loss_fn",
    "make_local_train_fn",
    "make_eval_fn",
    "pad_eval_set",
    "make_optimizer",
]
