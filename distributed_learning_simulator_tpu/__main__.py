"""``python -m distributed_learning_simulator_tpu`` — same CLI as
``python -m distributed_learning_simulator_tpu.simulator`` (the reference's
``python3 simulator.py`` entry, reference simulator.sh:1)."""

from distributed_learning_simulator_tpu.simulator import main

if __name__ == "__main__":
    main()
