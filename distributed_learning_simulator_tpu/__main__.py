"""``python -m distributed_learning_simulator_tpu`` — same CLI as
``python -m distributed_learning_simulator_tpu.simulator`` (the reference's
``python3 simulator.py`` entry, reference simulator.sh:1). With
``--sweep_seeds`` / ``--sweep_points`` set, the process runs a
multi-experiment sweep (sweep/engine.py) instead of one simulation."""

from distributed_learning_simulator_tpu.simulator import main

if __name__ == "__main__":
    main()
