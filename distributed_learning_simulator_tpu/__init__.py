"""distributed_learning_simulator_tpu — a TPU-native federated-learning simulator.

A ground-up JAX/XLA re-design of the capability surface of
``chen-zichen/distributed_learning_simulator`` (reference mounted at
``/root/reference``): synchronous federated learning with one logical server and
N simulated clients, five distributed algorithms (FedAvg, SignSGD majority
vote, quantized FedAvg, exact multi-round Shapley contribution scoring, and
GTG-Shapley Monte-Carlo scoring), heterogeneous/non-IID client data, and
compression-ratio accounting.

Design stance (not a port):
  * The reference simulates clients with one OS thread each and a blocking
    queue (reference simulator.py:60-69, servers/server.py:10-17). Here the
    client population is a *stacked leading axis* over the params pytree; a
    full round (local training on every client + aggregation + broadcast) is
    ONE jitted XLA program. "Communication" is array data flow: gather/average/
    broadcast collapse into reductions over the client axis, which XLA lowers
    to ICI collectives when the axis is sharded over a ``jax.sharding.Mesh``.
  * Server classes (reference servers/*.py) survive only as the algorithm
    strategy interface — see ``algorithms/base.py``.
"""

__version__ = "0.1.0"

from distributed_learning_simulator_tpu.config import ExperimentConfig, get_config
from distributed_learning_simulator_tpu.factory import get_algorithm, registered_algorithms

__all__ = [
    "ExperimentConfig",
    "get_config",
    "get_algorithm",
    "registered_algorithms",
    "__version__",
]
